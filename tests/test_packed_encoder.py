"""Differential tests for SnapshotEncoder.encode_packed — the delta-arena
fast path must be indistinguishable (field-for-field) from a full
encode()+pack() for ANY snapshot sequence: churn, pending-count changes,
dictionary growth, stable-side changes, in-place nomination updates.

Methodology (SURVEY.md §4, build-side additions): two encoders consume the
identical object sequence; encoder A uses encode_packed (exercising the
delta path wherever its prechecks allow), encoder B always full-encodes.
Unpacking A's arena buffers must reproduce B's snapshot exactly.
"""

import dataclasses

import numpy as np
import pytest

from k8s_scheduler_tpu.models import MakeNode, MakePod, SnapshotEncoder, packing
from k8s_scheduler_tpu.models.api import PodGroup
from k8s_scheduler_tpu.models.encoding import ClusterSnapshot
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods


def assert_same_snapshot(got: ClusterSnapshot, ref: ClusterSnapshot):
    for f in dataclasses.fields(ClusterSnapshot):
        rv = getattr(ref, f.name)
        gv = getattr(got, f.name)
        if rv is None and gv is None:
            continue
        if isinstance(rv, np.ndarray) or hasattr(rv, "dtype"):
            ga, ra = np.asarray(gv), np.asarray(rv)
            assert ga.shape == ra.shape, f.name
            eq = (
                np.array_equal(ga, ra, equal_nan=True)
                if ga.dtype.kind == "f"
                else np.array_equal(ga, ra)
            )
            assert eq, f"field {f.name} differs"
        else:
            assert rv == gv, f"aux {f.name}: {gv!r} != {rv!r}"


class Driver:
    """Feeds the same objects to the packed and the reference encoder."""

    def __init__(self, pad_pods=128, pad_nodes=16):
        self.a = SnapshotEncoder(pad_pods=pad_pods, pad_nodes=pad_nodes)
        self.b = SnapshotEncoder(pad_pods=pad_pods, pad_nodes=pad_nodes)

    def step(self, nodes, pending, existing=(), groups=(), mutated=frozenset(),
             **kw):
        w, bb, spec, vsnap, _dirty = self.a.encode_packed(
            nodes, pending, existing, groups, mutated_ids=mutated, **kw
        )
        ref = self.b.encode(nodes, pending, existing, groups, **kw)
        got = packing.unpack(np.asarray(w), np.asarray(bb), spec)
        assert_same_snapshot(got, ref)
        # the view snapshot must alias the arena (same data, same ids)
        assert vsnap.pod_requested.base is not None
        return spec


def test_packed_equals_full_over_churned_sequence():
    rng = np.random.default_rng(0)
    nodes = make_cluster(10)
    d = Driver()
    pending = make_pods(
        60, seed=1, affinity_fraction=0.3, anti_affinity_fraction=0.2,
        spread_fraction=0.2, selector_fraction=0.3, num_apps=6,
        priorities=(0, 10),
    )
    existing = [(p, f"node-{i % 10}") for i, p in enumerate(
        make_pods(20, seed=2, name_prefix="run", affinity_fraction=0.2,
                  num_apps=6)
    )]
    specs = set()
    for i in range(8):
        # churn ~25% with fresh objects (fresh names/apps grow dictionaries
        # in early rounds -> full path; later rounds hit the delta path)
        k = 15
        idx = rng.choice(len(pending), size=k, replace=False)
        fresh = make_pods(
            k, seed=100 + i, name_prefix=f"p{i}-", affinity_fraction=0.3,
            spread_fraction=0.2, selector_fraction=0.3, num_apps=6,
            priorities=(0, 10),
        )
        for j, f in zip(idx, fresh):
            pending[j] = f
        specs.add(d.step(nodes, pending, existing).key())
    assert len(specs) == 1  # sticky dims: no packed-regime churn


def test_packed_pending_count_changes():
    nodes = make_cluster(4)
    d = Driver()
    pods = make_pods(40, seed=3)
    d.step(nodes, pods)
    d.step(nodes, pods[:25])  # shrink
    d.step(nodes, pods[:25] + make_pods(10, seed=4, name_prefix="n"))  # grow
    d.step(nodes, [])  # empty pending


def test_packed_detects_stable_change():
    d = Driver()
    nodes = make_cluster(4)
    pods = make_pods(20, seed=5)
    d.step(nodes, pods, [(pods[0], "node-0")])
    # node list replaced -> full path, still exact
    nodes2 = make_cluster(5)
    d.step(nodes2, pods, [(pods[0], "node-0")])
    # existing set changed -> full path, still exact
    d.step(nodes2, pods, [(pods[0], "node-1"), (pods[1], "node-2")])


def test_packed_nominated_mutation_reported():
    d = Driver()
    nodes = make_cluster(4)
    pods = make_pods(20, seed=6)
    d.step(nodes, pods)
    # in-place nomination (what the serving driver does after preemption)
    pods[3].nominated_node_name = "node-2"
    d.step(nodes, pods, mutated=frozenset({id(pods[3])}))


def test_packed_gangs_and_ports_and_pins():
    d = Driver()
    nodes = make_cluster(6)
    pods = [
        MakePod(f"g-{i}").req({"cpu": "500m"}).group("job-a")
        .created(float(i)).obj()
        for i in range(4)
    ]
    pods.append(
        MakePod("portpod").req({"cpu": "100m"}).host_port(8080).obj()
    )
    pods.append(MakePod("pinned").req({"cpu": "100m"}).node("node-2").obj())
    groups = [PodGroup("job-a", 3)]
    d.step(nodes, pods, groups=groups)
    # churn the port pod (new distinct port within the sticky Q pad)
    pods[4] = MakePod("portpod2").req({"cpu": "100m"}).host_port(8081).obj()
    d.step(nodes, pods, groups=groups)
    # group min_member change flows through the delta path
    d.step(nodes, pods, groups=[PodGroup("job-a", 4)])


def test_arena_survives_async_dispatch_mutation():
    """The arena reuse contract, as the serving pipeline enforces it: a
    cycle's outputs are FETCHED before the next encode rewrites the
    arena (ServingPipeline.dispatch refuses cycle k+1 until cycle k's
    decisions were fetched; two slots alternate).

    This test originally asserted a stronger property — that JAX copies
    a jit's host (numpy) arguments synchronously at call time, so
    rewriting the arena IMMEDIATELY behind a dispatch is safe. That is
    false on this jaxlib's CPU backend: the host->device copy happens
    asynchronously on the dispatch thread, and a 15-line pure-jax loop
    (mutate a numpy arg right after a jit call, then force the output)
    reproduces torn copies with no repo code involved — which made this
    test an ~coin flip in full-suite runs on ANY tree. What serving
    actually relies on is the fetch-then-rewrite ordering; that is what
    is driven here. (Re-encoding after a mutation re-baselines the
    digest: interning dictionaries are grow-only, so a new pod's name
    legitimately shifts packed bytes.)"""
    import jax

    d = SnapshotEncoder(pad_pods=64, pad_nodes=8)
    nodes = make_cluster(4)
    pods = make_pods(30, seed=7)
    w, b, spec, _, _ = d.encode_packed(nodes, pods)

    @jax.jit
    def digest(wb, bb):
        return (wb % 9973).sum(), (bb.astype("int32")).sum()

    out = digest(w, b)
    ref = (int(np.asarray(out[0])), int(np.asarray(out[1])))
    for i in range(5):
        out = digest(w, b)
        # the decision-fetch analogue: force cycle i's outputs BEFORE
        # the arena may be rewritten for cycle i+1 (the pipeline's
        # require_decision_fetch guard provides this order in serving)
        got = (int(np.asarray(out[0])), int(np.asarray(out[1])))
        assert got == ref  # fetched outputs reflect this cycle's bytes
        # now the rewrite is legal (cycle i+1's delta writes)
        pods2 = list(pods)
        pods2[0] = MakePod(f"mut-{i}").req({"cpu": "250m"}).obj()
        d.encode_packed(nodes, pods2)
        # restore and re-encode for the next iteration's baseline
        w, b, spec, _, _ = d.encode_packed(nodes, pods)
        out = digest(w, b)
        ref = (int(np.asarray(out[0])), int(np.asarray(out[1])))


def test_sticky_dims_do_not_shrink():
    enc = SnapshotEncoder(pad_pods=32, pad_nodes=8)
    nodes = make_cluster(2)
    many_labels = MakePod("lab").labels(
        {f"k{i}": f"v{i}" for i in range(12)}
    ).req({"cpu": "1"}).obj()
    s1 = enc.encode(nodes, [many_labels])
    mpl = s1.pod_label_keys.shape[1]
    s2 = enc.encode(nodes, [MakePod("tiny").req({"cpu": "1"}).obj()])
    assert s2.pod_label_keys.shape[1] == mpl


if __name__ == "__main__":
    import sys

    pytest.main([__file__, "-v"] + sys.argv[1:])


def test_fused_mixed_native_and_fallback_rows():
    """Round-5 fused path (native pod_rows_into): a dirty batch mixing
    natively-written pods with Python-fallback pods (volumes force the
    fallback) must still be byte-identical to the full encode."""
    from k8s_scheduler_tpu import native

    if native.pod_rows_into is None:
        pytest.skip("native extension not built")
    nodes = make_cluster(6)
    d = Driver()
    pending = make_pods(30, seed=11, affinity_fraction=0.3, num_apps=4)
    # volume-bearing pods take the dict fallback inside the fused call
    pending += [
        MakePod(f"vol-{i}").req({"cpu": "250m"}).volume(f"claim-{i}").obj()
        for i in range(4)
    ]
    d.step(nodes, pending)
    # churn BOTH kinds in one dirty batch -> mixed fused/fallback delta
    pending[0] = make_pods(1, seed=99, name_prefix="fresh")[0]
    pending[30] = (
        MakePod("vol-new").req({"cpu": "250m"}).volume("claim-new").obj()
    )
    d.step(nodes, pending)
    # and again so the second delta reuses rows[i] stored by both paths
    pending[1] = make_pods(1, seed=100, name_prefix="fresh2")[0]
    d.step(nodes, pending)


def test_fused_guard_overflow_falls_back_to_full():
    """A dirty pod that overflows an arena dim (here: more labels than
    MPL) must make the fused call report guard_ok=False and the encoder
    take the full path — still exact."""
    from k8s_scheduler_tpu import native

    if native.pod_rows_into is None:
        pytest.skip("native extension not built")
    nodes = make_cluster(4)
    d = Driver()
    pods = make_pods(20, seed=12)
    d.step(nodes, pods)
    pods[3] = (
        MakePod("many-labels")
        .req({"cpu": "100m"})
        .labels({f"key-{j}": f"v-{j}" for j in range(40)})
        .obj()
    )  # blow past the sticky MPL dim
    d.step(nodes, pods)
    # subsequent delta over the grown arena still works
    pods[4] = make_pods(1, seed=101, name_prefix="after")[0]
    d.step(nodes, pods)


def test_fold_existing_append_tail_remove_and_rebase():
    """Round-5 incremental existing-fold: appending bound pods and
    removing a completion batch (tail) must update the stable side IN
    PLACE (no full encode) and stay byte-identical to a from-scratch
    assembly — including the NodePorts used-port lists, the node_pods
    victim table, and an exist_start re-base when an appended pod is
    older than every existing one."""
    from k8s_scheduler_tpu import native

    if native.pod_rows_into is None:
        pytest.skip("native extension not built")
    nodes = make_cluster(8)
    d = Driver()
    pods = make_pods(40, seed=21, affinity_fraction=0.2, num_apps=5)
    # one pending pod with a host port (exercises the port-dirty repair)
    pods[7] = (
        MakePod("portpod").req({"cpu": "100m"}).host_port(8080).obj()
    )
    existing = [
        (p, f"node-{i % 8}")
        for i, p in enumerate(
            make_pods(20, seed=22, name_prefix="run", num_apps=5)
        )
    ]
    d.step(nodes, pods, existing)
    d.step(nodes, pods, existing)  # warm the delta path
    folds0 = getattr(d.a, "fold_hits", 0)
    fulls0 = d.a.full_encodes

    # ---- bindings fold in (append), one of them port-bearing ----
    bound = [(pods[i], f"node-{i % 8}") for i in range(6)]
    bound.append(
        (MakePod("bport").req({"cpu": "100m"}).host_port(9090).obj(), "node-3")
    )
    existing2 = existing + bound
    pending2 = pods[6:] + make_pods(5, seed=31, name_prefix="arr", num_apps=5)
    d.step(nodes, pending2, existing2)
    assert d.a.fold_hits == folds0 + 1
    assert d.a.full_encodes == fulls0

    # ---- completion batch: the appended tail leaves ----
    existing3 = existing2[: len(existing)]
    d.step(nodes, pending2, existing3)
    assert d.a.fold_hits == folds0 + 2
    assert d.a.full_encodes == fulls0

    # ---- re-base: an appended pod OLDER than every existing pod ----
    old_pod = (
        MakePod("ancient").req({"cpu": "100m"}).created(-1000.0).obj()
    )
    existing4 = existing3 + [(old_pod, "node-1")]
    d.step(nodes, pending2, existing4)
    assert d.a.fold_hits == folds0 + 3
    assert d.a.full_encodes == fulls0

    # ---- middle-of-list removal: NOT foldable, full path, still exact
    existing5 = existing4[1:]
    d.step(nodes, pending2, existing5)
    assert d.a.full_encodes == fulls0 + 1


def test_fold_unfold_float_exactness_under_inexact_requests():
    """f32-rounding stress for the fold/un-fold node_requested recompute:
    0.1-core requests are inexact in float32, so a subtract-based un-fold
    would drift by ULPs from a from-scratch assembly. Repeated
    fold/evict cycles must stay byte-identical (the Driver compares
    every array)."""
    from k8s_scheduler_tpu import native

    if native.pod_rows_into is None:
        pytest.skip("native extension not built")
    nodes = make_cluster(4)
    d = Driver()
    pods = [
        MakePod(f"t-{i}").req({"cpu": "100m", "memory": "100Mi"}).obj()
        for i in range(24)
    ]
    existing = [
        (MakePod(f"r-{i}").req({"cpu": "100m"}).obj(), f"node-{i % 4}")
        for i in range(12)
    ]
    d.step(nodes, pods, existing)
    d.step(nodes, pods, existing)
    for round_ in range(3):
        bound = [
            (pods[round_ * 4 + j], f"node-{j % 4}") for j in range(4)
        ]
        existing = existing + bound
        d.step(nodes, pods, existing)
        existing = existing[:12]  # completion batch
        d.step(nodes, pods, existing)
    assert d.a.fold_hits >= 6


def test_pad_ma_mc_presize_keeps_regime_stable():
    """ADVICE r5: MA/MC bucket by 2, so a mid-serving arrival of a
    3-4-term affinity/spread pod flips the sticky regime (full recompile)
    unless pad_ma/pad_mc pre-size it — mirroring pad_existing/MPN."""
    nodes = [
        MakeNode("n0").capacity({"cpu": "8"}).labels({"app": "x"}).obj()
    ]

    def aff_pod(name, terms):
        p = MakePod(name).req({"cpu": "1"})
        for _ in range(terms):
            p = p.pod_affinity("kubernetes.io/hostname", {"app": "x"})
        return p.spread(1, "kubernetes.io/hostname", {"app": "x"}).obj()

    base = [aff_pod("p0", 1)]  # affinity/spread capability already on
    rich = aff_pod("p1", 4)
    unsized = SnapshotEncoder(pad_pods=8, pad_nodes=4)
    _, _, s1, _, _ = unsized.encode_packed(nodes, base)
    _, _, s1b, _, _ = unsized.encode_packed(nodes, base + [rich])
    assert s1b.key() != s1.key()  # the flip the knob exists to prevent

    sized = SnapshotEncoder(pad_pods=8, pad_nodes=4, pad_ma=4, pad_mc=4)
    assert sized._sticky_dims == {}
    _, _, s2, _, _ = sized.encode_packed(nodes, base)
    assert sized._sticky_dims["MA"] == 4
    assert sized._sticky_dims["MC"] == 4
    _, _, s2b, _, _ = sized.encode_packed(nodes, base + [rich])
    assert s2b.key() == s2.key()
