"""Cycle flight recorder (core/flight_recorder.py): ring bounds,
lock-free snapshot consistency, chrome-trace export, pod timelines."""

import json
import threading

import pytest

from k8s_scheduler_tpu.core.flight_recorder import (
    LANE_DEVICE,
    LANE_DIAG,
    LANE_HOST,
    FlightRecorder,
    PodTimelines,
    to_chrome_trace,
)


def _commit_cycle(fr, t0, *, profile="default-scheduler", slot=0,
                  encode_ms=2.0, device_ms=5.0, bind_ms=1.0,
                  diag_ms=3.0, **counts):
    """Synthesize one committed record with a realistic mark layout
    starting at recorder-clock second t0."""
    rec = fr.start(profile)
    rec.t_start = t0
    rec.slot = slot
    e = encode_ms / 1e3
    d = device_ms / 1e3
    b = bind_ms / 1e3
    rec.mark("encode_start", t0)
    rec.mark("dispatch_start", t0 + e)
    rec.mark("dispatch_end", t0 + e + 0.0005)
    rec.mark("decision_start", t0 + e + 0.0005)
    rec.mark("decision_end", t0 + e + d)
    rec.mark("winners_end", t0 + e + d + b)
    rec.mark("postfilter_end", t0 + e + d + b + 0.0002)
    rec.mark("diag_done", t0 + e + d + diag_ms / 1e3)
    rec.phases.update(
        encode_ms=encode_ms,
        decision_wait_ms=device_ms,
        encode_hidden_ms=max(0.0, encode_ms - device_ms),
        diag_lag_ms=diag_ms,
    )
    rec.counts.update(counts)
    rec.t_end = t0 + e + d + b + 0.001
    fr.commit(rec)
    return rec


# ---- ring semantics ------------------------------------------------------


def test_ring_bounds_and_wrap():
    fr = FlightRecorder(capacity=8)
    for i in range(20):
        _commit_cycle(fr, t0=float(i))
    assert fr.cycles == 20
    recs = fr.snapshot()
    # bounded at capacity, newest-last, contiguous sequence numbers
    assert len(recs) == 8
    assert [r.seq for r in recs] == list(range(12, 20))
    # last=N trims from the newest end
    assert [r.seq for r in fr.snapshot(last=3)] == [17, 18, 19]
    assert fr.last_record().seq == 19
    # to_dicts is JSON-clean
    json.dumps(fr.to_dicts(last=5))


def test_snapshot_consistent_under_concurrent_writer():
    """Reader snapshots taken while a writer hammers the ring must never
    contain torn windows: sequence numbers are contiguous ascending and
    every record is a fully-formed commit (t_end stamped)."""
    fr = FlightRecorder(capacity=16)
    stop = threading.Event()
    errors: list[str] = []

    def writer():
        i = 0
        while not stop.is_set():
            _commit_cycle(fr, t0=float(i), pods=i)
            i += 1

    def reader():
        for _ in range(3000):
            recs = fr.snapshot()
            seqs = [r.seq for r in recs]
            if seqs != sorted(seqs) or (
                seqs and seqs != list(range(seqs[0], seqs[-1] + 1))
            ):
                errors.append(f"non-contiguous window {seqs}")
                return
            for r in recs:
                if not r.t_end:
                    errors.append(f"uncommitted record {r.seq} visible")
                    return

    w = threading.Thread(target=writer)
    r1 = threading.Thread(target=reader)
    r2 = threading.Thread(target=reader)
    w.start(); r1.start(); r2.start()
    r1.join(); r2.join()
    stop.set(); w.join()
    assert not errors, errors[0]
    assert fr.cycles > 16  # the ring actually wrapped under test


def test_last_cycle_age_uses_epoch_before_first_cycle():
    t = {"now": 100.0}
    fr = FlightRecorder(capacity=4, now=lambda: t["now"])
    t["now"] = 107.5
    # no cycle EVER completed: age anchors at recorder creation so a
    # wedged-at-startup scheduler still ages out of its health deadline
    assert fr.last_cycle_age_s() == pytest.approx(7.5)
    _commit_cycle(fr, t0=107.5)
    t["now"] = 109.0
    assert fr.last_cycle_age_s() == pytest.approx(
        109.0 - fr.last_record().t_end
    )


# ---- chrome trace --------------------------------------------------------


def test_chrome_trace_validates_and_lanes_nest():
    fr = FlightRecorder(capacity=32)
    for i in range(5):
        _commit_cycle(fr, t0=float(i), slot=i % 2, pods=10 + i)
    # synthetic records carry their own small absolute times (t0=0..5),
    # so rebase against 0 rather than the recorder's real epoch
    trace = to_chrome_trace(fr.snapshot(), epoch=0.0)
    # round-trips as JSON with the two top-level chrome-trace keys
    parsed = json.loads(json.dumps(trace))
    assert set(parsed) == {"traceEvents", "displayTimeUnit"}
    events = parsed["traceEvents"]
    slices = [e for e in events if e["ph"] == "X"]
    metas = [e for e in events if e["ph"] == "M"]
    # lane metadata names all three tracks
    named = {m["args"]["name"] for m in metas if m["name"] == "thread_name"}
    assert len(named) == 3
    for ev in slices:
        assert ev["ts"] >= 0 and ev["dur"] >= 0
        assert ev["tid"] in (LANE_HOST, LANE_DEVICE, LANE_DIAG)
    # per-cycle: phase slices nest inside the host-lane cycle envelope,
    # the device slice spans dispatch->decision end, the diag slice
    # starts exactly where the decision fetch ended (overlapping the
    # host bind slice — the lanes Perfetto renders as parallel tracks)
    for seq in range(5):
        env = next(
            e for e in slices
            if e["name"] == f"cycle[{seq}]" and e["tid"] == LANE_HOST
        )
        t0, t1 = env["ts"], env["ts"] + env["dur"]
        children = [
            e for e in slices
            if e["tid"] == LANE_HOST and e is not env
            and t0 - 1 <= e["ts"] and e["ts"] + e["dur"] <= t1 + 1
            and e["name"] in (
                "encode", "dispatch", "decision_wait", "bind winners",
                "postfilter", "losers",
            )
        ]
        assert {c["name"] for c in children} == {
            "encode", "dispatch", "decision_wait", "bind winners",
            "postfilter", "losers",
        }
        dev = next(
            e for e in slices
            if e["tid"] == LANE_DEVICE and e["args"]["seq"] == seq
        )
        diag = next(
            e for e in slices
            if e["tid"] == LANE_DIAG and e["args"]["seq"] == seq
        )
        dec = next(
            c for c in children if c["name"] == "decision_wait"
        )
        bind = next(
            c for c in children if c["name"] == "bind winners"
        )
        # device lane covers the decision wait (the in-flight window)
        assert dev["ts"] <= dec["ts"]
        assert dev["ts"] + dev["dur"] == pytest.approx(
            dec["ts"] + dec["dur"], abs=1.0
        )
        # diag lag overlaps the host bind slice (distinct lanes, same
        # wall-clock window = the deferred-attribution overlap)
        assert diag["ts"] == pytest.approx(bind["ts"], abs=1.0)
        assert diag["dur"] > 0


def test_forced_sync_records_no_hidden_encode():
    fr = FlightRecorder(capacity=8)
    # forced-sync shape: the encode never overlaps (decision wait
    # includes the full device time, hidden = 0)
    _commit_cycle(fr, t0=0.0, encode_ms=4.0, device_ms=6.0)
    d = fr.derived()
    assert d["encode_hidden_ms_mean"] == 0.0
    assert d["overlap_ratio"] == 0.0
    # async shape: encode fully hidden behind a longer device window
    fr2 = FlightRecorder(capacity=8)
    _commit_cycle(fr2, t0=0.0, encode_ms=6.0, device_ms=2.0)
    d2 = fr2.derived()
    assert d2["encode_hidden_ms_mean"] == pytest.approx(4.0)
    assert d2["overlap_ratio"] == pytest.approx(4.0 / 6.0, abs=1e-3)


# ---- pod timelines -------------------------------------------------------


def test_pod_timelines_lru_bound_and_event_cap():
    tl = PodTimelines(max_pods=4, max_events=3)
    for i in range(10):
        tl.note(f"uid-{i}", f"pod-{i}", "Queued", t=float(i), wall=0.0)
    assert len(tl) == 4
    assert tl.get("uid-0") is None
    assert tl.get("uid-9")["name"] == "pod-9"
    for k in range(10):
        tl.note("uid-9", "pod-9", "Attempt", t=10.0 + k, wall=0.0, cycle=k)
    evs = tl.get("uid-9")["events"]
    assert len(evs) == 3  # capped, newest kept
    assert evs[-1]["cycle"] == 9


def test_pod_timeline_joins_requeue_and_preempt_paths():
    """The per-pod join across a requeue (unschedulable -> retry ->
    bound) and a preemption (bound-observed -> evicted), plus the
    events-ring half of the join, via Scheduler.pod_timeline."""
    from k8s_scheduler_tpu.core.scheduler import Scheduler

    s = Scheduler()
    fr = s.flight
    assert fr is not None  # default config enables the recorder

    # requeue path: queued, rejected in cycle 0, requeued, bound in 2
    fr.pod_event("u1", "web-1", "Queued")
    fr.pod_event("u1", "web-1", "Unschedulable", cycle=0,
                 plugin="NodeResourcesFit")
    fr.pod_event("u1", "web-1", "Updated")
    fr.pod_event("u1", "web-1", "Bound", cycle=2, node="node-3")
    s.events.record("Warning", "FailedScheduling",
                    type("P", (), {"uid": "u1", "name": "web-1"})(),
                    "0/4 nodes are available: 4 NodeResourcesFit.")
    tl = s.pod_timeline("u1")
    assert tl["state"] == "Bound"
    assert [a["result"] for a in tl["attempts"]] == [
        "Unschedulable", "Bound",
    ]
    assert tl["attempts"][0]["plugin"] == "NodeResourcesFit"
    assert tl["attempts"][0]["cycle"] == 0
    assert tl["attempts"][1] == {
        "cycle": 2, "result": "Bound", "node": "node-3",
    }
    # events-ring half of the join rides along until the shim drains it
    assert tl["ring_events"][0]["reason"] == "FailedScheduling"

    # preemption path: a running pod observed bound, then evicted
    fr.pod_event("u2", "batch-7", "BoundObserved", node="node-1")
    fr.pod_event("u2", "batch-7", "Evicted", cycle=5, node="node-1",
                 preemptor="web-9")
    tl2 = s.pod_timeline("u2")
    assert tl2["state"] == "Evicted"
    assert tl2["events"][-1]["preemptor"] == "web-9"

    # unseen pod: no timeline
    assert s.pod_timeline("nope") is None
    json.dumps(tl); json.dumps(tl2)  # endpoint payloads are JSON-clean


def test_overlap_from_records_pure():
    from k8s_scheduler_tpu.core.profiling import overlap_from_records

    out = overlap_from_records([])
    assert out["window"] == 0.0 and out["overlap_ratio"] == 0.0
    out = overlap_from_records(
        [
            {"encode_ms": 4.0, "decision_wait_ms": 1.0,
             "encode_hidden_ms": 3.0, "diag_lag_ms": 2.0},
            {"encode_ms": 2.0, "decision_wait_ms": 2.0,
             "encode_hidden_ms": 0.0},
        ]
    )
    assert out["window"] == 2.0
    assert out["encode_ms_mean"] == pytest.approx(3.0)
    assert out["overlap_ratio"] == pytest.approx(0.5)
    assert out["diag_lag_ms_mean"] == pytest.approx(2.0)
