"""Worker process for tests/test_distributed.py: one of N
`jax.distributed` CPU processes wired over localhost (the DCN bring-up
path of parallel/mesh.py, SURVEY.md §5.8).

Run:  python tests/_dcn_worker.py <coordinator_port> <process_id> <nproc>

Prints one line per proven stage; the parent test asserts on them.
NOTE: jax_platforms is flipped to cpu AFTER import (this environment's
sitecustomize imports jax at interpreter start; the env-var route hangs
— see tests/conftest.py)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    port, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

    import numpy as np

    from k8s_scheduler_tpu.parallel.mesh import (
        initialize_distributed,
        make_mesh,
        shard_snapshot,
    )

    # the wrapper under test: wires this process into the multi-host
    # runtime (DCN analogue; localhost gRPC here)
    initialize_distributed(f"127.0.0.1:{port}", nproc, pid)
    assert jax.process_count() == nproc, jax.process_count()
    devs = jax.devices()
    assert len(devs) == 2 * nproc, devs  # 2 local CPU devices per process
    print(f"INIT ok: processes={jax.process_count()} devices={len(devs)}",
          flush=True)

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    # ---- one cross-process collective: sum over a globally sharded axis
    mesh = make_mesh(devs)
    D = len(devs)
    L = 8 * D
    sharding = NamedSharding(mesh, PartitionSpec("pods"))
    global_vals = np.arange(L, dtype=np.float32)
    x = jax.make_array_from_callback(
        (L,), sharding, lambda idx: global_vals[idx]
    )
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(
        mesh, PartitionSpec()
    ))(x)
    got = float(np.asarray(total))
    want = float(global_vals.sum())
    assert got == want, (got, want)
    print(f"PSUM ok: {got}", flush=True)

    # ---- a tiny sharded scheduling cycle across both processes, proven
    # equal to the replicated run of the same snapshot
    from k8s_scheduler_tpu.core import build_cycle_fn
    from k8s_scheduler_tpu.models import MakeNode, MakePod, SnapshotEncoder

    nodes = [
        MakeNode(f"n{i}").capacity({"cpu": "4"}).obj() for i in range(8)
    ]
    pods = [
        MakePod(f"p{i}").req({"cpu": "2"}).created(float(i)).obj()
        for i in range(16)
    ]
    enc = SnapshotEncoder(pad_pods=16 * max(1, D // 2), pad_nodes=8)
    snap = enc.encode(nodes, pods)
    cycle = build_cycle_fn(commit_mode="rounds")

    ref = np.asarray(cycle(snap).assignment)  # replicated inputs
    sharded = shard_snapshot(snap, mesh)
    out = cycle(sharded)
    # replicate the (possibly sharded) result so every process can read
    # the full array
    rep = jax.jit(
        lambda a: a,
        out_shardings=NamedSharding(mesh, PartitionSpec()),
    )(out.assignment)
    got_a = np.asarray(rep)[: ref.size]
    np.testing.assert_array_equal(got_a, ref)
    placed = int((ref >= 0).sum())
    assert placed == 16  # 8 nodes x 4 cpu / 2-cpu pods
    print(f"CYCLE ok: placed={placed} sharded==replicated", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
