"""Cycle observer (core/observe.py): phase attribution, the anomaly
sentinel under synthetic injection, SLO burn rate, and the
/debug/anomalies + pod-filtered /debug/trace endpoints.

The injection tests are the ISSUE 5 live demonstration: a stalled
tunnel phase, a shape-signature flip, and a fold miss are each
fabricated as flight records, and the assertions pin the exact anomaly
class, the attributed dimension, the metric increments, and the seq
link back to the flight record."""

import json
import urllib.error
import urllib.request

from k8s_scheduler_tpu.cmd.httpserver import (
    staleness_healthz,
    start_http_server,
)
from k8s_scheduler_tpu.core.flight_recorder import (
    TRACE_LANE_FOR_PHASE,
    FlightRecorder,
)
from k8s_scheduler_tpu.core.observe import (
    ANOMALY_CLASSES,
    PHASE_BUCKETS_S,
    PHASES,
    CycleObserver,
    SloEngine,
    StreamHist,
    classify_latency_series,
    phase_seconds,
)
from k8s_scheduler_tpu.metrics import SchedulerMetrics


def _commit_cycle(
    fr, t0, *, profile="default-scheduler", encode_ms=2.0, fold_ms=0.0,
    device_ms=5.0, fetch_ms=None, bind_ms=1.0, diag_ms=0.0,
    compile_ms=0.0, sig=None, **counts,
):
    """Synthesize one committed record with a realistic mark layout at
    recorder-clock second t0; fetch_ms defaults to the device window."""
    rec = fr.start(profile)
    rec.t_start = t0
    e, d, b = encode_ms / 1e3, device_ms / 1e3, bind_ms / 1e3
    rec.mark("encode_start", t0)
    rec.mark("dispatch_start", t0 + e)
    rec.mark("dispatch_end", t0 + e + 0.0005)
    rec.mark("decision_start", t0 + e + 0.0005)
    rec.mark("decision_end", t0 + e + 0.0005 + d)
    rec.mark("apply_start", t0 + e + 0.0005 + d)
    rec.mark("winners_end", t0 + e + 0.0005 + d + b)
    rec.mark("postfilter_end", t0 + e + 0.0005 + d + b + 0.0002)
    rec.phases.update(
        encode_ms=encode_ms,
        dispatch_ms=0.5,
        decision_wait_ms=device_ms if fetch_ms is None else fetch_ms,
    )
    if fold_ms:
        rec.phases["fold_ms"] = fold_ms
    if diag_ms:
        rec.phases["diag_lag_ms"] = diag_ms
    if compile_ms:
        rec.phases["compile_ms"] = compile_ms
    rec.sig = sig
    rec.counts.update(counts)
    rec.t_end = t0 + e + 0.0005 + d + b + 0.001
    fr.commit(rec)
    return rec


def _observed(metrics=None, **kw):
    """Recorder + attached observer, warmup shrunk for short tests."""
    fr = FlightRecorder(capacity=64)
    obs = CycleObserver(metrics=metrics, warmup_cycles=4, **kw)
    obs.epoch = fr.epoch
    fr.observers.append(obs.observe)
    return fr, obs


# ---- phase attribution ---------------------------------------------------


def test_phase_seconds_decomposition():
    fr = FlightRecorder(capacity=4)
    rec = _commit_cycle(
        fr, 10.0, encode_ms=4.0, fold_ms=1.5, device_ms=6.0,
        diag_ms=2.0, compile_ms=120.0,
    )
    ph = phase_seconds(rec)
    # every emitted phase is a member of the canonical inventory
    assert set(ph) <= set(PHASES)
    # fold is attributed separately; encode keeps the non-fold remainder
    assert abs(ph["encode"] - 0.0025) < 1e-9
    assert abs(ph["fold"] - 0.0015) < 1e-9
    assert abs(ph["device"] - 0.006) < 1e-9
    assert abs(ph["decision_fetch"] - 0.006) < 1e-9
    assert abs(ph["compile"] - 0.120) < 1e-9
    assert abs(ph["diag_lag"] - 0.002) < 1e-9
    assert ph["total"] == rec.t_end - rec.t_start
    # absent work is absent, not zero: a minimal record emits no
    # bind/postfilter/diag/compile phases
    bare = fr.start()
    bare.t_start, bare.t_end = 20.0, 20.001
    assert set(phase_seconds(bare)) == {"total"}


def test_phase_inventory_matches_trace_lanes():
    # the schedlint ID005 contract, asserted at runtime too
    assert set(TRACE_LANE_FOR_PHASE) == set(PHASES)


def test_stream_hist_quantiles():
    h = StreamHist()
    for _ in range(99):
        h.observe(0.004)
    h.observe(28.0)
    # p50 lands inside the bucket owning 0.004; p99+ sees the outlier
    assert 0.0025 <= h.quantile(0.5) <= 0.005
    assert h.quantile(0.999) > 1.0
    assert h.max_seen == 28.0
    assert StreamHist().quantile(0.5) == 0.0


# ---- anomaly sentinel: synthetic injection -------------------------------


def test_injected_tunnel_stall_detected_within_one_cycle():
    m = SchedulerMetrics()
    fr, obs = _observed(metrics=m)
    for i in range(8):
        _commit_cycle(fr, float(i), device_ms=5.0)
    assert obs.anomalies() == []  # baseline traffic is quiet
    stalled = _commit_cycle(fr, 100.0, device_ms=28_000.0)
    evs = obs.anomalies()
    assert len(evs) == 1  # detected in the same cycle it was published
    ev = evs[0]
    assert ev["class"] == "tunnel_stall"
    assert ev["phase"] == "device"
    assert ev["seq"] == stalled.seq
    assert abs(ev["value_ms"] - 28_000.0) < 1.0
    # the seq links to a committed flight record (and thus the matching
    # /debug/trace window)
    assert any(r.seq == ev["seq"] for r in fr.snapshot())
    assert obs.anomaly_counts["tunnel_stall"] == 1
    text = m.expose().decode()
    assert 'scheduler_anomalies_total{class="tunnel_stall"} 1.0' in text
    # the stall fed the phase histogram winsorized: the NEXT identical
    # stall is still an outlier (the baseline did not chase it)
    again = _commit_cycle(fr, 200.0, device_ms=28_000.0)
    assert obs.anomalies()[-1]["seq"] == again.seq
    assert obs.anomaly_counts["tunnel_stall"] == 2
    # ...but the EXPORTED quantiles report the raw tail, not the
    # winsorized baseline: an operator watching p99 during a stall
    # episode must see the stall
    assert obs.quantile("device", 0.99) > 1.0


def test_warmup_stall_does_not_poison_the_baseline():
    fr, obs = _observed()  # warmup_cycles=4
    _commit_cycle(fr, 0.0, device_ms=5.0)
    # a stall INSIDE the warmup window: not classified (too little
    # history to page on)...
    _commit_cycle(fr, 1.0, device_ms=28_000.0)
    assert obs.anomalies() == []
    for i in range(2, 8):
        _commit_cycle(fr, float(i), device_ms=5.0)
    # ...but it was winsorized, not fed raw — so the p99 term did not
    # park at 28 s and the first post-warmup stall still classifies
    rec = _commit_cycle(fr, 100.0, device_ms=28_000.0)
    evs = obs.anomalies()
    assert [e["class"] for e in evs] == ["tunnel_stall"]
    assert evs[0]["seq"] == rec.seq


def test_stall_on_the_very_first_cycle_does_not_poison_baseline():
    """The rig is MOST stall-prone at startup (first-use buffer
    overhead, flaky tunnel): a 28 s outlier on cycle 1 — before any
    baseline exists — must be floor-winsorized like every other warmup
    outlier, not seed ewma/p99 at 28 s and mask the class."""
    fr, obs = _observed()  # warmup_cycles=4
    _commit_cycle(fr, 0.0, device_ms=28_000.0)  # the FIRST sample
    assert obs.anomalies() == []  # warmup: not classified
    for i in range(1, 8):
        _commit_cycle(fr, float(i), device_ms=5.0)
    rec = _commit_cycle(fr, 100.0, device_ms=28_000.0)
    evs = obs.anomalies()
    assert [e["class"] for e in evs] == ["tunnel_stall"]
    assert evs[0]["seq"] == rec.seq


def test_metrics_bucket_edges_cannot_drift():
    """metrics.py keeps a literal copy of PHASE_BUCKETS_S; wiring an
    observer to a metrics object whose exported histogram edges differ
    must refuse loudly instead of letting the exported histogram and
    the streaming quantile gauges silently disagree."""
    import pytest

    m = SchedulerMetrics()
    assert tuple(
        e for e in m.cycle_phase._upper_bounds if e != float("inf")
    ) == PHASE_BUCKETS_S  # the literal copy is in sync today
    CycleObserver(metrics=m)  # in-sync edges wire fine
    m.cycle_phase._upper_bounds = [0.5, 1.0, float("inf")]
    with pytest.raises(ValueError, match="drifted"):
        CycleObserver(metrics=m)


def test_fetch_stall_distinct_from_tunnel_stall():
    fr, obs = _observed()
    for i in range(8):
        _commit_cycle(fr, float(i), device_ms=5.0, fetch_ms=5.0)
    # the blocking fetch crawls while the device round-trip window stays
    # unremarkable: a transfer stall, not a stalled dispatch
    rec = _commit_cycle(fr, 100.0, device_ms=5.0, fetch_ms=2_000.0)
    evs = obs.anomalies()
    assert [e["class"] for e in evs] == ["fetch_stall"]
    assert evs[0]["phase"] == "decision_fetch"
    assert evs[0]["seq"] == rec.seq
    # when BOTH windows stall, tunnel_stall takes precedence (one event)
    _commit_cycle(fr, 200.0, device_ms=2_000.0, fetch_ms=2_000.0)
    assert [e["class"] for e in obs.anomalies()] == [
        "fetch_stall", "tunnel_stall",
    ]


def test_recompile_flip_attributes_dimension():
    m = SchedulerMetrics()
    fr, obs = _observed(metrics=m)
    base_sig = (("E", 256), ("MPN", 16), ("P", 8))
    _commit_cycle(fr, 0.0, sig=base_sig)
    assert obs.anomalies() == []  # first signature is not a flip
    _commit_cycle(fr, 1.0, sig=base_sig)
    assert obs.anomalies() == []  # unchanged signature is not a flip
    flip = _commit_cycle(
        fr, 2.0, sig=(("E", 512), ("MPN", 16), ("P", 8)),
        compile_ms=95_000.0, regime_flip=1,
    )
    evs = obs.anomalies()
    assert len(evs) == 1
    ev = evs[0]
    assert ev["class"] == "recompile" and ev["seq"] == flip.seq
    assert ev["detail"]["dims"] == ["E"]  # the flipping pad dimension
    assert ev["detail"]["from_sig"] == {"E": 256}
    assert ev["detail"]["to_sig"] == {"E": 512}
    assert abs(ev["value_ms"] - 95_000.0) < 1.0
    # a multi-dimension flip names every moved dimension
    _commit_cycle(
        fr, 3.0, sig=(("E", 256), ("MPN", 24), ("P", 8)), regime_flip=1,
    )
    assert obs.anomalies()[-1]["detail"]["dims"] == ["E", "MPN"]
    assert (
        'scheduler_anomalies_total{class="recompile"} 2.0'
        in m.expose().decode()
    )


def test_memoized_flip_flop_is_not_a_recompile():
    """A pad flip-flop riding the scheduler's _packed cache flips the
    signature every cycle but rebuilds nothing (no regime_flip stamp,
    ~zero cost): the sentinel must NOT raise per-cycle recompile events
    for it — an oscillating workload would otherwise flood the ring and
    grow scheduler_anomalies_total{class=recompile} unboundedly."""
    fr, obs = _observed()
    lo = (("P", 64),)
    hi = (("P", 128),)
    # first crossings genuinely rebuild (memo miss -> regime_flip)
    _commit_cycle(fr, 0.0, sig=lo, regime_flip=1, full_encodes=1)
    _commit_cycle(fr, 1.0, sig=hi, regime_flip=1, full_encodes=2)
    assert obs.anomaly_counts["recompile"] == 1  # first cycle is anchor
    # ...then the workload oscillates across the boundary: both regimes
    # are cached, every switch is a memo hit (and its full re-encode is
    # the shape change's fault, not a fold miss)
    for i in range(2, 12):
        _commit_cycle(
            fr, float(i), sig=lo if i % 2 == 0 else hi,
            full_encodes=i + 1,
        )
    assert obs.anomaly_counts["recompile"] == 1  # no spam
    assert obs.anomaly_counts["fold_miss"] == 0
    # a later genuine rebuild (e.g. after cache eviction) still fires,
    # with the dimension attributed from the same-cycle sig diff
    _commit_cycle(fr, 20.0, sig=(("P", 256),), regime_flip=1)
    ev = obs.anomalies()[-1]
    assert ev["class"] == "recompile" and ev["detail"]["dims"] == ["P"]


def test_fold_miss_only_without_regime_flip():
    fr, obs = _observed()
    sig = (("E", 256),)
    _commit_cycle(fr, 0.0, sig=sig, full_encodes=1)
    _commit_cycle(fr, 1.0, sig=sig, full_encodes=1)  # delta-path cycle
    assert obs.anomalies() == []
    # an UNexplained fall off the delta/fold path is a fold miss...
    miss = _commit_cycle(fr, 2.0, sig=sig, full_encodes=2)
    evs = obs.anomalies()
    assert [e["class"] for e in evs] == ["fold_miss"]
    assert evs[0]["seq"] == miss.seq
    assert evs[0]["detail"]["full_encodes"] == 1
    # ...but a full encode WITH a regime flip is the flip's fault: only
    # the recompile event is raised
    _commit_cycle(fr, 3.0, sig=(("E", 512),), full_encodes=3,
                  regime_flip=1)
    assert [e["class"] for e in obs.anomalies()] == [
        "fold_miss", "recompile",
    ]
    # a dictionary-growth recompile (spec.key() changed, every named
    # pad size identical — regime_flip stamped, signature unchanged) is
    # a recompile with no flipping dimension, NOT a fold miss
    _commit_cycle(
        fr, 4.0, sig=(("E", 512),), full_encodes=4, regime_flip=1,
    )
    ev = obs.anomalies()[-1]
    assert ev["class"] == "recompile"
    assert ev["detail"]["dims"] == []
    assert obs.anomaly_counts["fold_miss"] == 1  # unchanged


def test_wedge_precursor_from_strike_deltas():
    fr, obs = _observed()
    _commit_cycle(fr, 0.0, retry_strikes_total=2)  # pre-existing strikes
    assert obs.anomalies() == []  # first observation is the anchor
    _commit_cycle(fr, 1.0, retry_strikes_total=2)
    assert obs.anomalies() == []  # no new strikes
    rec = _commit_cycle(fr, 2.0, retry_strikes_total=5)
    evs = obs.anomalies()
    assert [e["class"] for e in evs] == ["wedge_precursor"]
    assert evs[0]["seq"] == rec.seq
    assert evs[0]["detail"]["strikes"] == 3
    # the strike counter is PROCESS-global (RESILIENT_STRIKES): every
    # profile's record carries the same sum, so a multi-profile cycle
    # must not raise the same strike once per profile
    _commit_cycle(fr, 3.0, profile="gpu-sched", retry_strikes_total=5)
    _commit_cycle(fr, 3.1, retry_strikes_total=5)
    assert obs.anomaly_counts["wedge_precursor"] == 1
    _commit_cycle(fr, 4.0, profile="gpu-sched", retry_strikes_total=6)
    _commit_cycle(fr, 4.1, retry_strikes_total=6)
    assert obs.anomaly_counts["wedge_precursor"] == 2  # one new strike


def test_anomaly_ring_is_bounded_and_last_filters():
    fr, obs = _observed(ring=8)
    for i in range(8):
        _commit_cycle(fr, float(i), device_ms=5.0)
    for i in range(20):
        _commit_cycle(fr, 100.0 + i, device_ms=28_000.0)
    assert obs.anomaly_counts["tunnel_stall"] == 20  # counts keep going
    assert len(obs.anomalies()) == 8  # ring stays bounded
    assert len(obs.anomalies(last=3)) == 3
    assert obs.anomalies(last=0) == []


def test_failing_observer_detaches_without_killing_the_loop():
    fr = FlightRecorder(capacity=8)
    calls = {"n": 0}

    def bad(rec):
        calls["n"] += 1
        raise RuntimeError("observer bug")

    fr.observers.append(bad)
    _commit_cycle(fr, 0.0)
    _commit_cycle(fr, 1.0)  # does not raise
    assert calls["n"] == 1  # detached after the first failure
    assert fr.observers == []
    assert fr.cycles == 2


# ---- SLO engine ----------------------------------------------------------


def test_slo_engine_burn_rate_and_budget():
    slo = SloEngine(p99_ms=100.0, window_cycles=256)
    assert slo.enabled
    for _ in range(256):
        slo.note(0.05)  # 50 ms: within objective
    assert slo.burn_rate("fast") == 0.0
    assert slo.budget_remaining() == 1.0
    assert not slo.degraded()
    # fast window (256/16 = 16 cycles) of pure violations: burn rate
    # 1.0/0.01 = 100x, way past the 6x degraded threshold
    for _ in range(16):
        assert slo.note(0.5) is True
    assert slo.burn_rate("fast") == 100.0
    assert slo.degraded()
    # slow window: 16 violations vs a budget of 1% of 256 cycles
    assert abs(slo.burn_rate("slow") - (16 / 256) / 0.01) < 1e-9
    assert slo.budget_remaining() < 0  # overspent
    st = slo.status()
    assert st["degraded"] and st["violations"] == 16
    # disabled objective: everything reads neutral
    off = SloEngine(p99_ms=0.0)
    off.note(999.0)
    assert not off.enabled and not off.degraded()
    assert off.burn_rate("fast") == 0.0 and off.budget_remaining() == 1.0


def test_healthz_reports_fast_burn_as_degraded_not_503():
    fr, obs = _observed(slo_p99_ms=10.0, slo_window_cycles=256)
    health = staleness_healthz(lambda: {"bootId": "b"}, fr, 0.0,
                               observer=obs)
    ok, detail = health()
    assert ok and "slo" in detail and "degraded" not in detail
    for i in range(16):
        _commit_cycle(fr, float(i), device_ms=50.0)  # ~53 ms cycles
    ok, detail = health()
    assert ok  # degraded is a paging signal, not a liveness failure
    assert detail["degraded"] is True
    assert "fast-burn" in detail["degraded_reason"]
    assert detail["slo"]["burn_rate"]["fast"] >= 6.0


def test_slo_config_plumbs_to_observer():
    from k8s_scheduler_tpu.config.types import load_config
    from k8s_scheduler_tpu.core import Scheduler

    cfg = load_config("sloP99Ms: 250\nsloWindowCycles: 512")
    assert cfg.slo_p99_ms == 250.0 and cfg.slo_window_cycles == 512
    sched = Scheduler(config=cfg)
    assert sched.observer is not None
    assert sched.observer.slo.p99_ms == 250.0
    assert sched.observer.slo.windows["slow"].maxlen == 512
    # recorder disabled -> no records to observe -> no observer
    cfg_off = load_config("flightRecorderSize: 0")
    assert Scheduler(config=cfg_off).observer is None


# ---- bench classifier ----------------------------------------------------


def test_classify_latency_series_counts_stalls():
    clean = [0.1] * 100
    assert classify_latency_series(clean) == {}
    with_stall = clean + [28.0]
    counts = classify_latency_series(with_stall)
    assert counts == {"tunnel_stall": 1}
    # every reported class is a member of the canonical inventory
    assert set(counts) <= set(ANOMALY_CLASSES)


# ---- debug endpoints -----------------------------------------------------


def _request(url, method="GET"):
    req = urllib.request.Request(url, method=method)
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_debug_anomalies_endpoint_shape_and_head_405():
    m = SchedulerMetrics()
    fr, obs = _observed(metrics=m)
    for i in range(8):
        _commit_cycle(fr, float(i), device_ms=5.0)
    stalled = _commit_cycle(fr, 100.0, device_ms=28_000.0)
    server = start_http_server(m, port=0, observer=obs)
    port = server.server_address[1]
    base = f"http://127.0.0.1:{port}"
    try:
        st, _, body = _request(f"{base}/debug/anomalies")
        assert st == 200
        payload = json.loads(body)
        assert [e["class"] for e in payload["anomalies"]] == [
            "tunnel_stall"
        ]
        assert payload["anomalies"][0]["seq"] == stalled.seq
        assert payload["anomaly_counts"]["tunnel_stall"] == 1
        assert payload["cycles"] == 9
        assert payload["phase_p50_ms"]["device"] > 0
        assert payload["slo"]["enabled"] is False
        # ?last=N trims the ring view, not the counters
        st, _, body = _request(f"{base}/debug/anomalies?last=1")
        assert json.loads(body)["anomaly_counts"]["tunnel_stall"] == 1
        # HEAD parity + 405 for mutating verbs, like every debug route
        gs, gh, gbody = _request(f"{base}/debug/anomalies")
        hs, hh, hbody = _request(f"{base}/debug/anomalies", "HEAD")
        assert (gs, hs) == (200, 200) and hbody == b""
        assert hh["Content-Length"] == str(len(gbody))
        st, headers, _ = _request(f"{base}/debug/anomalies", "POST")
        assert st == 405 and headers["Allow"] == "GET, HEAD"
    finally:
        server.shutdown()
    # without an observer the route 404s like other absent debug routes
    bare = start_http_server(SchedulerMetrics(), port=0)
    bport = bare.server_address[1]
    try:
        st, _, _ = _request(f"http://127.0.0.1:{bport}/debug/anomalies")
        assert st == 404
    finally:
        bare.shutdown()


def test_debug_trace_pod_filter_slices_to_touched_cycles():
    fr = FlightRecorder(capacity=16)
    for i in range(4):
        _commit_cycle(fr, float(i))
    # pod uid-1 was attempted in cycle 2 only (the timeline join key)
    fr.pod_event("uid-1", "pod-1", "Queued")
    fr.pod_event("uid-1", "pod-1", "Attempt", cycle=2, result="Bound")
    server = start_http_server(
        SchedulerMetrics(), port=0, recorder=fr,
        pod_timeline=fr.pods.get,
    )
    port = server.server_address[1]
    base = f"http://127.0.0.1:{port}"
    try:
        st, headers, body = _request(f"{base}/debug/trace?pod=uid-1")
        assert st == 200
        assert "attachment" in headers["Content-Disposition"]
        trace = json.loads(body)
        devices = [
            e["name"] for e in trace["traceEvents"]
            if e.get("ph") == "X" and e["name"].startswith("device cycle")
        ]
        assert devices == ["device cycle[2] slot=-1"]
        # the unfiltered trace still carries every cycle
        st, _, body = _request(f"{base}/debug/trace")
        full = json.loads(body)
        assert sum(
            1 for e in full["traceEvents"]
            if e.get("ph") == "X" and e["name"].startswith("device cycle")
        ) == 4
        # unknown pod: 404 with a JSON error, not an empty trace
        st, _, body = _request(f"{base}/debug/trace?pod=ghost")
        assert st == 404 and "not seen" in json.loads(body)["error"]
        # HEAD parity on the filtered route too
        hs, _, hbody = _request(f"{base}/debug/trace?pod=uid-1", "HEAD")
        assert hs == 200 and hbody == b""
    finally:
        server.shutdown()


# ---- live demonstration: the real scheduler ------------------------------


def test_live_scheduler_recompile_flip_attributed():
    """Drive the REAL Scheduler into a pad-regime flip: the second
    cycle's pending-pod count crosses the pad bucket, the packed regime
    rebuilds, and the observer must classify the recompile WITH the
    flipping dimension — within that same cycle."""
    from k8s_scheduler_tpu.core import Scheduler
    from k8s_scheduler_tpu.models import MakeNode, MakePod

    bound = {}
    sched = Scheduler(
        binder=lambda pod, node: bound.setdefault(pod.name, node),
        pad_bucket=8,
    )
    assert sched.observer is not None  # wired by the ctor
    for i in range(4):
        sched.on_node_add(
            MakeNode(f"n{i}").capacity({"cpu": "64"}).obj()
        )
    sched.on_pod_add(MakePod("p0").req({"cpu": "1"}).obj())
    sched.schedule_cycle()  # P pads to the first bucket
    assert sched.observer.anomalies() == []
    for i in range(1, 12):  # 12 pending pods: P crosses into bucket 16
        sched.on_pod_add(MakePod(f"p{i}").req({"cpu": "1"}).obj())
    sched.schedule_cycle()
    evs = [
        e for e in sched.observer.anomalies()
        if e["class"] == "recompile"
    ]
    assert len(evs) == 1
    ev = evs[0]
    assert "P" in ev["detail"]["dims"]
    assert (
        ev["detail"]["to_sig"]["P"] > ev["detail"]["from_sig"]["P"]
    )
    # the seq links to a real committed flight record of that cycle
    recs = {r.seq: r for r in sched.flight.snapshot()}
    assert ev["seq"] in recs
    assert recs[ev["seq"]].counts.get("regime_flip") == 1
    assert recs[ev["seq"]].phases.get("compile_ms", 0.0) >= 0.0
    # and the counter is visible on the metrics surface
    assert (
        'scheduler_anomalies_total{class="recompile"} 1.0'
        in sched.metrics.expose().decode()
    )
    assert len(bound) == 12  # scheduling itself was undisturbed
