"""Differential tests for the batched preemption pass (benchmark config #4
territory): ops/preemption.py vs oracle.preempt."""

import numpy as np

from k8s_scheduler_tpu import oracle
from k8s_scheduler_tpu.core import build_cycle_fn, build_preemption_fn
from k8s_scheduler_tpu.models import MakeNode, MakePod, SnapshotEncoder


def run_both(nodes, pods, existing=(), pdbs=()):
    snap = SnapshotEncoder().encode(nodes, pods, existing, pdbs=pdbs)
    cycle = build_cycle_fn()
    result = cycle(snap)
    pre = build_preemption_fn()(snap, result)
    got_nom = np.asarray(pre.nominated)[: len(pods)].tolist()
    got_victims = sorted(np.flatnonzero(np.asarray(pre.victims)).tolist())

    decisions, preemptions = oracle.schedule_with_preemption(
        nodes, pods, existing, pdbs=pdbs
    )
    want_nom = [-1] * len(pods)
    want_victims = []
    for pr in preemptions:
        want_nom[pr.pod_index] = pr.node_index
        want_victims.extend(pr.victims)
    return (got_nom, got_victims), (want_nom, sorted(want_victims)), (
        result, pre, decisions)


def test_basic_preemption_evicts_lowest_priority():
    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    existing = [
        (MakePod("victim-lo").req({"cpu": "1"}).priority(1).obj(), "n0"),
        (MakePod("bystander").req({"cpu": "900m"}).priority(5).obj(), "n0"),
    ]
    pods = [MakePod("urgent").req({"cpu": "1"}).priority(10).obj()]
    got, want, (_, pre, _) = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] == [0]  # nominated n0
    assert got[1] == [0]  # evicts victim-lo only (index 0 in existing)
    assert int(pre.num_preemptors) == 1


def test_no_preemption_when_higher_priority():
    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    existing = [
        (MakePod("e0").req({"cpu": "1800m"}).priority(100).obj(), "n0"),
    ]
    pods = [MakePod("p0").req({"cpu": "1"}).priority(10).obj()]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want == ([-1], [])


def test_preemption_policy_never():
    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    existing = [
        (MakePod("e0").req({"cpu": "1800m"}).priority(1).obj(), "n0"),
    ]
    pods = [
        MakePod("p0").req({"cpu": "1"}).priority(10)
        .preemption_policy("Never").obj()
    ]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want == ([-1], [])


def test_minimal_victim_set():
    # evicting one 1-cpu victim suffices; the other stays
    nodes = [MakeNode("n0").capacity({"cpu": "3"}).obj()]
    existing = [
        (MakePod("v0").req({"cpu": "1"}).priority(1).obj(), "n0"),
        (MakePod("v1").req({"cpu": "1"}).priority(2).obj(), "n0"),
        (MakePod("v2").req({"cpu": "900m"}).priority(8).obj(), "n0"),
    ]
    pods = [MakePod("p0").req({"cpu": "1"}).priority(10).obj()]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want
    assert got[1] == [0]  # only the lowest-priority victim


def test_picks_node_with_cheapest_victims():
    # n0's victims are priority 5; n1's victim is priority 1 -> prefer n1
    nodes = [
        MakeNode("n0").capacity({"cpu": "1"}).obj(),
        MakeNode("n1").capacity({"cpu": "1"}).obj(),
    ]
    existing = [
        (MakePod("a").req({"cpu": "1"}).priority(5).obj(), "n0"),
        (MakePod("b").req({"cpu": "1"}).priority(1).obj(), "n1"),
    ]
    pods = [MakePod("p0").req({"cpu": "1"}).priority(10).obj()]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] == [1]
    assert got[1] == [1]


def test_two_preemptors_do_not_share_victims():
    # two urgent pods, one node with two evictable 1-cpu victims: each
    # preemptor must claim a DIFFERENT victim's capacity
    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    existing = [
        (MakePod("v0").req({"cpu": "1"}).priority(1).obj(), "n0"),
        (MakePod("v1").req({"cpu": "1"}).priority(2).obj(), "n0"),
    ]
    pods = [
        MakePod("p0").req({"cpu": "1"}).priority(10).created(1).obj(),
        MakePod("p1").req({"cpu": "1"}).priority(9).created(2).obj(),
    ]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] == [0, 0]
    assert got[1] == [0, 1]  # both victims evicted, one per preemptor


def test_second_preemptor_runs_out_of_victims():
    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    existing = [
        (MakePod("v0").req({"cpu": "2"}).priority(1).obj(), "n0"),
    ]
    pods = [
        MakePod("p0").req({"cpu": "2"}).priority(10).created(1).obj(),
        MakePod("p1").req({"cpu": "2"}).priority(9).created(2).obj(),
    ]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] == [0, -1]  # only the first preemptor gets a nomination


def test_static_filters_gate_candidates():
    # n1 is tainted: preemption must not nominate it even though evicting
    # its victim would free capacity
    nodes = [
        MakeNode("n0").capacity({"cpu": "1"}).obj(),
        MakeNode("n1").capacity({"cpu": "4"}).taint("k", "v").obj(),
    ]
    existing = [
        (MakePod("a").req({"cpu": "1"}).priority(1).obj(), "n0"),
        (MakePod("b").req({"cpu": "4"}).priority(1).obj(), "n1"),
    ]
    pods = [MakePod("p0").req({"cpu": "1"}).priority(10).obj()]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] == [0]


def test_nominated_node_honored_next_cycle():
    # feed the nomination back through the encoder: the pod schedules on
    # the nominated node once the victim is gone
    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    pods = [MakePod("p0").req({"cpu": "2"}).priority(10)
            .nominated("n0").obj()]
    snap = SnapshotEncoder().encode(nodes, pods, existing=())
    result = build_cycle_fn()(snap)
    assert np.asarray(result.assignment)[0] == 0


def test_schedulable_pods_do_not_preempt():
    nodes = [
        MakeNode("n0").capacity({"cpu": "1"}).obj(),
        MakeNode("n1").capacity({"cpu": "4"}).obj(),
    ]
    existing = [(MakePod("e0").req({"cpu": "1"}).priority(0).obj(), "n0")]
    pods = [MakePod("p0").req({"cpu": "1"}).priority(10).obj()]
    got, want, (result, pre, _) = run_both(nodes, pods, existing)
    assert got == want == ([-1], [])
    assert np.asarray(result.assignment)[0] == 1
    assert int(pre.num_preemptors) == 0


def test_pdb_protected_victim_truncates_prefix():
    from k8s_scheduler_tpu.models.api import LabelSelector, PodDisruptionBudget

    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    existing = [
        (MakePod("protected").req({"cpu": "1"}).priority(1)
         .labels({"app": "db"}).obj(), "n0"),
        (MakePod("free").req({"cpu": "900m"}).priority(2).obj(), "n0"),
    ]
    pods = [MakePod("urgent").req({"cpu": "1800m"}).priority(10).obj()]
    pdbs = [PodDisruptionBudget(
        "db-pdb", selector=LabelSelector(match_labels={"app": "db"}),
        disruptions_allowed=0,
    )]
    # the lowest-priority victim is PDB-protected: the prefix is truncated
    # at it, so no eviction set frees enough -> no preemption at all
    got, want, _ = run_both(nodes, pods, existing, pdbs=pdbs)
    assert got == want == ([-1], [])
    # with budget, the same setup preempts
    pdbs[0].disruptions_allowed = 1
    got, want, _ = run_both(nodes, pods, existing, pdbs=pdbs)
    assert got == want
    assert got[0] == [0]


def test_pdb_budget_consumed_within_cycle():
    from k8s_scheduler_tpu.models.api import LabelSelector, PodDisruptionBudget

    # two nodes, each holding one member of the same PDB group with
    # budget 1: only ONE preemptor may evict this cycle
    nodes = [MakeNode(f"n{i}").capacity({"cpu": "1"}).obj() for i in range(2)]
    existing = [
        (MakePod(f"m{i}").req({"cpu": "1"}).priority(1)
         .labels({"app": "db"}).created(float(i)).obj(), f"n{i}")
        for i in range(2)
    ]
    pods = [
        MakePod(f"hi{i}").req({"cpu": "1"}).priority(10)
        .created(float(10 + i)).obj()
        for i in range(2)
    ]
    pdbs = [PodDisruptionBudget(
        "db-pdb", selector=LabelSelector(match_labels={"app": "db"}),
        disruptions_allowed=1,
    )]
    got, want, _ = run_both(nodes, pods, existing, pdbs=pdbs)
    assert got == want
    assert sum(1 for n in got[0] if n >= 0) == 1
    assert len(got[1]) == 1


def test_start_time_tie_break_prefers_younger_victim():
    # two identical nodes/victims except start time: evict the younger
    nodes = [MakeNode(f"n{i}").capacity({"cpu": "1"}).obj() for i in range(2)]
    existing = [
        (MakePod("old").req({"cpu": "1"}).priority(1).created(100.0).obj(),
         "n0"),
        (MakePod("young").req({"cpu": "1"}).priority(1).created(200.0).obj(),
         "n1"),
    ]
    pods = [MakePod("hi").req({"cpu": "1"}).priority(10).obj()]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] == [1]  # n1 hosts the younger victim
    assert got[1] == [1]


def test_randomized_differential_preemption():
    rng = np.random.default_rng(7)
    for trial in range(6):
        n_nodes = int(rng.integers(2, 6))
        nodes = [
            MakeNode(f"n{i}").capacity(
                {"cpu": f"{int(rng.integers(1, 5))}", "memory": "8Gi"}
            ).obj()
            for i in range(n_nodes)
        ]
        existing = []
        for i in range(int(rng.integers(0, 8))):
            existing.append((
                MakePod(f"e{i}").req(
                    {"cpu": f"{int(rng.integers(200, 1500))}m"}
                ).priority(int(rng.integers(0, 6))).obj(),
                f"n{int(rng.integers(0, n_nodes))}",
            ))
        pods = [
            MakePod(f"p{i}").req(
                {"cpu": f"{int(rng.integers(500, 3000))}m"}
            ).priority(int(rng.integers(0, 12))).created(float(i)).obj()
            for i in range(int(rng.integers(1, 8)))
        ]
        got, want, _ = run_both(nodes, pods, existing)
        assert got == want, f"trial {trial}: {got} != {want}"
