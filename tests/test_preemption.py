"""Differential tests for the batched preemption pass (benchmark config #4
territory): ops/preemption.py vs oracle.preempt."""

import numpy as np

from k8s_scheduler_tpu import oracle
from k8s_scheduler_tpu.core import build_cycle_fn, build_preemption_fn
from k8s_scheduler_tpu.models import MakeNode, MakePod, SnapshotEncoder


def run_both(nodes, pods, existing=(), pdbs=()):
    snap = SnapshotEncoder().encode(nodes, pods, existing, pdbs=pdbs)
    cycle = build_cycle_fn()
    result = cycle(snap)
    pre = build_preemption_fn()(snap, result)
    got_nom = np.asarray(pre.nominated)[: len(pods)].tolist()
    got_victims = sorted(np.flatnonzero(np.asarray(pre.victims)).tolist())

    decisions, preemptions = oracle.schedule_with_preemption(
        nodes, pods, existing, pdbs=pdbs
    )
    want_nom = [-1] * len(pods)
    want_victims = []
    for pr in preemptions:
        want_nom[pr.pod_index] = pr.node_index
        want_victims.extend(pr.victims)
    return (got_nom, got_victims), (want_nom, sorted(want_victims)), (
        result, pre, decisions)


def test_basic_preemption_evicts_lowest_priority():
    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    existing = [
        (MakePod("victim-lo").req({"cpu": "1"}).priority(1).obj(), "n0"),
        (MakePod("bystander").req({"cpu": "900m"}).priority(5).obj(), "n0"),
    ]
    pods = [MakePod("urgent").req({"cpu": "1"}).priority(10).obj()]
    got, want, (_, pre, _) = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] == [0]  # nominated n0
    assert got[1] == [0]  # evicts victim-lo only (index 0 in existing)
    assert int(pre.num_preemptors) == 1


def test_no_preemption_when_higher_priority():
    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    existing = [
        (MakePod("e0").req({"cpu": "1800m"}).priority(100).obj(), "n0"),
    ]
    pods = [MakePod("p0").req({"cpu": "1"}).priority(10).obj()]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want == ([-1], [])


def test_preemption_policy_never():
    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    existing = [
        (MakePod("e0").req({"cpu": "1800m"}).priority(1).obj(), "n0"),
    ]
    pods = [
        MakePod("p0").req({"cpu": "1"}).priority(10)
        .preemption_policy("Never").obj()
    ]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want == ([-1], [])


def test_minimal_victim_set():
    # evicting one 1-cpu victim suffices; the other stays
    nodes = [MakeNode("n0").capacity({"cpu": "3"}).obj()]
    existing = [
        (MakePod("v0").req({"cpu": "1"}).priority(1).obj(), "n0"),
        (MakePod("v1").req({"cpu": "1"}).priority(2).obj(), "n0"),
        (MakePod("v2").req({"cpu": "900m"}).priority(8).obj(), "n0"),
    ]
    pods = [MakePod("p0").req({"cpu": "1"}).priority(10).obj()]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want
    assert got[1] == [0]  # only the lowest-priority victim


def test_picks_node_with_cheapest_victims():
    # n0's victims are priority 5; n1's victim is priority 1 -> prefer n1
    nodes = [
        MakeNode("n0").capacity({"cpu": "1"}).obj(),
        MakeNode("n1").capacity({"cpu": "1"}).obj(),
    ]
    existing = [
        (MakePod("a").req({"cpu": "1"}).priority(5).obj(), "n0"),
        (MakePod("b").req({"cpu": "1"}).priority(1).obj(), "n1"),
    ]
    pods = [MakePod("p0").req({"cpu": "1"}).priority(10).obj()]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] == [1]
    assert got[1] == [1]


def test_two_preemptors_do_not_share_victims():
    # two urgent pods, one node with two evictable 1-cpu victims: each
    # preemptor must claim a DIFFERENT victim's capacity
    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    existing = [
        (MakePod("v0").req({"cpu": "1"}).priority(1).obj(), "n0"),
        (MakePod("v1").req({"cpu": "1"}).priority(2).obj(), "n0"),
    ]
    pods = [
        MakePod("p0").req({"cpu": "1"}).priority(10).created(1).obj(),
        MakePod("p1").req({"cpu": "1"}).priority(9).created(2).obj(),
    ]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] == [0, 0]
    assert got[1] == [0, 1]  # both victims evicted, one per preemptor


def test_second_preemptor_runs_out_of_victims():
    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    existing = [
        (MakePod("v0").req({"cpu": "2"}).priority(1).obj(), "n0"),
    ]
    pods = [
        MakePod("p0").req({"cpu": "2"}).priority(10).created(1).obj(),
        MakePod("p1").req({"cpu": "2"}).priority(9).created(2).obj(),
    ]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] == [0, -1]  # only the first preemptor gets a nomination


def test_static_filters_gate_candidates():
    # n1 is tainted: preemption must not nominate it even though evicting
    # its victim would free capacity
    nodes = [
        MakeNode("n0").capacity({"cpu": "1"}).obj(),
        MakeNode("n1").capacity({"cpu": "4"}).taint("k", "v").obj(),
    ]
    existing = [
        (MakePod("a").req({"cpu": "1"}).priority(1).obj(), "n0"),
        (MakePod("b").req({"cpu": "4"}).priority(1).obj(), "n1"),
    ]
    pods = [MakePod("p0").req({"cpu": "1"}).priority(10).obj()]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] == [0]


def test_nominated_node_honored_next_cycle():
    # feed the nomination back through the encoder: the pod schedules on
    # the nominated node once the victim is gone
    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    pods = [MakePod("p0").req({"cpu": "2"}).priority(10)
            .nominated("n0").obj()]
    snap = SnapshotEncoder().encode(nodes, pods, existing=())
    result = build_cycle_fn()(snap)
    assert np.asarray(result.assignment)[0] == 0


def test_schedulable_pods_do_not_preempt():
    nodes = [
        MakeNode("n0").capacity({"cpu": "1"}).obj(),
        MakeNode("n1").capacity({"cpu": "4"}).obj(),
    ]
    existing = [(MakePod("e0").req({"cpu": "1"}).priority(0).obj(), "n0")]
    pods = [MakePod("p0").req({"cpu": "1"}).priority(10).obj()]
    got, want, (result, pre, _) = run_both(nodes, pods, existing)
    assert got == want == ([-1], [])
    assert np.asarray(result.assignment)[0] == 1
    assert int(pre.num_preemptors) == 0


def test_pdb_last_resort_eviction_places_pod():
    """SURVEY §3.4 / PARITY #4 (round 5): a pod placeable ONLY by
    violating a PDB gets placed, as upstream would — protected victims
    no longer truncate the prefix, they cost a violation in the node
    choice instead."""
    from k8s_scheduler_tpu.models.api import LabelSelector, PodDisruptionBudget

    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    existing = [
        (MakePod("protected").req({"cpu": "1"}).priority(1)
         .labels({"app": "db"}).obj(), "n0"),
        (MakePod("free").req({"cpu": "900m"}).priority(2).obj(), "n0"),
    ]
    pods = [MakePod("urgent").req({"cpu": "1800m"}).priority(10).obj()]
    pdbs = [PodDisruptionBudget(
        "db-pdb", selector=LabelSelector(match_labels={"app": "db"}),
        disruptions_allowed=0,
    )]
    # budget exhausted: the ONLY way to place the pod evicts the
    # protected victim — last-resort eviction does it
    got, want, _ = run_both(nodes, pods, existing, pdbs=pdbs)
    assert got == want
    assert got[0] == [0]
    assert sorted(got[1]) == [0, 1]  # both victims evicted
    # with budget available the same setup preempts without a violation
    pdbs[0].disruptions_allowed = 1
    got, want, _ = run_both(nodes, pods, existing, pdbs=pdbs)
    assert got == want
    assert got[0] == [0]


def test_pdb_zero_violation_node_preferred():
    """pickOneNodeForPreemption criterion #1: a node whose victims
    violate no PDB always beats a node that needs a violation — even
    when the violating node would win every later tie-break."""
    from k8s_scheduler_tpu.models.api import LabelSelector, PodDisruptionBudget

    nodes = [MakeNode(f"n{i}").capacity({"cpu": "1"}).obj() for i in range(2)]
    existing = [
        # n0's victim is protected but LOWER priority (would win the
        # max-victim-priority tie-break if violations didn't come first)
        (MakePod("prot").req({"cpu": "1"}).priority(1)
         .labels({"app": "db"}).obj(), "n0"),
        (MakePod("free").req({"cpu": "1"}).priority(2).obj(), "n1"),
    ]
    pods = [MakePod("hi").req({"cpu": "1"}).priority(10).obj()]
    pdbs = [PodDisruptionBudget(
        "db-pdb", selector=LabelSelector(match_labels={"app": "db"}),
        disruptions_allowed=0,
    )]
    got, want, _ = run_both(nodes, pods, existing, pdbs=pdbs)
    assert got == want
    assert got[0] == [1]  # the zero-violation node
    assert got[1] == [1]


def test_pdb_budget_consumed_within_cycle():
    from k8s_scheduler_tpu.models.api import LabelSelector, PodDisruptionBudget

    # two nodes, each holding one member of the same PDB group with
    # budget 1: the first preemptor consumes the budget; the second
    # places only via a LAST-RESORT violation (as upstream may)
    nodes = [MakeNode(f"n{i}").capacity({"cpu": "1"}).obj() for i in range(2)]
    existing = [
        (MakePod(f"m{i}").req({"cpu": "1"}).priority(1)
         .labels({"app": "db"}).created(float(i)).obj(), f"n{i}")
        for i in range(2)
    ]
    pods = [
        MakePod(f"hi{i}").req({"cpu": "1"}).priority(10)
        .created(float(10 + i)).obj()
        for i in range(2)
    ]
    pdbs = [PodDisruptionBudget(
        "db-pdb", selector=LabelSelector(match_labels={"app": "db"}),
        disruptions_allowed=1,
    )]
    got, want, _ = run_both(nodes, pods, existing, pdbs=pdbs)
    assert got == want
    assert sum(1 for n in got[0] if n >= 0) == 2
    assert len(got[1]) == 2


def test_start_time_tie_break_prefers_younger_victim():
    # two identical nodes/victims except start time: evict the younger
    nodes = [MakeNode(f"n{i}").capacity({"cpu": "1"}).obj() for i in range(2)]
    existing = [
        (MakePod("old").req({"cpu": "1"}).priority(1).created(100.0).obj(),
         "n0"),
        (MakePod("young").req({"cpu": "1"}).priority(1).created(200.0).obj(),
         "n1"),
    ]
    pods = [MakePod("hi").req({"cpu": "1"}).priority(10).obj()]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] == [1]  # n1 hosts the younger victim
    assert got[1] == [1]


def test_randomized_differential_preemption():
    from k8s_scheduler_tpu.models.api import LabelSelector, PodDisruptionBudget

    rng = np.random.default_rng(7)
    for trial in range(8):
        n_nodes = int(rng.integers(2, 6))
        nodes = [
            MakeNode(f"n{i}").capacity(
                {"cpu": f"{int(rng.integers(1, 5))}", "memory": "8Gi"}
            ).obj()
            for i in range(n_nodes)
        ]
        existing = []
        for i in range(int(rng.integers(0, 8))):
            b = MakePod(f"e{i}").req(
                {"cpu": f"{int(rng.integers(200, 1500))}m"}
            ).priority(int(rng.integers(0, 6)))
            if rng.random() < 0.5:  # half the victims sit under a PDB
                b = b.labels({"app": f"a{int(rng.integers(0, 2))}"})
            existing.append((b.obj(), f"n{int(rng.integers(0, n_nodes))}"))
        pods = [
            MakePod(f"p{i}").req(
                {"cpu": f"{int(rng.integers(500, 3000))}m"}
            ).priority(int(rng.integers(0, 12))).created(float(i)).obj()
            for i in range(int(rng.integers(1, 8)))
        ]
        # tight budgets so BOTH the violation-counting and the
        # last-resort path get exercised across trials
        pdbs = [
            PodDisruptionBudget(
                f"pdb-a{g}",
                selector=LabelSelector(match_labels={"app": f"a{g}"}),
                disruptions_allowed=int(rng.integers(0, 2)),
            )
            for g in range(2)
        ]
        got, want, _ = run_both(nodes, pods, existing, pdbs=pdbs)
        assert got == want, f"trial {trial}: {got} != {want}"


def test_pdb_multi_member_prefix_counts_violations_per_victim():
    """Upstream filterPodsWithPDBViolation decrements per victim: a
    budget-1 group with TWO members in one victim prefix yields exactly
    ONE violation — so it TIES (and then loses later tie-breaks or wins)
    against a node violating an exhausted group once, rather than
    scoring a bogus zero."""
    from k8s_scheduler_tpu.models.api import LabelSelector, PodDisruptionBudget

    nodes = [MakeNode(f"n{i}").capacity({"cpu": "1"}).obj() for i in range(2)]
    existing = [
        # n0: two 500m members of budget-1 group "a" (both must go)
        (MakePod("a0").req({"cpu": "500m"}).priority(1)
         .labels({"app": "a"}).created(50.0).obj(), "n0"),
        (MakePod("a1").req({"cpu": "500m"}).priority(1)
         .labels({"app": "a"}).created(60.0).obj(), "n0"),
        # n1: one 1-cpu member of exhausted group "b"
        (MakePod("b0").req({"cpu": "1"}).priority(1)
         .labels({"app": "b"}).created(70.0).obj(), "n1"),
    ]
    pods = [MakePod("hi").req({"cpu": "1"}).priority(10).obj()]
    pdbs = [
        PodDisruptionBudget(
            "pdb-a", selector=LabelSelector(match_labels={"app": "a"}),
            disruptions_allowed=1,
        ),
        PodDisruptionBudget(
            "pdb-b", selector=LabelSelector(match_labels={"app": "b"}),
            disruptions_allowed=0,
        ),
    ]
    got, want, _ = run_both(nodes, pods, existing, pdbs=pdbs)
    assert got == want
    # both nodes need exactly ONE violation; the tie moves to
    # max-victim-priority (equal), sum (2 vs 1 -> n1 wins), so the
    # correct per-victim counting is observable in the node choice
    assert got[0] == [1]
    assert got[1] == [2]
