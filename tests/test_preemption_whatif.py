"""Preemption what-if fidelity (VERDICT r2 item 3): victim removal frees
NON-RESOURCE constraints — anti-affinity toward a victim, a victim's host
port, DoNotSchedule spread pressure — exactly as upstream's re-run-the-
Filters-with-victims-removed does, and never breaks the preemptor's own
required affinity by evicting its last matching pod. Every case is
differential: the TPU kernel (scan cycle + PostFilter) must agree with
oracle.schedule_with_preemption.
"""

import numpy as np
import pytest

from k8s_scheduler_tpu import oracle
from k8s_scheduler_tpu.core import build_cycle_fn, build_preemption_fn
from k8s_scheduler_tpu.models import MakeNode, MakePod, SnapshotEncoder


def run_both(nodes, pending, existing, pdbs=()):
    enc = SnapshotEncoder(pad_pods=16, pad_nodes=8)
    snap = enc.encode(nodes, pending, existing, pdbs=pdbs)
    out = build_cycle_fn(commit_mode="scan")(snap)
    pre = build_preemption_fn()(snap, out)
    nominated = np.asarray(pre.nominated)[: len(pending)]
    victims = np.asarray(pre.victims)[: len(existing)]
    decisions, opre = oracle.schedule_with_preemption(
        nodes, pending, existing, pdbs=pdbs
    )
    want_nom = np.full(len(pending), -1, np.int64)
    want_vic = np.zeros(len(existing), bool)
    for o in opre:
        want_nom[o.pod_index] = o.node_index
        for e in o.victims:
            want_vic[e] = True
    assert nominated.tolist() == want_nom.tolist(), (
        f"nominations differ: kernel={nominated.tolist()} "
        f"oracle={want_nom.tolist()}"
    )
    assert victims.tolist() == want_vic.tolist(), (
        f"victims differ: kernel={victims.tolist()} "
        f"oracle={want_vic.tolist()}"
    )
    return nominated, victims


def test_anti_affinity_toward_victim_clears():
    # pod blocked ONLY by anti-affinity toward a lower-priority running
    # pod: evicting it must clear the constraint and nominate the node
    nodes = [MakeNode("node-0").capacity({"cpu": "8"}).obj()]
    victim = (
        MakePod("victim").req({"cpu": "1"}).labels({"app": "x"})
        .priority(0).obj()
    )
    pend = (
        MakePod("pend").req({"cpu": "1"}).priority(10)
        .pod_affinity("kubernetes.io/hostname", {"app": "x"}, anti=True)
        .obj()
    )
    nom, vic = run_both(nodes, [pend], [(victim, "node-0")])
    assert nom[0] == 0 and vic[0]


def test_victims_host_port_clears():
    nodes = [MakeNode("node-0").capacity({"cpu": "8"}).obj()]
    victim = (
        MakePod("victim").req({"cpu": "1"}).host_port(8080)
        .priority(0).obj()
    )
    pend = (
        MakePod("pend").req({"cpu": "1"}).host_port(8080)
        .priority(10).obj()
    )
    nom, vic = run_both(nodes, [pend], [(victim, "node-0")])
    assert nom[0] == 0 and vic[0]


def test_winner_held_port_never_clears():
    # the port-holder this cycle is a WINNER (placed, not evictable):
    # no nomination may rely on evicting it
    nodes = [MakeNode("node-0").capacity({"cpu": "2"}).obj()]
    winner = (
        MakePod("winner").req({"cpu": "1"}).host_port(8080)
        .priority(100).created(0.0).obj()
    )
    pend = (
        MakePod("pend").req({"cpu": "1"}).host_port(8080)
        .priority(10).created(1.0).obj()
    )
    lowprio = (
        MakePod("low").req({"cpu": "1"}).priority(0).obj()
    )
    nom, vic = run_both(
        nodes, [winner, pend], [(lowprio, "node-0")]
    )
    assert nom[1] == -1 and not vic.any()


def test_spread_pressure_clears_via_resource_eviction():
    # zone-a holds 2 matching pods; zone-b is resource-full with a
    # low-priority victim. DoNotSchedule maxSkew=1 blocks zone-a; only
    # evicting zone-b's victim gives the pod a home.
    za = {"topology.kubernetes.io/zone": "zone-a"}
    zb = {"topology.kubernetes.io/zone": "zone-b"}
    nodes = [
        MakeNode("node-0").capacity({"cpu": "8"}).labels(za).obj(),
        MakeNode("node-1").capacity({"cpu": "2"}).labels(zb).obj(),
    ]
    run_a1 = MakePod("a1").req({"cpu": "1"}).labels({"app": "s"}).obj()
    run_a2 = MakePod("a2").req({"cpu": "1"}).labels({"app": "s"}).obj()
    vic_b = MakePod("b-low").req({"cpu": "2"}).priority(0).obj()
    pend = (
        MakePod("pend").req({"cpu": "1"}).labels({"app": "s"})
        .priority(10)
        .spread(1, "topology.kubernetes.io/zone", {"app": "s"})
        .obj()
    )
    nom, vic = run_both(
        nodes, [pend],
        [(run_a1, "node-0"), (run_a2, "node-0"), (vic_b, "node-1")],
    )
    assert nom[0] == 1 and vic[2] and not vic[0] and not vic[1]


def test_eviction_must_not_break_required_affinity():
    # the pod's only affinity anchor is the lowest-priority pod on the
    # node: a prefix that evicts the anchor frees resources but breaks
    # the pod's required affinity, so no nomination can result
    nodes = [MakeNode("node-0").capacity({"cpu": "3"}).obj()]
    anchor = (
        MakePod("anchor").req({"cpu": "2"}).labels({"app": "y"})
        .priority(0).obj()
    )
    pend = (
        MakePod("pend").req({"cpu": "2"}).priority(10)
        .pod_affinity("kubernetes.io/hostname", {"app": "y"})
        .obj()
    )
    nom, vic = run_both(nodes, [pend], [(anchor, "node-0")])
    assert nom[0] == -1 and not vic.any()


def test_affinity_preserved_when_nonanchor_evictable():
    # same shape, but a separate low-priority filler frees the
    # resources; the anchor survives, so the nomination goes through
    nodes = [MakeNode("node-0").capacity({"cpu": "4"}).obj()]
    anchor = (
        MakePod("anchor").req({"cpu": "1"}).labels({"app": "y"})
        .priority(50).created(0.0).obj()
    )
    filler = (
        MakePod("filler").req({"cpu": "2"}).priority(0).created(1.0).obj()
    )
    pend = (
        MakePod("pend").req({"cpu": "2"}).priority(10)
        .pod_affinity("kubernetes.io/hostname", {"app": "y"})
        .obj()
    )
    nom, vic = run_both(
        nodes, [pend], [(anchor, "node-0"), (filler, "node-0")]
    )
    assert nom[0] == 0 and vic[1] and not vic[0]


def test_symmetric_anti_owner_eviction_clears():
    # the VICTIM owns the anti-affinity term (against app=z); the
    # pending pod carries app=z. Evicting the owner clears the
    # symmetric constraint.
    nodes = [MakeNode("node-0").capacity({"cpu": "8"}).obj()]
    owner = (
        MakePod("owner").req({"cpu": "1"}).priority(0)
        .pod_affinity("kubernetes.io/hostname", {"app": "z"}, anti=True)
        .obj()
    )
    pend = (
        MakePod("pend").req({"cpu": "1"}).labels({"app": "z"})
        .priority(10).obj()
    )
    nom, vic = run_both(nodes, [pend], [(owner, "node-0")])
    assert nom[0] == 0 and vic[0]


if __name__ == "__main__":
    import sys

    pytest.main([__file__, "-v"] + sys.argv[1:])
