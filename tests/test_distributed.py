"""Multi-host DCN bring-up (SURVEY.md §5.8, VERDICT r3 item 6): two
real `jax.distributed` CPU processes on localhost prove
`initialize_distributed` wiring, a cross-process collective, and a tiny
scheduling cycle sharded across both processes (equal to the replicated
run). Slow-marked: two interpreter starts + distributed init."""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_dcn_psum_and_sharded_cycle():
    port = _free_port()
    env = dict(os.environ)
    # 2 local CPU devices per process -> a 4-device global mesh. Consumed
    # at first backend use, well after sitecustomize's jax import.
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    )
    env.pop("JAX_PLATFORMS", None)  # workers flip platform after import
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_ROOT, "tests", "_dcn_worker.py"),
             str(port), str(pid), "2"],
            cwd=_ROOT,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert "INIT ok: processes=2 devices=4" in out, out
        assert "PSUM ok: " in out, out
        assert "CYCLE ok: placed=16 sharded==replicated" in out, out
