"""Per-pod failure reasons, wired end-to-end (SURVEY.md §3.3, §5.5): the
cycle attributes every rejected node to the first rejecting filter plugin
(upstream's per-node Status), the scheduler turns the counts into
FailedScheduling events + queueing-hint reasons, and the queue only
requeues on events that can cure one of the pod's reasons."""

import numpy as np

from k8s_scheduler_tpu.core import Scheduler
from k8s_scheduler_tpu.core.events import FAILED_SCHEDULING, SCHEDULED
from k8s_scheduler_tpu.internal.queue import (
    EVENT_NODE_UPDATE,
    EVENT_POD_DELETE,
)
from k8s_scheduler_tpu.models import MakeNode, MakePod

from test_scheduler_host import FakeClock, make_scheduler


def test_reject_counts_attribute_first_rejecting_plugin():
    """Three nodes, three distinct rejections: a cordoned node
    (NodeUnschedulable), a label mismatch (NodeAffinity), and a full node
    (NodeResourcesFit) — each attributed to its plugin, filter order
    deciding ties like upstream's first failing Status."""
    sched, cluster, clock = make_scheduler()
    sched.on_node_add(
        MakeNode("cordoned").capacity({"cpu": "8"}).labels({"disk": "ssd"})
        .unschedulable().obj()
    )
    sched.on_node_add(
        MakeNode("wrong-label").capacity({"cpu": "8"}).obj()
    )
    sched.on_node_add(
        MakeNode("full").capacity({"cpu": "1"}).labels({"disk": "ssd"}).obj()
    )
    pod = (
        MakePod("p").req({"cpu": "4"}).node_selector({"disk": "ssd"}).obj()
    )
    sched.on_pod_add(pod)
    stats = sched.schedule_cycle()
    assert stats.unschedulable == 1

    events = [e for e in sched.events.events() if e.reason == FAILED_SCHEDULING]
    assert len(events) == 1
    msg = events[0].message
    assert msg.startswith("0/3 nodes are available:")
    assert "1 NodeUnschedulable" in msg
    assert "1 NodeAffinity" in msg
    assert "1 NodeResourcesFit" in msg


def test_node_affinity_reject_ignores_pod_delete_event():
    """The QUEUEING_HINTS table must actually filter: a NodeAffinity-
    rejected pod stays unschedulable on PodDelete but moves on NodeUpdate
    (VERDICT r1 item 5 — previously every event requeued everything)."""
    sched, cluster, clock = make_scheduler()
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "8"}).obj())
    pod = (
        MakePod("p").req({"cpu": "1"}).node_selector({"disk": "ssd"}).obj()
    )
    sched.on_pod_add(pod)
    sched.schedule_cycle()
    assert sched.queue.pending_counts()["unschedulable"] == 1

    # PodDelete cannot cure a node-affinity failure -> stays put
    moved = sched.queue.move_all_to_active_or_backoff(EVENT_POD_DELETE)
    assert moved == 0
    assert sched.queue.pending_counts()["unschedulable"] == 1

    # NodeUpdate can -> moves (into backoff: window still running)
    moved = sched.queue.move_all_to_active_or_backoff(EVENT_NODE_UPDATE)
    assert moved == 1
    assert sched.queue.pending_counts()["unschedulable"] == 0


def test_resources_reject_requeues_on_pod_delete():
    """The complementary case: a resources-rejected pod DOES move on
    PodDelete (freed capacity can cure it)."""
    sched, cluster, clock = make_scheduler()
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "1"}).obj())
    pod = MakePod("p").req({"cpu": "4"}).obj()
    sched.on_pod_add(pod)
    sched.schedule_cycle()
    assert sched.queue.pending_counts()["unschedulable"] == 1
    assert sched.queue.move_all_to_active_or_backoff(EVENT_POD_DELETE) == 1


def test_scheduled_event_and_reason_metric():
    sched, cluster, clock = make_scheduler()
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "8"}).obj())
    sched.on_pod_add(MakePod("ok").req({"cpu": "1"}).obj())
    sched.on_pod_add(MakePod("too-big").req({"cpu": "64"}).obj())
    sched.schedule_cycle()

    reasons = {e.reason for e in sched.events.events()}
    assert {SCHEDULED, FAILED_SCHEDULING} <= reasons
    # the per-plugin unschedulable counter ticked for NodeResourcesFit
    v = sched.metrics.registry.get_sample_value(
        "scheduler_unschedulable_reasons_total",
        {"plugin": "NodeResourcesFit", "profile": "default-scheduler"},
    )
    assert v == 1.0


def test_gang_drop_reason_is_coscheduling():
    from k8s_scheduler_tpu.models.api import PodGroup

    sched, cluster, clock = make_scheduler()
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "2"}).obj())
    sched.add_pod_group(PodGroup("job", 3))
    for i in range(3):
        sched.on_pod_add(
            MakePod(f"j-{i}").req({"cpu": "1"}).group("job").obj()
        )
    stats = sched.schedule_cycle()
    assert stats.gang_dropped >= 1
    # gang members wait for events Coscheduling's hints accept; PodDelete
    # is one of them (freed capacity can let the whole group place)
    assert sched.queue.pending_counts()["unschedulable"] >= 1
    assert sched.queue.move_all_to_active_or_backoff(EVENT_POD_DELETE) >= 1
