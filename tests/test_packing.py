"""Packed snapshot transfer (models/packing.py): round-trip fidelity and
packed-program equivalence with the unpacked path."""

import dataclasses

import numpy as np

from k8s_scheduler_tpu.core import (
    build_cycle_fn,
    build_packed_cycle_fn,
    build_packed_preemption_fn,
)
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.models import packing
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods


def _snap():
    nodes = make_cluster(20, taint_fraction=0.2, cpu_choices=(4,))
    pods = make_pods(
        80, seed=11, affinity_fraction=0.3, anti_affinity_fraction=0.2,
        spread_fraction=0.2, selector_fraction=0.3, toleration_fraction=0.2,
        priorities=(0, 10), num_apps=6,
    )
    existing = [
        (p, f"node-{i % 20}")
        for i, p in enumerate(make_pods(40, seed=12, name_prefix="run"))
    ]
    return SnapshotEncoder().encode(nodes, pods, existing)


def test_pack_unpack_round_trip():
    snap = _snap()
    spec = packing.make_spec(snap)
    w, b = packing.pack(snap, spec)
    import jax

    back = jax.jit(lambda w, b: packing.unpack(w, b, spec))(w, b)
    for f in dataclasses.fields(snap):
        v = getattr(snap, f.name)
        r = getattr(back, f.name)
        if hasattr(v, "dtype"):
            assert np.array_equal(
                np.asarray(v), np.asarray(r), equal_nan=True
            ), f.name
        else:
            assert v == r, f.name


def test_packed_cycle_matches_unpacked():
    snap = _snap()
    spec = packing.make_spec(snap)
    w, b = packing.pack(snap, spec)
    out_u = build_cycle_fn(commit_mode="rounds")(snap)
    out_p = build_packed_cycle_fn(spec, commit_mode="rounds")(w, b)
    assert np.array_equal(
        np.asarray(out_u.assignment), np.asarray(out_p.assignment)
    )
    assert np.array_equal(
        np.asarray(out_u.unschedulable), np.asarray(out_p.unschedulable)
    )
    pre = build_packed_preemption_fn(spec)(w, b, out_p)
    assert np.asarray(pre.nominated).shape[0] == snap.P


def test_stable_state_injection_matches():
    from k8s_scheduler_tpu.core import build_stable_state_fn

    snap = _snap()
    spec = packing.make_spec(snap)
    w, b = packing.pack(snap, spec)
    out_u = build_cycle_fn(commit_mode="rounds")(snap)
    st = build_stable_state_fn(spec)(w, b)
    out_p = build_packed_cycle_fn(spec, commit_mode="rounds")(w, b, st)
    assert np.array_equal(
        np.asarray(out_u.assignment), np.asarray(out_p.assignment)
    )
    assert np.array_equal(
        np.asarray(out_u.reject_counts), np.asarray(out_p.reject_counts)
    )


def test_stable_state_reused_across_pending_changes():
    """The production contract: stable state computed from snapshot A is
    valid for snapshot B when only the PENDING side changed — a stable_fn
    entry that accidentally read pending-side data would fail this."""
    from k8s_scheduler_tpu.core import build_stable_state_fn

    nodes = make_cluster(20, taint_fraction=0.2, cpu_choices=(4,))
    existing = [
        (p, f"node-{i % 20}")
        for i, p in enumerate(make_pods(40, seed=12, name_prefix="run"))
    ]
    enc = SnapshotEncoder()
    pods_a = make_pods(
        60, seed=21, affinity_fraction=0.3, anti_affinity_fraction=0.2,
        spread_fraction=0.2, num_apps=6,
    )
    snap_a = enc.encode(nodes, pods_a, existing)
    spec = packing.make_spec(snap_a)
    wa, ba = packing.pack(snap_a, spec)
    st_a = build_stable_state_fn(spec)(wa, ba)

    pods_b = make_pods(
        60, seed=22, affinity_fraction=0.3, anti_affinity_fraction=0.2,
        spread_fraction=0.2, num_apps=6,
    )
    snap_b = enc.encode(nodes, pods_b, existing)
    spec_b = packing.make_spec(snap_b)
    assert spec_b.key() == spec.key(), "fixture must stay in one regime"
    wb, bb = packing.pack(snap_b, spec_b)

    cycle = build_packed_cycle_fn(spec, commit_mode="rounds")
    out_fresh = cycle(wb, bb, build_stable_state_fn(spec)(wb, bb))
    out_reused = cycle(wb, bb, st_a)  # snapshot A's stable state
    assert np.array_equal(
        np.asarray(out_fresh.assignment), np.asarray(out_reused.assignment)
    )
    assert np.array_equal(
        np.asarray(out_fresh.reject_counts),
        np.asarray(out_reused.reject_counts),
    )
