"""Differential tests: batched JAX cycle vs the sequential Python oracle
(benchmark config #1 territory: resource fit + least-requested/balanced)."""

import numpy as np
import pytest

from k8s_scheduler_tpu import oracle
from k8s_scheduler_tpu.core import build_cycle_fn
from k8s_scheduler_tpu.models import MakeNode, MakePod, SnapshotEncoder


def run_both(nodes, pods, existing=(), framework=None):
    snap = SnapshotEncoder().encode(nodes, pods, existing)
    result = build_cycle_fn(framework)(snap)
    got = np.asarray(result.assignment)[: len(pods)]
    want = [
        d.node_index
        for d in oracle.schedule(nodes, pods, existing,
                                 weights=oracle.OracleWeights())
    ]
    return got.tolist(), want, result


def test_single_pod_picks_least_loaded():
    nodes = [
        MakeNode("n0").capacity({"cpu": "4", "memory": "8Gi"}).obj(),
        MakeNode("n1").capacity({"cpu": "4", "memory": "8Gi"}).obj(),
    ]
    existing = [(MakePod("e0").req({"cpu": "2", "memory": "4Gi"}).obj(), "n0")]
    pods = [MakePod("p0").req({"cpu": "1", "memory": "1Gi"}).obj()]
    got, want, _ = run_both(nodes, pods, existing)
    assert got == want == [1]


def test_capacity_exhaustion_sequential_commit():
    # one node fits only two of the three pods: the third must go elsewhere
    nodes = [
        MakeNode("n0").capacity({"cpu": "2", "memory": "4Gi"}).obj(),
        MakeNode("n1").capacity({"cpu": "8", "memory": "16Gi"}).obj(),
    ]
    pods = [MakePod(f"p{i}").req({"cpu": "900m", "memory": "1Gi"}).obj()
            for i in range(6)]
    got, want, _ = run_both(nodes, pods)
    assert got == want


def test_unschedulable_when_full():
    nodes = [MakeNode("n0").capacity({"cpu": "1", "memory": "1Gi"}).obj()]
    pods = [MakePod(f"p{i}").req({"cpu": "800m"}).obj() for i in range(3)]
    got, want, result = run_both(nodes, pods)
    assert got == want
    assert got.count(-1) == 2
    assert np.asarray(result.unschedulable)[:3].sum() == 2


def test_priority_order_respected():
    # high-priority pod gets the only slot even though it's later in the list
    nodes = [MakeNode("n0").capacity({"cpu": "1"}).obj()]
    pods = [
        MakePod("low").req({"cpu": "800m"}).priority(0).obj(),
        MakePod("high").req({"cpu": "800m"}).priority(100).obj(),
    ]
    got, want, _ = run_both(nodes, pods)
    assert got == want == [-1, 0]


def test_node_name_pin():
    nodes = [MakeNode("n0").capacity({"cpu": "4"}).obj(),
             MakeNode("n1").capacity({"cpu": "4"}).obj()]
    pods = [MakePod("p0").req({"cpu": "1"}).node("n1").obj(),
            MakePod("p1").req({"cpu": "1"}).node("missing").obj()]
    got, want, _ = run_both(nodes, pods)
    assert got[0] == want[0] == 1
    assert got[1] == -1  # unknown node: infeasible (oracle agrees)
    assert want[1] == -1


def test_unschedulable_node_excluded():
    nodes = [MakeNode("n0").capacity({"cpu": "4"}).unschedulable().obj(),
             MakeNode("n1").capacity({"cpu": "4"}).obj()]
    pods = [MakePod("p0").req({"cpu": "1"}).obj()]
    got, want, _ = run_both(nodes, pods)
    assert got == want == [1]


@pytest.mark.parametrize("seed", range(5))
def test_randomized_differential(seed):
    rng = np.random.default_rng(seed)
    n_nodes, n_pods = int(rng.integers(3, 12)), int(rng.integers(5, 40))
    nodes = [
        MakeNode(f"n{i}").capacity(
            {"cpu": f"{rng.integers(2, 16)}", "memory": f"{rng.integers(4, 32)}Gi"}
        ).obj()
        for i in range(n_nodes)
    ]
    pods = [
        MakePod(f"p{i}")
        .req({"cpu": f"{rng.integers(100, 3000)}m",
              "memory": f"{rng.integers(256, 4096)}Mi"})
        .priority(int(rng.integers(0, 5)))
        .created(float(rng.integers(0, 100)))
        .obj()
        for i in range(n_pods)
    ]
    existing = []
    for i in range(int(rng.integers(0, 10))):
        existing.append(
            (MakePod(f"e{i}").req({"cpu": f"{rng.integers(100, 2000)}m"}).obj(),
             f"n{rng.integers(0, n_nodes)}")
        )
    got, want, _ = run_both(nodes, pods, existing)
    if got != want:
        # f32 near-ties may legitimately diverge; validate the trajectory
        errors = oracle.validate_assignment(nodes, pods, got, existing)
        assert not errors, errors


def test_jit_cache_reuse_across_cycles():
    # same padded shapes -> no recompile (pad buckets keep shapes stable)
    enc = SnapshotEncoder(pad_pods=16, pad_nodes=8)
    nodes = [MakeNode(f"n{i}").capacity({"cpu": "4"}).obj() for i in range(3)]
    cycle = build_cycle_fn()
    s1 = enc.encode(nodes, [MakePod("a").req({"cpu": "1"}).obj()])
    s2 = enc.encode(nodes, [MakePod("b").req({"cpu": "2"}).obj(),
                            MakePod("c").req({"cpu": "1"}).obj()])
    r1 = cycle(s1)
    assert cycle._cache_size() == 1
    r2 = cycle(s2)
    assert cycle._cache_size() == 1  # second cycle hit the compiled program
    assert np.asarray(r1.assignment).shape == np.asarray(r2.assignment).shape
