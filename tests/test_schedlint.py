"""schedlint framework tests: known-bad fixture snippets per pass —
including a reproduction of PR 1's lazy-import-under-trace bug and a
cache -> queue lock inversion — plus suppression/baseline round-trips
and the tier-1 gate that keeps the real tree clean.

Fixture trees are written under tmp_path and linted with
`run_lint(root=tmp_path, paths=["."])`; the passes detect their targets
structurally (jit entry points, PluginBase subclasses, `set_journal`
classes, lock attribute chains), so the fixtures need no imports of the
real package.
"""

from __future__ import annotations

import json
import os
import textwrap

import pytest

from k8s_scheduler_tpu.analysis import (
    default_registry,
    load_baseline,
    run_lint,
    write_baseline,
)
from k8s_scheduler_tpu.analysis.registry import PassRegistry, all_codes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_fixture(tmp_path, files: dict[str, str], passes=None,
                 baseline_path=None, paths=None):
    for rel, src in files.items():
        full = tmp_path / rel
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(textwrap.dedent(src))
    return run_lint(
        str(tmp_path),
        paths=paths or ["."],
        passes=passes,
        pass_args={"INVENTORY-DRIFT": {"metrics_runtime": False}},
        baseline_path=baseline_path,
    )


def codes_at(result, code):
    return [f for f in result.findings if f.code == code]


# ---- TRACE-SAFETY --------------------------------------------------------


def test_trace_safety_catches_pr1_lazy_import_under_trace(tmp_path):
    """The exact PR 1 bug shape: a PostFilter plugin lazily imports an
    ops module whose module-level jnp constants would be created under
    the active trace (UnexpectedTracerError on retrace)."""
    result = lint_fixture(tmp_path, {
        "pkg/ops/preemption.py": """\
            import jax.numpy as jnp

            _BIG_I32 = jnp.int32(2**31 - 1)


            def run_preemption(ctx):
                return _BIG_I32
        """,
        "pkg/plugins.py": """\
            class PluginBase:
                def post_filter(self, ctx):
                    return None


            class DefaultPreemption(PluginBase):
                def post_filter(self, ctx):
                    from .ops import preemption as preemption_ops
                    return preemption_ops.run_preemption(ctx)
        """,
    }, passes=["TRACE-SAFETY"])
    (f,) = codes_at(result, "TS001")
    assert f.file == "pkg/plugins.py"
    assert f.line == 8  # the lazy import inside the traced post_filter
    assert "jnp constants" in f.message
    assert "UnexpectedTracerError" in f.message


def test_trace_safety_walks_call_graph_from_jit_entry(tmp_path):
    """time/global/literal-constant violations in a helper are caught
    because the helper is reachable from a jax.jit'd closure — and NOT
    flagged in host-side build code."""
    result = lint_fixture(tmp_path, {
        "prog.py": """\
            import time

            import jax
            import jax.numpy as jnp


            def helper(x):
                return x + time.monotonic()


            def build():
                import math  # host side: runs at build, never traced

                def cycle(x):
                    global _COUNT
                    k = jnp.array([1, 2, 3])
                    return helper(x) + k.sum() + math.pi

                return jax.jit(cycle)
        """,
    }, passes=["TRACE-SAFETY"])
    (ts2,) = codes_at(result, "TS002")
    assert (ts2.file, ts2.line) == ("prog.py", 8)
    assert "time.monotonic" in ts2.message
    (ts3,) = codes_at(result, "TS003")
    assert ts3.line == 15
    (ts4,) = codes_at(result, "TS004")
    assert ts4.line == 16
    # the host-side `import math` inside build() must NOT be flagged
    assert codes_at(result, "TS001") == []


def test_trace_safety_covers_plugin_compute_hooks(tmp_path):
    result = lint_fixture(tmp_path, {
        "plug.py": """\
            import random


            class PluginBase:
                def static_mask(self, ctx):
                    return None


            class Jittery(PluginBase):
                def static_mask(self, ctx):
                    return random.random()

                def host_helper(self):
                    return random.random()  # not a compute hook: fine
        """,
    }, passes=["TRACE-SAFETY"])
    (f,) = codes_at(result, "TS002")
    assert f.line == 11
    assert "random" in f.message


def test_trace_safety_decorator_and_module_level_jit(tmp_path):
    """Roots are also found in decorator form (@partial(jax.jit, ...))
    and at module scope (`X = jax.jit(fn)`)."""
    result = lint_fixture(tmp_path, {
        "prog.py": """\
            import time
            from functools import partial

            import jax


            @partial(jax.jit, static_argnums=0)
            def decorated(n, x):
                return x + time.time()


            def module_target(x):
                return x + time.perf_counter()


            MODULE_JIT = jax.jit(module_target)
        """,
    }, passes=["TRACE-SAFETY"])
    assert sorted(f.line for f in codes_at(result, "TS002")) == [9, 13]


def test_trace_safety_from_datetime_import(tmp_path):
    """`from datetime import datetime` is the common import style; the
    bound class's .now() must still be caught under trace."""
    result = lint_fixture(tmp_path, {
        "prog.py": """\
            from datetime import datetime

            import jax


            def cycle(x):
                return x + datetime.now().timestamp()


            F = jax.jit(cycle)
        """,
    }, passes=["TRACE-SAFETY"])
    (f,) = codes_at(result, "TS002")
    assert f.line == 7 and "datetime" in f.message


# ---- LOCK-DISCIPLINE -----------------------------------------------------


def test_lock_discipline_catches_cache_queue_inversion(tmp_path):
    result = lint_fixture(tmp_path, {
        "internal/bad.py": """\
            class Mgr:
                def snapshot_good(self):
                    with self._queue._lock:
                        with self._cache._lock:
                            pass

                def snapshot_bad(self):
                    with self._cache._lock:
                        with self._queue._lock:
                            pass
        """,
    }, passes=["LOCK-DISCIPLINE"])
    (f,) = codes_at(result, "LD001")
    assert (f.file, f.line) == ("internal/bad.py", 9)
    assert "queue" in f.message and "cache" in f.message


def test_lock_discipline_catches_blocking_under_lock(tmp_path):
    """Direct fsync under the queue lock, and a transitive one through
    a helper (the propagation that makes the pass interprocedural)."""
    result = lint_fixture(tmp_path, {
        "state/bad.py": """\
            import os


            def fsync_helper(fh):
                os.fsync(fh)


            class Mgr:
                def emit_bad(self, fh):
                    with self._queue._lock:
                        os.fsync(fh)

                def flush_bad(self, fh):
                    with self.journal._cond:
                        fsync_helper(fh)

                def writer_ok(self, fh):
                    os.fsync(fh)  # no lock held: the writer-thread shape
        """,
    }, passes=["LOCK-DISCIPLINE"])
    found = codes_at(result, "LD002")
    assert [(f.line) for f in found] == [11, 15]
    assert "via fsync_helper" in found[1].message


def test_lock_discipline_catches_single_statement_inversion(tmp_path):
    """`with a, b:` acquires left-to-right — the one-line form of the
    inversion must be caught exactly like the nested form."""
    result = lint_fixture(tmp_path, {
        "internal/bad.py": """\
            class Mgr:
                def snapshot_bad(self):
                    with self._cache._lock, self._queue._lock:
                        pass
        """,
    }, passes=["LOCK-DISCIPLINE"])
    (f,) = codes_at(result, "LD001")
    assert f.line == 3 and "queue" in f.message


def test_lock_discipline_allows_documented_order(tmp_path):
    result = lint_fixture(tmp_path, {
        "state/good.py": """\
            class Mgr:
                def snapshot(self):
                    with self._queue._lock:
                        with self._cache._lock:
                            with self.journal._cond:
                                pass
        """,
    }, passes=["LOCK-DISCIPLINE"])
    assert result.findings == []


# ---- JOURNAL-EMIT-ONCE ---------------------------------------------------

_QUEUE_FIXTURE = """\
    class BadQueue:
        def set_journal(self, journal):
            self._journal = journal

        def _emit(self, op, t, data):
            if self._journal is not None:
                self._journal(op, t, data)

        def double_clock(self, pod):
            now = self._now()
            self._emit("q.add", self._now(), {})

        def double_emit(self, pod):
            now = self._now()
            self._emit("q.a", now, {})
            self._emit("q.b", now, {})

        def _sneaky_helper(self):
            self._emit("q.c", self._now(), {})

        def good(self, pod):
            now = self._now()
            self._emit("q.ok", now, {})
"""


def test_journal_emit_once_fixture(tmp_path):
    result = lint_fixture(
        tmp_path, {"q.py": _QUEUE_FIXTURE}, passes=["JOURNAL-EMIT-ONCE"]
    )
    je1 = codes_at(result, "JE001")
    assert [f.line for f in je1] == [9]  # double_clock (def line)
    assert "2 times" in je1[0].message
    (je2,) = codes_at(result, "JE002")
    assert je2.line == 13 and "2 journal emission sites" in je2.message
    (je3,) = codes_at(result, "JE003")
    assert je3.line == 18 and "_sneaky_helper" in je3.message
    # `good` and the funnel itself are silent
    assert all(f.line not in (5, 22) for f in result.findings)


def test_journal_emit_once_mutually_recursive_mutators(tmp_path):
    """Mutators that call each other must BOTH be flagged — the memo
    must not cache a cycle-truncated undercount (order-dependent false
    negative)."""
    result = lint_fixture(tmp_path, {
        "q.py": """\
            class CyclicQueue:
                def set_journal(self, journal):
                    self._journal = journal

                def _emit(self, op, t, data):
                    self._journal(op, t, data)

                def alpha(self, pod):
                    self._emit("q.a", self._now(), {})
                    self.beta(pod)

                def beta(self, pod):
                    self._emit("q.b", self._now(), {})
                    self.alpha(pod)
        """,
    }, passes=["JOURNAL-EMIT-ONCE"])
    je2_lines = sorted(f.line for f in codes_at(result, "JE002"))
    assert je2_lines == [8, 12]  # both alpha and beta over-emit


# ---- INVENTORY-DRIFT -----------------------------------------------------


def test_inventory_drift_config_and_cli_cross_checks(tmp_path):
    result = lint_fixture(tmp_path, {
        "config/types.py": """\
            class SchedulerConfiguration:
                foo_bar: int = 0
                lost_field: int = 0
                grace_seconds: float = 1.0


            def load_config(data):
                return SchedulerConfiguration(
                    foo_bar=data.get("fooBar", 0),
                    grace=data.get("grace", 1.0),
                    orphan=data.get("orphanKey", None),
                )
        """,
        "cmd/main.py": """\
            def new_scheduler_command(ap):
                ap.add_argument("--foo-bar", type=int)
                return ap


            def main(args, config):
                if args.foo_bar:
                    config.foo_bar = args.foo_bar
                if args.typo_flag:
                    config.not_a_field = 1
        """,
    }, passes=["INVENTORY-DRIFT"])
    id2 = codes_at(result, "ID002")
    assert {f.message.split()[0] for f in id2} == {
        "SchedulerConfiguration.lost_field", "load_config",
    }
    # grace_seconds <-> "grace" matches via the _seconds-stripping rule
    assert not any("grace" in f.message for f in id2)
    id3 = codes_at(result, "ID003")
    assert sorted(m.message.split(",")[0] for m in id3) == [
        "cmd/main.py reads args.typo_flag",
        "cmd/main.py references config.not_a_field",
    ]
    # no README.md in the fixture tree -> ID004 is skipped
    assert codes_at(result, "ID004") == []


def test_inventory_drift_phase_inventory_id005(tmp_path):
    """ID005: the cycle-phase inventory cannot drift between
    observe.PHASES, the trace lane mapping, the metrics docstring, and
    the README Observability section — each surface is checked with a
    seeded drift."""
    result = lint_fixture(tmp_path, {
        "core/observe.py": """\
            PHASES = ("total", "encode", "device")
        """,
        # drifted both ways: 'device' missing, stale 'fetch' mapped
        "core/flight_recorder.py": """\
            TRACE_LANE_FOR_PHASE = {
                "total": (1, "cycle"),
                "encode": (1, "encode"),
                "fetch": (1, "decision_wait"),
            }
        """,
        # the scheduler_cycle_phase_seconds entry names no 'encode';
        # the stray mention under ANOTHER family must not satisfy it
        "metrics/metrics.py": '''\
            """Families:

            - scheduler_cycle_phase_seconds{phase} — total, device
            - scheduler_other_total — counts encode events
            """
        ''',
        "README.md": """\
            # fixture

            ## Observability

            phases: total, encode (the third one goes undocumented)
        """,
    }, passes=["INVENTORY-DRIFT"])
    msgs = [f.message for f in codes_at(result, "ID005")]
    assert sum("missing from TRACE_LANE_FOR_PHASE" in m for m in msgs) == 1
    assert any("'device'" in m and "TRACE_LANE_FOR_PHASE" in m
               for m in msgs)
    assert any("'fetch'" in m and "stale lane mapping" in m for m in msgs)
    assert any("'encode'" in m and "metrics docstring" in m for m in msgs)
    assert any("'device'" in m and "README" in m for m in msgs)
    assert len(msgs) == 4

    # a consistent tree lints clean
    clean = lint_fixture(tmp_path / "clean", {
        "core/observe.py": 'PHASES = ("total",)\n',
        "core/flight_recorder.py":
            'TRACE_LANE_FOR_PHASE = {"total": (1, "cycle")}\n',
        "metrics/metrics.py":
            '"""- scheduler_cycle_phase_seconds{phase} — total"""\n',
        "README.md": "## Observability\n\ntotal\n",
    }, passes=["INVENTORY-DRIFT"])
    assert codes_at(clean, "ID005") == []

    # no literal PHASES tuple at all: the inventory anchor itself is
    # flagged (every other surface check would silently vanish with it)
    anchorless = lint_fixture(tmp_path / "anchorless", {
        "core/observe.py": "PHASES = tuple(x for x in ())\n",
    }, passes=["INVENTORY-DRIFT"])
    assert any(
        "no literal PHASES tuple" in f.message
        for f in codes_at(anchorless, "ID005")
    )


def test_inventory_drift_compile_key_id006(tmp_path):
    """ID006: the compile-cache key inventory cannot drift between
    packing.SIGNATURE_DIMS, compile_cache.SIG_KEY_FIELDS, and the
    README key table — a new pad dim without a key field would alias
    distinct programs into one persistent-cache entry."""
    result = lint_fixture(tmp_path, {
        # a NEW pad dimension "MV" joined the signature...
        "models/packing.py": """\
            SIGNATURE_DIMS = (
                ("P", "pod_valid", 0),
                ("N", "node_valid", 0),
                ("MV", "pod_vol_mode", 1),
            )
        """,
        # ...but the cache key still carries a STALE "E" and no "MV"
        "core/compile_cache.py": """\
            SIG_KEY_FIELDS = ("P", "N", "E")
            EXTRA_KEY_FIELDS = ("spec", "kind")
        """,
        # README documents P/N/E/spec but not MV or kind
        "README.md": """\
            # fixture

            ## Compile-regime management

            key fields: P, N, E, spec
        """,
    }, passes=["INVENTORY-DRIFT"])
    msgs = [f.message for f in codes_at(result, "ID006")]
    assert any("'MV'" in m and "no cache-key field" in m for m in msgs)
    assert any("'E'" in m and "stale key field" in m for m in msgs)
    assert any("'kind'" in m and "README" in m for m in msgs)
    # MV is absent from SIG_KEY_FIELDS so it is not README-checked;
    # the three seeded drifts are exactly what fires
    assert len(msgs) == 3

    # a consistent tree lints clean
    clean = lint_fixture(tmp_path / "clean", {
        "models/packing.py":
            'SIGNATURE_DIMS = (("P", "pod_valid", 0),)\n',
        "core/compile_cache.py":
            'SIG_KEY_FIELDS = ("P",)\n'
            'EXTRA_KEY_FIELDS = ("spec",)\n',
        "README.md":
            "## Compile-regime management\n\nP and spec\n",
    }, passes=["INVENTORY-DRIFT"])
    assert codes_at(clean, "ID006") == []

    # no SIG_KEY_FIELDS literal at all: the anchor itself is flagged
    anchorless = lint_fixture(tmp_path / "anchorless", {
        "models/packing.py":
            'SIGNATURE_DIMS = (("P", "pod_valid", 0),)\n',
        "core/compile_cache.py":
            "SIG_KEY_FIELDS = tuple(x for x in ())\n",
    }, passes=["INVENTORY-DRIFT"])
    assert any(
        "no literal" in f.message and "SIG_KEY_FIELDS" in f.message
        for f in codes_at(anchorless, "ID006")
    )

    # a missing README section is flagged when both code surfaces exist
    sectionless = lint_fixture(tmp_path / "sectionless", {
        "models/packing.py":
            'SIGNATURE_DIMS = (("P", "pod_valid", 0),)\n',
        "core/compile_cache.py":
            'SIG_KEY_FIELDS = ("P",)\n'
            'EXTRA_KEY_FIELDS = ()\n',
        "README.md": "# no such section\n",
    }, passes=["INVENTORY-DRIFT"])
    assert any(
        "Compile-regime management" in f.message
        for f in codes_at(sectionless, "ID006")
    )


def test_inventory_drift_rung_table_id007(tmp_path):
    """ID007: the degradation rung table cannot drift — every rung name
    in degrade.RUNGS must appear in the README "## Failure model &
    degradation ladder" section (operators act on rung names /healthz
    and the transition events carry)."""
    # a rung was renamed in code but not in the README table
    result = lint_fixture(tmp_path, {
        "core/degrade.py": """\
            RUNGS = (
                "normal",
                "retrace",
                "half_speed",
            )
        """,
        "README.md": """\
            # fixture

            ## Failure model & degradation ladder

            | 0 | normal | fine |
            | 1 | retrace | clear + rebuild |
        """,
    }, passes=["INVENTORY-DRIFT"])
    msgs = [f.message for f in codes_at(result, "ID007")]
    assert len(msgs) == 1 and "'half_speed'" in msgs[0]

    # consistent tree lints clean
    clean = lint_fixture(tmp_path / "clean", {
        "core/degrade.py": 'RUNGS = ("normal", "retrace")\n',
        "README.md": (
            "## Failure model & degradation ladder\n\n"
            "normal then retrace\n"
        ),
    }, passes=["INVENTORY-DRIFT"])
    assert codes_at(clean, "ID007") == []

    # the section itself missing is flagged
    sectionless = lint_fixture(tmp_path / "sectionless", {
        "core/degrade.py": 'RUNGS = ("normal",)\n',
        "README.md": "# no ladder section\n",
    }, passes=["INVENTORY-DRIFT"])
    assert any(
        "Failure model & degradation ladder" in f.message
        for f in codes_at(sectionless, "ID007")
    )

    # no literal RUNGS tuple: the anchor itself is flagged
    anchorless = lint_fixture(tmp_path / "anchorless", {
        "core/degrade.py": "RUNGS = tuple(n for n in ())\n",
        "README.md": (
            "## Failure model & degradation ladder\n\nwords\n"
        ),
    }, passes=["INVENTORY-DRIFT"])
    assert any(
        "no literal RUNGS" in f.message
        for f in codes_at(anchorless, "ID007")
    )


def test_inventory_drift_collective_budgets_id008(tmp_path):
    """ID008: the sharded-collective budget inventory cannot drift —
    every COLLECTIVE_BUDGETS class and every MESH_AXES axis name must
    appear in the README "## Multi-chip and multi-host" budget table
    (the audit gate asserts against the budgets; a class renamed
    without its doc row silently un-classifies the collectives it
    bounds)."""
    result = lint_fixture(tmp_path, {
        "parallel/audit.py": """\
            COLLECTIVE_BUDGETS = {
                "static_base": 2.0,
                "claim_sort": 4.0,
                "shiny_new_class": 1.0,
            }
        """,
        "parallel/mesh.py": 'MESH_AXES = ("pods", "racks")\n',
        "README.md": """\
            # fixture

            ## Multi-chip and multi-host

            | static_base | ... | | claim_sort | ... |
            the pods axis shards the batch
        """,
    }, passes=["INVENTORY-DRIFT"])
    msgs = [f.message for f in codes_at(result, "ID008")]
    assert any(
        "'shiny_new_class'" in m and "budget table" in m for m in msgs
    )
    assert any("'racks'" in m and "MESH_AXES" in m for m in msgs)
    assert len(msgs) == 2  # documented class/axis names do not fire

    # consistent tree lints clean
    clean = lint_fixture(tmp_path / "clean", {
        "parallel/audit.py": 'COLLECTIVE_BUDGETS = {"claim_sort": 1.0}\n',
        "parallel/mesh.py": 'MESH_AXES = ("pods",)\n',
        "README.md": (
            "## Multi-chip and multi-host\n\n"
            "claim_sort rides the pods axis\n"
        ),
    }, passes=["INVENTORY-DRIFT"])
    assert codes_at(clean, "ID008") == []

    # the README section itself missing is flagged
    sectionless = lint_fixture(tmp_path / "sectionless", {
        "parallel/audit.py": 'COLLECTIVE_BUDGETS = {"claim_sort": 1.0}\n',
        "parallel/mesh.py": 'MESH_AXES = ("pods",)\n',
        "README.md": "# no such section\n",
    }, passes=["INVENTORY-DRIFT"])
    assert any(
        "Multi-chip and multi-host" in f.message
        for f in codes_at(sectionless, "ID008")
    )

    # no literal COLLECTIVE_BUDGETS: the allowlist anchor is flagged
    anchorless = lint_fixture(tmp_path / "anchorless", {
        "parallel/audit.py":
            "COLLECTIVE_BUDGETS = dict((k, 1.0) for k in ())\n",
        "parallel/mesh.py": 'MESH_AXES = ("pods",)\n',
        "README.md": "## Multi-chip and multi-host\n\npods\n",
    }, passes=["INVENTORY-DRIFT"])
    assert any(
        "no literal" in f.message and "COLLECTIVE_BUDGETS" in f.message
        for f in codes_at(anchorless, "ID008")
    )

    # a non-literal MESH_AXES is flagged even with budgets intact
    axeless = lint_fixture(tmp_path / "axeless", {
        "parallel/audit.py": 'COLLECTIVE_BUDGETS = {"claim_sort": 1.0}\n',
        "parallel/mesh.py": "MESH_AXES = tuple(a for a in ())\n",
        "README.md": (
            "## Multi-chip and multi-host\n\nclaim_sort\n"
        ),
    }, passes=["INVENTORY-DRIFT"])
    assert any(
        "no literal MESH_AXES" in f.message
        for f in codes_at(axeless, "ID008")
    )


# ---- ROBUSTNESS ----------------------------------------------------------


def test_robustness_rb001_flags_silent_swallow_and_reraise(tmp_path):
    """RB001: a broad handler in core//state//internal/ that neither
    logs, counts, nor emits before swallowing (or bare-re-raising) is
    flagged; the same shape OUTSIDE the target dirs is not."""
    result = lint_fixture(tmp_path, {
        "pkg/core/a.py": """\
            def swallow():
                try:
                    work()
                except Exception:
                    pass


            def forward():
                try:
                    work()
                except Exception:
                    raise
        """,
        # same shapes outside core//state//internal/: not this pass's
        # business
        "pkg/tools/b.py": """\
            def swallow():
                try:
                    work()
                except Exception:
                    pass
        """,
    }, passes=["ROBUSTNESS"])
    findings = codes_at(result, "RB001")
    assert len(findings) == 2
    assert all(f.file == "pkg/core/a.py" for f in findings)
    assert findings[0].line == 4 and findings[1].line == 11


def test_robustness_rb001_accepts_log_metric_event_or_new_raise(tmp_path):
    result = lint_fixture(tmp_path, {
        "pkg/state/ok.py": """\
            import logging

            log = logging.getLogger(__name__)


            def logs():
                try:
                    work()
                except Exception:
                    log.exception("died")


            def counts(metrics):
                try:
                    work()
                except Exception:
                    metrics.journal_failures.labels("io").inc()
                    raise


            def emits(events):
                try:
                    work()
                except Exception as e:
                    events.system("Failed", str(e))


            def transforms():
                try:
                    work()
                except Exception as e:
                    raise RuntimeError(f"wrapped: {e}")
        """,
    }, passes=["ROBUSTNESS"])
    assert codes_at(result, "RB001") == []


def test_robustness_rb001_suppression_inventories_intentional(tmp_path):
    result = lint_fixture(tmp_path, {
        "pkg/internal/quiet.py": """\
            def deliberate():
                try:
                    work()
                except Exception:  # schedlint: disable=RB001 -- ok
                    pass
        """,
    }, passes=["ROBUSTNESS"])
    assert codes_at(result, "RB001") == []
    assert len(result.suppressed) == 1


def test_robustness_rb001_narrow_handlers_exempt(tmp_path):
    """Typed handlers (except OSError) are the caller's business —
    only the broad Exception/BaseException/bare shapes are audited."""
    result = lint_fixture(tmp_path, {
        "pkg/core/narrow.py": """\
            def narrow():
                try:
                    work()
                except OSError:
                    pass


            def bare():
                try:
                    work()
                except:
                    pass
        """,
    }, passes=["ROBUSTNESS"])
    findings = codes_at(result, "RB001")
    assert len(findings) == 1 and findings[0].line == 11


# ---- HYGIENE -------------------------------------------------------------


def test_hygiene_unused_import_and_dead_constant(tmp_path):
    result = lint_fixture(tmp_path, {
        "mod.py": """\
            import os
            import sys

            _DEAD = 42
            _ALIVE = 43


            def use():
                return sys.argv, _ALIVE
        """,
    }, passes=["HYGIENE"])
    (hy1,) = codes_at(result, "HY001")
    assert hy1.line == 1 and "'os'" in hy1.message
    (hy2,) = codes_at(result, "HY002")
    assert hy2.line == 4 and "_DEAD" in hy2.message


def test_hygiene_script_inventory_hy003(tmp_path):
    """HY003: a scripts/*.py outside SCRIPT_ALLOWLIST is flagged (dead
    one-off probes accumulated 25 deep before ISSUE 6 pruned them), a
    dangling allowlist entry is flagged against hygiene.py itself, and
    a package-scoped scan that never saw scripts/ judges neither."""
    result = lint_fixture(tmp_path, {
        "scripts/_one_off_probe.py": """\
            X = 1
        """,
    }, passes=["HYGIENE"])
    hy3 = codes_at(result, "HY003")
    assert any(
        f.file == "scripts/_one_off_probe.py"
        and "SCRIPT_ALLOWLIST" in f.message
        for f in hy3
    )
    # every maintained entry is dangling in this fixture tree — flagged
    # once each, against the allowlist's own file
    assert any("no such file exists" in f.message for f in hy3)
    # a scan that covered no scripts/ files must not judge the
    # allowlist at all (fresh tree: tmp_path still holds the fixture
    # above)
    pkg_only = lint_fixture(tmp_path / "pkg_only", {
        "mod.py": """\
            Y = 2
        """,
    }, passes=["HYGIENE"])
    assert not codes_at(pkg_only, "HY003")
    # staleness is judged against the DISK, not the scanned set: a
    # path-scoped scan of ONE allowlisted script (the CLI accepts file
    # paths) must not flag the other, existing, entries
    from k8s_scheduler_tpu.analysis.hygiene import SCRIPT_ALLOWLIST

    scoped = lint_fixture(tmp_path / "scoped", {
        rel: "X = 1\n" for rel in SCRIPT_ALLOWLIST
    }, passes=["HYGIENE"], paths=[sorted(SCRIPT_ALLOWLIST)[0]])
    assert not codes_at(scoped, "HY003")
    # ...but a scan that saw the pass's own module and NO scripts/ at
    # all (scripts/ deleted wholesale, allowlist left behind) must
    # still flag every dangling entry — HY003 must not self-disable on
    # exactly the drift it exists to catch
    gone = lint_fixture(tmp_path / "gone", {
        "k8s_scheduler_tpu/analysis/hygiene.py": "X = 1\n",
    }, passes=["HYGIENE"])
    assert len(codes_at(gone, "HY003")) == len(SCRIPT_ALLOWLIST)


# ---- suppressions & baseline --------------------------------------------


def test_inline_suppression_and_disable_file(tmp_path):
    result = lint_fixture(tmp_path, {
        "mod.py": """\
            import os  # schedlint: disable=HY001 -- kept for doc example
            import sys
        """,
        "legacy.py": """\
            # schedlint: disable-file=HY001
            import os
            import sys
        """,
    }, passes=["HYGIENE"])
    assert [f.file for f in codes_at(result, "HY001")] == ["mod.py"]
    assert len(result.suppressed) == 3
    (live,) = result.findings
    assert "'sys'" in live.message and live.line == 2


def test_hygiene_counts_string_annotation_use(tmp_path):
    """A name referenced only inside a quoted annotation is a use (the
    false positive that briefly deleted profiling.py's Iterable)."""
    result = lint_fixture(tmp_path, {
        "mod.py": """\
            from typing import Iterable


            def f(x: "Iterable[int]") -> "Iterable[int]":
                return x
        """,
    }, passes=["HYGIENE"])
    assert result.findings == []


def test_suppression_without_separator_still_applies(tmp_path):
    """A justification written without `--` must not be absorbed into
    the code list and void the suppression."""
    result = lint_fixture(tmp_path, {
        "mod.py": "import os  # schedlint: disable=HY001 kept on purpose\n",
    }, passes=["HYGIENE"])
    assert result.findings == [] and len(result.suppressed) == 1


def test_baseline_round_trip(tmp_path):
    files = {"mod.py": "import os\nimport sys\n"}
    first = lint_fixture(tmp_path, files, passes=["HYGIENE"])
    assert len(first.findings) == 2
    baseline = tmp_path / "baseline.json"
    write_baseline(str(baseline), first.findings)
    assert len(load_baseline(str(baseline))) == 2
    second = lint_fixture(
        tmp_path, files, passes=["HYGIENE"], baseline_path=str(baseline)
    )
    assert second.findings == [] and len(second.grandfathered) == 2
    # a NEW finding still fails even with the old baseline in place
    third = lint_fixture(
        tmp_path, {"mod.py": "import os\nimport sys\nimport json\n"},
        passes=["HYGIENE"], baseline_path=str(baseline),
    )
    assert len(third.findings) == 1 and "'json'" in third.findings[0].message


def test_registry_mirrors_framework_semantics():
    reg = default_registry()
    assert reg.names() == sorted([
        "TRACE-SAFETY", "JIT-PURITY", "LOCK-DISCIPLINE",
        "JOURNAL-EMIT-ONCE", "DURABILITY-ORDER",
        "INVENTORY-DRIFT", "HYGIENE", "ROBUSTNESS",
        "THREADS", "RACES", "SHARD-SAFETY", "TENANCY-ISOLATION",
    ])
    with pytest.raises(KeyError):
        reg.make("NOPE")
    dup = PassRegistry()
    dup.register("X", lambda args: None)
    with pytest.raises(ValueError):
        dup.register("X", lambda args: None)
    codes = all_codes(reg)
    assert codes["TS001"].startswith("import executed")
    # the mesh-era families are registered with their full code span
    assert {"TR001", "TR002", "TR003", "TR004",
            "SH001", "SH002", "SH003", "ID009", "TN001"} <= set(codes)
    # the effect-engine families likewise
    assert {"JP001", "JP002", "JP003", "JP004", "JP005", "JP006",
            "DO001", "DO002", "DO003"} <= set(codes)


def test_all_codes_raises_on_cross_pass_collision():
    """Two passes claiming the same finding code would make baselines,
    suppressions, and SARIF rules ambiguous — registration-time error."""
    from k8s_scheduler_tpu.analysis.registry import PassBase

    class A(PassBase):
        name = "A-PASS"
        codes = {"XX001": "from A"}

        def run(self, ctx):
            return []

    class B(PassBase):
        name = "B-PASS"
        codes = {"XX001": "from B", "XX002": "fine"}

        def run(self, ctx):
            return []

    reg = PassRegistry()
    reg.register("A-PASS", lambda args: A())
    reg.register("B-PASS", lambda args: B())
    with pytest.raises(ValueError, match="XX001.*A-PASS.*B-PASS"):
        all_codes(reg)


# ---- the tier-1 gate: the real tree lints clean --------------------------


def test_tree_is_clean():
    """All passes over the real package + scripts: zero unsuppressed,
    non-baselined findings. A finding here means new code broke a
    machine-checked invariant (or needs an inline justification)."""
    result = run_lint(
        REPO,
        baseline_path=os.path.join(REPO, ".schedlint-baseline.json"),
    )
    assert result.findings == [], "\n".join(str(f) for f in result.findings)
    # sanity floor only (a typo'd root scanning ~nothing must fail);
    # ISSUE 6 pruned the 25 stale one-off probe scripts, hence not ~100
    assert result.files_scanned > 70
    # the mesh-era pass families must actually be registered and run —
    # a green lint that silently dropped THREADS/RACES/SHARD-SAFETY
    # would be the exact drift this gate exists to catch
    assert {"THREADS", "RACES", "SHARD-SAFETY", "INVENTORY-DRIFT"} <= \
        set(result.passes_run)


def test_schedlint_cli_json_mode(tmp_path, capsys):
    """The acceptance-criterion invocation, via the CLI entry point:
    exit 0 on the tree and a --json payload drivers can diff."""
    import importlib.util

    path = os.path.join(REPO, "scripts", "schedlint.py")
    spec = importlib.util.spec_from_file_location("schedlint_cli", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(["--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["ok"] is True and out["findings"] == []
    assert set(out) >= {"files_scanned", "passes", "suppressed",
                        "grandfathered"}
    # a typo'd path must be a usage error (exit 2), never a green run
    # over zero files
    assert mod.main(["k8s_scheduler_tpuu"]) == 2
    capsys.readouterr()


# ---- THREADS / RACES (ISSUE 12) ------------------------------------------


def test_threads_tr003_lifecycle_stories(tmp_path):
    """TR003: a spawned thread needs a join, a drain-exit (reference
    cleared), or it is the CompileWarmer leak class — at the creation
    line. daemon=True alone is not a story; a dropped Thread object
    always fires."""
    result = lint_fixture(tmp_path, {
        "pkg/workers.py": """\
            import threading


            class Leaky:
                def spawn(self):
                    t = threading.Thread(target=self._run, daemon=True)
                    t.start()

                def _run(self):
                    pass


            class Dropper:
                def spawn(self):
                    threading.Thread(target=self._run).start()

                def _run(self):
                    pass


            class Joined:
                def start_worker(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def close(self):
                    self._t.join()

                def _run(self):
                    pass


            class Drainer:
                def submit(self):
                    self._w = threading.Thread(target=self._drain)
                    self._w.start()

                def _drain(self):
                    self._w = None
        """,
    }, passes=["THREADS"])
    tr3 = codes_at(result, "TR003")
    assert [(f.line) for f in tr3] == [6, 15]
    assert "daemon=True only hides the leak" in tr3[0].message
    assert "drops the Thread object" in tr3[1].message
    # Joined (module-level join) and Drainer (drain-exit clear) are clean
    assert all(f.line not in (23, 35) for f in tr3)


def test_threads_tr003_suppression_round_trip(tmp_path):
    result = lint_fixture(tmp_path, {
        "pkg/w.py": """\
            import threading


            def fire_and_forget(fn):
                threading.Thread(target=fn, daemon=True).start()  # schedlint: disable=TR003 -- process-lifetime metrics pump, exits with the interpreter
        """,
    }, passes=["THREADS"])
    assert codes_at(result, "TR003") == []
    assert len(result.suppressed) == 1


_RACE_FIXTURE = """\
    import threading


    class Journal:
        def emit(self, rec):
            with self._cond:
                self._writer = threading.Thread(
                    target=self._run, name="journal-writer"
                )
                self._writer.start()
            self.tally = 1

        def close(self):
            self._writer.join()

        def _run(self):
            self.tally = 2


    def schedule_cycle(j):
        j.emit(1)
"""


def test_races_tr001_cross_role_unlocked_write(tmp_path):
    """TR001: `tally` is written by the serve role (emit, reached from
    schedule_cycle) and the journal-writer role (_run, the Thread
    target) with no common lock — one finding per writing function, at
    the write line. The role set must name both roles."""
    result = lint_fixture(
        tmp_path, {"state/j.py": _RACE_FIXTURE}, passes=["RACES"]
    )
    tr1 = codes_at(result, "TR001")
    assert [f.line for f in tr1] == [11, 17]
    assert all("journal-writer" in f.message and "serve" in f.message
               for f in tr1)
    # the locked variant is clean: both writes under the same cond
    locked = _RACE_FIXTURE.replace(
        "            self.tally = 1",
        "            with self._cond:\n"
        "                self.tally = 1",
    ).replace(
        "            self.tally = 2",
        "            with self._cond:\n"
        "                self.tally = 2",
    )
    clean = lint_fixture(
        tmp_path / "locked", {"state/j.py": locked}, passes=["RACES"]
    )
    assert codes_at(clean, "TR001") == []


def test_races_tr001_init_writes_exempt(tmp_path):
    """Construction precedes every spawn: __init__ writing the same
    attribute a thread role writes must NOT count as a second role."""
    result = lint_fixture(tmp_path, {
        "core/w.py": """\
            import threading


            class W:
                def __init__(self):
                    self.count = 0
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def close(self):
                    self._t.join()

                def _run(self):
                    self.count = 1


            def schedule_cycle(w):
                w.close()
        """,
    }, passes=["RACES"])
    assert codes_at(result, "TR001") == []


def test_races_tr001_suppression_inventories(tmp_path):
    suppressed = _RACE_FIXTURE.replace(
        "            self.tally = 1",
        "            self.tally = 1  # schedlint: disable=TR001 -- "
        "seqlock publication: the writer is joined first",
    ).replace(
        "            self.tally = 2",
        "            self.tally = 2  # schedlint: disable=TR001 -- "
        "single writer in practice",
    )
    result = lint_fixture(
        tmp_path, {"state/j.py": suppressed}, passes=["RACES"]
    )
    assert codes_at(result, "TR001") == []
    assert len(result.suppressed) == 2


def test_races_tr002_lock_order_inversion_anywhere(tmp_path):
    """TR002: A->B in one function and B->A in another is flagged at
    BOTH inner acquisition sites — in any directory (the LD001
    generalization); the ranked queue/cache pairs stay LD001's."""
    result = lint_fixture(tmp_path, {
        "service/locks.py": """\
            class A:
                def one(self):
                    with self.alpha_lock:
                        with self.beta_lock:
                            pass

                def two(self):
                    with self.beta_lock:
                        with self.alpha_lock:
                            pass

                def consistent(self):
                    with self.alpha_lock:
                        with self.gamma_lock:
                            pass
        """,
    }, passes=["RACES"])
    tr2 = codes_at(result, "TR002")
    assert sorted(f.line for f in tr2) == [4, 9]
    assert all("ABBA" in f.message for f in tr2)
    # both-ranked pairs are LD001's jurisdiction, not TR002's
    ranked = lint_fixture(tmp_path / "ranked", {
        "service/m.py": """\
            class M:
                def good(self):
                    with self._queue._lock:
                        with self._cache._lock:
                            pass

                def bad(self):
                    with self._cache._lock:
                        with self._queue._lock:
                            pass
        """,
    }, passes=["RACES"])
    assert codes_at(ranked, "TR002") == []


def test_races_tr004_serve_blocking_under_contended_lock(tmp_path):
    """TR004: the serve role fsyncs while holding a lock a background
    role also acquires; the same blocking under an uncontended lock is
    that function's own business."""
    result = lint_fixture(tmp_path, {
        "core/srv.py": """\
            import os
            import threading


            class S:
                def worker(self):
                    with self._lock:
                        pass

                def start_worker(self):
                    self._t = threading.Thread(
                        target=self.worker, name="bg"
                    )
                    self._t.start()

                def close(self):
                    self._t.join()

                def Cycle(self, fh):
                    with self._lock:
                        os.fsync(fh)
                    with self._private_lock:
                        os.fsync(fh)
        """,
    }, passes=["RACES"])
    tr4 = codes_at(result, "TR004")
    assert [f.line for f in tr4] == [21]
    assert "os.fsync" in tr4[0].message and "bg" in tr4[0].message
    # line 23 (uncontended _private_lock) must not fire


def test_thread_roles_ride_the_callgraph(tmp_path):
    """The shared-callgraph contract under the new consumers: roles
    propagate through lax.scan/cond callbacks and Thread(target=...)
    first-args (both count as called), and a helper reachable from two
    roles only transitively carries both."""
    from k8s_scheduler_tpu.analysis.core import LintContext, load_tree
    from k8s_scheduler_tpu.analysis.threads import thread_roles

    (tmp_path / "prog.py").write_text(textwrap.dedent("""\
        import threading

        import jax


        def shared_helper(x):
            return x


        def scan_body(c, x):
            return c, shared_helper(x)


        def cond_branch(x):
            return shared_helper(x)


        def schedule_cycle(snap, flag):
            jax.lax.cond(flag, cond_branch, cond_branch, snap)
            return jax.lax.scan(scan_body, 0, snap)


        def writer_loop():
            shared_helper(1)


        def start():
            t = threading.Thread(target=writer_loop, name="writer")
            t.start()
            t.join()
    """))
    files = load_tree(str(tmp_path), ["."])
    ctx = LintContext(str(tmp_path), files)
    sites, role_of = thread_roles(ctx)
    (site,) = sites
    assert site.role == "writer" and site.target_ids
    # Thread target first-arg: the writer role rides into the target...
    assert "writer" in role_of["prog.py::writer_loop"]
    # ...and the scan/cond callbacks carry the serve role
    assert "serve" in role_of["prog.py::scan_body"]
    assert "serve" in role_of["prog.py::cond_branch"]
    # the transitive helper is reachable from BOTH roles
    assert {"serve", "writer"} <= role_of["prog.py::shared_helper"]


def test_races_tr001_seeded_mutation_in_real_journal(tmp_path):
    """The acceptance-criterion mutation: delete the lock acquisition
    around state/journal.py's cut() (a cross-role attribute write —
    the writer's size rotation also bumps _cur_index) and TR001 must
    fire; the unmutated file stays clean."""
    src = open(
        os.path.join(REPO, "k8s_scheduler_tpu/state/journal.py"),
        encoding="utf-8",
    ).read()
    locked = (
        "        with self._cond:\n"
        "            if self._cur_count:\n"
        "                self._cur_index += 1\n"
        "                self._cur_count = 0\n"
        "            return self._cur_index\n"
    )
    assert locked in src, "journal.cut() changed; update this mutation"
    unlocked = (
        "        if self._cur_count:\n"
        "            self._cur_index += 1\n"
        "            self._cur_count = 0\n"
        "        return self._cur_index\n"
    )
    mutated = src.replace(locked, unlocked)
    # a serve-side driver so cut() carries the serve role (in the real
    # tree that role arrives via DurableState.snapshot)
    driver = "def schedule_cycle(j):\n    j.cut()\n"
    bad = lint_fixture(tmp_path, {
        "state/journal.py": mutated, "state/driver.py": driver,
    }, passes=["RACES"])
    line = mutated.splitlines().index(
        "            self._cur_index += 1"
    ) + 1
    tr1 = codes_at(bad, "TR001")
    assert any(
        f.line == line and "_cur_index" in f.message for f in tr1
    ), [str(f) for f in tr1]
    clean = lint_fixture(tmp_path / "clean", {
        "state/journal.py": src, "state/driver.py": driver,
    }, passes=["RACES"])
    assert not any(
        "_cur_index" in f.message for f in codes_at(clean, "TR001")
    )


# ---- SHARD-SAFETY --------------------------------------------------------


def test_shard_safety_sh001_sh002_mesh_reachable_only(tmp_path):
    result = lint_fixture(tmp_path, {
        "pkg/engine.py": """\
            import jax
            import jax.numpy as jnp


            def rounds_commit(scores, parts):
                best = jnp.argmax(scores, axis=1)
                vals, idx = jax.lax.top_k(scores, 4)
                joined = jnp.concatenate(parts)
                safe = jnp.concatenate(parts, axis=1)
                return best, vals, idx, joined, safe


            def host_helper(scores):
                return jnp.argmax(scores)
        """,
    }, passes=["SHARD-SAFETY"])
    sh1 = codes_at(result, "SH001")
    assert [f.line for f in sh1] == [6, 7]
    assert "argsel.argmax_first" in sh1[0].message
    assert "top_k_first" in sh1[1].message
    (sh2,) = codes_at(result, "SH002")
    assert sh2.line == 8  # axis=1 on line 9 is exempt
    # host_helper is NOT reachable from a mesh root: silent
    assert all(f.line != 14 for f in result.findings)


def test_shard_safety_sh001_clean_with_argsel(tmp_path):
    result = lint_fixture(tmp_path, {
        "pkg/engine.py": """\
            from . import argsel


            def rounds_commit(scores):
                return argsel.argmax_first(scores, axis=1)
        """,
        "pkg/argsel.py": """\
            def argmax_first(x, axis=-1):
                return x
        """,
    }, passes=["SHARD-SAFETY"])
    assert result.findings == []


def test_shard_safety_sh003_spec_outside_mesh_module(tmp_path):
    result = lint_fixture(tmp_path, {
        "pkg/parallel/mesh.py": """\
            from jax.sharding import NamedSharding, PartitionSpec


            def mesh_pin(arr, mesh, axes):
                return NamedSharding(mesh, PartitionSpec(*axes))
        """,
        "pkg/rogue.py": """\
            from jax.sharding import PartitionSpec


            def layout():
                return PartitionSpec("pods")
        """,
    }, passes=["SHARD-SAFETY"])
    sh3 = codes_at(result, "SH003")
    assert [(f.file, f.line) for f in sh3] == [("pkg/rogue.py", 5)]
    assert "mesh_pin" in sh3[0].message


def test_shard_safety_seeded_mutation_in_real_rounds(tmp_path):
    """The acceptance-criterion mutation: swap ops/rounds.py's
    shard-invariant shortlist top_k back to raw lax.top_k and SH001
    must fire at that line; the committed file (with its inventoried
    suppressions) lints clean."""
    src = open(
        os.path.join(REPO, "k8s_scheduler_tpu/ops/rounds.py"),
        encoding="utf-8",
    ).read()
    good = "vals, sl = argsel.top_k_first(scored0, k)  # [B, k]"
    assert good in src, "rounds.py shortlist changed; update this test"
    mutated = src.replace(
        good, "vals, sl = jax.lax.top_k(scored0, k)  # [B, k]"
    )
    bad = lint_fixture(
        tmp_path, {"ops/rounds.py": mutated}, passes=["SHARD-SAFETY"]
    )
    line = mutated.splitlines().index(
        "            vals, sl = jax.lax.top_k(scored0, k)  # [B, k]"
    ) + 1
    sh1 = codes_at(bad, "SH001")
    assert [f.line for f in sh1] == [line]
    clean = lint_fixture(
        tmp_path / "clean", {"ops/rounds.py": src},
        passes=["SHARD-SAFETY"],
    )
    assert clean.findings == [], [str(f) for f in clean.findings]
    assert clean.suppressed  # the inventoried SH002/SH003 sites


# ---- TENANCY-ISOLATION ---------------------------------------------------


def test_tenancy_isolation_tn001_outside_package(tmp_path):
    """Any `_tn_*` attribute access outside k8s_scheduler_tpu/tenancy/
    crosses the virtual-cluster boundary — reads and writes both."""
    result = lint_fixture(tmp_path, {
        "pkg/core.py": """\
            def drain(tenant):
                pods = list(tenant._tn_pending.values())
                tenant._tn_bound = {}
                return pods
        """,
    }, passes=["TENANCY-ISOLATION"])
    tn = codes_at(result, "TN001")
    assert [f.line for f in tn] == [2, 3]
    assert "_tn_pending" in tn[0].message
    assert "TenantRegistry" in tn[0].message


def test_tenancy_isolation_clean_inside_package(tmp_path):
    """The same access is the NORMAL idiom inside tenancy/ — the pass
    pins the boundary, not the prefix."""
    result = lint_fixture(tmp_path, {
        "k8s_scheduler_tpu/tenancy/inside.py": """\
            def fold(tenant):
                return list(tenant._tn_pending.values())
        """,
        "k8s_scheduler_tpu/core/clean.py": """\
            def depth(registry, tid):
                return registry.depth(tid)
        """,
    }, passes=["TENANCY-ISOLATION"])
    assert result.findings == []


def test_tenancy_isolation_seeded_mutation_in_real_arena(tmp_path):
    """Acceptance mutation: make arena's fold read the LIVE pending
    dict from outside the package (the exact race encode_active's
    captures exist to prevent) and TN001 must fire; the committed
    tenancy files lint clean (they live inside the boundary)."""
    src = open(
        os.path.join(REPO, "k8s_scheduler_tpu/tenancy/arena.py"),
        encoding="utf-8",
    ).read()
    good = "for j, pod in enumerate(pending):"
    assert good in src, "arena fold changed; update this test"
    mutated = src.replace(
        good, "for j, pod in enumerate(tenant._tn_pending.values()):"
    )
    bad = lint_fixture(
        tmp_path, {"pkg/rogue_arena.py": mutated},
        passes=["TENANCY-ISOLATION"],
    )
    assert any(
        "_tn_pending" in f.message for f in codes_at(bad, "TN001")
    )
    clean = lint_fixture(
        tmp_path / "clean",
        {"k8s_scheduler_tpu/tenancy/arena.py": mutated},
        passes=["TENANCY-ISOLATION"],
    )
    assert clean.findings == []


# ---- ID009: the pass/code table pin --------------------------------------


def test_inventory_drift_code_table_id009(tmp_path):
    from k8s_scheduler_tpu.analysis.registry import all_codes

    codes = sorted(all_codes())
    # complete table (range notation for TS, singles for the rest)
    singles = " ".join(c for c in codes if not c.startswith("TS"))
    clean = lint_fixture(tmp_path / "clean", {
        "README.md": (
            "# fixture\n\n## Static analysis\n\n"
            f"| TRACE-SAFETY | `TS001`–`TS004` | ... |\n{singles}\n"
            "fingerprints are SHA256-based digests\n"  # prose tokens
            # outside the code families must never read as stale rows
        ),
    }, passes=["INVENTORY-DRIFT"])
    assert codes_at(clean, "ID009") == []

    # a registered code missing from the table + a stale row both fire
    partial = " ".join(c for c in codes if c != "SH003")
    drift = lint_fixture(tmp_path / "drift", {
        "README.md": (
            "## Static analysis\n\n" + partial + " TS999\n"
        ),
    }, passes=["INVENTORY-DRIFT"])
    msgs = [f.message for f in codes_at(drift, "ID009")]
    assert any("'SH003'" in m and "missing" in m for m in msgs)
    assert any("'TS999'" in m and "stale row" in m for m in msgs)
    assert len(msgs) == 2

    # no Static-analysis section at all: silent in fixture trees (no
    # registry module), flagged when the real registry rides along
    sectionless = lint_fixture(tmp_path / "sectionless", {
        "README.md": "# no such section\n",
    }, passes=["INVENTORY-DRIFT"])
    assert codes_at(sectionless, "ID009") == []
    anchored = lint_fixture(tmp_path / "anchored", {
        "README.md": "# no such section\n",
        "k8s_scheduler_tpu/analysis/registry.py": "X = 1\n",
    }, passes=["INVENTORY-DRIFT"])
    assert any(
        "Static analysis" in f.message
        for f in codes_at(anchored, "ID009")
    )


# ---- ID010: the span-name inventory pin ----------------------------------


def test_inventory_drift_span_names_id010(tmp_path):
    """ID010: spans.SPAN_NAMES, the metrics docstring entry for
    scheduler_trace_spans_total, and the README '## Distributed
    tracing' span table cannot drift — a span stamped but undocumented
    is invisible to the operator reading the trace."""
    result = lint_fixture(tmp_path, {
        # a NEW span "mystery.span" joined the inventory...
        "core/spans.py": """\
            SPAN_NAMES = (
                "submit.validate",
                "bind.confirm",
                "mystery.span",
            )
        """,
        # ...the metrics docstring never heard of it...
        "metrics/metrics.py": '''\
            """Metric families.

            - scheduler_trace_spans_total{name}: spans recorded by
              name: submit.validate | bind.confirm
            - scheduler_decisions_total: decisions
            """
        ''',
        # ...and the README table dropped bind.confirm instead
        "README.md": """\
            # fixture

            ## Distributed tracing

            | `submit.validate` | validation |
            | `mystery.span` | ??? |
        """,
    }, passes=["INVENTORY-DRIFT"])
    msgs = [f.message for f in codes_at(result, "ID010")]
    assert any(
        "'mystery.span'" in m and "metrics docstring" in m for m in msgs
    )
    assert any(
        "'bind.confirm'" in m and "README" in m for m in msgs
    )
    assert len(msgs) == 2

    # a consistent tree lints clean
    clean = lint_fixture(tmp_path / "clean", {
        "core/spans.py":
            'SPAN_NAMES = ("submit.validate", "bind.confirm")\n',
        "metrics/metrics.py":
            '"""M.\n\n- scheduler_trace_spans_total{name}:\n'
            '  submit.validate | bind.confirm\n"""\n',
        "README.md":
            "## Distributed tracing\n\n"
            "`submit.validate` then `bind.confirm`\n",
    }, passes=["INVENTORY-DRIFT"])
    assert codes_at(clean, "ID010") == []

    # no literal SPAN_NAMES tuple: the anchor itself is flagged
    anchorless = lint_fixture(tmp_path / "anchorless", {
        "core/spans.py":
            "SPAN_NAMES = tuple(n for n in ())\n",
    }, passes=["INVENTORY-DRIFT"])
    assert any(
        "no literal SPAN_NAMES tuple" in f.message
        for f in codes_at(anchorless, "ID010")
    )

    # a missing README section flags every span (nothing is documented)
    sectionless = lint_fixture(tmp_path / "sectionless", {
        "core/spans.py":
            'SPAN_NAMES = ("submit.validate",)\n',
        "README.md": "# no such section\n",
    }, passes=["INVENTORY-DRIFT"])
    assert any(
        "Distributed tracing" in f.message
        for f in codes_at(sectionless, "ID010")
    )


# ---- ID011: the alert rule-pack inventory pin ----------------------------


def test_inventory_drift_alert_rules_id011(tmp_path):
    """ID011: rules.BUILTIN_RULES, the README 'Metrics history, alert
    rules & the black box' rule table, and the `alert` anomaly class
    cannot drift — an undocumented rule pages an operator the runbook
    never heard of, and a missing `alert` class crashes every firing."""
    result = lint_fixture(tmp_path, {
        # a NEW rule "mystery_burn" joined the pack, and the class list
        # lost "alert"...
        "metrics/rules.py": """\
            BUILTIN_RULES = (
                {"name": "slo_burn", "family": "scheduler_slo_burn_rate",
                 "agg": "avg", "window_s": 30.0, "threshold": 6.0},
                {"name": "mystery_burn", "family": "scheduler_x_total",
                 "agg": "rate", "window_s": 60.0, "threshold": 1.0},
            )
        """,
        "core/observe.py": """\
            ANOMALY_CLASSES = (
                "tunnel_stall",
                "degraded",
            )
        """,
        # ...and the README table documents a rule the pack deleted
        "README.md": """\
            # fixture

            ### Metrics history, alert rules & the black box

            | rule | condition |
            |---|---|
            | `slo_burn` | burn rate > 6 |
            | `ghost_rule` | long gone |
        """,
    }, passes=["INVENTORY-DRIFT"])
    msgs = [f.message for f in codes_at(result, "ID011")]
    assert any("'mystery_burn'" in m and "not" in m for m in msgs)
    assert any("'ghost_rule'" in m and "stale row" in m for m in msgs)
    assert any('"alert" is missing' in m for m in msgs)
    assert len(msgs) == 3

    # a consistent tree lints clean; scheduler_-prefixed first-column
    # rows (family names) belong to ID001 and are not phantom rules
    clean = lint_fixture(tmp_path / "clean", {
        "metrics/rules.py": """\
            BUILTIN_RULES = (
                {"name": "slo_burn", "family": "scheduler_slo_burn_rate",
                 "agg": "avg", "window_s": 30.0, "threshold": 6.0},
            )
        """,
        "core/observe.py": 'ANOMALY_CLASSES = ("alert",)\n',
        "README.md": (
            "### Metrics history, alert rules & the black box\n\n"
            "| `slo_burn` | burn rate > 6 |\n"
            "| `scheduler_slo_burn_rate` | the family itself |\n"
        ),
    }, passes=["INVENTORY-DRIFT"])
    assert codes_at(clean, "ID011") == []

    # the pack must stay a statically-extractable literal
    anchorless = lint_fixture(tmp_path / "anchorless", {
        "metrics/rules.py":
            "BUILTIN_RULES = tuple(make_rule(n) for n in ())\n",
    }, passes=["INVENTORY-DRIFT"])
    assert any(
        "no statically-extractable BUILTIN_RULES" in f.message
        for f in codes_at(anchorless, "ID011")
    )

    # the README section itself missing is flagged
    sectionless = lint_fixture(tmp_path / "sectionless", {
        "metrics/rules.py": """\
            BUILTIN_RULES = (
                {"name": "slo_burn", "family": "f",
                 "agg": "avg", "window_s": 30.0},
            )
        """,
        "README.md": "# no watchtower section\n",
    }, passes=["INVENTORY-DRIFT"])
    assert any(
        "Metrics history, alert rules" in f.message
        for f in codes_at(sectionless, "ID011")
    )


# ---- wall-clock satellites: parse cache, fingerprints, --changed ---------


def test_parse_cache_reuses_unchanged_files(tmp_path):
    from k8s_scheduler_tpu.analysis.core import load_tree

    f = tmp_path / "m.py"
    f.write_text("X = 1\n")
    (a,) = load_tree(str(tmp_path), ["."])
    (b,) = load_tree(str(tmp_path), ["."])
    assert a is b  # same parse served from the cache
    assert a.walk() is a.walk()  # the node list is computed once
    import time as _t

    _t.sleep(0.01)
    f.write_text("X = 2\n")  # same size — mtime must invalidate
    (c,) = load_tree(str(tmp_path), ["."])
    assert c is not a and "X = 2" in c.text


def test_finding_fingerprint_stable_and_line_independent():
    from k8s_scheduler_tpu.analysis.core import Finding

    a = Finding("x.py", 10, "TS001", "msg")
    b = Finding("x.py", 99, "TS001", "msg")
    assert a.fingerprint() == b.fingerprint()  # lines churn, id doesn't
    assert a.to_dict()["fingerprint"] == a.fingerprint()
    assert a.fingerprint() != Finding("x.py", 10, "TS002", "msg").fingerprint()
    assert a.fingerprint() != Finding("y.py", 10, "TS001", "msg").fingerprint()


def test_schedlint_changed_paths(tmp_path):
    import importlib.util
    import subprocess

    path = os.path.join(REPO, "scripts", "schedlint.py")
    spec = importlib.util.spec_from_file_location("schedlint_cli2", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    repo = tmp_path / "r"
    (repo / "k8s_scheduler_tpu").mkdir(parents=True)
    (repo / "scripts").mkdir()

    def git(*args):
        subprocess.run(
            ["git", "-C", str(repo), "-c", "user.name=t",
             "-c", "user.email=t@t", *args],
            check=True, capture_output=True,
        )

    git("init", "-q")
    (repo / "k8s_scheduler_tpu" / "mod.py").write_text("A = 1\n")
    (repo / "outside.py").write_text("B = 1\n")
    git("add", "-A")
    git("commit", "-q", "-m", "seed")
    assert mod.changed_paths(str(repo)) == ([], [])  # clean work tree
    (repo / "k8s_scheduler_tpu" / "mod.py").write_text("A = 2\n")
    (repo / "scripts" / "probe.py").write_text("C = 1\n")  # untracked
    (repo / "outside.py").write_text("B = 2\n")  # outside lint roots
    assert mod.changed_paths(str(repo)) == (
        ["k8s_scheduler_tpu/mod.py", "scripts/probe.py"],
        ["outside.py"],  # reported, never silently dropped
    )


def test_threads_tr003_multi_target_and_tuple_assigns(tmp_path):
    """Review regression: chained (`a = b = Thread()`) and elementwise
    tuple (`t1, t2 = Thread(), Thread()`) assignments are STORED, not
    'dropped' — each is judged by its own lifecycle story."""
    result = lint_fixture(tmp_path, {
        "pkg/multi.py": """\
            import threading


            class M:
                def spawn(self):
                    self._a = self._b = threading.Thread(target=self._run)
                    self._a.start()

                def close(self):
                    self._a.join()

                def _run(self):
                    pass


            def pair(fn):
                t1, t2 = threading.Thread(target=fn), threading.Thread(target=fn)
                t1.start()
                t2.start()
                t1.join()
                # t2 is never joined nor cleared: the real leak
        """,
    }, passes=["THREADS"])
    tr3 = codes_at(result, "TR003")
    assert len(tr3) == 1 and tr3[0].line == 17
    assert "t2" in tr3[0].message and "drops" not in tr3[0].message


def test_schedlint_changed_rejects_write_baseline(tmp_path, capsys):
    """Review regression: a baseline written from a --changed subset
    scan would delete every grandfathered entry for unscanned files."""
    import importlib.util

    path = os.path.join(REPO, "scripts", "schedlint.py")
    spec = importlib.util.spec_from_file_location("schedlint_cli3", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["--changed", "--write-baseline"]) == 2
    assert "full-tree" in capsys.readouterr().err


# ---- the effect engine (effects.py) --------------------------------------


def make_ctx(tmp_path, files: dict[str, str]):
    from k8s_scheduler_tpu.analysis.core import LintContext, load_tree

    for rel, src in files.items():
        full = tmp_path / rel
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(textwrap.dedent(src))
    return LintContext(str(tmp_path), load_tree(str(tmp_path), ["."]))


def fid_of(ctx, qualname: str) -> str:
    return next(
        fid for fid, fi in ctx.index.funcs.items()
        if fi.qualname == qualname
    )


def test_effect_engine_summary_propagates_to_fixpoint(tmp_path):
    """An effect three calls deep reaches the top summary, tagged with
    the FIRST hop it arrived through (that's what the witness in pass
    messages points at)."""
    ctx = make_ctx(tmp_path, {"pkg/chain.py": """\
        import os


        def leaf(path):
            os.fsync(path)


        def mid(path):
            leaf(path)


        def top(path):
            mid(path)
    """})
    engine = ctx.effects
    assert engine.summary(fid_of(ctx, "leaf"))["io"] == ("os.fsync()", None)
    assert engine.summary(fid_of(ctx, "mid"))["io"] == ("os.fsync()", "leaf")
    assert engine.summary(fid_of(ctx, "top"))["io"] == ("os.fsync()", "mid")


def test_effect_engine_traced_region_and_witness_path(tmp_path):
    ctx = make_ctx(tmp_path, {"pkg/prog.py": """\
        import jax


        def helper(x):
            return x + 1


        def kernel(x):
            return helper(x)


        cycle = jax.jit(kernel)
    """})
    engine = ctx.effects
    region = engine.traced_region()
    k, h = fid_of(ctx, "kernel"), fid_of(ctx, "helper")
    assert region[k] == ("kernel",)
    assert region[h] == ("kernel", "helper")
    assert engine.traced_roots()[k].startswith("jax.jit() at pkg/prog.py:")


def test_call_references_skip_attribute_reads(tmp_path):
    """The precision split that makes JIT-PURITY usable: a bare
    attribute READ passed to a builtin must NOT become a call edge
    (callgraph's by-name fallback would drag `Node.unschedulable` into
    the traced region), while TRACE-SAFETY's broad walk still sees it."""
    ctx = make_ctx(tmp_path, {"pkg/prec.py": """\
        import os

        import jax


        class Node:
            def unschedulable(self):
                os.fsync(0)


        def kernel(node):
            return bool(node.unschedulable)


        cycle = jax.jit(kernel)
    """})
    engine = ctx.effects
    k_fid = fid_of(ctx, "kernel")
    f = ctx.index.funcs[k_fid]
    meth = fid_of(ctx, "Node.unschedulable")
    assert meth not in engine.call_references(f)  # data read, not a call
    assert meth in ctx.index.references(f)  # the broad TS walk still does
    assert "io" not in engine.summary(k_fid)


# ---- JIT-PURITY ----------------------------------------------------------


def test_jp001_host_io_interprocedural(tmp_path):
    """os.fsync two frames below the jitted entry point is reported in
    the frame that performs it, with the traced-via witness — and is
    provably missed when the pass is off."""
    files = {"pkg/prog.py": """\
        import os

        import jax


        def _flush(fd):
            os.fsync(fd)


        def kernel(x):
            _flush(x)
            return x


        cycle = jax.jit(kernel)
    """}
    result = lint_fixture(tmp_path, files, passes=["JIT-PURITY"])
    jp = codes_at(result, "JP001")
    assert len(jp) == 1 and jp[0].line == 7
    assert "os.fsync()" in jp[0].message
    assert "traced via kernel -> _flush" in jp[0].message
    off = lint_fixture(tmp_path, files, passes=["TRACE-SAFETY"])
    assert not codes_at(off, "JP001")


def test_jp002_lock_under_trace(tmp_path):
    files = {"pkg/locky.py": """\
        import threading

        import jax


        class Engine:
            def __init__(self):
                self._lock = threading.Lock()

            def kernel(self, x):
                with self._lock:
                    return x

            def build(self):
                return jax.jit(self.kernel)
    """}
    result = lint_fixture(tmp_path, files, passes=["JIT-PURITY"])
    jp = codes_at(result, "JP002")
    assert len(jp) == 1 and jp[0].line == 11
    assert "no-op in the compiled program" in jp[0].message
    off = lint_fixture(tmp_path, files, passes=["LOCK-DISCIPLINE"])
    assert not codes_at(off, "JP002")


def test_jp003_journal_emit_under_trace(tmp_path):
    files = {"pkg/emitty.py": """\
        import jax


        class Cycle:
            def kernel(self, x):
                self._emit({"t": int(x)})
                return x

            def build(self):
                return jax.jit(self.kernel)
    """}
    result = lint_fixture(tmp_path, files, passes=["JIT-PURITY"])
    jp = codes_at(result, "JP003")
    assert len(jp) == 1 and jp[0].line == 6
    assert "WAL goes stale" in jp[0].message
    off = lint_fixture(tmp_path, files, passes=["JOURNAL-EMIT-ONCE"])
    assert not codes_at(off, "JP003")


def test_jp004_attr_write_under_trace_init_exempt(tmp_path):
    files = {"pkg/statey.py": """\
        import jax


        class Counter:
            def __init__(self):
                self.calls = 0

            def kernel(self, x):
                self.calls += 1
                return x

            def build(self):
                return jax.jit(self.kernel)
    """}
    result = lint_fixture(tmp_path, files, passes=["JIT-PURITY"])
    jp = codes_at(result, "JP004")
    assert len(jp) == 1 and jp[0].line == 9  # __init__ write exempt
    off = lint_fixture(tmp_path, files, passes=["TRACE-SAFETY"])
    assert not codes_at(off, "JP004")


def test_jp005_nondeterministic_discriminator(tmp_path):
    """id() and unsorted .keys() in jit keyword args churn the compile
    cache; sorted(...) neutralizes the dict-order dependence."""
    files = {"pkg/disc.py": """\
        import jax


        def build_bad_id(fn, x):
            return jax.jit(fn, backend=str(id(x)))


        def build_bad_keys(fn, cfg):
            return jax.jit(fn, static_argnames=tuple(cfg.keys()))


        def build_ok(fn, cfg):
            return jax.jit(fn, static_argnames=tuple(sorted(cfg.keys())))
    """}
    result = lint_fixture(tmp_path, files, passes=["JIT-PURITY"])
    jp = codes_at(result, "JP005")
    assert [f.line for f in jp] == [5, 9]
    assert "id() is process-random" in jp[0].message
    assert "wrap in sorted" in jp[1].message
    off = lint_fixture(tmp_path, files, passes=["TRACE-SAFETY"])
    assert not codes_at(off, "JP005")


def test_jp006_jit_wrapper_in_loop(tmp_path):
    files = {"pkg/loopy.py": """\
        import jax


        def compile_each(fns):
            out = []
            for fn in fns:
                out.append(jax.jit(fn))
            return out


        def compile_once(fn):
            return jax.jit(fn)
    """}
    result = lint_fixture(tmp_path, files, passes=["JIT-PURITY"])
    jp = codes_at(result, "JP006")
    assert len(jp) == 1 and jp[0].line == 7
    assert "fresh callable" in jp[0].message
    off = lint_fixture(tmp_path, files, passes=["TRACE-SAFETY"])
    assert not codes_at(off, "JP006")


# ---- DURABILITY-ORDER ----------------------------------------------------


def test_do001_mutate_without_journal(tmp_path):
    """A tracked-store write with no preceding journal append fires;
    the journal-first twin and the journaled-funnel call stay clean."""
    files = {"pkg/service/binder.py": """\
        class Binder:
            def apply_bad(self, uid, pod):
                self._bound[uid] = pod

            def apply_good(self, uid, pod):
                self._journal({"op": "bind", "uid": uid})
                self._bound[uid] = pod

            def apply_funnel(self, node):
                self.cache.add_node(node)
    """}
    result = lint_fixture(tmp_path, files, passes=["DURABILITY-ORDER"])
    do = codes_at(result, "DO001")
    assert len(do) == 1 and do[0].line == 3
    assert "_bound" in do[0].message and "replay" in do[0].message
    off = lint_fixture(tmp_path, files, passes=["JOURNAL-EMIT-ONCE"])
    assert not codes_at(off, "DO001")


def test_do001_interprocedural_out_of_perimeter_callee(tmp_path):
    """A service-side caller reaching a tracked-store write through a
    helper OUTSIDE the durability perimeter is flagged at the call site
    (the helper's own file is never scanned by this pass)."""
    files = {
        "pkg/internal/rawstore.py": """\
            class RawStore:
                def raw_write(self, uid, pod):
                    self._bound[uid] = pod
        """,
        "pkg/service/svc.py": """\
            from ..internal.rawstore import RawStore


            class Svc:
                def commit_bad(self, uid, pod):
                    self.store.raw_write(uid, pod)

                def commit_good(self, uid, pod):
                    self._journal({"op": "bind", "uid": uid})
                    self.store.raw_write(uid, pod)
        """,
    }
    result = lint_fixture(tmp_path, files, passes=["DURABILITY-ORDER"])
    do = codes_at(result, "DO001")
    assert len(do) == 1
    assert do[0].file == "pkg/service/svc.py" and do[0].line == 6
    assert "RawStore.raw_write" in do[0].message
    off = lint_fixture(tmp_path, files, passes=["TRACE-SAFETY"])
    assert not codes_at(off, "DO001")


def test_do002_ack_without_barrier(tmp_path):
    files = {"pkg/service/admit.py": """\
        class Admission:
            def submit_bad(self, pods):
                return SubmitResult(accepted=len(pods))

            def submit_good(self, pods):
                self._manager.ack_barrier()
                return SubmitResult(accepted=len(pods))

            def submit_rejected(self, pods):
                return SubmitResult(accepted=0)
    """}
    result = lint_fixture(tmp_path, files, passes=["DURABILITY-ORDER"])
    do = codes_at(result, "DO002")
    assert len(do) == 1 and do[0].line == 3
    assert "ack_barrier" in do[0].message
    off = lint_fixture(tmp_path, files, passes=["TRACE-SAFETY"])
    assert not codes_at(off, "DO002")


def test_do002_conditional_barrier_branch_join(tmp_path):
    """Optimistic branch join: a barrier under `if` counts for the
    fall-through path (the admission.py shape)."""
    files = {"pkg/service/admit2.py": """\
        class Admission:
            def submit(self, pods, durable):
                if durable:
                    self._manager.ack_barrier()
                return SubmitResult(accepted=len(pods))
    """}
    result = lint_fixture(tmp_path, files, passes=["DURABILITY-ORDER"])
    assert not codes_at(result, "DO002")


def test_do003_broad_swallow_between_journal_and_mutate(tmp_path):
    files = {"pkg/state/mgr.py": """\
        class Manager:
            def apply_bad(self, rec, pod):
                try:
                    self._journal(rec)
                    self._active[rec["uid"]] = pod
                except Exception:
                    pass

            def apply_good(self, rec, pod):
                try:
                    self._journal(rec)
                    self._active[rec["uid"]] = pod
                except Exception:
                    raise
    """}
    result = lint_fixture(tmp_path, files, passes=["DURABILITY-ORDER"])
    do = codes_at(result, "DO003")
    assert len(do) == 1 and do[0].line == 6
    assert "half-applied" in do[0].message
    assert not codes_at(result, "DO001")  # journal precedes the write
    off = lint_fixture(tmp_path, files, passes=["ROBUSTNESS"])
    assert not codes_at(off, "DO003")


def test_do_passes_ignore_files_outside_perimeter(tmp_path):
    files = {"pkg/core/engine.py": """\
        class Engine:
            def apply(self, uid, pod):
                self._bound[uid] = pod
    """}
    result = lint_fixture(tmp_path, files, passes=["DURABILITY-ORDER"])
    assert not result.findings


# ---- count-aware baseline (satellite) ------------------------------------


def test_baseline_count_aware_roundtrip(tmp_path):
    from k8s_scheduler_tpu.analysis.core import (
        Finding,
        apply_baseline,
        stale_baseline_entries,
    )

    f1 = Finding("a.py", 1, "XX001", "m")
    f2 = Finding("a.py", 9, "XX001", "m")  # same identity, moved line
    p = str(tmp_path / "b.json")
    write_baseline(p, [f1, f2])
    entries = load_baseline(p)
    assert entries == [
        {"file": "a.py", "code": "XX001", "message": "m", "count": 2},
    ]
    new, old = apply_baseline([f1, f2], entries)
    assert not new and len(old) == 2
    # a THIRD identical violation exceeds the grandfather budget
    f3 = Finding("a.py", 20, "XX001", "m")
    new, old = apply_baseline([f1, f2, f3], entries)
    assert len(new) == 1 and len(old) == 2
    # and when one of the two disappears, the leftover budget is stale
    assert stale_baseline_entries(entries, [f1]) == [
        (("a.py", "XX001", "m"), 1),
    ]
    # singleton entries carry no count key (diff noise)
    write_baseline(p, [f1])
    assert "count" not in load_baseline(p)[0]


# ---- suppression edges (satellite) ---------------------------------------


def test_disable_file_with_justification(tmp_path):
    """`# schedlint: disable-file=CODE -- why` parses: the justification
    after `--` does not break the code list."""
    result = lint_fixture(tmp_path, {"pkg/probe.py": """\
        # schedlint: disable-file=HY001 -- exploratory probe, imports vary
        import os
        import json
    """}, passes=["HYGIENE"])
    assert not codes_at(result, "HY001")
    assert len([f for f in result.suppressed if f.code == "HY001"]) == 2


def test_disable_all_beats_baseline_and_goes_stale(tmp_path):
    """disable=all suppresses BEFORE the baseline is consulted, so the
    baseline entry for the same identity matches nothing and
    --fail-on-new reports it stale — suppressing a grandfathered
    finding is how the baseline is meant to shrink."""
    from k8s_scheduler_tpu.analysis.core import stale_baseline_entries

    files = {"pkg/probe2.py": """\
        import os  # schedlint: disable=all
    """}
    bare = lint_fixture(tmp_path, files, passes=["HYGIENE"])
    assert not bare.findings
    [supp] = bare.suppressed
    base = str(tmp_path / "base.json")
    write_baseline(base, [supp])  # identity IS in the baseline...
    again = lint_fixture(
        tmp_path, files, passes=["HYGIENE"], baseline_path=base,
    )
    assert not again.findings and not again.grandfathered
    assert len(again.suppressed) == 1  # ...but suppression wins
    assert stale_baseline_entries(load_baseline(base),
                                  again.grandfathered) == [
        ((supp.file, supp.code, supp.message), 1),
    ]


def test_baseline_matches_moved_line(tmp_path):
    """The baseline identity is (file, code, message) — a finding that
    moves lines between runs still rides its entry; an inline
    suppression added on the NEW line flips it from grandfathered to
    suppressed."""
    v1 = {"pkg/mv.py": """\
        import os
    """}
    r1 = lint_fixture(tmp_path, v1, passes=["HYGIENE"])
    base = str(tmp_path / "base.json")
    write_baseline(base, r1.findings)
    v2 = {"pkg/mv.py": """\
        \"\"\"now with a docstring: the finding moved down two lines.\"\"\"

        import os
    """}
    r2 = lint_fixture(tmp_path, v2, passes=["HYGIENE"], baseline_path=base)
    assert not r2.findings and len(r2.grandfathered) == 1
    assert r2.grandfathered[0].line == 3  # new line, same identity
    v3 = {"pkg/mv.py": """\
        \"\"\"now with a docstring: the finding moved down two lines.\"\"\"

        import os  # schedlint: disable=HY001 -- kept for the doctest
    """}
    r3 = lint_fixture(tmp_path, v3, passes=["HYGIENE"], baseline_path=base)
    assert not r3.findings and not r3.grandfathered
    assert len(r3.suppressed) == 1


# ---- SARIF + --fail-on-new driver surface --------------------------------


def test_to_sarif_shapes():
    from k8s_scheduler_tpu.analysis.core import (
        Finding,
        LintResult,
        to_sarif,
    )

    res = LintResult(
        findings=[Finding("a.py", 3, "XX001", "bad")],
        suppressed=[Finding("b.py", 1, "XX002", "ok")],
        grandfathered=[Finding("c.py", 2, "XX001", "old")],
        files_scanned=3, passes_run=["X"],
    )
    doc = to_sarif(res, {"XX001": "d1", "XX002": "d2"})
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "schedlint"
    assert [r["id"] for r in run["tool"]["driver"]["rules"]] == [
        "XX001", "XX002",
    ]
    rows = [
        (r["ruleId"], r["level"], r.get("suppressions"))
        for r in run["results"]
    ]
    assert rows == [
        ("XX001", "error", None),
        ("XX002", "note", [{"kind": "inSource"}]),
        ("XX001", "note", [{"kind": "external"}]),
    ]
    fp = run["results"][0]["partialFingerprints"]["schedlintFingerprint/v1"]
    assert fp == res.findings[0].fingerprint()
    loc = run["results"][0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "a.py"
    assert loc["region"]["startLine"] == 3


def test_schedlint_fail_on_new_usage_errors(capsys):
    import importlib.util

    path = os.path.join(REPO, "scripts", "schedlint.py")
    spec = importlib.util.spec_from_file_location("schedlint_cli4", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["--fail-on-new", "--baseline", ""]) == 2
    assert "needs --baseline" in capsys.readouterr().err
    assert mod.main(["--fail-on-new", "--write-baseline"]) == 2
    assert "mutually exclusive" in capsys.readouterr().err


def test_lint_metrics_schedlint_summary_shape():
    import importlib.util

    path = os.path.join(REPO, "scripts", "lint_metrics.py")
    spec = importlib.util.spec_from_file_location("lint_metrics_t", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    summary = mod.schedlint_summary()
    assert set(summary["passes"]) == set(default_registry().names())
    assert summary["total"]["findings"] == 0  # the tree is clean
    row = summary["passes"]["JIT-PURITY"]
    assert set(row) == {"findings", "suppressed", "grandfathered"}
