"""Compile-regime management (ISSUE 8): the persistent executable
cache's framing robustness (truncation / bit flips / version and
fingerprint mismatches are refused loudly and recompiled, never crashed
on), atomic concurrent writes, the AOT load-or-compile path, the
adjacent-regime spec rewrite (packing.respec) against real encodes, pad
hysteresis (an oscillating workload holds the larger regime), the
_mc_fns LRU eviction regression, and the slow-tier end-to-end proofs:
warm restart with zero cold compiles, and a speculation-won flip with
compile_ms ~= 0."""

from __future__ import annotations

import struct
import threading
import time

import jax
import numpy as np
import pytest

from k8s_scheduler_tpu.config import SchedulerConfiguration
from k8s_scheduler_tpu.core import Scheduler
from k8s_scheduler_tpu.core import compile_cache as cc
from k8s_scheduler_tpu.core.cycle import _jit
from k8s_scheduler_tpu.models import MakeNode, MakePod, packing
from k8s_scheduler_tpu.models.encoding import SnapshotEncoder
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods


def _tiny_spec():
    """A real (cheap — no jit) PackSpec for key construction."""
    enc = SnapshotEncoder(pad_pods=8, pad_nodes=8)
    nodes = [MakeNode("n0").capacity({"cpu": "8"}).obj()]
    pods = [MakePod("p0").req({"cpu": "1"}).obj()]
    return packing.make_spec(enc.encode(nodes, pods))


def _fresh_fn(disc: str = "t"):
    """A distinctively-named jitted toy program (same deterministic
    name per disc — the cross-'process' cache-key property)."""
    return _jit(
        lambda w, b: {"s": w.sum() + b.sum(), "n": (b != 0).sum()},
        "cc_test", disc=disc,
    )


_ARGS = (
    jax.ShapeDtypeStruct((16,), np.uint32),
    jax.ShapeDtypeStruct((8,), np.uint8),
)


# ---- entry framing robustness -------------------------------------------


def test_load_or_compile_roundtrip(tmp_path):
    spec = _tiny_spec()
    cache = cc.CompileCache(str(tmp_path))
    comp, source, dt, out_sds = cc.load_or_compile(
        _fresh_fn(), cache, spec, "default", "cycle", args=_ARGS
    )
    assert comp is not None and source == "cold"
    assert cache.misses == 1 and cache.hits == 0
    assert out_sds["s"].shape == ()
    w = np.arange(16, dtype=np.uint32)
    b = np.ones(8, np.uint8)
    first = np.asarray(comp(w, b)["s"])

    # a "restarted process": fresh cache object, fresh (but
    # identically-named) jit wrapper, same directory — and the loaded-
    # executable memo cleared, so the load REALLY deserializes
    cc.clear_loaded_memo()
    cache2 = cc.CompileCache(str(tmp_path))
    comp2, source2, dt2, _ = cc.load_or_compile(
        _fresh_fn(), cache2, spec, "default", "cycle", args=_ARGS
    )
    assert comp2 is not None and source2 == "cache"
    assert cache2.hits == 1 and cache2.misses == 0
    assert cache2.load_seconds and cache2.load_seconds[0] == dt2
    assert np.asarray(comp2(w, b)["s"]) == first


def _entry_path(tmp_path):
    files = [p for p in tmp_path.iterdir() if p.name.endswith(".kscc")]
    assert len(files) == 1
    return files[0]


@pytest.mark.parametrize("damage", ["truncate", "bitflip", "version"])
def test_damaged_entry_refused_loudly_then_recompiled(
    tmp_path, caplog, damage
):
    """Satellite: truncated / bit-flipped / future-version entries are
    REFUSED with a loud log line and the program recompiles cleanly —
    the cache can cost a compile, never a crash."""
    spec = _tiny_spec()
    cache = cc.CompileCache(str(tmp_path))
    cc.load_or_compile(
        _fresh_fn(), cache, spec, "default", "cycle", args=_ARGS
    )
    path = _entry_path(tmp_path)
    blob = path.read_bytes()
    if damage == "truncate":
        path.write_bytes(blob[: len(blob) // 2])
    elif damage == "bitflip":
        mid = len(blob) // 2
        path.write_bytes(
            blob[:mid] + bytes([blob[mid] ^ 0x40]) + blob[mid + 1:]
        )
    else:  # a future format version must be refused, not half-parsed
        path.write_bytes(
            blob[:4] + struct.pack("<I", 99) + blob[8:]
        )
    cache2 = cc.CompileCache(str(tmp_path))
    with caplog.at_level("ERROR", logger=cc.log.name):
        comp, source, _dt, _ = cc.load_or_compile(
            _fresh_fn(), cache2, spec, "default", "cycle", args=_ARGS
        )
    assert comp is not None and source == "cold"  # clean recompile
    assert any("REFUSING" in r.message for r in caplog.records)
    # the recompile overwrote the bad entry: next load is a clean hit
    cache3 = cc.CompileCache(str(tmp_path))
    _comp, source3, _dt, _ = cc.load_or_compile(
        _fresh_fn(), cache3, spec, "default", "cycle", args=_ARGS
    )
    assert source3 == "cache"


def test_fingerprint_mismatch_is_miss_not_crash(tmp_path):
    """Satellite: a jaxlib/backend fingerprint mismatch is a MISS. The
    fingerprint rides the key (so a different backend gets a different
    filename) AND the entry meta (defense in depth, exercised here)."""
    spec = _tiny_spec()
    cache = cc.CompileCache(str(tmp_path))
    key = cc.cache_key(spec, "default", "cycle", "prog")
    assert cache.store(key, b"payload", 1.0)
    assert cache.load(key) == b"payload"
    cache._fingerprint = "jax9.9.9-othertpu"
    assert cache.load(key) is None  # miss, no exception
    # and the key itself embeds the fingerprint: a rebuilt key under
    # the new fingerprint names a different file entirely
    key2 = cc.cache_key(
        spec, "default", "cycle", "prog",
        fingerprint="jax9.9.9-othertpu",
    )
    assert key2.name != key.name


def test_concurrent_same_key_writers_leave_one_intact_entry(tmp_path):
    """Satellite: a warm-thread + serve-loop build of the same key must
    produce ONE entry with no torn bytes (tmp+fsync+rename, unique tmp
    per writer) — every interleaving loads a whole payload."""
    spec = _tiny_spec()
    cache = cc.CompileCache(str(tmp_path))
    key = cc.cache_key(spec, "default", "cycle", "prog")
    payloads = [bytes([i]) * 4096 for i in range(4)]
    stop = threading.Event()
    errors: list = []

    def writer(payload):
        while not stop.is_set():
            if not cache.store(key, payload, 0.1):
                errors.append("store failed")

    threads = [
        threading.Thread(target=writer, args=(p,), daemon=True)
        for p in payloads
    ]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 1.0
    reads = 0
    while time.monotonic() < deadline:
        got = cache.load(key)
        if got is not None:
            assert got in payloads  # whole payload, never torn
            reads += 1
    stop.set()
    for t in threads:
        t.join(5.0)
    assert not errors and reads > 0
    files = [p for p in tmp_path.iterdir() if p.name.endswith(".kscc")]
    assert len(files) == 1  # one entry; tmp files all cleaned/renamed


# ---- the adjacent-regime spec rewrite -----------------------------------


def _rich_workload():
    nodes = make_cluster(20, taint_fraction=0.3)
    pods = make_pods(
        40, seed=3, affinity_fraction=0.3, anti_affinity_fraction=0.2,
        spread_fraction=0.2, selector_fraction=0.3,
        toleration_fraction=0.2, priorities=(0, 10), num_apps=5,
    )
    existing = [
        (p, f"node-{i % 20}")
        for i, p in enumerate(make_pods(30, seed=9, name_prefix="run"))
    ]
    return nodes, pods, existing


def test_respec_matches_real_encode_exactly():
    """packing.respec's naming contract (pod_*/node_* carry P/N on axis
    0 and nowhere else) verified against the encoder's ground truth: the
    rewritten spec must equal the spec a REAL encode at the adjacent pad
    produces — byte-identical key, so the pre-built programs are the
    ones the flip will ask for."""
    nodes, pods, existing = _rich_workload()
    enc = SnapshotEncoder(pad_pods=64, pad_nodes=32)
    spec64 = packing.make_spec(enc.encode(nodes, pods, existing))
    enc.pad_pods = 128
    spec128 = packing.make_spec(enc.encode(nodes, pods, existing))
    enc.pad_pods = 64
    enc.pad_nodes = 64
    spec_n64 = packing.make_spec(enc.encode(nodes, pods, existing))

    up = packing.respec(spec64, {"P": 128})
    assert up is not None and up.key() == spec128.key()
    down = packing.respec(spec128, {"P": 64})
    assert down is not None and down.key() == spec64.key()
    n_up = packing.respec(spec64, {"N": 64})
    assert n_up is not None and n_up.key() == spec_n64.key()


def test_respec_refuses_extender_planes_and_unknown_dims():
    import dataclasses

    nodes, pods, existing = _rich_workload()
    enc = SnapshotEncoder(pad_pods=64, pad_nodes=32)
    snap = enc.encode(nodes, pods, existing)
    spec = packing.make_spec(snap)
    assert packing.respec(spec, {"E": 512}) is None  # sticky dims: no
    assert packing.respec(spec, {}) is None
    P, N = snap.pod_valid.shape[0], snap.node_valid.shape[0]
    ext = dataclasses.replace(
        snap,
        has_extender=True,
        pod_extender_mask=np.ones((P, N), bool),
        pod_extender_score=np.zeros((P, N), np.float32),
    )
    # the [P, N] verdict planes break the axis-0-only rule: refuse
    assert packing.respec(packing.make_spec(ext), {"P": 128}) is None


# ---- pad hysteresis ------------------------------------------------------


def test_hysteresis_pad_unit():
    enc = SnapshotEncoder(pad_hysteresis_pct=25.0)
    assert enc.hysteresis_pad("P", 64, 60) == 64   # first sighting
    assert enc.hysteresis_pad("P", 128, 80) == 128  # up-step: immediate
    # candidate shrank to 64 but real=60 leaves only 6% headroom: hold
    assert enc.hysteresis_pad("P", 64, 60) == 128
    # real=40 leaves 37% headroom inside 64: step down
    assert enc.hysteresis_pad("P", 64, 40) == 64
    # knob off = identity
    enc0 = SnapshotEncoder()
    assert enc0.hysteresis_pad("P", 128, 80) == 128
    assert enc0.hysteresis_pad("P", 64, 60) == 64


def test_hysteresis_holds_regime_under_oscillating_trace():
    """Satellite: an oscillating pending count crossing a pad-bucket
    boundary produces ZERO regime flips after the first up-step with
    hysteresis on, where the no-hysteresis baseline flips every
    crossing. Asserted on spec KEYS (what actually triggers a
    recompile) — no jit needed, so this runs in the fast tier."""
    nodes = make_cluster(8)

    def keys_for(pct: float) -> list:
        enc = SnapshotEncoder(pad_hysteresis_pct=pct)  # pow2 buckets
        out = []
        for i in range(8):
            pods = make_pods(70 if i % 2 else 60, seed=i)
            out.append(packing.make_spec(enc.encode(nodes, pods)).key())
        return out

    base = keys_for(0.0)
    base_flips = sum(1 for a, b in zip(base, base[1:]) if a != b)
    assert base_flips >= 7  # flips every crossing without hysteresis

    held = keys_for(15.0)
    held_flips = sum(1 for a, b in zip(held, held[1:]) if a != b)
    assert held_flips == 1  # the initial up-step only
    assert held[1:] == [held[1]] * 7  # larger regime held throughout


# ---- _mc_fns LRU eviction regression ------------------------------------


class _FakeSpec:
    def __init__(self, k):
        self._k = k

    def key(self):
        return self._k


def test_mc_fns_eviction_is_true_lru(monkeypatch):
    """Satellite regression: `next(iter(...))` popped FIFO insertion
    order, so the HOTTEST multi-cycle regime could be evicted while a
    cold one stayed. A hit must move the entry to the end."""
    from k8s_scheduler_tpu.core import cycle as cycle_mod

    monkeypatch.setattr(
        cycle_mod, "build_packed_multicycle_fn",
        lambda spec, **kw: ("mfn", spec.key()),
    )
    monkeypatch.setattr(
        cycle_mod, "build_diagnosis_fn",
        lambda spec, fw=None, **kw: ("diag", spec.key()),
    )
    s = Scheduler(
        config=SchedulerConfiguration(
            multi_cycle_k=4, flight_recorder_size=0
        )
    )
    cap = 4 * len(s.frameworks)
    profile = s._profile_order[0]
    for i in range(cap):
        s._mc_programs(_FakeSpec(f"regime{i}"), profile)
    # regime0 is the FIFO-oldest; a HIT must make it the LRU-newest
    s._mc_programs(_FakeSpec("regime0"), profile)
    s._mc_programs(_FakeSpec(f"regime{cap}"), profile)  # evicts one
    keys = {k[0] for k in s._mc_fns}
    assert "regime0" in keys       # hot regime survived the eviction
    assert "regime1" not in keys   # the actually-coldest one went
    assert len(s._mc_fns) == cap


def test_packed_memo_eviction_is_true_lru(monkeypatch):
    """Same property for the single-cycle program memo."""
    s = Scheduler(
        config=SchedulerConfiguration(flight_recorder_size=0)
    )
    profile = s._profile_order[0]
    monkeypatch.setattr(
        s, "_build_packed_entry",
        lambda spec, prof, aot: {
            "fns": ("f", spec.key()), "build_s": 0.0, "source": "cold",
        },
    )
    cap = 4 * len(s.frameworks)
    for i in range(cap):
        s._packed_fns(_FakeSpec(f"regime{i}"), profile)
    s._packed_fns(_FakeSpec("regime0"), profile)
    s._packed_fns(_FakeSpec(f"regime{cap}"), profile)
    keys = {k[0] for k in s._packed}
    assert "regime0" in keys and "regime1" not in keys


# ---- observer demand EWMA ------------------------------------------------


def test_observer_demand_ewma_tracks_pod_counts():
    from k8s_scheduler_tpu.core.observe import CycleObserver

    obs = CycleObserver(metrics=None)
    assert obs.demand_ewma("default-scheduler") == 0.0
    for _ in range(30):
        obs.observe_phases({"total": 0.01}, counts={"pods": 50})
    assert abs(obs.demand_ewma("default-scheduler") - 50.0) < 1.0
    # drifts toward a new level within a handful of cycles
    for _ in range(10):
        obs.observe_phases({"total": 0.01}, counts={"pods": 100})
    assert obs.demand_ewma("default-scheduler") > 80.0
    # per-profile isolation
    obs.observe_phases(
        {"total": 0.01}, counts={"pods": 7}, profile="other"
    )
    assert obs.demand_ewma("other") == 7.0


# ---- AOT fallback behaviour ---------------------------------------------


def test_resilient_falls_back_to_jit_on_convention_mismatch(tmp_path):
    """An installed AOT executable serves matching-aval calls; any
    other call shape falls through to the jit path instead of raising
    (the preemption program is legitimately called under two
    conventions)."""
    spec = _tiny_spec()
    cache = cc.CompileCache(str(tmp_path))
    fn = _fresh_fn("fallback")
    comp, source, _dt, _ = cc.load_or_compile(
        fn, cache, spec, "default", "cycle", args=_ARGS
    )
    fn.install_aot(comp)
    w = np.arange(16, dtype=np.uint32)
    b = np.ones(8, np.uint8)
    assert int(np.asarray(fn(w, b)["n"])) == 8  # AOT-served
    big_w = np.arange(32, dtype=np.uint32)
    big_b = np.ones(16, np.uint8)
    assert int(np.asarray(fn(big_w, big_b)["n"])) == 16  # jit fallback
    assert fn._aot is not None  # still installed for matching calls
    assert int(np.asarray(fn(w, b)["n"])) == 8


# ---- end-to-end proofs (slow tier) --------------------------------------


def _mini_cluster(s, n_nodes=4, cpu="640"):
    for i in range(n_nodes):
        s.on_node_add(MakeNode(f"n{i}").capacity({"cpu": cpu}).obj())


def test_warm_restart_compiles_zero_programs(tmp_path):
    """Acceptance: a second scheduler against a populated
    compile_cache/ records ZERO cold compiles for previously-seen
    regimes, with entry load time far below the cold compile it
    replaced."""
    cfg = SchedulerConfiguration(compile_cache_dir=str(tmp_path))
    s1 = Scheduler(config=cfg, pad_bucket=8)
    _mini_cluster(s1)
    for i in range(6):
        s1.on_pod_add(MakePod(f"p{i}").req({"cpu": "1"}).obj())
    t0 = time.perf_counter()
    assert s1.schedule_cycle().scheduled == 6
    cold_s = time.perf_counter() - t0
    assert s1._compile_cache.misses >= 5  # full program set stored
    assert s1._compile_cache.hits == 0

    # "restart": fresh Scheduler = fresh jit wrappers, empty in-memory
    # caches, loaded-executable memo cleared — only the disk entries
    # carry over, so every program REALLY deserializes
    cc.clear_loaded_memo()
    s2 = Scheduler(
        config=SchedulerConfiguration(compile_cache_dir=str(tmp_path)),
        pad_bucket=8,
    )
    _mini_cluster(s2)
    for i in range(6):
        s2.on_pod_add(MakePod(f"w{i}").req({"cpu": "1"}).obj())
    t0 = time.perf_counter()
    assert s2.schedule_cycle().scheduled == 6
    warm_s = time.perf_counter() - t0
    st = s2._compile_cache.status()
    assert st["misses"] == 0, "warm restart paid a cold compile"
    assert st["hits"] >= 5
    entry = next(iter(s2._packed.values()))
    assert entry["source"] == "cache"
    # flight record of the warm first cycle attributes the flip to the
    # cache, and the loads were cheap next to the cold build
    rec = s2.flight.snapshot()[0]
    assert rec.counts.get("regime_flip") == 1
    assert rec.compile_source == "cache"
    assert st["load_p50_s"] < 1.0
    assert warm_s < cold_s


def test_speculative_precompile_wins_the_flip(tmp_path):
    """Acceptance: with demand drifting toward the P bucket boundary,
    the warm thread pre-builds the adjacent regime; the flip then costs
    ~zero serve-path compile and is stamped
    compile_source="speculative" on the record AND the /debug/anomalies
    recompile event."""
    cfg = SchedulerConfiguration(
        compile_cache_dir=str(tmp_path),
        # pre-sized sticky pads (the documented fold-mode pattern):
        # the oscillation then moves exactly one dimension — P
        pad_existing=512,
        pad_pods_per_node=256,
    )
    s = Scheduler(config=cfg, binder=lambda p, n: None, pad_bucket=8)
    _mini_cluster(s)
    k = 0
    for _cyc in range(10):  # demand EWMA -> 7 >= 0.85 * P(=8)
        for _ in range(7):
            s.on_pod_add(MakePod(f"p{k}").req({"cpu": "1"}).obj())
            k += 1
        s.schedule_cycle()
    assert s._warmer is not None
    assert s._warmer.join(300), "speculative build never finished"
    assert s._warmer.built >= 1 and s._warmer.failed == 0
    assert any(
        e.get("fresh") for e in s._packed.values()
    ), "no speculative entry landed in the program memo"

    for _ in range(12):  # cross the boundary: P 8 -> 16
        s.on_pod_add(MakePod(f"p{k}").req({"cpu": "1"}).obj())
        k += 1
    t0 = time.perf_counter()
    s.schedule_cycle()
    flip_s = time.perf_counter() - t0
    flips = [
        r for r in s.flight.snapshot() if r.counts.get("regime_flip")
    ]
    won = [r for r in flips if r.compile_source == "speculative"]
    assert won, f"no speculation-won flip in {len(flips)} flips"
    assert won[-1].phases.get("compile_ms", 1e9) < 50.0  # ~zero
    evs = [
        e for e in s.observer.anomalies() if e["class"] == "recompile"
    ]
    assert evs and evs[-1]["detail"].get("compile_source") == (
        "speculative"
    )
    assert "P" in evs[-1]["detail"]["dims"]
    assert flip_s < 2.0  # the flip cycle never paid a compile
    assert (
        "scheduler_compile_cache_speculative_builds_total"
        in s.metrics.expose().decode()
    )


def test_regime_churn_soak_zero_compile_stalls(tmp_path, monkeypatch):
    """Acceptance (bench-shaped): the pad-bucket-crossing churn soak
    records zero compile-attributed stall cycles after the first
    traversal of each regime, a warm start with zero cold compiles,
    and hysteresis holding the oscillation to a single flip."""
    import bench_suite

    monkeypatch.setenv("BENCH_COMPILE_CACHE_DIR", str(tmp_path))
    r = bench_suite.run_config(6, snapshots=8)
    assert r["name"] == "regime_churn"
    assert r["stall_cycles"] == 0
    assert r["cache_misses"] == 0  # warm phase compiled nothing cold
    assert r["compile_cache_hit_rate"] == 1.0
    assert r["regime_flips"] >= 7  # the workload really oscillated
    assert r["hysteresis_flips"] == 1  # held after the first up-step
    assert r["warm_sources"] in ([], ["cache"])
    assert r["compile_seconds"] > r["warm_compile_seconds"]
