"""Observability (SURVEY.md §2 C13, §5.5): upstream metric names exposed
via prometheus_client, recorded by the host-side scheduling loop."""

import numpy as np

from k8s_scheduler_tpu.core.scheduler import Scheduler
from k8s_scheduler_tpu.metrics import SchedulerMetrics
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods


def _sample(metrics, name, labels=None):
    v = metrics.registry.get_sample_value(name, labels or {})
    return 0.0 if v is None else v


def test_upstream_metric_names_present():
    m = SchedulerMetrics()
    text = m.expose().decode()
    for name in [
        "scheduler_schedule_attempts_total",
        "scheduler_scheduling_attempt_duration_seconds",
        "scheduler_e2e_scheduling_duration_seconds",
        "scheduler_pending_pods",
        "scheduler_preemption_attempts_total",
        "scheduler_preemption_victims",
        "scheduler_binding_duration_seconds",
        "scheduler_framework_extension_point_duration_seconds",
        "scheduler_plugin_execution_duration_seconds",
        "scheduler_pod_scheduling_attempts",
        "scheduler_cache_size",
        "scheduler_cycle_duration_seconds",
        "scheduler_pod_node_decisions_total",
    ]:
        assert name in text, name


def test_cycle_records_attempts_and_pending():
    m = SchedulerMetrics()
    sched = Scheduler(metrics=m)
    for nd in make_cluster(4):
        sched.on_node_add(nd)
    for p in make_pods(6):
        sched.on_pod_add(p)
    stats = sched.schedule_cycle()
    assert stats.scheduled == 6

    scheduled = _sample(
        m,
        "scheduler_schedule_attempts_total",
        {"result": "scheduled", "profile": "default-scheduler"},
    )
    assert scheduled == 6
    assert _sample(m, "scheduler_pod_node_decisions_total") == 6 * 4
    assert _sample(m, "scheduler_cache_size", {"type": "nodes"}) == 4
    assert _sample(m, "scheduler_cache_size", {"type": "pods"}) == 6
    assert (
        _sample(
            m,
            "scheduler_cycle_duration_seconds_count",
            {"phase": "total"},
        )
        == 1
    )
    # everything scheduled -> pending gauges are zero
    for q in ("active", "backoff", "unschedulable"):
        assert _sample(m, "scheduler_pending_pods", {"queue": q}) == 0


def test_bind_error_and_unschedulable_results():
    m = SchedulerMetrics()
    calls = {"n": 0}

    def flaky_binder(pod, node):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("apiserver away")

    sched = Scheduler(metrics=m, binder=flaky_binder)
    for nd in make_cluster(2):
        sched.on_node_add(nd)
    # one pod that fits, one that can't (huge request)
    pods = make_pods(2)
    pods[1].spec.containers[0].requests["cpu"] = 10_000_000.0  # 10k cores
    for p in pods:
        sched.on_pod_add(p)
    stats = sched.schedule_cycle()
    assert stats.bind_errors == 1
    assert stats.unschedulable == 1
    assert (
        _sample(
            m,
            "scheduler_schedule_attempts_total",
            {"result": "error", "profile": "default-scheduler"},
        )
        == 1
    )
    assert (
        _sample(
            m,
            "scheduler_schedule_attempts_total",
            {"result": "unschedulable", "profile": "default-scheduler"},
        )
        == 1
    )


def test_profile_cycle_fills_per_plugin_histograms():
    m = SchedulerMetrics()
    sched = Scheduler(metrics=m)
    for nd in make_cluster(4):
        sched.on_node_add(nd)
    for p in make_pods(8, anti_affinity_fraction=0.5):
        sched.on_pod_add(p)
    report = sched.profile_cycle(repeats=1)
    # NodeResourcesFit is dynamic-only (fit runs in the commit scan), so
    # the static profile covers plugins with standalone kernels
    assert "NodeName/Filter" in report
    assert any(k.endswith("/Score") for k in report)
    for entry in report.values():
        assert entry["seconds"] >= 0.0
    nn = report["NodeName/Filter"]
    assert 0.0 < nn["feasible_fraction"] <= 1.0
    assert (
        _sample(
            m,
            "scheduler_plugin_execution_duration_seconds_count",
            {
                "plugin": "NodeName",
                "extension_point": "Filter",
                "status": "Success",
            },
        )
        == 1
    )
    assert (
        _sample(
            m,
            "scheduler_framework_extension_point_duration_seconds_count",
            {"extension_point": "Filter", "status": "Success"},
        )
        == 1
    )


def test_gauges_update_on_empty_cycles():
    m = SchedulerMetrics()
    sched = Scheduler(metrics=m)
    for nd in make_cluster(2):
        sched.on_node_add(nd)
    huge = make_pods(1)
    huge[0].spec.containers[0].requests["cpu"] = 10_000_000.0
    sched.on_pod_add(huge[0])
    sched.schedule_cycle()
    assert _sample(m, "scheduler_pending_pods", {"queue": "unschedulable"}) == 1
    # pod deleted while idle: the next (empty) cycle must clear the gauge
    sched.on_pod_delete(huge[0].uid)
    sched.schedule_cycle()
    assert _sample(m, "scheduler_pending_pods", {"queue": "unschedulable"}) == 0


def test_registries_are_isolated():
    a, b = SchedulerMetrics(), SchedulerMetrics()
    a.decisions.inc(5)
    assert _sample(a, "scheduler_pod_node_decisions_total") == 5
    assert _sample(b, "scheduler_pod_node_decisions_total") == 0


def test_flight_recorder_derived_gauges():
    """The pipeline-health gauges computed from the flight recorder:
    overlap ratio, in-flight count, diag-lag summary, and the
    scrape-time last-cycle age."""
    m = SchedulerMetrics()
    sched = Scheduler(metrics=m)
    for nd in make_cluster(4):
        sched.on_node_add(nd)
    pods = make_pods(6)
    # one loser forces the deferred diagnosis -> diag_lag observed
    pods[-1].spec.containers[0].requests["cpu"] = 10_000_000.0
    for p in pods:
        sched.on_pod_add(p)
    sched.schedule_cycle()

    assert sched.flight is not None and sched.flight.cycles == 1
    # overlap ratio was set from the recorder window (a real fraction)
    ratio = _sample(m, "scheduler_pipeline_overlap_ratio")
    assert 0.0 <= ratio <= 1.0
    # nothing in flight between cycles (decisions always fetched)
    assert _sample(m, "scheduler_cycle_inflight") == 0
    assert _sample(m, "scheduler_diag_lag_seconds_count") == 1
    assert _sample(m, "scheduler_diag_lag_seconds_sum") > 0
    # the age gauge is evaluated AT SCRAPE TIME (set_function), so a
    # wedged scheduler shows a growing age on /metrics
    age1 = _sample(m, "scheduler_last_cycle_age_seconds")
    import time

    time.sleep(0.02)
    age2 = _sample(m, "scheduler_last_cycle_age_seconds")
    assert age2 > age1 >= 0.0
    # the record behind the gauges carries the full phase/count set
    rec = sched.flight.last_record()
    assert rec.counts["scheduled"] == 5
    assert rec.counts["unschedulable"] == 1
    assert rec.counts["fetch_bytes"] > 0
    assert "decision_end" in rec.marks and "diag_done" in rec.marks


def test_metric_inventory_in_sync_with_docs():
    """Tier-1-adjacent wiring of scripts/lint_metrics.py: every
    registered metric family is documented in the metrics module
    docstring AND the README Observability table, and neither surface
    names a metric that no longer exists."""
    import importlib.util
    from pathlib import Path

    path = (
        Path(__file__).resolve().parent.parent
        / "scripts" / "lint_metrics.py"
    )
    spec = importlib.util.spec_from_file_location("lint_metrics", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.check_inventory() == []
