"""Split-phase serving pipeline: latency-program parity, slimmed decision
fetch, dispatch ordering, and forced-sync equivalence.

The contract under test (ISSUE 1 tentpole): the latency cycle program
(`build_cycle_fn(outputs="latency")` and the ServingPipeline that drives
the packed variants) is a SCHEDULING change, not a semantic one — the
decision carry (assignment / node_requested / unschedulable /
gang_dropped) is bit-identical to the monolithic program's in both commit
modes, the preemption chain consumes either interchangeably, and cycle
k's binds always fold into the cache before cycle k+1's encode reads it.
"""

import numpy as np
import pytest

from k8s_scheduler_tpu.config import SchedulerConfiguration
from k8s_scheduler_tpu.core import (
    Scheduler,
    ServingPipeline,
    build_cycle_fn,
    build_decision_slim_fn,
    build_preemption_fn,
)
from k8s_scheduler_tpu.models import MakeNode, MakePod, SnapshotEncoder
from k8s_scheduler_tpu.models.api import PodGroup


def _workload():
    """Nodes near capacity + a gang that can only partially place + a
    preemptor that needs an eviction + an infeasible pod: one snapshot
    that exercises normal placement, gang unwind, the preemption chain,
    and diagnosis-worthy unschedulability at once."""
    nodes = [
        MakeNode(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"})
        .labels({"zone": f"z{i % 2}"}).obj()
        for i in range(4)
    ]
    existing = [
        (MakePod(f"run{i}").req({"cpu": "3"}).priority(0).obj(), f"n{i}")
        for i in range(2)  # n0/n1 nearly full; n2/n3 empty
    ]
    pods = (
        # high-priority, fit on the empty nodes
        [MakePod(f"hi{i}").req({"cpu": "2"}).priority(100)
         .created(float(i)).obj() for i in range(2)]
        # preemptor: nothing free fits 4 cpu, but evicting a prio-0
        # running pod frees a node
        + [MakePod("pre").req({"cpu": "4"}).priority(100)
           .created(5.0).obj()]
        # gang of 3 (minMember 3): at most 2 members fit -> unwind
        + [MakePod(f"g{i}").req({"cpu": "2"}).priority(10)
           .group("job").created(10.0 + i).obj() for i in range(3)]
        # infeasible even with eviction
        + [MakePod("huge").req({"cpu": "64"}).created(99.0).obj()]
    )
    groups = [PodGroup("job", 3)]
    return nodes, pods, existing, groups


@pytest.mark.parametrize("mode", ["scan", "rounds"])
def test_latency_program_parity(mode):
    nodes, pods, existing, groups = _workload()
    enc = SnapshotEncoder(pad_pods=8, pad_nodes=4)
    snap = enc.encode(nodes, pods, existing, pod_groups=groups)
    full = build_cycle_fn(commit_mode=mode)(snap)
    lat = build_cycle_fn(commit_mode=mode, outputs="latency")(snap)
    for f in (
        "assignment", "node_requested", "unschedulable", "gang_dropped"
    ):
        np.testing.assert_array_equal(
            np.asarray(getattr(full, f)),
            np.asarray(getattr(lat, f)),
            err_msg=f"{mode}: {f} diverged between full and latency",
        )
    # the fixture really exercises the paths the parity claim covers
    assert np.asarray(full.gang_dropped).any(), "gang unwind never fired"
    assert np.asarray(full.unschedulable).any()

    # the preemption chain consumes either result interchangeably
    pre_fn = build_preemption_fn()
    a = pre_fn(snap, full)
    b = pre_fn(snap, lat)
    np.testing.assert_array_equal(
        np.asarray(a.nominated), np.asarray(b.nominated)
    )
    np.testing.assert_array_equal(
        np.asarray(a.victims), np.asarray(b.victims)
    )
    assert (np.asarray(a.nominated) >= 0).any(), "preemption never fired"


def test_decision_slim_fetch_roundtrip():
    rng = np.random.default_rng(7)
    P, N = 64, 1000
    assignment = rng.integers(-1, N, size=P).astype(np.int32)
    unsched = rng.random(P) < 0.3
    dropped = rng.random(P) < 0.2
    slim = build_decision_slim_fn(N)
    a, flags = slim(assignment, unsched, dropped)
    a, flags = np.asarray(a), np.asarray(flags)
    assert a.dtype == np.int16  # N < 2**15 narrows exactly
    assert flags.dtype == np.uint8
    np.testing.assert_array_equal(a.astype(np.int32), assignment)
    np.testing.assert_array_equal((flags & 1) != 0, unsched)
    np.testing.assert_array_equal((flags & 2) != 0, dropped)
    # a node axis too wide for i16 keeps i32 (no silent wrap)
    wide = build_decision_slim_fn(1 << 15)
    a32, _ = wide(assignment, unsched, dropped)
    assert np.asarray(a32).dtype == np.int32


def test_pipeline_ordering_guard_and_slim_matches_result():
    nodes = [
        MakeNode(f"n{i}").capacity({"cpu": "4"}).obj() for i in range(3)
    ]
    pods = [MakePod(f"p{i}").req({"cpu": "1"}).obj() for i in range(4)]
    enc = SnapshotEncoder(pad_pods=8, pad_nodes=4)
    wbuf, bbuf, spec, _snap, _dirty = enc.encode_packed(nodes, pods)
    from k8s_scheduler_tpu.core.cycle import (
        build_packed_cycle_fn,
        build_stable_state_fn,
    )

    cyc = build_packed_cycle_fn(spec, commit_mode="scan")
    stable = build_stable_state_fn(spec)(wbuf, bbuf)
    pipe = ServingPipeline(cyc)
    h1 = pipe.dispatch(wbuf, bbuf, stable)
    # strict ordering: cycle k+1 may not dispatch before cycle k's
    # decisions were fetched (binds could not have folded yet)
    with pytest.raises(RuntimeError, match="decisions were fetched"):
        pipe.dispatch(wbuf, bbuf, stable)
    assignment, unsched, dropped = h1.decisions()
    np.testing.assert_array_equal(
        assignment, np.asarray(h1.result.assignment)
    )
    np.testing.assert_array_equal(
        unsched, np.asarray(h1.result.unschedulable)
    )
    np.testing.assert_array_equal(
        dropped, np.asarray(h1.result.gang_dropped)
    )
    assert pipe.stats["fetch_bytes"] > 0
    assert pipe.stats["fetch_bytes"] < pipe.stats["fetch_bytes_full"]
    # after the fetch, the next dispatch proceeds (slot reuse path)
    h2 = pipe.dispatch(wbuf, bbuf, stable)
    a2, _, _ = h2.decisions()
    np.testing.assert_array_equal(a2, assignment)
    # fold-free loops may opt out of the guard
    pipe2 = ServingPipeline(cyc, require_decision_fetch=False)
    pipe2.dispatch(wbuf, bbuf, stable)
    pipe2.dispatch(wbuf, bbuf, stable).decisions()


def test_donate_diagnosis_refuses_preemption_consumer():
    # a donated diagnosis consumes the slot's packed buffers; a
    # preemption program dispatched after it would read freed memory
    with pytest.raises(ValueError, match="donate_diagnosis"):
        ServingPipeline(
            lambda *a: None,
            diag_fn=lambda *a: None,
            preempt_fn=lambda *a: None,
            donate_diagnosis=True,
        )


def test_donated_diagnosis_consumes_slot_buffers():
    """The donation path end to end: the diagnosis program is the slot's
    last consumer, reject counts still attribute, and the slot recycles
    for the next dispatch (fresh device_put per cycle)."""
    from k8s_scheduler_tpu.core.cycle import (
        build_diagnosis_fn,
        build_packed_cycle_fn,
        build_stable_state_fn,
    )

    nodes = [
        MakeNode(f"n{i}").capacity({"cpu": "4"}).obj() for i in range(3)
    ]
    pods = [MakePod(f"p{i}").req({"cpu": "1"}).obj() for i in range(3)]
    pods.append(MakePod("huge").req({"cpu": "64"}).obj())
    enc = SnapshotEncoder(pad_pods=8, pad_nodes=4)
    wbuf, bbuf, spec, _snap, _dirty = enc.encode_packed(nodes, pods)
    cyc = build_packed_cycle_fn(spec, commit_mode="scan")
    stable = build_stable_state_fn(spec)(wbuf, bbuf)
    pipe = ServingPipeline(
        cyc,
        diag_fn=build_diagnosis_fn(spec, donate=True),
        donate_diagnosis=True,
    )
    h = pipe.dispatch(wbuf, bbuf, stable)
    a, unsched, _ = h.decisions()
    assert unsched[3]  # 'huge' found no node
    rc = h.reject_counts()
    assert rc is not None and rc[3].sum() > 0  # attributed off-path
    assert h._wbuf is None  # buffers handed to the diagnosis program
    h2 = pipe.dispatch(wbuf, bbuf, stable)
    h2.decisions()
    np.testing.assert_array_equal(h2.reject_counts(), rc)


def _mini_cluster(s: Scheduler, n_pods: int, prefix: str):
    for i in range(n_pods):
        s.on_pod_add(
            MakePod(f"{prefix}{i}").req({"cpu": "1"}).created(float(i))
            .obj()
        )


def test_binds_fold_before_next_cycle_encodes():
    """Cycle k's binds must be visible (as existing/assumed pods) to the
    encode of cycle k+1 — the pipeline's strict ordering contract at the
    Scheduler level."""
    s = Scheduler()
    seq: list[tuple] = []
    enc = s._encoder
    orig = enc.encode_packed

    def wrapped(nodes, pending, existing, *a, **k):
        seq.append(("encode", sorted(p.name for p, _ in existing)))
        return orig(nodes, pending, existing, *a, **k)

    enc.encode_packed = wrapped
    s.binder = lambda pod, node: seq.append(("bind", pod.name))
    for i in range(2):
        s.on_node_add(
            MakeNode(f"n{i}").capacity({"cpu": "4"}).obj()
        )
    _mini_cluster(s, 3, "a")
    s.schedule_cycle()
    _mini_cluster(s, 2, "b")
    s.schedule_cycle()
    encodes = [e for e in seq if e[0] == "encode"]
    binds_c1 = {
        name for kind, name in seq[: seq.index(encodes[1])]
        if kind == "bind"
    }
    assert binds_c1, "cycle 1 bound nothing; fixture broken"
    assert binds_c1 <= set(encodes[1][1]), (
        "cycle 2 encoded before cycle 1's binds folded into the cache"
    )


def test_forced_sync_produces_identical_bindings():
    """forced_sync is an execution-order escape hatch, not a semantic
    switch: the same workload binds identically either way."""
    results = {}
    for sync in (False, True):
        s = Scheduler(
            config=SchedulerConfiguration(forced_sync=sync)
        )
        bound = []
        s.binder = lambda pod, node, bound=bound: bound.append(
            (pod.name, node)
        )
        for i in range(3):
            s.on_node_add(
                MakeNode(f"n{i}").capacity({"cpu": "4"}).obj()
            )
        _mini_cluster(s, 5, "p")
        s.on_pod_add(MakePod("huge").req({"cpu": "64"}).obj())
        st = s.schedule_cycle()
        results[sync] = (sorted(bound), st.scheduled, st.unschedulable)
        # the pipeline really ran and fetched the slimmed payload
        pipes = [v["fns"][6] for v in s._packed.values()]
        assert pipes and pipes[0].fetch_bytes_total > 0
        assert pipes[0].forced_sync is sync
    assert results[False] == results[True]


def test_cpu_backend_arena_copy_guards_deferred_programs():
    """The CPU-backend arena race (PR 4's open note), closed: a dispatch
    fed RAW numpy arena buffers (device_put=False — probe paths and
    K8S_TPU_NO_DEVICE_PUT=1) must take an explicit device copy before
    async dispatch on the CPU backend. The deferred diagnosis/preemption
    programs are forced lazily, possibly AFTER the next encode rewrote
    the arena in place; without the copy they would attribute against
    the NEXT cycle's bytes (jax's CPU backend converts numpy args
    asynchronously / by aliasing, so the rewrite tears them)."""
    import jax

    if jax.default_backend() != "cpu":
        pytest.skip("CPU-backend aliasing guard")
    from k8s_scheduler_tpu.core.cycle import (
        build_diagnosis_fn,
        build_packed_cycle_fn,
        build_stable_state_fn,
    )

    nodes = [
        MakeNode(f"n{i}").capacity({"cpu": "4"}).obj() for i in range(3)
    ]
    pods = [MakePod(f"p{i}").req({"cpu": "1"}).obj() for i in range(3)]
    pods.append(MakePod("huge").req({"cpu": "64"}).obj())
    enc = SnapshotEncoder(pad_pods=8, pad_nodes=4)
    wbuf, bbuf, spec, _snap, _dirty = enc.encode_packed(nodes, pods)
    cyc = build_packed_cycle_fn(
        spec, commit_mode="scan", outputs="latency"
    )
    pipe = ServingPipeline(cyc, diag_fn=build_diagnosis_fn(spec))
    stable = build_stable_state_fn(spec)(wbuf.copy(), bbuf.copy())

    h1 = pipe.dispatch(wbuf, bbuf, stable, device_put=False)
    _, unsched, _ = h1.decisions()
    assert unsched[3]  # 'huge' found no node; diagnosis has work to do
    rc_ref = np.asarray(h1.reject_counts()).copy()
    assert rc_ref[3].sum() > 0

    h2 = pipe.dispatch(wbuf, bbuf, stable, device_put=False)
    h2.decisions()
    # the next encode's in-place arena rewrite, BEFORE the deferred
    # diagnosis is forced — without the explicit copy the diagnosis
    # would read these zeros and attribute nothing
    wbuf[:] = 0
    bbuf[:] = 0
    np.testing.assert_array_equal(
        np.asarray(h2.reject_counts()), rc_ref,
        err_msg="deferred diagnosis read the rewritten arena "
        "(CPU-backend copy guard regressed)",
    )
