"""Fault tests for the measurement harness (VERDICT r3 weak #2 / item 3).

Round 3's official bench artifact was zeroed by one tunnel flake
(`remote_compile: read body: response body closed` → rc=1, parsed:null).
These tests prove that can no longer happen: per-config isolation in
bench.py emits partial JSON with error annotations, transport-class
errors get one retry, and the _Resilient program wrapper absorbs
transport flakes with a recorded strike.
"""

import json

import pytest

import bench
from k8s_scheduler_tpu.core.cycle import (
    RESILIENT_STRIKES,
    _Resilient,
    is_transport_error,
)


class _FakeTransportError(RuntimeError):
    pass


def _mk_result(cfg):
    return {
        "config": cfg,
        "decisions_per_sec": 1000.0 * cfg,
        "p50_ms": 1.0,
        "p99_ms": 2.0,
    }


def _run_bench_main(monkeypatch, capsys, run_config, configs="1,2"):
    monkeypatch.setenv("BENCH_CONFIGS", configs)
    monkeypatch.setenv("BENCH_SNAPSHOTS", "1")
    # in-process so the monkeypatched run_config is what executes (the
    # default subprocess isolation would run the real one)
    monkeypatch.setenv("BENCH_ISOLATE", "0")
    import bench_suite

    monkeypatch.setattr(bench_suite, "run_config", run_config)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()[-1]
    return json.loads(out)


def test_transport_flake_retried_and_bench_parses(monkeypatch, capsys):
    calls = {"n": 0}

    def run_config(c, snapshots):
        if c == 2 and calls["n"] == 0:
            calls["n"] += 1
            raise _FakeTransportError(
                "INTERNAL: http://127.0.0.1:8103/remote_compile: "
                "read body: response body closed before all bytes were read"
            )
        return _mk_result(c)

    doc = _run_bench_main(monkeypatch, capsys, run_config)
    assert [r["c"] for r in doc["configs"]] == [1, 2]
    # the retried flake is annotated, not fatal
    errs = doc["errors"]
    assert errs[0]["config"] == 2 and errs[0]["transport"] is True
    assert doc["value"] == 2000.0  # headline falls back to last config


def test_permanent_config_failure_yields_partial_json(monkeypatch, capsys):
    def run_config(c, snapshots):
        if c == 4:
            raise ValueError("genuine program bug")
        return _mk_result(c)

    doc = _run_bench_main(monkeypatch, capsys, run_config, configs="1,4,5")
    assert [r["c"] for r in doc["configs"]] == [1, 5]
    err = doc["errors"][0]
    assert err["config"] == 4 and err["transport"] is False
    assert err["attempt"] == 0  # non-transport errors are not retried
    assert doc["value"] == 5000.0  # headline falls back to last config


def test_all_configs_failing_still_emits_parseable_line(monkeypatch, capsys):
    def run_config(c, snapshots):
        raise _FakeTransportError("connection reset by peer")

    doc = _run_bench_main(monkeypatch, capsys, run_config)
    assert doc["value"] == 0.0
    assert doc["configs"] == []
    assert len(doc["errors"]) == 2
    # the full detail (incl. tracebacks of what failed) is on disk
    with open("BENCH_DETAIL.json") as f:
        det = json.load(f)
    assert len(det["errors"]) == 2


def test_is_transport_error_classification():
    assert is_transport_error(
        RuntimeError("remote_compile: response body closed")
    )
    assert is_transport_error(OSError("Connection reset by peer"))
    assert not is_transport_error(ValueError("rank mismatch"))
    assert not is_transport_error(
        ValueError("Executable expected parameter 3")
    )


def test_resilient_absorbs_transport_flake_and_counts_strike():
    # the one transport retry sleeps 0.5s — acceptable in a unit test
    state = {"calls": 0, "cleared": 0}

    def fn(x):
        state["calls"] += 1
        if state["calls"] == 1:
            raise _FakeTransportError(
                "http://127.0.0.1:8103/remote_execute: broken pipe"
            )
        return x + 1

    fn.__name__ = "fake_program"
    fn.clear_cache = lambda: state.__setitem__(
        "cleared", state["cleared"] + 1
    )

    RESILIENT_STRIKES.clear()
    r = _Resilient(fn)
    assert r(41) == 42
    assert state["calls"] == 2
    assert state["cleared"] == 0  # transport retries must NOT clear_cache
    assert RESILIENT_STRIKES == {("fake_program", "transport"): 1}

    from k8s_scheduler_tpu.metrics.metrics import global_metrics

    v = global_metrics().registry.get_sample_value(
        "scheduler_program_retry_strikes_total",
        {"program": "fake_program", "kind": "transport"},
    )
    assert v is not None and v >= 1


def test_resilient_corruption_strike_clears_cache_and_counts():
    state = {"calls": 0, "cleared": 0}

    def fn(x):
        state["calls"] += 1
        if state["calls"] == 1:
            raise ValueError(
                "Execution supplied 3 buffers but compiled program "
                "expected 4 buffers"
            )
        return x * 2

    fn.__name__ = "fake_corrupt"
    fn.clear_cache = lambda: state.__setitem__(
        "cleared", state["cleared"] + 1
    )

    RESILIENT_STRIKES.clear()
    r = _Resilient(fn)
    assert r(21) == 42
    assert state["cleared"] == 1
    assert RESILIENT_STRIKES == {("fake_corrupt", "executable_cache"): 1}


def test_resilient_wedge_fails_fast_with_strike():
    """The rig-wedge signature is NOT healable in-process (clear_cache +
    retrace fail once the backend session is wedged — PERF.md r5), so
    _Resilient must record the strike and raise on the FIRST attempt
    instead of burning ~100s retraces."""
    state = {"calls": 0, "cleared": 0}

    def fn(x):
        state["calls"] += 1
        raise RuntimeError(
            "INVALID_ARGUMENT: TPU backend error (InvalidArgument)."
        )

    fn.__name__ = "fake_wedge"
    fn.clear_cache = lambda: state.__setitem__(
        "cleared", state["cleared"] + 1
    )

    RESILIENT_STRIKES.clear()
    with pytest.raises(RuntimeError, match="TPU backend error"):
        _Resilient(fn)(1)
    assert state["calls"] == 1  # no doomed retries
    assert state["cleared"] == 0  # no needless retrace
    assert RESILIENT_STRIKES == {("fake_wedge", "backend_wedge"): 1}


def test_resilient_reraises_non_retryable():
    def fn(x):
        raise ValueError("rank mismatch in dot_general")

    fn.__name__ = "fake_bad"
    fn.clear_cache = lambda: None
    with pytest.raises(ValueError, match="rank mismatch"):
        _Resilient(fn)(1)


def test_strike_metric_reaches_served_registry():
    """VERDICT r3 item 7 end-to-end: a _Resilient strike must appear in
    the registry the CLI serves on /metrics. Strikes land in
    global_metrics(); the CLI constructs its Scheduler with
    metrics=global_metrics() (cmd/main.py), mirrored here."""
    from k8s_scheduler_tpu.core.scheduler import Scheduler
    from k8s_scheduler_tpu.metrics.metrics import global_metrics

    state = {"calls": 0}

    def fn(x):
        state["calls"] += 1
        if state["calls"] == 1:
            raise ValueError(
                "Executable expected parameter 0 of size 8 but got "
                "buffer with incompatible size 4"
            )
        return x

    fn.__name__ = "fake_served"
    fn.clear_cache = lambda: None
    assert _Resilient(fn)(5) == 5

    sched = Scheduler(metrics=global_metrics())
    assert sched.metrics is global_metrics()
    payload = sched.metrics.expose().decode()
    assert "scheduler_program_retry_strikes_total" in payload
    assert 'program="fake_served"' in payload


def test_two_schedulers_do_not_cross_count():
    """r4 regression (VERDICT r4 weak #2): default-constructed Schedulers
    must each get a FRESH registry — metric increments on one must not
    appear in the other's served payload, and neither must write the
    process-wide registry."""
    from k8s_scheduler_tpu.core.scheduler import Scheduler
    from k8s_scheduler_tpu.metrics.metrics import global_metrics

    a, b = Scheduler(), Scheduler()
    assert a.metrics is not b.metrics
    assert a.metrics is not global_metrics()

    a.metrics.schedule_attempts.labels(
        result="isolation-probe", profile="isolation-probe"
    ).inc()
    val = lambda m: m.registry.get_sample_value(
        "scheduler_schedule_attempts_total",
        {"result": "isolation-probe", "profile": "isolation-probe"},
    )
    assert val(a.metrics) == 1.0
    assert val(b.metrics) is None
    assert val(global_metrics()) is None
