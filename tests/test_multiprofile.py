"""Multi-profile serving (VERDICT r2 item 4; SURVEY.md §2 C12, §5.6):
pods route to the framework of the profile named by
`pod.spec.scheduler_name`; two profiles with different score weights
produce different placements for identical pods in one process; unknown
scheduler names are parked loudly, never silently scheduled under the
wrong profile.
"""

import pytest

from k8s_scheduler_tpu.config import (
    PluginEntry,
    Plugins,
    PluginSet,
    Profile,
    SchedulerConfiguration,
)
from k8s_scheduler_tpu.core import Scheduler
from k8s_scheduler_tpu.models import MakeNode, MakePod


def two_profile_config() -> SchedulerConfiguration:
    # profile A: ImageLocality massively upweighted; profile B: no
    # ImageLocality at all — identical pods diverge on an image-warm node
    return SchedulerConfiguration(profiles=[
        Profile(
            scheduler_name="image-lover",
            plugins=Plugins(score=PluginSet(
                disabled=["*"],
                enabled=[PluginEntry("ImageLocality", weight=100)],
            )),
        ),
        Profile(
            scheduler_name="image-blind",
            plugins=Plugins(score=PluginSet(
                disabled=["*"],
                enabled=[PluginEntry("NodeResourcesFit", weight=1)],
            )),
        ),
    ])


def make_cluster_and_scheduler():
    binds = {}
    sched = Scheduler(
        config=two_profile_config(),
        binder=lambda pod, node: binds.__setitem__(pod.name, node),
    )
    # node-1 holds the (huge, everywhere-counted) image but is slightly
    # more loaded; node-0 is emptier. Image-driven scoring picks node-1,
    # resource-driven scoring picks node-0.
    sched.on_node_add(MakeNode("node-0").capacity({"cpu": "8"}).obj())
    sched.on_node_add(
        MakeNode("node-1").capacity({"cpu": "8"})
        .image("big:v1", 2 * 2**30).obj()
    )
    filler = MakePod("filler").req({"cpu": "2"}).obj()
    sched.on_pod_add(filler, node_name="node-1")
    return sched, binds


def test_profiles_place_identical_pods_differently():
    sched, binds = make_cluster_and_scheduler()
    a = (
        MakePod("pod-a").req({"cpu": "1"}).image("big:v1")
        .scheduler("image-lover").obj()
    )
    b = (
        MakePod("pod-b").req({"cpu": "1"}).image("big:v1")
        .scheduler("image-blind").obj()
    )
    sched.on_pod_add(a)
    sched.on_pod_add(b)
    stats = sched.schedule_cycle()
    assert stats.scheduled == 2
    assert binds["pod-a"] == "node-1"  # image gravity
    assert binds["pod-b"] == "node-0"  # resource gravity


def test_unknown_scheduler_name_is_parked_loudly():
    sched, binds = make_cluster_and_scheduler()
    ghost = (
        MakePod("ghost").req({"cpu": "1"})
        .scheduler("no-such-scheduler").obj()
    )
    sched.on_pod_add(ghost)
    stats = sched.schedule_cycle()
    assert stats.scheduled == 0
    assert stats.unschedulable == 1
    assert "ghost" not in binds
    evs = [e for e in sched.events.events() if e.pod_name == "ghost"]
    assert evs and "no profile named" in evs[-1].message


def test_default_profile_name_still_routes():
    # a single default-profile scheduler keeps working unchanged
    binds = {}
    sched = Scheduler(
        binder=lambda pod, node: binds.__setitem__(pod.name, node)
    )
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "4"}).obj())
    sched.on_pod_add(MakePod("p").req({"cpu": "1"}).obj())
    stats = sched.schedule_cycle()
    assert stats.scheduled == 1 and binds["p"] == "n0"


def test_nomination_survives_other_profiles_encode():
    # profile B's preemption nominates in-place; profile A encoding
    # first in the next cycle must NOT consume B's mutation report
    # (per-profile mutation sets — the delta arena would otherwise keep
    # pod_nominated=-1 for B's preemptor)
    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clock = Clock()
    evicted = []
    sched = Scheduler(
        config=two_profile_config(),
        evictor=lambda pod, node: evicted.append(pod.name),
        now=clock,
    )
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "2"}).obj())
    victim = MakePod("victim").req({"cpu": "2"}).priority(0).obj()
    sched.on_pod_add(victim, node_name="n0")
    # keep profile A busy every cycle so its encode runs first
    a_pod = (
        MakePod("a-idle").req({"cpu": "100"})  # never fits; stays pending
        .scheduler("image-lover").obj()
    )
    preemptor = (
        MakePod("preemptor").req(ba := {"cpu": "2"}).priority(10)
        .scheduler("image-blind").created(1.0).obj()
    )
    sched.on_pod_add(a_pod)
    sched.on_pod_add(preemptor)
    s1 = sched.schedule_cycle()
    assert s1.preemptors == 1 and evicted == ["victim"]
    assert preemptor.nominated_node_name == "n0"
    # victim eviction observed; next cycles: the preemptor's nominated
    # row must be present in profile B's arena (not wiped by A's encode)
    clock.t += 30.0  # clear pod backoff
    sched.on_pod_delete(victim.uid)
    binds = {}
    sched.binder = lambda pod, node: binds.__setitem__(pod.name, node)
    s2 = sched.schedule_cycle()
    assert binds.get("preemptor") == "n0", (s2, binds)


def test_duplicate_profile_names_rejected():
    cfg = SchedulerConfiguration(
        profiles=[Profile("x"), Profile("x")]
    )
    with pytest.raises(ValueError):
        Scheduler(config=cfg)


if __name__ == "__main__":
    import sys

    pytest.main([__file__, "-v"] + sys.argv[1:])
