"""VolumeBinding filter: kernel/oracle differential tests incl. the
zone-conflict cases (a bound PV pinned to one zone must pin the pod),
static-PV candidacy, dynamic-provisioning topology, and the
unschedulable cases (missing PVC, unbound Immediate claim)."""

from __future__ import annotations

import numpy as np
import pytest

from k8s_scheduler_tpu import oracle
from k8s_scheduler_tpu.core.cycle import build_cycle_fn
from k8s_scheduler_tpu.framework.interfaces import CycleContext
from k8s_scheduler_tpu.framework.plugins import VolumeBinding
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.models.api import (
    VOLUME_BINDING_WAIT,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
)
from k8s_scheduler_tpu.models.builders import MakeNode, MakePod

ZONE = "topology.kubernetes.io/zone"
GiB = 1024**3


def zone_term(*zones: str) -> NodeSelectorTerm:
    return NodeSelectorTerm(
        (NodeSelectorRequirement(ZONE, "In", tuple(zones)),)
    )


def make_zoned_nodes(n_per_zone=2, zones=("z0", "z1", "z2")):
    nodes = []
    for z in zones:
        for i in range(n_per_zone):
            nodes.append(
                MakeNode(f"{z}-n{i}")
                .capacity({"cpu": "8"})
                .labels({ZONE: z})
                .obj()
            )
    return nodes


def kernel_mask(nodes, pods, pvcs=(), pvs=(), classes=()):
    snap = SnapshotEncoder().encode(
        nodes, pods, pvcs=pvcs, pvs=pvs, storage_classes=classes
    )
    plugin = VolumeBinding()
    ctx = CycleContext(snap)
    m = plugin.static_mask(ctx)
    if m is None:
        return None, snap
    return np.asarray(m), snap


def oracle_mask(nodes, pods, pvcs=(), pvs=(), classes=()):
    state = oracle.OracleState.build(nodes, (), pvcs, pvs, classes)
    return np.array(
        [
            [oracle.filter_volume_binding(p, state, i)
             for i in range(len(nodes))]
            for p in pods
        ]
    )


def assert_differential(nodes, pods, pvcs=(), pvs=(), classes=()):
    got, snap = kernel_mask(nodes, pods, pvcs, pvs, classes)
    want = oracle_mask(nodes, pods, pvcs, pvs, classes)
    assert got is not None
    np.testing.assert_array_equal(
        got[: len(pods), : len(nodes)], want,
        err_msg="kernel/oracle VolumeBinding mask disagreement",
    )


def test_bound_pv_zone_conflict_pins_pod():
    nodes = make_zoned_nodes()
    pvs = [
        PersistentVolume(
            "pv-z1", capacity=10 * GiB, storage_class="ssd",
            node_affinity=(zone_term("z1"),), claim_ref="default/data",
        )
    ]
    pvcs = [
        PersistentVolumeClaim(
            "data", storage_class="ssd", request=5 * GiB,
            volume_name="pv-z1",
        )
    ]
    pods = [MakePod("db").req({"cpu": "1"}).volume("data").obj()]
    got, _ = kernel_mask(nodes, pods, pvcs, pvs)
    # only the two z1 nodes are feasible
    assert got[0, :6].tolist() == [False, False, True, True, False, False]
    assert_differential(nodes, pods, pvcs, pvs)


def test_unbound_wait_class_static_pv_candidates():
    nodes = make_zoned_nodes()
    classes = [
        StorageClass("local", VOLUME_BINDING_WAIT, provisioner=False)
    ]
    pvs = [
        PersistentVolume("pv-a", capacity=10 * GiB, storage_class="local",
                         node_affinity=(zone_term("z0"),)),
        PersistentVolume("pv-small", capacity=1 * GiB,
                         storage_class="local",
                         node_affinity=(zone_term("z2"),)),
    ]
    pvcs = [
        PersistentVolumeClaim("scratch", storage_class="local",
                              request=5 * GiB)
    ]
    pods = [MakePod("w").req({"cpu": "1"}).volume("scratch").obj()]
    got, _ = kernel_mask(nodes, pods, pvcs, pvs, classes)
    # pv-a fits (z0); pv-small is too small (z2 excluded); no provisioner
    assert got[0, :6].tolist() == [True, True, False, False, False, False]
    assert_differential(nodes, pods, pvcs, pvs, classes)


def test_dynamic_provisioning_allowed_topologies():
    nodes = make_zoned_nodes()
    classes = [
        StorageClass(
            "ebs", VOLUME_BINDING_WAIT, provisioner=True,
            allowed_topologies=(zone_term("z2"),),
        )
    ]
    pvcs = [
        PersistentVolumeClaim("dyn", storage_class="ebs", request=5 * GiB)
    ]
    pods = [MakePod("w").req({"cpu": "1"}).volume("dyn").obj()]
    got, _ = kernel_mask(nodes, pods, pvcs, classes=classes)
    assert got[0, :6].tolist() == [False, False, False, False, True, True]
    assert_differential(nodes, pods, pvcs, classes=classes)


def test_missing_pvc_and_unbound_immediate_are_unschedulable():
    nodes = make_zoned_nodes()
    classes = [StorageClass("imm")]  # Immediate mode
    pvcs = [
        PersistentVolumeClaim("imm-claim", storage_class="imm",
                              request=1 * GiB)
    ]
    pods = [
        MakePod("no-pvc").req({"cpu": "1"}).volume("ghost").obj(),
        MakePod("imm").req({"cpu": "1"}).volume("imm-claim").obj(),
    ]
    got, _ = kernel_mask(nodes, pods, pvcs, classes=classes)
    assert not got[0].any()
    assert not got[1].any()
    assert_differential(nodes, pods, pvcs, classes=classes)


def test_claimed_pv_is_not_a_candidate():
    nodes = make_zoned_nodes()
    classes = [
        StorageClass("local", VOLUME_BINDING_WAIT, provisioner=False)
    ]
    pvs = [
        PersistentVolume("pv-a", capacity=10 * GiB, storage_class="local",
                         claim_ref="other/taken"),
    ]
    pvcs = [
        PersistentVolumeClaim("scratch", storage_class="local",
                              request=5 * GiB)
    ]
    pods = [MakePod("w").req({"cpu": "1"}).volume("scratch").obj()]
    got, _ = kernel_mask(nodes, pods, pvcs, pvs, classes)
    assert not got[0].any()
    assert_differential(nodes, pods, pvcs, pvs, classes)


def test_multi_volume_conjunction():
    nodes = make_zoned_nodes()
    pvs = [
        PersistentVolume("pv-z0z1", capacity=10 * GiB, storage_class="ssd",
                         node_affinity=(zone_term("z0", "z1"),),
                         claim_ref="default/a"),
        PersistentVolume("pv-z1z2", capacity=10 * GiB, storage_class="ssd",
                         node_affinity=(zone_term("z1", "z2"),),
                         claim_ref="default/b"),
    ]
    pvcs = [
        PersistentVolumeClaim("a", storage_class="ssd", request=GiB,
                              volume_name="pv-z0z1"),
        PersistentVolumeClaim("b", storage_class="ssd", request=GiB,
                              volume_name="pv-z1z2"),
    ]
    pods = [
        MakePod("both").req({"cpu": "1"}).volume("a").volume("b").obj()
    ]
    got, _ = kernel_mask(nodes, pods, pvcs, pvs)
    # intersection: z1 only
    assert got[0, :6].tolist() == [False, False, True, True, False, False]
    assert_differential(nodes, pods, pvcs, pvs)


def test_volume_free_cluster_pays_nothing():
    nodes = make_zoned_nodes()
    pods = [MakePod("plain").req({"cpu": "1"}).obj()]
    got, snap = kernel_mask(nodes, pods)
    assert got is None  # capability flag off -> kernel never traced
    assert not snap.has_volumes


def test_end_to_end_cycle_respects_volume_zone():
    nodes = make_zoned_nodes()
    pvs = [
        PersistentVolume("pv-z2", capacity=10 * GiB, storage_class="ssd",
                         node_affinity=(zone_term("z2"),),
                         claim_ref="default/data"),
    ]
    pvcs = [
        PersistentVolumeClaim("data", storage_class="ssd", request=GiB,
                              volume_name="pv-z2"),
    ]
    pods = [MakePod("db").req({"cpu": "1"}).volume("data").obj()]
    snap = SnapshotEncoder().encode(nodes, pods, pvcs=pvcs, pvs=pvs)
    for mode in ("scan", "rounds"):
        out = build_cycle_fn(commit_mode=mode)(snap)
        a = int(np.asarray(out.assignment)[0])
        assert a in (4, 5), f"{mode}: pod landed outside z2 (node {a})"


# ---- multi-volume joint claim (PARITY #8 closure, VERDICT r3 item 9) ----


def _joint_fixture(n_pvs, sizes=(5, 5), pv_caps=None, provisioner=False):
    """One pod with len(sizes) PVCs of class 'local'; n_pvs PVs."""
    nodes = [MakeNode("n0").capacity({"cpu": "8"}).obj()]
    classes = [
        StorageClass("local", VOLUME_BINDING_WAIT, provisioner=provisioner)
    ]
    caps = pv_caps or [10] * n_pvs
    pvs = [
        PersistentVolume(f"pv-{v}", capacity=caps[v] * GiB,
                         storage_class="local")
        for v in range(n_pvs)
    ]
    pvcs = [
        PersistentVolumeClaim(f"c{j}", storage_class="local",
                              request=sizes[j] * GiB)
        for j in range(len(sizes))
    ]
    mk = MakePod("w").req({"cpu": "1"})
    for j in range(len(sizes)):
        mk = mk.volume(f"c{j}")
    return nodes, [mk.obj()], pvcs, pvs, classes


def test_two_pvcs_one_pv_is_infeasible():
    """A pod whose two PVCs are satisfiable only by the SAME single PV
    must be masked out (it used to be over-admitted and fail at bind)."""
    nodes, pods, pvcs, pvs, classes = _joint_fixture(n_pvs=1)
    assert_differential(nodes, pods, pvcs, pvs, classes)
    got, _ = kernel_mask(nodes, pods, pvcs, pvs, classes)
    assert not got[0, 0]


def test_two_pvcs_two_pvs_is_feasible_and_claims_both():
    nodes, pods, pvcs, pvs, classes = _joint_fixture(n_pvs=2)
    assert_differential(nodes, pods, pvcs, pvs, classes)
    from k8s_scheduler_tpu.core import build_cycle_fn

    enc = SnapshotEncoder(pad_pods=8, pad_nodes=4)
    snap = enc.encode(nodes, pods, pvcs=pvcs, pvs=pvs,
                      storage_classes=classes)
    out = build_cycle_fn(commit_mode="scan")(snap)
    assert np.asarray(out.assignment)[0] == 0
    assert np.asarray(out.pv_claimed).sum() == 2  # distinct PVs claimed


def test_two_pvcs_one_pv_plus_provisioner_is_feasible():
    """A dynamic-capable class means one slot can ride provisioning, so
    a single static PV suffices for the other slot."""
    nodes, pods, pvcs, pvs, classes = _joint_fixture(
        n_pvs=1, provisioner=True
    )
    assert_differential(nodes, pods, pvcs, pvs, classes)
    got, _ = kernel_mask(nodes, pods, pvcs, pvs, classes)
    assert got[0, 0]


@pytest.mark.parametrize("mode", ["scan", "rounds"])
def test_constrained_slot_claims_first_no_deadend(mode):
    """Greedy dead-end case: slot c0 (1 GiB) fits pv-0 (10 GiB) and
    pv-1 (2 GiB); slot c1 (8 GiB) fits ONLY pv-0. Claiming c0 first
    with lowest-index choice would take pv-0 and strand c1 — the
    constrained-first ordering must assign c1=pv-0, c0=pv-1."""
    nodes, pods, pvcs, pvs, classes = _joint_fixture(
        n_pvs=2, sizes=(1, 8), pv_caps=[10, 2]
    )
    assert_differential(nodes, pods, pvcs, pvs, classes)
    from k8s_scheduler_tpu.core import build_cycle_fn

    enc = SnapshotEncoder(pad_pods=8, pad_nodes=4)
    snap = enc.encode(nodes, pods, pvcs=pvcs, pvs=pvs,
                      storage_classes=classes)
    out = build_cycle_fn(commit_mode=mode)(snap)
    assert np.asarray(out.assignment)[0] == 0
    assert np.asarray(out.pv_claimed).sum() == 2

    # oracle agrees and assigns distinct PVs
    state = oracle.OracleState.build(nodes, (), pvcs, pvs, classes)
    assert oracle.filter_volume_binding(pods[0], state, 0)
    state.add(0, pods[0])
    assert state.claimed_static == {"pv-0", "pv-1"}


def test_two_pods_two_pvcs_each_contending():
    """Differential under contention: two 2-PVC pods over 3 PVs — only
    one pod can satisfy both claims; the loser must not place."""
    nodes = [MakeNode("n0").capacity({"cpu": "8"}).obj()]
    classes = [
        StorageClass("local", VOLUME_BINDING_WAIT, provisioner=False)
    ]
    pvs = [
        PersistentVolume(f"pv-{v}", capacity=10 * GiB,
                         storage_class="local")
        for v in range(3)
    ]
    pvcs = [
        PersistentVolumeClaim(f"c{j}", storage_class="local",
                              request=5 * GiB)
        for j in range(4)
    ]
    pods = [
        MakePod("a").req({"cpu": "1"}).volume("c0").volume("c1")
        .created(0.0).obj(),
        MakePod("b").req({"cpu": "1"}).volume("c2").volume("c3")
        .created(1.0).obj(),
    ]
    from k8s_scheduler_tpu.core import build_cycle_fn

    enc = SnapshotEncoder(pad_pods=8, pad_nodes=4)
    snap = enc.encode(nodes, pods, pvcs=pvcs, pvs=pvs,
                      storage_classes=classes)
    for mode in ("scan", "rounds"):
        out = build_cycle_fn(commit_mode=mode)(snap)
        a = np.asarray(out.assignment)[:2]
        assert a[0] == 0 and a[1] < 0, (mode, a)

    # scan == oracle end to end
    want = [
        d.node_index
        for d in oracle.schedule(nodes, pods, pvcs=pvcs, pvs=pvs,
                                 storage_classes=classes)
    ]
    out = build_cycle_fn(commit_mode="scan")(snap)
    assert list(np.asarray(out.assignment)[:2]) == want
