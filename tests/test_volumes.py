"""VolumeBinding filter: kernel/oracle differential tests incl. the
zone-conflict cases (a bound PV pinned to one zone must pin the pod),
static-PV candidacy, dynamic-provisioning topology, and the
unschedulable cases (missing PVC, unbound Immediate claim)."""

from __future__ import annotations

import numpy as np
import pytest

from k8s_scheduler_tpu import oracle
from k8s_scheduler_tpu.core.cycle import build_cycle_fn
from k8s_scheduler_tpu.framework.interfaces import CycleContext
from k8s_scheduler_tpu.framework.plugins import VolumeBinding
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.models.api import (
    VOLUME_BINDING_WAIT,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
)
from k8s_scheduler_tpu.models.builders import MakeNode, MakePod

ZONE = "topology.kubernetes.io/zone"
GiB = 1024**3


def zone_term(*zones: str) -> NodeSelectorTerm:
    return NodeSelectorTerm(
        (NodeSelectorRequirement(ZONE, "In", tuple(zones)),)
    )


def make_zoned_nodes(n_per_zone=2, zones=("z0", "z1", "z2")):
    nodes = []
    for z in zones:
        for i in range(n_per_zone):
            nodes.append(
                MakeNode(f"{z}-n{i}")
                .capacity({"cpu": "8"})
                .labels({ZONE: z})
                .obj()
            )
    return nodes


def kernel_mask(nodes, pods, pvcs=(), pvs=(), classes=()):
    snap = SnapshotEncoder().encode(
        nodes, pods, pvcs=pvcs, pvs=pvs, storage_classes=classes
    )
    plugin = VolumeBinding()
    ctx = CycleContext(snap)
    m = plugin.static_mask(ctx)
    if m is None:
        return None, snap
    return np.asarray(m), snap


def oracle_mask(nodes, pods, pvcs=(), pvs=(), classes=()):
    state = oracle.OracleState.build(nodes, (), pvcs, pvs, classes)
    return np.array(
        [
            [oracle.filter_volume_binding(p, state, i)
             for i in range(len(nodes))]
            for p in pods
        ]
    )


def assert_differential(nodes, pods, pvcs=(), pvs=(), classes=()):
    got, snap = kernel_mask(nodes, pods, pvcs, pvs, classes)
    want = oracle_mask(nodes, pods, pvcs, pvs, classes)
    assert got is not None
    np.testing.assert_array_equal(
        got[: len(pods), : len(nodes)], want,
        err_msg="kernel/oracle VolumeBinding mask disagreement",
    )


def test_bound_pv_zone_conflict_pins_pod():
    nodes = make_zoned_nodes()
    pvs = [
        PersistentVolume(
            "pv-z1", capacity=10 * GiB, storage_class="ssd",
            node_affinity=(zone_term("z1"),), claim_ref="default/data",
        )
    ]
    pvcs = [
        PersistentVolumeClaim(
            "data", storage_class="ssd", request=5 * GiB,
            volume_name="pv-z1",
        )
    ]
    pods = [MakePod("db").req({"cpu": "1"}).volume("data").obj()]
    got, _ = kernel_mask(nodes, pods, pvcs, pvs)
    # only the two z1 nodes are feasible
    assert got[0, :6].tolist() == [False, False, True, True, False, False]
    assert_differential(nodes, pods, pvcs, pvs)


def test_unbound_wait_class_static_pv_candidates():
    nodes = make_zoned_nodes()
    classes = [
        StorageClass("local", VOLUME_BINDING_WAIT, provisioner=False)
    ]
    pvs = [
        PersistentVolume("pv-a", capacity=10 * GiB, storage_class="local",
                         node_affinity=(zone_term("z0"),)),
        PersistentVolume("pv-small", capacity=1 * GiB,
                         storage_class="local",
                         node_affinity=(zone_term("z2"),)),
    ]
    pvcs = [
        PersistentVolumeClaim("scratch", storage_class="local",
                              request=5 * GiB)
    ]
    pods = [MakePod("w").req({"cpu": "1"}).volume("scratch").obj()]
    got, _ = kernel_mask(nodes, pods, pvcs, pvs, classes)
    # pv-a fits (z0); pv-small is too small (z2 excluded); no provisioner
    assert got[0, :6].tolist() == [True, True, False, False, False, False]
    assert_differential(nodes, pods, pvcs, pvs, classes)


def test_dynamic_provisioning_allowed_topologies():
    nodes = make_zoned_nodes()
    classes = [
        StorageClass(
            "ebs", VOLUME_BINDING_WAIT, provisioner=True,
            allowed_topologies=(zone_term("z2"),),
        )
    ]
    pvcs = [
        PersistentVolumeClaim("dyn", storage_class="ebs", request=5 * GiB)
    ]
    pods = [MakePod("w").req({"cpu": "1"}).volume("dyn").obj()]
    got, _ = kernel_mask(nodes, pods, pvcs, classes=classes)
    assert got[0, :6].tolist() == [False, False, False, False, True, True]
    assert_differential(nodes, pods, pvcs, classes=classes)


def test_missing_pvc_and_unbound_immediate_are_unschedulable():
    nodes = make_zoned_nodes()
    classes = [StorageClass("imm")]  # Immediate mode
    pvcs = [
        PersistentVolumeClaim("imm-claim", storage_class="imm",
                              request=1 * GiB)
    ]
    pods = [
        MakePod("no-pvc").req({"cpu": "1"}).volume("ghost").obj(),
        MakePod("imm").req({"cpu": "1"}).volume("imm-claim").obj(),
    ]
    got, _ = kernel_mask(nodes, pods, pvcs, classes=classes)
    assert not got[0].any()
    assert not got[1].any()
    assert_differential(nodes, pods, pvcs, classes=classes)


def test_claimed_pv_is_not_a_candidate():
    nodes = make_zoned_nodes()
    classes = [
        StorageClass("local", VOLUME_BINDING_WAIT, provisioner=False)
    ]
    pvs = [
        PersistentVolume("pv-a", capacity=10 * GiB, storage_class="local",
                         claim_ref="other/taken"),
    ]
    pvcs = [
        PersistentVolumeClaim("scratch", storage_class="local",
                              request=5 * GiB)
    ]
    pods = [MakePod("w").req({"cpu": "1"}).volume("scratch").obj()]
    got, _ = kernel_mask(nodes, pods, pvcs, pvs, classes)
    assert not got[0].any()
    assert_differential(nodes, pods, pvcs, pvs, classes)


def test_multi_volume_conjunction():
    nodes = make_zoned_nodes()
    pvs = [
        PersistentVolume("pv-z0z1", capacity=10 * GiB, storage_class="ssd",
                         node_affinity=(zone_term("z0", "z1"),),
                         claim_ref="default/a"),
        PersistentVolume("pv-z1z2", capacity=10 * GiB, storage_class="ssd",
                         node_affinity=(zone_term("z1", "z2"),),
                         claim_ref="default/b"),
    ]
    pvcs = [
        PersistentVolumeClaim("a", storage_class="ssd", request=GiB,
                              volume_name="pv-z0z1"),
        PersistentVolumeClaim("b", storage_class="ssd", request=GiB,
                              volume_name="pv-z1z2"),
    ]
    pods = [
        MakePod("both").req({"cpu": "1"}).volume("a").volume("b").obj()
    ]
    got, _ = kernel_mask(nodes, pods, pvcs, pvs)
    # intersection: z1 only
    assert got[0, :6].tolist() == [False, False, True, True, False, False]
    assert_differential(nodes, pods, pvcs, pvs)


def test_volume_free_cluster_pays_nothing():
    nodes = make_zoned_nodes()
    pods = [MakePod("plain").req({"cpu": "1"}).obj()]
    got, snap = kernel_mask(nodes, pods)
    assert got is None  # capability flag off -> kernel never traced
    assert not snap.has_volumes


def test_end_to_end_cycle_respects_volume_zone():
    nodes = make_zoned_nodes()
    pvs = [
        PersistentVolume("pv-z2", capacity=10 * GiB, storage_class="ssd",
                         node_affinity=(zone_term("z2"),),
                         claim_ref="default/data"),
    ]
    pvcs = [
        PersistentVolumeClaim("data", storage_class="ssd", request=GiB,
                              volume_name="pv-z2"),
    ]
    pods = [MakePod("db").req({"cpu": "1"}).volume("data").obj()]
    snap = SnapshotEncoder().encode(nodes, pods, pvcs=pvcs, pvs=pvs)
    for mode in ("scan", "rounds"):
        out = build_cycle_fn(commit_mode=mode)(snap)
        a = int(np.asarray(out.assignment)[0])
        assert a in (4, 5), f"{mode}: pod landed outside z2 (node {a})"


# ---- multi-volume joint claim (PARITY #8 closure, VERDICT r3 item 9) ----


def _joint_fixture(n_pvs, sizes=(5, 5), pv_caps=None, provisioner=False):
    """One pod with len(sizes) PVCs of class 'local'; n_pvs PVs."""
    nodes = [MakeNode("n0").capacity({"cpu": "8"}).obj()]
    classes = [
        StorageClass("local", VOLUME_BINDING_WAIT, provisioner=provisioner)
    ]
    caps = pv_caps or [10] * n_pvs
    pvs = [
        PersistentVolume(f"pv-{v}", capacity=caps[v] * GiB,
                         storage_class="local")
        for v in range(n_pvs)
    ]
    pvcs = [
        PersistentVolumeClaim(f"c{j}", storage_class="local",
                              request=sizes[j] * GiB)
        for j in range(len(sizes))
    ]
    mk = MakePod("w").req({"cpu": "1"})
    for j in range(len(sizes)):
        mk = mk.volume(f"c{j}")
    return nodes, [mk.obj()], pvcs, pvs, classes


def test_two_pvcs_one_pv_is_infeasible():
    """A pod whose two PVCs are satisfiable only by the SAME single PV
    must be masked out (it used to be over-admitted and fail at bind)."""
    nodes, pods, pvcs, pvs, classes = _joint_fixture(n_pvs=1)
    assert_differential(nodes, pods, pvcs, pvs, classes)
    got, _ = kernel_mask(nodes, pods, pvcs, pvs, classes)
    assert not got[0, 0]


def test_two_pvcs_two_pvs_is_feasible_and_claims_both():
    nodes, pods, pvcs, pvs, classes = _joint_fixture(n_pvs=2)
    assert_differential(nodes, pods, pvcs, pvs, classes)
    from k8s_scheduler_tpu.core import build_cycle_fn

    enc = SnapshotEncoder(pad_pods=8, pad_nodes=4)
    snap = enc.encode(nodes, pods, pvcs=pvcs, pvs=pvs,
                      storage_classes=classes)
    out = build_cycle_fn(commit_mode="scan")(snap)
    assert np.asarray(out.assignment)[0] == 0
    assert np.asarray(out.pv_claimed).sum() == 2  # distinct PVs claimed


def test_two_pvcs_one_pv_plus_provisioner_is_feasible():
    """A dynamic-capable class means one slot can ride provisioning, so
    a single static PV suffices for the other slot."""
    nodes, pods, pvcs, pvs, classes = _joint_fixture(
        n_pvs=1, provisioner=True
    )
    assert_differential(nodes, pods, pvcs, pvs, classes)
    got, _ = kernel_mask(nodes, pods, pvcs, pvs, classes)
    assert got[0, 0]


@pytest.mark.parametrize("mode", ["scan", "rounds"])
def test_constrained_slot_claims_first_no_deadend(mode):
    """Greedy dead-end case: slot c0 (1 GiB) fits pv-0 (10 GiB) and
    pv-1 (2 GiB); slot c1 (8 GiB) fits ONLY pv-0. Naive lowest-index
    claiming in slot order would give c0 pv-0 and strand c1 — the
    SDR-safe choice (chosen_pv_sdr: each slot takes the lowest PV whose
    removal keeps Hall's condition over the remaining needy slots) must
    steer c0 to pv-1 so c1 gets pv-0."""
    nodes, pods, pvcs, pvs, classes = _joint_fixture(
        n_pvs=2, sizes=(1, 8), pv_caps=[10, 2]
    )
    assert_differential(nodes, pods, pvcs, pvs, classes)
    from k8s_scheduler_tpu.core import build_cycle_fn

    enc = SnapshotEncoder(pad_pods=8, pad_nodes=4)
    snap = enc.encode(nodes, pods, pvcs=pvcs, pvs=pvs,
                      storage_classes=classes)
    out = build_cycle_fn(commit_mode=mode)(snap)
    assert np.asarray(out.assignment)[0] == 0
    assert np.asarray(out.pv_claimed).sum() == 2

    # oracle agrees and assigns distinct PVs
    state = oracle.OracleState.build(nodes, (), pvcs, pvs, classes)
    assert oracle.filter_volume_binding(pods[0], state, 0)
    state.add(0, pods[0])
    assert state.claimed_static == {"pv-0", "pv-1"}


def test_two_pods_two_pvcs_each_contending():
    """Differential under contention: two 2-PVC pods over 3 PVs — only
    one pod can satisfy both claims; the loser must not place."""
    nodes = [MakeNode("n0").capacity({"cpu": "8"}).obj()]
    classes = [
        StorageClass("local", VOLUME_BINDING_WAIT, provisioner=False)
    ]
    pvs = [
        PersistentVolume(f"pv-{v}", capacity=10 * GiB,
                         storage_class="local")
        for v in range(3)
    ]
    pvcs = [
        PersistentVolumeClaim(f"c{j}", storage_class="local",
                              request=5 * GiB)
        for j in range(4)
    ]
    pods = [
        MakePod("a").req({"cpu": "1"}).volume("c0").volume("c1")
        .created(0.0).obj(),
        MakePod("b").req({"cpu": "1"}).volume("c2").volume("c3")
        .created(1.0).obj(),
    ]
    from k8s_scheduler_tpu.core import build_cycle_fn

    enc = SnapshotEncoder(pad_pods=8, pad_nodes=4)
    snap = enc.encode(nodes, pods, pvcs=pvcs, pvs=pvs,
                      storage_classes=classes)
    for mode in ("scan", "rounds"):
        out = build_cycle_fn(commit_mode=mode)(snap)
        a = np.asarray(out.assignment)[:2]
        assert a[0] == 0 and a[1] < 0, (mode, a)

    # scan == oracle end to end
    want = [
        d.node_index
        for d in oracle.schedule(nodes, pods, pvcs=pvcs, pvs=pvs,
                                 storage_classes=classes)
    ]
    out = build_cycle_fn(commit_mode="scan")(snap)
    assert list(np.asarray(out.assignment)[:2]) == want


# ---- SDR-safe claim choice (VERDICT r4 missing #3 closure) ----


def test_three_slot_nested_chain_places_and_claims_distinct():
    """3-slot nested chain: c0 (1 GiB) fits all three PVs, c1 (5 GiB)
    fits pv-0/pv-1, c2 (8 GiB) fits only pv-0. Lowest-index greedy in
    slot order would strand c2; the SDR-safe choice (claim the lowest
    PV whose removal keeps Hall's condition for the remaining needy
    slots) must assign c0=pv-2, c1=pv-1, c2=pv-0 in both engines and
    the oracle."""
    nodes, pods, pvcs, pvs, classes = _joint_fixture(
        n_pvs=3, sizes=(1, 5, 8), pv_caps=[10, 6, 2]
    )
    assert_differential(nodes, pods, pvcs, pvs, classes)
    enc = SnapshotEncoder(pad_pods=8, pad_nodes=4)
    snap = enc.encode(nodes, pods, pvcs=pvcs, pvs=pvs,
                      storage_classes=classes)
    for mode in ("scan", "rounds"):
        out = build_cycle_fn(commit_mode=mode)(snap)
        assert np.asarray(out.assignment)[0] == 0, mode
        assert np.asarray(out.pv_claimed).sum() == 3, mode

    state = oracle.OracleState.build(nodes, (), pvcs, pvs, classes)
    assert oracle.filter_volume_binding(pods[0], state, 0)
    state.add(0, pods[0])
    assert state.claimed_static == {"pv-0", "pv-1", "pv-2"}


def test_sdr_safe_choice_crossing_sets():
    """Unit test of the SDR chooser on CROSSING candidate sets — not
    producible through the encoder today (per-slot sets are capacity-
    nested within a class at one node, where the old constrained-
    count-first ordering happened to be exact); this guards the
    mechanism for richer future candidate semantics (PVC selectors,
    access modes), where count ordering is NOT enough. Sets A{0,3},
    B{0,1}, C{0,1}: every slot has 2 candidates, so count ordering
    degenerates to slot order, greedy gives A=pv0 and strands one of
    B/C; SDR must start A at pv3."""
    import jax.numpy as jnp

    from k8s_scheduler_tpu.ops.volumes import _sdr_safe_choice

    V = 4

    def row(*idx):
        r = np.zeros((1, V), bool)
        r[0, list(idx)] = True
        return jnp.asarray(r)

    cands = [row(0, 3), row(0, 1), row(0, 1)]
    needy = jnp.ones((1, 3), bool)
    no_dyn = jnp.zeros((1,), bool)
    assert int(_sdr_safe_choice(cands[0], cands, needy, no_dyn, 3, 0)[0]) == 3

    # dyn-capable slot with no safe candidate rides dynamic (-1)...
    cands2 = [row(0), row(0)]
    needy2 = jnp.asarray([[False, True]])
    assert int(
        _sdr_safe_choice(cands2[0], cands2, needy2, jnp.ones((1,), bool),
                         2, 0)[0]
    ) == -1
    # ...while a needy slot with no safe candidate falls back to the
    # lowest candidate (the pod is beyond Hall's guarantee)
    assert int(
        _sdr_safe_choice(cands2[0], cands2, needy2, no_dyn, 2, 0)[0]
    ) == 0


def test_chosen_pv_slots_intra_pod_distinct():
    """The rounds-engine guard's contention-free simulation must claim
    DISTINCT PVs across one pod's slots (per-pod `mine` bitmap), so its
    _RB_PV keys predict fold_pv_claims's first pass."""
    import jax.numpy as jnp

    from k8s_scheduler_tpu.framework.interfaces import CycleContext
    from k8s_scheduler_tpu.ops import volumes as volumes_ops

    nodes, pods, pvcs, pvs, classes = _joint_fixture(
        n_pvs=3, sizes=(1, 5, 8), pv_caps=[10, 6, 2]
    )
    enc = SnapshotEncoder(pad_pods=8, pad_nodes=4)
    snap = enc.encode(nodes, pods, pvcs=pvcs, pvs=pvs,
                      storage_classes=classes)
    ctx = CycleContext(snap)
    P = snap.P
    node_of = jnp.zeros((P,), jnp.int32)
    active = jnp.zeros((P,), bool).at[0].set(True)
    claimed0 = jnp.zeros((snap.pv_avail.shape[0],), bool)
    ch = np.asarray(
        volumes_ops.chosen_pv_slots(
            snap, ctx.expr_node_mask, claimed0, node_of, active
        )
    )[0]
    got = [c for c in ch if c >= 0]
    assert sorted(got) == [0, 1, 2], ch
    assert len(set(got)) == 3


def test_eight_slot_admission_mid_size_tight_subset_rejected():
    """8 slots forces the capped subset enumeration (MVol > 6); the
    Hall-tight subset here is a TRIPLE (three size-8 slots over two
    big PVs) that neither pairs nor the full set catch — the per-pod
    dominance groups must reject it, and the oracle (full enumeration)
    agrees."""
    nodes, pods, pvcs, pvs, classes = _joint_fixture(
        n_pvs=8, sizes=(8, 8, 8, 1, 1, 1, 1, 1),
        pv_caps=[10, 10, 2, 2, 2, 2, 2, 2],
    )
    assert_differential(nodes, pods, pvcs, pvs, classes)
    got, _ = kernel_mask(nodes, pods, pvcs, pvs, classes)
    assert not got[0, 0]


def test_eight_slot_claims_via_dominance_groups():
    """8 feasible slots (capped enumeration): the small slot s0 must
    NOT claim one of the three big PVs its three size-8 siblings need
    (a triple the singles/pairs/full-set margins all miss) — the
    dominance-group margin steers s0 to a small PV and all 8 slots
    claim distinct PVs."""
    nodes, pods, pvcs, pvs, classes = _joint_fixture(
        n_pvs=8, sizes=(1, 8, 8, 8, 1, 1, 1, 1),
        pv_caps=[10, 10, 10, 2, 2, 2, 2, 2],
    )
    assert_differential(nodes, pods, pvcs, pvs, classes)
    enc = SnapshotEncoder(pad_pods=8, pad_nodes=4)
    snap = enc.encode(nodes, pods, pvcs=pvcs, pvs=pvs,
                      storage_classes=classes)
    for mode in ("scan", "rounds"):
        out = build_cycle_fn(commit_mode=mode)(snap)
        assert np.asarray(out.assignment)[0] == 0, mode
        assert np.asarray(out.pv_claimed).sum() == 8, mode

    state = oracle.OracleState.build(nodes, (), pvcs, pvs, classes)
    assert oracle.filter_volume_binding(pods[0], state, 0)
    state.add(0, pods[0])
    assert state.claimed_static == {f"pv-{v}" for v in range(8)}
