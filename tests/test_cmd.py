"""Process layer (SURVEY.md §2 C1, §5.3/§5.5): flags, health/metrics
endpoints, and file-lease leader election."""

import json
import multiprocessing
import os
import time
import urllib.request

from k8s_scheduler_tpu.cmd import new_scheduler_command
from k8s_scheduler_tpu.cmd.httpserver import start_http_server
from k8s_scheduler_tpu.cmd.leaderelection import FileLease
from k8s_scheduler_tpu.metrics import SchedulerMetrics


def test_flag_surface_matches_upstream_names():
    ap = new_scheduler_command()
    args = ap.parse_args(
        ["--config", "x.yaml", "--leader-elect", "--http-port", "0"]
    )
    assert args.config == "x.yaml"
    assert args.leader_elect
    assert args.http_port == 0


def test_http_endpoints_serve_health_and_metrics():
    m = SchedulerMetrics()
    m.decisions.inc(42)
    server = start_http_server(m, port=0, healthz=lambda: (True, {"x": 1}))
    port = server.server_address[1]
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz") as r:
            body = json.loads(r.read())
            assert body["ok"] and body["x"] == 1
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
            text = r.read().decode()
            assert "scheduler_pod_node_decisions_total 42.0" in text
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope")
            assert False, "404 expected"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.shutdown()


def _hold_lease(path, hold_seconds, acquired):
    lease = FileLease(path, identity="other")
    assert lease.try_acquire()
    acquired.set()
    time.sleep(hold_seconds)
    lease.release()


def test_file_lease_single_holder(tmp_path):
    path = str(tmp_path / "lease")
    acquired = multiprocessing.Event()
    proc = multiprocessing.Process(
        target=_hold_lease, args=(path, 1.5, acquired)
    )
    proc.start()
    try:
        assert acquired.wait(10)
        mine = FileLease(path, identity="me")
        # flock is held by the other PROCESS: try_acquire must fail
        assert not mine.try_acquire()
        holder = mine.holder()
        assert holder and holder["holderIdentity"] == "other"
        # blocks until the holder releases, then wins
        assert mine.acquire(timeout=10)
        assert mine.is_leader()
        mine.release()
        assert not mine.is_leader()
    finally:
        proc.join(timeout=10)


def test_lease_intra_process_exclusion_and_holder_keeps_lock(tmp_path):
    # POSIX record locks never conflict within a process and are dropped
    # when ANY fd for the file closes — the FileLease registry must paper
    # over both (a leader reading its own heartbeat must not lose the lease)
    path = str(tmp_path / "lease")
    leader = FileLease(path, identity="leader")
    standby = FileLease(path, identity="standby")
    assert leader.try_acquire()
    try:
        assert not standby.try_acquire()  # same-process exclusion
        # holder() reads must not release the kernel lock
        assert leader.holder()["holderIdentity"] == "leader"
        assert standby.holder()["holderIdentity"] == "leader"
        assert not standby.try_acquire()
        assert leader.is_leader()
    finally:
        leader.release()
    assert standby.try_acquire()
    standby.release()


def test_lease_heartbeat_renews(tmp_path):
    path = str(tmp_path / "lease")
    lease = FileLease(path, identity="hb", renew_seconds=0.05)
    assert lease.try_acquire()
    try:
        first = lease.holder()["renewTime"]
        lease.start_renewing()
        time.sleep(0.3)
        assert lease.holder()["renewTime"] > first
    finally:
        lease.release()
