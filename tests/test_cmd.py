"""Process layer (SURVEY.md §2 C1, §5.3/§5.5): flags, health/metrics
endpoints, and file-lease leader election."""

import json
import multiprocessing
import os
import time
import urllib.request

import pytest

from k8s_scheduler_tpu.cmd import new_scheduler_command
from k8s_scheduler_tpu.cmd.httpserver import start_http_server
from k8s_scheduler_tpu.cmd.leaderelection import FileLease
from k8s_scheduler_tpu.metrics import SchedulerMetrics


def test_flag_surface_matches_upstream_names():
    ap = new_scheduler_command()
    args = ap.parse_args(
        ["--config", "x.yaml", "--leader-elect", "--http-port", "0"]
    )
    assert args.config == "x.yaml"
    assert args.leader_elect
    assert args.http_port == 0


def test_http_endpoints_serve_health_and_metrics():
    m = SchedulerMetrics()
    m.decisions.inc(42)
    server = start_http_server(m, port=0, healthz=lambda: (True, {"x": 1}))
    port = server.server_address[1]
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz") as r:
            body = json.loads(r.read())
            assert body["ok"] and body["x"] == 1
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
            text = r.read().decode()
            assert "scheduler_pod_node_decisions_total 42.0" in text
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope")
            assert False, "404 expected"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.shutdown()


def _get(url):
    with urllib.request.urlopen(url) as r:
        return r.status, dict(r.headers), r.read()


def _request(url, method):
    req = urllib.request.Request(url, method=method)
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_http_head_answered_and_mutations_405():
    """Probes commonly use HEAD (the stdlib handler would 501); any
    mutating verb on the read-only surface gets 405 + Allow."""
    m = SchedulerMetrics()
    server = start_http_server(m, port=0, healthz=lambda: (True, {}))
    port = server.server_address[1]
    base = f"http://127.0.0.1:{port}"
    try:
        for path in ("/healthz", "/metrics"):
            gs, gh, gbody = _request(f"{base}{path}", "GET")
            hs, hh, hbody = _request(f"{base}{path}", "HEAD")
            assert (gs, hs) == (200, 200)
            assert hbody == b""  # HEAD: headers only
            # HEAD advertises the same payload size GET serves
            assert hh["Content-Length"] == str(len(gbody))
        hs, _, _ = _request(f"{base}/nope", "HEAD")
        assert hs == 404
        for method in ("POST", "PUT", "DELETE", "PATCH"):
            st, headers, _ = _request(f"{base}/metrics", method)
            assert st == 405, method
            assert headers["Allow"] == "GET, HEAD"
    finally:
        server.shutdown()


def test_debug_endpoints_serve_flightrecorder_trace_and_pods():
    from k8s_scheduler_tpu.core.flight_recorder import FlightRecorder

    fr = FlightRecorder(capacity=16)
    for i in range(4):
        rec = fr.start()
        rec.mark("dispatch_start", rec.t_start + 0.001)
        rec.mark("decision_end", rec.t_start + 0.004)
        rec.phases["encode_ms"] = 1.0
        rec.counts["pods"] = 3 + i
        fr.commit(rec)
    fr.pod_event("uid-1", "pod-1", "Queued")
    fr.pod_event("uid-1", "pod-1", "Bound", cycle=3, node="n1")
    timelines = {
        "uid-1": {"uid": "uid-1", "name": "pod-1", "state": "Bound"}
    }
    server = start_http_server(
        SchedulerMetrics(), port=0, recorder=fr,
        pod_timeline=timelines.get,
    )
    port = server.server_address[1]
    base = f"http://127.0.0.1:{port}"
    try:
        st, _, body = _get(f"{base}/debug/flightrecorder?last=2")
        payload = json.loads(body)
        assert st == 200
        assert [c["seq"] for c in payload["cycles"]] == [2, 3]
        assert payload["derived"]["cycles"] == 4.0
        st, headers, body = _get(f"{base}/debug/trace?last=4")
        assert st == 200
        assert "attachment" in headers["Content-Disposition"]
        trace = json.loads(body)
        assert any(
            e["ph"] == "X" and e["name"].startswith("device cycle")
            for e in trace["traceEvents"]
        )
        st, _, body = _get(f"{base}/debug/pods/uid-1")
        assert st == 200 and json.loads(body)["state"] == "Bound"
        st, _, _ = _request(f"{base}/debug/pods/ghost", "GET")
        assert st == 404
        # malformed ?last falls back instead of erroring
        st, _, _ = _get(f"{base}/debug/flightrecorder?last=banana")
        assert st == 200
    finally:
        server.shutdown()


def test_healthz_staleness_503_when_cycles_stop():
    from k8s_scheduler_tpu.cmd.httpserver import staleness_healthz
    from k8s_scheduler_tpu.core.flight_recorder import FlightRecorder

    t = {"now": 0.0}
    fr = FlightRecorder(capacity=4, now=lambda: t["now"])
    health = staleness_healthz(lambda: {"bootId": "b"}, fr, 5.0)
    server = start_http_server(SchedulerMetrics(), port=0, healthz=health)
    port = server.server_address[1]
    url = f"http://127.0.0.1:{port}/healthz"
    try:
        # no cycle ever completed: fresh process is healthy...
        t["now"] = 1.0
        st, _, body = _request(url, "GET")
        assert st == 200 and json.loads(body)["last_cycle_age_s"] == 1.0
        # ...but ages into 503 if the first cycle never lands (wedged)
        t["now"] = 6.0
        st, _, body = _request(url, "GET")
        assert st == 503
        assert "no cycle completed" in json.loads(body)["reason"]
        # a completed cycle resets the age
        rec = fr.start()
        rec.t_end = t["now"]
        fr.commit(rec)
        st, _, body = _request(url, "GET")
        assert st == 200 and json.loads(body)["cycles"] == 1
        # and stopping again goes stale again
        t["now"] = 20.0
        st, _, _ = _request(url, "GET")
        assert st == 503
        # deadline 0 = never stale (the config default)
        never = staleness_healthz(None, fr, 0.0)
        ok, detail = never()
        assert ok and detail["last_cycle_age_s"] == 14.0
    finally:
        server.shutdown()


def _hold_lease(path, hold_seconds, acquired):
    lease = FileLease(path, identity="other")
    assert lease.try_acquire()
    acquired.set()
    time.sleep(hold_seconds)
    lease.release()


def test_file_lease_single_holder(tmp_path):
    path = str(tmp_path / "lease")
    acquired = multiprocessing.Event()
    proc = multiprocessing.Process(
        target=_hold_lease, args=(path, 1.5, acquired)
    )
    proc.start()
    try:
        assert acquired.wait(10)
        mine = FileLease(path, identity="me")
        # flock is held by the other PROCESS: try_acquire must fail
        assert not mine.try_acquire()
        holder = mine.holder()
        assert holder and holder["holderIdentity"] == "other"
        # blocks until the holder releases, then wins
        assert mine.acquire(timeout=10)
        assert mine.is_leader()
        mine.release()
        assert not mine.is_leader()
    finally:
        proc.join(timeout=10)


def test_lease_intra_process_exclusion_and_holder_keeps_lock(tmp_path):
    # POSIX record locks never conflict within a process and are dropped
    # when ANY fd for the file closes — the FileLease registry must paper
    # over both (a leader reading its own heartbeat must not lose the lease)
    path = str(tmp_path / "lease")
    leader = FileLease(path, identity="leader")
    standby = FileLease(path, identity="standby")
    assert leader.try_acquire()
    try:
        assert not standby.try_acquire()  # same-process exclusion
        # holder() reads must not release the kernel lock
        assert leader.holder()["holderIdentity"] == "leader"
        assert standby.holder()["holderIdentity"] == "leader"
        assert not standby.try_acquire()
        assert leader.is_leader()
    finally:
        leader.release()
    assert standby.try_acquire()
    standby.release()


def test_lease_heartbeat_renews(tmp_path):
    path = str(tmp_path / "lease")
    lease = FileLease(path, identity="hb", renew_seconds=0.05)
    assert lease.try_acquire()
    try:
        first = lease.holder()["renewTime"]
        lease.start_renewing()
        time.sleep(0.3)
        assert lease.holder()["renewTime"] > first
    finally:
        lease.release()


def test_lease_describe_and_leader_gauges(tmp_path):
    """scheduler_leader_state / scheduler_leader_lease_age_seconds are
    scrape-time views of the FileLease, and /healthz-style describe()
    surfaces identity + heartbeat age — not just a boolean."""
    path = str(tmp_path / "lease")
    leader = FileLease(path, identity="the-leader")
    standby = FileLease(path, identity="the-standby")
    assert leader.try_acquire()
    try:
        d = leader.describe()
        assert d["leader"] and d["holder"] == "the-leader"
        assert d["age_s"] >= 0.0 and d["path"] == path
        ds = standby.describe()
        assert not ds["leader"] and ds["holder"] == "the-leader"
        # the gauges evaluate the SAME lease at scrape time
        m = SchedulerMetrics()
        m.leader_state.set_function(
            lambda: 1.0 if leader.is_leader() else 0.0
        )
        m.leader_lease_age.set_function(leader.lease_age_seconds)
        text = m.expose().decode()
        assert "scheduler_leader_state 1.0" in text
        assert "scheduler_leader_lease_age_seconds" in text
        ms = SchedulerMetrics()
        ms.leader_state.set_function(
            lambda: 1.0 if standby.is_leader() else 0.0
        )
        assert "scheduler_leader_state 0.0" in ms.expose().decode()
    finally:
        leader.release()
    # no lease file content at all: age reads 0, no crash
    ghost = FileLease(str(tmp_path / "nope"))
    assert ghost.lease_age_seconds() == 0.0


def test_debug_state_endpoint(tmp_path):
    """/debug/state serves the DurableState status payload (journal
    lag/segments, snapshot + restore stats); absent without state."""
    from k8s_scheduler_tpu.internal.cache import SchedulerCache
    from k8s_scheduler_tpu.internal.queue import SchedulingQueue
    from k8s_scheduler_tpu.models import MakePod
    from k8s_scheduler_tpu.state import DurableState

    st = DurableState(str(tmp_path), snapshot_interval_seconds=0)
    q, c = SchedulingQueue(), SchedulerCache()
    st.attach(q, c)
    q.add(MakePod("p").obj())
    st.journal.flush()
    server = start_http_server(SchedulerMetrics(), port=0, state=st)
    port = server.server_address[1]
    try:
        st_, _, body = _get(f"http://127.0.0.1:{port}/debug/state")
        payload = json.loads(body)
        assert st_ == 200
        assert payload["journal"]["appended"] == 1
        assert payload["journal"]["fsync"] is True
        assert payload["last_restore"]["records_replayed"] == 0
    finally:
        server.shutdown()
    # without durable state the route 404s like other absent debug routes
    bare = start_http_server(SchedulerMetrics(), port=0)
    bport = bare.server_address[1]
    try:
        code, _, _ = _request(
            f"http://127.0.0.1:{bport}/debug/state", "GET"
        )
        assert code == 404
    finally:
        bare.shutdown()
    st.journal.close()


def test_pad_presizing_flows_from_yaml_to_encoder():
    """padExisting / padPodsPerNode (PERF.md 'fold-mode rig wedge'
    avoidance) must reach the per-profile encoders, and the encoded
    regime must honor them (E folded into the pow2 bucket, MPN into
    the bucket-of-8)."""
    from k8s_scheduler_tpu.config.types import load_config
    from k8s_scheduler_tpu.core.scheduler import Scheduler
    from k8s_scheduler_tpu.models import MakeNode, MakePod

    cfg = load_config(
        "padExisting: 300\npadPodsPerNode: 25\n"
    )
    assert cfg.pad_existing == 300 and cfg.pad_pods_per_node == 25
    sched = Scheduler(config=cfg)
    enc = sched._encoder
    assert enc.pad_existing == 300 and enc.pad_pods_per_node == 25
    nodes = [MakeNode("a").capacity({"cpu": "8"}).obj()]
    pods = [MakePod("p").req({"cpu": "1"}).obj()]
    ex = [(MakePod("e").req({"cpu": "1"}).obj(), "a")]
    snap = enc.encode(nodes, pods, existing=ex)
    assert snap.exist_valid.shape[0] == 512  # pow2 bucket of 300
    assert snap.node_pods.shape[1] == 32  # bucket-of-8 ABOVE the pad: a
    # depth within the operator's sizing must never outgrow the regime


# ---- thread-lifecycle regressions (schedlint TR003, ISSUE 12) -----------


def test_stop_http_server_joins_the_serve_thread():
    """The HTTP serve thread must have a shutdown JOIN story, not just
    daemon=True: stop_http_server drains it, closes the socket, and is
    idempotent (the CompileWarmer-leak class, machine-checked by TR003)."""
    import urllib.error
    import urllib.request

    from k8s_scheduler_tpu.cmd.httpserver import stop_http_server
    from k8s_scheduler_tpu.metrics import SchedulerMetrics

    server = start_http_server(SchedulerMetrics(), port=0)
    thread = server._serve_thread
    assert thread is not None and thread.is_alive()
    port = server.server_address[1]
    assert stop_http_server(server) is True
    assert not thread.is_alive()
    assert server._serve_thread is None
    # the listening socket is really gone, not merely unaccepted
    with pytest.raises((urllib.error.URLError, OSError)):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=0.5
        )
    # idempotent: a second stop is a no-op, not a crash
    assert stop_http_server(server) is True


def test_lease_release_joins_the_renewer(tmp_path):
    """FileLease.release must drain the renewer thread (the shutdown
    join mirroring CompileWarmer's drain-exit), so a released lease
    leaves no heartbeat writer behind to resurrect the file."""
    path = str(tmp_path / "lease")
    lease = FileLease(path, identity="joiner", renew_seconds=0.05)
    assert lease.try_acquire()
    lease.start_renewing()
    renewer = lease._renewer
    assert renewer is not None and renewer.is_alive()
    lease.release()
    assert not renewer.is_alive()
    assert lease._renewer is None
    # no post-release heartbeat: the file stops changing once released
    import os
    import time as _t

    before = os.stat(path).st_mtime_ns
    _t.sleep(0.15)
    assert os.stat(path).st_mtime_ns == before
