"""Failover exactness: differential replay over randomized mutation
traces, kill -9 subprocess takeover, clean-shutdown seal, and the
assumed-pod TTL expiry observability satellite (state/ package +
scripts/soak_failover.py)."""

import importlib.util
import os
import pathlib
import random
import signal
import subprocess
import sys
import time

import pytest

from k8s_scheduler_tpu.internal.cache import SchedulerCache
from k8s_scheduler_tpu.internal.queue import SchedulingQueue
from k8s_scheduler_tpu.models import MakeNode, MakePod
from k8s_scheduler_tpu.state import DurableState, state_digest

_SOAK_PATH = (
    pathlib.Path(__file__).resolve().parent.parent
    / "scripts" / "soak_failover.py"
)


def _soak_module():
    spec = importlib.util.spec_from_file_location(
        "soak_failover", _SOAK_PATH
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> None:
        self.t += dt


def _fresh_pair(clock):
    q = SchedulingQueue(
        initial_backoff_seconds=0.5, max_backoff_seconds=4.0,
        unschedulable_timeout_seconds=30.0, now=clock,
    )
    c = SchedulerCache(assumed_pod_ttl_seconds=2.0, now=clock)
    return q, c


@pytest.mark.parametrize("seed", [0, 1, 7])
def test_differential_random_trace_restores_identical_digest(
    tmp_path, seed
):
    """The tentpole acceptance: a randomized mutation trace journaled
    live, then replayed into a FRESH queue/cache, produces a
    bit-identical state digest — attempt counts, backoff expiries,
    tier order, in-flight sets, assumed-pod deadlines and all."""
    soak = _soak_module()
    d = str(tmp_path / f"s{seed}")
    clock = FakeClock()
    q, c = _fresh_pair(clock)
    st = DurableState(d, snapshot_interval_seconds=0)
    st.attach(q, c)
    rng = random.Random(seed)

    class SkewClock:  # adapt FakeClock to the soak driver's interface
        def advance(self, dt):
            clock.tick(dt)

        def __call__(self):
            return clock()

    sk = SkewClock()
    for i in range(250):
        soak.apply_random_op(rng, sk, q, c, i)
        if i in (80, 160):
            # mid-trace snapshot compactions must not perturb replay
            st.snapshot()
    st.journal.flush()
    live = state_digest(q, c)

    q2, c2 = _fresh_pair(FakeClock())
    st2 = DurableState(d, snapshot_interval_seconds=0)
    stats = st2.restore_into(q2, c2)
    assert state_digest(q2, c2) == live
    assert stats["snapshot"] is True  # compaction was actually used
    # determinism: a second independent restore agrees
    q3, c3 = _fresh_pair(FakeClock())
    DurableState(d, snapshot_interval_seconds=0).restore_into(q3, c3)
    assert state_digest(q3, c3) == live


@pytest.mark.parametrize("seed", [0, 7])
def test_differential_random_trace_with_batches(tmp_path, seed):
    """The digest-equivalence differential with the vectorized fold's
    group-append in the loop: the SAME randomized mutation trace,
    journaled once as singles and once with every chunk under
    DurableState.batch(), restores to the identical live digest — and
    the batched journal really does contain batch records."""
    from k8s_scheduler_tpu.state.journal import BATCH_OP, replay_dir

    soak = _soak_module()

    def drive(d, batched):
        import contextlib

        clock = FakeClock()
        q, c = _fresh_pair(clock)
        st = DurableState(d, snapshot_interval_seconds=0)
        st.attach(q, c)
        rng = random.Random(seed)

        class SkewClock:
            def advance(self, dt):
                clock.tick(dt)

            def __call__(self):
                return clock()

        sk = SkewClock()
        i = 0
        for _chunk in range(50):
            scope = st.batch() if batched else contextlib.nullcontext()
            with scope:
                for _ in range(5):
                    soak.apply_random_op(rng, sk, q, c, i)
                    i += 1
        st.journal.flush()
        live = state_digest(q, c)
        st.journal.close()
        return live

    da, db = str(tmp_path / "singles"), str(tmp_path / "batched")
    live_a = drive(da, batched=False)
    live_b = drive(db, batched=True)
    assert live_a == live_b
    assert any(op == BATCH_OP for op, _t, _d in replay_dir(db))

    for d in (da, db):
        q2, c2 = _fresh_pair(FakeClock())
        DurableState(d, snapshot_interval_seconds=0).restore_into(q2, c2)
        assert state_digest(q2, c2) == live_a, d


def test_restore_preserves_backoff_and_attempts_exactly(tmp_path):
    """Focused version of the digest test: the concrete fields a
    takeover used to lose (SURVEY §5 'stateless standby')."""
    d = str(tmp_path)
    clock = FakeClock()
    q, c = _fresh_pair(clock)
    st = DurableState(d, snapshot_interval_seconds=0)
    st.attach(q, c)
    pod = MakePod("flappy").req({"cpu": "1"}).obj()
    q.add(pod)
    for _ in range(3):  # three failed attempts -> exponential backoff
        clock.tick(10.0)
        q.pop_ready()
        q.requeue_backoff(pod)
    c.add_node(MakeNode("n0").capacity({"cpu": "8"}).obj())
    ass = MakePod("assumed").req({"cpu": "1"}).obj()
    q.add(ass)
    q.pop_ready()
    c.assume(ass, "n0")
    c.finish_binding(ass.uid)
    st.journal.flush()

    q2, c2 = _fresh_pair(FakeClock())
    DurableState(d, snapshot_interval_seconds=0).restore_into(q2, c2)
    # attempts carried over: 3 pops happened (the 4th attempt is next)
    e_live = q._backoff[pod.uid]
    e_rest = q2._backoff[pod.uid]
    assert e_rest.attempts == e_live.attempts == 3
    assert e_rest.backoff_expiry == e_live.backoff_expiry
    # assumed pod still assumed, with the SAME TTL deadline
    assert c2.is_assumed(ass.uid)
    assert c2._assumed[ass.uid].deadline == c._assumed[ass.uid].deadline
    assert c2.counts() == c.counts()


def test_torn_tail_never_resurrects_into_state(tmp_path):
    """Truncate the live journal at every byte of its final record:
    restore must never raise, and the restored state must equal the
    state BEFORE the final op — the torn record is discarded whole."""
    from k8s_scheduler_tpu.state.journal import (
        segment_indices,
        segment_path,
    )

    d = str(tmp_path / "live")
    clock = FakeClock()
    q, c = _fresh_pair(clock)
    st = DurableState(d, snapshot_interval_seconds=0)
    st.attach(q, c)
    q.add(MakePod("a").req({"cpu": "1"}).obj())
    clock.tick(1)
    q.add(MakePod("b").req({"cpu": "1"}).obj())
    digest_before_final = state_digest(q, c)
    clock.tick(1)
    q.add(MakePod("final").req({"cpu": "1"}).obj())
    st.journal.flush()
    (idx,) = segment_indices(d)
    blob = open(segment_path(d, idx), "rb").read()
    # the final record's frame: find its start by replaying sizes
    from k8s_scheduler_tpu.state.codec import pod_to_state
    from k8s_scheduler_tpu.state.journal import encode_record

    final_rec = encode_record(
        "q.add", clock(), {"pod": pod_to_state(MakePod("final").req(
            {"cpu": "1"}).obj())}
    )
    start = len(blob) - len(final_rec)
    assert blob[start:] == final_rec  # framing sanity
    for cut in range(start, len(blob)):
        tdir = str(tmp_path / f"torn{cut}")
        os.makedirs(tdir)
        with open(segment_path(tdir, idx), "wb") as f:
            f.write(blob[:cut])
        q2, c2 = _fresh_pair(FakeClock())
        DurableState(tdir, snapshot_interval_seconds=0).restore_into(
            q2, c2
        )
        assert state_digest(q2, c2) == digest_before_final, (
            f"cut at byte {cut}"
        )


def test_seal_then_takeover_replays_nothing(tmp_path):
    d = str(tmp_path)
    clock = FakeClock()
    q, c = _fresh_pair(clock)
    st = DurableState(d, snapshot_interval_seconds=0)
    st.attach(q, c)
    for i in range(10):
        q.add(MakePod(f"p{i}").req({"cpu": "1"}).obj())
    st.seal()  # the SIGTERM path: clean-shutdown snapshot
    q2, c2 = _fresh_pair(FakeClock())
    stats = DurableState(d, snapshot_interval_seconds=0).restore_into(
        q2, c2
    )
    assert stats["clean_shutdown"] is True
    assert stats["records_replayed"] == 0
    assert state_digest(q2, c2) == state_digest(q, c)


def test_kill9_failover_digest_matches_pre_kill(tmp_path):
    """The ISSUE satellite: a subprocess active dies on SIGKILL after
    flushing; the standby restores and its queue/cache digest equals
    the active's last recorded digest — nothing lost, nothing
    duplicated."""
    soak = _soak_module()
    d = str(tmp_path / "state")
    os.makedirs(d)
    digest_log = os.path.join(d, "digests.txt")
    child = subprocess.Popen(
        [
            sys.executable, str(_SOAK_PATH), "--child",
            "--state-dir", d, "--seed", "3", "--ops", "120",
            "--digest-log", digest_log, "--hold",
        ],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        # wait for the child's "done" marker: ops applied + journal
        # flushed, now idling in --hold — the SIGKILL lands on a fully
        # durable boundary
        deadline = time.monotonic() + 120
        done = False
        while time.monotonic() < deadline:
            try:
                with open(digest_log) as fh:
                    done = any(
                        line.startswith("done ") for line in fh
                    )
            except FileNotFoundError:
                pass
            if done:
                break
            assert child.poll() is None, "soak child died early"
            time.sleep(0.05)
        assert done, "child never reached its final flush"
    finally:
        child.send_signal(signal.SIGKILL)
        child.wait()
    res = soak.restore_and_check(d, digest_log)
    digests, flushed = soak.read_digest_log(digest_log)
    # everything durable at the done marker survived the SIGKILL
    assert res["boundary"] == flushed
    assert res["digest"] == digests[flushed][:12]


def test_soak_failover_smoke(tmp_path):
    """Smoke-tier subset of scripts/soak_failover.py: random-point
    SIGKILLs, restore invariants checked each round (marked slow in
    conftest — subprocess jax imports dominate)."""
    soak = _soak_module()
    results = soak.soak(
        str(tmp_path), rounds=2, ops=250, seed=11, verbose=False
    )
    assert len(results) == 2
    for r in results:
        assert r["boundary"] >= r["flushed_watermark"]


def test_scheduler_ctor_attaches_and_standby_restores(tmp_path):
    """End-to-end wiring: a Scheduler built with state= journals its
    informer-driven mutations, and a second Scheduler (the standby that
    just won the lease) built against the same dir restores the exact
    state in its constructor — before any cycle could run."""
    from k8s_scheduler_tpu.core import Scheduler

    d = str(tmp_path)
    clock = FakeClock()
    active = Scheduler(
        now=clock, state=DurableState(d, snapshot_interval_seconds=0)
    )
    active.on_node_add(MakeNode("n0").capacity({"cpu": "8"}).obj())
    for i in range(5):
        clock.tick(0.5)
        active.on_pod_add(MakePod(f"p{i}").req({"cpu": "1"}).obj())
    active.on_pod_add(
        MakePod("bound").req({"cpu": "1"}).obj(), node_name="n0"
    )
    active.on_pod_delete("default/p3")
    active.state.journal.flush()
    live = state_digest(active.queue, active.cache)

    standby = Scheduler(
        now=FakeClock(), state=DurableState(
            d, snapshot_interval_seconds=0
        )
    )
    assert state_digest(standby.queue, standby.cache) == live
    assert standby.queue.pending_counts()["active"] == 4
    assert standby.cache.counts() == {"nodes": 1, "bound": 1, "assumed": 0}


def test_records_after_seal_survive_the_next_takeover(tmp_path):
    """Regression: after a seal prunes every wal segment, the next
    process's journal must number segments ABOVE the snapshot's
    journal_from — records written below it would sit outside the
    restore tail and be silently skipped by the takeover after next."""
    d = str(tmp_path)
    clock = FakeClock()
    q, c = _fresh_pair(clock)
    st = DurableState(d, snapshot_interval_seconds=0)
    st.attach(q, c)
    q.add(MakePod("a").req({"cpu": "1"}).obj())
    st.seal()  # process A: clean shutdown, only a snapshot remains

    q2, c2 = _fresh_pair(FakeClock(2000.0))
    st2 = DurableState(d, snapshot_interval_seconds=0)
    st2.attach(q2, c2)
    q2.add(MakePod("b").req({"cpu": "1"}).obj())
    st2.journal.flush()  # process B: 'b' acknowledged durable, then dies

    q3, c3 = _fresh_pair(FakeClock(3000.0))
    st3 = DurableState(d, snapshot_interval_seconds=0)
    stats = st3.restore_into(q3, c3)
    assert stats["records_replayed"] == 1
    assert q3.pending_counts()["active"] == 2  # both a AND b survive
    assert state_digest(q3, c3) == state_digest(q2, c2)


def test_in_flight_pods_recovered_on_takeover(tmp_path):
    """A pod popped for a cycle whose outcome never reached the journal
    (leader died mid-cycle) must be requeued by the standby — there is
    no informer to re-deliver it, so dropping it would lose it forever."""
    from k8s_scheduler_tpu.core import Scheduler

    d = str(tmp_path)
    clock = FakeClock()
    q, c = _fresh_pair(clock)
    st = DurableState(d, snapshot_interval_seconds=0)
    st.attach(q, c)
    q.add(MakePod("mid-cycle").req({"cpu": "1"}).obj())
    q.add(MakePod("gone").req({"cpu": "1"}).obj())
    popped = q.pop_ready()  # both in flight; outcomes never journaled
    assert len(popped) == 2
    q.delete("default/gone")  # informer delete raced the crash
    st.journal.flush()

    standby = Scheduler(
        now=FakeClock(), state=DurableState(
            d, snapshot_interval_seconds=0
        )
    )
    counts = standby.queue.pending_counts()
    assert counts["active"] == 1  # recovered, minus the deleted one
    entry = standby.queue._active["default/mid-cycle"]
    assert entry.attempts == 1  # the crashed attempt stays counted
    assert "default/gone" not in standby.queue._active
    # and the recovery itself was journaled: a second takeover agrees
    standby.state.journal.flush()
    third = Scheduler(
        now=FakeClock(), state=DurableState(
            d, snapshot_interval_seconds=0
        )
    )
    assert state_digest(third.queue, third.cache) == state_digest(
        standby.queue, standby.cache
    )


def test_config_state_dir_and_snapshot_interval_load():
    from k8s_scheduler_tpu.config.types import load_config

    cfg = load_config("stateDir: /var/lib/sched\nsnapshotInterval: 90s\n")
    assert cfg.state_dir == "/var/lib/sched"
    assert cfg.snapshot_interval_seconds == 90.0
    # defaults: durability off, 60s cadence once enabled
    dflt = load_config("{}")
    assert dflt.state_dir == ""
    assert dflt.snapshot_interval_seconds == 60.0


def test_assumed_ttl_expiry_leaves_a_trace(tmp_path):
    """ISSUE satellite: TTL expiry used to drop assumed pods silently —
    now it must leave an events-ring entry and an 'Expired' pod-timeline
    attempt so /debug/pods/<uid> explains the disappearance."""
    from k8s_scheduler_tpu.core import Scheduler

    clock = FakeClock()
    sched = Scheduler(now=clock)
    pod = MakePod("ghost").req({"cpu": "1"}).obj()
    sched.cache.assume(pod, "n3")
    sched.cache.finish_binding(pod.uid)
    clock.tick(60.0)  # past the assumed-pod TTL
    stats = sched.schedule_cycle()  # empty cycle still sweeps
    assert stats.attempted == 0
    # requeued with backoff, not silently dropped
    assert pod.uid in sched.queue._backoff
    # events ring explains it
    ring = sched.events.events_for(pod.uid)
    assert any(e.reason == "AssumeExpired" for e in ring)
    msg = [e for e in ring if e.reason == "AssumeExpired"][0].message
    assert "n3" in msg and "expired" in msg
    # pod timeline shows an Expired attempt with the node
    tl = sched.pod_timeline(pod.uid)
    assert tl is not None
    expired = [a for a in tl["attempts"] if a["result"] == "Expired"]
    assert expired and expired[0]["node"] == "n3"
