"""Pluggable QueueSort (SURVEY.md §2 C11, VERDICT r3 item 8): the
profile-selected ordering plugin owns the encoder's pod_order rank, and
a swapped ordering changes placement under contention in BOTH commit
engines."""

import numpy as np
import pytest

from k8s_scheduler_tpu.core import build_cycle_fn
from k8s_scheduler_tpu.core.scheduler import Scheduler
from k8s_scheduler_tpu.config import load_config
from k8s_scheduler_tpu.framework.queuesort import (
    CreationSort,
    PrioritySort,
    QueueSortPlugin,
    make_queue_sort,
    queue_sort_for_profile,
    register_queue_sort,
)
from k8s_scheduler_tpu.models import MakeNode, MakePod, SnapshotEncoder


def one_slot_fixture():
    """One node that fits exactly one pod; two equal-priority claimants
    where `old` was created first."""
    nodes = [MakeNode("n0").capacity({"cpu": "1"}).obj()]
    pods = [
        MakePod("old").req({"cpu": "1"}).created(0.0).obj(),
        MakePod("new").req({"cpu": "1"}).created(100.0).obj(),
    ]
    return nodes, pods


def place(nodes, pods, queue_sort=None, mode="scan"):
    enc = SnapshotEncoder(pad_pods=8, pad_nodes=4, queue_sort=queue_sort)
    snap = enc.encode(nodes, pods)
    out = build_cycle_fn(commit_mode=mode)(snap)
    return np.asarray(out.assignment)[: len(pods)]


def test_priority_sort_rank_orders_by_priority_then_creation():
    prio = np.array([0, 10, 0], np.int32)
    creation = np.array([5.0, 9.0, 1.0])
    r = PrioritySort().rank([None] * 3, prio, creation)
    # pod 1 (highest priority) first, then pod 2 (earlier), then pod 0
    assert list(r) == [2, 0, 1]


def test_creation_sort_ignores_priority():
    prio = np.array([0, 10, 0], np.int32)
    creation = np.array([5.0, 9.0, 1.0])
    r = CreationSort().rank([None] * 3, prio, creation)
    assert list(r) == [1, 2, 0]
    r2 = CreationSort({"newest_first": True}).rank([None] * 3, prio,
                                                   creation)
    assert list(r2) == [1, 0, 2]


@pytest.mark.parametrize("mode", ["scan", "rounds"])
def test_custom_queuesort_flips_contention_winner(mode):
    nodes, pods = one_slot_fixture()
    a_default = place(nodes, pods, mode=mode)
    assert a_default[0] >= 0 and a_default[1] < 0  # older pod wins

    lifo = make_queue_sort("CreationSort", {"newest_first": True})
    a_lifo = place(nodes, pods, queue_sort=lifo, mode=mode)
    assert a_lifo[1] >= 0 and a_lifo[0] < 0  # newest-first flips it


def test_profile_config_selects_queuesort():
    cfg = load_config(
        """
profiles:
- schedulerName: default-scheduler
  plugins:
    queueSort:
      enabled:
      - name: CreationSort
  pluginConfig:
  - name: CreationSort
    args:
      newest_first: true
- schedulerName: fifo-scheduler
"""
    )
    qs = queue_sort_for_profile(cfg.profile("default-scheduler"))
    assert qs.name == "CreationSort" and qs.args == {"newest_first": True}
    assert (
        queue_sort_for_profile(cfg.profile("fifo-scheduler")).name
        == "PrioritySort"
    )
    # the scheduler hands each profile's plugin to that profile's encoder
    sched = Scheduler(config=cfg)
    assert (
        sched._encoders["default-scheduler"].queue_sort.name
        == "CreationSort"
    )
    assert (
        sched._encoders["fifo-scheduler"].queue_sort.name == "PrioritySort"
    )


def test_register_custom_queuesort():
    @register_queue_sort
    class NameSort(QueueSortPlugin):
        name = "NameSort"

        def rank(self, pods, priorities, creation):
            order = np.argsort([p.name for p in pods], kind="stable")
            out = np.empty(len(pods), np.int32)
            out[order] = np.arange(len(pods), dtype=np.int32)
            return out

    nodes, pods = one_slot_fixture()
    # alphabetical: "new" < "old", so the newer pod wins the slot
    a = place(nodes, pods, queue_sort=make_queue_sort("NameSort"))
    assert a[1] >= 0 and a[0] < 0


def test_unknown_queuesort_fails_loudly():
    with pytest.raises(KeyError, match="unknown queueSort"):
        make_queue_sort("TypoSort")
