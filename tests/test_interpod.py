"""Differential tests for InterPodAffinity + PodTopologySpread (benchmark
config #3 territory: the quadratic hot path)."""

import numpy as np
import pytest

from k8s_scheduler_tpu import oracle
from k8s_scheduler_tpu.core import build_cycle_fn
from k8s_scheduler_tpu.models import MakeNode, MakePod, SnapshotEncoder, api


def run_both(nodes, pods, existing=()):
    snap = SnapshotEncoder().encode(nodes, pods, existing)
    result = build_cycle_fn()(snap)
    got = np.asarray(result.assignment)[: len(pods)].tolist()
    want = [d.node_index for d in oracle.schedule(nodes, pods, existing)]
    return got, want


def zone_nodes(per_zone=2, zones=("za", "zb"), cpu="8"):
    nodes = []
    for z in zones:
        for i in range(per_zone):
            nodes.append(
                MakeNode(f"{z}-n{i}").capacity({"cpu": cpu, "memory": "16Gi"})
                .labels({"zone": z}).obj()
            )
    return nodes


def test_required_affinity_follows_existing():
    nodes = zone_nodes()
    existing = [
        (MakePod("db").labels({"app": "db"}).req({"cpu": "1"}).obj(), "zb-n0")
    ]
    pods = [
        MakePod("web").req({"cpu": "1"})
        .pod_affinity("zone", {"app": "db"}).obj()
    ]
    got, want = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] in (2, 3)  # zb zone


def test_required_affinity_no_match_infeasible():
    nodes = zone_nodes()
    pods = [
        MakePod("web").req({"cpu": "1"})
        .pod_affinity("zone", {"app": "db"}).obj()
    ]
    got, want = run_both(nodes, pods)
    assert got == want == [-1]


def test_affinity_bootstrap_first_pod_of_group():
    # pod matches its OWN selector and nothing else matches: allowed anywhere
    nodes = zone_nodes()
    pods = [
        MakePod("web").labels({"app": "web"}).req({"cpu": "1"})
        .pod_affinity("zone", {"app": "web"}).obj()
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    assert got[0] >= 0


def test_intra_batch_affinity_chain():
    # second pod's required affinity satisfied by the FIRST pod committed in
    # the same cycle (running domain counts inside the scan)
    nodes = zone_nodes()
    pods = [
        MakePod("leader").labels({"app": "grp"}).req({"cpu": "1"})
        .priority(10).created(0).obj(),
        MakePod("follower").req({"cpu": "1"}).created(1)
        .pod_affinity("zone", {"app": "grp"}).obj(),
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    lead_zone = got[0] // 2
    assert got[1] // 2 == lead_zone


def test_anti_affinity_spreads_by_hostname():
    nodes = zone_nodes(per_zone=2)
    pods = [
        MakePod(f"r{i}").labels({"app": "api"}).req({"cpu": "1"}).created(i)
        .pod_affinity("kubernetes.io/hostname", {"app": "api"}, anti=True)
        .obj()
        for i in range(5)
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    placed = [g for g in got if g >= 0]
    assert len(placed) == 4 and len(set(placed)) == 4  # one per node
    assert got.count(-1) == 1


def test_symmetric_anti_affinity_of_existing_pod():
    # existing pod has anti-affinity against app=web: incoming web pod must
    # avoid that pod's domain even though the INCOMING pod has no affinity
    nodes = zone_nodes()
    existing = [
        (
            MakePod("loner").labels({"app": "loner"}).req({"cpu": "1"})
            .pod_affinity("zone", {"app": "web"}, anti=True).obj(),
            "za-n0",
        )
    ]
    pods = [MakePod("web").labels({"app": "web"}).req({"cpu": "1"}).obj()]
    got, want = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] in (2, 3)  # pushed out of za


def test_symmetric_anti_affinity_intra_batch():
    # the anti-affine pod is committed FIRST (higher priority) in the same
    # cycle; the later pod must respect it
    nodes = zone_nodes()
    pods = [
        MakePod("loner").labels({"app": "loner"}).req({"cpu": "1"})
        .priority(10)
        .pod_affinity("zone", {"app": "web"}, anti=True).obj(),
        MakePod("web").labels({"app": "web"}).req({"cpu": "1"}).obj(),
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    assert got[0] >= 0 and got[1] >= 0
    assert got[1] // 2 != got[0] // 2  # different zones


def test_preferred_affinity_steers_together():
    nodes = zone_nodes()
    existing = [
        (MakePod("cache").labels({"app": "cache"}).req({"cpu": "1"}).obj(), "zb-n1")
    ]
    pods = [
        MakePod("web").req({"cpu": "1"})
        .pod_affinity("zone", {"app": "cache"}, weight=80).obj()
    ]
    got, want = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] in (2, 3)


def test_preferred_anti_affinity_steers_apart():
    nodes = zone_nodes()
    existing = [
        (MakePod("noisy").labels({"app": "noisy"}).req({"cpu": "1"}).obj(), "za-n0")
    ]
    pods = [
        MakePod("quiet").req({"cpu": "1"})
        .pod_affinity("zone", {"app": "noisy"}, anti=True, weight=80).obj()
    ]
    got, want = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] in (2, 3)


def test_topology_spread_do_not_schedule():
    nodes = zone_nodes()
    pods = [
        MakePod(f"w{i}").labels({"app": "spread"}).req({"cpu": "1"}).created(i)
        .spread(1, "zone", {"app": "spread"})
        .obj()
        for i in range(4)
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    zones = [g // 2 for g in got if g >= 0]
    assert abs(zones.count(0) - zones.count(1)) <= 1


def test_topology_spread_schedule_anyway_scores():
    nodes = zone_nodes()
    existing = [
        (MakePod(f"e{i}").labels({"app": "s"}).req({"cpu": "1"}).obj(), "za-n0")
        for i in range(3)
    ]
    pods = [
        MakePod("w").labels({"app": "s"}).req({"cpu": "1"})
        .spread(1, "zone", {"app": "s"}, when_unsatisfiable=api.SCHEDULE_ANYWAY)
        .obj()
    ]
    got, want = run_both(nodes, pods, existing)
    assert got == want
    assert got[0] in (2, 3)  # steered to the empty zone, not blocked


def test_namespace_scoping_of_selectors():
    nodes = zone_nodes()
    existing = [
        (
            MakePod("db-other", namespace="other").labels({"app": "db"})
            .req({"cpu": "1"}).obj(),
            "za-n0",
        )
    ]
    # pod in default ns: the other-ns db must NOT satisfy its affinity
    pods = [
        MakePod("web").req({"cpu": "1"}).pod_affinity("zone", {"app": "db"}).obj()
    ]
    got, want = run_both(nodes, pods, existing)
    assert got == want == [-1]


@pytest.mark.parametrize("seed", range(6))
def test_randomized_differential_affinity(seed):
    rng = np.random.default_rng(200 + seed)
    zones = ["za", "zb", "zc"]
    n_nodes = int(rng.integers(4, 10))
    nodes = [
        MakeNode(f"n{i}").capacity(
            {"cpu": f"{rng.integers(4, 16)}", "memory": f"{rng.integers(8, 32)}Gi"}
        ).labels({"zone": zones[i % 3]}).obj()
        for i in range(n_nodes)
    ]
    apps = [f"app-{j}" for j in range(4)]
    existing = []
    for i in range(int(rng.integers(0, 8))):
        existing.append(
            (
                MakePod(f"e{i}").labels({"app": apps[int(rng.integers(0, 4))]})
                .req({"cpu": "500m"}).obj(),
                f"n{int(rng.integers(0, n_nodes))}",
            )
        )
    pods = []
    for i in range(int(rng.integers(4, 16))):
        app = apps[int(rng.integers(0, 4))]
        b = (
            MakePod(f"p{i}").labels({"app": app})
            .req({"cpu": f"{rng.integers(200, 2000)}m"})
            .priority(int(rng.integers(0, 3))).created(float(i))
        )
        r = rng.random()
        target = apps[int(rng.integers(0, 4))]
        if r < 0.25:
            b.pod_affinity("zone", {"app": target})
        elif r < 0.5:
            b.pod_affinity("kubernetes.io/hostname", {"app": target}, anti=True)
        elif r < 0.65:
            b.pod_affinity("zone", {"app": target}, weight=int(rng.integers(10, 90)))
        elif r < 0.8:
            b.spread(int(rng.integers(1, 3)), "zone", {"app": app})
        pods.append(b.obj())
    got, _ = run_both(nodes, pods, existing)
    errors = oracle.validate_assignment(nodes, pods, got, existing)
    assert not errors, errors
