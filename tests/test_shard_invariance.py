"""Shard-exactness (ISSUE 10 / ROADMAP item 3): the same trace must
produce identical decisions at ANY device count.

Three layers:

- primitive: ops/argsel.py's argmax_first/top_k_first match numpy's
  single-device tie semantics exactly (lowest index first), plus the
  minimal reproduction of the SPMD concatenate miscompilation that was
  the true root cause of the old `dryrun_multichip_8` xfail (an axis-0
  concat of pods-sharded i32 vectors on a 2-D mesh comes back
  multiplied by the free-axis size — guarded by the stack+reshape
  workaround in ops/rounds.py's guard sweep);
- program: the mesh-built carry cycle (shard_view + local_update_fn +
  onehot compaction) places a contended guard-heavy trace bit-
  identically at devices ∈ {1, 2, 4, 8};
- serving: two Schedulers — shardDevices=0 and shardDevices=4 —
  driven through the same multi-cycle trace produce identical bind
  streams and state digests, and the sharded one stamps
  n_devices/collective metadata on flight records, the
  scheduler_shard_devices gauge, and /debug/state.

The conftest forces an 8-device virtual CPU platform, so everything
here is fast-tier except where marked.
"""

import hashlib
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from k8s_scheduler_tpu.ops import argsel
from k8s_scheduler_tpu.parallel.mesh import MESH_AXES, make_mesh


# ---- primitives ----------------------------------------------------------


def test_argmax_first_matches_numpy_first_occurrence():
    rng = np.random.default_rng(0)
    x = rng.integers(0, 4, size=(64, 33)).astype(np.float32)  # many ties
    got = np.asarray(jax.jit(lambda v: argsel.argmax_first(v, axis=1))(x))
    assert (got == x.argmax(axis=1)).all()
    # all-equal rows (every node NEG_INF) pick index 0, like argmax
    flat = np.full((3, 7), -1e9, np.float32)
    assert (np.asarray(argsel.argmax_first(jnp.asarray(flat), 1)) == 0).all()
    # 1-D form (the scan engine's per-step shape)
    v = np.array([2.0, 5.0, 5.0, 1.0], np.float32)
    assert int(argsel.argmax_first(jnp.asarray(v), 0)) == 1


def test_top_k_first_matches_lax_top_k_tie_order():
    rng = np.random.default_rng(1)
    x = rng.integers(0, 5, size=(32, 40)).astype(np.float32)
    vals, idx = jax.jit(lambda v: argsel.top_k_first(v, 6))(x)
    ref_v, ref_i = jax.lax.top_k(jnp.asarray(x), 6)
    assert (np.asarray(vals) == np.asarray(ref_v)).all()
    assert (np.asarray(idx) == np.asarray(ref_i)).all()


def test_argmax_first_shard_invariant_on_2d_mesh():
    mesh = make_mesh(jax.devices()[:8], nodes_axis=2)
    rng = np.random.default_rng(2)
    x = rng.integers(0, 3, size=(64, 32)).astype(np.float32)
    f = jax.jit(lambda v: argsel.argmax_first(v, axis=1))
    rep = np.asarray(f(x))
    sh = np.asarray(f(jax.device_put(
        x, NamedSharding(mesh, PartitionSpec(*MESH_AXES))
    )))
    assert (rep == sh).all()


def test_sharded_concat_workaround():
    """The minimal reproduction behind the old dryrun_multichip_8
    xfail: on a multi-axis mesh, axis-0 jnp.concatenate of 1-D
    pods-sharded integer vectors is miscompiled by this jaxlib's SPMD
    partitioner (partially-replicated operands get summed over the free
    'nodes' axis — every value comes back doubled on a 2-axis mesh).
    stack+reshape produces the same piece-major layout through a safe
    partitioner path; ops/rounds.py's guard sweep builds its
    participant tables with it. If this test ever FAILS on the concat
    side after a jaxlib upgrade, the workaround can be retired."""
    mesh = make_mesh(jax.devices()[:8], nodes_axis=2)
    x = np.arange(320, dtype=np.int32)
    xs = jax.device_put(x, NamedSharding(mesh, PartitionSpec("pods")))

    stacked = jax.jit(lambda v: jnp.stack([v, v], 0).reshape(-1))
    assert (np.asarray(stacked(xs)) == np.asarray(stacked(x))).all()
    # document the live miscompilation (non-fatal if fixed upstream:
    # the workaround is then merely redundant)
    cat = jax.jit(lambda v: jnp.concatenate([v, v]))
    broken = not (np.asarray(cat(xs)) == np.asarray(cat(x))).all()
    if not broken:
        pytest.skip(
            "jaxlib's partitioned concatenate is fixed on this "
            "version — the stack+reshape workaround is now optional"
        )


# ---- program layer: mesh-built carry cycle -------------------------------


def _contended_workload():
    from k8s_scheduler_tpu.models import SnapshotEncoder
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    nodes = make_cluster(24, taint_fraction=0.2, cpu_choices=(2, 4))
    pods = make_pods(
        300, seed=42, affinity_fraction=0.25, anti_affinity_fraction=0.2,
        spread_fraction=0.2, selector_fraction=0.3,
        toleration_fraction=0.3, priorities=(0, 10), num_apps=8,
    )
    enc = SnapshotEncoder(pad_pods=320, pad_nodes=32)
    return enc.encode_packed(nodes, pods)


def test_carry_cycle_shard_count_invariant():
    """devices ∈ {1, 2, 4, 8} → bit-identical assignment AND
    node_requested from the mesh-built carry cycle (shard_view pinning,
    shard_map state update, onehot compaction) over a contended trace
    with every guard capability active."""
    from k8s_scheduler_tpu.core import (
        build_packed_cycle_carry_fn,
        build_stable_state_fn,
    )
    from k8s_scheduler_tpu.core.cycle import CarryKeeper

    wbuf, bbuf, spec, _vs, _dirty = _contended_workload()
    stable = build_stable_state_fn(spec)(wbuf, bbuf)
    ref = None
    for d in (1, 2, 4, 8):
        mesh = make_mesh(jax.devices()[:d]) if d > 1 else None
        cyc = build_packed_cycle_carry_fn(
            spec, mesh=mesh,
            rounds_kw=(
                {"compact_gather": "onehot"} if mesh is not None
                else None
            ),
        )
        keeper = CarryKeeper(spec, mesh=mesh)
        carry = keeper.ci(wbuf, bbuf, stable)
        out = cyc(wbuf, bbuf, stable, carry)
        a = np.asarray(out.assignment)
        nr = np.asarray(out.node_requested)
        if ref is None:
            ref = (a, nr)
            assert (a >= 0).sum() > 30, "trace places a real workload"
        else:
            assert (a == ref[0]).all(), (
                f"{d}-device placements diverged at "
                f"{np.flatnonzero(a != ref[0])[:8]}"
            )
            assert (nr == ref[1]).all(), (
                f"{d}-device node_requested not bit-identical"
            )


# ---- serving layer: bind streams + state digests + stamping --------------


def _drive(shard_devices: int, metrics=None):
    from k8s_scheduler_tpu.config import SchedulerConfiguration
    from k8s_scheduler_tpu.core.scheduler import Scheduler
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    binds = []
    # deterministic LOGICAL clock: backoff expiries / attempt stamps
    # land in the state digest, so both drives must see identical time
    t = [0.0]

    def clock():
        t[0] += 0.001
        return t[0]

    sched = Scheduler(
        config=SchedulerConfiguration(shard_devices=shard_devices),
        binder=lambda p, n: binds.append((p.name, n)),
        metrics=metrics,
        now=clock,
    )
    for n in make_cluster(12, taint_fraction=0.2):
        sched.on_node_add(n)
    for i in range(3):
        for p in make_pods(
            60, seed=10 + i, name_prefix=f"c{i}-",
            selector_fraction=0.3, toleration_fraction=0.3,
            anti_affinity_fraction=0.2,
        ):
            sched.on_pod_add(p)
        sched.schedule_cycle()
    return binds, sched


def _digest(sched) -> str:
    from k8s_scheduler_tpu.state.codec import state_digest

    return state_digest(sched.queue, sched.cache)


def test_scheduler_shard_devices_bind_stream_and_digest_invariant(
    tmp_path,
):
    from k8s_scheduler_tpu.metrics import SchedulerMetrics

    m = SchedulerMetrics()
    b0, s0 = _drive(0)
    b4, s4 = _drive(4, metrics=m)
    assert len(b0) > 100  # the trace binds a real workload
    assert b0 == b4, "sharded bind stream diverged from single-device"
    assert _digest(s0) == _digest(s4)
    assert s0.n_devices == 1 and s4.n_devices == 4
    # flight records carry the mesh width; single-device stamps 1
    for sched, want in ((s0, 1), (s4, 4)):
        recs = sched.flight.to_dicts(last=1)
        assert recs[-1]["counts"]["n_devices"] == want
        assert "collective_payload_bytes" in recs[-1]["counts"]
    # metric families on the sharded scheduler's registry
    text = m.expose().decode()
    assert "scheduler_shard_devices 4.0" in text
    # the payload gauge family exists even before an AOT probe runs
    assert "scheduler_collective_payload_bytes" in text
    # /debug/state surfacing rides the DurableState pin
    from k8s_scheduler_tpu.state import DurableState

    st = DurableState(str(tmp_path / "state"))
    st.sharding = s4._shard_status
    status = st.status()
    assert status["sharding"]["n_devices"] == 4
    assert status["sharding"]["mesh"] == {"pods": 4}
    st.seal()


def test_shard_devices_validation():
    from k8s_scheduler_tpu.config import SchedulerConfiguration
    from k8s_scheduler_tpu.core.scheduler import Scheduler

    with pytest.raises(ValueError, match="only .* device"):
        Scheduler(config=SchedulerConfiguration(shard_devices=512))
    with pytest.raises(ValueError, match="divide the pod pad bucket"):
        Scheduler(config=SchedulerConfiguration(shard_devices=3))


def test_compile_cache_key_distinguishes_sharded_builds():
    """Satellite 6: the persistent-cache key must never alias a sharded
    build with the single-device build of the same regime — the mesh
    field (derived from argument shardings) and the mesh-descriptor
    program names both separate them."""
    from k8s_scheduler_tpu.core import compile_cache as cc
    from k8s_scheduler_tpu.core.cycle import _mesh_desc

    k_plain = cc.cache_key(_FakeSpec(), "default", "cycle", "prog")
    k_mesh = cc.cache_key(
        _FakeSpec(), "default", "cycle", "prog", mesh="pods4"
    )
    assert k_plain.name != k_mesh.name
    assert "mesh=pods4" in k_mesh.text and "mesh=none" in k_plain.text

    # _args_mesh_desc: sharded argument layouts digest differently
    mesh = make_mesh(jax.devices()[:4])
    x = np.arange(64, dtype=np.int32)
    xs = jax.device_put(x, NamedSharding(mesh, PartitionSpec("pods")))
    assert cc._args_mesh_desc((jnp.asarray(x),), {}) == "none"
    d4 = cc._args_mesh_desc((xs,), {})
    assert d4 != "none"
    mesh8 = make_mesh(jax.devices()[:8])
    x8 = jax.device_put(x, NamedSharding(mesh8, PartitionSpec("pods")))
    assert cc._args_mesh_desc((x8,), {}) != d4

    # the mesh-closure route: program names differ by mesh descriptor
    assert _mesh_desc(None) == "none"
    assert _mesh_desc(mesh) == "pods4"
    assert _mesh_desc(make_mesh(jax.devices()[:8], nodes_axis=2)) == (
        "pods4,nodes2"
    )


class _FakeSpec:
    """Just enough PackSpec surface for cache_key."""

    words = (("pod_valid", "int32", (64,), 0),)
    bools = ()
    aux = ()

    def key(self):
        return ("fake",)


def test_flight_record_payload_digest_stable():
    """The serving payload probe and the audit gate share one parser:
    a synthetic HLO module must round-trip through both identically."""
    from k8s_scheduler_tpu.parallel import audit

    hlo = "\n".join([
        "  %ar = f32[100,10]{1,0} all-reduce(f32[100,10]{1,0} %x)",
        "  %ag = s32[64]{0} all-gather(s32[8]{0} %y)",
        "  %cp = u8[32]{0} collective-permute(u8[32]{0} %z)",
        "  %ars = (f32[4]{0}, pred[8]{0}) all-reduce-start(...)",
        "  %unrelated = f32[9]{0} add(f32[9]{0} %a, f32[9]{0} %b)",
    ])
    colls = audit.parse_collectives(hlo)
    assert [c.base_op for c in colls] == [
        "all-reduce", "all-gather", "collective-permute", "all-reduce",
    ]
    assert colls[0].bytes == 100 * 10 * 4
    assert colls[2].bytes == 32  # u8 counts 1 byte under real widths
    assert colls[2].flat4 == 32 * 4  # r05-comparable flat model
    assert colls[3].elems == 12  # tuple result, async -start form
    total = audit.collective_payload_bytes(hlo)
    assert total == sum(c.bytes for c in colls)
    digest = hashlib.sha256(str(total).encode()).hexdigest()
    assert len(digest) == 64  # parser output is deterministic


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_diff_gates_sharded_metrics(tmp_path):
    """bench_diff gates config 8 directionally: scaling_efficiency may
    not drop, collective_payload_mb may not rise; artifacts predating
    config 8 (r05) still diff clean against new ones."""
    base = {
        "config": 8, "name": "sharded_scale",
        "scaling_efficiency": 0.8, "collective_payload_mb": 3.7,
        "per_device_ms": 50.0, "p50_ms": 0.0,
    }
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(base))
    worse = dict(base)
    worse["scaling_efficiency"] = 0.4  # -50% efficiency
    worse["collective_payload_mb"] = 40.0  # the diet regressed
    new.write_text(json.dumps(worse))
    diff = os.path.join(_REPO, "scripts", "bench_diff.py")
    same = subprocess.run(
        [sys.executable, diff, str(old), str(old)],
        capture_output=True, text=True,
    )
    assert same.returncode == 0, same.stdout + same.stderr
    reg = subprocess.run(
        [sys.executable, diff, "--json", str(old), str(new)],
        capture_output=True, text=True,
    )
    assert reg.returncode == 1, reg.stdout + reg.stderr
    regressed = {
        c["metric"] for c in json.loads(reg.stdout)["regressions"]
    }
    assert {"scaling_efficiency", "collective_payload_mb"} <= regressed
    # backward compatibility: an r05 artifact (no config 8 rows) diffs
    # clean against a new artifact that has them
    r05 = os.path.join(_REPO, "BENCH_r05.json")
    back = subprocess.run(
        [sys.executable, diff, r05, str(new)],
        capture_output=True, text=True,
    )
    assert back.returncode == 0, back.stdout + back.stderr


@pytest.mark.slow
def test_bench_sharded_scale_smoke(monkeypatch):
    """Bench config 8 end-to-end at a smoke grid: sweeps the virtual
    devices, asserts the invariance contract internally, and reports
    the headline keys bench_diff gates."""
    import bench_suite

    monkeypatch.setenv("BENCH_SHARDED_GRID", "512x128")
    monkeypatch.setenv("BENCH_SHARDED_DEVICES", "1,2")
    r = bench_suite.run_sharded_scale_config(snapshots=2)
    assert r["config"] == 8 and r["name"] == "sharded_scale"
    assert "scaling_efficiency" in r and r["scaling_efficiency"] > 0
    assert r["collective_payload_mb"] >= 0
    assert r["grid"][0]["devices"]["2"]["per_device_ms"] > 0
    # the 100k x 50k target grid stays documented in CONFIG_SHAPES
    assert bench_suite.CONFIG_SHAPES[8] == (100000, 50000)


def test_budget_checker_flags_unknown_class_and_overrun():
    from k8s_scheduler_tpu.parallel import audit

    mb = 1024 * 1024
    clean = {k: 0 for k in audit.COLLECTIVE_BUDGETS}
    assert audit.check_budgets(clean) == []
    over = dict(clean)
    over["claim_sort"] = int(
        (audit.COLLECTIVE_BUDGETS["claim_sort"] + 1) * mb
    )
    assert any("claim_sort" in p for p in audit.check_budgets(over))
    rogue = dict(clean)
    rogue["brand_new"] = 1
    assert any(
        "not in" in p and "brand_new" in p
        for p in audit.check_budgets(rogue)
    )
    total_buster = {k: 0 for k in audit.COLLECTIVE_BUDGETS}
    total_buster["static_base"] = int(
        (audit.TOTAL_BUDGET_MB + 1) * mb
    )
    assert any(
        "total collective payload" in p
        for p in audit.check_budgets(total_buster)
    )
