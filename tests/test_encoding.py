import numpy as np

from k8s_scheduler_tpu.models import MakeNode, MakePod, SnapshotEncoder
from k8s_scheduler_tpu.models import api
from k8s_scheduler_tpu.utils import parse_quantity


def test_parse_quantity():
    assert parse_quantity("100m", as_millis=True) == 100.0
    assert parse_quantity("2", as_millis=True) == 2000.0
    assert parse_quantity("1Gi") == 2**30
    assert parse_quantity("512Mi") == 512 * 2**20
    assert parse_quantity("1500m", as_millis=True) == 1500.0
    assert parse_quantity("1k") == 1000.0
    assert parse_quantity("2e3") == 2000.0
    assert parse_quantity(2, as_millis=True) == 2000.0


def test_pod_resource_requests():
    p = MakePod("a").req({"cpu": "500m", "memory": "1Gi"}).obj()
    r = p.resource_requests()
    assert r["cpu"] == 500.0
    assert r["memory"] == 2**30
    assert r["pods"] == 1.0  # implicit pod-slot request


def test_encode_basic_shapes():
    nodes = [
        MakeNode(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"}).obj()
        for i in range(3)
    ]
    pods = [MakePod(f"p{i}").req({"cpu": "1"}).obj() for i in range(5)]
    snap = SnapshotEncoder().encode(nodes, pods)
    assert snap.num_nodes == 3 and snap.num_pending == 5
    assert snap.N >= 3 and snap.P >= 5  # padded
    assert snap.node_valid[:3].all() and not snap.node_valid[3:].any()
    cpu = snap.resource_names.index("cpu")
    assert np.allclose(snap.node_allocatable[:3, cpu], 4000.0)
    assert np.allclose(snap.pod_requested[:5, cpu], 1000.0)


def test_encode_existing_pods_aggregate():
    nodes = [MakeNode("n0").capacity({"cpu": "4"}).obj(),
             MakeNode("n1").capacity({"cpu": "4"}).obj()]
    existing = [
        (MakePod("e0").req({"cpu": "1"}).obj(), "n0"),
        (MakePod("e1").req({"cpu": "2"}).obj(), "n0"),
    ]
    snap = SnapshotEncoder().encode(nodes, [], existing)
    cpu = snap.resource_names.index("cpu")
    assert snap.node_requested[0, cpu] == 3000.0
    assert snap.node_requested[1, cpu] == 0.0
    # preemption table: sorted ascending by priority
    assert set(snap.node_pods[0][snap.node_pods[0] >= 0].tolist()) == {0, 1}


def test_encode_priority_order():
    nodes = [MakeNode("n0").capacity({"cpu": "4"}).obj()]
    pods = [
        MakePod("low").priority(1).created(5).obj(),
        MakePod("high").priority(10).created(9).obj(),
        MakePod("mid-old").priority(5).created(1).obj(),
        MakePod("mid-new").priority(5).created(2).obj(),
    ]
    snap = SnapshotEncoder().encode(nodes, pods)
    # rank: high(0), mid-old(1), mid-new(2), low(3)
    assert snap.pod_order[:4].tolist() == [3, 0, 1, 2]


def test_encode_taints_tolerations_dedup():
    nodes = [
        MakeNode("n0").capacity({"cpu": "1"}).taint("gpu", "true").obj(),
        MakeNode("n1").capacity({"cpu": "1"}).taint("gpu", "true").obj(),
        MakeNode("n2").capacity({"cpu": "1"}).obj(),
    ]
    pods = [
        MakePod("p0").toleration("gpu", "true", api.NO_SCHEDULE).obj(),
        MakePod("p1").toleration("gpu", "true", api.NO_SCHEDULE).obj(),
        MakePod("p2").obj(),
    ]
    snap = SnapshotEncoder().encode(nodes, pods)
    # dedup: both tainted nodes share a taint-set id
    assert snap.node_taintset[0] == snap.node_taintset[1]
    assert snap.node_taintset[0] != snap.node_taintset[2]
    assert snap.pod_tolset[0] == snap.pod_tolset[1]
    assert snap.pod_tolset[0] != snap.pod_tolset[2]


def test_encode_node_affinity_dedup():
    nodes = [MakeNode("n0").capacity({"cpu": "1"}).labels({"zone": "a"}).obj()]
    pods = [
        MakePod("p0").node_affinity_in("zone", ["a", "b"]).obj(),
        MakePod("p1").node_affinity_in("zone", ["a", "b"]).obj(),
        MakePod("p2").node_affinity_in("zone", ["c"]).obj(),
        MakePod("p3").obj(),
    ]
    snap = SnapshotEncoder().encode(nodes, pods)
    assert snap.pod_req_id[0] == snap.pod_req_id[1]
    assert snap.pod_req_id[0] != snap.pod_req_id[2]
    assert snap.pod_req_id[3] == -1


def test_encode_topology_domains():
    nodes = [
        MakeNode("n0").capacity({"cpu": "1"}).labels({"zone": "a"}).obj(),
        MakeNode("n1").capacity({"cpu": "1"}).labels({"zone": "a"}).obj(),
        MakeNode("n2").capacity({"cpu": "1"}).labels({"zone": "b"}).obj(),
    ]
    pods = [MakePod("p0").pod_affinity("zone", {"app": "web"}).obj()]
    snap = SnapshotEncoder().encode(nodes, pods)
    assert "zone" in snap.topology_keys
    k = snap.topology_keys.index("zone")
    # n0,n1 same zone-domain; n2 different; hostname domains all distinct
    assert snap.node_domains[0, k] == snap.node_domains[1, k]
    assert snap.node_domains[0, k] != snap.node_domains[2, k]
    # hostname is always topology key 0; its domains are all distinct
    assert len({int(snap.node_domains[i, 0]) for i in range(3)}) == 3


def test_snapshot_is_pytree():
    import jax

    nodes = [MakeNode("n0").capacity({"cpu": "1"}).obj()]
    pods = [MakePod("p0").req({"cpu": "1"}).obj()]
    snap = SnapshotEncoder().encode(nodes, pods)
    leaves = jax.tree_util.tree_leaves(snap)
    assert all(isinstance(x, np.ndarray) for x in leaves)
    # round-trips through flatten/unflatten with static meta preserved
    flat, treedef = jax.tree_util.tree_flatten(snap)
    snap2 = jax.tree_util.tree_unflatten(treedef, flat)
    assert snap2.resource_names == snap.resource_names
    assert snap2.num_nodes == 1


def test_encode_malformed_gt_and_matchfields_no_crash():
    from k8s_scheduler_tpu.models.api import (
        NodeSelectorRequirement, NodeSelectorTerm,
    )
    from k8s_scheduler_tpu.models import encoding as enc_mod

    nodes = [MakeNode("n0").capacity({"cpu": "1"}).obj()]
    bad_gt = MakePod("bad-gt").node_affinity_required(
        NodeSelectorTerm((NodeSelectorRequirement("size", "Gt", ("abc",)),))
    ).obj()
    bad_field = MakePod("bad-field").node_affinity_required(
        NodeSelectorTerm(match_fields=(
            NodeSelectorRequirement("metadata.name", "Exists", ()),
        ))
    ).obj()
    snap = SnapshotEncoder().encode(nodes, [bad_gt, bad_field])
    # both malformed requirements compile to the never-matching expression
    assert (snap.ex_op == enc_mod.OP_IMPOSSIBLE).any()
    assert snap.pod_req_id[0] >= 0 and snap.pod_req_id[1] >= 0
