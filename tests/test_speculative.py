"""Depth-2 speculative dispatch pipelining + streamed decision fetch
(ISSUE 13): device-saturated multi-cycle serving that never trades
correctness for latency.

Four layers:

- device level: chaining batch B onto batch A's device-resident carry
  through the carry_in continuation program is bit-identical to the
  combined [A;B] batch;
- pipeline level: streamed per-row decisions equal the stacked fetch,
  the speculative ordering-guard relaxation ("binds fold before the
  next ADOPTED encode"), the speculation ledger, and the
  slot-accounting invariant (depth-2 never overwrites an unfetched
  slot — three slots required, refused loudly on two);
- scheduler level: a speculativeDispatch=on scheduler is bit-identical
  to the same trace with speculation off AND to the K=1 sequential
  scheduler (binds, journal decision records, state digests); the
  forced-mismatch path (a bind error in the predecessor's fold)
  abandons, re-dispatches against the true carry, still lands
  bit-identical binds, and counts one abandoned + one redispatched in
  the ledger; flight records carry first_bind_ms and the speculation
  tag;
- sentinel: a high abandon-rate EWMA raises speculation_thrash and
  auto-disables speculation for degradePromoteCycles opportunities.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from k8s_scheduler_tpu.config import SchedulerConfiguration, load_config
from k8s_scheduler_tpu.core import Scheduler
from k8s_scheduler_tpu.core.cycle import build_packed_multicycle_fn
from k8s_scheduler_tpu.core.pipeline import ServingPipeline
from k8s_scheduler_tpu.framework.runtime import Framework
from k8s_scheduler_tpu.models import MakeNode, MakePod, packing
from k8s_scheduler_tpu.models.encoding import SnapshotEncoder
from k8s_scheduler_tpu.state import DurableState, state_digest

from test_multicycle import FakeClock, _journal_streams


# ---- shared device-level fixtures ---------------------------------------


def _nodes(n=5, cpu="4"):
    return [
        MakeNode(f"n{i}").capacity({"cpu": cpu, "memory": "8Gi"}).obj()
        for i in range(n)
    ]


def _encode_stacked(groups, nodes, k):
    enc = SnapshotEncoder()
    enc.pad_pods = 8
    enc.pad_nodes = 8
    snaps = [enc.encode(nodes, g, ()) for g in groups]
    spec = packing.make_spec(snaps[0])
    for s in snaps[1:]:
        assert packing.make_spec(s).key() == spec.key()
    wb = np.zeros((k, spec.n_words), np.uint32)
    bb = np.zeros((k, spec.n_bytes), np.uint8)
    for i, s in enumerate(snaps):
        wb[i], bb[i] = packing.pack(s, spec)
    return spec, wb, bb


def _rand_groups(seed, n_groups, max_pods=5):
    rng = random.Random(seed)
    groups, uid = [], 0
    for _ in range(n_groups):
        g = []
        for _ in range(rng.randint(1, max_pods)):
            g.append(
                MakePod(f"p{uid}")
                .req({"cpu": rng.choice(["1", "2", "3"]),
                      "memory": "1Gi"})
                .obj()
            )
            uid += 1
        groups.append(g)
    return groups


# ---- device level: continuation chaining ---------------------------------


@pytest.mark.parametrize("seed", [0, 7])
def test_carry_chain_matches_combined_batch(seed):
    """Batch A (row 0) chained into batch B (rows 1..K-1) through the
    carry_in continuation program produces bit-identical decisions and
    final carry to the combined [A;B] dispatch — the property that
    makes adopting a speculative batch correctness-free."""
    nodes = _nodes()
    groups = _rand_groups(seed, 4)
    K = 4
    spec, wb, bb = _encode_stacked(groups, nodes, K)
    fw = Framework.from_config()
    mfn = build_packed_multicycle_fn(spec, framework=fw, k=K)
    mcont = build_packed_multicycle_fn(
        spec, framework=fw, k=K, carry_in=True
    )
    full = mfn(wb, bb, None, np.int32(4))
    wa = np.zeros_like(wb)
    ba = np.zeros_like(bb)
    wa[0], ba[0] = wb[0], bb[0]
    wB = np.zeros_like(wb)
    bB = np.zeros_like(bb)
    wB[:3], bB[:3] = wb[1:], bb[1:]
    ra = mfn(wa, ba, None, np.int32(1))
    rb = mcont(
        wB, bB, None, np.int32(3),
        ra.carry_node_requested, ra.carry_gplaced,
    )
    assert int(ra.cycles_run) == 1 and int(rb.cycles_run) == 3
    np.testing.assert_array_equal(
        np.asarray(full.assignment)[0], np.asarray(ra.assignment)[0]
    )
    for i in range(3):
        np.testing.assert_array_equal(
            np.asarray(full.assignment)[i + 1],
            np.asarray(rb.assignment)[i],
            err_msg=f"chained inner cycle {i} diverged",
        )
        np.testing.assert_array_equal(
            np.asarray(full.unschedulable)[i + 1],
            np.asarray(rb.unschedulable)[i],
        )
        np.testing.assert_array_equal(
            np.asarray(full.gang_dropped)[i + 1],
            np.asarray(rb.gang_dropped)[i],
        )
    np.testing.assert_array_equal(
        np.asarray(full.carry_node_requested),
        np.asarray(rb.carry_node_requested),
    )
    # continuation batches report their own gplaced DELTA so chains add
    np.testing.assert_array_equal(
        np.asarray(full.carry_gplaced),
        np.asarray(ra.carry_gplaced) + np.asarray(rb.carry_gplaced),
    )


# ---- pipeline level ------------------------------------------------------


def _pipe_with_programs(spec, k, slots=3):
    fw = Framework.from_config()
    pipe = ServingPipeline(lambda *a: None, slots=slots)
    pipe.multi_fn = build_packed_multicycle_fn(spec, framework=fw, k=k)
    pipe.multi_cont_fn = build_packed_multicycle_fn(
        spec, framework=fw, k=k, carry_in=True
    )
    return pipe


def test_streamed_rows_equal_stacked_fetch():
    nodes = _nodes()
    groups = _rand_groups(3, 4)
    spec, wb, bb = _encode_stacked(groups, nodes, 4)
    pipe = _pipe_with_programs(spec, 4)
    h = pipe.dispatch_multi(wb, bb, None, 4)
    rows = [h.decisions_row(i) for i in range(4)]
    assert h.fetched  # every live row fetched -> guard released
    a, u, gd, att, ran = h.decisions()
    assert ran == 4 and h.cycles_run() == 4
    for i in range(4):
        np.testing.assert_array_equal(a[i], rows[i][0])
        np.testing.assert_array_equal(u[i], rows[i][1])
        np.testing.assert_array_equal(gd[i], rows[i][2])
        np.testing.assert_array_equal(att[i], rows[i][3])


def test_speculative_guard_and_ledger():
    """The ordering guard relaxes only for speculative dispatches: a
    normal dispatch with the predecessor unfetched is still refused,
    a speculative one proceeds, and a second dispatch is refused until
    the speculation resolves."""
    nodes = _nodes()
    groups = _rand_groups(5, 4)
    spec, wb, bb = _encode_stacked(groups, nodes, 4)
    pipe = _pipe_with_programs(spec, 4)
    wa = np.zeros_like(wb)
    ba = np.zeros_like(bb)
    wa[0], ba[0] = wb[0], bb[0]
    ha = pipe.dispatch_multi(wa, ba, None, 1)
    with pytest.raises(RuntimeError, match="before .* fetched"):
        pipe.dispatch_multi(wb, bb, None, 4)  # non-speculative: refused
    hb = pipe.dispatch_multi(
        wb, bb, None, 3,
        carry0=(ha.result.carry_node_requested, ha.result.carry_gplaced),
        speculative=True,
    )
    assert pipe.inflight() == 2  # depth 2: both batches in flight
    with pytest.raises(RuntimeError, match="unresolved speculative"):
        pipe.dispatch_multi(wb, bb, None, 4)
    ha.decisions_row(0)
    adopted = pipe.adopt_speculative()
    assert adopted is hb
    for i in range(3):
        hb.decisions_row(i)
    assert pipe.speculation == {
        "adopted": 1, "abandoned": 0, "redispatched": 0,
    }
    # resolved + fetched: the next dispatch proceeds normally
    pipe.dispatch_multi(wb, bb, None, 4)


def test_abandon_frees_the_slot_and_counts():
    nodes = _nodes()
    groups = _rand_groups(6, 4)
    spec, wb, bb = _encode_stacked(groups, nodes, 4)
    pipe = _pipe_with_programs(spec, 4)
    wa = np.zeros_like(wb)
    ba = np.zeros_like(bb)
    wa[0], ba[0] = wb[0], bb[0]
    ha = pipe.dispatch_multi(wa, ba, None, 1)
    hb = pipe.dispatch_multi(
        wb, bb, None, 3,
        carry0=(ha.result.carry_node_requested, ha.result.carry_gplaced),
        speculative=True,
    )
    pipe.abandon_speculative()
    assert hb.result is None  # released
    assert pipe.inflight() == 1  # only the predecessor remains
    assert hb not in pipe._slots  # the slot did not leak
    pipe.note_redispatch()
    assert pipe.speculation == {
        "adopted": 0, "abandoned": 1, "redispatched": 1,
    }
    # abandoning again is a no-op (failure paths call unconditionally)
    pipe.abandon_speculative()
    assert pipe.speculation["abandoned"] == 1


def test_depth2_never_overwrites_an_unfetched_slot():
    """The slot-accounting invariant: with only the two double-buffered
    slots, a dispatch sequence that would reuse the slot of a batch
    whose decisions were never fetched is refused loudly (dispatch A ->
    speculate B -> abandon -> re-speculate wraps to A's slot); the
    third slot makes the same sequence legal."""
    nodes = _nodes()
    groups = _rand_groups(8, 4)
    spec, wb, bb = _encode_stacked(groups, nodes, 4)

    def drive(slots):
        pipe = _pipe_with_programs(spec, 4, slots=slots)
        wa = np.zeros_like(wb)
        ba = np.zeros_like(bb)
        wa[0], ba[0] = wb[0], bb[0]
        ha = pipe.dispatch_multi(wa, ba, None, 1)
        carry = (
            ha.result.carry_node_requested, ha.result.carry_gplaced
        )
        pipe.dispatch_multi(
            wb, bb, None, 3, carry0=carry, speculative=True
        )
        pipe.abandon_speculative()
        # re-speculating claims the NEXT slot — with two slots that is
        # A's, still unfetched and still in flight
        return pipe.dispatch_multi(
            wb, bb, None, 3, carry0=carry, speculative=True
        )

    with pytest.raises(RuntimeError, match="unfetched in-flight"):
        drive(slots=2)
    drive(slots=3)  # the third arena slot makes depth 2 safe


# ---- scheduler level -----------------------------------------------------


def _drive(k, seed, state_dir, *, speculative, n_cycles=6,
           fail_uids=frozenset()):
    """One randomized arrival trace through a Scheduler (frozen clock,
    journaled); `fail_uids` makes the binder fail those pods — the
    deterministic fold divergence the mismatch path tests force."""
    clock = FakeClock()
    binds = []
    cfg = SchedulerConfiguration(
        multi_cycle_k=k, multi_cycle_max_wait_ms=1e9,
        speculative_dispatch=speculative,
    )
    state = DurableState(str(state_dir), snapshot_interval_seconds=0)

    def binder(pod, node):
        if pod.uid in fail_uids:
            raise RuntimeError(f"induced bind failure for {pod.uid}")
        binds.append((pod.uid, node))

    sched = Scheduler(
        config=cfg, binder=binder, now=clock, pad_bucket=8, state=state,
    )
    for i in range(6):
        sched.on_node_add(
            MakeNode(f"n{i}")
            .capacity({"cpu": "4", "memory": "8Gi"}).obj()
        )
    rng = random.Random(seed)
    uid = 0
    for _c in range(n_cycles):
        for _ in range(rng.randint(1, 5)):
            sched.on_pod_add(
                MakePod(f"p{uid}")
                .req({"cpu": rng.choice(["1", "2", "3"]),
                      "memory": "1Gi"})
                .obj()
            )
            uid += 1
        sched.schedule_cycle()
    for _ in range(2):
        sched.schedule_cycle()  # idle pops flush the buffer
    recs = [
        (r.counts.get("pods"), r.counts.get("scheduled"),
         r.counts.get("unschedulable"), r.counts.get("gang_dropped"))
        for r in sched.flight.snapshot()
    ]
    digest = state_digest(sched.queue, sched.cache)
    state.journal.flush()
    state.journal.close()
    return binds, recs, digest, sched


@pytest.mark.parametrize("seed", [0, 9])
def test_scheduler_speculative_matches_sequential(tmp_path, seed):
    """The tentpole acceptance: speculation on is bit-identical to
    speculation off AND to the K=1 sequential scheduler — same bind
    streams, same journal decision records, same state digests — while
    the ledger proves batches were actually adopted."""
    b1, r1, d1, _s1 = _drive(
        1, seed, tmp_path / "seq", speculative=False
    )
    b4, r4, d4, _s4 = _drive(
        4, seed, tmp_path / "mc", speculative=False
    )
    bs, rs, ds, sched = _drive(
        4, seed, tmp_path / "spec", speculative=True
    )
    assert bs == b4 == b1
    assert ds == d4 == d1
    assert rs == r4
    led = sched.speculation_ledger()
    assert led["adopted"] >= 1, led
    assert led["abandoned"] == led["redispatched"] == 0
    dec1, arr1 = _journal_streams(tmp_path / "seq")
    decs, arrs = _journal_streams(tmp_path / "spec")
    assert decs == dec1
    assert arrs == arr1
    assert sched.observer.anomaly_counts["speculation_thrash"] == 0


def test_mismatch_abandons_redispatches_bit_identical(tmp_path):
    """The forced-mismatch path: a bind error in the predecessor
    batch's fold diverges from the speculation's predicate digest —
    the in-flight batch must be abandoned, its groups re-dispatched
    against the true carry, the resulting binds bit-identical to the
    sequential scheduler under the same failing binder, and the ledger
    must count one abandoned + one redispatched."""
    # the first flushed batch's row-0 group contains p0: failing its
    # bind makes the first speculation's fold diverge deterministically
    fail = frozenset({"default/p0"})
    b1, _r1, d1, _s1 = _drive(
        1, 0, tmp_path / "seq", speculative=False, fail_uids=fail
    )
    bs, _rs, ds, sched = _drive(
        4, 0, tmp_path / "spec", speculative=True, fail_uids=fail
    )
    assert bs == b1
    assert ds == d1
    led = sched.speculation_ledger()
    assert led["abandoned"] >= 1, led
    assert led["redispatched"] == led["abandoned"]
    dec1, _arr1 = _journal_streams(tmp_path / "seq")
    decs, _arrs = _journal_streams(tmp_path / "spec")
    assert decs == dec1


def test_records_carry_first_bind_and_speculation_tag(tmp_path):
    """Observability satellites: the flush's first record carries the
    streamed-fetch first_bind phase and the speculation outcome; the
    adopted batch's records are its own dispatch's, not copies of the
    predecessor's window."""
    clock = FakeClock()
    cfg = SchedulerConfiguration(
        multi_cycle_k=3, multi_cycle_max_wait_ms=1e9,
        speculative_dispatch=True,
    )
    sched = Scheduler(config=cfg, now=clock, pad_bucket=8)
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "64"}).obj())
    for i in range(3):
        sched.on_pod_add(MakePod(f"p{i}").req({"cpu": "1"}).obj())
        clock.tick(0.01)
        sched.schedule_cycle()
    recs = sched.flight.snapshot()
    assert len(recs) == 3
    from k8s_scheduler_tpu.core.observe import phase_seconds

    ph0 = phase_seconds(recs[0])
    assert "first_bind" in ph0
    assert recs[0].phases["first_bind_ms"] >= 0.0
    assert recs[0].speculation == "adopted"
    assert recs[0].to_dict()["speculation"] == "adopted"
    # exactly ONE record carries the outcome (one EWMA sample per
    # speculation); the adopted batch's own records are untagged
    assert [r.speculation for r in recs[1:]] == ["", ""]
    # record 1 is the adopted batch's record 0: its own dispatch marks
    assert "dispatch_start" in recs[1].marks
    assert recs[1].counts["multi_cycle_k"] == 3
    # the speculative dispatch itself is visible on the predecessor
    assert "spec_dispatch_ms" in recs[0].phases


def test_forced_sync_and_ladder_disable_speculation(tmp_path):
    """The escape hatches: forcedSync and a ladder rung at/below
    `sequential` force speculation off (batches still serve)."""
    clock = FakeClock()
    cfg = SchedulerConfiguration(
        multi_cycle_k=2, multi_cycle_max_wait_ms=1e9,
        speculative_dispatch=True, forced_sync=True,
    )
    binds = []
    sched = Scheduler(
        config=cfg, binder=lambda p, n: binds.append(p.uid),
        now=clock, pad_bucket=8,
    )
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "64"}).obj())
    for i in range(2):
        sched.on_pod_add(MakePod(f"p{i}").req({"cpu": "1"}).obj())
        clock.tick(0.01)
        sched.schedule_cycle()
    sched.schedule_cycle()
    assert sorted(binds) == ["default/p0", "default/p1"]
    assert sched.speculation_ledger() == {
        "adopted": 0, "abandoned": 0, "redispatched": 0,
    }


def test_fold_free_driver_keeps_silent_slot_release():
    """require_decision_fetch=False (fold-free probes/throughput loops)
    opted out of the ordering guard — slot reuse must keep the old
    silent release, never the depth-2 unfetched-slot refusal."""
    nodes = _nodes()
    groups = _rand_groups(11, 4)
    spec, wb, bb = _encode_stacked(groups, nodes, 4)
    fw = Framework.from_config()
    pipe = ServingPipeline(
        lambda *a: None, require_decision_fetch=False, slots=2
    )
    pipe.multi_fn = build_packed_multicycle_fn(spec, framework=fw, k=4)
    for _ in range(3):  # third dispatch wraps onto an unfetched slot
        pipe.dispatch_multi(wb, bb, None, 4)


def test_apply_failure_releases_guard_and_speculation(tmp_path):
    """A NON-fetch failure inside the apply loop (here: a host plugin
    raising a plain exception) must release the ordering guard and
    free the in-flight speculation — the old stacked fetch had marked
    the handle consumed before any apply, and one apply-path error
    must not wedge the pipeline forever."""
    from k8s_scheduler_tpu.framework.host import HostPlugin

    class Boom(HostPlugin):
        name = "Boom"
        fired = False

        def reserve(self, pod, node_name):
            if not Boom.fired:
                Boom.fired = True
                raise RuntimeError("induced host-plugin failure")
            return None

    clock = FakeClock()
    binds = []
    cfg = SchedulerConfiguration(
        multi_cycle_k=2, multi_cycle_max_wait_ms=1e9,
        speculative_dispatch=True,
    )
    sched = Scheduler(
        config=cfg, binder=lambda p, n: binds.append(p.uid),
        now=clock, pad_bucket=8, host_plugins=[Boom()],
    )
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "64"}).obj())
    sched.on_pod_add(MakePod("p0").req({"cpu": "1"}).obj())
    clock.tick(0.01)
    sched.schedule_cycle()  # buffers group 0
    sched.on_pod_add(MakePod("p1").req({"cpu": "1"}).obj())
    clock.tick(0.01)
    with pytest.raises(RuntimeError, match="induced host-plugin"):
        sched.schedule_cycle()  # the flush whose row-0 apply explodes
    # the pipeline is NOT wedged: later cycles schedule normally
    for i in range(2, 4):
        sched.on_pod_add(MakePod(f"p{i}").req({"cpu": "1"}).obj())
        clock.tick(0.01)
        sched.schedule_cycle()
    sched.schedule_cycle()
    assert "default/p2" in binds and "default/p3" in binds


# ---- sentinel: speculation_thrash ---------------------------------------


def test_sentinel_thrash_holds_and_reenables():
    from k8s_scheduler_tpu.core.observe import CycleObserver

    obs = CycleObserver(
        metrics=None, spec_hold_cycles=3, spec_warmup=4,
    )
    for i in range(4):
        obs.observe_phases(
            {"total": 0.01}, profile="p", seq=i,
            speculation="abandoned",
        )
    assert obs.anomaly_counts["speculation_thrash"] == 1
    ev = obs.anomalies(last=1)[0]
    assert ev["class"] == "speculation_thrash"
    assert ev["detail"]["hold_cycles"] == 3
    # the hold: three refused opportunities, then re-enabled
    assert [obs.speculation_ok("p") for _ in range(4)] == [
        False, False, False, True,
    ]
    # adopted outcomes keep the EWMA low: no re-fire
    for i in range(8):
        obs.observe_phases(
            {"total": 0.01}, profile="p", seq=10 + i,
            speculation="adopted",
        )
    assert obs.anomaly_counts["speculation_thrash"] == 1
    assert obs.speculation_ok("p")


def test_scheduler_consults_the_thrash_hold(tmp_path):
    """With the hold active the scheduler serves the batch without
    speculating (ledger stays flat while binds still land)."""
    clock = FakeClock()
    cfg = SchedulerConfiguration(
        multi_cycle_k=2, multi_cycle_max_wait_ms=1e9,
        speculative_dispatch=True,
    )
    binds = []
    sched = Scheduler(
        config=cfg, binder=lambda p, n: binds.append(p.uid),
        now=clock, pad_bucket=8,
    )
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "64"}).obj())
    # arm the hold directly (the unit above covers how it arises)
    with sched.observer._lock:
        sched.observer._prof.setdefault(
            "default-scheduler", {"sig": None, "counts": {}, "cycles": 0}
        )["spec_hold"] = 100
    for i in range(2):
        sched.on_pod_add(MakePod(f"p{i}").req({"cpu": "1"}).obj())
        clock.tick(0.01)
        sched.schedule_cycle()
    sched.schedule_cycle()
    assert sorted(binds) == ["default/p0", "default/p1"]
    assert sched.speculation_ledger()["adopted"] == 0


# ---- bench: the K-sweep acceptance shape ---------------------------------


def test_bench_sweep_reports_first_bind_and_hit_rate():
    """ISSUE 13 bench acceptance (CPU smoke): with depth-2 + streamed
    fetch on, the K-sweep reports first_bind_p50_ms and
    speculation_hit_rate; first bind lands within ~1 inner cycle (the
    `<= 2x a single inner cycle` criterion, with sched_effective_p50
    = flush wall / K as the inner-cycle yardstick) instead of waiting
    the whole K-cycle batch, and a clean drive adopts every
    speculation."""
    import bench_suite

    for attempt in range(2):
        out = bench_suite.run_multicycle_config(
            1, k_values=(1, 4), batches=3
        )
        assert "skipped" not in out
        assert out["speculation_hit_rate"] == 1.0
        pt = out["per_k"]["4"]
        assert pt["speculation_ledger"]["adopted"] >= 1
        fb = out["first_bind_p50_ms"]
        if (
            fb <= 2 * pt["sched_effective_p50_ms"]
            and fb < pt["sched_batch_p50_ms"]
        ):
            break
    else:
        assert fb <= 2 * pt["sched_effective_p50_ms"]
        assert fb < pt["sched_batch_p50_ms"]


def test_bench_diff_gates_the_new_metrics(tmp_path):
    """bench_diff: first_bind_p50_ms higher = regressed,
    speculation_hit_rate drop = regressed — and both stay
    backward-compatible with artifacts predating the sweep (r05)."""
    import json
    import subprocess
    import sys
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    old = {"configs": [{
        "config": 2, "p50_ms": 10.0,
        "first_bind_p50_ms": 5.0, "speculation_hit_rate": 1.0,
    }]}
    new = {"configs": [{
        "config": 2, "p50_ms": 10.0,
        "first_bind_p50_ms": 20.0, "speculation_hit_rate": 0.4,
    }]}
    r05 = {"configs": [{"config": 2, "p50_ms": 10.0}]}
    p_old = tmp_path / "old.json"
    p_new = tmp_path / "new.json"
    p_r05 = tmp_path / "r05.json"
    p_old.write_text(json.dumps(old))
    p_new.write_text(json.dumps(new))
    p_r05.write_text(json.dumps(r05))

    def diff(a, b):
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "scripts", "bench_diff.py"),
             "--json", str(a), str(b)],
            capture_output=True, text=True,
        )
        return proc.returncode, json.loads(proc.stdout)

    rc, res = diff(p_old, p_new)
    assert rc == 1
    regressed = {c["metric"] for c in res["regressions"]}
    assert {"first_bind_p50_ms", "speculation_hit_rate"} <= regressed
    # r05-era artifact without the metrics: skipped, not crashed
    rc, res = diff(p_r05, p_new)
    assert rc == 0, res


# ---- config / CLI plumbing ----------------------------------------------


def test_config_and_cli_plumbing():
    assert SchedulerConfiguration().speculative_dispatch is True
    cfg = load_config({"speculativeDispatch": False})
    assert cfg.speculative_dispatch is False
    from k8s_scheduler_tpu.cmd.main import new_scheduler_command

    ap = new_scheduler_command()
    args = ap.parse_args(["--speculative-dispatch", "0"])
    assert args.speculative_dispatch == 0
    assert ap.parse_args([]).speculative_dispatch == -1
