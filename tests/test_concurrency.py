"""Thread-safety stress: informer callbacks land on arbitrary threads
while the scheduling loop runs (SURVEY.md §5.2 — the queue/cache locks
were previously claimed but never exercised under real threads)."""

from __future__ import annotations

import threading

from k8s_scheduler_tpu.core.scheduler import Scheduler
from k8s_scheduler_tpu.models.builders import MakeNode, MakePod

N_THREADS = 4
PODS_PER_THREAD = 120


def test_informer_threads_racing_the_cycle_loop():
    bound: dict[str, str] = {}
    bind_lock = threading.Lock()

    def binder(pod, node):
        with bind_lock:
            assert pod.uid not in bound, f"double bind of {pod.uid}"
            bound[pod.uid] = node

    s = Scheduler(binder=binder)
    for i in range(16):
        s.on_node_add(MakeNode(f"n{i}").capacity({"cpu": "64"}).obj())

    start = threading.Barrier(N_THREADS + 1)
    errors: list[BaseException] = []

    def informer(tid: int) -> None:
        try:
            start.wait()
            for j in range(PODS_PER_THREAD):
                pod = (
                    MakePod(f"p{tid}-{j}")
                    .req({"cpu": "1"})
                    .created(float(tid * PODS_PER_THREAD + j))
                    .obj()
                )
                s.on_pod_add(pod)
                if j % 3 == 0:
                    s.on_pod_update(pod)
                if j % 7 == 0:
                    s.on_pod_delete(pod.uid)
                if j % 11 == 0:
                    s.on_node_update(
                        MakeNode(f"n{j % 16}").capacity({"cpu": "64"}).obj()
                    )
        except BaseException as e:  # propagate into the main thread
            errors.append(e)

    threads = [
        threading.Thread(target=informer, args=(t,)) for t in range(N_THREADS)
    ]
    for t in threads:
        t.start()
    start.wait()
    # the scheduling loop races the informers
    for _ in range(12):
        s.schedule_cycle()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors[:3]

    # drain what's left
    for _ in range(20):
        stats = s.schedule_cycle()
        if stats.attempted == 0:
            break

    # invariants after the dust settles: every non-deleted pod is bound
    # exactly once, deleted pods are not bound... a deleted pod MAY have
    # been bound before its delete arrived (real informer races do that);
    # what must hold is no double-bind (asserted in binder) and queue/cache
    # agreement
    counts = s.queue.pending_counts()
    assert counts.get("active", 0) == 0
    # without an agent confirming binds, bound pods stay "assumed" until
    # TTL: the cache must account for exactly the binder's successes
    c = s.cache.counts()
    assert c.get("assumed", 0) + c.get("bound", 0) == len(bound)
