"""Fault-injection framework, dispatch watchdog, and degradation ladder
(ISSUE 9): plan parsing/determinism, the watchdog's bound on a hung
decision fetch, ladder transitions + promotion + observability wiring,
fetch-failure attribution, journal-ENOSPC stateless degrade, and
compile-cache torn/ENOSPC store robustness. The kill -9
crash-during-degradation path rides tests/test_state_failover.py and
the slow-marked soak_chaos smoke below."""

from __future__ import annotations

import importlib.util
import json
import os
import pathlib
import subprocess
import sys
import time

import pytest

from k8s_scheduler_tpu.core import faults
from k8s_scheduler_tpu.core.degrade import RUNGS, DegradationLadder
from k8s_scheduler_tpu.core.events import EventRecorder
from k8s_scheduler_tpu.core.observe import ANOMALY_CLASSES, CycleObserver
from k8s_scheduler_tpu.metrics import SchedulerMetrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm():
    """No fault plan may leak across tests (arming is process-global)."""
    yield
    faults.disarm()


# ---- FaultPlan parsing / determinism --------------------------------------


def test_fault_plan_parse_full_grammar():
    p = faults.FaultPlan.parse(
        "seed=9; fetch_hang@cycle=40:ms=5000 ;"
        "device_error@cycle=5..9:kind=wedge:p=0.5:n=2,"
        "journal_enospc"
    )
    assert p.seed == 9
    hang, dev, jrn = p.rules
    assert (hang.point, hang.lo, hang.hi, hang.ms) == (
        "fetch_hang", 40, 40, 5000.0
    )
    assert (dev.point, dev.lo, dev.hi, dev.kind, dev.prob, dev.count) == (
        "device_error", 5, 9, "wedge", 0.5, 2
    )
    assert (jrn.point, jrn.lo, jrn.count) == ("journal_enospc", None, None)


@pytest.mark.parametrize("bad", [
    "nonsense@cycle=1",            # unknown point
    "fetch_hang@cycle",            # param without value
    "fetch_hang@wat=3",            # unknown param
    "device_error@kind=sideways",  # unknown error kind
    "",                            # no rules at all
    "seed=4",                      # seed only, still no rules
])
def test_fault_plan_parse_refuses_bad_specs(bad):
    with pytest.raises(faults.FaultPlanError):
        faults.FaultPlan.parse(bad)


def test_fault_plan_fires_deterministically():
    def run():
        p = faults.FaultPlan.parse(
            "seed=3;fetch_delay@cycle=1..20:p=0.4:ms=1"
        )
        return [
            cyc for cyc in range(1, 21)
            if p.fire("fetch_delay", cyc) is not None
        ]

    a, b = run(), run()
    assert a == b and 0 < len(a) < 20  # seeded, partial, reproducible


def test_fault_plan_window_count_and_log():
    p = faults.FaultPlan.parse("device_error@cycle=5:kind=corrupt:n=1")
    assert p.fire("device_error", 4) is None   # outside window
    assert p.fire("fetch_hang", 5) is None     # other point
    assert p.fire("device_error", 5) is not None
    assert p.fire("device_error", 5) is None   # count exhausted
    assert p.fired_points() == {"device_error"}
    assert p.log[0]["cycle"] == 5 and p.log[0]["kind"] == "corrupt"


def test_unarmed_hooks_are_dead_branches():
    assert faults.ARMED is False
    assert faults.fire("fetch_hang") is None
    assert faults.skew_s() == 0.0
    assert faults.torn_store() is False
    faults.raise_enospc("cache_enospc")  # no plan: must not raise


def test_injected_device_errors_match_real_classifiers():
    from k8s_scheduler_tpu.core.cycle import classify_failure

    for kind, expect in (
        ("transport", "transport"), ("corrupt", "corrupt"),
        ("wedge", "wedge"),
    ):
        faults.arm(faults.FaultPlan.parse(f"device_error@kind={kind}"))
        with pytest.raises(RuntimeError) as ei:
            faults.raise_device_error()
        assert classify_failure(ei.value) == expect
        faults.disarm()


# ---- degradation ladder (unit) --------------------------------------------


def test_ladder_degrade_promote_and_observability():
    m = SchedulerMetrics()
    ev = EventRecorder()
    obs = CycleObserver(metrics=m)
    lad = DegradationLadder(
        promote_after=2, metrics=m, events=ev, observer=obs
    )
    assert lad.rung == 0 and "degraded" in ANOMALY_CLASSES
    assert lad.degrade("tunnel hung", seq=7) == 1
    assert lad.degrade("still hung") == 2
    # bottom is sticky: further failures re-emit without moving past it
    for _ in range(5):
        lad.degrade("cascade")
    assert lad.rung == len(RUNGS) - 1
    # promotion: one rung per promote_after clean cycles
    for _ in range(2):
        lad.note_clean_cycle()
    assert lad.rung == len(RUNGS) - 2
    st = lad.status()
    assert st["name"] == RUNGS[lad.rung]
    assert st["degradations"] == 7
    # observability: events ring + anomaly ring + counters
    reasons = [e.reason for e in ev.events()]
    assert "Degraded" in reasons and "Promoted" in reasons
    degr = [a for a in obs.anomalies() if a["class"] == "degraded"]
    assert degr and degr[0]["seq"] == 7
    assert degr[0]["detail"]["from_rung"] == "normal"
    assert obs.anomaly_counts["degraded"] == len(lad.transitions)
    # fully recover, then one full episode is measurable
    for _ in range(20):
        lad.note_clean_cycle()
    assert lad.rung == 0
    lad.degrade("again")
    lad.note_clean_cycle()
    lad.note_clean_cycle()
    assert len(lad.recovery_episodes_ms()) == 2


def test_ladder_bottom_rung_failures_report_down_not_up():
    """A degrade() at the sticky bottom rung (old == new) must still
    read as a FAILURE — event reason Degraded, anomaly direction down —
    not as a promotion (the old/new comparison would say 'up')."""
    ev = EventRecorder()
    obs = CycleObserver()
    lad = DegradationLadder(promote_after=2, events=ev, observer=obs)
    for _ in range(len(RUNGS)):  # walk to the bottom...
        lad.degrade("cascade")
    ev.clear()
    lad.degrade("still failing")  # ...and fail AT the bottom
    (bottom_ev,) = ev.events()
    assert bottom_ev.reason == "Degraded"
    assert obs.anomalies()[-1]["detail"]["direction"] == "down"


def test_ladder_floor_pins_promotion():
    """With the floor pinned (the scheduler sets it at `stateless`
    after sealing durability away), clean cycles never promote past it
    — the ladder must not report 'normal' while mutations go
    unjournaled."""
    lad = DegradationLadder(promote_after=1)
    for _ in range(len(RUNGS)):
        lad.degrade("cascade")
    lad.floor = len(RUNGS) - 1
    for _ in range(10):
        lad.note_clean_cycle()
    assert lad.rung == len(RUNGS) - 1
    assert lad.status()["floor"] == len(RUNGS) - 1
    # clearing the floor (a fresh process) lets promotion resume
    lad.floor = 0
    lad.note_clean_cycle()
    assert lad.rung == len(RUNGS) - 2


def test_ladder_sticky_bottom_reapplies_rung_actions():
    """PR 8 ladder finding 1 (ISSUE 11 satellite): a degrade() at the
    sticky bottom rung kept old == new and skipped on_transition, so
    the retrace action was never re-applied under continued failure.
    The hook must fire on every DOWN call, sticky repeats included —
    and promotions must still fire only on a real rung change."""
    calls: list[tuple[int, int]] = []
    lad = DegradationLadder(
        promote_after=1, on_transition=lambda o, n, r: calls.append((o, n))
    )
    bottom = len(RUNGS) - 1
    for _ in range(bottom):
        lad.degrade("cascade")
    assert calls == [(i, i + 1) for i in range(bottom)]
    calls.clear()
    lad.degrade("still failing")  # sticky repeat AT the bottom
    assert calls == [(bottom, bottom)], (
        "sticky-bottom degrade must re-fire on_transition"
    )
    calls.clear()
    lad.note_clean_cycle()  # promotion: exactly one hook call, changed rung
    assert calls == [(bottom, bottom - 1)]


def test_scheduler_sticky_retrace_reclears_program_memos():
    """The scheduler-side half of finding 1: the retrace action (clear
    every program memo) runs again on a sticky-bottom repeat, so an
    executable installed after the last clear cannot survive into the
    next retry."""
    from k8s_scheduler_tpu.core.scheduler import Scheduler

    sched = Scheduler(binder=lambda p, n: None)
    bottom = len(RUNGS) - 1
    sched._packed[("stale-regime", "default-scheduler")] = {"fns": ()}
    sched._mc_fns[("stale-regime", "default-scheduler")] = {"fns": ()}
    sched._dev_stable[("stale", 0, 0)] = (None, None)
    sched._on_rung_transition(bottom, bottom, "still failing")
    assert not sched._packed and not sched._mc_fns
    assert not sched._dev_stable
    # ...and a promotion (new < old) must NOT clear a live regime
    sched._packed[("live-regime", "default-scheduler")] = {"fns": ()}
    sched._on_rung_transition(bottom, bottom - 1, "promoted")
    assert sched._packed


def test_ladder_transitions_are_a_bounded_ring():
    """PR 8 ladder finding 2 (ISSUE 11 satellite): `transitions` grew
    one dict per degrade forever in a long-lived process. It is now a
    bounded ring; the exact lifetime counts ride the counters."""
    from k8s_scheduler_tpu.core.degrade import TRANSITIONS_CAP

    lad = DegradationLadder(promote_after=1)
    n = TRANSITIONS_CAP + 100
    for _ in range(n):
        lad.degrade("storm")
        lad.note_clean_cycle()
    # every degrade and every promotion transitioned; the ring holds
    # only the recent window, the counters stay exact
    assert len(lad.transitions) == TRANSITIONS_CAP
    assert lad.transitions_total > TRANSITIONS_CAP
    assert lad.degradations == n
    st = lad.status()
    assert st["transitions"] == lad.transitions_total
    assert st["transitions_buffered"] == TRANSITIONS_CAP
    # MTTR episodes stay measurable over the buffered window
    assert lad.recovery_episodes_ms()


def test_observer_raise_anomaly_refuses_unknown_class():
    obs = CycleObserver()
    with pytest.raises(ValueError):
        obs.raise_anomaly("not_a_class")


# ---- dispatch watchdog (unit) ---------------------------------------------


def test_fetch_worker_bounds_a_hang_and_recovers():
    from k8s_scheduler_tpu.core.pipeline import (
        DispatchDeadlineExceeded,
        _FetchWorker,
    )

    w = _FetchWorker()
    assert w.run(lambda: 42, deadline_s=5.0) == 42
    t0 = time.perf_counter()
    with pytest.raises(DispatchDeadlineExceeded):
        w.run(lambda: time.sleep(3.0), deadline_s=0.1)
    assert time.perf_counter() - t0 < 1.0  # bounded, not the full hang
    # the wedged worker was abandoned; a fresh one serves the next call
    assert w.run(lambda: "after", deadline_s=5.0) == "after"
    # exceptions inside the bounded call are delivered whole
    def boom():
        raise ValueError("inner")

    with pytest.raises(ValueError, match="inner"):
        w.run(boom, deadline_s=5.0)


# ---- the acceptance scenario: fetch_hang vs dispatchDeadlineMs ------------


def _make_sched(fault_spec: str, deadline_ms: float = 250.0,
                promote: int = 2, binds=None):
    from k8s_scheduler_tpu.config import SchedulerConfiguration
    from k8s_scheduler_tpu.core.scheduler import Scheduler

    cfg = SchedulerConfiguration(
        dispatch_deadline_ms=deadline_ms,
        degrade_promote_cycles=promote,
        fault_spec=fault_spec,
        pod_initial_backoff_seconds=0.01,
        pod_max_backoff_seconds=0.05,
        pad_existing=256, pad_pods_per_node=128,
        speculative_compile=False,
    )
    sink = binds if binds is not None else []
    return Scheduler(config=cfg, binder=lambda p, n: sink.append(p.uid))


def test_fetch_hang_never_blocks_past_deadline_and_ladder_recovers():
    """The ISSUE acceptance criterion: an injected fetch_hang longer
    than dispatchDeadlineMs never blocks the serve loop past the
    deadline — the watchdog fires, the ladder steps down with event +
    anomaly + gauge + degraded /healthz, every pod requeues, and the
    scheduler promotes back to the top rung within N clean cycles."""
    from k8s_scheduler_tpu.cmd.httpserver import staleness_healthz
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    binds: list[str] = []
    sched = _make_sched(
        "fetch_hang@cycle=3:ms=5000:n=1", deadline_ms=250.0, promote=2,
        binds=binds,
    )
    for nd in make_cluster(4):
        sched.on_node_add(nd)
    healthz = staleness_healthz(None, None, 0.0, ladder=sched.ladder)
    added: set[str] = set()
    walls: dict[int, float] = {}
    rung_after: dict[int, int] = {}
    for i in range(1, 8):
        for p in make_pods(3, seed=300 + i, name_prefix=f"a{i}-"):
            sched.on_pod_add(p)
            added.add(p.uid)
        t0 = time.perf_counter()
        sched.schedule_cycle()
        walls[i] = time.perf_counter() - t0
        rung_after[i] = sched.ladder.rung
        if i == 3:
            # degraded right now: /healthz carries the rung (still 200
            # — the ladder is actively recovering)
            ok, detail = healthz()
            assert ok and detail["degraded"] is True
            assert detail["degradation"]["name"] == "retrace"
        time.sleep(0.02)  # let the short backoffs expire
    # cycles 1-2 warm the programs; cycle 3's wall is watchdog-bounded
    # (the 5 s hang never reaches the serve loop; generous margin for a
    # loaded CI box, still far below the hang)
    assert walls[3] < 2.5, walls
    assert rung_after[3] == 1  # stepped down exactly one rung
    # the hang cycle's pods were requeued, retried, and eventually
    # bound: nothing lost, nothing double-bound
    assert set(binds) == added
    assert len(binds) == len(added)
    # promoted back to the top rung within N clean cycles
    assert sched.ladder.rung == 0
    assert sched.ladder.degradations == 1
    assert sched.ladder.recovery_episodes_ms()
    # attribution: metric + events-ring entry + degraded anomaly + gauge
    vals = {}
    for f in sched.metrics.registry.collect():
        for s in f.samples:
            vals[(s.name, tuple(sorted(s.labels.items())))] = s.value
    assert vals[(
        "scheduler_fetch_failures_total",
        (("class", "deadline"),),
    )] == 1.0
    assert vals[("scheduler_degradation_rung", ())] == 0.0
    assert vals[(
        "scheduler_degradation_transitions_total",
        (("from", "normal"), ("to", "retrace")),
    )] == 1.0
    assert any(
        e.reason == "FetchFailed" for e in sched.events.events()
    )
    assert any(
        e.reason in ("Degraded", "Promoted")
        for e in sched.events.events()
    )
    degr = [
        a for a in sched.observer.anomalies() if a["class"] == "degraded"
    ]
    assert len(degr) == 2  # down + up
    # the aborted cycle left a flight record stamped aborted + rung,
    # and the pods' timelines carry the DispatchFailed attempt
    recs = sched.flight.snapshot()
    ab = [r for r in recs if r.counts.get("aborted")]
    assert len(ab) == 1 and ab[0].counts["rung"] == 1
    some_uid = next(iter(added))
    # at least one pod has a DispatchFailed attempt in its timeline
    failed_attempts = [
        a
        for uid in added
        for a in (sched.pod_timeline(uid) or {}).get("attempts", [])
        if a["result"] == "DispatchFailed"
    ]
    assert failed_attempts and some_uid  # attribution reached timelines


def test_wedge_degrades_but_transport_and_corrupt_are_absorbed():
    """device_error routing: transport and corrupt classes are absorbed
    in-cycle by _Resilient (strikes, no rung change); a wedge fails
    fast and steps the ladder."""
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    binds: list[str] = []
    sched = _make_sched(
        "device_error@cycle=3:kind=transport:n=1;"
        "device_error@cycle=4:kind=corrupt:n=1;"
        "device_error@cycle=6:kind=wedge:n=1",
        deadline_ms=0.0,  # no watchdog: this test is about _Resilient
        promote=2,
        binds=binds,
    )
    for nd in make_cluster(4):
        sched.on_node_add(nd)
    added: set[str] = set()
    for i in range(1, 10):
        for p in make_pods(2, seed=600 + i, name_prefix=f"d{i}-"):
            sched.on_pod_add(p)
            added.add(p.uid)
        rung_before = sched.ladder.rung
        sched.schedule_cycle()
        if i in (3, 4):
            # absorbed: the retry recovered inside the cycle
            assert sched.ladder.rung == rung_before == 0, i
        if i == 6:
            assert sched.ladder.rung == 1  # wedge fails fast
        time.sleep(0.02)
    assert set(binds) == added
    assert sched.ladder.degradations == 1
    # wedge_precursor anomalies recorded the absorbed strikes
    assert sched.observer.anomaly_counts["wedge_precursor"] >= 1


def test_sequential_rung_drains_buffered_multicycle_groups():
    """Degrading to the `sequential` rung while multi-cycle groups are
    still coalescing must DRAIN them as single-cycle dispatches — a
    stranded buffer's pods would be neither queued nor in-flight."""
    from k8s_scheduler_tpu.config import SchedulerConfiguration
    from k8s_scheduler_tpu.core.degrade import RUNG_SEQUENTIAL
    from k8s_scheduler_tpu.core.scheduler import Scheduler
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    binds: list[str] = []
    sched = Scheduler(
        config=SchedulerConfiguration(
            multi_cycle_k=4,
            multi_cycle_max_wait_ms=10_000.0,  # only K or idle flushes
            pad_existing=256, pad_pods_per_node=128,
            speculative_compile=False,
        ),
        binder=lambda p, n: binds.append(p.uid),
    )
    for nd in make_cluster(4):
        sched.on_node_add(nd)
    added: set[str] = set()
    for p in make_pods(3, seed=41, name_prefix="b1-"):
        sched.on_pod_add(p)
        added.add(p.uid)
    sched.schedule_cycle()  # group pops and BUFFERS (k=4 not reached)
    assert not binds and any(sched._mc_groups.values())
    while sched.ladder.rung < RUNG_SEQUENTIAL:
        sched.ladder.degrade("forced by test")
    for p in make_pods(2, seed=42, name_prefix="b2-"):
        sched.on_pod_add(p)
        added.add(p.uid)
    stats = sched.schedule_cycle()  # drains the buffer sequentially
    assert not any(sched._mc_groups.values())
    assert set(binds) == added, "buffered pods were stranded"
    assert stats.attempted == len(added)


# ---- journal ENOSPC -> stateless degrade ----------------------------------


def test_journal_enospc_degrades_to_stateless(tmp_path):
    from k8s_scheduler_tpu.internal.cache import SchedulerCache
    from k8s_scheduler_tpu.internal.queue import SchedulingQueue
    from k8s_scheduler_tpu.models import MakePod
    from k8s_scheduler_tpu.state import DurableState, StateError

    faults.arm(faults.FaultPlan.parse("journal_enospc@n=1"))
    st = DurableState(str(tmp_path), snapshot_interval_seconds=0)
    q = SchedulingQueue()
    c = SchedulerCache()
    st.attach(q, c)
    q.add(MakePod("p1").req({"cpu": "1"}).obj())
    with pytest.raises(StateError):
        st.journal.flush(timeout=5.0)  # writer died on the injected fault
    assert st.journal.failed is not None
    # the NEXT mutation detaches the emitters (stateless degrade) and
    # the queue keeps serving
    q.add(MakePod("p2").req({"cpu": "1"}).obj())
    assert q._journal is None and c._journal is None
    assert len(q) == 2


# ---- compile-cache store faults -------------------------------------------


def test_cache_enospc_refuses_store_without_crash(tmp_path):
    from k8s_scheduler_tpu.core.compile_cache import CacheKey, CompileCache

    cc = CompileCache(str(tmp_path))
    key = CacheKey("k|v", "cycle")
    faults.arm(faults.FaultPlan.parse("cache_enospc@n=1"))
    assert cc.store(key, b"payload" * 100) is False  # refused, no raise
    assert cc.load(key) is None  # nothing landed
    # the cache is still writable after the fault clears
    assert cc.store(key, b"payload" * 100) is True
    assert cc.load(key) == b"payload" * 100


def test_cache_torn_store_is_refused_at_load(tmp_path):
    from k8s_scheduler_tpu.core.compile_cache import CacheKey, CompileCache

    cc = CompileCache(str(tmp_path))
    key = CacheKey("k|v", "cycle")
    faults.arm(faults.FaultPlan.parse("cache_torn@n=1"))
    assert cc.store(key, b"\x01\x02" * 512) is False
    # a truncated entry IS on disk at the final path...
    assert os.path.exists(os.path.join(str(tmp_path), key.name))
    # ...and load refuses it loudly instead of crashing or returning
    # garbage; a clean re-store then overwrites it whole
    assert cc.load(key) is None
    faults.disarm()
    assert cc.store(key, b"\x01\x02" * 512) is True
    assert cc.load(key) == b"\x01\x02" * 512


# ---- /debug/state + ladder surfacing --------------------------------------


def test_debug_state_and_healthz_carry_the_rung(tmp_path):
    from k8s_scheduler_tpu.cmd.httpserver import staleness_healthz
    from k8s_scheduler_tpu.state import DurableState

    lad = DegradationLadder(promote_after=4)
    st = DurableState(str(tmp_path), snapshot_interval_seconds=0)
    st.degradation = lad
    assert st.status()["degradation"]["rung"] == 0
    lad.degrade("testing")
    assert st.status()["degradation"]["name"] == "retrace"
    healthz = staleness_healthz(None, None, 0.0, ladder=lad)
    ok, detail = healthz()
    assert ok  # degraded is a paging signal, not a liveness failure
    assert detail["degraded"] is True
    assert "retrace" in detail["degraded_reason"]
    st.journal.close()


# ---- chaos soak smoke (slow tier) -----------------------------------------


def _load_soak_chaos():
    path = (
        pathlib.Path(__file__).parent.parent / "scripts" / "soak_chaos.py"
    )
    spec = importlib.util.spec_from_file_location("soak_chaos", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_soak_chaos_smoke(tmp_path):
    """Smoke subset of scripts/soak_chaos.py: a short plan in which
    every fault class fires once (serve + enospc phases in-process,
    the kill -9 crash phase as a subprocess), all invariants asserted
    by the phases themselves."""
    soak = _load_soak_chaos()
    serve = soak.run_serve_phase(
        cycles=30, cache_dir=str(tmp_path / "cc"), verbose=False
    )
    assert serve["bound"] == serve["added"]
    assert serve["mttr_ms"] > 0
    assert serve["degraded_cycles"] > 0
    enospc = soak.run_enospc_phase(str(tmp_path / "en"), verbose=False)
    assert enospc["journal_failed"]
    crash = soak.run_crash_phase(str(tmp_path / "cr"), verbose=False)
    assert crash["digest_matched"] and crash["restored_rung"] == 0


@pytest.mark.slow
def test_bench_fault_storm_reports_mttr(tmp_path):
    """Bench config 7 (fault_storm) end-to-end: the artifact carries
    mttr_ms/degraded_cycles and bench_diff gates them directionally."""
    import bench_suite

    r = bench_suite.run_fault_storm_config(snapshots=28)
    assert r["config"] == 7 and r["name"] == "fault_storm"
    assert r["mttr_ms"] > 0 and r["degraded_cycles"] > 0
    assert r["max_blocked_ms"] < r["deadline_ms"] * 4
    # bench_diff: identical artifacts diff clean; a slower recovery and
    # more degraded cycles regress
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(r))
    worse = dict(r)
    worse["mttr_ms"] = r["mttr_ms"] * 2.5
    worse["degraded_cycles"] = r["degraded_cycles"] + 5
    new.write_text(json.dumps(worse))
    diff = os.path.join(REPO, "scripts", "bench_diff.py")
    same = subprocess.run(
        [sys.executable, diff, str(old), str(old)],
        capture_output=True, text=True,
    )
    assert same.returncode == 0, same.stdout + same.stderr
    reg = subprocess.run(
        [sys.executable, diff, "--json", str(old), str(new)],
        capture_output=True, text=True,
    )
    assert reg.returncode == 1
    out = json.loads(reg.stdout)
    regressed = {c["metric"] for c in out["regressions"]}
    assert {"mttr_ms", "degraded_cycles"} <= regressed
