"""The carry-based latency path must schedule identically to the classic
packed rounds cycle, and the diagnosis program must attribute reasons for
EVERY unplaced pod (VERDICT r2 item 5 — no blank reasons, ever).
"""

import numpy as np
import pytest

from k8s_scheduler_tpu.core import (
    build_carry_fns,
    build_diagnosis_fn,
    build_packed_cycle_carry_fn,
    build_packed_cycle_fn,
    build_stable_state_fn,
)
from k8s_scheduler_tpu.framework.runtime import Framework
from k8s_scheduler_tpu.models import MakePod, SnapshotEncoder
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods


def drive_carry(enc, nodes, pending, existing, carry_state):
    w, b, spec, snap, dirty = enc.encode_packed(nodes, pending, existing)
    key = spec.key()
    if carry_state.get("key") != key:
        carry_state.clear()
        carry_state["key"] = key
        carry_state["cycle"] = build_packed_cycle_carry_fn(spec)
        carry_state["plain"] = build_packed_cycle_fn(
            spec, commit_mode="rounds"
        )
        carry_state["stable_fn"] = build_stable_state_fn(spec)
        ci, cu = build_carry_fns(spec)
        carry_state["ci"], carry_state["cu"] = ci, cu
        dirty = None
    stable = carry_state["stable_fn"](w, b)
    if dirty is None or "carry" not in carry_state:
        carry_state["carry"] = carry_state["ci"](w, b, stable)
    elif len(dirty):
        bucket = max(8, 1 << int(len(dirty) - 1).bit_length())
        idx = np.full(bucket, dirty[0], np.int32)
        idx[: len(dirty)] = dirty
        carry_state["carry"] = carry_state["cu"](bucket)(
            w, b, stable, carry_state["carry"], idx
        )
    out_c = carry_state["cycle"](w, b, stable, carry_state["carry"])
    out_p = carry_state["plain"](w, b, stable)
    return w, b, spec, stable, out_c, out_p


def test_carry_cycle_matches_plain_over_churn():
    rng = np.random.default_rng(1)
    nodes = make_cluster(10)
    enc = SnapshotEncoder(pad_pods=128, pad_nodes=16)
    pending = make_pods(
        70, seed=1, affinity_fraction=0.3, anti_affinity_fraction=0.2,
        spread_fraction=0.2, selector_fraction=0.3, num_apps=6,
        priorities=(0, 10),
    )
    existing = [(p, f"node-{i % 10}") for i, p in enumerate(
        make_pods(20, seed=2, name_prefix="run", affinity_fraction=0.2,
                  num_apps=6)
    )]
    st = {}
    for i in range(6):
        idx = rng.choice(len(pending), size=18, replace=False)
        fresh = make_pods(
            18, seed=50 + i, name_prefix=f"c{i}-", affinity_fraction=0.3,
            spread_fraction=0.2, selector_fraction=0.3, num_apps=6,
            priorities=(0, 10),
        )
        for j, f in zip(idx, fresh):
            pending[j] = f
        _w, _b, _spec, _stable, out_c, out_p = drive_carry(
            enc, nodes, pending, existing, st
        )
        assert np.array_equal(
            np.asarray(out_c.assignment), np.asarray(out_p.assignment)
        ), f"iteration {i}: carry assignment diverged"
        assert np.array_equal(
            np.asarray(out_c.unschedulable), np.asarray(out_p.unschedulable)
        )


def test_diagnosis_attributes_every_unplaced_pod():
    # 50 pods demand a label no node has -> all unschedulable via
    # NodeAffinity; window=8 forces the diagnosis loop to iterate
    nodes = make_cluster(4)
    enc = SnapshotEncoder(pad_pods=64, pad_nodes=8)
    pods = [
        MakePod(f"p{i}").req({"cpu": "100m"})
        .node_selector({"no-such-label": "x"}).created(float(i)).obj()
        for i in range(50)
    ]
    w, b, spec, snap, _ = enc.encode_packed(nodes, pods)
    stable = build_stable_state_fn(spec)(w, b)
    ci, _cu = build_carry_fns(spec)
    carry = ci(w, b, stable)
    out = build_packed_cycle_carry_fn(spec)(w, b, stable, carry)
    assert int(np.asarray(out.unschedulable).sum()) == 50
    diag = build_diagnosis_fn(spec, window=8)
    rej = np.asarray(
        diag(w, b, stable, out.assignment, out.node_requested)
    )
    fw = Framework.from_config()
    col = fw.filter_names.index("NodeAffinity")
    unplaced = np.asarray(out.unschedulable)
    # EVERY unplaced pod gets a nonzero attribution row, and the
    # first-rejector is NodeAffinity on all real nodes
    assert (rej[unplaced].sum(axis=1) > 0).all()
    assert (rej[unplaced][:, col] == 4).all()
    # placed/padding rows stay zero
    assert (rej[~unplaced] == 0).all()


if __name__ == "__main__":
    import sys

    pytest.main([__file__, "-v"] + sys.argv[1:])
