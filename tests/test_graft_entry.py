import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import __graft_entry__ as ge  # noqa: E402


def test_entry_compiles_and_runs():
    import jax

    fn, args = ge.entry()
    result = jax.jit(fn)(*args)
    a = np.asarray(result.assignment)
    assert (a[:8] >= 0).all()  # tiny cluster has room for all 8 pods


def test_dryrun_multichip_8():
    """Was xfail from the seed through PR 9: the 2-D (pods=4, nodes=2)
    mesh diverged at contention scale. ISSUE 10 root-caused it — not
    reduce tie ordering alone, but an SPMD partitioner miscompilation
    of axis-0 concatenate over the sharded axis on multi-axis meshes
    (values multiplied by the free-axis size inside the guard sweep;
    minimal repro in tests/test_shard_invariance.py) — and fixed both:
    stack+reshape table builds plus shard-invariant argmax/top_k
    (ops/argsel.py). Sharded == replicated now holds bit-identically in
    both commit modes; this run also audits the compiled carry cycle
    for [P,N]-scale collectives."""
    ge.dryrun_multichip(8)


def test_dryrun_multichip_2():
    ge.dryrun_multichip(2)


def test_2d_mesh_sharded_cycle_with_affinity():
    """Full feature set (affinity + spread + taints) compiled and executed
    over a 2-D ('pods','nodes') mesh."""
    import jax
    import numpy as np

    from k8s_scheduler_tpu.core import build_cycle_fn
    from k8s_scheduler_tpu.models import SnapshotEncoder
    from k8s_scheduler_tpu.parallel import make_mesh, shard_snapshot
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    mesh = make_mesh(jax.devices()[:8], nodes_axis=2)
    nodes = make_cluster(8, with_labels=True, taint_fraction=0.2)
    pods = make_pods(
        16, affinity_fraction=0.3, anti_affinity_fraction=0.3,
        toleration_fraction=0.5, selector_fraction=0.3, spread_fraction=0.4,
    )
    existing = [(p, nodes[i % 8].name) for i, p in enumerate(
        make_pods(6, seed=9, name_prefix="exist", anti_affinity_fraction=0.5)
    )]
    snap = SnapshotEncoder(pad_pods=16, pad_nodes=8).encode(nodes, pods, existing)
    assert snap.has_topology_spread and snap.has_inter_pod_affinity
    snap = shard_snapshot(snap, mesh)
    r = build_cycle_fn()(snap)
    a = np.asarray(r.assignment)
    assert a.shape == (16,)
    assert (a >= -1).all()
