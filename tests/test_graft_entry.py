import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import __graft_entry__ as ge  # noqa: E402


def test_entry_compiles_and_runs():
    import jax

    fn, args = ge.entry()
    result = jax.jit(fn)(*args)
    a = np.asarray(result.assignment)
    assert (a[:8] >= 0).all()  # tiny cluster has room for all 8 pods


def test_dryrun_multichip_8():
    ge.dryrun_multichip(8)


def test_dryrun_multichip_2():
    ge.dryrun_multichip(2)
