"""Durable-state layer: journal framing, torn-tail crash consistency,
segment rotation/cut semantics, format versioning, codec round-trips,
and the no-fsync-on-the-append-path contract (state/ package)."""

import os
import struct
import threading
import zlib

import pytest

from k8s_scheduler_tpu.internal.cache import SchedulerCache
from k8s_scheduler_tpu.internal.queue import SchedulingQueue
from k8s_scheduler_tpu.models import MakeNode, MakePod
from k8s_scheduler_tpu.state import (
    DurableState,
    Journal,
    StateVersionError,
    replay_dir,
)
from k8s_scheduler_tpu.state.journal import (
    FORMAT_VERSION,
    encode_record,
    read_segment,
    segment_header,
    segment_indices,
    segment_path,
)


def _drain(journal):
    journal.flush()
    journal.close()


def test_journal_round_trip(tmp_path):
    d = str(tmp_path)
    j = Journal(d)
    recs = [("q.add", 1.5, {"pod": {"m": {"n": f"p{i}"}}}) for i in range(8)]
    for op, t, data in recs:
        j.append(op, t, data)
    _drain(j)
    assert list(replay_dir(d)) == recs


def test_torn_final_record_discarded_at_every_byte_offset(tmp_path):
    """The crash-consistency core claim: truncate the segment at EVERY
    byte offset inside the final record; replay must never raise and
    must yield exactly the records before it — a torn record is
    discarded whole, never partially applied."""
    d = str(tmp_path / "src")
    j = Journal(d)
    for i in range(5):
        j.append("q.add", float(i), {"pod": {"m": {"n": f"pod-{i}"}}})
    _drain(j)
    (idx,) = segment_indices(d)
    blob = open(segment_path(d, idx), "rb").read()
    final = encode_record("q.add", 4.0, {"pod": {"m": {"n": "pod-4"}}})
    body_end = len(blob)
    body_start = body_end - len(final)
    tdir = str(tmp_path / "torn")
    os.makedirs(tdir)
    tpath = segment_path(tdir, 0)
    for cut in range(body_start, body_end):
        with open(tpath, "wb") as f:
            f.write(blob[:cut])
        got = list(read_segment(tpath))
        assert len(got) == 4, f"cut at byte {cut}"
        assert [r[2]["pod"]["m"]["n"] for r in got] == [
            f"pod-{i}" for i in range(4)
        ]
    # untouched file yields all 5
    with open(tpath, "wb") as f:
        f.write(blob)
    assert len(list(read_segment(tpath))) == 5


def test_mid_segment_corruption_raises_not_truncates(tmp_path):
    """A bad record FOLLOWED BY MORE BYTES is not a crash tear (tears
    can only sit at EOF — every batch is fsynced before ack): replaying
    past a hole would silently diverge, so it must raise."""
    from k8s_scheduler_tpu.state import StateCorruption

    d = str(tmp_path)
    j = Journal(d)
    for i in range(5):
        j.append("q.add", float(i), {"pod": {"m": {"n": f"pod-{i}"}}})
    _drain(j)
    (idx,) = segment_indices(d)
    p = segment_path(d, idx)
    blob = bytearray(open(p, "rb").read())
    # flip one payload byte of the FIRST record (well before EOF)
    first = encode_record("q.add", 0.0, {"pod": {"m": {"n": "pod-0"}}})
    header_len = len(segment_header())
    blob[header_len + 8 + 2] ^= 0xFF
    with open(p, "wb") as f:
        f.write(blob)
    with pytest.raises(StateCorruption, match="mid-segment"):
        list(read_segment(p))
    assert len(first) > 8  # framing sanity for the offset above


def test_torn_segment_header_is_empty_not_error(tmp_path):
    p = segment_path(str(tmp_path), 0)
    header = segment_header()
    for cut in range(len(header)):
        with open(p, "wb") as f:
            f.write(header[:cut])
        assert list(read_segment(p)) == []


def test_future_format_version_refused(tmp_path):
    """A segment stamped by a NEWER build must fail loudly, not be
    misparsed into garbage state."""
    p = segment_path(str(tmp_path), 0)
    body = struct.pack("<8sI", b"TPUSWAL\x00", FORMAT_VERSION + 1)
    with open(p, "wb") as f:
        f.write(body + struct.pack("<I", zlib.crc32(body)))
        f.write(encode_record("q.pop", 0.0, {}))
    with pytest.raises(StateVersionError) as ei:
        list(read_segment(p))
    assert "newer than this build" in str(ei.value)
    # and the manager surfaces it on restore, not silently
    q, c = SchedulingQueue(), SchedulerCache()
    st = DurableState(str(tmp_path / "other"), snapshot_interval_seconds=0)
    st.restore_into(q, c)  # empty dir restores fine
    with pytest.raises(StateVersionError):
        list(replay_dir(str(tmp_path)))


def test_future_snapshot_version_refused(tmp_path):
    from k8s_scheduler_tpu.state.snapshot import (
        SNAPSHOT_MAGIC,
        read_snapshot,
        snapshot_path,
    )

    p = snapshot_path(str(tmp_path), 0)
    body = b"{}"
    with open(p, "wb") as f:
        f.write(
            struct.pack(
                "<8sIII", SNAPSHOT_MAGIC, FORMAT_VERSION + 1,
                zlib.crc32(body), len(body),
            )
        )
        f.write(body)
    with pytest.raises(StateVersionError):
        read_snapshot(p)


def test_segment_rotation_and_cut(tmp_path):
    d = str(tmp_path)
    j = Journal(d, max_segment_bytes=256)
    for i in range(20):
        j.append("q.add", float(i), {"pod": {"m": {"n": f"p{i:02d}"}}})
        if i % 5 == 4:
            # size rotation takes effect at group-commit granularity
            # (the writer checks real bytes after each drained batch)
            j.flush()
    assert len(segment_indices(d)) > 1  # size rotation happened
    # cut: everything after lands strictly in segments >= the cut index
    cut = j.cut()
    for i in range(20, 25):
        j.append("q.add", float(i), {"pod": {"m": {"n": f"p{i:02d}"}}})
    _drain(j)
    pre = [r[2]["pod"]["m"]["n"] for r in replay_dir(d) ]
    assert pre == [f"p{i:02d}" for i in range(25)]  # order preserved
    tail = [r[2]["pod"]["m"]["n"] for r in replay_dir(d, from_index=cut)]
    assert tail == [f"p{i:02d}" for i in range(20, 25)]
    # prune below the cut: only the tail remains
    j2 = Journal(d)
    j2.prune(cut)
    j2.close()
    assert [r[2]["pod"]["m"]["n"] for r in replay_dir(d)] == tail


def test_append_path_never_fsyncs_caller_thread(tmp_path, monkeypatch):
    """The ISSUE acceptance contract: group fsync lives on the writer
    thread only — mutations on the scheduling thread (the bind path)
    must never block on fsync."""
    fsync_threads = []
    real_fsync = os.fsync

    def spy(fd):
        fsync_threads.append(threading.current_thread().name)
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", spy)
    q = SchedulingQueue()
    c = SchedulerCache()
    st = DurableState(str(tmp_path), snapshot_interval_seconds=0)
    st.attach(q, c)
    for i in range(50):
        q.add(MakePod(f"p{i}").req({"cpu": "1"}).obj())
        c.add_node(MakeNode(f"n{i}").capacity({"cpu": "8"}).obj())
    pods = q.pop_ready()
    for p in pods[:10]:
        c.assume(p, "n0")
        c.finish_binding(p.uid)
    st.journal.flush()
    assert fsync_threads, "writer thread never fsynced"
    assert set(fsync_threads) == {"journal-writer"}
    st.journal.close()


def test_codec_round_trips_rich_pod_and_node():
    from k8s_scheduler_tpu.models.api import pod_from_dict
    from k8s_scheduler_tpu.state.codec import (
        node_from_state,
        node_to_state,
        pod_from_state,
        pod_to_state,
    )

    pod = pod_from_dict(
        {
            "metadata": {
                "name": "rich",
                "namespace": "ns1",
                "uid": "u-1",
                "labels": {"app": "db", "tier": "backend"},
                "annotations": {"k": "v"},
                "creationTimestamp": 12.5,
            },
            "spec": {
                "containers": [
                    {
                        "name": "main",
                        "image": "img:1",
                        "resources": {
                            "requests": {"cpu": "1500m", "memory": "2Gi"}
                        },
                        "ports": [{"containerPort": 80, "hostPort": 8080}],
                    }
                ],
                "nodeSelector": {"disk": "ssd"},
                "affinity": {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {
                                    "matchExpressions": [
                                        {
                                            "key": "zone",
                                            "operator": "In",
                                            "values": ["a", "b"],
                                        }
                                    ]
                                }
                            ]
                        }
                    },
                    "podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "labelSelector": {
                                    "matchLabels": {"app": "db"}
                                },
                                "topologyKey": "kubernetes.io/hostname",
                            }
                        ]
                    },
                },
                "tolerations": [
                    {"key": "gpu", "operator": "Exists",
                     "effect": "NoSchedule"}
                ],
                "topologySpreadConstraints": [
                    {
                        "maxSkew": 1,
                        "topologyKey": "zone",
                        "whenUnsatisfiable": "DoNotSchedule",
                        "labelSelector": {"matchLabels": {"app": "db"}},
                    }
                ],
                "priority": 100,
                "priorityClassName": "high",
                "preemptionPolicy": "Never",
                "schedulerName": "tpu-scheduler",
                "podGroup": "g1",
            },
            "status": {"nominatedNodeName": "n7"},
        }
    )
    assert pod_from_state(pod_to_state(pod)) == pod

    node = (
        MakeNode("n1")
        .labels({"zone": "a"})
        .capacity({"cpu": "64", "memory": "128Gi"})
        .taint("dedicated", "db", "NoSchedule")
        .obj()
    )
    assert node_from_state(node_to_state(node)) == node


def test_restart_never_appends_into_old_segment(tmp_path):
    """A restarted process opens a fresh segment past everything on
    disk (old tails may be torn); replay glues them in order."""
    d = str(tmp_path)
    j1 = Journal(d)
    j1.append("q.add", 0.0, {"pod": {"m": {"n": "a"}}})
    _drain(j1)
    j2 = Journal(d)
    j2.append("q.add", 1.0, {"pod": {"m": {"n": "b"}}})
    _drain(j2)
    assert len(segment_indices(d)) == 2
    assert [r[2]["pod"]["m"]["n"] for r in replay_dir(d)] == ["a", "b"]


def test_writer_io_failure_fails_loudly_not_silently(tmp_path):
    """A dead disk must not leave append() buffering into a deque
    nobody drains: the writer marks the journal failed, flush() and
    append() raise, close() still joins."""
    import shutil

    from k8s_scheduler_tpu.state import StateError

    d = str(tmp_path / "j")
    j = Journal(d)
    shutil.rmtree(d)  # the writer's next segment open() will fail
    j.append("q.pop", 0.0, {})
    with pytest.raises(StateError, match="writer failed"):
        j.flush()
    assert j.failed is not None
    assert j.status()["failed"] is not None
    with pytest.raises(StateError, match="writer failed"):
        j.append("q.pop", 1.0, {})
    j.close()  # no hang, no raise


def test_manager_degrades_to_stateless_on_journal_failure(tmp_path):
    """DurableState must trade durability for availability: when the
    journal dies mid-run, emitters detach and the scheduler keeps
    mutating state untouched."""
    import shutil

    d = str(tmp_path / "state")
    q, c = SchedulingQueue(), SchedulerCache()
    st = DurableState(d, snapshot_interval_seconds=0)
    st.attach(q, c)
    q.add(MakePod("before").obj())
    st.journal.flush()
    shutil.rmtree(d)
    # POSIX keeps the already-open segment fd writable after the unlink;
    # force a segment switch so the writer must open() in the gone dir
    st.journal.cut()
    q.add(MakePod("buffered").obj())  # buffered; writer dies async
    deadline = __import__("time").monotonic() + 10
    while st.journal.failed is None:
        assert __import__("time").monotonic() < deadline
        __import__("time").sleep(0.01)
    # the NEXT emit hits the failure, detaches, and does not raise
    q.add(MakePod("after-failure").obj())
    assert q._journal is None and c._journal is None
    assert st.status()["sealed"]
    # serving continues: mutations still land in live state
    q.add(MakePod("still-serving").obj())
    assert q.pending_counts()["active"] == 4
    st.journal.close()


# ---- batch group-append (the vectorized apply/bind fold's record) ------


class _Clock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> None:
        self.t += dt


def _pair(clock):
    q = SchedulingQueue(
        initial_backoff_seconds=0.5, max_backoff_seconds=4.0,
        unschedulable_timeout_seconds=30.0, now=clock,
    )
    c = SchedulerCache(assumed_pod_ttl_seconds=2.0, now=clock)
    return q, c


def _drive_fold_trace(state_dir, *, batched, seed=11, n=60):
    """A randomized mutation trace shaped like the apply/bind fold:
    adds, pops, assumes/binds, backoff requeues — journaled either as
    singles or with each chunk grouped under DurableState.batch()."""
    import contextlib
    import random

    from k8s_scheduler_tpu.state import state_digest

    clock = _Clock()
    q, c = _pair(clock)
    st = DurableState(state_dir, snapshot_interval_seconds=0)
    st.attach(q, c)
    c.add_node(MakeNode("n0").capacity({"cpu": "64"}).obj())
    rng = random.Random(seed)
    uid = 0
    for _cycle in range(n):
        clock.tick(rng.random())
        scope = st.batch() if batched else contextlib.nullcontext()
        with scope:
            for _ in range(rng.randint(1, 5)):
                roll = rng.random()
                if roll < 0.5 or uid == 0:
                    pod = MakePod(f"p{uid}").req({"cpu": "1"}).obj()
                    uid += 1
                    q.add(pod)
                elif roll < 0.75:
                    e = q.pop_ready()
                    if e:
                        c.assume(e[0], "n0")
                        c.finish_binding(e[0].uid)
                else:
                    e = q.pop_ready()
                    if e:
                        q.requeue_backoff(e[0])
    st.journal.flush()
    digest = state_digest(q, c)
    st.journal.close()
    return digest


def test_batch_record_digest_identical_to_singles(tmp_path):
    """The group-append contract: the SAME randomized mutation trace
    journaled as one batch record per cycle vs N single records
    restores to a bit-identical state digest — each sub-op replays
    under its own clock value, so nothing (backoff expiries, assumed
    deadlines, tier order) can drift."""
    from k8s_scheduler_tpu.state import state_digest
    from k8s_scheduler_tpu.state.journal import BATCH_OP

    da, db = str(tmp_path / "singles"), str(tmp_path / "batched")
    live_a = _drive_fold_trace(da, batched=False)
    live_b = _drive_fold_trace(db, batched=True)
    assert live_a == live_b  # identical trace: journaling is a shadow

    ops_a = [op for op, _t, _d in replay_dir(da)]
    ops_b = [op for op, _t, _d in replay_dir(db)]
    assert BATCH_OP not in ops_a
    assert BATCH_OP in ops_b          # the variant actually folded
    assert len(ops_b) < len(ops_a)    # fewer records, same state

    for d in (da, db):
        q2 = SchedulingQueue(
            initial_backoff_seconds=0.5, max_backoff_seconds=4.0,
            unschedulable_timeout_seconds=30.0, now=_Clock(),
        )
        c2 = SchedulerCache(assumed_pod_ttl_seconds=2.0, now=_Clock())
        DurableState(d, snapshot_interval_seconds=0).restore_into(q2, c2)
        assert state_digest(q2, c2) == live_a, d


def test_torn_tail_batch_record_discarded_whole(tmp_path):
    """Crash atomicity at batch granularity: truncate the segment at
    EVERY byte offset inside a final BATCH record — replay must yield
    exactly the records before it, never a partially-applied prefix of
    the cycle's fold (the batch is one frame under one CRC)."""
    from k8s_scheduler_tpu.state.journal import (
        BATCH_OP,
        encode_batch_payload,
    )

    d = str(tmp_path / "src")
    j = Journal(d)
    for i in range(3):
        j.append("q.add", float(i), {"pod": {"m": {"n": f"pod-{i}"}}})
    sub_ops = [
        ("c.assume", 3.0 + k, {"uid": f"default/pod-{k}", "node": "n0"})
        for k in range(4)
    ]
    payload = encode_batch_payload(sub_ops)
    j.append(BATCH_OP, 6.0, payload)
    j.flush()
    j.close()
    (idx,) = segment_indices(d)
    blob = open(segment_path(d, idx), "rb").read()
    final = encode_record(BATCH_OP, 6.0, payload)
    body_start = len(blob) - len(final)
    assert blob[body_start:] == final  # framing sanity
    tdir = str(tmp_path / "torn")
    os.makedirs(tdir)
    tpath = segment_path(tdir, 0)
    for cut in range(body_start, len(blob)):
        with open(tpath, "wb") as f:
            f.write(blob[:cut])
        got = list(read_segment(tpath))
        assert [r[0] for r in got] == ["q.add"] * 3, f"cut at byte {cut}"
    with open(tpath, "wb") as f:
        f.write(blob)
    assert [r[0] for r in list(read_segment(tpath))][-1] == BATCH_OP


def test_open_batch_is_invisible_until_scope_exit(tmp_path):
    """kill -9 mid-flush: a batch scope that never exits contributes
    NOTHING durable — the segment bytes captured while the scope is
    open restore to the exact pre-batch state (the fold becomes
    durable atomically at scope exit, or not at all)."""
    import shutil

    from k8s_scheduler_tpu.state import state_digest

    d = str(tmp_path / "live")
    clock = _Clock()
    q, c = _pair(clock)
    st = DurableState(d, snapshot_interval_seconds=0)
    st.attach(q, c)
    q.add(MakePod("before").req({"cpu": "1"}).obj())
    st.journal.flush()
    pre = state_digest(q, c)
    (idx,) = segment_indices(d)

    mid = str(tmp_path / "mid")
    post = str(tmp_path / "post")
    with st.batch():
        q.add(MakePod("in-batch-1").req({"cpu": "1"}).obj())
        q.add(MakePod("in-batch-2").req({"cpu": "1"}).obj())
        # the crash point: nothing of the open batch may be on disk
        st.journal.flush()
        os.makedirs(mid)
        shutil.copy(segment_path(d, idx), segment_path(mid, idx))
    st.journal.flush()
    os.makedirs(post)
    shutil.copy(segment_path(d, idx), segment_path(post, idx))
    st.journal.close()

    q2, c2 = _pair(_Clock())
    DurableState(mid, snapshot_interval_seconds=0).restore_into(q2, c2)
    assert state_digest(q2, c2) == pre
    q3, c3 = _pair(_Clock())
    DurableState(post, snapshot_interval_seconds=0).restore_into(q3, c3)
    assert state_digest(q3, c3) == state_digest(q, c)


def test_debug_state_status_shape(tmp_path):
    q, c = SchedulingQueue(), SchedulerCache()
    st = DurableState(str(tmp_path), snapshot_interval_seconds=0)
    st.attach(q, c)
    q.add(MakePod("p").obj())
    st.journal.flush()
    s = st.status()
    assert s["journal"]["appended"] == 1
    assert s["journal"]["durable"] == 1
    assert s["journal"]["segments"] == 1
    assert s["last_restore"]["records_replayed"] == 0
    st.snapshot()
    s = st.status()
    assert s["last_snapshot"]["bytes"] > 0
    st.seal()
    assert st.status()["sealed"]
