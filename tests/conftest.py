"""Test environment: force an 8-device virtual CPU mesh.

This environment's sitecustomize imports jax and registers the axon TPU
PJRT plugin at interpreter start, so env vars are already baked into
jax.config by the time pytest runs — `jax.config.update` (not os.environ)
is the only switch that still works here. Tests must never touch the real
TPU tunnel (single chip, slow first-compile); multi-chip sharding is
exercised on the virtual CPU mesh instead, as the driver does via
`__graft_entry__.dryrun_multichip`.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)
