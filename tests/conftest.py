"""Test environment: force an 8-device virtual CPU mesh.

This environment's sitecustomize imports jax and registers the axon TPU
PJRT plugin at interpreter start, so env vars are already baked into
jax.config by the time pytest runs — `jax.config.update` (not os.environ)
is the only switch that still works here. Tests must never touch the real
TPU tunnel (single chip, slow first-compile); multi-chip sharding is
exercised on the virtual CPU mesh instead, as the driver does via
`__graft_entry__.dryrun_multichip`.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

from k8s_scheduler_tpu.utils.compilation_cache import (  # noqa: E402
    enable_compilation_cache,
)

enable_compilation_cache()

import pytest  # noqa: E402

# Tests measured >8s (compile-bound integration tests; `--durations`
# re-survey when this list drifts). The fast tier skips them:
#   python -m pytest tests/ -q -m "not slow"
_SLOW_TESTS = {
    "test_packed_cycle_matches_unpacked",
    "test_carry_cycle_matches_plain_over_churn",
    "test_stable_state_injection_matches",
    "test_profile_cycle_fills_per_plugin_histograms",
    "test_stable_state_reused_across_pending_changes",
    "test_rounds_deterministic",
    "test_extender_error_nonignorable_backoff",
    "test_rounds_throughput_close_to_scan",
    "test_bind_error_and_unschedulable_results",
    "test_gang_drop_reason_is_coscheduling",
    "test_rounds_validity_on_mixed_workload",
    "test_dryrun_multichip_2",
    "test_rounds_validity_with_existing_pods",
    "test_profiles_place_identical_pods_differently",
    "test_scheduled_event_and_reason_metric",
    "test_extender_filter_and_bind_delegation",
    "test_rounds_affinity_bootstrap_and_colocation",
    "test_host_plugin_lifecycle_order",
    "test_scheduler_sequential_cycles_respect_capacity",
    "test_scheduler_end_to_end_bind",
    "test_scheduler_preemption_flow",
    "test_volume_binding_over_the_wire",
    "test_scheduler_node_delete_requeues",
    "test_scheduler_gang_requeue",
    # durable-state failover tests that spawn jax-importing subprocesses
    "test_kill9_failover_digest_matches_pre_kill",
    "test_soak_failover_smoke",
    # multi-cycle heavyweights: the 3-seed scheduler-level equivalence
    # drive (~40 s/seed: two full Schedulers + WAL per seed), the
    # 15-cycle burst/lull trace, and the bench K-sweeps (wall-clock
    # perf bounds — kept out of the functional tier so machine load
    # can't flake it; the device-level equivalence cases stay fast)
    "test_scheduler_multicycle_matches_sequential",
    "test_mixed_burst_lull_traffic_no_false_fold_miss",
    "test_bench_multicycle_sweep_amortizes_dispatch",
    "test_bench_multicycle_sweep_respects_envelope",
    # compile-regime management end-to-end proofs (ISSUE 8): each
    # drives real Schedulers through cold XLA compiles of whole
    # program sets (warm-restart zero-cold-compile, speculation-won
    # flip, and the three-phase regime_churn bench soak)
    "test_warm_restart_compiles_zero_programs",
    "test_speculative_precompile_wins_the_flip",
    "test_regime_churn_soak_zero_compile_stalls",
    # scenario-fuzzer live differential smoke (ISSUE 11): each case is
    # a full trace replay through a fresh Scheduler (engine compile) —
    # and for the differential cases a second, oracle-side replay. The
    # corpus replays and shrinker units stay fast-tier: minimal-repro
    # traces compile tiny programs the persistent cache keeps warm.
    "test_fuzz_differential_plain_seed",
    "test_fuzz_differential_multicycle_seed",
    "test_fuzz_differential_sharded_seed",
    "test_fuzz_chaos_seed",
    "test_fuzz_catches_seeded_tiebreak_bug",
    "test_corpus_repro_still_catches_its_bug",
    "test_fuzz_soak_smoke",
    # depth-2 speculative dispatch (ISSUE 13) heavyweights: the
    # 3-scheduler equivalence ladder and the 2-scheduler mismatch
    # drive (~40 s of Scheduler+WAL each), the speculative fuzz
    # differential (TWO engine replays per trace), the chaos
    # mid-speculation replay (a real 15 s injected hang bounded by
    # the watchdog), and the scheduler-driven bench sweep point —
    # the device-level chain/pipeline/record/sentinel cases stay fast
    "test_scheduler_speculative_matches_sequential",
    "test_mismatch_abandons_redispatches_bit_identical",
    "test_fuzz_differential_speculative_seed",
    "test_fuzz_chaos_fetch_hang_mid_speculation",
    "test_bench_sweep_reports_first_bind_and_hit_rate",
    # admission-time incremental encode (ISSUE 16) heavyweights: the
    # incremental fuzz differential (TWO engine replays per trace,
    # same class as its sibling seeds above) and the two table-growth
    # drives (each compiles a fresh K=4 packed program set) — the
    # journal batch-record and bench_diff gate cases stay fast
    "test_fuzz_differential_incremental_seed",
    "test_multicycle_table_growth_within_padding_rebinds",
    "test_multicycle_growth_reencode_reuses_interned_entries",
    # tier-1 headroom re-survey (ISSUE 17 --durations audit): the four
    # slowest fast-tier tests, each a compile-bound integration drive
    # (92 s dominance-group claims, 69 s shard-invariance digest, 26 s
    # 8-device dryrun, 23 s randomized preemption differential) — the
    # properties they prove have faster fast-tier siblings
    "test_eight_slot_claims_via_dominance_groups",
    "test_scheduler_shard_devices_bind_stream_and_digest_invariant",
    "test_dryrun_multichip_8",
    "test_randomized_differential_preemption",
}
_SLOW_MODULES = {"tests.test_concurrency"}


def pytest_collection_modifyitems(config, items):
    for it in items:
        base = it.name.split("[")[0]
        if base in _SLOW_TESTS or it.module.__name__ in _SLOW_MODULES:
            it.add_marker(pytest.mark.slow)
