"""Host-side extension points (Reserve/Permit/PreBind/PostBind) and the
HTTP scheduler-extender shim (SURVEY.md §2 C10)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from k8s_scheduler_tpu.config import load_config
from k8s_scheduler_tpu.core.scheduler import Scheduler
from k8s_scheduler_tpu.framework.host import HostPlugin
from k8s_scheduler_tpu.models.builders import MakeNode, MakePod


class RecordingPlugin(HostPlugin):
    name = "Recorder"

    def __init__(self):
        self.calls = []

    def reserve(self, pod, node_name):
        self.calls.append(("reserve", pod.name, node_name))
        return None

    def unreserve(self, pod, node_name):
        self.calls.append(("unreserve", pod.name, node_name))

    def permit(self, pod, node_name):
        self.calls.append(("permit", pod.name, node_name))
        return None

    def pre_bind(self, pod, node_name):
        self.calls.append(("pre_bind", pod.name, node_name))
        return None

    def post_bind(self, pod, node_name):
        self.calls.append(("post_bind", pod.name, node_name))


class VetoPlugin(HostPlugin):
    """Out-of-tree plugin that vetoes binds of pods labeled deny=yes."""

    name = "Veto"

    def __init__(self, point="Permit"):
        self.point = point

    def permit(self, pod, node_name):
        if self.point == "Permit" and pod.metadata.labels.get("deny") == "yes":
            return "policy says no"
        return None

    def pre_bind(self, pod, node_name):
        if self.point == "PreBind" and pod.metadata.labels.get("deny") == "yes":
            return "attach failed"
        return None


def make_sched(**kw):
    bound = {}
    s = Scheduler(
        binder=lambda pod, node: bound.__setitem__(pod.uid, node), **kw
    )
    return s, bound


def test_host_plugin_lifecycle_order():
    rec = RecordingPlugin()
    s, bound = make_sched(host_plugins=[rec])
    s.on_node_add(MakeNode("n0").capacity({"cpu": "4"}).obj())
    s.on_pod_add(MakePod("p0").req({"cpu": "1"}).obj())
    stats = s.schedule_cycle()
    assert stats.scheduled == 1 and bound
    assert [c[0] for c in rec.calls] == [
        "reserve", "permit", "pre_bind", "post_bind"
    ]


def test_permit_veto_blocks_bind_and_requeues_unschedulable():
    rec = RecordingPlugin()
    s, bound = make_sched(host_plugins=[rec, VetoPlugin("Permit")])
    s.on_node_add(MakeNode("n0").capacity({"cpu": "4"}).obj())
    s.on_pod_add(MakePod("ok").req({"cpu": "1"}).obj())
    s.on_pod_add(
        MakePod("bad").req({"cpu": "1"}).labels({"deny": "yes"}).obj()
    )
    stats = s.schedule_cycle()
    assert stats.scheduled == 1
    assert stats.unschedulable == 1
    assert len(bound) == 1
    # the vetoed pod's reservation was rolled back
    assert ("unreserve", "bad", "n0") in rec.calls
    # veto reason reaches the events stream
    msgs = [e.message for e in s.events.events()
            if e.reason == "FailedScheduling"]
    assert any("Veto rejected at Permit" in m for m in msgs)


def test_prebind_failure_retries_with_backoff():
    s, bound = make_sched(host_plugins=[VetoPlugin("PreBind")])
    s.on_node_add(MakeNode("n0").capacity({"cpu": "4"}).obj())
    s.on_pod_add(
        MakePod("bad").req({"cpu": "1"}).labels({"deny": "yes"}).obj()
    )
    stats = s.schedule_cycle()
    assert stats.bind_errors == 1 and not bound
    # pod is in backoff, not unschedulable
    assert s.queue.pending_counts().get("backoff", 0) == 1


# ---------------------------------------------------------------------------
# HTTP extender
# ---------------------------------------------------------------------------


class _ExtenderHandler(BaseHTTPRequestHandler):
    calls: list = []

    def do_POST(self):
        body = json.loads(
            self.rfile.read(int(self.headers["Content-Length"]))
        )
        type(self).calls.append((self.path, body))
        if self.path.endswith("/filter"):
            # only nodes labeled allowed (name ends with '1') pass
            names = [n for n in body["NodeNames"] if n.endswith("1")]
            out = {"NodeNames": names}
        elif self.path.endswith("/prioritize"):
            out = {
                "Items": [
                    {"Host": n, "Score": 10 if n == "n1" else 0}
                    for n in body["NodeNames"]
                ]
            }
        elif self.path.endswith("/bind"):
            out = {"Error": ""}
        else:
            out = {"Error": f"unknown verb {self.path}"}
        data = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):  # quiet
        pass


@pytest.fixture()
def extender_server():
    _ExtenderHandler.calls = []
    srv = HTTPServer(("127.0.0.1", 0), _ExtenderHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}/scheduler"
    srv.shutdown()


def test_extender_filter_and_bind_delegation(extender_server):
    cfg = load_config({
        "extenders": [{
            "urlPrefix": extender_server,
            "filterVerb": "filter",
            "prioritizeVerb": "prioritize",
            "bindVerb": "bind",
            "weight": 2,
        }]
    })
    s, bound = make_sched(config=cfg)
    for i in range(3):
        s.on_node_add(MakeNode(f"n{i}").capacity({"cpu": "4"}).obj())
    s.on_pod_add(MakePod("p0").req({"cpu": "1"}).obj())
    stats = s.schedule_cycle()
    assert stats.scheduled == 1
    # the default binder was NOT used: the extender owns binding
    assert not bound
    paths = [p for p, _ in _ExtenderHandler.calls]
    assert any(p.endswith("/filter") for p in paths)
    assert any(p.endswith("/bind") for p in paths)
    # only n1 passed the extender filter
    bind_calls = [b for p, b in _ExtenderHandler.calls if p.endswith("/bind")]
    assert bind_calls[0]["Node"] == "n1"


def test_extender_error_nonignorable_backoff():
    cfg = load_config({
        "extenders": [{
            # nothing listens on port 9: connection refused -> ExtenderError
            "urlPrefix": "http://127.0.0.1:9/scheduler",
            "filterVerb": "filter",
            "httpTimeout": 0.5,
            "ignorable": False,
        }]
    })
    s, bound = make_sched(config=cfg)
    s.on_node_add(MakeNode("n1").capacity({"cpu": "4"}).obj())
    s.on_pod_add(MakePod("p0").req({"cpu": "1"}).obj())
    stats = s.schedule_cycle()
    assert stats.scheduled == 0 and not bound
    assert stats.bind_errors == 1
    assert s.queue.pending_counts().get("backoff", 0) == 1


def test_extender_verdict_carry_matches_fallback(extender_server):
    """VERDICT r4 item 7: carryVerdicts keeps the device-carry latency
    path with a LIVE HTTP extender — placements must equal the fallback
    (full-path) scheduler's over churned cycles, and after warmup the
    webhook is consulted only for CHANGED pods."""
    ext = {
        "urlPrefix": extender_server,
        "filterVerb": "filter",
        "prioritizeVerb": "prioritize",
        "weight": 2,
    }
    cfg_carry = load_config({
        "extenders": [dict(ext, carryVerdicts=True)]
    })
    cfg_full = load_config({"extenders": [dict(ext)]})
    s_carry, bound_carry = make_sched(config=cfg_carry)
    s_full, bound_full = make_sched(config=cfg_full)
    assert s_carry._use_carry and not s_full._use_carry

    for s in (s_carry, s_full):
        for i in range(4):
            s.on_node_add(
                MakeNode(f"n{i}").capacity({"cpu": "4"}).obj()
            )
        # a second allowed node so scoring (n1 boosted) is observable
        s.on_node_add(MakeNode("m1").capacity({"cpu": "4"}).obj())

    pods = [MakePod(f"p{i}").req({"cpu": "1"}).obj() for i in range(6)]
    for s in (s_carry, s_full):
        for p in pods:
            s.on_pod_add(p)
        s.schedule_cycle()
    assert sorted(bound_carry.items()) == sorted(bound_full.items())
    assert bound_carry  # extender filter left n1/m1; pods placed

    # churn: one NEW pod arrives; the carried scheduler re-consults the
    # webhook only for it (plus any requeued losers)
    _ExtenderHandler.calls = []
    for s, nm in ((s_carry, "fresh-a"), (s_full, "fresh-b")):
        s.on_pod_add(MakePod(nm).req({"cpu": "1"}).obj())
    n0_carry = len(bound_carry)
    n0_full = len(bound_full)
    calls_before = len(
        [p for p, _ in _ExtenderHandler.calls if p.endswith("/filter")]
    )
    s_carry.schedule_cycle()
    carry_filter_pods = {
        b["Pod"]["metadata"]["name"]
        for p, b in _ExtenderHandler.calls
        if p.endswith("/filter")
    }
    carry_filter_calls = len(
        [p for p, _ in _ExtenderHandler.calls if p.endswith("/filter")]
    ) - calls_before
    s_full.schedule_cycle()
    assert len(bound_carry) - n0_carry == len(bound_full) - n0_full == 1
    # exactly ONE webhook filter consult: the fresh arrival (all other
    # verdict rows were carried on device)
    assert carry_filter_calls == 1, carry_filter_calls
    fresh_a = next(
        n for u, n in bound_carry.items() if u.endswith("/fresh-a")
    )
    fresh_b = next(
        n for u, n in bound_full.items() if u.endswith("/fresh-b")
    )
    assert fresh_a == fresh_b
    # the carried scheduler consulted the webhook ONLY for changed pods
    # (the fresh arrival; earlier pods' verdict rows were carried)
    assert "fresh-a" in carry_filter_pods
    assert not any(p.startswith("p") for p in carry_filter_pods), (
        f"carried pods re-consulted: {carry_filter_pods}"
    )
