"""The native pod_row builder must be indistinguishable from the Python
pod_rowdata walk: encoding the same object sequence with the native path
enabled vs disabled must produce byte-identical snapshots (this also
pins interning ORDER, since ids bake into every table)."""

import dataclasses

import numpy as np
import pytest

from k8s_scheduler_tpu import native
from k8s_scheduler_tpu.models import MakeNode, MakePod, SnapshotEncoder
from k8s_scheduler_tpu.models.api import (
    VOLUME_BINDING_WAIT,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
)
from k8s_scheduler_tpu.models.encoding import ClusterSnapshot
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods


def mixed_pods():
    pods = make_pods(
        40, seed=3, affinity_fraction=0.4, anti_affinity_fraction=0.3,
        spread_fraction=0.3, selector_fraction=0.4,
        toleration_fraction=0.4, priorities=(0, 5), num_apps=5,
    )
    pods.append(
        MakePod("ports").req({"cpu": "1"}).host_port(80)
        .host_port(53, "UDP").obj()
    )
    pods.append(
        MakePod("gang").req({"cpu": "1"}).group("job-x")
        .image("img:v1").obj()
    )
    pods.append(
        MakePod("never").req({"cpu": "1"})
        .preemption_policy("Never").obj()
    )
    # fallback pods: volumes and real node affinity
    pods.append(MakePod("vol").req({"cpu": "1"}).volume("claim-a").obj())
    pods.append(
        MakePod("na").req({"cpu": "1"})
        .node_affinity_in("node-type", ["compute", "general"]).obj()
    )
    return pods


def encode_both(native_on_first=True):
    nodes = make_cluster(6, taint_fraction=0.3)
    pvcs = [PersistentVolumeClaim("claim-a", storage_class="local",
                                  request=1.0)]
    pvs = [PersistentVolume("pv-0", capacity=10.0, storage_class="local")]
    classes = [StorageClass("local", VOLUME_BINDING_WAIT,
                            provisioner=False)]
    snaps = []
    for use_native in (native_on_first, not native_on_first):
        enc = SnapshotEncoder(pad_pods=64, pad_nodes=8)
        pods = mixed_pods()
        existing = [(p, f"node-{i % 6}") for i, p in enumerate(
            make_pods(8, seed=9, name_prefix="run", affinity_fraction=0.3,
                      num_apps=5)
        )]
        saved = native.pod_row
        if not use_native:
            native.pod_row = None
        try:
            snaps.append(enc.encode(nodes, pods, existing, pvcs=pvcs,
                                    pvs=pvs, storage_classes=classes))
        finally:
            native.pod_row = saved
    return snaps


@pytest.mark.skipif(native.pod_row is None,
                    reason="native extension not built")
def test_native_rows_match_python_rows():
    got, ref = encode_both()
    for f in dataclasses.fields(ClusterSnapshot):
        gv, rv = getattr(got, f.name), getattr(ref, f.name)
        if rv is None and gv is None:
            continue
        if isinstance(rv, np.ndarray) or hasattr(rv, "dtype"):
            ga, ra = np.asarray(gv), np.asarray(rv)
            eq = (
                np.array_equal(ga, ra, equal_nan=True)
                if ga.dtype.kind == "f" else np.array_equal(ga, ra)
            )
            assert eq, f"field {f.name} differs between native and python"
        else:
            assert gv == rv, f"aux {f.name}: {gv!r} != {rv!r}"


if __name__ == "__main__":
    import sys

    pytest.main([__file__, "-v"] + sys.argv[1:])
