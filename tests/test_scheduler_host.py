"""Host-side runtime tests: SchedulingQueue tiers, SchedulerCache
lifecycle, and the Scheduler driver end-to-end against a fake cluster
(SURVEY.md §4: fakes + integration-style tests, no real cluster)."""

import numpy as np

from k8s_scheduler_tpu.core import Scheduler
from k8s_scheduler_tpu.internal.cache import SchedulerCache
from k8s_scheduler_tpu.internal.queue import (
    EVENT_NODE_ADD,
    EVENT_POD_DELETE,
    SchedulingQueue,
)
from k8s_scheduler_tpu.models import MakeNode, MakePod
from k8s_scheduler_tpu.models.api import PodGroup


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


class FakeCluster:
    """Stands in for the API server: records binds/evictions and feeds
    confirmation events back, like the informer would."""

    def __init__(self, sched=None):
        self.bound = {}
        self.evicted = []
        self.fail_next_binds = 0
        self.sched = sched

    def bind(self, pod, node_name):
        if self.fail_next_binds > 0:
            self.fail_next_binds -= 1
            raise RuntimeError("bind failed")
        self.bound[pod.name] = node_name
        if self.sched is not None:  # informer echo: pod now bound
            self.sched.cache.confirm(pod.uid)

    def evict(self, pod, node_name):
        self.evicted.append(pod.name)
        if self.sched is not None:
            self.sched.on_pod_delete(pod.uid)


def make_scheduler(clock=None):
    clock = clock or FakeClock()
    cluster = FakeCluster()
    sched = Scheduler(binder=cluster.bind, evictor=cluster.evict, now=clock,
                      pad_bucket=8)
    cluster.sched = sched
    return sched, cluster, clock


# ---- queue unit tests ------------------------------------------------------


def test_queue_update_honors_backoff_window():
    clock = FakeClock()
    q = SchedulingQueue(initial_backoff_seconds=10.0, now=clock)
    pod = MakePod("p").obj()
    q.add(pod)
    q.pop_ready()
    q.requeue_unschedulable(pod, reasons="NodeResourcesFit")
    # a spec update can cure the failure but must not skip the 10s backoff
    q.update(pod)
    assert q.pop_ready() == []
    clock.tick(10.1)
    assert [p.name for p in q.pop_ready()] == ["p"]


def test_observed_bind_drops_stale_queue_entry():
    sched, cluster, clock = make_scheduler()
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "4"}).obj())
    pod = MakePod("p").req({"cpu": "1"}).obj()
    sched.on_pod_add(pod)
    # late informer echo: the pod is observed bound before the cycle runs
    sched.on_pod_add(pod, node_name="n0")
    stats = sched.schedule_cycle()
    # must not double-schedule (pod would be both pending and existing)
    assert stats.attempted == 0
    assert sched.cache.counts()["bound"] == 1


def test_queue_backoff_grows_and_expires():
    clock = FakeClock()
    q = SchedulingQueue(initial_backoff_seconds=1.0, max_backoff_seconds=4.0,
                        now=clock)
    pod = MakePod("p").obj()
    q.add(pod)
    assert [p.name for p in q.pop_ready()] == ["p"]
    q.requeue_backoff(pod)
    assert q.pop_ready() == []  # still backing off
    clock.tick(1.1)
    assert [p.name for p in q.pop_ready()] == ["p"]  # attempt 2
    q.requeue_backoff(pod)
    clock.tick(1.1)
    assert q.pop_ready() == []  # backoff doubled to 2s
    clock.tick(1.0)
    assert [p.name for p in q.pop_ready()] == ["p"]


def test_queue_unschedulable_waits_for_matching_event():
    clock = FakeClock()
    q = SchedulingQueue(now=clock)
    pod = MakePod("p").obj()
    q.add(pod)
    q.pop_ready()
    q.requeue_unschedulable(pod, reasons="NodeResourcesFit")
    # PodDelete can cure NodeResourcesFit; backoff already expired?
    assert q.pending_counts()["unschedulable"] == 1
    q.move_all_to_active_or_backoff(EVENT_POD_DELETE)
    counts = q.pending_counts()
    assert counts["unschedulable"] == 0
    assert counts["active"] + counts["backoff"] == 1


def test_queue_hint_filters_irrelevant_events():
    clock = FakeClock()
    q = SchedulingQueue(now=clock)
    pod = MakePod("p").obj()
    q.add(pod)
    q.pop_ready()
    q.requeue_unschedulable(pod, reasons="NodeAffinity")
    # PodDelete cannot cure a NodeAffinity rejection
    q.move_all_to_active_or_backoff(EVENT_POD_DELETE)
    assert q.pending_counts()["unschedulable"] == 1
    q.move_all_to_active_or_backoff(EVENT_NODE_ADD)
    assert q.pending_counts()["unschedulable"] == 0


def test_queue_unschedulable_timeout_flush():
    clock = FakeClock()
    q = SchedulingQueue(unschedulable_timeout_seconds=300.0, now=clock)
    pod = MakePod("p").obj()
    q.add(pod)
    q.pop_ready()
    q.requeue_unschedulable(pod, reasons="NodeAffinity")
    clock.tick(301.0)
    q.flush_unschedulable_timeout()
    assert q.pending_counts()["unschedulable"] == 0


# ---- cache unit tests ------------------------------------------------------


def test_cache_assume_confirm_lifecycle():
    clock = FakeClock()
    c = SchedulerCache(assumed_pod_ttl_seconds=30.0, now=clock)
    c.add_node(MakeNode("n0").capacity({"cpu": "4"}).obj())
    pod = MakePod("p").obj()
    c.assume(pod, "n0")
    assert c.is_assumed(pod.uid)
    assert len(c.existing_pods()) == 1  # assumed counts as existing
    c.finish_binding(pod.uid)
    c.confirm(pod.uid)
    assert not c.is_assumed(pod.uid)
    assert c.counts()["bound"] == 1


def test_cache_assumed_ttl_expiry():
    clock = FakeClock()
    c = SchedulerCache(assumed_pod_ttl_seconds=30.0, now=clock)
    pod = MakePod("p").obj()
    c.assume(pod, "n0")
    c.finish_binding(pod.uid)
    clock.tick(31.0)
    expired = c.cleanup_expired()
    assert [(p.name, n) for p, n in expired] == [("p", "n0")]
    assert c.counts()["assumed"] == 0


def test_cache_forget_on_bind_failure():
    c = SchedulerCache()
    pod = MakePod("p").obj()
    c.assume(pod, "n0")
    c.forget(pod.uid)
    assert c.existing_pods() == []


# ---- scheduler end-to-end --------------------------------------------------


def test_scheduler_end_to_end_bind():
    sched, cluster, _ = make_scheduler()
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "4"}).obj())
    sched.on_node_add(MakeNode("n1").capacity({"cpu": "4"}).obj())
    for i in range(4):
        sched.on_pod_add(MakePod(f"p{i}").req({"cpu": "1"}).obj())
    stats = sched.schedule_cycle()
    assert stats.attempted == 4
    assert stats.scheduled == 4
    assert len(cluster.bound) == 4
    assert sched.cache.counts()["bound"] == 4  # confirmations arrived
    # second cycle: nothing pending
    assert sched.schedule_cycle().attempted == 0


def test_scheduler_sequential_cycles_respect_capacity():
    sched, cluster, _ = make_scheduler()
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "2"}).obj())
    sched.on_pod_add(MakePod("a").req({"cpu": "2"}).obj())
    sched.schedule_cycle()
    sched.on_pod_add(MakePod("b").req({"cpu": "2"}).obj())
    stats = sched.schedule_cycle()
    assert stats.unschedulable == 1  # n0 is full with a bound pod
    assert cluster.bound == {"a": "n0"}


def test_scheduler_bind_failure_backs_off_and_retries():
    sched, cluster, clock = make_scheduler()
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "4"}).obj())
    pod = MakePod("p").req({"cpu": "1"}).obj()
    sched.on_pod_add(pod)
    cluster.fail_next_binds = 1
    stats = sched.schedule_cycle()
    assert stats.bind_errors == 1 and stats.scheduled == 0
    assert not sched.cache.is_assumed(pod.uid)  # assumption forgotten
    clock.tick(2.0)  # past initial backoff
    stats = sched.schedule_cycle()
    assert stats.scheduled == 1
    assert cluster.bound == {"p": "n0"}


def test_scheduler_preemption_flow():
    sched, cluster, clock = make_scheduler()
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "2"}).obj())
    victim = MakePod("victim").req({"cpu": "2"}).priority(1).obj()
    sched.on_pod_add(victim, node_name="n0")  # already bound
    sched.on_pod_add(MakePod("urgent").req({"cpu": "2"}).priority(10).obj())
    stats = sched.schedule_cycle()
    assert stats.unschedulable == 1
    assert stats.preemptors == 1
    assert stats.victims == 1
    assert cluster.evicted == ["victim"]
    # eviction event moved the preemptor out of the unschedulable tier;
    # next cycle it lands on its nominated node
    clock.tick(2.0)
    stats = sched.schedule_cycle()
    assert stats.scheduled == 1
    assert cluster.bound == {"urgent": "n0"}


def test_scheduler_gang_requeue():
    sched, cluster, clock = make_scheduler()
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "4"}).obj())
    sched.add_pod_group(PodGroup("job", 3))
    for i in range(3):
        sched.on_pod_add(
            MakePod(f"g{i}").req({"cpu": "2"}).group("job").created(i).obj()
        )
    stats = sched.schedule_cycle()
    assert stats.scheduled == 0
    assert stats.gang_dropped == 2
    assert stats.unschedulable == 3
    assert cluster.bound == {}
    # more capacity arrives -> the whole gang lands
    sched.on_node_add(MakeNode("n1").capacity({"cpu": "4"}).obj())
    clock.tick(2.0)
    stats = sched.schedule_cycle()
    assert stats.scheduled == 3
    assert len(cluster.bound) == 3


def test_scheduler_node_delete_requeues():
    sched, cluster, clock = make_scheduler()
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "1"}).obj())
    sched.on_pod_add(MakePod("p").req({"cpu": "2"}).obj())
    stats = sched.schedule_cycle()
    assert stats.unschedulable == 1
    sched.on_node_add(MakeNode("big").capacity({"cpu": "8"}).obj())
    clock.tick(2.0)
    stats = sched.schedule_cycle()
    assert stats.scheduled == 1
    assert cluster.bound == {"p": "big"}
