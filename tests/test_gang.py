"""Gang scheduling (benchmark config #5): all-or-nothing group commit."""

import numpy as np

from k8s_scheduler_tpu import oracle
from k8s_scheduler_tpu.core import build_cycle_fn
from k8s_scheduler_tpu.models import MakeNode, MakePod, SnapshotEncoder
from k8s_scheduler_tpu.models.api import PodGroup
from k8s_scheduler_tpu.utils.synth import make_gang_pods


def run_both(nodes, pods, groups, existing=()):
    snap = SnapshotEncoder().encode(nodes, pods, existing, pod_groups=groups)
    result = build_cycle_fn()(snap)
    got = np.asarray(result.assignment)[: len(pods)].tolist()
    want, dropped = oracle.schedule_with_gangs(
        nodes, pods, existing, groups
    )
    return got, [d.node_index for d in want], result, dropped


def test_gang_fits_all_members_placed():
    nodes = [MakeNode(f"n{i}").capacity({"cpu": "4"}).obj() for i in range(4)]
    pods = [MakePod(f"g-{i}").req({"cpu": "2"}).group("job").created(i).obj()
            for i in range(4)]
    got, want, result, _ = run_both(nodes, pods, [PodGroup("job", 4)])
    assert got == want
    assert all(n >= 0 for n in got)
    assert not np.asarray(result.gang_dropped)[:4].any()


def test_gang_unwound_when_min_member_unmet():
    # capacity for only 2 members, minMember=3: everything rolls back
    nodes = [MakeNode("n0").capacity({"cpu": "4"}).obj()]
    pods = [MakePod(f"g-{i}").req({"cpu": "2"}).group("job").created(i).obj()
            for i in range(3)]
    got, want, result, dropped = run_both(nodes, pods, [PodGroup("job", 3)])
    assert got == want == [-1, -1, -1]
    assert np.asarray(result.gang_dropped)[:3].sum() == 2
    assert len(dropped) == 2
    # capacity released: the running node_requested is back to zero
    np.testing.assert_allclose(
        np.asarray(result.node_requested)[0],
        np.asarray(SnapshotEncoder().encode(nodes, pods,
                                            pod_groups=[PodGroup("job", 3)]
                                            ).node_requested)[0],
    )


def test_gang_failure_releases_capacity_for_later_cycle():
    # after the unwind, a non-gang pod can take the freed capacity in the
    # NEXT cycle (the host requeues; in-cycle order already passed it)
    nodes = [MakeNode("n0").capacity({"cpu": "4"}).obj()]
    gang = [MakePod(f"g-{i}").req({"cpu": "2"}).group("job")
            .priority(10).created(i).obj() for i in range(3)]
    snap = SnapshotEncoder().encode(nodes, gang, pod_groups=[PodGroup("job", 3)])
    result = build_cycle_fn()(snap)
    assert (np.asarray(result.assignment)[:3] == -1).all()
    solo = [MakePod("solo").req({"cpu": "4"}).obj()]
    snap2 = SnapshotEncoder().encode(nodes, solo)
    r2 = build_cycle_fn()(snap2)
    assert np.asarray(r2.assignment)[0] == 0


def test_partial_group_min_member_lower_than_size():
    # minMember=2 of 3: two members placing is enough, third stays pending
    nodes = [MakeNode("n0").capacity({"cpu": "4"}).obj()]
    pods = [MakePod(f"g-{i}").req({"cpu": "2"}).group("job").created(i).obj()
            for i in range(3)]
    got, want, result, _ = run_both(nodes, pods, [PodGroup("job", 2)])
    assert got == want
    assert sum(1 for n in got if n >= 0) == 2


def test_undeclared_group_never_gates():
    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    pods = [MakePod(f"g-{i}").req({"cpu": "2"}).group("mystery").created(i).obj()
            for i in range(2)]
    got, want, result, _ = run_both(nodes, pods, [])
    assert got == want == [0, -1]
    assert not np.asarray(result.gang_dropped)[:2].any()


def test_two_gangs_contending():
    # both gangs want 2x2cpu; only one node fits both members of one gang.
    # higher-priority gang wins, the other unwinds fully.
    nodes = [MakeNode("n0").capacity({"cpu": "4"}).obj()]
    a = [MakePod(f"a-{i}").req({"cpu": "2"}).group("a").priority(5)
         .created(i).obj() for i in range(2)]
    b = [MakePod(f"b-{i}").req({"cpu": "2"}).group("b").priority(1)
         .created(10 + i).obj() for i in range(2)]
    groups = [PodGroup("a", 2), PodGroup("b", 2)]
    got, want, result, _ = run_both(nodes, a + b, groups)
    assert got == want
    assert got[0] >= 0 and got[1] >= 0
    assert got[2] == -1 and got[3] == -1


def test_gang_disabled_keeps_partial_placement():
    nodes = [MakeNode("n0").capacity({"cpu": "4"}).obj()]
    pods = [MakePod(f"g-{i}").req({"cpu": "2"}).group("job").created(i).obj()
            for i in range(3)]
    snap = SnapshotEncoder().encode(nodes, pods, pod_groups=[PodGroup("job", 3)])
    result = build_cycle_fn(gang_scheduling=False)(snap)
    assert (np.asarray(result.assignment)[:3] >= 0).sum() == 2


def test_synth_gang_workload_differential():
    pods, groups = make_gang_pods(4, replicas=4)
    nodes = [MakeNode(f"n{i}").capacity({"cpu": "8", "memory": "16Gi"}).obj()
             for i in range(6)]
    got, want, _, _ = run_both(nodes, pods, groups)
    assert got == want


def test_gang_dropped_members_do_not_preempt():
    # gang of 2 can't meet minMember=2; another node holds a low-priority
    # pod. The dropped members must NOT nominate/evict anything (upstream
    # never runs PostFilter for Permit/coscheduling rejections).
    from k8s_scheduler_tpu.core import build_preemption_fn

    nodes = [
        MakeNode("n0").capacity({"cpu": "2"}).obj(),
        MakeNode("n1").capacity({"cpu": "2"}).obj(),
    ]
    existing = [
        (MakePod("low").req({"cpu": "2"}).priority(0).obj(), "n1"),
    ]
    pods = [MakePod(f"g-{i}").req({"cpu": "2"}).group("job").priority(10)
            .created(i).obj() for i in range(2)]
    snap = SnapshotEncoder().encode(nodes, pods, existing,
                                    pod_groups=[PodGroup("job", 2)])
    result = build_cycle_fn()(snap)
    assert (np.asarray(result.assignment)[:2] == -1).all()
    pre = build_preemption_fn()(snap, result)
    # g-1 genuinely lacked a node (not gang-dropped) -> may preempt;
    # g-0 was gang-dropped -> must not
    dropped = np.asarray(result.gang_dropped)[:2]
    noms = np.asarray(pre.nominated)[:2]
    assert noms[np.flatnonzero(dropped)].max(initial=-1) == -1


def test_gang_counts_running_members():
    # 2 of 3 members already run; the third retried alone must place
    nodes = [MakeNode("n0").capacity({"cpu": "8"}).obj()]
    existing = [
        (MakePod(f"g-{i}").req({"cpu": "2"}).group("job").created(i).obj(),
         "n0")
        for i in range(2)
    ]
    pods = [MakePod("g-2").req({"cpu": "2"}).group("job").created(2).obj()]
    got, want, result, _ = run_both(nodes, pods, [PodGroup("job", 3)],
                                    existing)
    assert got == want == [0]
    assert not np.asarray(result.gang_dropped)[:1].any()
