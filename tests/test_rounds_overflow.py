"""The matcher-overflow branch of the rounds engine (VERDICT r2 item 7):
a pod matching MORE than MS_MATCH guard-active selectors is invisible to
other claims' guard checks, so it may only be accepted in a round that
accepts nothing else (`ops/rounds.py` docstring). These tests pin down
(a) that overflow placements are still VALID, (b) that overflow degrades
throughput to roughly one-such-pod-per-round rather than producing wrong
placements, and (c) the scan engine is untouched by overflow.
"""

import numpy as np
import pytest

from k8s_scheduler_tpu import oracle
from k8s_scheduler_tpu.core import build_cycle_fn
from k8s_scheduler_tpu.models import MakePod, SnapshotEncoder
from k8s_scheduler_tpu.ops.rounds import MS_MATCH
from k8s_scheduler_tpu.utils.synth import make_cluster


def overflow_fixture(n_overflow: int = 3):
    """`n_overflow` pods each matching MS_MATCH+2 guard-active selectors
    (every selector is used by some pod's required anti-affinity, making
    it guard-active), plus the anti-affinity hunters themselves."""
    n_sel = MS_MATCH + 2
    nodes = make_cluster(8, with_labels=True)
    pods = []
    # hunters: one per selector; their anti terms make selectors active
    for i in range(n_sel):
        pods.append(
            MakePod(f"hunter-{i}").req({"cpu": "500m"})
            .priority(10).created(float(i))
            .pod_affinity(
                "kubernetes.io/hostname", {f"k{i}": "v"}, anti=True
            )
            .obj()
        )
    # overflow pods: labels matching ALL n_sel guard-active selectors
    labels = {f"k{i}": "v" for i in range(n_sel)}
    for j in range(n_overflow):
        pods.append(
            MakePod(f"ovf-{j}").req({"cpu": "500m"})
            .labels(labels).priority(0).created(100.0 + j)
            .obj()
        )
    return nodes, pods


def test_overflow_placements_are_valid():
    nodes, pods = overflow_fixture(3)
    enc = SnapshotEncoder(pad_pods=32, pad_nodes=8)
    snap = enc.encode(nodes, pods)
    out = build_cycle_fn(commit_mode="rounds")(snap)
    a = np.asarray(out.assignment)[: len(pods)].tolist()
    errs = oracle.validate_rounds_assignment(nodes, pods, a)
    assert not errs, errs
    # the anti-affinity constraints are satisfiable on 8 nodes; every
    # overflow pod must eventually place (one per round, not dropped)
    assert all(x >= 0 for x in a), a


def test_overflow_accepts_one_per_round():
    n_ovf = 4
    nodes, pods = overflow_fixture(n_ovf)
    enc = SnapshotEncoder(pad_pods=32, pad_nodes=8)
    snap = enc.encode(nodes, pods)
    out = build_cycle_fn(commit_mode="rounds")(snap)
    used = int(np.asarray(out.rounds_used))
    hist = np.asarray(out.accepted_per_round)[:used]
    # overflow pods are deferred while any normal claimant exists and
    # then accepted ONE per round: the engine needs at least one round
    # per overflow pod beyond the first
    assert used >= n_ovf, (used, hist.tolist())
    # the overflow tail accepts exactly one pod per round
    tail = hist[hist > 0][-n_ovf:]
    assert (tail == 1).all(), hist.tolist()


def test_overflow_scan_engine_unaffected():
    nodes, pods = overflow_fixture(3)
    enc = SnapshotEncoder(pad_pods=32, pad_nodes=8)
    snap = enc.encode(nodes, pods)
    out = build_cycle_fn(commit_mode="scan")(snap)
    got = np.asarray(out.assignment)[: len(pods)].tolist()
    want = [d.node_index for d in oracle.schedule(nodes, pods)]
    assert got == want


if __name__ == "__main__":
    import sys

    pytest.main([__file__, "-v"] + sys.argv[1:])
