"""Scenario fuzzer + trace-level differential oracle (ISSUE 11).

Four layers:

- generator/format units: seeded determinism, dict/file round-trips;
- shrinker units: synthetic (engine-free) checkers prove the reducer
  reaches the documented minimum AND never shrinks to a different
  failure class;
- corpus replay (fast tier): every committed minimal repro under
  tests/corpus/ replays CLEAN against the current engine — each file
  is the regression test for a bug class the differential once caught;
- smoke (slow tier): live differential cases across the axes (plain /
  gangs+PDBs / sharded / chaos / multi-cycle), plus the harness
  self-test — a deliberately seeded engine bug (mutated claim-path
  tie-break) must be CAUGHT, and the corpus repro must reproduce its
  recorded class when the bug is re-injected.
"""

from __future__ import annotations

import copy
import glob
import os
import tempfile

import pytest

from k8s_scheduler_tpu.fuzz import (
    Failure,
    engine_bug,
    generate_trace,
    replay_artifact,
    run_case,
    shrink_trace,
    trace_from_dict,
    trace_to_dict,
)
from k8s_scheduler_tpu.fuzz.trace import load_trace, save_trace

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "corpus")


# ---- generator + format -------------------------------------------------


def test_generator_is_deterministic():
    a = trace_to_dict(generate_trace(7))
    b = trace_to_dict(generate_trace(7))
    assert a == b
    assert a != trace_to_dict(generate_trace(8))
    # kwargs are part of the stamp: the same seed with different axes
    # must still be reproducible, not equal
    c = trace_to_dict(generate_trace(7, devices=4, multi_cycle=True))
    assert c == trace_to_dict(generate_trace(7, devices=4, multi_cycle=True))
    assert c != a


def test_generator_covers_the_plugin_inventory():
    """Across a seed band the generator exercises the full scenario
    inventory: gangs, PDBs, PV topology, taints, spreads, affinity,
    priorities, churn, chaos plans."""
    import json

    seen = set()
    for seed in range(40):
        t = generate_trace(seed)
        if t.pod_groups:
            seen.add("gangs")
        if t.pdbs:
            seen.add("pdbs")
        if any(v.get("na") for v in t.pvs):
            seen.add("pv_topology")
        blob = json.dumps(t.cycles)
        if '"tol"' in blob:
            seen.add("tolerations")
        if '"tsc"' in blob:
            seen.add("spread")
        if '"af"' in blob:
            seen.add("affinity")
        if '"pri": 100' in blob:
            seen.add("preemption_pressure")
        if '"delete_pod"' in blob:
            seen.add("pod_churn")
        if '"delete_node"' in blob or '"update_node"' in blob:
            seen.add("node_churn")
        if int(t.config["multi_cycle_k"]) > 1:
            seen.add("multi_cycle")
    t = generate_trace(3, chaos=True)
    if t.fault_spec:
        seen.add("chaos")
    assert seen == {
        "gangs", "pdbs", "pv_topology", "tolerations", "spread",
        "affinity", "preemption_pressure", "pod_churn", "node_churn",
        "multi_cycle", "chaos",
    }


def test_trace_roundtrips(tmp_path):
    t = generate_trace(11, chaos=True)
    d = trace_to_dict(t)
    assert trace_to_dict(trace_from_dict(d)) == d
    p = str(tmp_path / "t.json")
    save_trace(p, t)
    assert trace_to_dict(load_trace(p)) == d


def test_multicycle_traces_stay_in_the_exactness_envelope():
    """Coalescing traces must be arrivals-only and frozen-clock — churn
    or ticking backoffs across the batch window are legal semantic
    differences the differential must never be exposed to."""
    for seed in range(20):
        t = generate_trace(seed, multi_cycle=True)
        assert t.tick_s == 0.0
        ops = {e["op"] for evs in t.cycles for e in evs}
        assert not ops & {"delete_pod", "add_node", "update_node",
                          "delete_node"}
        # preemption-free: uniform priorities — an eviction's informer
        # echo lands after the flush, a legal batch-window difference
        pris = {
            e["pod"].get("s", {}).get("pri", 0)
            for evs in t.cycles for e in evs if "pod" in e
        }
        assert pris <= {0}


# ---- shrinker units (synthetic checkers: no engine, no compile) ---------


def _poison_check(trace):
    """Synthetic bug: fails iff any arrival carries priority 10. The
    documented minimum: 1 node, 1 cycle, 1 event, no volume/PDB/gang
    objects, the pod stripped to its priority."""
    for ci, evs in enumerate(trace.cycles):
        for ev in evs:
            if ev.get("op") == "add_pod" and (
                ev["pod"].get("s", {}).get("pri") == 10
            ):
                return Failure("synthetic/poison", ci, "poison present")
    return None


def _seeded_poisoned_trace():
    for seed in range(100):
        t = generate_trace(seed, multi_cycle=False)
        if _poison_check(t) is not None:
            return t
    raise AssertionError("no seed in range produced a priority-10 pod")


def test_shrinker_reaches_the_documented_minimum():
    t = _seeded_poisoned_trace()
    f = _poison_check(t)
    mint, minf = shrink_trace(t, f, _poison_check, max_evals=3000)
    assert minf.cls == "synthetic/poison"
    assert _poison_check(mint) is not None
    assert len(mint.nodes) == 1  # the shrinker keeps >=1 node
    assert len(mint.cycles) == 1
    assert sum(len(evs) for evs in mint.cycles) == 1
    assert not mint.pvs and not mint.pvcs and not mint.pdbs
    assert not mint.pod_groups and not mint.storage_classes
    (ev,) = mint.cycles[0]
    # every strippable attribute is gone; the load-bearing one stays
    s = ev["pod"]["s"]
    assert s.get("pri") == 10
    for k in ("af", "tsc", "tol", "sel", "vol", "pg"):
        assert k not in s


def test_shrinker_preserves_the_failure_class():
    """No shrink-to-a-different-bug: a reduction that flips the failure
    class is rejected even when it would still 'fail'."""
    t = _seeded_poisoned_trace()

    def two_class_check(trace):
        base = _poison_check(trace)
        if base is None:
            return None
        if len(trace.nodes) >= 3:
            return Failure("synthetic/big", base.cycle, "poison, >=3 nodes")
        return Failure("synthetic/small", base.cycle, "poison, <3 nodes")

    assert len(t.nodes) >= 3  # generator minimum is 4
    f = two_class_check(t)
    assert f.cls == "synthetic/big"
    mint, minf = shrink_trace(t, f, two_class_check, max_evals=3000)
    assert minf.cls == "synthetic/big"
    # node removal stopped exactly where the class would have flipped
    assert len(mint.nodes) == 3
    assert two_class_check(mint).cls == "synthetic/big"


def test_shrinker_input_is_not_mutated():
    t = _seeded_poisoned_trace()
    before = copy.deepcopy(trace_to_dict(t))
    shrink_trace(t, _poison_check(t), _poison_check, max_evals=500)
    assert trace_to_dict(t) == before


def test_replay_refuses_rounds_mode():
    """The differential is defined for the scan engine (exact vs the
    oracle; at-turn attribution). A rounds-mode trace must be refused
    loudly, never silently compared into phantom divergences."""
    from k8s_scheduler_tpu.fuzz.replay import replay_engine, replay_oracle

    t = generate_trace(0)
    t.config["commit_mode"] = "rounds"
    with pytest.raises(ValueError, match="scan"):
        replay_engine(t)
    with pytest.raises(ValueError, match="scan"):
        replay_oracle(t)


def test_engine_bug_patch_restores():
    from k8s_scheduler_tpu.ops import argsel

    orig = argsel.argmax_first
    with engine_bug("tiebreak"):
        assert argsel.argmax_first is not orig
    assert argsel.argmax_first is orig
    with pytest.raises(ValueError):
        with engine_bug("not_a_bug"):
            pass


# ---- corpus replay (fast tier: the committed regression suite) ----------


def _corpus_files():
    return sorted(glob.glob(os.path.join(CORPUS_DIR, "*.json")))


def test_corpus_exists():
    assert _corpus_files(), "tests/corpus/ must hold >=1 minimal repro"


@pytest.mark.parametrize("path", _corpus_files())
def test_corpus_replays_clean(path):
    """Every committed minimal repro must replay with ZERO divergences
    and zero invariant violations against the current engine — each
    file pins a bug class the differential once caught."""
    failures = replay_artifact(path)
    assert not failures, [str(f) for f in failures]


# ---- live differential smoke (slow tier) --------------------------------


def test_fuzz_differential_plain_seed():
    """One full plain case: random trace (churn, priorities, taints,
    spreads) through the live engine and the trace oracle — bit-equal
    streams, zero invariant violations."""
    failures = run_case(generate_trace(2, multi_cycle=False))
    assert not failures, [str(f) for f in failures]


def test_fuzz_differential_multicycle_seed():
    """The K=4 coalescing path against the sequential oracle: the
    flattened outcome streams must be identical (PR 6's contract,
    now fuzz-checked rather than only equivalence-suite-checked)."""
    failures = run_case(generate_trace(1, multi_cycle=True))
    assert not failures, [str(f) for f in failures]


def test_fuzz_differential_sharded_seed():
    """Sharded serving (shardDevices=4 on the virtual CPU mesh) must
    stay bit-identical to the oracle — PR 9's shard-invariant
    tie-breaking is what makes this assertion exact."""
    failures = run_case(generate_trace(31, devices=4, multi_cycle=False))
    assert not failures, [str(f) for f in failures]


def test_fuzz_differential_speculative_seed():
    """The depth-2 pipelining variant (speculativeDispatch over
    multiCycleK=4): the speculative engine must be per-cycle
    bit-identical to the non-speculative engine on the same trace —
    adoption/abandonment may never change what is decided, when it
    lands, or its order — and the case fails if the trace never
    actually dispatched a speculation (a silently-vacuous variant
    would be a permanent green)."""
    t = generate_trace(1, speculative=True)
    assert t.config["speculative_dispatch"] is True
    assert t.config["multi_cycle_k"] == 4
    failures = run_case(t)
    assert not failures, [str(f) for f in failures]


def test_fuzz_differential_incremental_seed():
    """The admission-time incremental encode variant (incrementalEncode
    over multiCycleK=4): the same trace runs with ingest-at-ack on AND
    off and must produce byte-identical dispatched packed arenas plus
    bit-equal decision / journal / event streams — and the case fails
    if the on-run never folded a staged row (a variant whose ingest
    always misses would be a permanent vacuous green)."""
    t = generate_trace(1, incremental=True)
    assert t.config["incremental_encode"] is True
    assert t.config["multi_cycle_k"] == 4
    failures = run_case(t)
    assert not failures, [str(f) for f in failures]


def test_speculative_traces_stay_in_the_exactness_envelope():
    """Speculative traces must actually exercise the device loop they
    pipeline: the envelope-leaving capabilities (affinity / spread /
    volumes / host ports) are drawn but not applied, and the mc
    invariants (arrivals-only, frozen clock, flat priority) hold."""
    import json

    for seed in range(10):
        t = generate_trace(seed, speculative=True)
        assert t.tick_s == 0.0
        blob = json.dumps(t.cycles)
        for key in ('"af"', '"tsc"', '"vol"'):
            assert key not in blob, (seed, key)


def test_fuzz_chaos_fetch_hang_mid_speculation(tmp_path):
    """Chaos fused with speculation: fetch_hang fires on the first
    bounded fetch of a flush — AFTER the continuation batch was
    speculatively dispatched — so the watchdog must bound it, the
    abandoned dispatch must not leak an arena slot (the trace keeps
    serving flushes through the same 3-slot pipeline), and the PR 8
    soak invariants hold: no lost/duplicate binds, ladder recovered,
    digest-verified restore."""
    from k8s_scheduler_tpu.fuzz.replay import replay_engine

    t = generate_trace(30, chaos=True, speculative=True)
    t.fault_spec = "seed=30;fetch_hang@cycle=2..40:ms=15000:n=1"
    eng = replay_engine(t, state_dir=str(tmp_path / "state"))
    assert not eng.failures, [str(f) for f in eng.failures]
    led = eng.stats["speculation"]
    # the hang hit the predecessor's fetch mid-speculation: the
    # in-flight continuation was abandoned (slot freed), and later
    # flushes kept speculating (adoptions after the recovery)
    assert led["abandoned"] >= 1, led


def test_fuzz_chaos_seed(tmp_path):
    """Chaos fusion: a random FaultPlan over a random trace. The PR 8
    soak invariants hold throughout — watchdog bound, no lost/duplicate
    binds, ladder recovered on the tail, digest-verified restore."""
    t = generate_trace(30, chaos=True)
    assert t.fault_spec
    failures = run_case(t, state_dir=str(tmp_path / "state"))
    assert not failures, [str(f) for f in failures]


def test_fuzz_catches_seeded_tiebreak_bug():
    """Harness self-test: with the claim-path tie-break deliberately
    mutated (first-max -> last-max), the differential must report a
    bind-stream divergence — the exact silent-wrongness class PR 9
    eliminated and the reason bit-equality is assertable at all."""
    failures = run_case(generate_trace(1, multi_cycle=False), bug="tiebreak")
    assert any(f.cls == "divergence/binds" for f in failures), (
        [str(f) for f in failures]
    )


def test_corpus_repro_still_catches_its_bug():
    """The committed minimal repro, replayed WITH its recorded engine
    mutation, must reproduce the recorded failure class — proof the
    oracle still catches the class, not just that the engine is
    currently correct."""
    for path in _corpus_files():
        from k8s_scheduler_tpu.fuzz import load_artifact

        art = load_artifact(path)
        if not art["bug"]:
            continue
        failures = replay_artifact(path, with_bug=True)
        assert any(f.cls == art["failure"].cls for f in failures), (
            path, [str(f) for f in failures],
        )


def test_fuzz_soak_smoke():
    """The scripts/fuzz_scheduler.py smoke path, in-process: a handful
    of seeds across the axes with shrink disabled."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    with tempfile.TemporaryDirectory() as td:
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "scripts", "fuzz_scheduler.py"),
             "--smoke", "--no-shrink", "--artifact-dir", td],
            capture_output=True, text=True, timeout=1500, env=env,
            cwd=repo,
        )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert '"fuzz": "ok"' in proc.stdout
