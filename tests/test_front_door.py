"""The submission front door (ISSUE 14): admission control,
WAL-before-ack durability, backpressure, drain, and the failover
contract.

Fast tier: admission semantics (accept / shed / invalid), the
durability contract's two fast halves (ack-implies-journaled,
rejected-never-journaled), the half-open degraded trickle, metrics,
the submit_bind flight-record phase, the HTTP POST path, gRPC
round-trip semantics, and graceful drain.

Slow tier: the kill -9 failover mid-loadgen (a real CLI process with
--submit-addr, an open-loop gRPC load, SIGKILL, restore — zero lost
acked pods, zero duplicate binds), the arrivals_via_api fuzz variant,
the soak_chaos overload phase, and a bench config 9 smoke.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from k8s_scheduler_tpu.config import SchedulerConfiguration
from k8s_scheduler_tpu.core.scheduler import Scheduler
from k8s_scheduler_tpu.internal.cache import SchedulerCache
from k8s_scheduler_tpu.internal.queue import SchedulingQueue
from k8s_scheduler_tpu.service.admission import (
    AdmissionClosed,
    AdmissionController,
    FrontDoor,
)
from k8s_scheduler_tpu.state import DurableState
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sched(state=None, binds=None, **cfg):
    cfg.setdefault("pod_initial_backoff_seconds", 0.05)
    cfg.setdefault("pod_max_backoff_seconds", 0.2)
    binds = binds if binds is not None else {}
    sched = Scheduler(
        config=SchedulerConfiguration(**cfg),
        binder=lambda p, n: binds.__setitem__(
            p.uid, binds.get(p.uid, 0) + 1
        ),
        state=state,
    )
    return sched, binds


def _restore_bare(state_dir):
    q, c = SchedulingQueue(), SchedulerCache()
    st = DurableState(state_dir, snapshot_interval_seconds=0)
    st.restore_into(q, c)
    st.journal.close()
    return q, c


# ---------------------------------------------------------------------------
# admission semantics (no dispatch needed)
# ---------------------------------------------------------------------------


def test_accept_is_atomic_and_counts_metrics():
    sched, _ = _sched()
    adm = AdmissionController(sched, queue_depth=100)
    pods = make_pods(5, seed=1, name_prefix="a-")
    res = adm.submit(pods)
    assert res.ok and res.accepted == 5 and res.queue_depth == 5
    assert not res.durable  # no state dir
    assert sched.queue.pending_counts()["active"] == 5
    text = sched.metrics.expose().decode()
    assert 'scheduler_admission_total{outcome="accepted"} 5.0' in text
    assert "scheduler_submit_ack_seconds_count 1.0" in text
    assert "scheduler_admission_queue_depth 5.0" in text


def test_shed_on_full_queue_whole_request():
    sched, _ = _sched()
    adm = AdmissionController(sched, queue_depth=6, retry_after_ms=123.0)
    assert adm.submit(make_pods(4, seed=2, name_prefix="b-")).ok
    res = adm.submit(make_pods(4, seed=3, name_prefix="c-"))
    assert res.shed == 4 and not res.ok
    assert "admission queue full" in res.reason
    assert res.retry_after_ms == 123.0
    # atomic: NONE of the shed request's pods were enqueued
    assert sched.queue.pending_counts()["active"] == 4
    assert adm.overloaded() == ""  # 4+1 <= 6: not saturated right now
    assert adm.submit(make_pods(2, seed=30, name_prefix="c2-")).ok
    assert "admission queue full" in adm.overloaded()  # 6+1 > 6
    text = sched.metrics.expose().decode()
    assert 'scheduler_admission_total{outcome="shed"} 4.0' in text


def test_invalid_submissions_reject_whole_request():
    sched, _ = _sched()
    adm = AdmissionController(sched, queue_depth=100)
    good = make_pods(2, seed=4, name_prefix="d-")
    bad = make_pods(1, seed=5, name_prefix="e-")[0]
    bad.metadata.uid = ""
    res = adm.submit(good + [bad])
    assert res.invalid and not res.ok
    assert sched.queue.pending_counts()["active"] == 0  # nothing in
    # duplicate uid within one request
    p = make_pods(1, seed=6, name_prefix="f-")[0]
    res = adm.submit([p, p])
    assert res.invalid
    # duplicate of a still-pending accepted uid
    assert adm.submit([p]).ok
    res = adm.submit([p])
    assert res.invalid and "already pending" in res.reason


def test_delete_before_bind_frees_the_uid():
    """A pod deleted before binding must leave the accepted-pending
    set: a re-created pod reusing the uid is a fresh admission, not
    an 'already pending' duplicate."""
    sched, _ = _sched()
    adm = AdmissionController(sched, queue_depth=100)
    p = make_pods(1, seed=27, name_prefix="del-")[0]
    assert adm.submit([p]).ok
    assert adm.submit([p]).invalid  # still pending: duplicate
    sched.on_pod_delete(p.uid)
    res = adm.submit([p])  # re-created pod, same uid: admitted
    assert res.ok, res.reason


def test_shed_on_degraded_ladder_with_halfopen_trickle():
    sched, _ = _sched()
    adm = AdmissionController(sched, queue_depth=256)
    sched.ladder.degrade("test: forced")
    # the flood sheds (past the half-open trickle bound of depth/8=32)
    res = adm.submit(make_pods(40, seed=7, name_prefix="g-"))
    assert res.shed and "degradation ladder at rung 1" in res.reason
    # with an EMPTY queue the door would still admit a probe — the
    # half-open trickle means "would shed right now" is false here
    assert adm.overloaded() == ""
    # ...but a probe trickle keeps flowing (depth/8 = 32, floor 16):
    # recovery evidence is traffic-driven, a closed door never heals
    res = adm.submit(make_pods(3, seed=8, name_prefix="h-"))
    assert res.ok


def test_shed_on_slo_fast_burn():
    sched, _ = _sched(slo_p99_ms=1.0)
    adm = AdmissionController(sched, queue_depth=256)
    for _ in range(64):
        sched.observer.slo.note(10.0)  # every cycle violates: burn >> 6x
    res = adm.submit(make_pods(40, seed=9, name_prefix="i-"))
    assert res.shed and "SLO fast-burn" in res.reason
    # the half-open trickle still admits a probe
    assert adm.submit(make_pods(2, seed=31, name_prefix="i2-")).ok


def test_draining_after_close():
    sched, _ = _sched()
    adm = AdmissionController(sched, queue_depth=100)
    adm.close()
    res = adm.submit(make_pods(1, seed=10, name_prefix="j-"))
    assert res.reason == "draining" and res.shed == 1
    with pytest.raises(AdmissionClosed):
        adm.node_churn(adds=make_cluster(1))


# ---------------------------------------------------------------------------
# the durability contract (fast halves)
# ---------------------------------------------------------------------------


def test_ack_implies_journaled_across_crash(tmp_path):
    """Crash between ack and dispatch: the acked pods must be fully
    recoverable by replay — no cycle ever ran, no snapshot, no seal."""
    st = DurableState(str(tmp_path), snapshot_interval_seconds=0)
    sched, _ = _sched(state=st)
    adm = AdmissionController(sched, queue_depth=100)
    adm.node_churn(adds=make_cluster(4))
    pods = make_pods(6, seed=11, name_prefix="k-")
    res = adm.submit(pods)
    assert res.ok and res.durable
    # simulate kill -9: no flush, no seal — just read the dir back
    q, c = _restore_bare(str(tmp_path))
    restored = {p.uid for p in q.all_pending()}
    assert {p.uid for p in pods} <= restored
    assert len(c.nodes()) == 4  # NodeChurn journaled too


def test_rejected_submission_never_journaled(tmp_path):
    st = DurableState(str(tmp_path), snapshot_interval_seconds=0)
    sched, _ = _sched(state=st)
    adm = AdmissionController(sched, queue_depth=4)
    assert adm.submit(make_pods(3, seed=12, name_prefix="l-")).ok
    shed = make_pods(4, seed=13, name_prefix="m-")
    assert adm.submit(shed).shed
    bad = make_pods(1, seed=14, name_prefix="n-")[0]
    bad.metadata.uid = ""
    assert adm.submit([bad]).invalid
    st.journal.flush()
    q, _c = _restore_bare(str(tmp_path))
    restored = {p.uid for p in q.all_pending()}
    assert len(restored) == 3
    assert not ({p.uid for p in shed} & restored)


def test_ack_not_durable_after_journal_death(tmp_path):
    """Durability lost mid-run: acks must degrade to durable=False,
    never block or crash."""
    st = DurableState(str(tmp_path), snapshot_interval_seconds=0)
    sched, _ = _sched(state=st)
    adm = AdmissionController(sched, queue_depth=100)
    assert adm.submit(make_pods(1, seed=15, name_prefix="o-")).durable
    st.journal.failed = "ENOSPC (test)"
    res = adm.submit(make_pods(1, seed=16, name_prefix="p-"))
    assert res.ok and not res.durable


# ---------------------------------------------------------------------------
# serving: submit_bind phase + drain
# ---------------------------------------------------------------------------


def test_submit_bind_phase_on_flight_record():
    sched, binds = _sched()
    adm = AdmissionController(sched, queue_depth=100)
    adm.node_churn(adds=make_cluster(4))
    assert adm.submit(make_pods(3, seed=17, name_prefix="q-")).ok
    sched.schedule_cycle()
    assert len(binds) == 3
    recs = [
        r for r in sched.flight.snapshot()
        if "submit_bind_ms" in r.phases
    ]
    assert recs, "no flight record carries the submit_bind phase"
    assert recs[-1].phases["submit_bind_ms"] > 0.0
    # the observer streamed it: scrape-time quantile is live
    assert sched.observer.quantile("submit_bind", 0.5) > 0.0


def test_front_door_drain_flushes_and_closes():
    sched, binds = _sched(multi_cycle_k=4, multi_cycle_max_wait_ms=1e6)
    adm = AdmissionController(sched, queue_depth=100)
    adm.node_churn(adds=make_cluster(4))
    fd = FrontDoor(adm)
    fd.start()
    assert adm.submit(make_pods(4, seed=18, name_prefix="r-")).ok
    drained = fd.stop()  # closes admission, flushes buffered groups
    assert drained
    assert adm.closed
    assert sched.queue.pending_counts()["active"] == 0
    assert not any(sched._mc_groups.values())
    assert len(binds) == 4
    assert adm.submit(
        make_pods(1, seed=19, name_prefix="s-")
    ).reason == "draining"


def test_resubmit_after_bind_is_rejected():
    """A client retrying a Submit whose ack was lost AFTER the pod
    bound must not re-admit it: note_bind has already dropped the uid
    from the accepted-pending set, so the cache (assumed or bound) is
    the dup authority — re-queueing a bound pod double-schedules it."""
    sched, binds = _sched()
    adm = AdmissionController(sched, queue_depth=100)
    adm.node_churn(adds=make_cluster(2))
    p = make_pods(1, seed=40, name_prefix="rb-")[0]
    assert adm.submit([p]).ok
    sched.schedule_cycle()
    assert binds.get(p.uid) == 1
    res = adm.submit([p])  # retry after bind: duplicate, not fresh
    assert res.invalid and "already bound" in res.reason
    sched.schedule_cycle()
    assert binds.get(p.uid) == 1  # still exactly one bind
    # a genuine delete frees the uid for re-creation
    sched.on_pod_delete(p.uid)
    assert adm.submit([p]).ok


def test_serve_loop_survives_cycle_exception():
    """A host-side exception escaping the cycle must not silently kill
    the serve thread while admission keeps acking: the loop logs,
    counts, backs off, and keeps serving — accepted pods dispatch the
    moment the fault clears."""
    sched, binds = _sched()
    adm = AdmissionController(sched, queue_depth=100)
    adm.node_churn(adds=make_cluster(2))
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise RuntimeError("injected host bug")
        return sched.schedule_cycle()

    fd = FrontDoor(adm, cycle_fn=flaky)
    fd._failure_backoff = 0.01
    fd.start()
    try:
        assert adm.submit(make_pods(2, seed=41, name_prefix="fl-")).ok
        deadline = time.monotonic() + 30
        while len(binds) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(binds) == 2, "loop never recovered from the fault"
        assert fd.cycle_failures == 2
    finally:
        fd.stop(drain=False)


def test_serve_loop_fails_shut_on_fatal_exit():
    """A BaseException killing the loop thread outright (the
    non-Exception escape the retry path cannot absorb) must close
    admission: the door never acks durable pods into a serve loop
    that no longer exists."""
    sched, _ = _sched()
    adm = AdmissionController(sched, queue_depth=100)

    def fatal():
        raise SystemExit(1)

    fd = FrontDoor(adm, cycle_fn=fatal)
    # the injected BaseException IS the test — keep pytest's
    # unhandled-thread-exception hook from flagging it as a warning
    old_hook = threading.excepthook
    threading.excepthook = lambda args: None
    fd.start()
    try:
        deadline = time.monotonic() + 10
        while not adm.closed and time.monotonic() < deadline:
            time.sleep(0.01)
        assert adm.closed
        res = adm.submit(make_pods(1, seed=42, name_prefix="ft-"))
        assert res.reason == "draining" and res.shed == 1
    finally:
        fd.stop(drain=False)
        threading.excepthook = old_hook


def test_local_front_door_confirms_binds_no_ttl_rebind():
    """The agentless CLI path (`--submit-addr`): run_local_cycle
    discards the response-collection list, so without the
    self-confirming binder chain every assumed bind would TTL-expire
    ('AssumeExpired') and re-bind forever. With it, binds are
    confirmed through the informer path each cycle: exactly one bind
    per pod outlives many TTL windows, and the pod lands bound (not
    assumed) in the cache."""
    from k8s_scheduler_tpu.service.admission import (
        self_confirming_front_door,
    )
    from k8s_scheduler_tpu.service.server import SchedulerService

    svc = SchedulerService(
        config=SchedulerConfiguration(
            pod_initial_backoff_seconds=0.05,
            pod_max_backoff_seconds=0.2,
        )
    )
    sched = svc.scheduler
    sched.cache._ttl = 0.05  # expiry chances galore within the test
    adm = AdmissionController(sched, queue_depth=100)
    fd = self_confirming_front_door(svc, adm)
    counts: dict[str, int] = {}
    inner = sched.binder  # the confirm-chained binder

    def counting(p, n):
        counts[p.uid] = counts.get(p.uid, 0) + 1
        inner(p, n)

    sched.binder = counting
    adm.node_churn(adds=make_cluster(2))
    pods = make_pods(3, seed=43, name_prefix="cf-")
    fd.start()
    try:
        assert adm.submit(pods).ok
        deadline = time.monotonic() + 60
        while len(counts) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(counts) == 3, "pods never bound"
        # outlive several TTL windows with the loop running: a missing
        # confirmation would AssumeExpired-requeue and re-bind here
        time.sleep(0.5)
        assert all(c == 1 for c in counts.values()), counts
        for p in pods:
            assert sched.cache.has_pod(p.uid)
            assert not sched.cache.is_assumed(p.uid)
    finally:
        fd.stop(drain=False)


# ---------------------------------------------------------------------------
# HTTP POST path + healthz
# ---------------------------------------------------------------------------


def test_http_submit_path_and_degraded_healthz():
    from k8s_scheduler_tpu.cmd.httpserver import (
        staleness_healthz,
        start_http_server,
        stop_http_server,
    )
    from k8s_scheduler_tpu.state.codec import pod_to_state

    sched, _ = _sched()
    adm = AdmissionController(sched, queue_depth=6, retry_after_ms=500.0)
    healthz = staleness_healthz(
        None, sched.flight, 0.0, observer=sched.observer,
        ladder=sched.ladder, admission=adm,
    )
    server = start_http_server(
        sched.metrics, port=0, healthz=healthz, admission=adm,
    )
    port = server.server_address[1]
    try:
        def post(body: bytes):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/submit", data=body,
                method="POST",
            )
            try:
                r = urllib.request.urlopen(req, timeout=10)
                return r.status, dict(r.headers), json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, dict(e.headers), json.loads(e.read())

        pods = make_pods(6, seed=20, name_prefix="t-")
        body = json.dumps(
            {"pods": [pod_to_state(p) for p in pods]}
        ).encode()
        status, _h, payload = post(body)
        assert status == 200 and payload["accepted"] == 6

        # over the bound: 429 + Retry-After
        more = make_pods(5, seed=21, name_prefix="u-")
        status, headers, payload = post(json.dumps(
            {"pods": [pod_to_state(p) for p in more]}
        ).encode())
        assert status == 429 and payload["shed"] == 5
        # RFC 7231: integer delta-seconds, rounded UP from 500 ms
        assert headers.get("Retry-After") == "1"

        # saturated: /healthz reports degraded (still 200)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10
        ) as r:
            assert r.status == 200
            detail = json.loads(r.read())
        assert detail["degraded"] is True
        assert "admission" in detail

        # garbage body: 400
        status, _h, payload = post(b"{not json")
        assert status == 400 and "error" in payload

        # oversized Content-Length: refused 413 BEFORE any read — the
        # bounded-memory contract holds on the HTTP path too
        with socket.create_connection(
            ("127.0.0.1", port), timeout=10
        ) as s:
            s.sendall(
                b"POST /submit HTTP/1.1\r\nHost: t\r\n"
                b"Content-Length: 10737418240\r\n\r\n"
            )
            first = s.recv(65536).split(b"\r\n", 1)[0]
        assert b"413" in first, first

        # POST anywhere else keeps the read-only 405 contract
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/metrics", data=b"x",
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 405
    finally:
        stop_http_server(server)


# ---------------------------------------------------------------------------
# gRPC round trip
# ---------------------------------------------------------------------------


def test_grpc_submit_shed_and_node_churn():
    import grpc

    from k8s_scheduler_tpu.service.client import SchedulerClient
    from k8s_scheduler_tpu.service.server import serve

    server, service, port = serve("127.0.0.1:0")
    client = SchedulerClient(f"127.0.0.1:{port}")
    try:
        # front door disabled: FAILED_PRECONDITION
        with pytest.raises(grpc.RpcError) as ei:
            client.submit(make_pods(1, seed=22, name_prefix="v-"))
        assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION

        service.enable_front_door(
            queue_depth=6, retry_after_ms=250.0
        )
        resp = client.node_churn(adds=make_cluster(3))
        assert resp.boot_id == service.boot_id
        resp = client.submit(make_pods(4, seed=23, name_prefix="w-"))
        assert resp.accepted == 4 and resp.queue_depth == 4

        with pytest.raises(grpc.RpcError) as ei:
            client.submit(make_pods(4, seed=24, name_prefix="x-"))
        e = ei.value
        assert e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        md = dict(e.trailing_metadata() or ())
        assert md.get("retry-after-ms") == "250"

        # a NAMELESS pod is the wire-reachable invalid case (an empty
        # uid re-derives as namespace/name in ObjectMeta.__post_init__,
        # so it cannot survive the round trip)
        bad = make_pods(1, seed=25, name_prefix="y-")[0]
        bad.metadata.name = ""
        with pytest.raises(grpc.RpcError) as ei:
            client.submit([bad])
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT

        # draining: UNAVAILABLE on both RPCs
        service.admission.close()
        with pytest.raises(grpc.RpcError) as ei:
            client.submit(make_pods(1, seed=26, name_prefix="z-"))
        assert ei.value.code() == grpc.StatusCode.UNAVAILABLE
        with pytest.raises(grpc.RpcError) as ei:
            client.node_churn(deletes=["node-0"])
        assert ei.value.code() == grpc.StatusCode.UNAVAILABLE
    finally:
        client.close()
        server.stop(grace=0)


def test_bench_diff_gates_host_encode_metrics(tmp_path):
    """bench_diff config-10 gates: finalize_p50_ms rise = regressed,
    encode_hidden_pct drop = regressed, --min-encode-hidden floors the
    new artifact absolutely — and all three stay backward-compatible
    with artifacts predating config 10 (r05)."""
    old = {"configs": [{
        "config": 10, "encode_hidden_pct": 96.0, "finalize_p50_ms": 1.0,
    }]}
    worse = {"configs": [{"c": 10, "ehid": 40.0, "finp50": 8.0}]}
    r05 = {"configs": [{"config": 2, "p50_ms": 10.0}]}
    paths = {}
    for name, art in (("old", old), ("worse", worse), ("r05", r05)):
        p = tmp_path / f"{name}.json"
        p.write_text(json.dumps(art))
        paths[name] = str(p)

    def diff(a, b, *extra):
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "bench_diff.py"),
             "--json", *extra, a, b],
            capture_output=True, text=True,
        )
        return proc.returncode, json.loads(proc.stdout)

    rc, res = diff(paths["old"], paths["old"])
    assert rc == 0, res
    rc, res = diff(paths["old"], paths["worse"])
    assert rc == 1
    regressed = {c["metric"] for c in res["regressions"]}
    assert {"finalize_p50_ms", "encode_hidden_pct"} <= regressed
    # the absolute floor trips even when the relative drift passes
    rc, res = diff(paths["old"], paths["old"], "--min-encode-hidden", "97")
    assert rc == 1
    assert any(
        c["metric"] == "encode_hidden_pct_floor"
        for c in res["regressions"]
    )
    # r05-era artifact without config 10: skipped, not crashed
    rc, res = diff(paths["r05"], paths["old"])
    assert rc == 0, res


# ---------------------------------------------------------------------------
# slow tier
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fuzz_arrivals_via_api_bit_equal():
    from k8s_scheduler_tpu.fuzz import generate_trace, run_api_case

    for seed, mc in ((7, False), (1234, True)):
        trace = generate_trace(seed, multi_cycle=mc)
        failures = run_api_case(trace)
        assert not failures, (
            f"seed {seed} mc={mc}: {[str(f) for f in failures[:3]]}"
        )


@pytest.mark.slow
def test_soak_overload_phase():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import soak_chaos

    result = soak_chaos.run_overload_phase(verbose=False)
    assert result["shed"] > 0
    assert result["max_queue_depth"] <= result["depth_bound"] + 8
    assert not result["lost"] and result["duplicate_binds"] == 0
    assert result["degraded_during_burst"] and result["final_rung"] == 0


@pytest.mark.slow
def test_bench_front_door_config_and_diff_gate(tmp_path):
    sys.path.insert(0, REPO)
    import bench_suite

    r = bench_suite.run_front_door_config(snapshots=6)
    assert r["config"] == 9 and r["shed_rate"] == 0.0
    assert r["overload_shed"] > 0 and r["drained"]
    assert r["submit_bind_p99_ms"] > 0.0
    # bench_diff round trip: the new keys gate directionally and a
    # self-diff is clean
    art = tmp_path / "fd.json"
    art.write_text(json.dumps({"configs": [r]}))
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_diff.py"),
         str(art), str(art)],
        capture_output=True, text=True,
    )
    assert p.returncode == 0, p.stdout + p.stderr
    assert "submit_bind_p99_ms" in p.stdout
    # a doubled submit p99 + nonzero shed rate must trip the gate
    worse = dict(r)
    worse["submit_bind_p99_ms"] = r["submit_bind_p99_ms"] * 3
    worse["shed_rate"] = 0.25
    art2 = tmp_path / "fd2.json"
    art2.write_text(json.dumps({"configs": [worse]}))
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_diff.py"),
         str(art), str(art2)],
        capture_output=True, text=True,
    )
    assert p.returncode == 1, p.stdout + p.stderr


@pytest.mark.slow
def test_bench_host_encode_config_and_diff_gate(tmp_path):
    sys.path.insert(0, REPO)
    import bench_suite

    r = bench_suite.run_host_encode_config(snapshots=6)
    assert r["config"] == 10
    # the incremental legs actually staged rows (a vacuous variant —
    # ladder degraded, mc gated off — raises inside the config, but
    # belt and braces here)
    assert r["ingest_hits"] > 0
    assert r["finalize_p50_ms"] > 0.0
    assert 0.0 <= r["encode_hidden_pct"] <= 100.0
    assert r["submit_bind_p50_ms"] > 0.0
    # self-diff round trip through the new gates is clean
    art = tmp_path / "he.json"
    art.write_text(json.dumps({"configs": [r]}))
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_diff.py"),
         str(art), str(art)],
        capture_output=True, text=True,
    )
    assert p.returncode == 0, p.stdout + p.stderr
    assert "encode_hidden_pct" in p.stdout


@pytest.mark.slow
def test_kill9_failover_mid_loadgen(tmp_path):
    """The acceptance soak's failover half: a REAL CLI front door
    (--submit-addr + --state-dir) under open-loop gRPC load is
    SIGKILLed mid-flood; the restored state must hold every acked pod
    (zero lost), and a standby scheduler binds each exactly once."""
    state_dir = str(tmp_path / "state")
    acked_log = str(tmp_path / "acked.log")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        submit_port = s.getsockname()[1]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    server = subprocess.Popen(
        [sys.executable, "-m", "k8s_scheduler_tpu",
         "--address", "127.0.0.1:0",
         "--submit-addr", f"127.0.0.1:{submit_port}",
         "--http-port", "-1",
         "--state-dir", state_dir,
         "--admission-queue-depth", "4096"],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    loadgen = None
    try:
        deadline = time.monotonic() + 120
        for line in server.stdout:
            if "front door: submissions on port" in line:
                break
            assert time.monotonic() < deadline, "server never came up"
        loadgen = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "scripts", "loadgen.py"),
             "--mode", "grpc", "--addr", f"127.0.0.1:{submit_port}",
             "--rate", "6000", "--duration", "30", "--batch", "4",
             "--nodes", "8", "--acked-log", acked_log],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        # let the flood run, then kill -9 the scheduler mid-load —
        # after enough acks AND enough wall time that the first cycles
        # completed, so the crash interleaves acked-pending, assumed,
        # and in-flight pods (not just a cold pre-dispatch queue)
        t_load = time.monotonic()
        deadline = t_load + 120
        while time.monotonic() < deadline:
            n_acked = 0
            if os.path.exists(acked_log) and os.path.getsize(acked_log):
                with open(acked_log) as f:
                    n_acked = sum(1 for _ in f)
            if n_acked >= 200 and time.monotonic() - t_load >= 15.0:
                break
            assert loadgen.poll() is None, loadgen.stdout.read()
            time.sleep(0.2)
        server.send_signal(signal.SIGKILL)
        server.wait()
        out, _ = loadgen.communicate(timeout=120)
        report = json.loads(out.strip().splitlines()[-1])
        assert report["stopped_draining"], (
            "loadgen never observed the kill"
        )
    finally:
        server.kill()
        server.wait()
        if loadgen is not None and loadgen.poll() is None:
            loadgen.kill()

    # the client-side ack journal is the oracle: every uid acked as
    # durable must be in the restored state — bound (in the cache) or
    # still pending — and bound at most once
    acked = []
    with open(acked_log) as f:
        for line in f:
            uid, durable = line.split()
            assert durable == "durable=True", line
            acked.append(uid)
    assert len(acked) >= 40
    q, c = _restore_bare(state_dir)
    pending = {p.uid for p in q.all_pending()}
    pending |= {e.pod.uid for e in q._in_flight.values()}
    bound = [p.uid for p, _n in c.existing_pods()]
    assert len(bound) == len(set(bound)), "duplicate binds in cache"
    tracked = pending | set(bound)
    lost = [u for u in acked if u not in tracked]
    assert not lost, (
        f"{len(lost)} acked pods lost across kill -9: {lost[:5]}"
    )

    # standby takeover: a fresh Scheduler on the same dir serves the
    # recovered queue and binds every remaining acked pod exactly once
    st = DurableState(state_dir, snapshot_interval_seconds=0)
    binds: dict[str, int] = {}
    standby = Scheduler(
        config=SchedulerConfiguration(
            pod_initial_backoff_seconds=0.05,
            pod_max_backoff_seconds=0.2,
        ),
        binder=lambda p, n: binds.__setitem__(
            p.uid, binds.get(p.uid, 0) + 1
        ),
        state=st,
    )
    assert standby.ladder.rung == 0
    deadline = time.monotonic() + 180
    while (
        standby.queue.pending_counts()["active"]
        and time.monotonic() < deadline
    ):
        standby.schedule_cycle()
        for pod, node in list(standby.cache.existing_pods()):
            pass  # no informer: assumed pods are fine for this check
    assert all(n == 1 for n in binds.values()), binds
    st.journal.close()
