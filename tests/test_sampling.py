"""percentageOfNodesToScore: the knob must have an observable effect
(VERDICT r1 #9 — previously parsed but dead)."""

from __future__ import annotations

import numpy as np

from k8s_scheduler_tpu.core.cycle import build_cycle_fn
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.models.builders import MakeNode, MakePod


def _cluster(n=200):
    return [MakeNode(f"n{i}").capacity({"cpu": "8"}).labels(
        {"slot": str(i)}) .obj() for i in range(n)]


def test_sampling_window_excludes_far_nodes():
    # rank-0's 50% window on this snapshot (cycle_index=1) covers
    # (c - 137) % 200 < 100, i.e. [137, 199] + [0, 36]; the only feasible
    # node (slot=100) sits outside it, so sampled scheduling must fail
    # where full scoring succeeds
    nodes = _cluster(200)
    pods = [MakePod("p0").req({"cpu": "1"})
            .node_selector({"slot": "100"}).obj()]
    snap = SnapshotEncoder().encode(nodes, pods)
    full = build_cycle_fn(percentage_of_nodes_to_score=100)(snap)
    sampled = build_cycle_fn(percentage_of_nodes_to_score=50)(snap)
    assert int(np.asarray(full.assignment)[0]) == 100
    assert int(np.asarray(sampled.assignment)[0]) == -1


def test_sampling_rotates_across_cycles_no_starvation():
    # the same pod re-encoded on later cycles gets different windows, so
    # an excluded-this-cycle node becomes reachable in a later cycle
    nodes = _cluster(200)
    pods = [MakePod("p0").req({"cpu": "1"})
            .node_selector({"slot": "100"}).obj()]
    enc = SnapshotEncoder()
    fn = build_cycle_fn(percentage_of_nodes_to_score=50)
    placed = []
    for _ in range(6):
        snap = enc.encode(nodes, pods)
        placed.append(int(np.asarray(fn(snap).assignment)[0]))
    assert 100 in placed, f"sampling starved the pod across cycles: {placed}"


def test_small_clusters_are_never_sampled():
    # <100-node floor: adaptive default must not drop candidates
    nodes = _cluster(50)
    pods = [MakePod("p0").req({"cpu": "1"})
            .node_selector({"slot": "49"}).obj()]
    snap = SnapshotEncoder().encode(nodes, pods)
    out = build_cycle_fn(percentage_of_nodes_to_score=0)(snap)
    assert int(np.asarray(out.assignment)[0]) == 49


def test_sampling_rotates_with_rank():
    # many identical pods: rotation spreads their windows, so a large
    # cluster still fills evenly under aggressive sampling
    nodes = _cluster(200)
    pods = [
        MakePod(f"p{i}").req({"cpu": "1"}).created(float(i)).obj()
        for i in range(100)
    ]
    snap = SnapshotEncoder().encode(nodes, pods)
    out = build_cycle_fn(percentage_of_nodes_to_score=50)(snap)
    a = np.asarray(out.assignment)[:100]
    assert (a >= 0).all()
    # windows rotate: placements are not all in the first half
    assert (a >= 100).any()
