"""Round-based batched commit (ops/rounds.py): validity invariants,
contention behaviour, determinism, and gang interplay.

The rounds engine deliberately does NOT replicate the strict scan's exact
placements (hash tie-break, scores against round-start state — the
documented semantics contract in ops/rounds.py), so these tests check the
properties that define correctness for it:

  - every placement is valid under the FINAL cluster state (capacity,
    ports, anti-affinity both directions, affinity w/ bootstrap, spread
    skew) — `oracle.validate_rounds_assignment`;
  - unplaced pods are genuinely infeasible against the final state;
  - contention workloads (same hostPort, self-anti-affinity, tight
    spread) converge across rounds to the same outcomes the sequential
    scan reaches;
  - identical snapshots produce identical assignments (determinism).
"""

from __future__ import annotations

import numpy as np
import pytest

from k8s_scheduler_tpu import oracle
from k8s_scheduler_tpu.core.cycle import build_cycle_fn
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.models.builders import MakeNode, MakePod
from k8s_scheduler_tpu.utils.synth import make_cluster, make_gang_pods, make_pods


def run_rounds(nodes, pods, existing=(), groups=(), **kw):
    snap = SnapshotEncoder().encode(nodes, pods, existing, groups)
    out = build_cycle_fn(commit_mode="rounds", **kw)(snap)
    a = np.asarray(out.assignment)[: len(pods)]
    return snap, out, a


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_rounds_validity_on_mixed_workload(seed):
    nodes = make_cluster(40, taint_fraction=0.2)
    pods = make_pods(
        250,
        seed=seed,
        affinity_fraction=0.3,
        anti_affinity_fraction=0.2,
        spread_fraction=0.2,
        selector_fraction=0.3,
        toleration_fraction=0.2,
        priorities=(0, 10, 100),
        num_apps=25,
    )
    _, out, a = run_rounds(nodes, pods)
    errors = oracle.validate_rounds_assignment(nodes, pods, a)
    assert errors == [], errors[:10]


def test_rounds_validity_with_existing_pods():
    nodes = make_cluster(30)
    existing_pods = make_pods(
        60, seed=7, name_prefix="run", affinity_fraction=0.2,
        anti_affinity_fraction=0.2, num_apps=10,
    )
    existing = [(p, f"node-{i % 30}") for i, p in enumerate(existing_pods)]
    pods = make_pods(
        120, seed=8, affinity_fraction=0.3, anti_affinity_fraction=0.3,
        spread_fraction=0.3, num_apps=10,
    )
    _, out, a = run_rounds(nodes, pods, existing=existing)
    errors = oracle.validate_rounds_assignment(nodes, pods, a, existing)
    assert errors == [], errors[:10]


def test_rounds_throughput_close_to_scan():
    nodes = make_cluster(50)
    pods = make_pods(
        300, affinity_fraction=0.3, anti_affinity_fraction=0.2,
        spread_fraction=0.2, num_apps=30,
    )
    snap = SnapshotEncoder().encode(nodes, pods)
    scan = build_cycle_fn(commit_mode="scan")(snap)
    rounds = build_cycle_fn(commit_mode="rounds")(snap)
    v = np.asarray(snap.pod_valid)
    n_scan = int((np.asarray(scan.assignment) >= 0)[v.nonzero()].sum())
    n_rounds = int((np.asarray(rounds.assignment) >= 0)[v.nonzero()].sum())
    # different tie-breaks can shift a few placements either way, but the
    # engines must agree on workload-level throughput
    assert abs(n_scan - n_rounds) <= max(3, int(0.02 * len(pods)))


def test_rounds_hostport_exclusive_per_node():
    # 12 pods all demanding hostPort 8080 on 4 nodes: exactly 4 place
    nodes = [MakeNode(f"n{i}").capacity({"cpu": "32"}).obj() for i in range(4)]
    pods = [
        MakePod(f"p{i}").req({"cpu": "1"}).host_port(8080).created(float(i)).obj()
        for i in range(12)
    ]
    _, out, a = run_rounds(nodes, pods)
    placed = a[a >= 0]
    assert len(placed) == 4
    assert len(set(placed.tolist())) == 4  # one per node
    assert oracle.validate_rounds_assignment(nodes, pods, a) == []


def test_rounds_self_anti_affinity_one_per_node():
    # classic one-replica-per-host: 6 replicas, 4 nodes -> 4 place
    nodes = [
        MakeNode(f"n{i}")
        .capacity({"cpu": "32"})
        .labels({"kubernetes.io/hostname": f"n{i}"})
        .obj()
        for i in range(4)
    ]
    pods = [
        MakePod(f"r{i}")
        .req({"cpu": "1"})
        .labels({"app": "db"})
        .pod_affinity("kubernetes.io/hostname", {"app": "db"}, anti=True)
        .created(float(i))
        .obj()
        for i in range(6)
    ]
    _, out, a = run_rounds(nodes, pods)
    placed = a[a >= 0]
    assert len(placed) == 4
    assert len(set(placed.tolist())) == 4
    assert oracle.validate_rounds_assignment(nodes, pods, a) == []


def test_rounds_spread_do_not_schedule_skew_holds():
    # 3 zones x 2 nodes, 10 replicas, maxSkew=1 -> counts differ by <= 1
    nodes = []
    for i in range(6):
        nodes.append(
            MakeNode(f"n{i}")
            .capacity({"cpu": "32"})
            .labels({"topology.kubernetes.io/zone": f"z{i % 3}"})
            .obj()
        )
    pods = [
        MakePod(f"w{i}")
        .req({"cpu": "1"})
        .labels({"app": "web"})
        .spread(1, "topology.kubernetes.io/zone", {"app": "web"})
        .created(float(i))
        .obj()
        for i in range(10)
    ]
    _, out, a = run_rounds(nodes, pods)
    assert (a >= 0).all()
    zone_of = [i % 3 for i in range(6)]
    counts = [0, 0, 0]
    for node in a:
        counts[zone_of[node]] += 1
    assert max(counts) - min(counts) <= 1, counts
    assert oracle.validate_rounds_assignment(nodes, pods, a) == []


def test_rounds_affinity_bootstrap_and_colocation():
    # a self-affine group: first pod bootstraps, the rest must co-locate
    # in its zone
    nodes = []
    for i in range(4):
        nodes.append(
            MakeNode(f"n{i}")
            .capacity({"cpu": "8"})
            .labels({"topology.kubernetes.io/zone": f"z{i % 2}"})
            .obj()
        )
    pods = [
        MakePod(f"g{i}")
        .req({"cpu": "1"})
        .labels({"app": "grp"})
        .pod_affinity("topology.kubernetes.io/zone", {"app": "grp"})
        .created(float(i))
        .obj()
        for i in range(5)
    ]
    _, out, a = run_rounds(nodes, pods)
    assert (a >= 0).all()
    zones = {("z0" if n in (0, 2) else "z1") for n in a.tolist()}
    assert len(zones) == 1, f"group split across zones: {sorted(zones)}"
    assert oracle.validate_rounds_assignment(nodes, pods, a) == []


def test_rounds_gang_unwind():
    nodes = [MakeNode(f"n{i}").capacity({"cpu": "4"}).obj() for i in range(2)]
    pods, groups = make_gang_pods(2, replicas=8, seed=3)
    # 16 pods wanting >= 1 cpu each on 8 cpus: no gang fully places ->
    # all-or-nothing unwind drops every placement of the failing group
    snap = SnapshotEncoder().encode(nodes, pods, (), groups)
    out = build_cycle_fn(commit_mode="rounds")(snap)
    a = np.asarray(out.assignment)[: len(pods)]
    dropped = np.asarray(out.gang_dropped)[: len(pods)]
    placed_by_group = {}
    for i, pod in enumerate(pods):
        if a[i] >= 0:
            placed_by_group.setdefault(pod.spec.pod_group, 0)
            placed_by_group[pod.spec.pod_group] += 1
    for g, n in placed_by_group.items():
        assert n >= 8, f"group {g} placed {n} < minMember yet not unwound"
    assert dropped.sum() >= 0  # unwind bookkeeping surfaced


def test_rounds_deterministic():
    nodes = make_cluster(30)
    pods = make_pods(
        200, affinity_fraction=0.3, anti_affinity_fraction=0.2,
        spread_fraction=0.2, num_apps=20,
    )
    snap = SnapshotEncoder().encode(nodes, pods)
    fn = build_cycle_fn(commit_mode="rounds")
    a1 = np.asarray(fn(snap).assignment)
    a2 = np.asarray(fn(snap).assignment)
    assert (a1 == a2).all()


def test_rounds_priority_dominance():
    # one node, one slot: the high-priority pod must win it
    nodes = [MakeNode("n0").capacity({"cpu": "2"}).obj()]
    pods = [
        MakePod("low").req({"cpu": "2"}).priority(0).created(0.0).obj(),
        MakePod("high").req({"cpu": "2"}).priority(100).created(1.0).obj(),
    ]
    _, out, a = run_rounds(nodes, pods)
    assert a[1] == 0 and a[0] == -1


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("shortlist", [2, 8, 32])
def test_shortlist_rounds_validity_on_mixed_workload(seed, shortlist):
    """The shortlist pass chain (incl. shortlist=2, which forces the
    rescue pass: up to `passes` in-round deaths exceed k) must keep the
    engine's defining invariants: final-state validity and
    unplaced => infeasible."""
    nodes = make_cluster(40, taint_fraction=0.2)
    pods = make_pods(
        250,
        seed=seed,
        affinity_fraction=0.3,
        anti_affinity_fraction=0.2,
        spread_fraction=0.2,
        selector_fraction=0.3,
        toleration_fraction=0.2,
        priorities=(0, 10, 100),
        num_apps=25,
    )
    _, out, a = run_rounds(nodes, pods,
                           rounds_kw={"shortlist": shortlist})
    errors = oracle.validate_rounds_assignment(nodes, pods, a)
    assert errors == [], errors[:10]


def test_shortlist_placement_quality_close_to_wide():
    """Shortlist placements must not collapse vs the wide engine: same
    cluster, placed-count within 3%."""
    nodes = make_cluster(30, cpu_choices=(2, 4))
    pods = make_pods(200, seed=5, selector_fraction=0.2,
                     priorities=(0, 10))
    _, _, a_wide = run_rounds(nodes, pods)
    _, _, a_sl = run_rounds(nodes, pods, rounds_kw={"shortlist": 8})
    placed_wide = int((a_wide >= 0).sum())
    placed_sl = int((a_sl >= 0).sum())
    assert placed_sl >= placed_wide * 0.97, (placed_wide, placed_sl)
