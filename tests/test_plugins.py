"""Differential tests for the label/taint/port/image plugin kernels vs the
oracle (benchmark config #2 territory: node-affinity + taints/tolerations)."""

import numpy as np
import pytest

from k8s_scheduler_tpu import oracle
from k8s_scheduler_tpu.core import build_cycle_fn
from k8s_scheduler_tpu.models import MakeNode, MakePod, SnapshotEncoder
from k8s_scheduler_tpu.models import api


def run_both(nodes, pods, existing=()):
    snap = SnapshotEncoder().encode(nodes, pods, existing)
    result = build_cycle_fn()(snap)
    got = np.asarray(result.assignment)[: len(pods)].tolist()
    want = [d.node_index for d in oracle.schedule(nodes, pods, existing)]
    return got, want


def test_node_selector():
    nodes = [
        MakeNode("gen").capacity({"cpu": "4"}).labels({"type": "general"}).obj(),
        MakeNode("cmp").capacity({"cpu": "4"}).labels({"type": "compute"}).obj(),
    ]
    pods = [
        MakePod("p0").req({"cpu": "1"}).node_selector({"type": "compute"}).obj(),
        MakePod("p1").req({"cpu": "1"}).node_selector({"type": "general"}).obj(),
        MakePod("p2").req({"cpu": "1"}).node_selector({"type": "gpu"}).obj(),
    ]
    got, want = run_both(nodes, pods)
    assert got == want == [1, 0, -1]


def test_node_affinity_required_in_notin():
    nodes = [
        MakeNode("a").capacity({"cpu": "4"}).labels({"zone": "east"}).obj(),
        MakeNode("b").capacity({"cpu": "4"}).labels({"zone": "west"}).obj(),
        MakeNode("c").capacity({"cpu": "4"}).obj(),  # no zone label
    ]
    from k8s_scheduler_tpu.models.api import NodeSelectorRequirement, NodeSelectorTerm

    pods = [
        MakePod("in-east").req({"cpu": "1"}).node_affinity_in("zone", ["east"]).obj(),
        # NotIn matches absent keys too: feasible on b and c
        MakePod("not-east").req({"cpu": "1"}).node_affinity_required(
            NodeSelectorTerm((NodeSelectorRequirement("zone", api.OP_NOT_IN, ("east",)),))
        ).obj(),
        MakePod("exists").req({"cpu": "1"}).node_affinity_required(
            NodeSelectorTerm((NodeSelectorRequirement("zone", api.OP_EXISTS),))
        ).obj(),
        MakePod("not-exists").req({"cpu": "1"}).node_affinity_required(
            NodeSelectorTerm((NodeSelectorRequirement("zone", api.OP_DOES_NOT_EXIST),))
        ).obj(),
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    assert got[0] == 0  # only east
    assert got[3] == 2  # only unlabeled


def test_node_affinity_gt_lt():
    from k8s_scheduler_tpu.models.api import NodeSelectorRequirement, NodeSelectorTerm

    nodes = [
        MakeNode("small").capacity({"cpu": "4"}).labels({"size": "10"}).obj(),
        MakeNode("big").capacity({"cpu": "4"}).labels({"size": "100"}).obj(),
        MakeNode("odd").capacity({"cpu": "4"}).labels({"size": "huge"}).obj(),
    ]
    pods = [
        MakePod("gt50").req({"cpu": "1"}).node_affinity_required(
            NodeSelectorTerm((NodeSelectorRequirement("size", api.OP_GT, ("50",)),))
        ).obj(),
        MakePod("lt50").req({"cpu": "1"}).node_affinity_required(
            NodeSelectorTerm((NodeSelectorRequirement("size", api.OP_LT, ("50",)),))
        ).obj(),
    ]
    got, want = run_both(nodes, pods)
    assert got == want == [1, 0]


def test_node_affinity_or_of_terms():
    nodes = [
        MakeNode("a").capacity({"cpu": "4"}).labels({"zone": "east"}).obj(),
        MakeNode("b").capacity({"cpu": "4"}).labels({"tier": "gold"}).obj(),
        MakeNode("c").capacity({"cpu": "4"}).obj(),
    ]
    from k8s_scheduler_tpu.models.api import NodeSelectorRequirement, NodeSelectorTerm

    # two terms = OR: zone=east OR tier=gold
    pods = [
        MakePod("p").req({"cpu": "1"}).node_affinity_required(
            NodeSelectorTerm((NodeSelectorRequirement("zone", api.OP_IN, ("east",)),)),
            NodeSelectorTerm((NodeSelectorRequirement("tier", api.OP_IN, ("gold",)),)),
        ).obj()
        for _ in range(3)
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    assert -1 not in got[:2] and got[2] in (0, 1)


def test_node_affinity_preferred_steers():
    nodes = [
        MakeNode("plain").capacity({"cpu": "8"}).obj(),
        MakeNode("ssd").capacity({"cpu": "8"}).labels({"disk": "ssd"}).obj(),
    ]
    pods = [
        MakePod("p").req({"cpu": "1"})
        .node_affinity_preferred(100, "disk", ["ssd"]).obj()
    ]
    got, want = run_both(nodes, pods)
    assert got == want == [1]


def test_taints_block_and_tolerations_admit():
    nodes = [
        MakeNode("tainted").capacity({"cpu": "8"}).taint("gpu", "yes").obj(),
        MakeNode("open").capacity({"cpu": "2"}).obj(),
    ]
    pods = [
        MakePod("tolerant").req({"cpu": "1"})
        .toleration("gpu", "yes", api.NO_SCHEDULE).obj(),
        MakePod("plain-1").req({"cpu": "1"}).obj(),
        MakePod("plain-2").req({"cpu": "1"}).obj(),
        MakePod("plain-3").req({"cpu": "1"}).obj(),  # open node full -> -1
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    assert want[0] == 0  # tolerant pod prefers the empty tainted node
    assert want[3] == -1


def test_toleration_exists_and_wildcard():
    nodes = [
        MakeNode("t1").capacity({"cpu": "4"}).taint("a", "1").obj(),
        MakeNode("t2").capacity({"cpu": "4"}).taint("b", "2", api.NO_EXECUTE).obj(),
    ]
    pods = [
        # operator Exists on key a: tolerates any value of a
        MakePod("ex").req({"cpu": "1"}).toleration("a", op="Exists").obj(),
        # empty key + Exists: tolerates everything
        MakePod("wild").req({"cpu": "1"}).toleration("", op="Exists").obj(),
        MakePod("none").req({"cpu": "1"}).obj(),
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    assert got[0] == 0 and got[1] in (0, 1) and got[2] == -1


def test_prefer_no_schedule_scoring():
    nodes = [
        MakeNode("soft").capacity({"cpu": "8"})
        .taint("maint", "true", api.PREFER_NO_SCHEDULE).obj(),
        MakeNode("clean").capacity({"cpu": "8"}).obj(),
    ]
    pods = [MakePod("p").req({"cpu": "1"}).obj()]
    got, want = run_both(nodes, pods)
    assert got == want == [1]  # PreferNoSchedule steers away, doesn't block


def test_host_ports_conflict_with_existing():
    nodes = [MakeNode("n0").capacity({"cpu": "8"}).obj(),
             MakeNode("n1").capacity({"cpu": "8"}).obj()]
    existing = [(MakePod("web").req({"cpu": "1"}).host_port(80).obj(), "n0")]
    pods = [MakePod("also-web").req({"cpu": "1"}).host_port(80).obj()]
    got, want = run_both(nodes, pods, existing)
    assert got == want == [1]


def test_image_locality_steers():
    img = "registry/model-server:v1"
    nodes = [
        MakeNode("cold").capacity({"cpu": "8"}).obj(),
        MakeNode("warm").capacity({"cpu": "8"}).image(img, 800 * 2**20).obj(),
    ]
    pods = [MakePod("p").req({"cpu": "1"}).image(img).obj()]
    got, want = run_both(nodes, pods)
    assert got == want == [1]


@pytest.mark.parametrize("seed", range(8))
def test_randomized_differential_with_labels(seed):
    rng = np.random.default_rng(100 + seed)
    n_nodes = int(rng.integers(3, 10))
    zones = ["za", "zb", "zc"]
    nodes = []
    for i in range(n_nodes):
        b = MakeNode(f"n{i}").capacity(
            {"cpu": f"{rng.integers(2, 16)}", "memory": f"{rng.integers(4, 32)}Gi"}
        ).labels({"zone": zones[i % 3], "idx": str(i)})
        if rng.random() < 0.3:
            b.taint("dedicated", "x")
        if rng.random() < 0.2:
            b.unschedulable()
        nodes.append(b.obj())
    pods = []
    for i in range(int(rng.integers(5, 25))):
        b = MakePod(f"p{i}").req(
            {"cpu": f"{rng.integers(100, 3000)}m",
             "memory": f"{rng.integers(256, 2048)}Mi"}
        ).priority(int(rng.integers(0, 3))).created(float(i))
        r = rng.random()
        if r < 0.3:
            b.node_affinity_in("zone", [zones[int(rng.integers(0, 3))]])
        elif r < 0.5:
            b.node_selector({"zone": zones[int(rng.integers(0, 3))]})
        if rng.random() < 0.4:
            b.toleration("dedicated", "x", api.NO_SCHEDULE)
        if rng.random() < 0.3:
            b.node_affinity_preferred(
                int(rng.integers(1, 100)), "zone", [zones[int(rng.integers(0, 3))]]
            )
        pods.append(b.obj())
    got, _ = run_both(nodes, pods)
    # trajectory validation, not exact equality: f32 kernel scores can tie
    # where the f64 oracle differs in the 7th digit (see validate_assignment)
    errors = oracle.validate_assignment(nodes, pods, got)
    assert not errors, errors


def test_host_ports_conflict_within_batch():
    # two pending pods want the same host port; one node is strongly
    # preferred — the scan's port-claim bitmap must push the second pod to
    # the other node, exactly like the oracle's sequential NodeInfo update
    nodes = [MakeNode("n0").capacity({"cpu": "8"}).obj(),
             MakeNode("n1").capacity({"cpu": "8"}).obj()]
    pods = [
        MakePod("web-a").req({"cpu": "1"}).host_port(80).created(0).obj(),
        MakePod("web-b").req({"cpu": "1"}).host_port(80).created(1).obj(),
        MakePod("web-c").req({"cpu": "1"}).host_port(80).created(2).obj(),
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    assert sorted(got[:2]) == [0, 1] and got[2] == -1


def test_unknown_plugin_in_config_raises():
    import pytest as _pytest

    from k8s_scheduler_tpu.config import load_config
    from k8s_scheduler_tpu.framework.runtime import Framework

    cfg = load_config("""
profiles:
- plugins:
    score:
      enabled: [{name: NodePort, weight: 5}]
""")
    with _pytest.raises(KeyError, match="NodePort"):
        Framework.from_config(cfg)
