"""Watchtower tests (ISSUE 20): the in-process metrics TSDB, the
declarative alert-rule engine, and the crash black box.

Layout mirrors the subsystem:

- TSDB storage: ring wrap, tier downsampling vs a numpy reference,
  seqlock snapshot consistency under a live concurrent writer, the
  series-cardinality ceiling, and the unarmed-hook overhead contract.
- Rules: `for`-duration gating, clear-threshold + symmetric-hold
  hysteresis (no flap), recording rules, file loading, validation —
  and the FaultPlan-shaped acceptance scenario: a stall burst fires
  `tunnel_stall_burst` only after its hold, then resolves cleanly,
  with both wall timestamps queryable.
- Black box: bundle round-trip through `scripts/blackbox_read.py`,
  retention rotation, throttling, and the unarmed trigger no-op.
- Endpoints: /debug/metrics/history, /debug/alerts, /debug/dashboard,
  the /debug/anomalies tenant filter, and the /debug/state ladder
  transition ring.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from k8s_scheduler_tpu.core import blackbox as _blackbox
from k8s_scheduler_tpu.core.degrade import DegradationLadder
from k8s_scheduler_tpu.core.observe import CycleObserver
from k8s_scheduler_tpu.metrics import tsdb as _tsdb
from k8s_scheduler_tpu.metrics.metrics import SchedulerMetrics
from k8s_scheduler_tpu.metrics.rules import (
    Rule,
    RuleEngine,
    builtin_rules,
    load_rules_file,
    replay_alerts,
    scale_rules,
)
from k8s_scheduler_tpu.metrics.tsdb import MetricsTSDB

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends with both modules disarmed."""
    yield
    _tsdb.disarm()
    _blackbox.disarm()


# ---- TSDB storage ---------------------------------------------------------


def test_raw_ring_wraps_and_keeps_newest():
    db = MetricsTSDB(raw_cap=16, sec_cap=16, min_cap=16)
    for i in range(40):
        db.append("f", (), float(i), t=1000.0 + i)
    q = db.query("f", window_s=1e9, now=1040.0)
    (s,) = q["series"]
    assert s["total_samples"] == 40
    assert len(s["points"]) == 16  # capped at the ring size
    assert [p[1] for p in s["points"]] == [float(i) for i in range(24, 40)]
    ts = [p[0] for p in s["points"]]
    assert ts == sorted(ts)


def test_query_tier_selection_and_window_clip():
    db = MetricsTSDB()
    for i in range(120):
        db.append("f", {"k": "a"}, float(i), t=1000.0 + i)
    raw = db.query("f", window_s=10.0, now=1120.0)
    assert raw["tier"] == "raw"
    assert all(len(p) == 2 and p[0] >= 1110.0 for p in raw["series"][0]["points"])
    sec = db.query("f", window_s=30.0, step_s=1.0, now=1120.0)
    assert sec["tier"] == "1s"
    assert all(len(p) == 6 for p in sec["series"][0]["points"])
    mn = db.query("f", window_s=1e9, step_s=60.0, now=1120.0)
    assert mn["tier"] == "1m"
    # 120 one-second samples spanning 1000..1119 cover exactly 2 full
    # minute buckets + the open one
    assert len(mn["series"][0]["points"]) == 3


def test_label_selector_is_subset_match():
    db = MetricsTSDB()
    db.append("f", {"cls": "a", "x": "1"}, 1.0, t=10.0)
    db.append("f", {"cls": "b", "x": "1"}, 2.0, t=10.0)
    q = db.query("f", labels={"cls": "a"}, window_s=1e9, now=11.0)
    assert len(q["series"]) == 1
    assert q["series"][0]["labels"] == {"cls": "a", "x": "1"}
    q = db.query("f", labels={"x": "1"}, window_s=1e9, now=11.0)
    assert len(q["series"]) == 2


def test_downsample_matches_numpy_reference():
    """1 s and 1 m buckets (flushed + open) agree with a numpy groupby
    over the same randomized series."""
    rng = np.random.default_rng(7)
    t0 = 5000.0
    ts = np.sort(t0 + rng.uniform(0, 180.0, size=400))
    vs = rng.normal(10.0, 4.0, size=400)
    db = MetricsTSDB(raw_cap=1024, sec_cap=1024, min_cap=64)
    for t, v in zip(ts, vs):
        db.append("f", (), float(v), t=float(t))
    for step, width in ((1.0, 1.0), (60.0, 60.0)):
        q = db.query("f", window_s=1e9, step_s=step, now=float(ts[-1]) + 1)
        (s,) = q["series"]
        for bt, mn, mx, sm, cnt, last in s["points"]:
            mask = (ts >= bt) & (ts < bt + width)
            ref = vs[mask]
            assert cnt == int(mask.sum())
            assert mn == pytest.approx(ref.min())
            assert mx == pytest.approx(ref.max())
            assert sm == pytest.approx(ref.sum())
            assert last == pytest.approx(ref[-1])
        # the buckets cover every sample exactly once
        assert sum(p[4] for p in s["points"]) == len(ts)


def test_seqlock_snapshot_consistent_under_live_writer():
    """A reader snapshotting while a writer appends never sees a torn
    point: every raw point keeps the v == t invariant the writer
    maintains, and timestamps stay strictly increasing."""
    db = MetricsTSDB(raw_cap=64, sec_cap=64, min_cap=64)
    stop = threading.Event()
    wrote = [0]

    def writer():
        i = 0
        while not stop.is_set():
            db.append("f", (), float(i), t=float(i))
            i += 1
        wrote[0] = i

    th = threading.Thread(target=writer)
    th.start()
    try:
        deadline = time.monotonic() + 0.5
        reads = 0
        while time.monotonic() < deadline:
            q = db.query("f", window_s=1e9, now=1e12)
            for pt in q["series"][0]["points"] if q["series"] else []:
                assert pt[0] == pt[1]  # never a half-written pair
            snap = db.snapshot_all()
            for s in snap["series"]:
                ts = [p[0] for p in s["raw"]]
                assert ts == sorted(ts)
                for t, v in s["raw"]:
                    assert t == v
            reads += 1
    finally:
        stop.set()
        th.join()
    assert reads > 10 and wrote[0] > 100


def test_series_cardinality_ceiling_drops_not_grows():
    db = MetricsTSDB(max_series=4)
    for i in range(10):
        db.append("f", {"i": str(i)}, 1.0, t=10.0)
    st = db.status()
    assert st["series"] == 4
    assert st["dropped_series"] == 6


def test_unarmed_observe_record_is_a_noop():
    """The unarmed hook must not sample (one flag check and out)."""

    class Rec:
        wall_start = 1.0
        phases = {"total": 5.0}
        counts = {"pods": 3}

    db = MetricsTSDB()
    assert not _tsdb.ARMED
    db.observe_record(Rec())
    assert db.status()["series"] == 0
    _tsdb.arm(db)
    db.observe_record(Rec())
    assert db.status()["series"] == 2  # cycle_phase_ms + cycle_count
    fams = {f["family"] for f in db.families()}
    assert fams == {"cycle_phase_ms", "cycle_count"}


def test_arm_disarm_keeps_store_readable():
    db = _tsdb.arm(MetricsTSDB())
    db.append("f", (), 1.0, t=5.0)
    _tsdb.disarm()
    assert not _tsdb.ARMED and _tsdb.STORE is None
    # post-mortem reads still work (the black box relies on this)
    assert db.query("f", window_s=1e9, now=6.0)["series"]


def test_ticker_samples_registry_gauges(tmp_path):
    gm = SchedulerMetrics()
    db = _tsdb.arm(MetricsTSDB())
    db.start_ticker(gm.registry, interval_s=0.05)
    try:
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            q = db.query("scheduler_uptime_seconds", window_s=1e9)
            if q["series"] and q["series"][0]["points"]:
                break
            time.sleep(0.05)
        else:
            pytest.fail("ticker never sampled scheduler_uptime_seconds")
        # scrape-time gauge evaluated through collect() (whole seconds,
        # so a sub-second-old process legitimately reads 0)
        assert q["series"][0]["points"][-1][1] >= 0.0
        # histogram bucket fan-out is excluded from storage
        assert not [f for f in db.families()
                    if f["family"].endswith("_bucket")]
    finally:
        _tsdb.disarm()
    assert db._ticker is None  # disarm joined the ticker thread


# ---- rules ----------------------------------------------------------------


def _mk_engine(rule: Rule, **kw):
    db = MetricsTSDB()
    return db, RuleEngine([rule], db, **kw)


def test_for_duration_gates_firing():
    rule = Rule(name="r", family="f", agg="last", window_s=10.0,
                threshold=1.0, for_s=5.0)
    db, eng = _mk_engine(rule)
    st = eng._states["r"]
    db.append("f", (), 2.0, t=100.0)
    eng.evaluate(now=100.0)
    assert st.stage == "pending" and eng.fired_total == 0
    db.append("f", (), 2.0, t=103.0)
    eng.evaluate(now=103.0)  # held 3 s < for_s
    assert st.stage == "pending" and eng.fired_total == 0
    db.append("f", (), 2.0, t=105.5)
    eng.evaluate(now=105.5)  # held 5.5 s >= for_s
    assert st.stage == "firing" and eng.fired_total == 1
    (active,) = eng.status()["active"]
    assert active["fired_wall"] == 105.5
    assert active["resolved_wall"] is None


def test_pending_resets_when_condition_breaks_before_hold():
    rule = Rule(name="r", family="f", agg="last", window_s=10.0,
                threshold=1.0, for_s=5.0)
    db, eng = _mk_engine(rule)
    db.append("f", (), 2.0, t=100.0)
    eng.evaluate(now=100.0)
    db.append("f", (), 0.0, t=102.0)  # breaks before the hold
    eng.evaluate(now=102.0)
    assert eng._states["r"].stage == "ok"
    db.append("f", (), 2.0, t=104.0)
    eng.evaluate(now=104.0)
    eng.evaluate(now=108.0)  # held only 4 s since the RESTART
    assert eng.fired_total == 0


def test_hysteresis_no_flap_and_resolve_timestamps():
    """Once firing, values oscillating between `clear` and `threshold`
    keep the alert firing; resolution needs the value below `clear`
    held for the symmetric duration — then both wall timestamps land
    in the resolved tail."""
    rule = Rule(name="r", family="f", agg="last", window_s=30.0,
                threshold=1.0, for_s=4.0, clear=0.3)
    db, eng = _mk_engine(rule)
    st = eng._states["r"]
    for t in (100.0, 105.0):
        db.append("f", (), 2.0, t=t)
        eng.evaluate(now=t)
    assert st.stage == "firing" and eng.fired_total == 1
    # oscillate in the hysteresis band: below threshold, above clear
    for t in (107.0, 109.0, 111.0, 113.0):
        db.append("f", (), 0.6 if int(t) % 4 else 1.4, t=t)
        eng.evaluate(now=t)
        assert st.stage == "firing", t
    # drop below clear, but pop back up once before the hold elapses:
    # the clear clock must restart, not resolve
    db.append("f", (), 0.1, t=115.0)
    eng.evaluate(now=115.0)
    db.append("f", (), 0.6, t=117.0)
    eng.evaluate(now=117.0)
    assert st.stage == "firing"
    # now hold below clear for >= for_s
    db.append("f", (), 0.1, t=119.0)
    eng.evaluate(now=119.0)
    db.append("f", (), 0.1, t=124.0)
    eng.evaluate(now=124.0)
    assert st.stage == "ok"
    assert eng.fired_total == 1  # one firing, despite all oscillation
    status = eng.status()
    assert status["active"] == []
    (resolved,) = status["resolved"]
    assert resolved["fired_wall"] == 105.0
    assert resolved["resolved_wall"] == 124.0
    assert resolved["resolved_wall"] > resolved["fired_wall"]


def test_rate_agg_sums_series_and_clamps_counter_reset():
    rule = Rule(name="r", family="f", agg="rate", window_s=100.0,
                threshold=0.5, for_s=0.0)
    db, eng = _mk_engine(rule)
    # two labelsets, each rising 1/s -> combined rate 2/s
    for t in range(100, 111):
        db.append("f", {"k": "a"}, float(t - 100), t=float(t))
        db.append("f", {"k": "b"}, float(t - 100), t=float(t))
    assert eng._value(rule, now=110.0) == pytest.approx(2.0)
    # a counter reset reads as quiet, not a huge negative rate
    db2, eng2 = _mk_engine(rule)
    db2.append("f", (), 1000.0, t=100.0)
    db2.append("f", (), 1.0, t=110.0)
    assert eng2._value(rule, now=110.0) == 0.0


def test_recording_rule_appends_derived_series():
    rule = Rule(name="rec", family="f", agg="rate", window_s=60.0,
                kind="record", record_as="f_rate_1m")
    db, eng = _mk_engine(rule)
    for t in range(100, 120):
        db.append("f", (), float(t - 100), t=float(t))
    eng.evaluate(now=119.0)
    q = db.query("f_rate_1m", window_s=1e9, now=120.0)
    assert q["series"][0]["points"][-1][1] == pytest.approx(1.0)


def test_rule_validation_and_file_loading(tmp_path):
    with pytest.raises(ValueError):
        Rule.from_dict({"name": "x", "family": "f", "agg": "wat",
                        "window_s": 1.0})
    with pytest.raises(ValueError):
        Rule.from_dict({"name": "x", "family": "f", "agg": "avg",
                        "window_s": 1.0, "severity": "page-me"})
    with pytest.raises(ValueError):
        Rule.from_dict({"name": "x", "family": "f", "agg": "avg",
                        "window_s": 1.0, "kind": "record"})  # no record_as
    rules_json = tmp_path / "rules.json"
    rules_json.write_text(json.dumps([
        {"name": "x", "family": "f", "agg": "avg", "window_s": 5.0,
         "threshold": 2.0, "labels": {"k": "v"}},
    ]))
    (r,) = load_rules_file(str(rules_json))
    assert r.labels == (("k", "v"),)
    rules_yaml = tmp_path / "rules.yaml"
    rules_yaml.write_text(
        "- name: y\n  family: g\n  agg: max\n  window_s: 9\n"
        "  threshold: 3\n")
    (r,) = load_rules_file(str(rules_yaml))
    assert r.name == "y" and r.window_s == 9.0


def test_scale_rules_shrinks_windows_only():
    scaled = scale_rules(builtin_rules(), 0.1)
    orig = {r.name: r for r in builtin_rules()}
    for r in scaled:
        assert r.window_s == pytest.approx(orig[r.name].window_s * 0.1)
        assert r.for_s == pytest.approx(orig[r.name].for_s * 0.1)
        assert r.threshold == orig[r.name].threshold


def test_builtin_pack_parses_and_is_quiet_on_empty_store():
    db = MetricsTSDB()
    eng = RuleEngine(builtin_rules(), db)
    eng.evaluate(now=100.0)
    assert eng.fired_total == 0
    assert {r["state"] for r in eng.status()["rules"]} <= {"ok"}


# ---- the FaultPlan-shaped stall acceptance scenario -----------------------


def test_faultplan_stall_burst_fires_after_hold_and_resolves():
    """The acceptance scenario: a FaultPlan drives which cycles stall
    (the `fetch_hang` grammar), the PRODUCTION anomaly classifier turns
    the stalls into `tunnel_stall` anomalies, and the unmodified
    built-in `tunnel_stall_burst` rule fires only after its 10 s hold,
    stays up through the burst, and resolves with hysteresis once the
    plan goes quiet — with both timestamps queryable."""
    from k8s_scheduler_tpu.core import faults

    plan = faults.FaultPlan.parse("fetch_hang@cycle=40..75:ms=28000")
    metrics = SchedulerMetrics()
    obs = CycleObserver(metrics=metrics)
    db = MetricsTSDB()
    eng = RuleEngine(
        [r for r in builtin_rules() if r.name == "tunnel_stall_burst"],
        db, observer=obs, metrics=metrics)
    st = eng._states["tunnel_stall_burst"]
    fired_at = resolved_at = None
    first_stall = None
    for c in range(140):
        hang = plan.fire("fetch_hang", c)
        t = 28.0 if hang is not None else 0.5
        obs.observe_phases(
            {"total": t, "device": t, "decision_fetch": t},
            profile="fault", seq=c)
        now = float(c + 1)  # virtual clock: 1 s per cycle
        n = obs.anomaly_counts.get("tunnel_stall", 0)
        if n and first_stall is None:
            first_stall = now
        db.append("scheduler_anomalies_total",
                  {"class": "tunnel_stall"}, float(n), t=now)
        eng.evaluate(now=now)
        if st.stage == "firing" and fired_at is None:
            fired_at = now
        if fired_at is not None and resolved_at is None \
                and st.stage == "ok":
            resolved_at = now
    assert first_stall is not None  # the classifier saw the fault
    assert fired_at is not None and resolved_at is not None
    # the for-duration gated the page: never before hold elapsed
    assert fired_at >= first_stall + 10.0
    assert resolved_at > 75  # only after the plan went quiet
    assert eng.fired_total == 1  # burst + recovery, zero flap
    (resolved,) = eng.status()["resolved"]
    assert resolved["rule"] == "tunnel_stall_burst"
    assert resolved["severity"] == "critical"
    assert resolved["fired_wall"] == fired_at
    assert resolved["resolved_wall"] == resolved_at
    # the firing raised the `alert` anomaly with rule attribution
    alerts = [e for e in obs.anomalies() if e["class"] == "alert"]
    assert len(alerts) == 1
    assert alerts[0]["detail"]["rule"] == "tunnel_stall_burst"
    # ...and the counter metric
    vals = {}
    for f in metrics.registry.collect():
        for s in f.samples:
            vals[(s.name, tuple(sorted(s.labels.items())))] = s.value
    assert vals[("scheduler_alerts_total_total" if (
        "scheduler_alerts_total_total",
        (("rule", "tunnel_stall_burst"), ("severity", "critical")),
    ) in vals else "scheduler_alerts_total",
        (("rule", "tunnel_stall_burst"), ("severity", "critical")))] == 1.0


def test_replay_alerts_headline():
    clean = replay_alerts([0.5] * 40)
    assert clean == {"alerts_fired": 0, "fired_rules": []}
    stormy = replay_alerts([0.5] * 10 + [28.0] * 25 + [0.5] * 5)
    assert stormy["alerts_fired"] >= 1
    assert "tunnel_stall_burst" in stormy["fired_rules"]


# ---- black box ------------------------------------------------------------


def _loaded_box(tmp_path, retention=8):
    metrics = SchedulerMetrics()
    obs = CycleObserver(metrics=metrics)
    obs.raise_anomaly("tunnel_stall", seq=7, profile="t", value_s=28.0)
    db = MetricsTSDB()
    db.append("f", (), 1.0, t=100.0)
    eng = RuleEngine(builtin_rules(), db, observer=obs, metrics=metrics)
    lad = DegradationLadder(promote_after=2)
    lad.degrade("blackbox-test")
    return _blackbox.BlackBox(
        str(tmp_path / "bb"), retention=retention,
        config={"statePath": "x"}, observer=obs, tsdb=db, engine=eng,
        ladder=lad)


def test_blackbox_bundle_round_trip(tmp_path):
    box = _loaded_box(tmp_path)
    path = box.dump("watchdog", "seq=7 deadline")
    assert path is not None and os.path.exists(path)
    assert not os.path.exists(path + ".tmp")
    b = _blackbox.load_bundle(path)
    assert b["trigger"] == "watchdog"
    assert b["detail"] == "seq=7 deadline"
    assert b["config"] == {"statePath": "x"}
    # the anomaly tail matches the injected fault
    evs = b["anomalies"]["events"]
    assert evs[-1]["class"] == "tunnel_stall" and evs[-1]["seq"] == 7
    assert b["alerts"]["fired_total"] == 0
    assert b["metrics_history"]["series"][0]["family"] == "f"
    (tr,) = b["ladder"]["transitions"]
    assert tr["reason"] == "blackbox-test" and "wall" in tr


def test_blackbox_throttle_and_sigterm_exemption(tmp_path):
    box = _loaded_box(tmp_path)
    assert box.dump("watchdog") is not None
    assert box.dump("watchdog") is None  # throttled per trigger
    assert box.dump("stateless") is not None  # other trigger unaffected
    assert box.dump("sigterm") is not None  # exempt
    assert box.dump("sigterm") is not None
    assert box.dumps == 4


def test_blackbox_retention_keeps_newest(tmp_path):
    box = _loaded_box(tmp_path, retention=2)
    box._last_dump = {}  # bypass throttle; rotation is what's under test
    paths = []
    for i in range(4):
        paths.append(box.dump("sigterm", f"n{i}"))
    names = box.status()["bundles"]
    assert len(names) == 2
    assert os.path.basename(paths[-1]) in names
    assert os.path.basename(paths[-2]) in names
    # sequence numbers keep rising past rotated-away bundles
    assert names[-1].startswith("blackbox-000003-")


def test_blackbox_trigger_unarmed_is_noop_and_armed_dumps(tmp_path):
    assert _blackbox.trigger("watchdog", "x") is None  # unarmed: no-op
    box = _blackbox.arm(_loaded_box(tmp_path))
    p = _blackbox.trigger("watchdog", "armed now")
    assert p is not None
    _blackbox.disarm()
    assert _blackbox.trigger("watchdog") is None
    assert box.dumps == 1


def test_blackbox_read_script_round_trip(tmp_path):
    """scripts/blackbox_read.py: summary on a directory (newest bundle),
    --json dump, and --perfetto trace extraction."""
    from k8s_scheduler_tpu.core import Scheduler
    from k8s_scheduler_tpu.models import MakeNode, MakePod

    sched = Scheduler(binder=lambda pod, node: None)
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "8"}).obj())
    sched.on_pod_add(MakePod("p0").req({"cpu": "1"}).obj())
    sched.schedule_cycle()
    box = _blackbox.BlackBox(
        str(tmp_path / "bb"), recorder=sched.flight,
        observer=sched.observer, ladder=sched.ladder,
        events=sched.events)
    box.dump("serve_loop", "boom")
    script = os.path.join(REPO, "scripts", "blackbox_read.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, script, str(tmp_path / "bb")],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "trigger:  serve_loop  (boom)" in r.stdout
    r = subprocess.run(
        [sys.executable, script, box.last_path, "--json"],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["trigger"] == "serve_loop"
    out = str(tmp_path / "trace.json")
    r = subprocess.run(
        [sys.executable, script, box.last_path, "--perfetto", out],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stderr
    trace = json.load(open(out))
    assert trace.get("traceEvents")


# ---- endpoints ------------------------------------------------------------


def _get(url):
    with urllib.request.urlopen(url) as r:
        return r.status, dict(r.headers), r.read()


def _watch_server(tmp_path):
    from k8s_scheduler_tpu.cmd.httpserver import start_http_server
    from k8s_scheduler_tpu.state import DurableState

    metrics = SchedulerMetrics()
    obs = CycleObserver(metrics=metrics)
    obs.raise_anomaly("tenant_starved", seq=3, profile="arena",
                      tenant="team-a", pending=4, streak=9)
    obs.raise_anomaly("tenant_starved", seq=4, profile="arena",
                      tenant="team-b", pending=1, streak=5)
    obs.raise_anomaly("tunnel_stall", seq=5, profile="p", value_s=2.0)
    db = MetricsTSDB()
    now = time.time()
    for i in range(30):
        db.append("scheduler_slo_burn_rate", {"window": "fast"},
                  0.4, t=now - 30.0 + i)
    eng = RuleEngine(builtin_rules(), db, observer=obs)
    eng.evaluate(now=now)
    state = DurableState(str(tmp_path / "st"), snapshot_interval_seconds=0)
    lad = DegradationLadder(promote_after=2)
    lad.degrade("endpoint-test")
    lad.note_clean_cycle()
    lad.note_clean_cycle()  # promote_after=2 clean cycles -> back up
    state.degradation = lad
    server = start_http_server(
        metrics, port=0, observer=obs, state=state, tsdb=db, alerts=eng)
    return server, state


def test_history_alerts_dashboard_and_state_endpoints(tmp_path):
    server, state = _watch_server(tmp_path)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        # inventory form (no family)
        st, _, body = _get(f"{base}/debug/metrics/history")
        assert st == 200
        inv = json.loads(body)
        assert any(f["family"] == "scheduler_slo_burn_rate"
                   for f in inv["families"])
        # series form, with labels + window + step
        st, _, body = _get(
            f"{base}/debug/metrics/history?family=scheduler_slo_burn_rate"
            "&labels=window=fast&window=1000000&step=1")
        assert st == 200
        hist = json.loads(body)
        assert hist["tier"] == "1s"
        assert hist["series"][0]["points"]
        assert hist["series"][0]["labels"] == {"window": "fast"}
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/debug/metrics/history?family=f&window=nope")
        assert ei.value.code == 400
        # alerts: quiet store, full rule inventory visible
        st, _, body = _get(f"{base}/debug/alerts")
        assert st == 200
        al = json.loads(body)
        assert al["active"] == [] and al["fired_total"] == 0
        assert {r["name"] for r in al["rules"]} == {
            r.name for r in builtin_rules()}
        # dashboard: self-contained HTML
        st, headers, body = _get(f"{base}/debug/dashboard")
        assert st == 200
        assert headers["Content-Type"].startswith("text/html")
        assert b"<svg" in body or b"sparkline" in body.lower()
        # anomaly tenant filter + counts
        st, _, body = _get(f"{base}/debug/anomalies?tenant=team-a")
        assert st == 200
        an = json.loads(body)
        assert an["tenant"] == "team-a"
        assert an["tenant_counts"] == {"team-a": 1, "team-b": 1}
        assert [e["detail"]["tenant"]
                for e in an["anomalies"]] == ["team-a"]
        st, _, body = _get(f"{base}/debug/anomalies")
        assert json.loads(body)["tenant"] is None
        assert len(json.loads(body)["anomalies"]) == 3
        # /debug/state carries the timestamped ladder transition ring
        st, _, body = _get(f"{base}/debug/state")
        assert st == 200
        moves = json.loads(body)["degradation"]["transition_log"]
        assert len(moves) == 2
        assert moves[0]["reason"] == "endpoint-test"
        assert all("wall" in m and "t" in m for m in moves)
        assert moves[0]["to"] > moves[1]["to"]  # down then back up
    finally:
        server.shutdown()
        state.journal.close()


def test_dashboard_disabled_404s(tmp_path):
    from k8s_scheduler_tpu.cmd.httpserver import start_http_server

    db = MetricsTSDB()
    server = start_http_server(
        SchedulerMetrics(), port=0, tsdb=db, dashboard=False)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/debug/dashboard")
        assert ei.value.code == 404
    finally:
        server.shutdown()


# ---- config / CLI surface -------------------------------------------------


def test_config_knobs_round_trip(tmp_path):
    from k8s_scheduler_tpu.config import load_config

    cfg_file = tmp_path / "cfg.yaml"
    cfg_file.write_text(
        "metricsHistorySamples: 128\n"
        "metricsTickerSeconds: 0.5\n"
        "alertRulesFile: /tmp/rules.yaml\n"
        "blackboxRetention: 3\n"
        "debugDashboard: false\n")
    cfg = load_config(str(cfg_file))
    assert cfg.metrics_history_samples == 128
    assert cfg.metrics_ticker_seconds == 0.5
    assert cfg.alert_rules_file == "/tmp/rules.yaml"
    assert cfg.blackbox_retention == 3
    assert cfg.debug_dashboard is False
