"""Same-cycle static-PV arbitration (VERDICT r2 item 8): two pods whose
unbound WaitForFirstConsumer claims target the SAME single PV must not
both place in one cycle — the first by rank claims it, the loser goes
unschedulable instead of binding-and-failing at the agent. With enough
equivalent PVs, every claimant places, each on a distinct volume.
Differential against the upgraded oracle (which now claims PVs as it
commits pods) for the scan engine; validity + placement counts for the
rounds engine (whose _RB_PV guard arbitrates same-round claimants).
"""

import numpy as np
import pytest

from k8s_scheduler_tpu import oracle
from k8s_scheduler_tpu.core import build_cycle_fn
from k8s_scheduler_tpu.models import MakeNode, MakePod, SnapshotEncoder
from k8s_scheduler_tpu.models.api import (
    VOLUME_BINDING_WAIT,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
)

GiB = 2**30


def fixture(n_pvs: int, n_claimants: int):
    nodes = [
        MakeNode(f"n{i}").capacity({"cpu": "8"}).obj() for i in range(3)
    ]
    classes = [
        StorageClass("local", VOLUME_BINDING_WAIT, provisioner=False)
    ]
    pvs = [
        PersistentVolume(f"pv-{v}", capacity=10 * GiB,
                         storage_class="local")
        for v in range(n_pvs)
    ]
    pvcs = [
        PersistentVolumeClaim(f"claim-{p}", storage_class="local",
                              request=5 * GiB)
        for p in range(n_claimants)
    ]
    pods = [
        MakePod(f"pod-{p}").req({"cpu": "1"}).volume(f"claim-{p}")
        .created(float(p)).obj()
        for p in range(n_claimants)
    ]
    return nodes, pods, pvcs, pvs, classes


def run_engine(mode, nodes, pods, pvcs, pvs, classes):
    enc = SnapshotEncoder(pad_pods=16, pad_nodes=4)
    snap = enc.encode(nodes, pods, pvcs=pvcs, pvs=pvs,
                      storage_classes=classes)
    out = build_cycle_fn(commit_mode=mode)(snap)
    return np.asarray(out.assignment)[: len(pods)]


@pytest.mark.parametrize("mode", ["scan", "rounds"])
def test_single_pv_single_winner(mode):
    nodes, pods, pvcs, pvs, classes = fixture(n_pvs=1, n_claimants=3)
    a = run_engine(mode, nodes, pods, pvcs, pvs, classes)
    assert (a >= 0).sum() == 1, a
    assert a[0] >= 0  # rank order: the earliest-created claimant wins


@pytest.mark.parametrize("mode", ["scan", "rounds"])
def test_enough_pvs_all_place(mode):
    nodes, pods, pvcs, pvs, classes = fixture(n_pvs=3, n_claimants=3)
    a = run_engine(mode, nodes, pods, pvcs, pvs, classes)
    assert (a >= 0).all(), a


def test_scan_matches_oracle_under_contention():
    for n_pvs, n_cl in [(1, 3), (2, 3), (3, 3), (2, 4)]:
        nodes, pods, pvcs, pvs, classes = fixture(n_pvs, n_cl)
        a = run_engine("scan", nodes, pods, pvcs, pvs, classes)
        want = [
            d.node_index for d in oracle.schedule(
                nodes, pods, pvcs=pvcs, pvs=pvs, storage_classes=classes
            )
        ]
        assert a.tolist() == want, (n_pvs, n_cl, a.tolist(), want)


def test_diagnosis_attributes_pv_loser():
    # the diagnosis program replays ALL placements in one batched fold;
    # contended same-class claims must still reconstruct the claim
    # bitmap exactly (fixed-point fold), so the loser's reasons name
    # VolumeBinding
    from k8s_scheduler_tpu.core import (
        build_diagnosis_fn,
        build_packed_cycle_carry_fn,
        build_stable_state_fn,
    )
    from k8s_scheduler_tpu.core.cycle import CarryKeeper
    from k8s_scheduler_tpu.framework.runtime import Framework

    nodes, pods, pvcs, pvs, classes = fixture(n_pvs=2, n_claimants=3)
    enc = SnapshotEncoder(pad_pods=16, pad_nodes=4)
    w, b, spec, snap, _ = enc.encode_packed(
        nodes, pods, pvcs=pvcs, pvs=pvs, storage_classes=classes
    )
    stable = build_stable_state_fn(spec)(w, b)
    keeper = CarryKeeper(spec)
    carry = keeper.ci(w, b, stable)
    out = build_packed_cycle_carry_fn(spec)(w, b, stable, carry)
    a = np.asarray(out.assignment)[:3]
    assert (a >= 0).sum() == 2 and a[2] == -1  # 2 PVs, 3 claimants
    rej = np.asarray(
        build_diagnosis_fn(spec)(w, b, stable, out.assignment,
                                 out.node_requested)
    )
    col = Framework.from_config().filter_names.index("VolumeBinding")
    assert rej[2, col] > 0, rej[2]


def test_mixed_static_and_dynamic_not_blocked():
    # a provisioner-backed class keeps dynamic claimants schedulable
    # even when every static PV is claimed
    nodes, pods, pvcs, pvs, classes = fixture(n_pvs=1, n_claimants=2)
    classes = [
        StorageClass("local", VOLUME_BINDING_WAIT, provisioner=True)
    ]
    a = run_engine("scan", nodes, pods, pvcs, pvs, classes)
    assert (a >= 0).all(), a  # loser of the PV rides provisioning


if __name__ == "__main__":
    import sys

    pytest.main([__file__, "-v"] + sys.argv[1:])


@pytest.mark.parametrize("mode", ["scan", "rounds"])
def test_gang_unwind_releases_pv_claims(mode):
    """ADVICE r3 #2: a gang member that places and claims a static PV,
    then gets unwound because its group missed minMember, must not leave
    a phantom claim in CycleResult.pv_claimed (the diagnosis program
    would misattribute VolumeBinding rejections for other pods)."""
    from k8s_scheduler_tpu.models.api import PodGroup

    # one 1-cpu node: only one of the two 1-cpu gang members can place,
    # so minMember=2 fails and the placed member (holding the PV) unwinds
    nodes = [MakeNode("n0").capacity({"cpu": "1"}).obj()]
    classes = [
        StorageClass("local", VOLUME_BINDING_WAIT, provisioner=False)
    ]
    pvs = [PersistentVolume("pv-0", capacity=10 * GiB,
                            storage_class="local")]
    pvcs = [
        PersistentVolumeClaim(f"claim-{p}", storage_class="local",
                              request=5 * GiB)
        for p in range(2)
    ]
    pods = [
        MakePod(f"g-{p}").req({"cpu": "1"}).volume(f"claim-{p}")
        .group("job").created(float(p)).obj()
        for p in range(2)
    ]
    enc = SnapshotEncoder(pad_pods=16, pad_nodes=4)
    snap = enc.encode(nodes, pods, pod_groups=[PodGroup("job", 2)],
                      pvcs=pvcs, pvs=pvs, storage_classes=classes)
    out = build_cycle_fn(commit_mode=mode)(snap)
    a = np.asarray(out.assignment)[: len(pods)]
    assert (a < 0).all(), a  # gang unwound entirely
    assert np.asarray(out.gang_dropped).sum() == 1
    assert not np.asarray(out.pv_claimed).any(), (
        "unwound gang member left a phantom PV claim"
    )


@pytest.mark.parametrize("mode", ["scan", "rounds"])
def test_surviving_placements_keep_pv_claims_after_unwind(mode):
    """The refold after a gang unwind must keep claims of pods that
    actually survived the cycle."""
    from k8s_scheduler_tpu.models.api import PodGroup

    # node n0 fits exactly one 1-cpu pod; the solo claimant places and
    # keeps its PV while the 2-member gang (needing 2 cpu total on the
    # remaining 1-cpu node) fails and unwinds
    nodes = [
        MakeNode("n0").capacity({"cpu": "1"}).obj(),
        MakeNode("n1").capacity({"cpu": "1"}).obj(),
    ]
    classes = [
        StorageClass("local", VOLUME_BINDING_WAIT, provisioner=False)
    ]
    pvs = [
        PersistentVolume(f"pv-{v}", capacity=10 * GiB,
                         storage_class="local")
        for v in range(3)
    ]
    pvcs = [
        PersistentVolumeClaim("claim-solo", storage_class="local",
                              request=5 * GiB),
        PersistentVolumeClaim("claim-g0", storage_class="local",
                              request=5 * GiB),
        PersistentVolumeClaim("claim-g1", storage_class="local",
                              request=5 * GiB),
    ]
    pods = [
        MakePod("solo").req({"cpu": "1"}).volume("claim-solo")
        .created(0.0).obj(),
        MakePod("g-0").req({"cpu": "1"}).volume("claim-g0")
        .group("job").created(1.0).obj(),
        MakePod("g-1").req({"cpu": "1"}).volume("claim-g1")
        .group("job").created(2.0).obj(),
    ]
    enc = SnapshotEncoder(pad_pods=16, pad_nodes=4)
    snap = enc.encode(nodes, pods, pod_groups=[PodGroup("job", 2)],
                      pvcs=pvcs, pvs=pvs, storage_classes=classes)
    out = build_cycle_fn(commit_mode=mode)(snap)
    a = np.asarray(out.assignment)[: len(pods)]
    assert a[0] >= 0  # solo placed
    assert (a[1:] < 0).all()  # gang unwound
    assert np.asarray(out.pv_claimed).sum() == 1  # solo's claim kept
