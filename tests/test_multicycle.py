"""Multi-cycle on-device serving: bit-identical equivalence of the
K-cycle device-resident loop (core/cycle.build_packed_multicycle_fn +
Scheduler._schedule_profile_multi) against K sequential single-cycle
dispatches with host bind-folding between them.

Three layers, matching the exactness contract the docstrings state:

- device level: the stacked loop's decisions vs the shared cycle body
  invoked K times with the carry folded on host (including the K=1
  degenerate program and the early-exit-on-drain path);
- scheduler level: randomized arrival traces through a multiCycleK=K
  scheduler vs a K=1 scheduler — identical bind streams, identical
  journal decision-record streams (modulo the q.pop markers, whose
  position is the ONLY thing batching moves), identical state digests,
  and identical per-cycle flight-record outcome counts;
- envelope: workloads that leave the exactness envelope (host ports,
  volumes, affinity, extenders) fall back to sequential dispatches and
  pin the profile out of batching.
"""

from __future__ import annotations

import dataclasses
import random

import numpy as np
import pytest

from k8s_scheduler_tpu.config import SchedulerConfiguration
from k8s_scheduler_tpu.core import Scheduler
from k8s_scheduler_tpu.core.cycle import (
    build_cycle_fn,
    build_packed_multicycle_fn,
    multicycle_unsupported_reason,
)
from k8s_scheduler_tpu.framework.runtime import Framework
from k8s_scheduler_tpu.models import MakeNode, MakePod, packing
from k8s_scheduler_tpu.models.encoding import SnapshotEncoder
from k8s_scheduler_tpu.state import DurableState, state_digest
from k8s_scheduler_tpu.state.journal import replay_dir


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> None:
        self.t += dt


# ---- device level -------------------------------------------------------


def _encode_groups(groups, nodes, existing=(), pod_groups=(),
                   pad_pods=8, pad_nodes=8):
    """Encode each arrival group against the same pre-batch state with
    one long-lived encoder (the scheduler's contract) and return
    (snaps, spec, wbufs, bbufs) stacked for the multi-cycle program."""
    enc = SnapshotEncoder()
    enc.pad_pods = pad_pods
    enc.pad_nodes = pad_nodes
    snaps = [
        enc.encode(nodes, g, existing, pod_groups=pod_groups)
        for g in groups
    ]
    spec = packing.make_spec(snaps[0])
    for s in snaps[1:]:
        assert packing.make_spec(s).key() == spec.key()
    packed = [packing.pack(s, spec) for s in snaps]
    wbufs = np.stack([w for w, _ in packed])
    bbufs = np.stack([b for _, b in packed])
    return snaps, spec, wbufs, bbufs


def _sequential_reference(snaps, fw, **cycle_kw):
    """K sequential single-cycle dispatches of the SAME cycle body with
    the node_requested + gang placed-count carry folded on host — the
    semantics the device loop must reproduce bit-identically."""
    cyc = build_cycle_fn(framework=fw, outputs="latency", **cycle_kw)
    out = []
    node_req = None
    gplaced = None
    for snap in snaps:
        if node_req is not None:
            snap = dataclasses.replace(
                snap,
                node_requested=node_req,
                group_existing_count=(
                    snap.group_existing_count + gplaced
                ),
            )
        dec = cyc(snap)
        a = np.asarray(dec.assignment)
        placed = np.asarray(snap.pod_valid) & (a >= 0)
        G = snap.group_min_member.shape[0]
        pg = np.asarray(snap.pod_group)
        add = np.zeros(G, np.int32)
        np.add.at(add, np.clip(pg, 0, G - 1),
                  np.where((pg >= 0) & placed, 1, 0))
        gplaced = add if gplaced is None else gplaced + add
        node_req = np.asarray(dec.node_requested)
        out.append(dec)
    return out


def _rand_groups(rng, n_groups, nodes):
    groups = []
    uid = 0
    for _ in range(n_groups):
        g = []
        for _ in range(rng.randint(1, 6)):
            cpu = rng.choice(["1", "2", "3"])
            g.append(
                MakePod(f"p{uid}")
                .req({"cpu": cpu, "memory": "1Gi"})
                .obj()
            )
            uid += 1
        groups.append(g)
    return groups


@pytest.mark.parametrize("seed", [0, 3, 11])
@pytest.mark.parametrize("commit_mode", ["rounds", "scan"])
def test_device_loop_matches_sequential_dispatches(seed, commit_mode):
    rng = random.Random(seed)
    nodes = [
        MakeNode(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"}).obj()
        for i in range(5)
    ]
    groups = _rand_groups(rng, 4, nodes)
    snaps, spec, wbufs, bbufs = _encode_groups(groups, nodes)
    assert all(multicycle_unsupported_reason(s) is None for s in snaps)
    fw = Framework.from_config()
    kw = dict(commit_mode=commit_mode, gang_scheduling=True)
    mfn = build_packed_multicycle_fn(spec, framework=fw, k=4, **kw)
    res = mfn(wbufs, bbufs, None, np.int32(4))
    ref = _sequential_reference(snaps, fw, **kw)
    assert int(res.cycles_run) == 4
    for i, (snap, dec) in enumerate(zip(snaps, ref)):
        valid = np.asarray(snap.pod_valid)
        a_ref = np.where(valid, np.asarray(dec.assignment), -1)
        np.testing.assert_array_equal(
            np.asarray(res.assignment)[i], a_ref,
            err_msg=f"inner cycle {i} assignment diverged",
        )
        np.testing.assert_array_equal(
            np.asarray(res.unschedulable)[i],
            np.asarray(dec.unschedulable),
        )
        np.testing.assert_array_equal(
            np.asarray(res.gang_dropped)[i],
            np.asarray(dec.gang_dropped),
        )
        np.testing.assert_array_equal(
            np.asarray(res.attempted)[i], valid
        )
        np.testing.assert_array_equal(
            np.asarray(res.node_requested)[i],
            np.asarray(dec.node_requested),
            err_msg=f"inner cycle {i} capacity carry diverged",
        )


def test_device_loop_gang_carry_spans_inner_cycles():
    """A gang placed by inner cycle 0 counts toward minMember for a
    straggler member arriving in inner cycle 1 ONLY through the loop's
    placed-count carry (the stale snapshot says zero members exist) —
    sequential reference and the device loop must agree."""
    from k8s_scheduler_tpu.models.api import PodGroup

    nodes = [
        MakeNode(f"n{i}").capacity({"cpu": "8", "memory": "8Gi"}).obj()
        for i in range(4)
    ]
    pg = [PodGroup(name="gang", min_member=2)]
    groups = [
        [MakePod(f"a{i}").req({"cpu": "1"}).group("gang").obj()
         for i in range(2)],
        # a lone straggler: 1 < minMember unless cycle 0's placements
        # carry into its group_existing_count
        [MakePod("b0").req({"cpu": "1"}).group("gang").obj()],
    ]
    snaps, spec, wbufs, bbufs = _encode_groups(
        groups, nodes, pod_groups=pg
    )
    fw = Framework.from_config()
    kw = dict(commit_mode="rounds", gang_scheduling=True)
    mfn = build_packed_multicycle_fn(spec, framework=fw, k=2, **kw)
    res = mfn(wbufs, bbufs, None, np.int32(2))
    ref = _sequential_reference(snaps, fw, **kw)
    for i in range(2):
        np.testing.assert_array_equal(
            np.asarray(res.assignment)[i],
            np.where(
                np.asarray(snaps[i].pod_valid),
                np.asarray(ref[i].assignment), -1,
            ),
        )
        np.testing.assert_array_equal(
            np.asarray(res.gang_dropped)[i],
            np.asarray(ref[i].gang_dropped),
        )
    # cycle 0 reaches minMember on its own; the cycle-1 straggler
    # survives only because the carry counts cycle 0's placements
    assert (np.asarray(res.assignment)[0][:2] >= 0).all()
    assert int(np.asarray(res.assignment)[1][0]) >= 0
    assert not np.asarray(res.gang_dropped)[1][0]


def test_device_loop_k1_degenerate():
    nodes = [
        MakeNode(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"}).obj()
        for i in range(3)
    ]
    groups = [[MakePod("p0").req({"cpu": "1"}).obj(),
               MakePod("p1").req({"cpu": "2"}).obj()]]
    snaps, spec, wbufs, bbufs = _encode_groups(groups, nodes)
    fw = Framework.from_config()
    kw = dict(commit_mode="rounds", gang_scheduling=True)
    mfn = build_packed_multicycle_fn(spec, framework=fw, k=1, **kw)
    res = mfn(wbufs, bbufs, None, np.int32(1))
    ref = _sequential_reference(snaps, fw, **kw)
    assert int(res.cycles_run) == 1
    np.testing.assert_array_equal(
        np.asarray(res.assignment)[0],
        np.where(np.asarray(snaps[0].pod_valid),
                 np.asarray(ref[0].assignment), -1),
    )


def test_device_loop_early_exit_on_drain():
    """Rows whose pod_valid is all-false end the loop: a short batch
    never pays the full K iterations, and the unran rows keep the init
    fill (-1 / False)."""
    nodes = [
        MakeNode(f"n{i}").capacity({"cpu": "4", "memory": "8Gi"}).obj()
        for i in range(3)
    ]
    groups = [[MakePod("p0").req({"cpu": "1"}).obj()],
              [MakePod("p1").req({"cpu": "1"}).obj()]]
    snaps, spec, wbufs, bbufs = _encode_groups(groups, nodes)
    k = 4
    wk = np.zeros((k,) + wbufs.shape[1:], wbufs.dtype)
    bk = np.zeros((k,) + bbufs.shape[1:], bbufs.dtype)
    wk[:2], bk[:2] = wbufs, bbufs
    fw = Framework.from_config()
    mfn = build_packed_multicycle_fn(
        spec, framework=fw, k=k, commit_mode="rounds",
        gang_scheduling=True,
    )
    res = mfn(wk, bk, None, np.int32(k))
    assert int(res.cycles_run) == 2
    a = np.asarray(res.assignment)
    assert (a[0][:1] >= 0).all() and (a[1][:1] >= 0).all()
    assert (a[2:] == -1).all()
    assert not np.asarray(res.attempted)[2:].any()


def test_envelope_gate_rejects_stateful_capabilities():
    nodes = [MakeNode("n0").capacity({"cpu": "4"}).obj()]
    enc = SnapshotEncoder()
    enc.pad_pods = enc.pad_nodes = 8
    ported = enc.encode(
        nodes, [MakePod("p").req({"cpu": "1"}).host_port(80).obj()]
    )
    assert multicycle_unsupported_reason(ported) == "host_ports"
    enc2 = SnapshotEncoder()
    enc2.pad_pods = enc2.pad_nodes = 8
    clean = enc2.encode(nodes, [MakePod("p").req({"cpu": "1"}).obj()])
    assert multicycle_unsupported_reason(clean) is None
    affine = enc2.encode(
        nodes,
        [MakePod("q").req({"cpu": "1"})
         .pod_affinity("zone", {"app": "x"}).obj()],
    )
    assert multicycle_unsupported_reason(affine) == "inter_pod_affinity"


def test_hold_pop_keeps_buffered_groups_recoverable(tmp_path):
    """A crash while K groups are coalescing must recover EVERY
    buffered group, not just the last pop's: the journaled hold-pop
    accumulates the in-flight set instead of replacing it."""
    from k8s_scheduler_tpu.internal.cache import SchedulerCache
    from k8s_scheduler_tpu.internal.queue import SchedulingQueue

    clock = FakeClock()
    q = SchedulingQueue(now=clock)
    c = SchedulerCache(now=clock)
    st = DurableState(str(tmp_path / "wal"), snapshot_interval_seconds=0)
    st.attach(q, c)
    q.add(MakePod("p0").req({"cpu": "1"}).obj())
    assert [p.uid for p in q.pop_ready()] == ["default/p0"]
    q.add(MakePod("p1").req({"cpu": "1"}).obj())
    # the second group's pop HOLDS the first group's in-flight entry
    assert [p.uid for p in q.pop_ready(hold=True)] == ["default/p1"]
    # a delete tombstone for a buffered pod must survive the hold-pop
    q.delete("default/p0")
    st.journal.flush()
    st.journal.close()

    q2 = SchedulingQueue(now=clock)
    c2 = SchedulerCache(now=clock)
    st2 = DurableState(
        str(tmp_path / "wal"), snapshot_interval_seconds=0
    )
    st2.attach(q2, c2)
    assert q2.recover_in_flight() == 1  # p1 requeued; p0's tombstone held
    assert [p.uid for p in q2.pop_ready()] == ["default/p1"]
    st2.journal.close()


def test_retire_in_flight_bounds_hold_accumulation(tmp_path):
    """Hold pops only ACCUMULATE the in-flight set; the batch flush
    must retire the pods whose outcomes it applied (journaled, so a
    replayed takeover recovers the same bounded set) — otherwise bound
    pods stay "recoverable" forever and a failover re-binds them."""
    from k8s_scheduler_tpu.internal.cache import SchedulerCache
    from k8s_scheduler_tpu.internal.queue import SchedulingQueue

    clock = FakeClock()
    q = SchedulingQueue(now=clock)
    c = SchedulerCache(now=clock)
    st = DurableState(str(tmp_path / "wal"), snapshot_interval_seconds=0)
    st.attach(q, c)
    q.add(MakePod("p0").req({"cpu": "1"}).obj())
    q.pop_ready(hold=True)
    q.add(MakePod("p1").req({"cpu": "1"}).obj())
    q.pop_ready(hold=True)
    assert set(q._in_flight) == {"default/p0", "default/p1"}
    # flush applied p0's bind; p1 is still buffered — p0 retires, p1
    # stays recoverable
    q.retire_in_flight(["default/p0", "default/never-in-flight"])
    assert set(q._in_flight) == {"default/p1"}
    st.journal.flush()
    st.journal.close()

    q2 = SchedulingQueue(now=clock)
    c2 = SchedulerCache(now=clock)
    st2 = DurableState(str(tmp_path / "wal"), snapshot_interval_seconds=0)
    st2.attach(q2, c2)
    assert set(q2._in_flight) == {"default/p1"}  # replay reproduces it
    assert q2.recover_in_flight() == 1  # only p1 — p0 is NOT re-bound
    st2.journal.close()


# ---- scheduler level ----------------------------------------------------


def _drive_trace(k, seed, state_dir, n_cycles=6):
    """Run one randomized arrival trace through a Scheduler with
    multiCycleK=k, journaling into state_dir. The clock is FROZEN so
    the only difference between a k=1 and a k=K run is the batching
    itself (backoffs never expire mid-trace, so each cycle's pop is
    exactly that cycle's arrivals in both runs)."""
    clock = FakeClock()
    binds = []
    cfg = SchedulerConfiguration(
        multi_cycle_k=k, multi_cycle_max_wait_ms=1e9
    )
    state = DurableState(state_dir, snapshot_interval_seconds=0)
    sched = Scheduler(
        config=cfg,
        binder=lambda pod, node: binds.append((pod.uid, node)),
        now=clock, pad_bucket=8, state=state,
    )
    for i in range(6):
        sched.on_node_add(
            MakeNode(f"n{i}")
            .capacity({"cpu": "4", "memory": "8Gi"}).obj()
        )
    rng = random.Random(seed)
    uid = 0
    for _c in range(n_cycles):
        for _ in range(rng.randint(1, 5)):
            sched.on_pod_add(
                MakePod(f"p{uid}")
                .req({"cpu": rng.choice(["1", "2", "3"]),
                      "memory": "1Gi"})
                .obj()
            )
            uid += 1
        sched.schedule_cycle()
    # idle pops flush any buffered groups (and are no-ops for k=1)
    for _ in range(2):
        sched.schedule_cycle()
    recs = [
        (r.counts.get("pods"), r.counts.get("scheduled"),
         r.counts.get("unschedulable"), r.counts.get("gang_dropped"))
        for r in sched.flight.snapshot()
    ]
    digest = state_digest(sched.queue, sched.cache)
    state.journal.flush()
    state.journal.close()
    return binds, recs, digest


def _journal_streams(state_dir):
    """Split the journal into the two streams batching may legitimately
    re-interleave but must each preserve exactly:

    - decisions: every scheduling-outcome record (assume, bind finish,
      requeues, forgets, evictions) — multi-cycle applies these per
      inner cycle in batch order, so the stream must be IDENTICAL to
      the sequential scheduler's (same ops, order, payloads, times);
    - arrivals: informer-driven records (adds/updates/deletes, node
      churn), journaled when they happen — batching moves the decision
      stream relative to them (K groups arrive before the batch
      flushes), but the arrival stream itself must be identical.

    The q.pop/q.move/q.flush/q.retire markers are the cycle-boundary
    bookkeeping whose position and hold-flag shape IS the batching, so
    they are the one thing excluded from the equivalence claim
    (q.retire exists ONLY under batching: it undoes what the hold pops
    accumulated; a K=1 journal never contains one)."""
    markers = {
        "q.pop", "q.move", "q.flush_backoff", "q.flush_timeout",
        "q.retire",
    }
    arrivals = {
        "q.add", "q.update", "q.delete", "c.add_node", "c.update_node",
        "c.remove_node", "c.add_pod", "c.remove_pod",
    }
    dec_stream, arr_stream = [], []
    for op, t, data in replay_dir(str(state_dir)):
        if op in markers:
            continue
        (arr_stream if op in arrivals else dec_stream).append(
            (op, t, data)
        )
    return dec_stream, arr_stream


@pytest.mark.parametrize("seed", [0, 5, 9])
def test_scheduler_multicycle_matches_sequential(tmp_path, seed):
    """The tentpole acceptance: a k=4 batched scheduler and a k=1
    sequential scheduler produce identical bind streams, identical
    journal decision records (same ops, same order, same payloads,
    same timestamps), identical state digests, and identical per-cycle
    flight outcome counts over a randomized trace."""
    b1, r1, d1 = _drive_trace(1, seed, str(tmp_path / "seq"))
    b4, r4, d4 = _drive_trace(4, seed, str(tmp_path / "mc"))
    assert b4 == b1
    assert r4 == r1
    assert d4 == d1
    dec1, arr1 = _journal_streams(tmp_path / "seq")
    dec4, arr4 = _journal_streams(tmp_path / "mc")
    assert dec4 == dec1
    assert arr4 == arr1


def _drive_selector_growth(incremental):
    """A K=4 batch where every later group interns a NEW node-selector
    expression WITHIN the padded table regime (Ex pads to 8, so the
    spec key never changes): the regression the table-growth re-encode
    trigger exists for. Returns (binds, encoder)."""
    clock = FakeClock()
    binds = []
    cfg = SchedulerConfiguration(
        multi_cycle_k=4, multi_cycle_max_wait_ms=1e9,
        incremental_encode=incremental,
    )
    sched = Scheduler(
        config=cfg,
        binder=lambda pod, node: binds.append((pod.uid, node)),
        now=clock, pad_bucket=8,
    )
    for i, tier in enumerate(("gold", "silver", "bronze", "iron")):
        sched.on_node_add(
            MakeNode(f"n{i}").capacity({"cpu": "8", "memory": "8Gi"})
            .labels({"tier": tier}).obj()
        )
    # group 0 interns nothing selector-shaped; groups 1..3 each bring a
    # selector value the tables have never seen
    sched.on_pod_add(MakePod("p0").req({"cpu": "1"}).obj())
    sched.schedule_cycle()
    for i, tier in enumerate(("silver", "bronze", "iron")):
        sched.on_pod_add(
            MakePod(f"p{i + 1}").req({"cpu": "1"})
            .node_selector({"tier": tier}).obj()
        )
        sched.schedule_cycle()  # 4th call flushes the batch
    return binds, sched._encoders["default-scheduler"]


def test_multicycle_table_growth_within_padding_rebinds(tmp_path):
    """A later group's pod may intern a new expression row WITHOUT
    changing the padded spec key — row 0's stable tables (the whole
    batch's stable side) would lack the entry its row references, and
    the pod was falsely rejected as NodeAffinity-unschedulable. The
    table-growth re-encode trigger must rebuild the batch so every
    selector pod binds to its labeled node."""
    binds, _enc = _drive_selector_growth(incremental=False)
    d = dict(binds)
    # p0 has no selector — its node is a scoring tiebreak; the
    # selector pods MUST land on their labeled nodes (without the
    # growth trigger they were falsely NodeAffinity-unschedulable)
    assert "default/p0" in d
    assert {k: d.get(k) for k in
            ("default/p1", "default/p2", "default/p3")} == {
        "default/p1": "n1", "default/p2": "n2", "default/p3": "n3",
    }


def test_multicycle_growth_reencode_reuses_interned_entries():
    """The dim-growth re-encode's second pass must REUSE the entries
    pass 1 interned (delta hits against the grown tables), not run a
    second round of full encodes — and under incrementalEncode the
    decisions are identical to the non-incremental engine."""
    binds_off, _ = _drive_selector_growth(incremental=False)
    binds_on, enc = _drive_selector_growth(incremental=True)
    assert binds_on == binds_off
    # pass 1: the growing groups full-encode; the retry pass re-rows
    # the earlier groups via the delta path (tables already grown, so
    # nothing forces a second full rebuild)
    assert enc.delta_hits > 0, (enc.delta_hits, enc.full_encodes)
    assert enc.full_encodes <= 4, (enc.delta_hits, enc.full_encodes)


def test_scheduler_flushes_on_latency_bound(tmp_path):
    """A buffered group is never held past multiCycleMaxWaitMs even if
    arrivals keep trickling in below the K threshold."""
    clock = FakeClock()
    binds = []
    cfg = SchedulerConfiguration(
        multi_cycle_k=8, multi_cycle_max_wait_ms=50.0
    )
    sched = Scheduler(
        config=cfg,
        binder=lambda pod, node: binds.append(pod.uid),
        now=clock, pad_bucket=8,
    )
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "64"}).obj())
    sched.on_pod_add(MakePod("p0").req({"cpu": "1"}).obj())
    sched.schedule_cycle()
    assert binds == []  # buffered: below K, stream active, under bound
    clock.tick(0.2)  # past the 50 ms bound
    sched.on_pod_add(MakePod("p1").req({"cpu": "1"}).obj())
    sched.schedule_cycle()
    assert sorted(binds) == ["default/p0", "default/p1"]
    assert (
        sched.metrics.multicycle_batch._sum.get() == 2.0
    )  # one 2-cycle batch


def test_scheduler_envelope_fallback_pins_profile_off(tmp_path):
    """A STICKY capability (inter-pod affinity: the encoder's flag is
    grow-only) that leaves the envelope mid-run falls back to
    sequential dispatches (nothing lost) and pins batching off for the
    profile's lifetime."""
    clock = FakeClock()
    binds = []
    cfg = SchedulerConfiguration(
        multi_cycle_k=4, multi_cycle_max_wait_ms=1e9
    )
    sched = Scheduler(
        config=cfg,
        binder=lambda pod, node: binds.append(pod.uid),
        now=clock, pad_bucket=8,
    )
    sched.on_node_add(
        MakeNode("n0").capacity({"cpu": "64"})
        .labels({"zone": "z0"}).obj()
    )
    sched.on_pod_add(
        MakePod("p0").req({"cpu": "1"})
        .pod_affinity("zone", {"app": "x"}).obj()
    )
    sched.schedule_cycle()
    sched.on_pod_add(MakePod("p1").req({"cpu": "1"}).obj())
    sched.schedule_cycle()
    sched.schedule_cycle()  # idle pop -> flush -> envelope fallback
    assert "default/p1" in binds
    assert (
        sched._mc_off.get("default-scheduler") == "inter_pod_affinity"
    )
    # later arrivals go straight through the single-cycle path
    sched.on_pod_add(MakePod("p2").req({"cpu": "1"}).obj())
    sched.schedule_cycle()
    assert "default/p2" in binds


def test_scheduler_host_ports_fallback_is_per_batch(tmp_path):
    """host_ports is a per-SNAPSHOT envelope exit (only a PENDING pod
    requesting a port occupies one): the carrying batch falls back
    sequentially but the profile is NOT pinned — the next port-free
    batch dispatches through the device loop again."""
    clock = FakeClock()
    binds = []
    cfg = SchedulerConfiguration(
        multi_cycle_k=2, multi_cycle_max_wait_ms=1e9
    )
    sched = Scheduler(
        config=cfg,
        binder=lambda pod, node: binds.append(pod.uid),
        now=clock, pad_bucket=8,
    )
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "64"}).obj())
    sched.on_pod_add(
        MakePod("p0").req({"cpu": "1"}).host_port(8080).obj()
    )
    sched.schedule_cycle()
    sched.on_pod_add(MakePod("p1").req({"cpu": "1"}).obj())
    sched.schedule_cycle()  # batch of 2 -> host_ports fallback
    assert sorted(binds) == ["default/p0", "default/p1"]
    assert "default-scheduler" not in sched._mc_off
    assert sched.metrics.multicycle_batch._sum.get() == 0.0
    # port-free traffic re-enters the batched path
    sched.on_pod_add(MakePod("p2").req({"cpu": "1"}).obj())
    sched.schedule_cycle()
    sched.on_pod_add(MakePod("p3").req({"cpu": "1"}).obj())
    sched.schedule_cycle()
    assert sorted(binds)[2:] == ["default/p2", "default/p3"]
    assert sched.metrics.multicycle_batch._sum.get() == 2.0


def test_multicycle_records_carry_batched_phases(tmp_path):
    """Inner-cycle flight records carry the batched decomposition the
    observer exports: batch_wait, device_share, and the multi_cycle_k
    marker that excuses their full encodes from fold_miss."""
    clock = FakeClock()
    # speculative depth-2 splits a flush into TWO dispatches, each with
    # its own record-0 pipeline window — this test pins the COMBINED
    # single-dispatch decomposition (the split shape is covered by
    # tests/test_speculative.py)
    cfg = SchedulerConfiguration(
        multi_cycle_k=2, multi_cycle_max_wait_ms=1e9,
        speculative_dispatch=False,
    )
    sched = Scheduler(config=cfg, now=clock, pad_bucket=8)
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "64"}).obj())
    for i in range(2):
        sched.on_pod_add(MakePod(f"p{i}").req({"cpu": "1"}).obj())
        clock.tick(0.01)
        sched.schedule_cycle()
    recs = sched.flight.snapshot()
    assert len(recs) == 2
    waits = []
    for rec in recs:
        assert rec.counts["multi_cycle_k"] == 2
        assert "device_share_ms" in rec.phases
        waits.append(rec.phases["batch_wait_ms"])
        assert rec.counts["scheduled"] == 1
    # group 0 waited ~10 ms for group 1; group 1 flushed immediately
    assert waits[0] > waits[1]
    from k8s_scheduler_tpu.core.observe import phase_seconds

    ph = phase_seconds(recs[0])
    assert "batch_wait" in ph and "device_share" in ph
    assert sched.observer.anomaly_counts["fold_miss"] == 0
    # the batch-wide pipeline window lands ONLY on inner record 0 — K
    # copies would feed the phase histograms K observations of one
    # dispatch (and K duplicate stall anomalies); later records carry
    # the apportioned decomposition instead
    assert "device" in ph and "dispatch" in ph
    ph1 = phase_seconds(recs[1])
    assert "device" not in ph1 and "dispatch" not in ph1
    assert "device_share" in ph1 and "batch_wait" in ph1


def test_multicycle_records_carry_diag_lag(tmp_path):
    """An inner cycle whose pod found no node forces the deferred
    diagnosis through MultiCycleHandle.reject_counts — its flight
    record must carry the diag_lag phase and feed the
    scheduler_diag_lag_seconds summary, exactly as the single-cycle
    path does (stage_report is snapshotted before the apply loop, so
    the lag rides the handle instead)."""
    clock = FakeClock()
    cfg = SchedulerConfiguration(
        multi_cycle_k=2, multi_cycle_max_wait_ms=1e9
    )
    sched = Scheduler(config=cfg, now=clock, pad_bucket=8)
    sched.on_node_add(MakeNode("n0").capacity({"cpu": "4"}).obj())
    sched.on_pod_add(MakePod("fits").req({"cpu": "1"}).obj())
    clock.tick(0.01)
    sched.schedule_cycle()
    sched.on_pod_add(MakePod("huge").req({"cpu": "64"}).obj())
    clock.tick(0.01)
    sched.schedule_cycle()  # batch of 2 flushes; cycle 1 diagnoses
    recs = sched.flight.snapshot()
    assert [r.counts["multi_cycle_k"] for r in recs] == [2, 2]
    assert "diag_lag_ms" in recs[1].phases  # 'huge' was diagnosed
    assert "diag_lag_ms" not in recs[0].phases  # 'fits' bound clean
    assert sched.metrics.diag_lag._count.get() == 1


def test_mixed_burst_lull_traffic_no_false_fold_miss(tmp_path):
    """Bursts (batched) interleaved with lulls (single-cycle): every pod
    binds exactly once, and the first single-cycle dispatch after a
    batch — whose full re-encode is the batch's doing, because the
    stacked plain encodes leave the packed arena's _delta_state stale —
    is stamped post_batch=1 and raises NO fold_miss anomaly."""
    from collections import Counter

    clock = FakeClock()
    binds = []
    cfg = SchedulerConfiguration(
        multi_cycle_k=3, multi_cycle_max_wait_ms=1e9
    )
    sched = Scheduler(
        config=cfg,
        binder=lambda pod, node: binds.append(pod.uid),
        now=clock, pad_bucket=8,
    )
    for i in range(6):
        sched.on_node_add(
            MakeNode(f"n{i}").capacity({"cpu": "16"}).obj()
        )
    uid = 0
    attempted = []
    for _round in range(3):
        for _g in range(3):  # burst: 3 groups coalesce into one batch
            for _ in range(2):
                sched.on_pod_add(
                    MakePod(f"p{uid}").req({"cpu": "1"}).obj()
                )
                uid += 1
            clock.tick(0.01)
            attempted.append(sched.schedule_cycle().attempted)
        # lull: a lone group goes through the single-cycle path
        sched.on_pod_add(MakePod(f"p{uid}").req({"cpu": "1"}).obj())
        uid += 1
        clock.tick(0.01)
        attempted.append(sched.schedule_cycle().attempted)
        clock.tick(0.01)
        attempted.append(sched.schedule_cycle().attempted)  # idle flush
    assert sorted(Counter(binds).values()) == [1] * uid  # no dup binds
    assert len(binds) == uid
    # a pod is attempted in the cycle whose dispatch carried it —
    # exactly once across the trace (buffering cycles report 0, flush
    # cycles the batch size), so Σscheduled/Σattempted rates are honest
    assert sum(attempted) == uid
    assert attempted[:3] == [0, 0, 6]  # 2 buffering cycles, then flush
    # every flushed pod's outcome retired it from the in-flight set
    assert not sched.queue._in_flight
    assert sched.observer.anomaly_counts["fold_miss"] == 0
    recs = sched.flight.snapshot()
    # each round: 2 buffering cycles, then 3 batch inner records, then
    # the lone single-cycle records — the first single-cycle record
    # after each batch carries the post_batch excuse
    post = [
        r for r in recs
        if "multi_cycle_k" not in r.counts and "post_batch" in r.counts
    ]
    assert len(post) == 3  # one per round's first post-batch dispatch
    for r in post:
        assert r.counts["post_batch"] == 1


def test_bench_multicycle_sweep_amortizes_dispatch():
    """The bench acceptance shape: the K-sweep's K>=8 effective
    per-cycle round trip beats the single dispatch (amortization > 1)
    with zero stall cycles, and satisfies the ISSUE criterion
    p50_eff <= 2*(rt_single/K) + device_ms — on the CPU rig rt_single
    upper-bounds the per-cycle device time, so the bound reduces to
    2*(rt1/K) + rt1."""
    import bench_suite

    # wall-clock bound: one retry absorbs a transiently loaded machine
    # (the programs are warm on the second pass, so a retry measures
    # the real dispatch cost, not compile or load noise)
    for attempt in range(2):
        out = bench_suite.run_multicycle_config(
            1, k_values=(1, 8), batches=4
        )
        assert "skipped" not in out
        rt1 = out["per_k"]["1"]["effective_p50_ms"]
        eff8 = out["per_k"]["8"]["effective_p50_ms"]
        assert out["per_k"]["8"]["stall_cycles"] == 0
        if eff8 <= 2 * (rt1 / 8) + rt1 and (
            out["tunnel_amortization"] > 1.0
        ):
            break
    else:
        assert eff8 <= 2 * (rt1 / 8) + rt1
        assert out["tunnel_amortization"] > 1.0


def test_bench_multicycle_sweep_respects_envelope():
    """Configs whose workload leaves the exactness envelope report a
    skip reason instead of sweeping (the bench mirrors the serving
    fallback)."""
    import bench_suite

    out = bench_suite.run_multicycle_config(3, k_values=(1,), batches=1)
    assert out.get("skipped") == "inter_pod_affinity"
