"""Pod-lifecycle tracing (core/spans.py + wiring): span ring
semantics, W3C traceparent propagation, deterministic head sampling,
the cross-thread trace join (submit thread -> serve thread -> bind),
the unarmed-overhead bound, chrome/OTLP export, the /debug/traces +
/debug/explain endpoints with the deprecated /debug/trace alias, and
the bench_diff --max-trace-overhead ceiling."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from k8s_scheduler_tpu.cmd.httpserver import start_http_server
from k8s_scheduler_tpu.config import SchedulerConfiguration
from k8s_scheduler_tpu.core import spans as _spans
from k8s_scheduler_tpu.core.scheduler import Scheduler
from k8s_scheduler_tpu.core.spans import (
    SPAN_NAMES,
    SpanRecorder,
    TraceContext,
    export_otlp_dir,
    format_traceparent,
    parse_traceparent,
    sampled,
    spans_to_chrome_events,
    to_otlp_json,
)
from k8s_scheduler_tpu.metrics import SchedulerMetrics
from k8s_scheduler_tpu.service.admission import (
    AdmissionController,
    FrontDoor,
)
from k8s_scheduler_tpu.state import DurableState
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sched(state=None, binds=None, **cfg):
    cfg.setdefault("pod_initial_backoff_seconds", 0.05)
    cfg.setdefault("pod_max_backoff_seconds", 0.2)
    binds = binds if binds is not None else {}
    sched = Scheduler(
        config=SchedulerConfiguration(**cfg),
        binder=lambda p, n: binds.__setitem__(
            p.uid, binds.get(p.uid, 0) + 1
        ),
        state=state,
    )
    return sched, binds


def _ctx() -> TraceContext:
    return TraceContext(_spans.new_trace_id(), _spans.new_span_id())


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------


def test_span_ring_bounds_and_wrap():
    rec = SpanRecorder(capacity=8)
    c = _ctx()
    for i in range(20):
        rec.record("dispatch", c, float(i), float(i) + 0.5, uid=f"u{i}")
    assert rec.count == 20
    spans = rec.snapshot()
    # bounded at capacity, oldest-first, the newest window survives
    assert len(spans) == 8
    assert [s.seq for s in spans] == list(range(12, 20))
    # last=N trims from the newest end
    assert [s.seq for s in rec.snapshot(last=3)] == [17, 18, 19]
    assert rec.for_uid("u19")[0].seq == 19
    assert rec.for_uid("u0") == []  # overwritten by the wrap
    # to_dicts is JSON-clean and rebased against the recorder epoch
    json.dumps(rec.to_dicts(last=5))


def test_span_snapshot_consistent_under_concurrent_writers():
    """Snapshots taken while SEVERAL writer threads hammer the ring
    (the real deployment shape: gRPC/HTTP submit workers + the serve
    loop) must never contain torn windows: seqs strictly ascending,
    all inside one capacity window, every span fully formed."""
    rec = SpanRecorder(capacity=16)
    stop = threading.Event()
    errors: list[str] = []

    def writer(tag: str):
        c = _ctx()
        i = 0
        while not stop.is_set():
            rec.record(
                "decision.row", c, float(i), float(i) + 0.1,
                uid=f"{tag}-{i}",
            )
            i += 1

    def reader():
        for _ in range(2000):
            spans = rec.snapshot()
            seqs = [s.seq for s in spans]
            if seqs != sorted(set(seqs)):
                errors.append(f"non-ascending window {seqs}")
                return
            if seqs and seqs[0] <= seqs[-1] - rec.capacity:
                errors.append(f"window wider than capacity {seqs}")
                return
            for s in spans:
                if not s.trace_id or s.name != "decision.row":
                    errors.append(f"torn span at seq {s.seq}")
                    return

    ws = [
        threading.Thread(target=writer, args=(t,)) for t in ("a", "b", "c")
    ]
    rs = [threading.Thread(target=reader) for _ in range(2)]
    for t in ws + rs:
        t.start()
    for t in rs:
        t.join()
    stop.set()
    for t in ws:
        t.join()
    assert not errors, errors[0]
    assert rec.count > 16  # the ring actually wrapped under test


# ---------------------------------------------------------------------------
# traceparent + sampling
# ---------------------------------------------------------------------------


def test_traceparent_roundtrip_and_malformed_rejection():
    tid, sid = _spans.new_trace_id(), _spans.new_span_id()
    tp = format_traceparent(tid, sid)
    assert tp == f"00-{tid}-{sid}-01"
    assert parse_traceparent(tp) == (tid, sid)
    # tolerant of case and surrounding whitespace (header transports)
    assert parse_traceparent(f"  {tp.upper()}  ") == (tid, sid)
    for bad in (
        "",
        "garbage",
        f"01-{tid}-{sid}-01",  # unknown version
        f"00-{tid[:-1]}-{sid}-01",  # short trace id
        f"00-{tid}-{sid}",  # missing flags
        f"00-{'0' * 32}-{sid}-01",  # all-zero trace id (spec invalid)
        f"00-{tid}-{'0' * 16}-01",  # all-zero span id
    ):
        assert parse_traceparent(bad) is None, bad


def test_sampling_deterministic_and_rate_bounds():
    uids = [f"pod-{i}" for i in range(2000)]
    # deterministic: the same uid at the same rate always decides the
    # same way (a shed retry keeps its sampling fate)
    for u in uids[:50]:
        assert sampled(u, 0.25) == sampled(u, 0.25)
    assert all(sampled(u, 1.0) for u in uids)
    assert not any(sampled(u, 0.0) for u in uids)
    assert not any(sampled(u, -1.0) for u in uids)
    # the coin is unbiased enough to be a rate: 2000 uids at 0.5
    hits = sum(sampled(u, 0.5) for u in uids)
    assert 800 < hits < 1200
    # distinct uids decide independently (both outcomes occur at 1/64)
    verdicts = {sampled(u, 1.0 / 64.0) for u in uids}
    assert verdicts == {True, False}


def test_register_idempotent_adopts_traceparent_and_releases():
    _spans.arm(rate=1.0)
    try:
        c1 = _spans.register("uid-a")
        assert c1 is not None
        # idempotent: a duplicate submit keeps the original binding
        assert _spans.register("uid-a") is c1
        assert _spans.ctx_for("uid-a") is c1
        # an explicit traceparent joins the CALLER's trace verbatim
        tid, sid = _spans.new_trace_id(), _spans.new_span_id()
        c2 = _spans.register("uid-b", format_traceparent(tid, sid))
        assert (c2.trace_id, c2.span_id) == (tid, sid)
        assert c2.traceparent() == format_traceparent(tid, sid)
        # release drops the live join only
        _spans.release("uid-a")
        assert _spans.ctx_for("uid-a") is None
        assert _spans.ctx_for("uid-b") is c2
    finally:
        _spans.disarm()
    # disarm cleared the context map and the stamp-site flag
    assert _spans.ctx_for("uid-b") is None
    assert _spans.register("uid-c") is None  # unarmed: no binding


def test_rate_zero_still_joins_explicit_traceparent():
    """Head sampling gates LOCAL trace starts only: a caller that
    already carries a trace always gets its spans, whatever the armed
    rate — that is what makes traceparent an operator debugging tool."""
    _spans.arm(rate=0.0)
    try:
        assert _spans.register("uid-z") is None
        tid, sid = _spans.new_trace_id(), _spans.new_span_id()
        c = _spans.register("uid-z", format_traceparent(tid, sid))
        assert c is not None and c.trace_id == tid
    finally:
        _spans.disarm()


# ---------------------------------------------------------------------------
# overhead: the unarmed fast path
# ---------------------------------------------------------------------------


def _guard_cost_s(n: int) -> float:
    """Wall time of `n` unarmed stamp-site guards (`if _spans.ARMED`)
    — exactly the bytecode every hot site pays when tracing is off."""
    sink = 0
    t0 = time.perf_counter()
    for _ in range(n):
        if _spans.ARMED:
            sink += 1
    dt = time.perf_counter() - t0
    assert sink == 0
    return dt


def test_unarmed_overhead_below_one_percent():
    """ISSUE 17's <1% bound, measured structurally rather than as a
    flaky A/B latency diff: a pod's whole life crosses ~8 stamp sites,
    so the unarmed tax on N pods is N*8 guard evaluations — time those
    directly and compare against the REAL submit+cycle cost of the
    same N pods."""
    assert not _spans.ARMED
    sched, _binds = _sched()
    adm = AdmissionController(sched, queue_depth=10_000)
    adm.node_churn(adds=make_cluster(8))
    # warm-up: pay the first-compile outside the measured window
    assert adm.submit(make_pods(8, seed=70, name_prefix="warm-")).ok
    sched.schedule_cycle()
    n = 100
    pods = make_pods(n, seed=71, name_prefix="ovh-")
    t0 = time.perf_counter()
    for i in range(0, n, 4):
        assert adm.submit(pods[i:i + 4]).ok
    sched.schedule_cycle()
    lifecycle_s = time.perf_counter() - t0
    guard_s = min(_guard_cost_s(n * 8) for _ in range(5))
    assert guard_s < 0.01 * lifecycle_s, (
        f"unarmed guards cost {guard_s * 1e6:.1f}us for {n} pods vs "
        f"{lifecycle_s * 1e3:.1f}ms submit+cycle — over the 1% budget"
    )


# ---------------------------------------------------------------------------
# the cross-thread trace join: Submit -> serve -> bind, one trace
# ---------------------------------------------------------------------------


def test_cross_thread_trace_join_submit_to_bind(tmp_path):
    """Spans stamped on the submit thread (validate/journal/ack), the
    serve thread (buffer wait, dispatch, decision row, apply fold,
    bind confirm) and the WAL writer's barrier must all land in ONE
    trace — the caller's, when an explicit traceparent rode the
    Submit — with the registration span id as every span's parent."""
    st = DurableState(str(tmp_path), snapshot_interval_seconds=0)
    sched, binds = _sched(
        state=st, multi_cycle_k=4, multi_cycle_max_wait_ms=1e6
    )
    adm = AdmissionController(sched, queue_depth=100)
    adm.node_churn(adds=make_cluster(4))
    fd = FrontDoor(adm)
    tid, sid = _spans.new_trace_id(), _spans.new_span_id()
    tp = format_traceparent(tid, sid)
    rec = _spans.arm(rate=1.0)
    try:
        fd.start()
        pods = make_pods(4, seed=72, name_prefix="tj-")
        result: dict = {}

        def submit():
            result["res"] = adm.submit(pods, traceparent=tp)

        t = threading.Thread(target=submit)
        t.start()
        t.join()
        res = result["res"]
        assert res.ok and res.durable
        # the effective traceparent echoes back to the submitter
        assert res.traceparent == tp
        deadline = time.time() + 60.0
        while len(binds) < 4 and time.time() < deadline:
            time.sleep(0.02)
        fd.stop()
    finally:
        _spans.disarm()
    assert len(binds) == 4
    spans = rec.snapshot()
    assert spans, "no spans recorded"
    # one trace: every span joined the caller's trace id, and every
    # span is a direct child of the registration parent (flat tree)
    assert {s.trace_id for s in spans} == {tid}
    assert {s.parent for s in spans} == {sid}
    assert {s.name for s in spans} <= set(SPAN_NAMES)
    names = {s.name for s in spans}
    assert {
        "submit.validate", "submit.journal", "ack.barrier",
        "mc.buffer_wait", "dispatch", "decision.row", "apply.fold",
        "bind.confirm",
    } <= names, f"missing lifecycle spans, got {sorted(names)}"
    # every pod's life is individually complete
    for p in pods:
        mine = {s.name for s in spans if s.attrs.get("uid") == p.uid}
        assert {"submit.validate", "bind.confirm"} <= mine
    # the ack barrier carries its group-commit join + durability
    ack = [s for s in spans if s.name == "ack.barrier"]
    assert all(s.attrs.get("durable") for s in ack)
    assert all(s.attrs.get("flush_seq", -1) >= 0 for s in ack)
    # serve-side spans carry the cycle-seq exemplar join, and the
    # flight records carry the reverse trace_ids stamp
    serve = [s for s in spans if s.name == "dispatch"]
    assert all(s.attrs.get("seq", -1) >= 0 for s in serve)
    traced_recs = [
        r for r in sched.flight.snapshot() if tid in r.trace_ids
    ]
    assert traced_recs, "no flight record carries the trace exemplar"
    # bind released the live context; the ring stays queryable by uid
    assert _spans.ctx_for(pods[0].uid) is None
    assert rec.for_uid(pods[0].uid)


def test_tracing_on_off_streams_bit_identical():
    """Satellite 3's fuzz spot check: replaying the same corpus trace
    through the REAL Submit/NodeChurn API with tracing armed at rate
    1.0 vs disarmed must leave the decision/bind streams bit-identical
    — tracing observes the schedule, it must never perturb it."""
    from k8s_scheduler_tpu.fuzz.corpus import load_artifact
    from k8s_scheduler_tpu.fuzz.replay import (
        _PER_CYCLE_KEYS,
        replay_engine,
    )

    art = load_artifact(os.path.join(
        REPO, "tests", "corpus", "attribution_static_dyn_split.json"
    ))
    trace = art["trace"]
    eng_off = replay_engine(trace, via_api=True)
    rec = _spans.arm(rate=1.0)
    try:
        eng_on = replay_engine(trace, via_api=True)
    finally:
        _spans.disarm()
    assert not eng_off.failures and not eng_on.failures
    assert len(eng_on.records) == len(eng_off.records)
    for a, b in zip(eng_off.records, eng_on.records):
        for key in _PER_CYCLE_KEYS + ("requeues", "rung"):
            assert a[key] == b[key], (key, a["cycle"])
    assert eng_on.binds == eng_off.binds
    assert rec.count > 0  # the armed replay actually traced


# ---------------------------------------------------------------------------
# export: chrome tracks, OTLP-JSON, the rotated dump directory
# ---------------------------------------------------------------------------


def test_chrome_events_tracks_and_merge():
    assert spans_to_chrome_events([]) == []
    rec = SpanRecorder(capacity=64)
    c1, c2 = _ctx(), _ctx()
    rec.record("dispatch", c1, 1.0, 1.5, uid="u1", seq=7)
    rec.record("bind.confirm", c1, 1.5, 1.6, uid="u1", node="n1")
    rec.record("dispatch", c2, 2.0, 2.2, uid="u2", seq=8)
    events = spans_to_chrome_events(rec.snapshot(), epoch=1.0)
    procs = [e for e in events if e["name"] == "process_name"]
    assert procs == [{
        "name": "process_name", "ph": "M",
        "pid": _spans.TRACE_TRACK_PID, "args": {"name": "pod traces"},
    }]
    # one tid per trace, named by the trace's pods
    tnames = {
        e["args"]["name"] for e in events if e["name"] == "thread_name"
    }
    assert any("pod=u1" in n for n in tnames)
    assert any("pod=u2" in n for n in tnames)
    slices = [e for e in events if e["ph"] == "X"]
    assert len(slices) == 3
    assert all(e["cat"] == "pod-trace" for e in slices)
    d = next(e for e in slices if e["name"] == "bind.confirm")
    assert d["ts"] == pytest.approx(0.5e6)
    assert d["dur"] == pytest.approx(0.1e6)
    assert d["args"]["node"] == "n1" and d["args"]["parent"] == c1.span_id
    # the two traces render on distinct tracks
    assert len({e["tid"] for e in slices}) == 2

    # and to_chrome_trace merges span tracks beside the cycle lanes
    from k8s_scheduler_tpu.core.flight_recorder import (
        FlightRecorder,
        to_chrome_trace,
    )

    fr = FlightRecorder(capacity=8)
    r = fr.start()
    r.mark("dispatch_start", r.t_start + 0.001)
    r.mark("decision_end", r.t_start + 0.004)
    fr.commit(r)
    trace = to_chrome_trace(fr.snapshot(), spans=rec.snapshot())
    pids = {e.get("pid") for e in trace["traceEvents"]}
    assert _spans.TRACE_TRACK_PID in pids  # span tracks present
    assert len(pids) > 1  # alongside the cycle lanes


def test_otlp_json_shape():
    rec = SpanRecorder(capacity=8)
    root = _ctx()
    child = TraceContext(root.trace_id, _spans.new_span_id())
    rec.record(
        "submit.validate",
        TraceContext(root.trace_id, ""),  # root: no parent
        rec.epoch + 1.0, rec.epoch + 1.5, uid="u1",
    )
    rec.record(
        "ack.barrier", child, rec.epoch + 1.5, rec.epoch + 2.0,
        uid="u1", flush_seq=3, durable=True, frac=0.5,
    )
    out = to_otlp_json(
        rec.snapshot(), rec.epoch, rec.wall_epoch, service_name="t"
    )
    json.dumps(out)  # JSON-clean
    (rs,) = out["resourceSpans"]
    attrs = rs["resource"]["attributes"]
    assert {"key": "service.name", "value": {"stringValue": "t"}} in attrs
    (ss,) = rs["scopeSpans"]
    s_root, s_child = ss["spans"]
    assert "parentSpanId" not in s_root  # root omits the parent key
    assert s_child["parentSpanId"] == child.span_id
    assert s_child["traceId"] == root.trace_id
    assert s_child["kind"] == 1
    # nanos anchor at the wall epoch; duration survives the rebase
    t0 = int(s_child["startTimeUnixNano"])
    t1 = int(s_child["endTimeUnixNano"])
    assert t1 - t0 == pytest.approx(0.5e9)
    assert t0 == pytest.approx((rec.wall_epoch + 1.5) * 1e9, rel=1e-6)
    # attrs map to typed OTLP values
    by_key = {a["key"]: a["value"] for a in s_child["attributes"]}
    assert by_key["uid"] == {"stringValue": "u1"}
    assert by_key["flush_seq"] == {"intValue": "3"}
    assert by_key["durable"] == {"boolValue": True}
    assert by_key["frac"] == {"doubleValue": 0.5}


def test_export_otlp_dir_sequence_and_rotation(tmp_path):
    d = str(tmp_path / "otlp")
    rec = SpanRecorder(capacity=64)
    assert export_otlp_dir(rec, d) is None  # empty ring: no file
    c = _ctx()
    for i in range(20):
        rec.record("dispatch", c, float(i), float(i) + 0.1, uid=f"u{i}")
    p0 = export_otlp_dir(rec, d)
    p1 = export_otlp_dir(rec, d)
    assert os.path.basename(p0) == "spans-000000.json"
    assert os.path.basename(p1) == "spans-000001.json"
    with open(p1) as f:
        assert json.load(f)["resourceSpans"]
    # a tiny budget rotates the OLDEST dumps out, never the new one
    for _ in range(3):
        newest = export_otlp_dir(rec, d, max_bytes=1)
    left = sorted(os.listdir(d))
    assert left == [os.path.basename(newest)]
    assert newest.endswith("spans-000004.json")  # numbering continued


# ---------------------------------------------------------------------------
# the HTTP surface: /debug/traces, the alias, /debug/explain
# ---------------------------------------------------------------------------


def _get(url):
    with urllib.request.urlopen(url) as r:
        return r.status, dict(r.headers), r.read()


def _request(url, method):
    req = urllib.request.Request(url, method=method)
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _trace_server():
    """A server with 3 committed cycles, one pod timeline, and a span
    ring holding two traces (uid-1 in cycle 2, uid-2 in cycle 0)."""
    from k8s_scheduler_tpu.core.flight_recorder import FlightRecorder

    fr = FlightRecorder(capacity=16)
    for _ in range(3):
        r = fr.start()
        r.mark("dispatch_start", r.t_start + 0.001)
        r.mark("decision_end", r.t_start + 0.004)
        fr.commit(r)
    rec = SpanRecorder(capacity=64)
    c1, c2 = _ctx(), _ctx()
    rec.record("dispatch", c1, rec.epoch, rec.epoch + 0.01,
               uid="uid-1", seq=2)
    rec.record("bind.confirm", c1, rec.epoch + 0.01, rec.epoch + 0.02,
               uid="uid-1", node="n1")
    rec.record("dispatch", c2, rec.epoch, rec.epoch + 0.01,
               uid="uid-2", seq=0)
    timelines = {
        "uid-1": {
            "uid": "uid-1", "name": "pod-1", "state": "Pending",
            "attempts": [
                {"result": "Unschedulable", "plugin": "TaintToleration",
                 "cycle": 1},
                {"result": "Unschedulable", "plugin": "NodeResourcesFit",
                 "cycle": 2},
                {"result": "Unschedulable", "plugin": "TaintToleration",
                 "cycle": 2},
            ],
            "events": [{"cycle": 1}, {"cycle": 2}],
        }
    }
    server = start_http_server(
        SchedulerMetrics(), port=0, recorder=fr,
        pod_timeline=timelines.get, spans_recorder=rec,
    )
    return server, c1, c2


def test_debug_traces_filters_and_deprecated_alias():
    server, c1, c2 = _trace_server()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        st, headers, body = _get(f"{base}/debug/traces?last=8")
        assert st == 200
        assert "attachment" in headers["Content-Disposition"]
        assert "Deprecation" not in headers  # canonical route
        trace = json.loads(body)
        slices = [
            e for e in trace["traceEvents"]
            if e.get("cat") == "pod-trace"
        ]
        assert len(slices) == 3  # both traces' spans merged in
        # pod= slices spans to the pod and records to its cycles (the
        # span seq exemplar keeps cycle 2 even without timeline events)
        st, _, body = _get(f"{base}/debug/traces?pod=uid-1")
        t = json.loads(body)
        pod_slices = [
            e for e in t["traceEvents"] if e.get("cat") == "pod-trace"
        ]
        assert {e["args"]["trace_id"] for e in pod_slices} == {c1.trace_id}
        assert len(pod_slices) == 2
        # trace= slices to one trace id
        st, _, body = _get(f"{base}/debug/traces?trace={c2.trace_id}")
        t = json.loads(body)
        ids = {
            e["args"]["trace_id"] for e in t["traceEvents"]
            if e.get("cat") == "pod-trace"
        }
        assert ids == {c2.trace_id}
        # a pod nobody ever saw is a 404
        st, _, _ = _request(f"{base}/debug/traces?pod=ghost", "GET")
        assert st == 404
        # the deprecated alias: identical payload, deprecation headers
        gs, gh, gbody = _get(f"{base}/debug/traces?last=8")
        as_, ah, abody = _get(f"{base}/debug/trace?last=8")
        assert (gs, as_) == (200, 200)
        assert abody == gbody
        assert ah["Deprecation"] == "true"
        assert "successor-version" in ah["Link"]
        assert "/debug/traces" in ah["Link"]
    finally:
        server.shutdown()


def test_debug_explain_joined_verdict():
    server, c1, _c2 = _trace_server()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        st, _, body = _request(f"{base}/debug/explain", "GET")
        assert st == 400  # missing ?pod=
        st, _, body = _request(f"{base}/debug/explain?pod=ghost", "GET")
        assert st == 404
        st, _, body = _get(f"{base}/debug/explain?pod=uid-1")
        assert st == 200
        v = json.loads(body)
        # first-rejector attribution: each failed attempt charges the
        # FIRST plugin that rejected the pod
        assert v["first_rejector"] == "TaintToleration"
        assert v["last_rejector"] == "TaintToleration"
        assert v["reject_counts"] == {
            "TaintToleration": 2, "NodeResourcesFit": 1,
        }
        assert v["state"] == "Pending" and len(v["attempts"]) == 3
        # the span join: durations, totals, and the trace ids
        assert v["trace_ids"] == [c1.trace_id]
        names = {s["name"] for s in v["spans"]}
        assert names == {"dispatch", "bind.confirm"}
        assert v["span_totals_ms"]["dispatch"] == pytest.approx(10.0)
    finally:
        server.shutdown()


def test_new_endpoints_head_and_mutations_405():
    """HEAD/405 parity for every endpoint this PR added (the ISSUE 17
    satellite): probes HEAD them, and mutating verbs stay refused."""
    server, _c1, _c2 = _trace_server()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        for path in (
            "/debug/traces?last=4",
            "/debug/trace?last=4",
            "/debug/explain?pod=uid-1",
        ):
            gs, _gh, gbody = _request(f"{base}{path}", "GET")
            hs, hh, hbody = _request(f"{base}{path}", "HEAD")
            assert (gs, hs) == (200, 200), path
            assert hbody == b""  # HEAD: headers only
            assert hh["Content-Length"] == str(len(gbody)), path
        for path in ("/debug/traces", "/debug/explain"):
            for method in ("POST", "PUT", "DELETE", "PATCH"):
                st, headers, _ = _request(f"{base}{path}", method)
                assert st == 405, (path, method)
                assert headers["Allow"] == "GET, HEAD"
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# bench_diff: the --max-trace-overhead ceiling
# ---------------------------------------------------------------------------


def _bench_diff(tmp_path, old_row, new_row, *extra):
    for name, row in (("old.json", old_row), ("new.json", new_row)):
        (tmp_path / name).write_text(json.dumps({"configs": [row]}))
    return subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "bench_diff.py"),
            *extra,
            str(tmp_path / "old.json"), str(tmp_path / "new.json"),
        ],
        capture_output=True, text=True,
    )


def test_trace_overhead_pct_absorbs_fsync_bimodality():
    sys.path.insert(0, REPO)
    try:
        import bench_suite
    finally:
        sys.path.remove(REPO)
    f = bench_suite.trace_overhead_pct
    # the measured rig flip: untraced stage lands the lucky fsync mode
    # (0.34 ms ack p99), traced stage the slow one (4.5 ms) — same
    # code, same disk. The naive p99 ratio reads +1219%; the floored
    # axis must not count it (bind p50 barely moves)
    assert f(0.341, 4.5, 18385.0, 18553.0) < 5.0
    # and the reverse flip clamps at 0, never negative
    assert f(4.361, 0.341, 18500.0, 18400.0) == 0.0
    # a catastrophic ack regression (far past the jitter floor) still
    # trips a 50% ceiling regardless of which mode the base landed in
    assert f(0.341, 30.0, 18385.0, 18553.0) > 50.0
    assert f(4.361, 30.0, 18385.0, 18553.0) > 50.0
    # a serve-loop-serializing bug shows on the bind p50 axis plainly
    assert f(4.0, 4.0, 10000.0, 40000.0) == pytest.approx(300.0)


def test_bench_diff_trace_overhead_ceiling(tmp_path):
    base = {"config": 9, "submit_ack_p99_ms": 5.0}
    # under the ceiling: clean (the old side has no trov at all —
    # pre-PR artifacts must keep diffing against traced ones)
    r = _bench_diff(
        tmp_path, dict(base), dict(base, trace_overhead_pct=12.0),
        "--max-trace-overhead", "50",
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "trace_overhead_ceiling" in r.stdout
    # over the ceiling: the absolute gate trips on the NEW artifact
    r = _bench_diff(
        tmp_path, dict(base), dict(base, trace_overhead_pct=80.0),
        "--max-trace-overhead", "50",
    )
    assert r.returncode == 1
    assert "REGRESSED" in r.stdout
    # 0 disables the gate entirely
    r = _bench_diff(
        tmp_path, dict(base), dict(base, trace_overhead_pct=80.0),
        "--max-trace-overhead", "0",
    )
    assert r.returncode == 0, r.stdout + r.stderr
    # artifacts without the metric (both sides pre-PR) diff clean
    r = _bench_diff(tmp_path, dict(base), dict(base))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "trace_overhead_ceiling" not in r.stdout
