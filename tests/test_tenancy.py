"""Multi-tenant arena (ISSUE 18): virtual clusters on one compiled
program.

Three layers under test, matching the tenancy/ package:

- TenantRegistry: virtual-cluster lifecycle, pod/node routing, journal
  replay (`restore_registry` failover rebuilds every tenant).
- MultiTenantArena + ArenaPacker: the central property — a packed
  N-tenant arena run is BIT-EQUAL per tenant to N sequential
  single-tenant runs — checked directly on synth clusters and through
  the fuzz grammar (`generate_multitenant_trace` / `run_tenant_case`),
  plus the negative control: the deliberate row_skew cross-tenant leak
  MUST be caught.
- AdmissionController in tenant mode: unknown/suspended tenants are
  invalid, per-tenant quota sheds with retry-after, the weighted-fair
  share sheds a flooding tenant under global pressure, and the
  starved-tenant anomaly fires on the schedule side.

The small-config tests here are tier-1; the 1000-tenant scale check is
additionally `slow` (run with `-m slow`).
"""

import numpy as np
import pytest

from k8s_scheduler_tpu.core import spans
from k8s_scheduler_tpu.core.observe import CycleObserver
from k8s_scheduler_tpu.fuzz import (
    generate_multitenant_trace,
    run_case,
    run_tenant_case,
)
from k8s_scheduler_tpu.metrics import SchedulerMetrics
from k8s_scheduler_tpu.service.admission import AdmissionController
from k8s_scheduler_tpu.state.journal import Journal
from k8s_scheduler_tpu.tenancy import (
    MultiTenantArena,
    TenantError,
    TenantFrontHost,
    TenantRegistry,
    TenantSuspended,
    UnknownTenant,
    pow2_bucket,
    restore_registry,
)
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

pytestmark = pytest.mark.tenancy


def _sample(metrics, name, labels=None):
    v = metrics.registry.get_sample_value(name, labels or {})
    return 0.0 if v is None else v


def _retenant(objs, tenant_id):
    """Move synth objects into a virtual cluster: tenant identity rides
    the namespace, and the namespace-qualified uid keeps same-named
    objects in different tenants from colliding."""
    for o in objs:
        o.metadata.namespace = tenant_id
        o.metadata.uid = f"{tenant_id}/{o.metadata.name}"
    return objs


def _populate(reg, tenant_ids, *, nodes=3, pods=5, node_seed=7,
              pod_seed=11):
    """Identical small shapes per tenant (shared spec bucket): same
    node/pod SEEDS so layouts match, namespace-scoped names so content
    is still per-tenant."""
    for i, tid in enumerate(tenant_ids):
        if reg.get(tid) is None:
            reg.create(tid)
        for nd in _retenant(make_cluster(nodes, seed=node_seed), tid):
            reg.add_node(tid, nd)
        for p in _retenant(
            make_pods(pods, seed=pod_seed, name_prefix=f"t{i}-pod"), tid
        ):
            reg.add_pod(tid, p)


def _tenant_decisions(arena):
    """last_decisions regrouped per tenant (order preserved)."""
    out: dict = {}
    for tid, uid, node in arena.last_decisions:
        out.setdefault(tid, []).append((uid, node))
    return out


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_pow2_bucket():
    assert [pow2_bucket(n) for n in (1, 2, 3, 5, 8, 9, 1000)] == [
        1, 2, 4, 8, 8, 16, 1024,
    ]


def test_registry_lifecycle_and_routing():
    reg = TenantRegistry()
    reg.create("team-a", quota=10, weight=2.0)
    reg.create("team-b")
    with pytest.raises(TenantError):
        reg.create("team-a")  # duplicate

    node = _retenant(make_cluster(1), "team-a")[0]
    reg.add_node("team-a", node)
    pod = _retenant(make_pods(1, name_prefix="p"), "team-a")[0]
    reg.route(pod)  # tenant rides the namespace
    assert reg.depth("team-a") == 1
    assert reg.has_pod(pod.uid)

    reg.suspend("team-a")
    with pytest.raises(TenantSuspended):
        reg.add_pod(
            "team-a", _retenant(make_pods(1, name_prefix="q"), "team-a")[0]
        )
    assert reg.require("team-a").lifecycle == "suspended"
    reg.resume("team-a")
    assert reg.require("team-a").lifecycle == "active"

    reg.bind("team-a", pod.uid, node.name)
    t = reg.require("team-a")
    assert t.bound_node(pod.uid) == node.name
    assert t.depth() == 0 and t.bound_count() == 1

    with pytest.raises(TenantError):
        reg.bind("team-a", pod.uid, node.name)  # no longer pending
    with pytest.raises(UnknownTenant):
        reg.add_pod("ghost", pod)

    reg.delete("team-b")
    assert reg.ids() == ["team-a"]
    st = reg.status()
    assert st["tenants"] == 1 and st["bound"] == 1


def test_registry_suspended_tenant_skipped_by_encode():
    reg = TenantRegistry()
    _populate(reg, ["a", "b"], nodes=2, pods=2)
    assert {t.id for t, *_ in reg.encode_active()} == {"a", "b"}
    reg.suspend("b")
    assert {t.id for t, *_ in reg.encode_active()} == {"a"}


def test_restore_registry_failover(tmp_path):
    """Crash/failover drill: every tn.* mutation journals, and a fresh
    registry rebuilt from the journal directory alone carries the same
    virtual clusters — lifecycle, quotas, nodes, pending order, binds."""
    wal = tmp_path / "tenancy-wal"
    j = Journal(str(wal))
    reg = TenantRegistry()
    reg.set_journal(j.append)

    reg.create("team-a", quota=4, weight=2.0)
    reg.create("team-b")
    reg.create("team-c")
    node = _retenant(make_cluster(2), "team-a")
    for nd in node:
        reg.add_node("team-a", nd)
    pods = _retenant(make_pods(3, name_prefix="p"), "team-a")
    for p in pods:
        reg.add_pod("team-a", p)
    reg.bind("team-a", pods[0].uid, node[0].name)
    reg.remove_pod("team-a", pods[2].uid)
    reg.suspend("team-b")
    reg.delete("team-c")
    j.flush()
    j.close()

    restored = restore_registry(str(wal))
    assert sorted(restored.ids()) == ["team-a", "team-b"]
    a = restored.require("team-a")
    assert (a.quota, a.weight, a.lifecycle) == (4, 2.0, "active")
    assert a.node_count() == 2
    assert [p.uid for p in a.pending_pods()] == [pods[1].uid]
    assert a.bound_node(pods[0].uid) == node[0].name
    assert restored.require("team-b").lifecycle == "suspended"


def test_restore_refuses_unknown_op(tmp_path):
    reg = TenantRegistry()
    with pytest.raises(ValueError, match="unknown tenancy journal op"):
        reg.apply("tn.frobnicate", 0.0, {})


# ---------------------------------------------------------------------------
# arena: the bit-equality property
# ---------------------------------------------------------------------------


def test_packed_equals_sequential_synth():
    """The isolation contract, directly: a packed 3-tenant arena cycle
    produces per-tenant decision streams bit-equal to 3 sequential
    single-tenant runs — and same-shape tenants share ONE dispatch."""
    tids = ["team-a", "team-b", "team-c"]
    reg_p = TenantRegistry()
    reg_s = TenantRegistry()
    _populate(reg_p, tids, nodes=3, pods=6)
    _populate(reg_s, tids, nodes=3, pods=6)

    packed = MultiTenantArena(reg_p)
    seq = MultiTenantArena(reg_s, sequential=True)
    sp = packed.run_cycle()
    ss = seq.run_cycle()

    assert sp["tenants"] == ss["tenants"] == 3
    # same spec bucket -> one arena launch vs three sequential ones
    assert sp["dispatches"] == 1 and ss["dispatches"] == 3
    assert sp["bound"] == ss["bound"] > 0
    assert _tenant_decisions(packed) == _tenant_decisions(seq)
    for tid in tids:
        tp, ts = reg_p.require(tid), reg_s.require(tid)
        assert tp.bound_count() == ts.bound_count()
        for uid in list(tp._tn_bound):
            assert tp.bound_node(uid) == ts.bound_node(uid)


def test_arena_builds_flat_across_cycles():
    """Zero compiles after warmup: a second wave of same-shape demand
    reuses the cached (spec bucket, T-pad) executable — `builds` stays
    flat while `dispatches` grows."""
    tids = [f"vc-{i}" for i in range(4)]
    reg = TenantRegistry()
    _populate(reg, tids, nodes=2, pods=3)
    arena = MultiTenantArena(reg)
    s1 = arena.run_cycle()
    builds = arena.packer.builds
    assert builds >= 1
    for i, tid in enumerate(tids):
        for p in _retenant(
            make_pods(3, seed=23, name_prefix=f"w2-{i}"), tid
        ):
            reg.add_pod(tid, p)
    s2 = arena.run_cycle()
    assert arena.packer.builds == builds
    assert s2["dispatches"] >= 1
    assert arena.packer.dispatches == s1["dispatches"] + s2["dispatches"]


def test_row_skew_leak_breaks_equality():
    """Negative control for the property itself: the planted cross-
    tenant leak (rolling decision rows within a bucket) must separate
    packed from sequential — otherwise the equality check is vacuous."""
    tids = ["team-a", "team-b", "team-c"]
    reg_p = TenantRegistry()
    reg_s = TenantRegistry()
    # distinct pod seeds per tenant so neighboring rows differ (a roll
    # of identical rows would be invisible)
    for i, tid in enumerate(tids):
        for reg in (reg_p, reg_s):
            reg.create(tid)
            for nd in _retenant(make_cluster(2, seed=3), tid):
                reg.add_node(tid, nd)
            for p in _retenant(
                make_pods(4, seed=31 + i, name_prefix=f"t{i}-p"), tid
            ):
                reg.add_pod(tid, p)
    packed = MultiTenantArena(reg_p)
    packed.inject = "row_skew"
    seq = MultiTenantArena(reg_s, sequential=True)
    packed.run_cycle()
    seq.run_cycle()
    assert _tenant_decisions(packed) != _tenant_decisions(seq)


# ---------------------------------------------------------------------------
# fuzz grammar: multi-tenant differential cases
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_multitenant_clean(seed):
    """The multi-tenant fuzz grammar (tenant churn, suspends, deletes,
    per-tenant arrivals) replays with zero failures — and run_case
    routes tenancy traces to the tenant oracle automatically."""
    trace = generate_multitenant_trace(seed)
    assert run_case(trace) == []


@pytest.mark.parametrize("seed", range(3))
def test_fuzz_multitenant_catches_row_skew(seed):
    failures = run_tenant_case(
        generate_multitenant_trace(seed), bug="tenant_row_skew"
    )
    assert failures, "planted cross-tenant leak escaped the oracle"
    assert all(f.cls.startswith("tenant/") for f in failures)


# ---------------------------------------------------------------------------
# admission: tenant validity, quota, weighted-fair share
# ---------------------------------------------------------------------------


def _front(reg, **adm_kw):
    host = TenantFrontHost(reg)
    adm = AdmissionController(host, tenants=reg, **adm_kw)
    return host, adm


def test_admission_unknown_and_suspended_tenant_invalid():
    reg = TenantRegistry()
    reg.create("team-a")
    _host, adm = _front(reg)

    ghost = _retenant(make_pods(1, name_prefix="g"), "nobody")
    res = adm.submit(ghost)
    assert not res.ok and res.accepted == 0
    assert res.invalid == (ghost[0].uid,)
    assert "unknown tenant" in res.reason and "nobody" in res.reason
    assert res.retry_after_ms == 0.0  # caller bug, not backpressure

    reg.suspend("team-a")
    locked = _retenant(make_pods(1, name_prefix="s"), "team-a")
    res = adm.submit(locked)
    assert res.invalid == (locked[0].uid,)
    assert "suspended" in res.reason and "team-a" in res.reason
    assert reg.depth("team-a") == 0  # nothing routed

    reg.resume("team-a")
    res = adm.submit(locked)
    assert res.ok and res.accepted == 1
    assert reg.depth("team-a") == 1


def test_admission_tenant_quota_shed():
    reg = TenantRegistry()
    reg.create("team-a", quota=4)
    reg.create("team-b")
    _host, adm = _front(reg)

    first = _retenant(make_pods(3, name_prefix="a"), "team-a")
    assert adm.submit(first).ok
    assert adm.tenant_depth("team-a") == 3

    over = _retenant(make_pods(3, seed=5, name_prefix="b"), "team-a")
    res = adm.submit(over)
    assert res.shed == 3 and res.accepted == 0
    assert res.retry_after_ms > 0
    assert "team-a" in res.reason and "quota exceeded" in res.reason
    assert reg.depth("team-a") == 3  # the over-quota wave never routed

    # the quota is tenant-scoped: team-b's traffic still lands
    other = _retenant(make_pods(3, seed=6, name_prefix="c"), "team-b")
    assert adm.submit(other).ok
    m = _host.metrics
    assert _sample(
        m, "scheduler_tenancy_events_total", {"event": "quota_shed"}
    ) == 1


def test_admission_weighted_fair_share_under_pressure():
    """Two tenants saturating a small front door: the heavy-weight
    tenant keeps its larger share, the light tenant sheds once past
    its own — and only under global pressure (idle fleets are
    work-conserving)."""
    reg = TenantRegistry()
    reg.create("heavy", weight=3.0)
    reg.create("light", weight=1.0)
    _host, adm = _front(reg, queue_depth=16)

    # no pressure: light may exceed its static share of 4
    early = _retenant(make_pods(5, name_prefix="e"), "light")
    assert adm.submit(early).ok

    # push the fleet past depth_bound // 2 from the heavy tenant
    # (share = 16 * 3/4 = 12, so this is within its own cap)
    wave = _retenant(make_pods(6, seed=5, name_prefix="h"), "heavy")
    assert adm.submit(wave).ok

    # light is now over its weighted share (5 held + 2 > 4) under
    # pressure -> fair shed with a tenant-scoped reason
    res = adm.submit(
        _retenant(make_pods(2, seed=6, name_prefix="l"), "light")
    )
    assert res.shed == 2 and res.retry_after_ms > 0
    assert "light" in res.reason
    assert "weighted-fair share" in res.reason

    # the heavy tenant still has headroom at the same fleet depth
    assert adm.submit(
        _retenant(make_pods(1, seed=7, name_prefix="h2"), "heavy")
    ).ok
    assert _sample(
        _host.metrics,
        "scheduler_tenancy_events_total",
        {"event": "fair_shed"},
    ) == 1


def test_admission_note_bind_untracks_tenant_depth():
    reg = TenantRegistry()
    reg.create("team-a")
    host, adm = _front(reg)
    for nd in _retenant(make_cluster(2), "team-a"):
        host.on_node_add(nd)
    pods = _retenant(make_pods(2, name_prefix="p"), "team-a")
    assert adm.submit(pods).ok
    assert adm.tenant_depth("team-a") == 2
    stats = host.schedule_cycle()
    assert stats.bound == 2
    # arena folds call note_bind -> the quota denominator drains
    assert adm.tenant_depth("team-a") == 0


# ---------------------------------------------------------------------------
# starvation + observability attribution
# ---------------------------------------------------------------------------


def test_starved_tenant_anomaly():
    """A tenant with standing demand that binds nothing while others
    bind trips `tenant_starved` after starve_after cycles — once per
    streak, attributed to the tenant."""
    m = SchedulerMetrics()
    obs = CycleObserver(metrics=m, warmup_cycles=0)
    reg = TenantRegistry(metrics=m)
    reg.create("fed")
    reg.create("starved")
    for nd in _retenant(make_cluster(2), "fed"):
        reg.add_node("fed", nd)
    # the starved tenant has demand but zero capacity: every cycle
    # leaves it pending while `fed` binds
    for p in _retenant(make_pods(2, name_prefix="s"), "starved"):
        reg.add_pod("starved", p)
    arena = MultiTenantArena(reg, observer=obs, metrics=m, starve_after=2)

    for cycle in range(3):
        for p in _retenant(
            make_pods(1, seed=cycle, name_prefix=f"f{cycle}"), "fed"
        ):
            reg.add_pod("fed", p)
        arena.run_cycle()

    assert obs.anomaly_counts["tenant_starved"] == 1  # once per streak
    ev = [e for e in obs.ring if e["class"] == "tenant_starved"][0]
    assert ev["profile"] == "starved"
    assert ev["detail"]["tenant"] == "starved"
    assert ev["detail"]["streak"] == 2
    assert _sample(
        m, "scheduler_tenancy_events_total", {"event": "starved"}
    ) == 1
    # binding the starved tenant's demand resets the streak machinery
    for nd in _retenant(make_cluster(2), "starved"):
        reg.add_node("starved", nd)
    arena.run_cycle()
    assert reg.require("starved").starve_streak == 0


def test_tenancy_lifecycle_metrics():
    m = SchedulerMetrics()
    reg = TenantRegistry(metrics=m)
    reg.create("a")
    reg.suspend("a")
    reg.resume("a")
    reg.delete("a")
    for event in ("created", "suspended", "resumed", "deleted"):
        assert _sample(
            m, "scheduler_tenancy_events_total", {"event": event}
        ) == 1


def test_spans_carry_tenant_attribution():
    """Submit-path spans inherit the tenant from their trace context,
    and the Perfetto export leads the track name with it so one
    virtual cluster's lanes group together."""
    rec = spans.arm(rate=1.0)
    try:
        reg = TenantRegistry()
        reg.create("team-a")
        _host, adm = _front(reg)
        pods = _retenant(make_pods(1, name_prefix="p"), "team-a")
        res = adm.submit(pods)
        assert res.ok
        got = rec.snapshot()
        assert got, "submit path recorded no spans while armed"
        assert all(s.attrs.get("tenant") == "team-a" for s in got)
        events = spans.spans_to_chrome_events(got)
        names = [
            e["args"]["name"] for e in events
            if e["name"] == "thread_name"
        ]
        assert any(n.startswith("tenant team-a trace ") for n in names)
    finally:
        spans.disarm()


# ---------------------------------------------------------------------------
# scale (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_thousand_tenants_one_bucket():
    """The headline shape: 1000 small same-spec virtual clusters pack
    into ONE arena dispatch per cycle (T padded to 1024), and a second
    wave of same-shape demand compiles nothing new."""
    reg = TenantRegistry()
    T = 1000
    for i in range(T):
        tid = f"vc-{i:04d}"
        reg.create(tid)
        for nd in _retenant(make_cluster(2, seed=7), tid):
            reg.add_node(tid, nd)
        for p in _retenant(
            make_pods(2, seed=11, name_prefix=f"p{i}"), tid
        ):
            reg.add_pod(tid, p)
    arena = MultiTenantArena(reg)
    s1 = arena.run_cycle()
    assert s1["tenants"] == T
    assert s1["dispatches"] == 1  # one spec bucket, one launch
    assert s1["bound"] > 0
    builds = arena.packer.builds

    for i in range(T):
        tid = f"vc-{i:04d}"
        for p in _retenant(
            make_pods(2, seed=13, name_prefix=f"q{i}"), tid
        ):
            reg.add_pod(tid, p)
    s2 = arena.run_cycle()
    assert arena.packer.builds == builds  # zero compiles after warmup
    assert s2["dispatches"] == 1
