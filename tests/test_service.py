"""gRPC shim tests (SURVEY.md §7 step 7): end-to-end over a real grpc
channel on localhost, plus the fault-tolerance contract from §5.3 —
shim restart recovers via agent re-list, bind failures forget+backoff,
and no pod is ever double-bound."""

import grpc
import pytest

from k8s_scheduler_tpu.models import MakeNode, MakePod
from k8s_scheduler_tpu.models.api import PodGroup
from k8s_scheduler_tpu.service import (
    SchedulerAgent,
    SchedulerClient,
    serve,
)
from k8s_scheduler_tpu.service import convert
from k8s_scheduler_tpu.service import scheduler_pb2 as pb


# ---- conversion round-trips ------------------------------------------------


def test_pod_proto_roundtrip_preserves_scheduling_fields():
    pod = (
        MakePod("web-1", namespace="prod")
        .req({"cpu": "500m", "memory": "1Gi"})
        .labels({"app": "web"})
        .priority(7)
        .node_selector({"disk": "ssd"})
        .toleration("dedicated", "gpu", "NoSchedule")
        .pod_affinity("topology.kubernetes.io/zone", {"app": "cache"})
        .pod_affinity("kubernetes.io/hostname", {"app": "web"}, anti=True)
        .spread(2, "topology.kubernetes.io/zone", {"app": "web"})
        .host_port(8080)
        .group("gang-a")
        .obj()
    )
    back = convert.pod_from(convert.pod_to(pod))
    assert back.uid == pod.uid
    assert back.resource_requests() == pod.resource_requests()
    assert back.spec.priority == 7
    assert back.spec.node_selector == {"disk": "ssd"}
    assert back.spec.tolerations == pod.spec.tolerations
    assert back.spec.affinity == pod.spec.affinity
    assert (
        back.spec.topology_spread_constraints
        == pod.spec.topology_spread_constraints
    )
    assert back.host_ports() == pod.host_ports()
    assert back.spec.pod_group == "gang-a"


def test_node_proto_roundtrip():
    node = (
        MakeNode("n1")
        .capacity({"cpu": "16", "memory": "32Gi"})
        .labels({"topology.kubernetes.io/zone": "zone-a"})
        .taint("dedicated", "gpu")
        .obj()
    )
    back = convert.node_from(convert.node_to(node))
    assert back.name == "n1"
    assert back.status.allocatable == node.status.allocatable
    assert back.spec.taints == node.spec.taints
    assert back.metadata.labels == node.metadata.labels


# ---- end-to-end over localhost ---------------------------------------------


class Applier:
    """Fake cluster-side bind applier."""

    def __init__(self):
        self.bound = {}
        self.fail_uids = set()
        self.evicted = []

    def bind(self, uid, name, namespace, node_name):
        if uid in self.fail_uids:
            raise RuntimeError("binding POST failed")
        assert uid not in self.bound, f"double bind of {uid}"
        self.bound[uid] = node_name

    def evict(self, uid, node_name):
        self.evicted.append(uid)


@pytest.fixture()
def shim():
    server, service, port = serve("127.0.0.1:0")
    client = SchedulerClient(f"127.0.0.1:{port}")
    yield server, service, client
    client.close()
    server.stop(grace=None)


def test_service_schedules_over_the_wire(shim):
    _, _, client = shim
    applier = Applier()
    agent = SchedulerAgent(client, applier.bind, applier.evict)
    for i in range(3):
        agent.upsert_node(MakeNode(f"n{i}").capacity({"cpu": "8"}).obj())
    for i in range(6):
        agent.upsert_pod(MakePod(f"p{i}").req({"cpu": "1"}).obj())
    resp = agent.run_cycle()
    assert resp.stats.scheduled == 6
    assert len(applier.bound) == 6
    assert set(applier.bound.values()) <= {"n0", "n1", "n2"}
    # Scheduled events ride the response, drained per cycle
    assert sum(1 for ev in resp.events if ev.reason == "Scheduled") == 6
    # second cycle: nothing pending
    resp2 = agent.run_cycle()
    assert resp2.stats.attempted == 0
    assert len(resp2.events) == 0
    assert client.health().ok
    assert b"scheduler_schedule_attempts_total" in client.metrics_text()


def test_volume_binding_over_the_wire(shim):
    from k8s_scheduler_tpu.models.api import (
        VOLUME_BINDING_WAIT,
        NodeSelectorRequirement,
        NodeSelectorTerm,
        PersistentVolume,
        PersistentVolumeClaim,
        StorageClass,
    )

    _, _, client = shim
    applier = Applier()
    agent = SchedulerAgent(client, applier.bind, applier.evict)
    zone = "topology.kubernetes.io/zone"
    for i in range(4):
        agent.upsert_node(
            MakeNode(f"n{i}")
            .capacity({"cpu": "8"})
            .labels({zone: f"z{i % 2}"})
            .obj()
        )
    agent.upsert_storage_class(
        StorageClass("local", VOLUME_BINDING_WAIT, provisioner=False)
    )
    agent.upsert_pv(
        PersistentVolume(
            "pv-z1", capacity=10.0, storage_class="local",
            node_affinity=(
                NodeSelectorTerm(
                    (NodeSelectorRequirement(zone, "In", ("z1",)),)
                ),
            ),
        )
    )
    agent.upsert_pvc(
        PersistentVolumeClaim("data", storage_class="local", request=1.0)
    )
    agent.upsert_pod(MakePod("db").req({"cpu": "1"}).volume("data").obj())
    resp = agent.run_cycle()
    assert resp.stats.scheduled == 1
    # the only candidate PV is zone-restricted to z1 (nodes n1, n3)
    assert list(applier.bound.values())[0] in ("n1", "n3")


def test_serve_raises_on_unbindable_address():
    server, _, port = serve("127.0.0.1:0")
    try:
        # grpc raises RuntimeError itself when SO_REUSEPORT is off; the
        # serve() OSError is the belt-and-braces path for versions that
        # signal failure by returning port 0 instead
        with pytest.raises((OSError, RuntimeError)):
            serve(f"127.0.0.1:{port}")  # already taken
    finally:
        server.stop(grace=None)


def test_bind_failure_forgets_and_retries(shim):
    _, _, client = shim
    applier = Applier()
    agent = SchedulerAgent(client, applier.bind, applier.evict)
    agent.upsert_node(MakeNode("n0").capacity({"cpu": "8"}).obj())
    pod = MakePod("p").req({"cpu": "1"}).obj()
    agent.upsert_pod(pod)
    applier.fail_uids.add(pod.uid)
    resp = agent.run_cycle()
    assert len(resp.bindings) == 1 and not applier.bound
    # the failure report goes out with the next cycle; backoff applies, so
    # drive cycles until the pod comes back (initial backoff 1s is too long
    # for a test -> flush by event instead: a node update unsticks nothing
    # in backoff; wait out via repeated cycles is flaky. Use the queue
    # directly through the service's scheduler for determinism.)
    applier.fail_uids.clear()
    service = shim[1]
    agent.run_cycle()  # reports the failure; pod now in backoff
    assert not service.scheduler.cache.is_assumed(pod.uid)
    # force the backoff to expire deterministically
    for e in service.scheduler.queue._backoff.values():
        e.backoff_expiry = 0.0
    resp = agent.run_cycle()
    assert resp.stats.scheduled == 1
    assert applier.bound[pod.uid] == "n0"


def test_gang_scheduling_over_the_wire(shim):
    _, _, client = shim
    applier = Applier()
    agent = SchedulerAgent(client, applier.bind, applier.evict)
    agent.upsert_node(MakeNode("n0").capacity({"cpu": "4", "pods": "110"}).obj())
    agent.add_pod_group(PodGroup("gang", 3))
    for i in range(3):
        agent.upsert_pod(
            MakePod(f"g{i}").req({"cpu": "2"}).group("gang").obj()
        )
    resp = agent.run_cycle()
    # only 2 of 3 fit -> all-or-nothing unwind, nothing binds
    assert resp.stats.scheduled == 0
    assert resp.stats.gang_dropped == 2
    assert not applier.bound


def test_batched_updates_coalesce_into_one_rpc(shim):
    _, service, client = shim
    applier = Applier()
    agent = SchedulerAgent(client, applier.bind, applier.evict)
    calls = {"n": 0}
    orig = client.update

    def counting_update(req, timeout=10.0):
        calls["n"] += 1
        return orig(req, timeout=timeout)

    client.update = counting_update
    with agent.batched():
        for i in range(4):
            agent.upsert_node(MakeNode(f"n{i}").capacity({"cpu": "8"}).obj())
        for i in range(20):
            agent.upsert_pod(MakePod(f"p{i}").req({"cpu": "1"}).obj())
    assert calls["n"] == 1  # 24 objects, one RPC
    resp = agent.run_cycle()
    assert resp.stats.scheduled == 20


def test_shim_restart_recovers_without_double_bind(shim):
    server, _, client = shim
    applier = Applier()
    agent = SchedulerAgent(client, applier.bind, applier.evict)
    agent.upsert_node(MakeNode("n0").capacity({"cpu": "8"}).obj())
    agent.upsert_pod(MakePod("a").req({"cpu": "1"}).obj())
    resp = agent.run_cycle()
    assert resp.stats.scheduled == 1 and len(applier.bound) == 1

    # kill the shim mid-flight and bring up a fresh one (new state)
    server.stop(grace=None)
    new_server, new_service, new_port = serve("127.0.0.1:0")
    try:
        agent.client = SchedulerClient(f"127.0.0.1:{new_port}")
        # agent notices the restart on the next call and re-lists; the
        # bound pod is replayed WITH its binding, the new pod without
        agent.upsert_pod(MakePod("b").req({"cpu": "1"}).obj())
        resp = agent.run_cycle()
        # restart must not re-schedule pod a (it is bound state, not
        # pending) — only b binds, and the applier asserts no double-bind
        assert resp.stats.scheduled == 1
        assert set(applier.bound) == {"default/a", "default/b"}
        assert new_service.scheduler.cache.counts()["bound"] >= 1
    finally:
        agent.client.close()
        new_server.stop(grace=None)


def test_preemption_over_the_wire(shim):
    _, _, client = shim
    applier = Applier()
    agent = SchedulerAgent(client, applier.bind, applier.evict)
    agent.upsert_node(MakeNode("n0").capacity({"cpu": "2", "pods": "110"}).obj())
    victim = MakePod("victim").req({"cpu": "2"}).priority(1).obj()
    agent.upsert_pod(victim, bound_node="n0")
    urgent = MakePod("urgent").req({"cpu": "2"}).priority(10).obj()
    agent.upsert_pod(urgent)
    resp = agent.run_cycle()
    assert resp.stats.scheduled == 0
    assert [n.pod_uid for n in resp.nominations] == [urgent.uid]
    assert [e.pod_uid for e in resp.evictions] == [victim.uid]
    assert applier.evicted == [victim.uid]
