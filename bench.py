#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line with the headline metric.

Headline (BASELINE.md north star): pod-node scoring decisions per second —
P x N feasibility+scoring decisions divided by wall-clock cycle time — at
benchmark config #4 scale (10k pods x 5k nodes) by default. vs_baseline is
against the driver target of 50,000 decisions/s on v5e-8.

Runs on whatever accelerator `jax.devices()` provides (the real TPU chip
under the driver; CPU elsewhere via BENCH_FORCE_CPU=1). Sizes can be
overridden with BENCH_PODS / BENCH_NODES / BENCH_ITERS.
"""

import json
import os
import sys
import time

TARGET_DECISIONS_PER_SEC = 50_000.0


def main() -> None:
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np

    from k8s_scheduler_tpu.core import build_cycle_fn
    from k8s_scheduler_tpu.models import SnapshotEncoder
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    P = int(os.environ.get("BENCH_PODS", 10_000))
    N = int(os.environ.get("BENCH_NODES", 5_000))
    iters = int(os.environ.get("BENCH_ITERS", 5))

    nodes = make_cluster(N, with_labels=True)
    pods = make_pods(P)
    pad = lambda n, b: ((n + b - 1) // b) * b
    enc = SnapshotEncoder(pad_pods=pad(P, 128), pad_nodes=pad(N, 128))
    snap = enc.encode(nodes, pods)

    cycle = build_cycle_fn()
    t0 = time.perf_counter()
    result = cycle(snap)
    jax.block_until_ready(result.assignment)
    compile_s = time.perf_counter() - t0

    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        result = cycle(snap)
        jax.block_until_ready(result.assignment)
        times.append(time.perf_counter() - t0)
    cycle_s = min(times)
    decisions_per_sec = P * N / cycle_s

    assignment = np.asarray(result.assignment)[:P]
    print(
        json.dumps(
            {
                "metric": "pod_node_scoring_decisions_per_sec",
                "value": round(decisions_per_sec, 1),
                "unit": "decisions/s",
                "vs_baseline": round(decisions_per_sec / TARGET_DECISIONS_PER_SEC, 4),
                "detail": {
                    "pods": P,
                    "nodes": N,
                    "cycle_seconds": round(cycle_s, 6),
                    "compile_seconds": round(compile_s, 3),
                    "scheduled": int((assignment >= 0).sum()),
                    "unschedulable": int((assignment < 0).sum()),
                    "device": str(jax.devices()[0].platform),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
