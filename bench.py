#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line with the headline metric.

Headline (BASELINE.md north star): pod-node scoring decisions per second at
benchmark config #4 (10k pods x 5k nodes, full default plugin set, real
preemption activity). `detail.configs` carries the full five-config
scheduler_perf-style suite (bench_suite.py).

Per config, bench_suite reports BOTH:
- decisions_per_sec / pipelined_ms — THROUGHPUT, measured by encoding and
  dispatching every snapshot back-to-back with one force at the end (host
  encode overlaps device compute via JAX async dispatch — how a
  production driver runs); 20% of the pending set is fresh per snapshot
  (BENCH_CHURN), the rest carries over like a real queue.
- p50_ms / p99_ms — forced-sync per-cycle LATENCY (each cycle ends with a
  device->host read), which on this rig includes one fixed tunnel
  round-trip, reported separately as tunnel_rt_ms; device_ms is the
  dispatch-amortized device compute time. (Round-1's 66B decisions/s was
  an async-dispatch artifact; numbers here force real results.)

Env knobs: BENCH_FORCE_CPU=1, BENCH_SNAPSHOTS=<n> (per-config override),
BENCH_CONFIGS=1,2,3,4,5, BENCH_CHURN=<frac>, BENCH_COMMIT_MODE,
BENCH_ISOLATE=0 (disable the per-config subprocess isolation).
"""

import json
import os
import sys

TARGET_DECISIONS_PER_SEC = 50_000.0

# distinct snapshots per config; overridable via BENCH_SNAPSHOTS
# (config 6 = the compile-regime churn soak: cycles per drive phase;
# config 7 = the fault-storm soak: serving cycles under the fault plan;
# config 8 = the sharded scale sweep: timed cycles per grid point x
# device count; config 9 = the front-door load drive: ~seconds of
# open-loop arrival split across the sustained/overload phases;
# config 10 = the admission-time incremental-encode drive: ~2 seconds
# of open-loop arrival per leg x the three rebuild/incremental/2x legs)
DEFAULT_SNAPSHOTS = {1: 50, 2: 50, 3: 50, 4: 30, 5: 30, 6: 24, 7: 40,
                     8: 4, 9: 12, 10: 12}


def _run_one_isolated(c: int, n: int):
    """Run one config in a FRESH interpreter (default; BENCH_ISOLATE=0
    falls back to in-process). The axon rig can WEDGE a whole process:
    after certain executable-cache faults (observed: the second
    invocation of a second-regime preemption program raising
    'INVALID_ARGUMENT: TPU backend error'), every later device op in the
    process — including plain device_put — fails. In-process isolation
    (_run_one) then loses every later config too, which is exactly how
    round 5's first full run zeroed configs 4-5 after one fault.
    Subprocess isolation contains the wedge to one config attempt, and
    the retry gets a clean backend session."""
    import subprocess
    import tempfile

    fd, out_path = tempfile.mkstemp(prefix=f"bench_cfg{c}_", suffix=".json")
    os.close(fd)
    code = (
        "import json, bench_suite\n"
        f"r = bench_suite.run_config({c}, snapshots={n})\n"
        f"json.dump(r, open({out_path!r}, 'w'))\n"
    )
    timeout_s = float(os.environ.get("BENCH_CONFIG_TIMEOUT", "2400"))
    last_err = None
    try:
        for attempt in range(2):
            env = dict(os.environ)
            if attempt == 1 and last_err and not last_err.get("transport"):
                # wedge-class failures are deterministic in the fold
                # replay: the retry drops bind-folding (recorded
                # honestly — the result carries fold_binds:false and the
                # error stays in errors[]) so the config still produces
                # evidence
                env["BENCH_FOLD"] = "0"
            try:
                p = subprocess.run(
                    [sys.executable, "-c", code],
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                    capture_output=True, text=True, timeout=timeout_s,
                    env=env,
                )
            except subprocess.TimeoutExpired:
                # a hang-shaped wedge: the fresh-process retry (with
                # folding dropped) is still worth one shot
                last_err = {"config": c, "attempt": attempt,
                            "transport": False,
                            "error": f"timeout after {timeout_s}s"}
                print(f"bench: config {c} attempt {attempt} timed out",
                      file=sys.stderr, flush=True)
                continue
            if p.stderr:
                sys.stderr.write(p.stderr[-4000:])
                sys.stderr.flush()
            if p.returncode == 0 and os.path.getsize(out_path) > 0:
                with open(out_path) as f:
                    r = json.load(f)
                return r, last_err
            from k8s_scheduler_tpu.core.cycle import is_transport_error

            tail = (p.stderr or "").strip().splitlines()
            msg = tail[-1] if tail else f"rc={p.returncode}"
            transport = is_transport_error(RuntimeError(p.stderr or ""))
            last_err = {"config": c, "attempt": attempt,
                        "transport": transport, "error": msg[-300:]}
            print(f"bench: config {c} attempt {attempt} failed "
                  f"(subprocess): {msg[-300:]}", file=sys.stderr, flush=True)
            # a fresh process IS the recovery for wedge-class faults, so
            # one retry is worth it for any failure class here
        return None, last_err
    finally:
        try:
            os.unlink(out_path)
        except OSError:
            pass


def _run_one(run_config, c: int, n: int):
    """Run one config with per-config fault isolation: transport-class
    rig flakes (the tunnel's `remote_compile: response body closed`
    killed round 3's entire official bench run) get ONE retry; any
    failure is captured as an error record instead of propagating, so a
    single bad config can never zero the whole round's evidence.
    Returns (result_or_None, error_or_None)."""
    import traceback

    from k8s_scheduler_tpu.core.cycle import is_transport_error

    last_err = None
    for attempt in range(2):
        try:
            return run_config(c, snapshots=n), last_err
        except Exception as e:  # noqa: BLE001 — isolation is the point
            err = {
                "config": c,
                "attempt": attempt,
                "transport": is_transport_error(e),
                "error": f"{type(e).__name__}: {e}",
            }
            print(
                f"bench: config {c} attempt {attempt} failed: "
                f"{err['error']}\n{traceback.format_exc()}",
                file=sys.stderr,
                flush=True,
            )
            last_err = err  # keep the final failure
            if attempt == 0 and is_transport_error(e):
                continue  # one retry for rig flakes only
            return None, last_err
    return None, last_err


def main() -> None:
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import bench_suite

    configs = [
        int(c)
        for c in os.environ.get("BENCH_CONFIGS", "1,2,3,4,5").split(",")
    ]
    override = os.environ.get("BENCH_SNAPSHOTS")
    isolate = os.environ.get("BENCH_ISOLATE", "1") == "1"
    results = []
    errors = []
    for c in configs:
        n = int(override) if override else DEFAULT_SNAPSHOTS[c]
        if isolate:
            r, err = _run_one_isolated(c, n)
        else:
            r, err = _run_one(bench_suite.run_config, c, n)
        if r is not None:
            results.append(r)
        if err is not None:
            errors.append(err)

    from k8s_scheduler_tpu.core.cycle import RESILIENT_STRIKES

    # the same fingerprint scheduler_build_info exports at startup, so
    # a BENCH_*.json artifact names the exact jax/jaxlib/backend/tree
    # it measured — latency diffs across artifacts stop guessing what
    # changed underneath them
    from k8s_scheduler_tpu.metrics.metrics import build_fingerprint

    detail = {
        "device": str(jax.devices()[0].platform),
        "build": build_fingerprint(),
        "configs": results,
    }
    if errors:
        detail["errors"] = errors
    if RESILIENT_STRIKES:
        detail["resilient_strikes"] = {
            f"{prog}:{kind}": n
            for (prog, kind), n in sorted(RESILIENT_STRIKES.items())
        }
    if results:
        # config 6 (regime churn) carries no latency axes: never the
        # headline unless it is the only thing that ran
        head = next((r for r in results if r["config"] == 4), None)
        if head is None:
            # fall back to the LAST config carrying latency axes, as
            # before; config 6 rows qualify only when nothing else ran
            head = next(
                (
                    r for r in reversed(results)
                    if "decisions_per_sec" in r
                ),
                results[-1],
            )
        dps = head.get("decisions_per_sec", 0.0)
        detail.update(
            headline_config=head["config"],
            p50_ms=head.get("p50_ms", 0.0),
            p99_ms=head.get("p99_ms", 0.0),
        )
    else:
        dps = 0.0  # every config failed: still emit a parseable line

    # Full detail is NOT printed to stdout: the driver records only a
    # ~2000-char stdout tail, and rounds 2-4's ~2.4 kB single line came
    # back truncated and unparseable (`parsed: null` in BENCH_r0{2,4}).
    # Detail goes to a file + stderr; stdout's LAST line is a compact
    # headline summary that fits the tail whole.
    with open("BENCH_DETAIL.json", "w") as f:
        json.dump(detail, f, indent=1)
    print(json.dumps(detail), file=sys.stderr, flush=True)

    def _c(r):  # compact per-config row, short keys, rounded
        return {
            "c": r["config"],
            "dps": round(r.get("decisions_per_sec", 0.0)),
            "p50": round(r.get("p50_ms", 0.0), 1),
            "p99": round(r.get("p99_ms", 0.0), 1),
            "dev": round(r.get("device_ms", 0.0), 1),
            "enc": round(r.get("encode_p50_ms", 0.0), 1),
            # split-phase pipeline: encode-overlap % and decision-fetch
            # bytes (the slimmed payload the bind path blocks on)
            "ov": round(r.get("overlap_pct", 0.0)),
            "fb": r.get("fetch_bytes", 0),
            # stall transparency, promoted from bench detail to the
            # headline rows so the 28 s-outlier class diffs across
            # BENCH_rN artifacts (scripts/bench_diff.py): raw >10x-p50
            # cycle count, the tunnel round-trip p99, and the
            # production classifier's anomaly counts by class
            "stall": r.get("stall_cycles", 0),
            "trt99": round(r.get("tunnel_rt_p99_ms", 0.0), 1),
            "anom": r.get("anomalies", {}),
            "alerts": r.get("alerts_fired", 0),
            "sched": r.get("scheduled", 0),
            "unsched": r.get("unschedulable", 0),
            # multi-cycle K-sweep headline (BENCH_MULTI_K): amortization
            # factor vs the single dispatch and the best-K effective
            # per-cycle p50 — both diffed directionally by bench_diff
            **(
                {
                    "amort": r["tunnel_amortization"],
                    "effp50": r["effective_cycle_p50_ms"],
                }
                if "tunnel_amortization" in r else {}
            ),
            # device-saturated streaming (ISSUE 13): depth-2 first-bind
            # p50 and the speculation hit rate — diffed directionally
            # by bench_diff (fbp50 rise / shr drop = regression)
            **(
                {
                    "fbp50": r["first_bind_p50_ms"],
                    "shr": r["speculation_hit_rate"],
                }
                if "first_bind_p50_ms" in r else {}
            ),
            # compile-regime churn soak (config 6): cold compile spend,
            # warm-restart hit rate, and compile-attributed stall
            # cycles after first traversal — diffed by bench_diff
            **(
                {
                    "comp": r["compile_seconds"],
                    "cchr": r["compile_cache_hit_rate"],
                    "rflips": r["regime_flips"],
                }
                if "compile_cache_hit_rate" in r else {}
            ),
            # fault-storm soak (config 7): mean recovery time and
            # cycles spent below the top rung — diffed by bench_diff
            **(
                {
                    "mttr": r["mttr_ms"],
                    "degc": r["degraded_cycles"],
                }
                if "mttr_ms" in r else {}
            ),
            # front-door load drive (config 9): submit-ack p99 (incl.
            # the WAL-before-ack fsync barrier), end-to-end
            # submit->bind p50/p99, and the sustained-phase shed rate
            # (0 unless admission started refusing nominal load) —
            # sbp99/sack99 rise and shed rise diffed by bench_diff
            **(
                {
                    "sack99": r["submit_ack_p99_ms"],
                    "sbp50": r["submit_bind_p50_ms"],
                    "sbp99": r["submit_bind_p99_ms"],
                    "shed": r["shed_rate"],
                }
                if "submit_bind_p99_ms" in r else {}
            ),
            # pod-lifecycle tracing overhead (config 9 trace stage):
            # worst-case armed (rate 1.0) latency delta vs tracing off
            # — gated by bench_diff --max-trace-overhead
            **(
                {"trov": r["trace_overhead_pct"]}
                if "trace_overhead_pct" in r else {}
            ),
            # admission-time incremental encode (config 10): hidden
            # encode share, flush-side finalize p50, flush cadence,
            # rebuild/finalize mean ratio, and the base/2x submit->bind
            # p50 flatness — ehid drop and finp50 rise diffed
            # directionally by bench_diff
            **(
                {
                    "ehid": r["encode_hidden_pct"],
                    "finp50": r["finalize_p50_ms"],
                    "frate": r["flush_rate_per_s"],
                    "fsx": r["finalize_speedup"],
                    "sbp50": r["submit_bind_p50_ms"],
                    "flat": r["submit_bind_flat_pct"],
                }
                if "encode_hidden_pct" in r else {}
            ),
            # sharded scale sweep (config 8): scaling efficiency at the
            # largest grid point's max device count, the compiled
            # collective payload per cycle, and per-device ms — seff
            # and cpmb diffed directionally by bench_diff
            **(
                {
                    "seff": r["scaling_efficiency"],
                    "cpmb": r["collective_payload_mb"],
                    "pdms": r["per_device_ms"],
                }
                if "scaling_efficiency" in r else {}
            ),
        }

    line = {
        "metric": "pod_node_scoring_decisions_per_sec",
        "value": dps,
        "unit": "decisions/s",
        "vs_baseline": round(dps / TARGET_DECISIONS_PER_SEC, 4),
        "device": detail["device"],
        "configs": [_c(r) for r in results],
        "errors": [
            {
                "config": e["config"],
                "transport": e["transport"],
                "attempt": e["attempt"],
            }
            for e in errors
        ],
    }
    out = json.dumps(line)
    if len(out) > 1900:  # belt-and-braces: never exceed the tail window
        line.pop("configs")
        out = json.dumps(line)
    print(out, flush=True)


if __name__ == "__main__":
    sys.exit(main())
