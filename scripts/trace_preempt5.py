"""Fusion-level TPU trace of the config-#4 preemption program."""
import collections, glob, gzip, json, sys
sys.path.insert(0, ".")
import jax

from k8s_scheduler_tpu.utils.compilation_cache import enable_compilation_cache

enable_compilation_cache()
import numpy as np
from bench_suite import make_config_base, make_config_workload, _pad
from k8s_scheduler_tpu.core import (
    build_packed_cycle_carry_fn, build_packed_preemption_fn,
    build_stable_state_fn,
)
from k8s_scheduler_tpu.core.cycle import CarryKeeper
from k8s_scheduler_tpu.models import SnapshotEncoder


def main():
    enc = SnapshotEncoder(pad_pods=_pad(10000), pad_nodes=_pad(5000))
    bn, be = make_config_base(4)
    _n, pods, _e, groups = make_config_workload(4, seed=1000)
    w, b, spec, snap, dirty = enc.encode_packed(bn, pods, be, groups)
    w = jax.device_put(np.asarray(w))
    b = jax.device_put(np.asarray(b))
    cycle = build_packed_cycle_carry_fn(spec)
    stable = build_stable_state_fn(spec)(w, b)
    keeper = CarryKeeper(spec)
    carry = keeper.ci(w, b, stable)
    out = cycle(w, b, stable, carry)
    pre = build_packed_preemption_fn(spec)
    op = pre(w, b, out, stable)
    np.asarray(op.nominated)

    import shutil

    shutil.rmtree("/tmp/jaxtrace5", ignore_errors=True)
    with jax.profiler.trace("/tmp/jaxtrace5"):
        for _ in range(3):
            op = pre(w, b, out, stable)
        np.asarray(op.nominated)

    hlo = pre.lower(w, b, out, stable).compile().as_text()
    src_of = {}
    for line in hlo.splitlines():
        line = line.strip()
        if not line.startswith("%") or "metadata=" not in line:
            continue
        name = line.split(" ", 1)[0].lstrip("%")
        m = ""
        if 'op_name="' in line:
            m = line.split('op_name="', 1)[1].split('"', 1)[0]
        sf = ""
        if 'source_file="' in line:
            sf = line.split('source_file="', 1)[1].split('"', 1)[0]
            if 'source_line=' in line:
                sf += ":" + line.split("source_line=", 1)[1].split(
                    ",", 1)[0].rstrip("} ")
        src_of[name] = (m, sf)

    files = glob.glob("/tmp/jaxtrace5/**/*.trace.json.gz", recursive=True)
    agg = collections.Counter()
    for f in files:
        with gzip.open(f, "rt") as fh:
            data = json.load(fh)
        for ev in data.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            name = ev.get("name", "")
            dur = ev.get("dur", 0)
            args = ev.get("args", {})
            hname = args.get("hlo_op", name)
            agg[hname] += dur
    total = sum(agg.values())
    print(f"total traced us: {total} (3 reps)")
    for name, us in agg.most_common(30):
        mo, sf = src_of.get(name, ("", ""))
        print(f"{us/3:9.0f} us  {name[:46]:46s} {mo[:40]:40s} {sf[-40:]}")


if __name__ == "__main__":
    main()
