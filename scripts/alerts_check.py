#!/usr/bin/env python
"""CI gate: a short CLEAN soak must fire zero watchtower alerts.

Drives the real Scheduler (CPU backend, tiny shapes) for a few dozen
cycles with healthy synthetic churn, the in-process TSDB armed and the
built-in rule pack evaluated exactly as the CLI wires it — windows
scaled down (--time-scale) so `for`-durations hold within the soak.
Any firing means either the pack's thresholds drifted into the healthy
envelope (a false-page waiting to happen) or the scheduler's healthy
envelope drifted into the thresholds (a regression); both are CI
failures. Prints ONE JSON line and exits nonzero on any firing.

    JAX_PLATFORMS=cpu python scripts/alerts_check.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pods", type=int, default=32)
    ap.add_argument("--nodes", type=int, default=8)
    ap.add_argument("--cycles", type=int, default=40)
    ap.add_argument(
        "--time-scale", type=float, default=0.05,
        help="rule window/for-duration scale: production rules carry "
        "10-60 s horizons, the soak runs seconds — 0.05 turns a 20 s "
        "for-duration into 1 s so a sustained-bad condition WOULD fire "
        "within the soak (and a clean one still must not)",
    )
    args = ap.parse_args()

    from k8s_scheduler_tpu.core import Scheduler
    from k8s_scheduler_tpu.metrics import tsdb as _tsdb
    from k8s_scheduler_tpu.metrics.rules import (
        RuleEngine,
        builtin_rules,
        scale_rules,
    )
    from k8s_scheduler_tpu.models import MakeNode, MakePod

    bound: dict[str, str] = {}
    sched = Scheduler(
        binder=lambda pod, node: bound.setdefault(pod.name, node),
    )
    store = _tsdb.arm(_tsdb.MetricsTSDB(eval_interval_s=0.0))
    try:
        engine = RuleEngine(
            scale_rules(builtin_rules(), args.time_scale),
            store,
            observer=sched.observer,
            events=sched.events,
            metrics=sched.metrics,
        )
        store.engine = engine
        sched.flight.observers.append(store.observe_record)
        store.start_ticker(sched.metrics.registry, interval_s=0.2)

        for i in range(args.nodes):
            sched.on_node_add(
                MakeNode(f"n{i}").capacity({"cpu": "64"}).obj()
            )
        t0 = time.perf_counter()
        for c in range(args.cycles):
            # healthy churn: a fresh small batch each cycle, binding
            # immediately — the clean envelope the pack must tolerate
            for p in range(args.pods // 4):
                sched.on_pod_add(
                    MakePod(f"c{c}-p{p}").req({"cpu": "1"}).obj()
                )
            sched.schedule_cycle()
        soak_s = time.perf_counter() - t0
        # let the ticker land a few registry sweeps + evaluations
        time.sleep(1.0)
        store.stop_ticker()
        status = engine.status()
    finally:
        _tsdb.disarm()

    row = {
        "cycles": args.cycles,
        "soak_s": round(soak_s, 3),
        "bound": len(bound),
        "alerts_fired": status["fired_total"],
        "active": [a["rule"] for a in status["active"]],
        "resolved": [a["rule"] for a in status["resolved"]],
        "evaluations": status["evaluations"],
        "series": store.status()["series"],
        "time_scale": args.time_scale,
    }
    print(json.dumps(row, sort_keys=True))
    if status["fired_total"]:
        print(
            "alerts_check: FAILED — clean soak fired "
            f"{status['fired_total']} alert(s): "
            f"{sorted(set(row['active'] + row['resolved']))}",
            file=sys.stderr,
        )
        return 1
    print(
        f"alerts_check: ok ({args.cycles} cycles, "
        f"{status['evaluations']} evaluations, 0 firings)",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
