#!/usr/bin/env python
"""Scenario-fuzzer soak: random cluster traces through the live engine
vs the trace-semantics oracle, chaos fused in, failures auto-shrunk.

Default soak mixes plain differential cases (bit-equal bind streams +
standing invariants) with chaos cases (random FaultPlan over a random
trace; PR 8 soak invariants) across device counts {1, 4}:

    JAX_PLATFORMS=cpu python scripts/fuzz_scheduler.py 10        # minutes
    python scripts/fuzz_scheduler.py --smoke                     # a few seeds
    python scripts/fuzz_scheduler.py --seed 1234 --devices 4     # one case
    python scripts/fuzz_scheduler.py --replay tests/corpus/x.json
    python scripts/fuzz_scheduler.py --seed 1 --inject-bug tiebreak

Every failure is stamped `FUZZ-FAIL seed=<s> devices=<d> chaos=<0|1>
mc=<0|1> bug=<name> fault_spec=<spec> class=<cls>` — the run is
reproducible from that log line alone (`--seed/--devices/--chaos/
--multi-cycle/--speculative/--incremental/--inject-bug` re-derive the
identical trace) — then
shrunk to a minimal repro and written as a corpus artifact
(fuzz/corpus.py format) under --artifact-dir for triage or promotion
into tests/corpus/.

Exit status: 0 = no failures, 1 = failures, 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the 8-device virtual CPU mesh must exist before jax initializes —
# sharded cases (devices {4}) dispatch over it (tests/conftest.py does
# the same; harmless for devices=1)
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _stamp(trace, bug, failure, via_api=False) -> str:
    return (
        f"FUZZ-FAIL seed={trace.seed} "
        f"devices={max(int(trace.config.get('shard_devices', 0)), 1)} "
        f"chaos={int(trace.chaos)} "
        f"mc={int(int(trace.config.get('multi_cycle_k', 1)) > 1)} "
        f"spec={int(bool(trace.config.get('speculative_dispatch')))} "
        f"inc={int(bool(trace.config.get('incremental_encode')))} "
        f"api={int(via_api)} "
        f"bug={bug or '-'} fault_spec={trace.fault_spec or '-'} "
        f"class={failure.cls}"
    )


def _run_with_tmp_state(trace, bug, via_api=False):
    """run_case with a self-cleaning state dir for chaos traces (the
    digest-restore check needs a journal; a soak + shrink loop must
    not leave hundreds of journal dirs under /tmp). `via_api` routes
    arrivals through the real Submit/NodeChurn RPCs and compares
    against the direct-enqueue engine (run_api_case; plain traces
    only — the engine bug hooks and chaos state dirs stay with the
    oracle differential)."""
    from k8s_scheduler_tpu.fuzz import run_api_case, run_case

    if via_api:
        return run_api_case(trace)
    if not trace.chaos:
        return run_case(trace, bug=bug)
    with tempfile.TemporaryDirectory(prefix="fuzz-state-") as sd:
        return run_case(trace, state_dir=sd, bug=bug)


def run_one(seed, *, devices, chaos, multi_cycle, bug, artifact_dir,
            shrink, shrink_evals, speculative=False, incremental=False,
            via_api=False) -> "tuple[int, str | None]":
    """Returns (n_failures, artifact_path | None)."""
    from k8s_scheduler_tpu.fuzz import (
        generate_trace,
        save_artifact,
        shrink_trace,
    )

    trace = generate_trace(
        seed, devices=devices, chaos=chaos, multi_cycle=multi_cycle,
        speculative=speculative, incremental=incremental,
    )
    failures = _run_with_tmp_state(trace, bug, via_api=via_api)
    if not failures:
        return 0, None
    first = failures[0]
    print(_stamp(trace, bug, first, via_api=via_api), flush=True)
    for f in failures[:5]:
        print(f"  {f}", flush=True)
    path = None
    if shrink:
        def check(tr):
            fs = _run_with_tmp_state(tr, bug, via_api=via_api)
            return fs[0] if fs else None

        mint, minf = shrink_trace(
            trace, first, check, max_evals=shrink_evals
        )
        os.makedirs(artifact_dir, exist_ok=True)
        path = os.path.join(
            artifact_dir,
            f"repro_seed{seed}_{minf.cls.replace('/', '_')}.json",
        )
        save_artifact(
            path, mint, minf, bug=bug,
            note=_stamp(trace, bug, first, via_api=via_api),
        )
        print(
            f"  shrunk to {sum(len(c) for c in mint.cycles)} events / "
            f"{len(mint.cycles)} cycles / {len(mint.nodes)} nodes "
            f"-> {path}", flush=True,
        )
    return len(failures), path


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("minutes", nargs="?", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=None,
                    help="run exactly one seed instead of a soak")
    ap.add_argument("--devices", type=int, default=0,
                    help="shardDevices for --seed runs (soak mixes 1/4)")
    ap.add_argument("--chaos", action="store_true")
    ap.add_argument("--multi-cycle", action="store_true")
    ap.add_argument("--speculative", action="store_true",
                    help="depth-2 speculative dispatch pipelining over "
                    "the coalesced batches (forces --multi-cycle)")
    ap.add_argument("--incremental", action="store_true",
                    help="admission-time incremental encode variant "
                    "(forces --multi-cycle): the same trace runs with "
                    "incrementalEncode on AND off and must produce "
                    "byte-identical packed arenas and bit-equal "
                    "decision streams")
    ap.add_argument("--via-api", action="store_true",
                    help="arrivals_via_api variant: route every pod "
                    "arrival through a real gRPC Submit round trip and "
                    "node churn through NodeChurn, and require "
                    "bit-equal streams vs the direct-enqueue engine")
    ap.add_argument("--inject-bug", default=None, choices=("tiebreak",),
                    help="deliberately mutate the engine (self-test: "
                    "the differential must catch it)")
    ap.add_argument("--replay", default="",
                    help="replay a corpus artifact instead of fuzzing "
                    "(exit 1 if it fails clean-side)")
    ap.add_argument("--replay-with-bug", action="store_true",
                    help="with --replay: re-inject the recorded bug "
                    "and expect the recorded failure class")
    ap.add_argument("--smoke", action="store_true",
                    help="a handful of seeds across the axes, no clock")
    ap.add_argument("--no-shrink", action="store_true")
    ap.add_argument("--shrink-evals", type=int, default=150)
    ap.add_argument("--artifact-dir", default="fuzz-artifacts")
    args = ap.parse_args()
    if args.via_api and (args.chaos or args.inject_bug):
        ap.error(
            "--via-api is an engine-vs-engine variant for plain "
            "traces; chaos and bug injection belong to the oracle "
            "differential"
        )

    from k8s_scheduler_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()

    if args.replay:
        from k8s_scheduler_tpu.fuzz import load_artifact, replay_artifact

        art = load_artifact(args.replay)
        failures = replay_artifact(
            args.replay, with_bug=args.replay_with_bug
        )
        if args.replay_with_bug:
            ok = any(f.cls == art["failure"].cls for f in failures)
            print(json.dumps({
                "replay": args.replay, "with_bug": art["bug"],
                "expected_class": art["failure"].cls,
                "reproduced": ok,
            }), flush=True)
            return 0 if ok else 1
        for f in failures:
            print(f"  {f}", flush=True)
        print(json.dumps({
            "replay": args.replay, "clean": not failures,
        }), flush=True)
        return 1 if failures else 0

    kw = dict(
        artifact_dir=args.artifact_dir,
        shrink=not args.no_shrink,
        shrink_evals=args.shrink_evals,
        bug=args.inject_bug,
    )
    if args.seed is not None:
        n, _p = run_one(
            args.seed, devices=args.devices, chaos=args.chaos,
            multi_cycle=args.multi_cycle or None,
            speculative=args.speculative,
            incremental=args.incremental, via_api=args.via_api, **kw,
        )
        print(json.dumps({"seed": args.seed, "failures": n}), flush=True)
        return 1 if n else 0

    # the soak: plain, chaos, speculative-depth-2, incremental-encode,
    # and arrivals-via-API cases interleaved, devices {1, 4} —
    # (seed, devices, chaos, speculative, incremental, via_api)
    seeds = (
        [(s, 1, False, False, False, False) for s in range(100, 103)]
        + [(110, 4, False, False, False, False),
           (111, 1, True, False, False, False),
           (112, 1, False, True, False, False),
           (114, 1, False, False, True, False),
           (113, 1, False, False, False, True)]
    ) if args.smoke else None
    deadline = None if args.smoke else time.time() + args.minutes * 60
    total = failures_n = cases = 0
    artifacts = []
    seed = 10_000
    while True:
        if seeds is not None:
            if cases >= len(seeds):
                break
            (s, devices, chaos, speculative, incremental,
             via_api) = seeds[cases]
        else:
            if time.time() >= deadline or failures_n >= 5:
                break
            s = seed
            seed += 1
            devices = 4 if s % 4 == 3 else 1
            chaos = s % 5 == 2
            # every seventh case pipelines depth-2 over the coalesced
            # batches (forces mc; disjoint from nothing — it composes
            # with chaos and sharding alike)
            speculative = s % 7 == 1
            # every thirteenth non-chaos case runs the same trace with
            # incrementalEncode on AND off (chaos traces return before
            # the on/off comparison, so they would not exercise it)
            incremental = s % 13 == 2 and not chaos
            # every eleventh plain case routes arrivals through the
            # real Submit/NodeChurn RPCs (engine-vs-engine; chaos and
            # bug injection stay with the oracle differential)
            via_api = s % 11 == 4 and not chaos and not speculative
        n, path = run_one(
            s, devices=devices, chaos=chaos, multi_cycle=None,
            speculative=speculative, incremental=incremental,
            via_api=via_api, **kw
        )
        cases += 1
        total += n
        failures_n += bool(n)
        if path:
            artifacts.append(path)
        if cases % 10 == 0:
            print(
                f"  {cases} cases, {failures_n} failing", flush=True
            )
    print(json.dumps({
        "fuzz": "ok" if not failures_n else "FAIL",
        "cases": cases,
        "failing_cases": failures_n,
        "artifacts": artifacts,
    }), flush=True)
    return 1 if failures_n else 0


if __name__ == "__main__":
    sys.exit(main())
