"""Per-plugin and per-primitive timing at config-#4 scale.

Each candidate hot spot gets its own tiny jit returning a scalar (so
device->host transfer is negligible); a no-op jit measures the fixed
dispatch overhead to subtract mentally. Best of 5.

Run:  python scripts/profile_plugins4.py [cfg]
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from bench_suite import make_config_base, make_config_workload, CONFIG_SHAPES, _pad
from k8s_scheduler_tpu.framework.interfaces import CycleContext
from k8s_scheduler_tpu.framework.runtime import Framework
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.ops import interpod as ip


def timed(label, fn, *args, n=5):
    fn(*args)  # compile
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        r = fn(*args)
        np.asarray(r)
        best = min(best, time.perf_counter() - t0)
    print(f"{label:44s} {best*1e3:9.1f} ms", flush=True)
    return best


def main():
    cfg = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    P_real, N_real = CONFIG_SHAPES[cfg]
    enc = SnapshotEncoder(pad_pods=_pad(P_real), pad_nodes=_pad(N_real))
    base_nodes, base_existing = make_config_base(cfg)
    _n, pods, _e, groups = make_config_workload(cfg, seed=1000)
    snap = enc.encode(base_nodes, pods, base_existing, groups)
    print(f"P={snap.P} N={snap.N} E={snap.E} S={snap.sel_exprs.shape[0]} "
          f"D={snap.domain_key.shape[0]} K={snap.node_domains.shape[1]} "
          f"MA={snap.pod_anti_terms.shape[1]} MC={snap.pod_tsc.shape[1]} "
          f"Ex={snap.expr_key.shape[0] if hasattr(snap, 'expr_key') else '?'}",
          flush=True)

    fw = Framework.from_config()

    timed("noop dispatch", jax.jit(lambda s: s.pod_valid.sum()), snap)

    # per-plugin static masks
    for f in fw.filters:
        g = jax.jit(lambda s, f=f: (lambda m: m.sum() if m is not None else jnp.int32(0))(f.static_mask(CycleContext(s))))
        timed(f"static_mask {f.name}", g, snap)
    for s_, w in fw.scores:
        g = jax.jit(lambda s, s_=s_: (lambda v: v.sum() if v is not None else jnp.float32(0))(s_.static_score(CycleContext(s))))
        timed(f"static_score {s_.name}", g, snap)

    # matched tables
    timed("matched_pending [S,P]", jax.jit(lambda s: ip.matched_pending(s).sum()), snap)
    timed("matched_existing [S,E]", jax.jit(lambda s: ip.matched_existing(s).sum()), snap)

    def init_state(s):
        st = ip.initial_state(s, ip.matched_existing(s))
        return st.counts.sum() + st.total.sum() + st.anti_presence.sum() + st.pref_sym.sum()
    timed("initial_state (all tables)", jax.jit(init_state), snap)

    def cbn_f(s):
        st = ip.initial_state(s, ip.matched_existing(s))
        return ip.counts_by_node(s, st).sum()
    timed("initial_state + counts_by_node", jax.jit(cbn_f), snap)

    # dyn pieces on full [P, N]
    def mk_state(s):
        return ip.initial_state(s, ip.matched_existing(s))

    def aff_mask(s):
        st = mk_state(s)
        mp = ip.matched_pending(s)
        cbn = ip.counts_by_node(s, st)
        return ip.affinity_mask_batched(s, st, mp, cbn).sum()
    timed("affinity_mask_batched (incl deps)", jax.jit(aff_mask), snap)

    def aff_score(s):
        st = mk_state(s)
        mp = ip.matched_pending(s)
        cbn = ip.counts_by_node(s, st)
        feas = jnp.ones((s.P, s.N), bool)
        return ip.affinity_score_batched(s, st, mp, cbn, feas).sum()
    timed("affinity_score_batched (incl deps)", jax.jit(aff_score), snap)

    def spread_m(s):
        st = mk_state(s)
        cbn = ip.counts_by_node(s, st)
        minc = ip.spread_minc(s, st)
        return ip.spread_mask_batched(s, st, cbn, minc).sum()
    timed("spread_mask_batched (incl deps)", jax.jit(spread_m), snap)

    # primitive costs
    S = snap.sel_exprs.shape[0]
    K = snap.node_domains.shape[1]
    P, N = snap.P, snap.N

    def gather_PN(s):
        st = mk_state(s)
        cbn = ip.counts_by_node(s, st)
        sel = s.pod_anti_terms[:, 0, 0]
        k = s.pod_anti_terms[:, 0, 1]
        return ip._term_counts(s, cbn, sel, k).sum()
    timed("one [P,N] row-gather from cbn", jax.jit(gather_PN), snap)

    def matmul_PSN(s):
        mp = ip.matched_pending(s)
        st = mk_state(s)
        return (mp.T.astype(jnp.float32) @ st.anti_presence.astype(jnp.float32)).sum()
    timed("one [P,S]@[S,N] f32 matmul (incl deps)", jax.jit(matmul_PSN), snap)

    def elemwise(s):
        a = jnp.broadcast_to(s.node_valid[None, :], (s.P, s.N))
        b = a & (s.pod_valid[:, None])
        return (b & a).sum()
    timed("two [P,N] bool elementwise", jax.jit(elemwise), snap)

    def fit_mask(s):
        free = s.node_allocatable - s.node_requested  # [N, R]
        ok = jnp.all(s.pod_requested[:, None, :] <= free[None, :, :], axis=-1)
        return ok.sum()
    timed("resources fit [P,N,R] reduce", jax.jit(fit_mask), snap)

    def argsort_P(s):
        return jnp.argsort(jnp.where(s.pod_valid, s.pod_order, 2**31 - 1)).sum()
    timed("argsort over [P]", jax.jit(argsort_P), snap)

    def sort_guard(s):
        L = 20 * s.P
        keys = (s.pod_order[jnp.arange(L) % s.P]).astype(jnp.int32)
        a, b = jax.lax.sort((keys, keys), num_keys=1)
        return a.sum() + b.sum()
    timed("lax.sort over [20P] pairs", jax.jit(sort_guard), snap)


if __name__ == "__main__":
    main()
