"""Device cost of the extender-verdict carry at config #4: the carry
cycle with device-resident verdict arrays (unchanged verdicts) vs the
plain carry cycle. Run: python scripts/probe_extender_carry5.py"""
import sys
sys.path.insert(0, ".")
sys.path.insert(0, "scripts")
import jax
from k8s_scheduler_tpu.utils.compilation_cache import enable_compilation_cache
enable_compilation_cache()
import numpy as np
from bench_suite import make_config_base, make_config_workload, _pad
from devtime import devtime
from k8s_scheduler_tpu.core import build_packed_cycle_carry_fn, build_stable_state_fn
from k8s_scheduler_tpu.core.cycle import CarryKeeper
from k8s_scheduler_tpu.models import SnapshotEncoder

enc = SnapshotEncoder(pad_pods=_pad(10000), pad_nodes=_pad(5000))
bn, be = make_config_base(4)
_n, pods, _e, groups = make_config_workload(4, seed=1000)
w, b, spec, snap, dirty = enc.encode_packed(bn, pods, be, groups)
w = jax.device_put(np.asarray(w)); b = jax.device_put(np.asarray(b))
stable = build_stable_state_fn(spec)(w, b)
keeper = CarryKeeper(spec)
carry = keeper.ci(w, b, stable)
P = carry["sbase"].shape[0]; N = carry["sbase"].shape[1]
cyc = build_packed_cycle_carry_fn(spec)
cyc_e = build_packed_cycle_carry_fn(spec, extender_args=True)
em = jax.device_put(np.ones((P, N), bool))
es = jax.device_put(np.zeros((P, N), np.float32))
a0 = np.asarray(cyc(w, b, stable, carry).assignment)
a1 = np.asarray(cyc_e(w, b, stable, carry, em, es).assignment)
print("all-pass extender == plain:", bool((a0 == a1).all()))
print(f"plain carry cycle   : {devtime(lambda: cyc(w, b, stable, carry), reps=8)*1e3:7.1f} ms")
print(f"extender-carry cycle: {devtime(lambda: cyc_e(w, b, stable, carry, em, es), reps=8)*1e3:7.1f} ms")
