"""Device-time of candidate primitive implementations at config-#4 scale.

Run:  python scripts/profile_prims4.py
"""

import sys

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from bench_suite import make_config_base, make_config_workload, _pad
from devtime import report
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.ops import interpod as ip


def main():
    enc = SnapshotEncoder(pad_pods=_pad(10000), pad_nodes=_pad(5000))
    base_nodes, base_existing = make_config_base(4)
    _n, pods, _e, groups = make_config_workload(4, seed=1000)
    snap = enc.encode(base_nodes, pods, base_existing, groups)
    P, N = snap.P, snap.N
    S = snap.sel_exprs.shape[0]
    K = snap.node_domains.shape[1]
    KS = K * S
    D = snap.domain_key.shape[0]
    print(f"P={P} N={N} S={S} K={K} D={D} E={snap.E}", flush=True)

    key = jax.random.PRNGKey(0)
    cbn = jax.random.uniform(key, (KS, N)) * 100  # stand-in counts table
    rows = jax.random.randint(key, (P,), 0, KS)
    cnts_sd = jax.random.uniform(key, (S, D)) * 100
    m_pend = jax.random.uniform(key, (S, P)) < 0.01  # [S, P] sparse matches
    anti_sn = jax.random.uniform(key, (S, N)) < 0.01

    report("row-gather cbn[rows] -> [P,N]",
           jax.jit(lambda c, r: c[r].sum()), cbn, rows)

    def onehot_mm(c, r):
        oh = (r[:, None] == jnp.arange(KS)[None, :]).astype(jnp.bfloat16)
        return (oh @ c.astype(jnp.bfloat16)).astype(jnp.float32).sum()
    report("one-hot [P,KS]@[KS,N] bf16", jax.jit(onehot_mm), cbn, rows)

    def onehot_mm_f32(c, r):
        oh = (r[:, None] == jnp.arange(KS)[None, :]).astype(jnp.float32)
        return (oh @ c).sum()
    report("one-hot [P,KS]@[KS,N] f32", jax.jit(onehot_mm_f32), cbn, rows)

    sel = jax.random.randint(key, (P,), 0, S)
    def onehot_S_mm(c, r):
        oh = (r[:, None] == jnp.arange(S)[None, :]).astype(jnp.bfloat16)
        return (oh @ c.astype(jnp.bfloat16)).astype(jnp.float32).sum()
    report("one-hot [P,S]@[S,D] bf16 (domain space)",
           jax.jit(onehot_S_mm), cnts_sd, sel)

    nd0 = snap.node_domains[:, 0]
    def col_gather(pd, nd):
        return pd[:, jnp.clip(nd, 0, pd.shape[1] - 1)].sum()
    pd = jax.random.uniform(key, (P, D))
    report("column-gather [P,D]->[P,N]", jax.jit(col_gather), pd, nd0)

    report("matmul [P,S]@[S,N] f32 (symmetric viol)",
           jax.jit(lambda m, a: ((m.T.astype(jnp.float32)
                                  @ a.astype(jnp.float32)) > 0).sum()),
           m_pend, anti_sn)
    report("matmul [P,S]@[S,N] bf16",
           jax.jit(lambda m, a: ((m.T.astype(jnp.bfloat16)
                                  @ a.astype(jnp.bfloat16)) > 0).sum()),
           m_pend, anti_sn)

    # matched tables candidates: current expr kernel vs matmul reformulation
    report("matched_pending current [S,P]",
           jax.jit(lambda s: ip.matched_pending(s).sum()), snap)
    report("matched_existing current [S,E]",
           jax.jit(lambda s: ip.matched_existing(s).sum()), snap)

    def init_state_cur(s):
        st = ip.initial_state(s, ip.matched_existing(s))
        return (st.counts.sum() + st.total.sum() + st.anti_presence.sum()
                + st.pref_sym.sum())
    report("initial_state current", jax.jit(init_state_cur), snap)

    # counts via matmul: m_exist [S,E] @ onehot(dom) [E,D]
    def counts_mm(s):
        me = ip.matched_existing(s).astype(jnp.bfloat16)
        dom = ip._exist_domains(s)  # [E, K]
        c = jnp.zeros((S, D), jnp.float32)
        for k in range(K):
            oh = (dom[:, k][:, None] == jnp.arange(D)[None, :])
            c = c + (me @ oh.astype(jnp.bfloat16)).astype(jnp.float32)
        return c.sum()
    report("counts via [S,E]@[E,D] bf16 matmul", jax.jit(counts_mm), snap)

    # guards-scale sort
    L = 26 * 1280
    kk = jax.random.randint(key, (L,), 0, 1 << 20)
    def sort5(a):
        outs = jax.lax.sort((a, a, a, a, a), num_keys=2)
        return outs[0].sum()
    report("lax.sort 5-tuple L=33k", jax.jit(sort5), kk)
    L2 = 26 * 10112
    kk2 = jax.random.randint(key, (L2,), 0, 1 << 20)
    report("lax.sort 5-tuple L=263k", jax.jit(sort5), kk2)

    report("argsort [P] i32",
           jax.jit(lambda r: jnp.argsort(r).sum()), rows)
    be = jax.random.uniform(key, (1280, N))
    report("argmax [1280,N]", jax.jit(lambda x: jnp.argmax(x, 1).sum()), be)
    bp = jax.random.uniform(key, (P, N))
    report("argmax [P,N]", jax.jit(lambda x: jnp.argmax(x, 1).sum()), bp)
    report("scatter dead [1280,N]",
           jax.jit(lambda x, r: x.at[jnp.arange(1280), r[:1280]].max(True).sum()),
           be < 0.5, rows)


if __name__ == "__main__":
    main()
