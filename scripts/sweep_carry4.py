"""Sweep rounds-engine geometry (compact, passes, passes_round0) on the
carry-based config-#4 cycle. Usage: python scripts/sweep_carry4.py"""
import sys, time
sys.path.insert(0, ".")
import jax

from k8s_scheduler_tpu.utils.compilation_cache import enable_compilation_cache

enable_compilation_cache()
import numpy as np
from bench_suite import make_config_base, make_config_workload, _pad
from k8s_scheduler_tpu.core import build_packed_cycle_carry_fn, build_stable_state_fn
from k8s_scheduler_tpu.core.cycle import CarryKeeper
from k8s_scheduler_tpu.models import SnapshotEncoder

enc = SnapshotEncoder(pad_pods=_pad(10000), pad_nodes=_pad(5000))
bn, be = make_config_base(4)
_n, pods, _e, groups = make_config_workload(4, seed=1000)
w, b, spec, snap, dirty = enc.encode_packed(bn, pods, be, groups)
w = jax.device_put(np.asarray(w)); b = jax.device_put(np.asarray(b))
stable = build_stable_state_fn(spec)(w, b)
keeper = CarryKeeper(spec)
carry = keeper.ci(w, b, stable)

cases = [
    dict(compact=8, passes=6, passes_round0=10),  # current default
    dict(compact=8, passes=4, passes_round0=8),
    dict(compact=4, passes=4, passes_round0=8),
    dict(compact=4, passes=6, passes_round0=10),
    dict(compact=6, passes=4, passes_round0=6),
    dict(compact=3, passes=4, passes_round0=8),
]
for kw in cases:
    t0 = time.perf_counter()
    cyc = build_packed_cycle_carry_fn(spec, rounds_kw=kw)
    out = cyc(w, b, stable, carry)
    np.asarray(out.assignment)
    comp = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(8):
        out = cyc(w, b, stable, carry)
    np.asarray(out.assignment)
    dt = (time.perf_counter() - t0) / 8 * 1e3
    used = int(np.asarray(out.rounds_used))
    acc = np.asarray(out.accepted_per_round)[:used].tolist()
    print(f"{kw} -> {dt:.1f} ms/rep rounds={used} unsched="
          f"{int(np.asarray(out.unschedulable).sum())} acc={acc} "
          f"(compile {comp:.0f}s)", flush=True)
