"""Compare rounds-engine shortlist settings on the carry config-#4
cycle (real TPU). Usage: python scripts/sweep_shortlist4.py [k1 k2 ...]
Each case prints amortized device ms, rounds used, and acceptance
history — convergence changes show up as extra rounds."""
import sys
import time

sys.path.insert(0, ".")
import jax

from k8s_scheduler_tpu.utils.compilation_cache import (
    enable_compilation_cache,
)

enable_compilation_cache()
import numpy as np

from bench_suite import make_config_base, make_config_workload, _pad
from k8s_scheduler_tpu.core import (
    build_packed_cycle_carry_fn,
    build_stable_state_fn,
)
from k8s_scheduler_tpu.core.cycle import CarryKeeper
from k8s_scheduler_tpu.models import SnapshotEncoder

enc = SnapshotEncoder(pad_pods=_pad(10000), pad_nodes=_pad(5000))
bn, be = make_config_base(4)
_n, pods, _e, groups = make_config_workload(4, seed=1000)
w, b, spec, snap, dirty = enc.encode_packed(bn, pods, be, groups)
w = jax.device_put(np.asarray(w))
b = jax.device_put(np.asarray(b))
stable = build_stable_state_fn(spec)(w, b)
keeper = CarryKeeper(spec)
carry = keeper.ci(w, b, stable)

cases = [
    dict(shortlist=0),                      # wide engine (the DEFAULT:
    # measured faster at config-#4 geometry, see PERF.md round 4)
    dict(shortlist=32),
    dict(shortlist=16),
    dict(shortlist=64),
    dict(shortlist=32, passes=8, passes_round0=14),
    dict(shortlist=32, compact=4),
]
if len(sys.argv) > 1:
    cases = [dict(shortlist=int(a)) for a in sys.argv[1:]]

REPS = 24
for kw in cases:
    t0 = time.perf_counter()
    cyc = build_packed_cycle_carry_fn(spec, rounds_kw=kw)
    out = cyc(w, b, stable, carry)
    np.asarray(out.assignment)
    comp = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = cyc(w, b, stable, carry)
    np.asarray(out.assignment)
    single = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = cyc(w, b, stable, carry)
    np.asarray(out.assignment)
    total = time.perf_counter() - t0
    dt = (total - single) / (REPS - 1) * 1e3
    used = int(np.asarray(out.rounds_used))
    acc = np.asarray(out.accepted_per_round)[:used].tolist()
    print(
        f"{kw} -> {dt:.1f} ms/rep rounds={used} "
        f"unsched={int(np.asarray(out.unschedulable).sum())} acc={acc} "
        f"(compile {comp:.0f}s)",
        flush=True,
    )
