"""Device-time profile of the config-#4 cycle pieces (dispatch-amortized).

Run:  python scripts/profile_device4.py [cfg] [passes]
"""

import sys

sys.path.insert(0, ".")

import jax

from k8s_scheduler_tpu.utils.compilation_cache import enable_compilation_cache

enable_compilation_cache()

from bench_suite import make_config_base, make_config_workload, CONFIG_SHAPES, _pad
from devtime import report
from k8s_scheduler_tpu.core import build_cycle_fn, build_preemption_fn
from k8s_scheduler_tpu.framework.interfaces import CycleContext
from k8s_scheduler_tpu.framework.runtime import Framework
from k8s_scheduler_tpu.models import SnapshotEncoder


def main():
    cfg = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    P_real, N_real = CONFIG_SHAPES[cfg]
    enc = SnapshotEncoder(pad_pods=_pad(P_real), pad_nodes=_pad(N_real))
    base_nodes, base_existing = make_config_base(cfg)
    _n, pods, _e, groups = make_config_workload(cfg, seed=1000)
    snap = enc.encode(base_nodes, pods, base_existing, groups)
    fw = Framework.from_config()

    report("noop", jax.jit(lambda s: s.pod_valid.sum()), snap)

    @jax.jit
    def static_only(s):
        ctx = CycleContext(s)
        m, sc, r = fw.static(ctx)
        return m.sum(), sc.sum(), r.sum()

    report("static masks+scores+attribution", static_only, snap)

    @jax.jit
    def extra_init_only(s):
        ctx = CycleContext(s)
        if s.has_inter_pod_affinity or s.has_topology_spread:
            ctx.matched_pending
        extra = fw.extra_init(ctx)
        return jax.tree_util.tree_map(lambda x: x.sum(), extra)

    report("matched tables + extra_init", extra_init_only, snap)

    @jax.jit
    def dyn_only(s):
        ctx = CycleContext(s)
        smask, _, _ = fw.static(ctx)
        if s.has_inter_pod_affinity or s.has_topology_spread:
            ctx.matched_pending
        extra = fw.extra_init(ctx)
        m, sc, pf = fw.dyn_batched(ctx, s.node_requested, extra, smask)
        return m.sum(), sc.sum()

    report("static + init + 1 full dyn pass", dyn_only, snap)

    cycle = build_cycle_fn(commit_mode="rounds")
    out = report("cycle (rounds, current)", cycle, snap)
    pre = build_preemption_fn()
    if pre is not None and cfg == 4:
        o = cycle(snap)
        report("preemption pass", pre, snap, o)


if __name__ == "__main__":
    main()
