#!/usr/bin/env python
"""bench_diff: compare two BENCH_*.json headline artifacts for
regressions — the CI tripwire the perf rounds read instead of eyeballing
JSON blobs.

    python scripts/bench_diff.py BENCH_r04.json BENCH_r05.json
    python scripts/bench_diff.py --json old.json new.json
    python scripts/bench_diff.py --max-p50-rise 10 old.json new.json

Accepts every artifact shape the repo produces:

- driver-wrapped rounds artifacts (`BENCH_rN.json`: {"tail", "parsed"})
  — uses `parsed.configs` (the compact headline rows) when the driver
  managed to parse the line, and otherwise SCANS the recorded stdout/
  stderr tail for embedded `{"config": N, ...}` records (r02/r04 came
  back with `parsed: null` because the tail window truncated the line;
  the per-config records inside the tail are still recoverable);
- the full detail file (`BENCH_DETAIL.json`: {"configs": [...]});
- a bare bench_suite JSON-lines dump (one record per line).

Compared per config present in BOTH artifacts, each with its own
threshold flag (percent):

    dps            decisions/s        regression = drop  > --max-dps-drop
    p50_ms         cycle latency p50  regression = rise  > --max-p50-rise
    p99_ms         cycle latency p99  regression = rise  > --max-p99-rise
                   (looser by default: ROUND5.md p99 embeds tunnel
                   stalls that come and go between runs)
    device_ms      device compute     regression = rise  > --max-device-rise
    encode_p50_ms  host encode p50    regression = rise  > --max-encode-rise
    tunnel_amortization  multi-cycle amortization factor
                   regression = drop  > --max-amortization-drop
    effective_p50_ms     multi-cycle best-K effective per-cycle p50
                   regression = rise  > --max-effective-p50-rise
    compile_seconds      cold compile spend
                   regression = rise  > --max-compile-rise
    compile_cache_hit_rate  warm-start executable-cache hit rate
                   regression = drop  > --max-hit-rate-drop
    mttr_ms        fault-storm mean recovery time
                   regression = rise  > --max-mttr-rise
    submit_ack_p99_ms    front-door submit-ack p99 (incl. WAL barrier)
                   regression = rise  > --max-submit-ack-rise
    submit_bind_p99_ms   front-door end-to-end submit->bind p99
                   regression = rise  > --max-submit-bind-rise
    shed_rate      sustained-phase admission shed rate
                   regression = rise  > --max-shed-rise (default 0)
    trace_overhead_pct   config-9 pod-lifecycle tracing overhead
                   (armed at sample rate 1.0 vs off, worst of the
                   submit-ack p99 / submit-bind p50 deltas); gated as
                   an ABSOLUTE ceiling on the new artifact via
                   --max-trace-overhead, not as a relative diff — the
                   asserted-near-zero baseline makes percentages of a
                   percentage pure noise
    scaling_efficiency   config-8 sharded scaling efficiency
                   regression = drop  > --max-scaling-efficiency-drop
    collective_payload_mb  config-8 compiled collective payload/cycle
                   regression = rise  > --max-payload-rise
    stall_cycles   >10x-p50 cycles    regression = new > old + --allow-stalls
    anomalies      classifier total   regression = new > old + --allow-stalls
    degraded_cycles  cycles below the top ladder rung
                   regression = new > old + --allow-stalls

Millisecond metrics additionally ignore absolute deltas below
--min-ms-delta (CPU smoke configs sit at sub-ms device times where a
percentage gate is pure noise). Exit status: 0 = clean, 1 = regression,
2 = usage/parse error. `--json` emits the full comparison object.
"""

from __future__ import annotations

import argparse
import json
import sys

# metric -> (kind, long key, compact key)
_METRICS = {
    "dps": ("higher", "decisions_per_sec", "dps"),
    "p50_ms": ("lower", "p50_ms", "p50"),
    "p99_ms": ("lower", "p99_ms", "p99"),
    "device_ms": ("lower", "device_ms", "dev"),
    "encode_p50_ms": ("lower", "encode_p50_ms", "enc"),
    # multi-cycle serving (BENCH_MULTI_K sweep): the amortization factor
    # must not DROP and the best-K effective per-cycle p50 must not
    # RISE — both skipped (like any metric) for configs/artifacts that
    # predate the sweep or sit outside the exactness envelope
    "tunnel_amortization": ("higher", "tunnel_amortization", "amort"),
    "effective_p50_ms": ("lower", "effective_cycle_p50_ms", "effp50"),
    # device-saturated streaming (ISSUE 13): first-bind latency under
    # depth-2 speculative dispatch must not RISE (a pod admitted into
    # row 0 waits ~1 inner cycle, not the whole batch) and the
    # speculation hit rate must not DROP (every abandoned speculation
    # re-dispatches — a falling rate means the predicate is thrashing).
    # Both skipped for artifacts predating the sweep (r05 and older).
    "first_bind_p50_ms": ("lower", "first_bind_p50_ms", "fbp50"),
    "speculation_hit_rate": ("higher", "speculation_hit_rate", "shr"),
    # compile-regime management (ISSUE 8): cold compile spend must not
    # RISE (a new program or a lost cache hit re-pays 8.8-16.8 s per
    # program) and the warm-start cache hit rate must not DROP (every
    # lost hit is a cold compile at restart/failover time). stall_cycles
    # (higher = regressed) already gates via _COUNT_METRICS below.
    "compile_seconds": ("lower", "compile_seconds", "comp"),
    "compile_cache_hit_rate": ("higher", "compile_cache_hit_rate",
                               "cchr"),
    # fault-storm soak (ISSUE 9): mean recovery time after a fault
    # must not RISE (a slower ladder is a regression even when every
    # invariant still holds); degraded_cycles (higher = regressed)
    # gates via _COUNT_METRICS below.
    "mttr_ms": ("lower", "mttr_ms", "mttr"),
    # submission front door (ISSUE 14, config 9 front_door): the
    # submit-ack p99 (which embeds the WAL-before-ack group-fsync
    # barrier) and the end-to-end submit->bind p99 must not RISE, and
    # the SUSTAINED-phase shed rate must not rise above its asserted-
    # zero baseline (any shed at nominal load means admission started
    # refusing traffic the door used to carry). All skipped for
    # artifacts predating config 9 (r05 and older).
    "submit_ack_p99_ms": ("lower", "submit_ack_p99_ms", "sack99"),
    "submit_bind_p99_ms": ("lower", "submit_bind_p99_ms", "sbp99"),
    "shed_rate": ("lower", "shed_rate", "shed"),
    # sharded multi-chip serving (ISSUE 10, config 8 sharded_scale):
    # scaling efficiency must not DROP (sharding that stops paying for
    # itself is the headline regressing) and the compiled collective
    # payload per cycle must not RISE (the payload diet is what makes
    # the scale grid reachable — AUDIT_SHARDED r05 43.2 MB -> r06
    # 3.7 MB). Both skipped for artifacts predating config 8.
    "scaling_efficiency": ("higher", "scaling_efficiency", "seff"),
    "collective_payload_mb": ("lower", "collective_payload_mb",
                              "cpmb"),
    # admission-time incremental encode (ISSUE 16, config 10
    # host_encode): the flush-side finalize residue must not RISE (a
    # growing finalize means host encode cost crept back onto the
    # dispatch critical path) and the share of encode host time hidden
    # in the ack path's shadow must not DROP (falling hidden share
    # means ingest stopped pre-staging rows and the flush re-parses).
    # Both skipped for artifacts predating config 10 (r05 and older);
    # --min-encode-hidden additionally floors the NEW artifact's
    # absolute hidden share.
    "finalize_p50_ms": ("lower", "finalize_p50_ms", "finp50"),
    "encode_hidden_pct": ("higher", "encode_hidden_pct", "ehid"),
    # multi-tenant arena (ISSUE 18, config 11 tenant_arena): the
    # packed-vs-sequential speedup must not DROP (the whole point of
    # stacking tenants into one program) and tenants-per-dispatch must
    # not DROP (falling packing density means tenant shapes stopped
    # quantizing into shared spec buckets — each stray bucket is a
    # compile and a dispatch). arena_warm_builds additionally gates as
    # an ABSOLUTE ceiling (--max-arena-warm-builds, default 0): any
    # executable built inside the timed window is a compile the fleet
    # pays at serving time. All skipped for artifacts predating
    # config 11.
    "arena_speedup": ("higher", "arena_speedup", "aspd"),
    "arena_device_speedup": ("higher", "arena_device_speedup", "adspd"),
    "tenants_per_dispatch": ("higher", "tenants_per_dispatch", "tpd"),
}
_COUNT_METRICS = (
    "stall_cycles", "anomalies_total", "degraded_cycles", "alerts_fired",
)


def _scan_tail(text: str) -> list[dict]:
    """Recover per-config records from a (possibly truncated) recorded
    stdout/stderr tail: raw-decode a JSON object at every '{"config"'
    (long rows) and '{"c"' (compact rows); torn objects are skipped."""
    dec = json.JSONDecoder()
    rows: list[dict] = []
    for needle in ('{"config"', '{"c"'):
        start = 0
        while True:
            i = text.find(needle, start)
            if i < 0:
                break
            try:
                obj, _end = dec.raw_decode(text[i:])
            except ValueError:
                start = i + 1
                continue
            if isinstance(obj, dict):
                rows.append(obj)
            start = i + 1
    return rows


def _normalize(row: dict) -> dict | None:
    """One per-config record (long or compact keys) -> canonical dict."""
    cfg = row.get("config", row.get("c"))
    if cfg is None:
        return None
    out: dict = {"config": int(cfg)}
    for name, (_kind, long_k, short_k) in _METRICS.items():
        v = row.get(long_k, row.get(short_k))
        if v is not None:
            out[name] = float(v)
    # stall/anomaly keys are emitted only when the SOURCE row carries
    # them: a pre-PR5 compact row following the detail line in a tail
    # must not clobber the detail's real counts with defaults
    stall = row.get("stall_cycles", row.get("stall"))
    if stall is not None:
        out["stall_cycles"] = int(stall)
    degc = row.get("degraded_cycles", row.get("degc"))
    if degc is not None:
        out["degraded_cycles"] = int(degc)
    # tracing overhead is gated as an absolute ceiling (see module
    # docstring), so it rides outside _METRICS' relative comparison
    trov = row.get("trace_overhead_pct", row.get("trov"))
    if trov is not None:
        out["trace_overhead_pct"] = float(trov)
    # config-11 warm-window compile count: absolute ceiling, rides
    # outside the relative comparison like trace_overhead_pct
    awb = row.get("arena_warm_builds", row.get("awb"))
    if awb is not None:
        out["arena_warm_builds"] = int(awb)
    anom = row.get("anomalies", row.get("anom"))
    if anom is not None:
        out["anomalies"] = dict(anom)
        out["anomalies_total"] = int(sum(anom.values()))
    # watchtower replay (ISSUE 20): rule-pack firings over the same
    # latency series — absent on artifacts predating the pack
    alerts = row.get("alerts_fired", row.get("alerts"))
    if alerts is not None:
        out["alerts_fired"] = int(alerts)
    # require at least one real metric besides the config id, so a torn
    # tail fragment can't masquerade as a record
    if not any(k in out for k in _METRICS):
        return None
    return out


def load_configs(path: str) -> dict[int, dict]:
    """-> {config_number: normalized record}; later records win (the
    detail line in a tail is followed by the compact headline line —
    both describe the same run)."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    rows: list[dict] = []
    try:
        data = json.loads(text)
    except ValueError:
        data = None
    if isinstance(data, dict):
        parsed = data.get("parsed")
        if isinstance(parsed, dict) and parsed.get("configs"):
            rows = list(parsed["configs"])
        elif data.get("configs"):
            rows = list(data["configs"])
        elif isinstance(data.get("tail"), str):
            rows = _scan_tail(data["tail"])
        elif "config" in data or "c" in data:
            rows = [data]
    elif isinstance(data, list):
        rows = [r for r in data if isinstance(r, dict)]
    else:
        # JSON-lines (bench_suite standalone) or arbitrary text: scan
        rows = _scan_tail(text)
    out: dict[int, dict] = {}
    for row in rows:
        norm = _normalize(row)
        if norm is not None:
            # merge: a later row for the same config fills gaps but a
            # compact row must not erase the long row's extra fields
            out.setdefault(norm["config"], {}).update(
                {k: v for k, v in norm.items() if v is not None}
            )
    return out


def compare(
    old: dict[int, dict],
    new: dict[int, dict],
    thresholds: dict[str, float],
    allow_stalls: int,
    min_ms_delta: float,
) -> dict:
    checks: list[dict] = []
    regressions: list[dict] = []
    common = sorted(set(old) & set(new))
    for cfg in common:
        o, n = old[cfg], new[cfg]
        for name, (kind, _lk, _sk) in _METRICS.items():
            if name not in o or name not in n:
                continue
            ov, nv = o[name], n[name]
            limit = thresholds[name]
            if ov:
                delta_pct = (nv - ov) / ov * 100.0
                worse = -delta_pct if kind == "higher" else delta_pct
                regressed = worse > limit
            else:
                # zero baseline (compact rows round sub-0.05ms values
                # to 0.0): percentages are undefined, and `x/0-guarded
                # -> 0%` would let an unbounded rise through. A
                # lower-is-better metric leaving 0 regresses on the
                # absolute gate below; higher-is-better leaving 0 is an
                # improvement.
                delta_pct = None
                regressed = kind == "lower" and nv > 0
            if regressed and name.endswith("_ms"):
                if abs(nv - ov) < min_ms_delta:
                    regressed = False  # sub-noise absolute move
            check = {
                "config": cfg,
                "metric": name,
                "old": ov,
                "new": nv,
                "delta_pct": (
                    round(delta_pct, 2) if delta_pct is not None
                    else None
                ),
                "limit_pct": limit,
                "regressed": regressed,
            }
            checks.append(check)
            if regressed:
                regressions.append(check)
        for name in _COUNT_METRICS:
            ov, nv = o.get(name, 0), n.get(name, 0)
            regressed = nv > ov + allow_stalls
            check = {
                "config": cfg,
                "metric": name,
                "old": ov,
                "new": nv,
                "allow": allow_stalls,
                "regressed": regressed,
            }
            if name == "anomalies_total":
                check["classes"] = {
                    "old": o.get("anomalies", {}),
                    "new": n.get("anomalies", {}),
                }
            checks.append(check)
            if regressed:
                regressions.append(check)
    return {
        "configs_compared": common,
        "only_old": sorted(set(old) - set(new)),
        "only_new": sorted(set(new) - set(old)),
        "checks": checks,
        "regressions": regressions,
        "ok": not regressions,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_diff",
        description="diff two BENCH_*.json artifacts; non-zero exit on "
        "regression (thresholds in percent)",
    )
    ap.add_argument("old")
    ap.add_argument("new")
    # Default calibration: loose enough that a known-good round pair
    # with a methodology change between them diffs clean (r04 -> r05
    # turned on fold-mode benching, which moved real incremental-fold
    # cost into encode_p50_ms and shifted per-config device_ms), tight
    # enough that a 2x phase regression or a dps drop still trips.
    # Rounds comparing like-for-like runs should pass tighter values.
    ap.add_argument("--max-dps-drop", type=float, default=10.0)
    ap.add_argument("--max-p50-rise", type=float, default=20.0)
    ap.add_argument("--max-p99-rise", type=float, default=50.0)
    ap.add_argument("--max-device-rise", type=float, default=35.0)
    ap.add_argument("--max-encode-rise", type=float, default=60.0)
    ap.add_argument(
        "--max-amortization-drop", type=float, default=25.0,
        help="multi-cycle tunnel_amortization may drop this many "
        "percent before it counts as a regression",
    )
    ap.add_argument(
        "--max-effective-p50-rise", type=float, default=25.0,
        help="multi-cycle best-K effective per-cycle p50 may rise "
        "this many percent before it counts as a regression",
    )
    ap.add_argument(
        "--max-first-bind-rise", type=float, default=25.0,
        help="depth-2 speculative first_bind_p50_ms may rise this many "
        "percent before it counts as a regression",
    )
    ap.add_argument(
        "--max-speculation-hit-drop", type=float, default=10.0,
        help="speculation_hit_rate may drop this many percent before "
        "it counts as a regression (an abandon-heavy workload pays "
        "the speculative dispatch for nothing)",
    )
    ap.add_argument(
        "--max-compile-rise", type=float, default=75.0,
        help="per-config compile_seconds may rise this many percent "
        "before it counts as a regression (compile time is rig-noisy; "
        "a genuinely new program or a lost cache hit roughly doubles "
        "it — r04->r05 moved -7%%/-42%% on the shared configs)",
    )
    ap.add_argument(
        "--max-hit-rate-drop", type=float, default=10.0,
        help="warm-start compile_cache_hit_rate may drop this many "
        "percent before it counts as a regression",
    )
    ap.add_argument(
        "--max-mttr-rise", type=float, default=50.0,
        help="fault-storm mean-time-to-recovery may rise this many "
        "percent before it counts as a regression (recovery time is "
        "promotion-cycle-quantized, so small shifts are noise)",
    )
    ap.add_argument(
        "--max-submit-ack-rise", type=float, default=50.0,
        help="front-door submit_ack_p99_ms may rise this many percent "
        "before it counts as a regression (the ack path embeds one "
        "group-commit fsync, which is disk-noisy)",
    )
    ap.add_argument(
        "--max-submit-bind-rise", type=float, default=30.0,
        help="front-door end-to-end submit_bind_p99_ms may rise this "
        "many percent before it counts as a regression",
    )
    ap.add_argument(
        "--max-shed-rise", type=float, default=0.0,
        help="sustained-phase shed_rate above the old artifact's "
        "(asserted-zero) baseline is a regression at any size — the "
        "door refusing nominal load is never noise",
    )
    ap.add_argument(
        "--max-scaling-efficiency-drop", type=float, default=25.0,
        help="config-8 scaling_efficiency may drop this many percent "
        "before it counts as a regression (virtual-CPU sweeps are "
        "noisy; a real fall-off-the-cliff is far larger)",
    )
    ap.add_argument(
        "--max-payload-rise", type=float, default=25.0,
        help="config-8 collective_payload_mb may rise this many "
        "percent before it counts as a regression (the compile-only "
        "audit gate asserts the hard per-class budgets; this catches "
        "drift between rounds)",
    )
    ap.add_argument(
        "--max-finalize-rise", type=float, default=50.0,
        help="config-10 flush-side finalize_p50_ms may rise this many "
        "percent before it counts as a regression (millisecond-scale "
        "on CPU smoke; the --min-ms-delta noise floor also applies)",
    )
    ap.add_argument(
        "--max-encode-hidden-drop", type=float, default=25.0,
        help="config-10 encode_hidden_pct may drop this many percent "
        "RELATIVE to the old artifact before it counts as a "
        "regression (the absolute floor is --min-encode-hidden)",
    )
    ap.add_argument(
        "--min-encode-hidden", type=float, default=0.0,
        help="absolute floor: the NEW artifact's encode_hidden_pct "
        "must be at least this (percent of encode host time staged in "
        "the ack path's shadow). 0 disables — CPU smoke runs at toy "
        "pod counts where fixed flush overhead dominates; full-scale "
        "rounds should pass the ISSUE 16 target (95)",
    )
    ap.add_argument(
        "--max-arena-speedup-drop", type=float, default=25.0,
        help="config-11 packed-vs-sequential arena_speedup may drop "
        "this many percent before it counts as a regression",
    )
    ap.add_argument(
        "--max-tenants-per-dispatch-drop", type=float, default=25.0,
        help="config-11 tenants_per_dispatch (packing density) may "
        "drop this many percent before it counts as a regression",
    )
    ap.add_argument(
        "--max-arena-warm-builds", type=int, default=0,
        help="absolute ceiling on the NEW artifact's config-11 "
        "arena_warm_builds: executables compiled inside the timed "
        "window (the zero-compiles-after-warmup contract). -1 "
        "disables",
    )
    ap.add_argument(
        "--max-trace-overhead", type=float, default=50.0,
        help="absolute ceiling on the NEW artifact's config-9 "
        "trace_overhead_pct (worst-case armed-at-rate-1.0 latency "
        "delta vs tracing off; the ack axis only counts past the "
        "group-commit fsync-jitter floor, see "
        "bench_suite.trace_overhead_pct). Applied to the new "
        "artifact alone: the old side is shown for context only, "
        "because relative diffs of a near-zero percentage are pure "
        "noise. Loose by default — CPU smoke's sub-ms latencies make "
        "small absolute moves read as big percentages; 0 disables",
    )
    ap.add_argument(
        "--allow-stalls", type=int, default=1,
        help="stall/anomaly count may grow by this many before it "
        "counts as a regression (one stall is a known rig flake — "
        "ROUND5.md's 28 s outlier was absent on rerun; two is a trend)",
    )
    ap.add_argument(
        "--min-ms-delta", type=float, default=2.0,
        help="ignore millisecond-metric regressions smaller than this "
        "absolute delta (CPU smoke noise floor)",
    )
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    try:
        old = load_configs(args.old)
        new = load_configs(args.new)
    except OSError as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2
    if not old or not new:
        print(
            f"bench_diff: no per-config records found "
            f"(old: {len(old)}, new: {len(new)}) — nothing to compare "
            "is a parse error, not a pass",
            file=sys.stderr,
        )
        return 2

    result = compare(
        old, new,
        thresholds={
            "dps": args.max_dps_drop,
            "p50_ms": args.max_p50_rise,
            "p99_ms": args.max_p99_rise,
            "device_ms": args.max_device_rise,
            "encode_p50_ms": args.max_encode_rise,
            "tunnel_amortization": args.max_amortization_drop,
            "effective_p50_ms": args.max_effective_p50_rise,
            "first_bind_p50_ms": args.max_first_bind_rise,
            "speculation_hit_rate": args.max_speculation_hit_drop,
            "compile_seconds": args.max_compile_rise,
            "compile_cache_hit_rate": args.max_hit_rate_drop,
            "mttr_ms": args.max_mttr_rise,
            "submit_ack_p99_ms": args.max_submit_ack_rise,
            "submit_bind_p99_ms": args.max_submit_bind_rise,
            "shed_rate": args.max_shed_rise,
            "scaling_efficiency": args.max_scaling_efficiency_drop,
            "collective_payload_mb": args.max_payload_rise,
            "finalize_p50_ms": args.max_finalize_rise,
            "encode_hidden_pct": args.max_encode_hidden_drop,
            "arena_speedup": args.max_arena_speedup_drop,
            "arena_device_speedup": args.max_arena_speedup_drop,
            "tenants_per_dispatch": args.max_tenants_per_dispatch_drop,
        },
        allow_stalls=args.allow_stalls,
        min_ms_delta=args.min_ms_delta,
    )
    if args.min_encode_hidden > 0:
        # absolute floor, gated on the NEW artifact only: the relative
        # check above tolerates drift, but a full-scale round must not
        # ship with the hidden share below the ISSUE 16 target no
        # matter what the old artifact reported
        for cfg in sorted(new):
            nv = new[cfg].get("encode_hidden_pct")
            if nv is None:
                continue
            check = {
                "config": cfg,
                "metric": "encode_hidden_pct_floor",
                "old": args.min_encode_hidden,
                "new": nv,
                "delta_pct": None,
                "limit_pct": args.min_encode_hidden,
                "regressed": nv < args.min_encode_hidden,
            }
            result["checks"].append(check)
            if check["regressed"]:
                result["regressions"].append(check)
                result["ok"] = False
    if args.max_trace_overhead > 0:
        # absolute ceiling, gated on the NEW artifact only (see the
        # module docstring for why this is not a relative diff)
        for cfg in sorted(new):
            nv = new[cfg].get("trace_overhead_pct")
            if nv is None:
                continue
            check = {
                "config": cfg,
                "metric": "trace_overhead_ceiling",
                "old": old.get(cfg, {}).get(
                    "trace_overhead_pct", 0.0
                ),
                "new": nv,
                "delta_pct": None,
                "limit_pct": args.max_trace_overhead,
                "regressed": nv > args.max_trace_overhead,
            }
            result["checks"].append(check)
            if check["regressed"]:
                result["regressions"].append(check)
                result["ok"] = False
    if args.max_arena_warm_builds >= 0:
        # absolute ceiling on the NEW artifact only: a compile inside
        # config 11's timed window is a serving-time stall regardless
        # of what the old artifact did
        for cfg in sorted(new):
            nv = new[cfg].get("arena_warm_builds")
            if nv is None:
                continue
            check = {
                "config": cfg,
                "metric": "arena_warm_builds_ceiling",
                "old": old.get(cfg, {}).get("arena_warm_builds", 0),
                "new": nv,
                "delta_pct": None,
                "limit_pct": args.max_arena_warm_builds,
                "regressed": nv > args.max_arena_warm_builds,
            }
            result["checks"].append(check)
            if check["regressed"]:
                result["regressions"].append(check)
                result["ok"] = False
    if args.json:
        print(json.dumps(result, indent=2))
        return 0 if result["ok"] else 1

    for c in result["checks"]:
        flag = "REGRESSED" if c["regressed"] else "ok"
        if "delta_pct" in c:
            dp = (
                f"{c['delta_pct']:+7.2f}%"
                if c["delta_pct"] is not None else "   n/a  "
            )
            print(
                f"config {c['config']:>2} {c['metric']:<14} "
                f"{c['old']:>14.3f} -> {c['new']:>14.3f} "
                f"({dp} vs ±{c['limit_pct']:g}%) "
                f"{flag}"
            )
        else:
            print(
                f"config {c['config']:>2} {c['metric']:<14} "
                f"{c['old']:>14d} -> {c['new']:>14d} "
                f"(allow +{c['allow']}) {flag}"
            )
    for side, cfgs in (("old", result["only_old"]),
                       ("new", result["only_new"])):
        if cfgs:
            print(f"note: configs only in {side} artifact: {cfgs}")
    if result["regressions"]:
        print(
            f"bench_diff: {len(result['regressions'])} regression(s) "
            f"across configs {result['configs_compared']}",
            file=sys.stderr,
        )
        return 1
    print(
        f"bench_diff: clean — configs {result['configs_compared']}, "
        f"{len(result['checks'])} checks"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
