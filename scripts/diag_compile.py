"""Diagnose the config-#4 compile blowup: time trace / compile / first-run
separately for the affinity-enabled cycle at increasing pod counts.

Usage: python scripts/diag_compile.py P N [flags]
  flags: noaff nospread noanti cpu apps=<num_distinct_apps> exist=<frac>
  Unknown flags are an error. `cpu` flips to the CPU backend post-import
  (the documented-safe way; exporting JAX_PLATFORMS=cpu hangs sitecustomize).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from k8s_scheduler_tpu.core import build_cycle_fn
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods


def main() -> None:
    p_real = int(sys.argv[1]) if len(sys.argv) > 1 else 10000
    n_real = int(sys.argv[2]) if len(sys.argv) > 2 else 1000
    flags = set(sys.argv[3:])
    num_apps, exist_frac = 20, 0.0
    known = {"noaff", "nointer", "noanti", "nospread", "cpu"}
    for f in list(flags):
        if f.startswith("apps="):
            num_apps = int(f.split("=")[1])
            flags.discard(f)
        elif f.startswith("exist="):
            exist_frac = float(f.split("=")[1])
            flags.discard(f)
        elif f not in known:
            sys.exit(f"unknown flag: {f!r} (known: {sorted(known)}, apps=N, exist=F)")
    if "cpu" in flags:
        jax.config.update("jax_platforms", "cpu")
    aff = 0.0 if ("noaff" in flags or "nointer" in flags) else 0.3
    anti = 0.0 if ("noaff" in flags or "noanti" in flags) else 0.2
    spread = 0.0 if ("noaff" in flags or "nospread" in flags) else 0.2

    t0 = time.time()
    nodes = make_cluster(n_real, with_labels=True, taint_fraction=0.1)
    pods = make_pods(
        p_real,
        affinity_fraction=aff,
        anti_affinity_fraction=anti,
        spread_fraction=spread,
        selector_fraction=0.3,
        toleration_fraction=0.1,
        priorities=(0, 0, 0, 100),
        num_apps=num_apps,
    )
    existing = []
    if exist_frac:
        rng = np.random.default_rng(7)
        epods = make_pods(
            int(p_real * exist_frac),
            seed=9,
            name_prefix="run",
            affinity_fraction=aff,
            anti_affinity_fraction=anti,
            spread_fraction=spread,
            num_apps=num_apps,
        )
        existing = [
            (p, f"node-{int(rng.integers(0, n_real))}") for p in epods
        ]
    print(f"synth: {time.time() - t0:.1f}s", flush=True)

    t0 = time.time()
    snap = SnapshotEncoder().encode(nodes, pods, existing)
    print(
        f"encode: {time.time() - t0:.1f}s  P={snap.P} N={snap.N} E={snap.E} "
        f"S={snap.sel_exprs.shape[0]} D={snap.domain_key.shape[0]} "
        f"Ex={snap.ex_key.shape[0]} MA={snap.pod_aff_terms.shape[1]} "
        f"aff={snap.has_inter_pod_affinity} spread={snap.has_topology_spread}",
        flush=True,
    )

    cycle = build_cycle_fn()
    t0 = time.time()
    lowered = cycle.lower(snap)
    print(f"trace/lower: {time.time() - t0:.1f}s", flush=True)

    t0 = time.time()
    compiled = lowered.compile()
    print(f"compile: {time.time() - t0:.1f}s", flush=True)

    t0 = time.time()
    res = compiled(snap)
    jax.block_until_ready(res.assignment)
    print(f"first run: {time.time() - t0:.2f}s", flush=True)

    for _ in range(3):
        t0 = time.time()
        res = compiled(snap)
        jax.block_until_ready(res.assignment)
        print(f"steady run: {(time.time() - t0) * 1e3:.1f}ms", flush=True)
    a = np.asarray(res.assignment)
    print(f"scheduled {(a >= 0).sum()} / {p_real}")


if __name__ == "__main__":
    main()
