#!/usr/bin/env python
"""schedlint: run the repo's static-analysis passes.

    JAX_PLATFORMS=cpu python scripts/schedlint.py            # lint the tree
    python scripts/schedlint.py --json                       # machine output
    python scripts/schedlint.py --changed --fail-on-new      # pre-commit loop
    python scripts/schedlint.py --passes TRACE-SAFETY        # one pass
    python scripts/schedlint.py --sarif out.sarif            # CI annotations
    python scripts/schedlint.py --list-codes                 # code inventory
    python scripts/schedlint.py --write-baseline             # regrandfather

Exit status: 0 = no unsuppressed, non-baselined findings; 1 = findings;
2 = usage error. The committed baseline is .schedlint-baseline.json at
the repo root (line-independent, count-aware entries; shrink it, don't
grow it). `--changed` scopes the scan to the .py files git reports
modified or untracked under the default lint roots — the fast
pre-commit loop (the parse cache makes repeats near-free); the
full-tree run stays the tier-1/CI gate, since cross-file inventories
can only be judged whole. A --changed run whose modifications all fall
OUTSIDE the lint roots says so explicitly instead of printing a pass
that looks like a clean lint. `--fail-on-new` is the regression gate:
it requires a baseline, prints each new finding with its stable
fingerprint, and nags about stale baseline entries that matched
nothing so the file shrinks. `--sarif FILE` additionally writes SARIF
2.1.0 for code-scanning UIs. See README "Static analysis" for
pass/code docs and the `# schedlint: disable=CODE -- why` suppression
syntax.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, ".schedlint-baseline.json")

def changed_paths(repo: str) -> tuple[list[str], list[str]] | None:
    """(lintable, skipped): repo-relative files git reports modified
    (vs HEAD) or untracked, split into .py files under the lint roots
    and everything else — the caller reports the skipped set so a
    "no changed files" pass can never be mistaken for a clean lint of
    the change. None when git is unavailable or this is not a work
    tree (the caller turns that into a usage error — silently linting
    nothing would be a permanent green). NUL-separated output (-z) so
    octal-quoted non-ASCII names cannot be dropped."""
    from k8s_scheduler_tpu.analysis.core import DEFAULT_PATHS

    roots = tuple(p.rstrip("/") + "/" for p in DEFAULT_PATHS)
    rels: set[str] = set()
    try:
        for args in (
            ["diff", "--name-only", "-z", "HEAD", "--"],
            ["ls-files", "--others", "--exclude-standard", "-z", "--"],
        ):
            out = subprocess.run(
                ["git", "-C", repo, *args],
                capture_output=True, text=True, check=True,
            ).stdout
            rels.update(r for r in out.split("\0") if r)
    except (OSError, subprocess.CalledProcessError):
        return None
    present = sorted(
        r for r in rels if os.path.exists(os.path.join(repo, r))
    )
    lintable = [
        r for r in present if r.endswith(".py") and r.startswith(roots)
    ]
    skipped = [r for r in present if r not in lintable]
    return lintable, skipped


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="schedlint",
        description="repo-native static analysis (trace safety, lock "
        "discipline, journal emit-once, inventory drift, hygiene, "
        "robustness, thread lifecycle/races, shard safety)",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/dirs to lint (default: k8s_scheduler_tpu + scripts)",
    )
    ap.add_argument(
        "--passes", default="",
        help="comma-separated pass names (default: all registered)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit one JSON object (findings + suppressed + "
        "grandfathered counts; each finding carries a stable "
        "line-independent fingerprint) so drivers can diff across PRs",
    )
    ap.add_argument(
        "--changed", action="store_true",
        help="lint only the .py files git reports modified/untracked "
        "under the default roots (fast pre-commit loop; the full-tree "
        "run stays the CI gate)",
    )
    ap.add_argument(
        "--baseline", default=DEFAULT_BASELINE,
        help="baseline file ('' = none)",
    )
    ap.add_argument(
        "--fail-on-new", action="store_true",
        help="regression-gate mode: requires a baseline, prints each "
        "new finding with its stable fingerprint, and warns about "
        "stale baseline entries that matched nothing",
    )
    ap.add_argument(
        "--sarif", default="", metavar="FILE",
        help="also write a SARIF 2.1.0 report (new findings at error "
        "level; suppressed/baselined carried with suppression kind)",
    )
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="write the current unsuppressed findings as the new "
        "baseline and exit 0",
    )
    ap.add_argument(
        "--list-codes", action="store_true",
        help="print every registered pass + finding code and exit",
    )
    args = ap.parse_args(argv)

    from k8s_scheduler_tpu.analysis import (
        default_registry,
        run_lint,
        write_baseline,
    )

    registry = default_registry()
    if args.list_codes:
        for name in registry.names():
            p = registry.make(name)
            print(name)
            for code, desc in sorted(p.codes.items()):
                print(f"  {code}  {desc}")
        return 0

    if args.changed:
        if args.paths:
            print(
                "schedlint: --changed and explicit paths are mutually "
                "exclusive", file=sys.stderr,
            )
            return 2
        if args.write_baseline:
            # a baseline written from a subset scan would silently
            # DELETE every grandfathered entry for unscanned files —
            # the next full-tree run then fails on all of them
            print(
                "schedlint: --write-baseline needs the full-tree scan, "
                "not --changed", file=sys.stderr,
            )
            return 2
        split = changed_paths(REPO)
        if split is None:
            print(
                "schedlint: --changed needs a git work tree",
                file=sys.stderr,
            )
            return 2
        changed, skipped = split
        if skipped:
            # loud, not silent: "ok" below must never read as a clean
            # lint of files this scan never looked at
            print(
                f"schedlint: warning — {len(skipped)} changed file(s) "
                "outside the lint roots were NOT scanned: "
                + ", ".join(skipped[:5])
                + (" ..." if len(skipped) > 5 else ""),
                file=sys.stderr,
            )
        if not changed:
            note = " (nothing was linted)" if skipped else ""
            print(
                "schedlint: ok — no changed files under the lint "
                f"roots{note}"
            )
            return 0
        args.paths = changed

    if args.fail_on_new and not args.baseline:
        print(
            "schedlint: --fail-on-new is a baseline diff; it needs "
            "--baseline pointing at a file (the default works even "
            "when the file does not exist yet)", file=sys.stderr,
        )
        return 2
    if args.fail_on_new and args.write_baseline:
        print(
            "schedlint: --fail-on-new and --write-baseline are "
            "mutually exclusive (one gates on the baseline, the other "
            "replaces it)", file=sys.stderr,
        )
        return 2

    passes = None
    if args.passes:
        passes = [p.strip() for p in args.passes.split(",") if p.strip()]
        unknown = sorted(set(passes) - set(registry.names()))
        if unknown:
            print(
                f"schedlint: unknown pass(es) {unknown}; registered: "
                f"{registry.names()}", file=sys.stderr,
            )
            return 2

    try:
        result = run_lint(
            REPO,
            paths=args.paths or None,
            registry=registry,
            passes=passes,
            baseline_path="" if args.write_baseline else args.baseline,
        )
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    if result.files_scanned == 0:
        print(
            "schedlint: 0 files scanned — nothing to lint is a "
            "configuration error, not a pass", file=sys.stderr,
        )
        return 2

    if args.write_baseline:
        write_baseline(args.baseline or DEFAULT_BASELINE, result.findings)
        print(
            f"schedlint: baseline written with {len(result.findings)} "
            f"finding(s) -> {args.baseline or DEFAULT_BASELINE}"
        )
        return 0

    if args.sarif:
        from k8s_scheduler_tpu.analysis.core import to_sarif
        from k8s_scheduler_tpu.analysis.registry import all_codes

        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(to_sarif(result, all_codes(registry)), fh, indent=2)
            fh.write("\n")
        print(f"schedlint: SARIF written -> {args.sarif}", file=sys.stderr)

    if args.fail_on_new:
        from k8s_scheduler_tpu.analysis.core import (
            load_baseline,
            stale_baseline_entries,
        )

        for (file, code, message), left in stale_baseline_entries(
            load_baseline(args.baseline), result.grandfathered
        ):
            print(
                f"schedlint: stale baseline entry ({left} unmatched): "
                f"{file} {code} {message!r} — the finding is gone; "
                "shrink the baseline", file=sys.stderr,
            )

    if args.json:
        print(json.dumps(result.to_dict(), indent=2))
        return 0 if result.ok else 1

    for f in result.findings:
        suffix = f"  [{f.fingerprint()}]" if args.fail_on_new else ""
        print(f"{f}{suffix}", file=sys.stderr)
    tail = []
    if result.suppressed:
        tail.append(f"{len(result.suppressed)} suppressed")
    if result.grandfathered:
        tail.append(f"{len(result.grandfathered)} grandfathered")
    suffix = f" ({', '.join(tail)})" if tail else ""
    if result.findings:
        print(
            f"schedlint: {len(result.findings)} finding(s) over "
            f"{result.files_scanned} files{suffix}", file=sys.stderr,
        )
        return 1
    print(
        f"schedlint: ok — {result.files_scanned} files, passes: "
        f"{', '.join(result.passes_run)}{suffix}"
    )
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
