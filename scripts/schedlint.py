#!/usr/bin/env python
"""schedlint: run the repo's static-analysis passes.

    JAX_PLATFORMS=cpu python scripts/schedlint.py            # lint the tree
    python scripts/schedlint.py --json                       # machine output
    python scripts/schedlint.py --changed                    # diff-scoped
    python scripts/schedlint.py --passes TRACE-SAFETY        # one pass
    python scripts/schedlint.py --list-codes                 # code inventory
    python scripts/schedlint.py --write-baseline             # regrandfather

Exit status: 0 = no unsuppressed, non-baselined findings; 1 = findings;
2 = usage error. The committed baseline is .schedlint-baseline.json at
the repo root (line-independent entries; shrink it, don't grow it).
`--changed` scopes the scan to the .py files git reports modified or
untracked under the default lint roots — the fast pre-commit loop (the
parse cache makes repeats near-free); the full-tree run stays the
tier-1/CI gate, since cross-file inventories can only be judged whole.
See README "Static analysis" for pass/code docs and the
`# schedlint: disable=CODE` suppression syntax.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, ".schedlint-baseline.json")

def changed_paths(repo: str) -> list[str] | None:
    """Repo-relative .py files under the lint roots that git reports
    modified (vs HEAD) or untracked. None when git is unavailable or
    this is not a work tree (the caller turns that into a usage error —
    silently linting nothing would be a permanent green). NUL-separated
    output (-z) so octal-quoted non-ASCII names cannot be dropped."""
    from k8s_scheduler_tpu.analysis.core import DEFAULT_PATHS

    roots = tuple(p.rstrip("/") + "/" for p in DEFAULT_PATHS)
    rels: set[str] = set()
    try:
        for args in (
            ["diff", "--name-only", "-z", "HEAD", "--"],
            ["ls-files", "--others", "--exclude-standard", "-z", "--"],
        ):
            out = subprocess.run(
                ["git", "-C", repo, *args],
                capture_output=True, text=True, check=True,
            ).stdout
            rels.update(r for r in out.split("\0") if r)
    except (OSError, subprocess.CalledProcessError):
        return None
    return sorted(
        r for r in rels
        if r.endswith(".py")
        and r.startswith(roots)
        and os.path.exists(os.path.join(repo, r))
    )


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="schedlint",
        description="repo-native static analysis (trace safety, lock "
        "discipline, journal emit-once, inventory drift, hygiene, "
        "robustness, thread lifecycle/races, shard safety)",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/dirs to lint (default: k8s_scheduler_tpu + scripts)",
    )
    ap.add_argument(
        "--passes", default="",
        help="comma-separated pass names (default: all registered)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit one JSON object (findings + suppressed + "
        "grandfathered counts; each finding carries a stable "
        "line-independent fingerprint) so drivers can diff across PRs",
    )
    ap.add_argument(
        "--changed", action="store_true",
        help="lint only the .py files git reports modified/untracked "
        "under the default roots (fast pre-commit loop; the full-tree "
        "run stays the CI gate)",
    )
    ap.add_argument(
        "--baseline", default=DEFAULT_BASELINE,
        help="baseline file ('' = none)",
    )
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="write the current unsuppressed findings as the new "
        "baseline and exit 0",
    )
    ap.add_argument(
        "--list-codes", action="store_true",
        help="print every registered pass + finding code and exit",
    )
    args = ap.parse_args(argv)

    from k8s_scheduler_tpu.analysis import (
        default_registry,
        run_lint,
        write_baseline,
    )

    registry = default_registry()
    if args.list_codes:
        for name in registry.names():
            p = registry.make(name)
            print(name)
            for code, desc in sorted(p.codes.items()):
                print(f"  {code}  {desc}")
        return 0

    if args.changed:
        if args.paths:
            print(
                "schedlint: --changed and explicit paths are mutually "
                "exclusive", file=sys.stderr,
            )
            return 2
        if args.write_baseline:
            # a baseline written from a subset scan would silently
            # DELETE every grandfathered entry for unscanned files —
            # the next full-tree run then fails on all of them
            print(
                "schedlint: --write-baseline needs the full-tree scan, "
                "not --changed", file=sys.stderr,
            )
            return 2
        changed = changed_paths(REPO)
        if changed is None:
            print(
                "schedlint: --changed needs a git work tree",
                file=sys.stderr,
            )
            return 2
        if not changed:
            print("schedlint: ok — no changed files under the lint roots")
            return 0
        args.paths = changed

    passes = None
    if args.passes:
        passes = [p.strip() for p in args.passes.split(",") if p.strip()]
        unknown = sorted(set(passes) - set(registry.names()))
        if unknown:
            print(
                f"schedlint: unknown pass(es) {unknown}; registered: "
                f"{registry.names()}", file=sys.stderr,
            )
            return 2

    try:
        result = run_lint(
            REPO,
            paths=args.paths or None,
            registry=registry,
            passes=passes,
            baseline_path="" if args.write_baseline else args.baseline,
        )
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    if result.files_scanned == 0:
        print(
            "schedlint: 0 files scanned — nothing to lint is a "
            "configuration error, not a pass", file=sys.stderr,
        )
        return 2

    if args.write_baseline:
        write_baseline(args.baseline or DEFAULT_BASELINE, result.findings)
        print(
            f"schedlint: baseline written with {len(result.findings)} "
            f"finding(s) -> {args.baseline or DEFAULT_BASELINE}"
        )
        return 0

    if args.json:
        print(json.dumps(result.to_dict(), indent=2))
        return 0 if result.ok else 1

    for f in result.findings:
        print(str(f), file=sys.stderr)
    tail = []
    if result.suppressed:
        tail.append(f"{len(result.suppressed)} suppressed")
    if result.grandfathered:
        tail.append(f"{len(result.grandfathered)} grandfathered")
    suffix = f" ({', '.join(tail)})" if tail else ""
    if result.findings:
        print(
            f"schedlint: {len(result.findings)} finding(s) over "
            f"{result.files_scanned} files{suffix}", file=sys.stderr,
        )
        return 1
    print(
        f"schedlint: ok — {result.files_scanned} files, passes: "
        f"{', '.join(result.passes_run)}{suffix}"
    )
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
