#!/usr/bin/env python
"""Read a crash black-box bundle: summarize, dump, or extract the trace.

A bundle is the single JSON file `core/blackbox.py` writes at fault
time (watchdog abort, degrade-to-stateless, serve-loop exception,
SIGTERM). This reader is the post-mortem side of that contract:

    python scripts/blackbox_read.py <bundle.json | blackbox-dir>
        # human summary: trigger, alert + anomaly tails, ladder moves
    python scripts/blackbox_read.py <path> --json
        # full bundle to stdout (pipe to jq)
    python scripts/blackbox_read.py <path> --perfetto out.json
        # extract the pre-rendered Chrome/Perfetto trace for ui.perfetto.dev

Given a directory (e.g. <stateDir>/blackbox/), reads the NEWEST bundle.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _resolve(path: str) -> str:
    if os.path.isdir(path):
        names = sorted(
            n for n in os.listdir(path)
            if n.startswith("blackbox-") and n.endswith(".json")
        )
        if not names:
            raise SystemExit(f"no blackbox-*.json bundles under {path}")
        return os.path.join(path, names[-1])
    return path


def _wall(w) -> str:
    try:
        return datetime.datetime.fromtimestamp(
            float(w), tz=datetime.timezone.utc
        ).isoformat(timespec="milliseconds")
    except (TypeError, ValueError, OSError):
        return repr(w)


def _summary(path: str, b: dict) -> None:
    print(f"bundle:   {path}")
    print(f"trigger:  {b.get('trigger')}  ({b.get('detail') or '-'})")
    print(f"wall:     {_wall(b.get('wall'))}  pid={b.get('pid')}")
    build = b.get("build") or {}
    if build:
        print("build:    " + " ".join(
            f"{k}={build[k]}" for k in sorted(build)
        ))

    alerts = b.get("alerts") or {}
    active = alerts.get("active") or []
    resolved = alerts.get("resolved") or []
    print(f"\nalerts:   {len(active)} active, {len(resolved)} resolved "
          f"(fired_total={alerts.get('fired_total', 0)})")
    for a in active:
        print(f"  FIRING  {a['rule']} [{a['severity']}] "
              f"value={a.get('value')} {a.get('op')} {a.get('threshold')} "
              f"since {_wall(a.get('fired_wall'))}")
    for a in resolved[-5:]:
        print(f"  resolved {a['rule']} [{a['severity']}] "
              f"{_wall(a.get('fired_wall'))} -> "
              f"{_wall(a.get('resolved_wall'))}")

    anomalies = (b.get("anomalies") or {}).get("events") or []
    print(f"\nanomalies: {len(anomalies)} in ring; tail:")
    for ev in anomalies[-10:]:
        det = ev.get("detail") or {}
        det_s = " ".join(f"{k}={det[k]}" for k in sorted(det))
        print(f"  {ev.get('class'):<16} seq={ev.get('seq'):>6} "
              f"phase={ev.get('phase') or '-'} "
              f"value_ms={ev.get('value_ms')} {det_s}")

    ladder = b.get("ladder") or {}
    moves = ladder.get("transitions") or []
    print(f"\nladder:   {len(moves)} transitions; tail:")
    for m in moves[-8:]:
        print(f"  seq={m.get('seq'):>6} {m.get('from_name')} -> "
              f"{m.get('to_name')}  ({m.get('reason')})")

    faults = b.get("faults") or {}
    fired = faults.get("fired") or []
    if fired:
        print(f"\nfaults:   {len(fired)} injection points fired")

    hist = b.get("metrics_history") or {}
    print(f"\nmetrics_history: {len(hist.get('series') or [])} series "
          "captured")
    flight = (b.get("flight") or {})
    print(f"flight:   {len(flight.get('records') or [])} cycle records, "
          f"cycles={flight.get('cycles')}")
    print(f"events:   {len(b.get('events') or [])} in tail")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="bundle file, or directory of bundles")
    ap.add_argument("--json", action="store_true",
                    help="dump the full bundle JSON to stdout")
    ap.add_argument("--perfetto", metavar="OUT",
                    help="write the bundle's chrome_trace to OUT")
    args = ap.parse_args()

    from k8s_scheduler_tpu.core.blackbox import load_bundle

    path = _resolve(args.path)
    bundle = load_bundle(path)

    if args.perfetto:
        trace = bundle.get("chrome_trace")
        if trace is None:
            raise SystemExit(
                "bundle has no chrome_trace key (flight recorder was "
                "not attached when the box was armed)"
            )
        with open(args.perfetto, "w") as f:
            json.dump(trace, f)
        n = len(trace.get("traceEvents", trace)) if isinstance(
            trace, (dict, list)
        ) else 0
        print(f"wrote {args.perfetto} ({n} trace events) — open at "
              "https://ui.perfetto.dev", file=sys.stderr)
        return 0

    if args.json:
        json.dump(bundle, sys.stdout, indent=2, default=str)
        print()
        return 0

    _summary(path, bundle)
    return 0


if __name__ == "__main__":
    sys.exit(main())
