#!/usr/bin/env python
"""Failover soak: kill the active at random points, assert the standby
resumes losslessly.

Each round spawns a CHILD process that plays "active scheduler": it
attaches `DurableState` to a fresh queue/cache pair (restoring whatever
the previous round left in the shared state dir), then applies a seeded
random mutation stream — pod adds, cycle pops, assume/finish/confirm,
requeues, deletes, node churn, TTL sweeps — journaling every op. After
EVERY op the child appends a line `<op_index> <digest>` to a digest log
(its own fsync'd side file), so the parent knows the canonical state
digest at every op boundary; every FLUSH_EVERY ops it calls
`journal.flush()` and records the durability watermark.

The PARENT kills the child with SIGKILL at a random moment, then plays
"standby that just won the lease": restore into fresh queue/cache and
assert

1. restore never raises (torn final record handling),
2. the restored digest appears in the child's digest log — i.e. the
   survived journal prefix reproduces EXACTLY the state the active had
   at some op boundary: nothing lost, nothing duplicated, nothing
   half-applied,
3. that boundary is >= the child's last flushed watermark: everything
   the active was TOLD was durable survived the kill.

(2) is strictly stronger than "no lost or duplicated pods" — the digest
covers tier membership, attempt counts, backoff expiries, in-flight
sets, and assumed-pod deadlines bit-for-bit.

Standalone:

    JAX_PLATFORMS=cpu python scripts/soak_failover.py --rounds 10

A smoke-tier subset runs as tests/test_state_failover.py::
test_soak_failover_smoke (marked slow).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FLUSH_EVERY = 16


class Clock:
    """Monotonic-anchored controllable clock: real monotonic plus a
    skew the driver advances, so backoff expiries both order correctly
    and actually expire during the soak."""

    def __init__(self) -> None:
        self.skew = 0.0

    def advance(self, dt: float) -> None:
        self.skew += dt

    def __call__(self) -> float:
        return time.monotonic() + self.skew


def make_pair(clock):
    from k8s_scheduler_tpu.internal.cache import SchedulerCache
    from k8s_scheduler_tpu.internal.queue import SchedulingQueue

    q = SchedulingQueue(
        initial_backoff_seconds=0.05, max_backoff_seconds=0.4,
        unschedulable_timeout_seconds=2.0, now=clock,
    )
    c = SchedulerCache(assumed_pod_ttl_seconds=0.3, now=clock)
    return q, c


def apply_random_op(rng: random.Random, clock, q, c, i: int) -> None:
    """One step of the scheduler-shaped mutation stream. Mirrors what
    the real driver does to the queue/cache around a cycle: intake,
    pop, assume/finish/confirm/forget, requeue tiers, deletes, node
    churn, sweeps."""
    from k8s_scheduler_tpu.models import MakeNode, MakePod

    clock.advance(rng.random() * 0.05)
    roll = rng.randrange(12)
    if roll <= 2:  # intake (weighted: arrivals dominate)
        # deterministic names: the delete/update arms below must be able
        # to hit REAL uids, or those replay paths go untested (a re-add
        # of a restored round's uid is just an informer re-add)
        pod = MakePod(f"p{rng.randrange(max(2 * i, 1))}").req(
            {"cpu": str(1 + rng.randrange(4))}
        ).obj()
        if rng.random() < 0.2:
            pod.spec.priority = rng.randrange(10)
        q.add(pod)
    elif roll == 3:
        c.add_node(
            MakeNode(f"n{rng.randrange(8)}").capacity({"cpu": "64"}).obj()
        )
    elif roll == 4:  # a scheduling cycle: pop + split outcomes
        pods = q.pop_ready()
        for j, p in enumerate(pods):
            k = rng.randrange(4)
            if k == 0:
                try:
                    c.assume(p, f"n{rng.randrange(8)}")
                except ValueError:
                    continue
                c.finish_binding(p.uid)
                if rng.random() < 0.5:
                    c.confirm(p.uid)
            elif k == 1:
                q.requeue_backoff(p)
            elif k == 2:
                q.requeue_unschedulable(
                    p, reasons=rng.choice(
                        [("NodeResourcesFit",), ("NodeAffinity",), ()]
                    ),
                )
            # k == 3: dropped on the floor (stays only in-flight)
    elif roll == 5:
        q.flush_backoff()
    elif roll == 6:
        q.move_all_to_active_or_backoff(
            rng.choice(["NodeAdd", "PodDelete", "NodeUpdate"])
        )
    elif roll == 7:
        q.flush_unschedulable_timeout()
    elif roll == 8:
        for p, n in c.cleanup_expired():
            q.requeue_backoff(p, event="AssumeExpired")
    elif roll == 9:
        uid = f"default/p{rng.randrange(max(2 * i, 1))}"
        q.delete(uid)
        if rng.random() < 0.5:
            c.remove_pod(uid)
    elif roll == 10:
        c.remove_node(f"n{rng.randrange(8)}")
    else:
        # spec update hitting a REAL uid lands in whatever tier (or the
        # in-flight set) the pod currently occupies; a miss exercises
        # the fresh-add fallback
        q.update(
            MakePod(f"p{rng.randrange(max(2 * i, 1))}").req(
                {"cpu": "2"}
            ).obj()
        )


# ---------------------------------------------------------------------------
# child: the active
# ---------------------------------------------------------------------------


# every public mutator of each object — the wrapped set must cover
# everything apply_random_op touches, and none of these call each other
# (internal helpers are underscore-named and unwrapped)
_Q_MUTATORS = (
    "add", "update", "delete", "pop_ready", "requeue_unschedulable",
    "requeue_backoff", "flush_backoff", "flush_unschedulable_timeout",
    "move_all_to_active_or_backoff", "recover_in_flight",
)
_C_MUTATORS = (
    "add_node", "update_node", "remove_node", "add_pod", "remove_pod",
    "assume", "finish_binding", "confirm", "forget", "cleanup_expired",
)


def run_child(state_dir: str, seed: int, ops: int, digest_log: str,
              hold: bool) -> int:
    from k8s_scheduler_tpu.state import DurableState, state_digest

    clock = Clock()
    q, c = make_pair(clock)
    st = DurableState(state_dir, snapshot_interval_seconds=0)
    st.attach(q, c)
    # test-only determinism knob: drain the journal ONLY at flush()
    # barriers (flush notifies past the poll), so no record can become
    # durable before its digest line below is already fsync'd — every
    # restorable boundary is guaranteed to be logged
    st.journal._poll_s = 60.0
    rng = random.Random(seed)
    f = open(digest_log, "a")

    def log_line(kind: str, idx: int, dig: str) -> None:
        f.write(f"{kind} {idx} {dig}\n")
        f.flush()
        os.fsync(f.fileno())

    # digest after EVERY public mutation, not every apply_random_op
    # step: one mutation == at most one journal record, so a SIGKILL
    # landing mid-step (after the pop persisted, before the assumes)
    # still restores onto a logged boundary — the invariant is
    # record-granular, matching what the journal can actually lose
    counter = {"i": 0}

    def _wrap(obj, name):
        orig = getattr(obj, name)

        def wrapped(*a, **k):
            r = orig(*a, **k)
            counter["i"] += 1
            log_line("op", counter["i"], state_digest(q, c))
            return r

        setattr(obj, name, wrapped)

    for name in _Q_MUTATORS:
        _wrap(q, name)
    for name in _C_MUTATORS:
        _wrap(c, name)

    log_line("start", 0, state_digest(q, c))
    # the takeover step a real standby performs (Scheduler ctor):
    # requeue pods the dead leader had in flight — wrapped above, so
    # the post-recovery state is a logged (and journaled) boundary
    q.recover_in_flight()
    for i in range(1, ops + 1):
        apply_random_op(rng, clock, q, c, i)
        if i % FLUSH_EVERY == 0:
            st.journal.flush()
            log_line("flushed", counter["i"], state_digest(q, c))
        # occasional snapshot compaction mid-stream (exercises the
        # cut/prune path under kills)
        if i % 97 == 0:
            st.snapshot()
    st.journal.flush()
    log_line("done", counter["i"], state_digest(q, c))
    if hold:
        # fast-test mode: quiesce so the parent's SIGKILL lands at a
        # known boundary ("died mid-cycle while idle")
        while True:
            time.sleep(0.2)
    return 0


# ---------------------------------------------------------------------------
# parent: the standby
# ---------------------------------------------------------------------------


def read_digest_log(path: str):
    """(digests_by_index, last_flushed_index). Tolerates a torn final
    line — the child may die mid-write."""
    digests: dict[int, str] = {}
    flushed = 0
    try:
        with open(path) as f:
            for line in f:
                parts = line.strip().split()
                if len(parts) != 3 or len(parts[2]) != 64:
                    continue  # torn tail
                kind, idx, dig = parts[0], int(parts[1]), parts[2]
                digests[idx] = dig
                if kind in ("flushed", "done"):
                    flushed = max(flushed, idx)
    except FileNotFoundError:
        pass
    return digests, flushed


def restore_and_check(state_dir: str, digest_log: str) -> dict:
    from k8s_scheduler_tpu.state import DurableState, state_digest

    clock = Clock()
    q, c = make_pair(clock)
    st = DurableState(state_dir, snapshot_interval_seconds=0)
    stats = st.restore_into(q, c)
    dig = state_digest(q, c)
    digests, flushed = read_digest_log(digest_log)
    if dig not in digests.values():
        raise AssertionError(
            f"restored digest {dig[:12]}... matches NO op boundary the "
            f"active recorded ({len(digests)} boundaries) — state was "
            "lost, duplicated, or partially applied"
        )
    boundary = max(i for i, d in digests.items() if d == dig)
    if flushed and boundary < flushed:
        raise AssertionError(
            f"restore landed at op {boundary} but the active had flushed "
            f"through op {flushed} — acknowledged-durable records were lost"
        )
    st.journal.close()
    return {
        "boundary": boundary,
        "flushed_watermark": flushed,
        "replayed": stats["records_replayed"],
        "snapshot": stats["snapshot"],
        "digest": dig[:12],
    }


def soak(state_dir: str, rounds: int = 5, ops: int = 400,
         seed: int = 0, verbose: bool = True) -> list[dict]:
    """The soak loop: child mutates+journals, parent SIGKILLs at a
    random moment, standby restores, invariants checked; the next round
    continues from the restored state dir."""
    results = []
    digest_log = os.path.join(state_dir, "digests.txt")
    for r in range(rounds):
        # fresh digest log per round: digests are only comparable
        # within one child's lifetime (the stream continues from the
        # restored state, re-logged from its own boundary 0)
        if os.path.exists(digest_log):
            os.unlink(digest_log)
        child = subprocess.Popen(
            [
                sys.executable, os.path.abspath(__file__),
                "--child", "--state-dir", state_dir,
                "--seed", str(seed + r), "--ops", str(ops),
                "--digest-log", digest_log,
            ],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        # the child pays several seconds of interpreter/jax import
        # before its first op — wait for the digest log's first line so
        # the kill lands inside the mutation stream, then at a random
        # point of the child's useful life
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if os.path.exists(digest_log) and os.path.getsize(digest_log):
                break
            if child.poll() is not None:
                raise RuntimeError(
                    f"soak child exited early (rc={child.returncode})"
                )
            time.sleep(0.02)
        time.sleep(random.Random(seed + r).random() * 1.2)
        child.send_signal(signal.SIGKILL)
        child.wait()
        res = restore_and_check(state_dir, digest_log)
        res["round"] = r
        results.append(res)
        if verbose:
            print(json.dumps(res), flush=True)
    return results


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--state-dir", default="")
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--ops", type=int, default=400)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--child", action="store_true", help="internal")
    ap.add_argument("--digest-log", default="")
    ap.add_argument("--hold", action="store_true",
                    help="child idles after finishing (internal)")
    args = ap.parse_args()
    if args.child:
        return run_child(
            args.state_dir, args.seed, args.ops,
            args.digest_log or os.path.join(args.state_dir, "digests.txt"),
            args.hold,
        )
    state_dir = args.state_dir
    if not state_dir:
        import tempfile

        state_dir = tempfile.mkdtemp(prefix="soak-failover-")
        print(f"state dir: {state_dir}", flush=True)
    results = soak(state_dir, rounds=args.rounds, ops=args.ops,
                   seed=args.seed)
    exact = sum(1 for r in results if r["boundary"] > 0)
    print(
        f"soak_failover: {len(results)} kills survived, "
        f"{exact} with non-trivial restored state — no lost or "
        "duplicated pods",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
