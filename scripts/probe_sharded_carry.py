"""GSPMD probe: compile the FULL carry cycle with the carry state
sharded over an 8-device virtual CPU mesh and report (a) whether the
big [P,N] tensors stay partitioned, (b) every collective XLA inserted,
with shapes — the evidence VERDICT r3 item 2 asks for, and the
decision input for GSPMD-vs-shard_map.

Run:  python scripts/probe_sharded_carry.py [P N]
"""

import re
import sys

sys.path.insert(0, ".")

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from k8s_scheduler_tpu.core import (
    build_packed_cycle_carry_fn,
    build_stable_state_fn,
)
from k8s_scheduler_tpu.core.cycle import CarryKeeper
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.parallel.mesh import make_mesh
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods


def main():
    P = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    N = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    mesh = make_mesh(jax.devices()[:8], nodes_axis=1)
    enc = SnapshotEncoder(pad_pods=P, pad_nodes=N)
    nodes = make_cluster(max(8, N // 2), taint_fraction=0.2,
                         cpu_choices=(2, 4))
    pods = make_pods(
        max(16, P // 2), seed=3, affinity_fraction=0.2,
        anti_affinity_fraction=0.2, spread_fraction=0.2,
        selector_fraction=0.3, toleration_fraction=0.3,
        priorities=(0, 10), num_apps=8,
    )
    w, b, spec, snap, dirty = enc.encode_packed(nodes, pods)
    w = jax.device_put(np.asarray(w))
    b = jax.device_put(np.asarray(b))
    stable = build_stable_state_fn(spec)(w, b)
    keeper = CarryKeeper(spec)
    carry = keeper.ci(w, b, stable)

    # shard the carry: sbase [P, N] on pods, matched-pending [S, P] on
    # its pod axis; packed buffers + stable precomputes replicated
    carry_sh = {
        "sbase": jax.device_put(
            carry["sbase"], NamedSharding(mesh, PartitionSpec("pods", None))
        ),
        "mp": jax.device_put(
            carry["mp"], NamedSharding(mesh, PartitionSpec(None, "pods"))
        ),
    }
    rep = NamedSharding(mesh, PartitionSpec())
    w_r = jax.device_put(np.asarray(w), rep)
    b_r = jax.device_put(np.asarray(b), rep)
    stable_r = {k: jax.device_put(v, rep) for k, v in stable.items()}

    cyc = build_packed_cycle_carry_fn(spec)
    comp = cyc.lower(w_r, b_r, stable_r, carry_sh).compile()
    hlo = comp.as_text()

    # lazy (.*?) so TUPLE result types (async '-start' pairs, variadic
    # collectives) match too — '(f32[a,b], f32[c,d]) all-gather-start('
    # has a space inside the result type
    colls = re.findall(
        r"^\s*\S+ = (.*?) ((?:all-reduce|all-gather|reduce-scatter|"
        r"all-to-all|collective-permute)(?:-start)?)\(", hlo, re.M)
    from collections import Counter

    hist = Counter((op, shape) for shape, op in colls)
    total_bytes = 0
    max_elems = 0
    claim_sort_ags = 0
    print(f"P={P} N={N} collectives={len(colls)}")
    for (op, shape), n in sorted(hist.items(), key=lambda kv: -kv[1]):
        # per-bracket-group product, max over groups (tuple shapes have
        # several); NOT a flat digit scan — the '{0}' layout suffix would
        # zero the product and trivially pass the payload assertions
        elems = max(
            (
                int(np.prod([int(x) for x in g.split(",")]))
                for g in re.findall(r"\[([\d,]+)\]", shape)
            ),
            default=0,
        )
        bytes_ = elems * (2 if "bf16" in shape else 4)
        total_bytes += n * bytes_
        max_elems = max(max_elems, elems)
        if op.startswith("all-gather") and f"[{P}," in shape:
            # the per-pass claim sort's replicated tiny [P, k] gathers —
            # linear in P, watched because they are the one P-scaling
            # collective left (VERDICT r4 weak #5)
            claim_sort_ags += n
        print(f"  {n:3d} x {op:20s} {shape}  (~{bytes_/1e3:.1f} KB each)")
    print(f"approx collective payload total: {total_bytes/1e6:.2f} MB")
    print(f"max single-collective payload: {max_elems} elems "
          f"({max_elems * 4 / 1e6:.2f} MB at f32)")
    print(f"P-scaling claim-sort all-gathers (s32[{P},k]-class): "
          f"{claim_sort_ags}")

    # did the big tensors stay partitioned? look for full-size [P,N]
    # parameters/fusions vs [P/8, N]
    full = hlo.count(f"f32[{P},{N}]")
    part = hlo.count(f"f32[{P//8},{N}]")
    print(f"f32[{P},{N}] occurrences (replicated-size): {full}")
    print(f"f32[{P//8},{N}] occurrences (partitioned-size): {part}")
    # the defining bounds (asserted, not just printed): nothing moves the
    # [P,N] static base, and no collective exceeds a [B,N]-round payload
    assert full == 0 or max_elems < P * N, (
        f"a collective moves ~[P,N]: max {max_elems} elems"
    )
    bound = 2 * max(1280 * N, 64 * N)  # [B,N] round all-reduce class
    assert max_elems <= bound, (
        f"collective payload {max_elems} exceeds the [B,N] bound {bound}"
    )

    if os.environ.get("PROBE_COMPILE_ONLY") == "1":
        print("compile-only audit PASSED (payload bounds asserted)")
        return
    out = cyc(w_r, b_r, stable_r, carry_sh)
    a_sh = np.asarray(out.assignment)
    out2 = cyc(w, b, stable, carry)
    a_rep = np.asarray(out2.assignment)
    print("sharded == unsharded:", bool((a_sh == a_rep).all()),
          f"placed={int((a_rep >= 0).sum())}")


if __name__ == "__main__":
    main()
