"""Print the rounds-engine acceptance history at a given config.

Run:  python scripts/probe_rounds4.py [cfg]   (add CPU=1 for cpu backend)
"""

import os
import sys
import time

sys.path.insert(0, ".")

if os.environ.get("CPU") == "1":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

from k8s_scheduler_tpu.utils.compilation_cache import enable_compilation_cache

enable_compilation_cache()

from bench_suite import make_config_base, make_config_workload, CONFIG_SHAPES, _pad
from k8s_scheduler_tpu.core import build_cycle_fn
from k8s_scheduler_tpu.models import SnapshotEncoder


def main():
    cfg = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    P_real, N_real = CONFIG_SHAPES[cfg]
    enc = SnapshotEncoder(pad_pods=_pad(P_real), pad_nodes=_pad(N_real))
    base_nodes, base_existing = make_config_base(cfg)
    _n, pods, _e, groups = make_config_workload(cfg, seed=1000)
    snap = enc.encode(base_nodes, pods, base_existing, groups)

    cycle = build_cycle_fn(commit_mode="rounds")
    out = cycle(snap)
    np.asarray(out.assignment)
    t0 = time.perf_counter()
    out = cycle(snap)
    np.asarray(out.assignment)
    print(f"cycle: {(time.perf_counter()-t0)*1e3:.1f} ms")
    hist = np.asarray(out.accepted_per_round)
    used = int(np.asarray(out.rounds_used))
    print("rounds_used:", used)
    print("accepted_per_round:", hist[:used].tolist())
    print("unschedulable:", int(np.asarray(out.unschedulable).sum()),
          "gang_dropped:", int(np.asarray(out.gang_dropped).sum()))
    diag = np.asarray(out.diag_per_round)[:used]
    print("per-round (live, cap_rej, guard_rej):")
    for r in range(used):
        print(f"  r{r}: {diag[r].tolist()}")


if __name__ == "__main__":
    main()
