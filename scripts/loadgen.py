#!/usr/bin/env python
"""Open-loop load generator for the submission front door (ISSUE 14).

Arrival-rate-driven, never closed-loop: submission i is DUE at
t0 + i/rate regardless of how fast acks or binds come back, so an
overloaded scheduler actually overloads (and must shed) instead of
silently throttling the generator. Rates are pods/minute to match the
10k-1M pods/min ROADMAP target.

Two modes:

- **inproc** (default) — spins the whole front door in this process on
  `bench_suite.front_door_drive` (the same harness bench config 9 and
  the soak_chaos overload phase use): exact per-pod submit->bind
  latency from the binder's own timestamps, BENCH-diffable JSON out.

      JAX_PLATFORMS=cpu python scripts/loadgen.py --rate 30000 --duration 10

- **grpc** — drives a LIVE scheduler's Submit RPC (started with
  `python -m k8s_scheduler_tpu --submit-addr ...`): client-side ack
  latency + shed accounting, optional `--acked-log` journal of every
  acked uid (fsynced per batch) so a kill -9 failover harness can
  assert zero lost acked pods against the restored state. Server-side
  submit->bind quantiles ride the `submit_bind` phase gauges on
  /metrics and /debug/anomalies.

      python scripts/loadgen.py --mode grpc --addr 127.0.0.1:50052 \\
          --rate 60000 --duration 30 --nodes 16 --acked-log /tmp/acked
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the ONE percentile implementation (bench_suite's module level is
# stdlib-only): the load tool and the bench must never disagree on
# quantile indexing
from bench_suite import _percentile as _pctl  # noqa: E402


def run_inproc(args) -> dict:
    import bench_suite

    rate_pps = args.rate / 60.0
    d = bench_suite.front_door_drive(
        duration_s=args.duration,
        rate_pps=rate_pps,
        queue_depth=args.queue_depth,
        n_nodes=args.nodes,
        batch=args.batch,
        state_dir=args.state_dir,
        name_prefix="lg",
    )
    bind_ms = sorted(
        (t - d["acked"][u]) * 1e3
        for u, (_c, t) in d["binds"].items()
        if u in d["acked"]
    )
    ack_ms = [v * 1e3 for v in d["ack_lat_s"]]
    total = d["accepted"] + d["shed"]
    out = {
        "config": 9,
        "name": "front_door",
        "mode": "inproc",
        "rate_pods_per_min": args.rate,
        "duration_s": args.duration,
        "accepted": d["accepted"],
        "shed": d["shed"],
        "shed_rate": round(d["shed"] / max(total, 1), 4),
        "scheduled": len(d["binds"]),
        "duplicate_binds": d["duplicate_binds"],
        "lost": d["lost"],
        "max_queue_depth": d["max_depth"],
        "bind_rate_pps": round(d["bind_rate_pps"], 1),
        "submit_ack_p50_ms": round(_pctl(ack_ms, 50), 3),
        "submit_ack_p99_ms": round(_pctl(ack_ms, 99), 3),
        "submit_bind_p50_ms": round(_pctl(bind_ms, 50), 3),
        "submit_bind_p99_ms": round(_pctl(bind_ms, 99), 3),
        "drained": d["drained"],
        "durable": bool(args.state_dir),
    }
    if d["state"] is not None:
        d["state"].seal()
    return out


def _tenant_picker(ids: list, dist: str, seed: int):
    """Per-batch tenant selection: `roundrobin` exercises every virtual
    cluster evenly (the packing/fairness smoke), `zipf` concentrates
    load on a few hot tenants (rank-weighted 1/r) — the shape that
    actually trips per-tenant quota and weighted-fair sheds."""
    if dist == "roundrobin":
        import itertools

        it = itertools.cycle(ids)
        return lambda: next(it)
    import random

    rng = random.Random(seed)
    weights = [1.0 / (r + 1) for r in range(len(ids))]
    return lambda: rng.choices(ids, weights)[0]


def run_tenants(args) -> dict:
    """Multi-tenant in-proc mode (--tenants N): the open-loop generator
    in front of TenantFrontHost + AdmissionController + the arena
    packer. A batch carries ONE tenant (its pods' namespace); the serve
    side runs an arena cycle between arrivals, so the output reports
    both admission outcomes (quota/fair sheds per tenant) and packing
    efficiency (dispatches vs tenants folded, builds after warmup)."""
    from k8s_scheduler_tpu.service.admission import AdmissionController
    from k8s_scheduler_tpu.tenancy import TenantFrontHost, TenantRegistry
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    ids = [f"vc-{i:03d}" for i in range(args.tenants)]
    reg = TenantRegistry()
    host = TenantFrontHost(reg)
    for tid in ids:
        reg.create(tid, quota=args.tenant_quota)
        # same seed per tenant on purpose: identical node shapes keep
        # the fleet in one spec bucket (the headline packing regime)
        for nd in make_cluster(args.nodes_per_tenant, seed=7):
            nd.metadata.namespace = tid
            nd.metadata.uid = f"{tid}/{nd.metadata.name}"
            host.on_node_add(nd)
    adm = AdmissionController(
        host, queue_depth=args.queue_depth or None, tenants=reg,
    )
    pick = _tenant_picker(ids, args.tenant_dist, args.seed)

    rate_pps = args.rate / 60.0
    interval = args.batch / rate_pps
    n_batches = max(int(args.duration / interval), 1)
    ack_ms: list[float] = []
    accepted = shed = invalid = 0
    shed_by: dict[str, int] = {}
    t0 = time.perf_counter()
    for i in range(n_batches):
        due = t0 + i * interval
        now = time.perf_counter()
        if now < due:
            time.sleep(due - now)
        tid = pick()
        pods = make_pods(
            args.batch, seed=args.seed + i,
            name_prefix=f"{args.prefix}{i}-",
        )
        for p in pods:
            p.metadata.namespace = tid
            p.metadata.uid = f"{tid}/{p.metadata.name}"
        t_sub = time.perf_counter()
        res = adm.submit(pods)
        ack_ms.append((time.perf_counter() - t_sub) * 1e3)
        accepted += res.accepted
        shed += res.shed
        invalid += len(res.invalid)
        if res.shed:
            shed_by[tid] = shed_by.get(tid, 0) + res.shed
        host.schedule_cycle()
    # drain: standing demand left by the open-loop window (stop once a
    # cycle binds nothing — what remains is capacity-starved, not queued)
    for _ in range(64):
        if host.schedule_cycle().bound == 0:
            break
    st = reg.status()
    arena = host.arena
    total = accepted + shed
    return {
        "config": 9,
        "name": "tenant_front_door",
        "mode": "inproc",
        "tenants": args.tenants,
        "tenant_dist": args.tenant_dist,
        "rate_pods_per_min": args.rate,
        "duration_s": args.duration,
        "accepted": accepted,
        "shed": shed,
        "invalid": invalid,
        "shed_rate": round(shed / max(total, 1), 4),
        "shed_tenants": len(shed_by),
        "bound": st["bound"],
        "pending": st["pending"],
        "arena_dispatches": arena.packer.dispatches,
        "arena_builds": arena.packer.builds,
        "tenants_packed": arena.packer.tenants_packed,
        "tenants_per_dispatch": round(
            arena.packer.tenants_packed
            / max(arena.packer.dispatches, 1), 2,
        ),
        "submit_ack_p50_ms": round(_pctl(ack_ms, 50), 3),
        "submit_ack_p99_ms": round(_pctl(ack_ms, 99), 3),
    }


def run_grpc(args) -> dict:
    import grpc

    from k8s_scheduler_tpu.service.client import SchedulerClient
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    client = SchedulerClient(args.addr)
    if args.nodes:
        client.node_churn(adds=make_cluster(args.nodes))
    log_f = open(args.acked_log, "a") if args.acked_log else None
    rate_pps = args.rate / 60.0
    interval = args.batch / rate_pps
    n_batches = max(int(args.duration / interval), 1)
    ack_ms: list[float] = []
    accepted = shed = 0
    retry_after: list[float] = []
    draining = False
    t0 = time.perf_counter()
    for i in range(n_batches):
        due = t0 + i * interval
        now = time.perf_counter()
        if now < due:
            time.sleep(due - now)
        pods = make_pods(
            args.batch, seed=args.seed + i,
            name_prefix=f"{args.prefix}{i}-",
        )
        t_sub = time.perf_counter()
        try:
            resp = client.submit(pods)
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED:
                shed += len(pods)
                for k, v in e.trailing_metadata() or ():
                    if k == "retry-after-ms":
                        retry_after.append(float(v))
                continue
            if e.code() == grpc.StatusCode.UNAVAILABLE:
                # server draining (shutdown) or killed mid-load: an
                # open-loop generator records it and stops — the acks
                # already on disk are the failover contract
                draining = True
                break
            raise
        ack_ms.append((time.perf_counter() - t_sub) * 1e3)
        accepted += resp.accepted
        if log_f is not None:
            # the acked-uid journal is the failover oracle: fsync per
            # batch so a parent that kill -9s BOTH of us still reads
            # every uid whose ack reached this client
            for p in pods:
                log_f.write(f"{p.uid} durable={resp.durable}\n")
            log_f.flush()
            os.fsync(log_f.fileno())
    total = accepted + shed
    out = {
        "config": 9,
        "name": "front_door",
        "mode": "grpc",
        "addr": args.addr,
        "rate_pods_per_min": args.rate,
        "duration_s": args.duration,
        "accepted": accepted,
        "shed": shed,
        "shed_rate": round(shed / max(total, 1), 4),
        "submit_ack_p50_ms": round(_pctl(ack_ms, 50), 3),
        "submit_ack_p99_ms": round(_pctl(ack_ms, 99), 3),
        "retry_after_ms_seen": sorted(set(retry_after)),
        "stopped_draining": draining,
    }
    if log_f is not None:
        log_f.close()
    client.close()
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("inproc", "grpc"), default="inproc")
    ap.add_argument(
        "--rate", type=float, default=30000.0,
        help="open-loop arrival rate, pods per MINUTE (default 30k)",
    )
    ap.add_argument("--duration", type=float, default=10.0,
                    help="open-loop window, seconds")
    ap.add_argument("--batch", type=int, default=8,
                    help="pods per Submit request")
    ap.add_argument("--nodes", type=int, default=16,
                    help="nodes to create (grpc: pushed via NodeChurn)")
    ap.add_argument("--queue-depth", type=int, default=0,
                    help="inproc: admission bound (0 = unbounded)")
    ap.add_argument("--state-dir", default="",
                    help="inproc: durable state dir (WAL-before-ack on)")
    ap.add_argument("--addr", default="127.0.0.1:50052",
                    help="grpc: the front door's --submit-addr")
    ap.add_argument("--acked-log", default="",
                    help="grpc: append every acked uid here (fsynced "
                    "per batch; the kill -9 failover oracle)")
    ap.add_argument(
        "--tenants", type=int, default=0,
        help="inproc: drive N virtual clusters through the tenant "
        "arena front door (0 = single-cluster bench_suite path)",
    )
    ap.add_argument(
        "--tenant-dist", choices=("roundrobin", "zipf"),
        default="roundrobin",
        help="per-batch tenant selection: even coverage vs hot-tenant "
        "skew (zipf is what trips quota/fair-share sheds)",
    )
    ap.add_argument("--nodes-per-tenant", type=int, default=2,
                    help="tenant mode: nodes per virtual cluster")
    ap.add_argument("--tenant-quota", type=int, default=0,
                    help="tenant mode: per-tenant accepted-unbound "
                    "ceiling (0 = unlimited)")
    ap.add_argument("--seed", type=int, default=50_000)
    ap.add_argument("--prefix", default="lg")
    args = ap.parse_args()
    if args.mode == "inproc" and args.tenants > 0:
        out = run_tenants(args)
    else:
        out = run_inproc(args) if args.mode == "inproc" else run_grpc(args)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
