"""Randomized differential soak: many random clusters through the device
engines vs the sequential oracle.

- scan engine: exact assignment match against oracle.schedule.
- rounds engine: validity invariants (oracle.validate_rounds_assignment),
  a placement-quality floor (rounds must place >= 90% of what the
  sequential oracle places), and a SCORE-REGRET bound: replaying the
  rounds assignment through the oracle's sequential state, the average
  deficit of the chosen node's score vs the best feasible score must stay
  under REGRET_BOUND (the engine's integer rounding + hash tie-break make
  some divergence by design — this measures its magnitude instead of only
  bounding placement count).
- preemption: whenever the scan pass leaves unschedulable pods, the
  what-if kernel's nominations/victims must match
  oracle.schedule_with_preemption exactly (covers eviction freeing
  anti-affinity/ports/spread, VERDICT r2 item 3).

Run:  python scripts/soak_differential.py [minutes]
"""

import sys
import time

sys.path.insert(0, ".")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from k8s_scheduler_tpu import oracle
from k8s_scheduler_tpu.core import build_cycle_fn, build_preemption_fn
from k8s_scheduler_tpu.ops import preemption as preemption_ops
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

REGRET_BOUND = 60.0  # avg per-placed-pod score deficit (scale: ~1000)


def rounds_regret(nodes, pods, existing, a_r) -> tuple[float, int]:
    """Average oracle-score deficit of the rounds engine's choices,
    replayed in rank order on the oracle's sequential state."""
    w = oracle.OracleWeights()
    state = oracle.OracleState.build(nodes, existing)
    total, n = 0.0, 0
    for pi in oracle.queue_order(pods):
        node = int(a_r[pi])
        if node < 0:
            continue
        pod = pods[pi]
        feasible = oracle.feasible_nodes(pod, state, oracle.DEFAULT_FILTERS)
        if node in feasible:
            cn = oracle._CrossNodeRaws.compute(pod, state, feasible, w)
            scores = {
                i: oracle._score_pod(pod, state, i, w, cn)
                for i in feasible
            }
            total += max(0.0, max(scores.values()) - scores[node])
            n += 1
        state.add(node, pod)
    return total / max(n, 1), n


def one_case(seed: int, scan_cycle, rounds_cycle, pre_fn, enc):
    rng = np.random.default_rng(seed)
    n_nodes = int(rng.integers(5, 40))
    n_pods = int(rng.integers(5, 120))
    nodes = make_cluster(
        n_nodes,
        taint_fraction=float(rng.uniform(0, 0.4)),
        cpu_choices=(2, 4, 8),
    )
    pods = make_pods(
        n_pods,
        seed=seed,
        affinity_fraction=float(rng.uniform(0, 0.4)),
        anti_affinity_fraction=float(rng.uniform(0, 0.4)),
        spread_fraction=float(rng.uniform(0, 0.3)),
        selector_fraction=float(rng.uniform(0, 0.4)),
        toleration_fraction=float(rng.uniform(0, 0.4)),
        priorities=(0, 5, 10),
        num_apps=int(rng.integers(2, 12)),
    )
    # existing pods must FIT where they are placed (a real cluster's bound
    # pods passed admission) — small fixed requests, capped per node
    from k8s_scheduler_tpu.models import MakePod

    n_exist = int(rng.integers(0, 2 * n_nodes))
    existing = [
        (
            MakePod(f"run-{i}")
            .req({"cpu": "100m", "memory": "64Mi"})
            .labels({"app": f"app-{i % 8}"})
            .obj(),
            f"node-{i % n_nodes}",
        )
        for i in range(n_exist)
    ]
    snap = enc.encode(nodes, pods, existing)

    # scan vs oracle: exact
    out_s = scan_cycle(snap)
    a_s = np.asarray(out_s.assignment)[: len(pods)]
    want = [d.node_index for d in oracle.schedule(nodes, pods, existing)]
    got = [int(x) for x in a_s]
    if got != want:
        return f"seed {seed}: scan mismatch\n  got {got}\n  want {want}"

    # rounds: validity + quality floor
    out_r = rounds_cycle(snap)
    a_r = np.asarray(out_r.assignment)[: len(pods)]
    errs = oracle.validate_rounds_assignment(nodes, pods, a_r, existing)
    if errs:
        return f"seed {seed}: rounds violations: {errs[:3]}"
    placed_r = int((a_r >= 0).sum())
    placed_o = sum(1 for w in want if w is not None and w >= 0)
    if placed_o > 0 and placed_r < int(0.9 * placed_o):
        return (
            f"seed {seed}: rounds quality {placed_r}/{placed_o} "
            f"below 90% of sequential"
        )
    regret, n_scored = rounds_regret(nodes, pods, existing, a_r)
    one_case.regrets.append(regret)
    if n_scored >= 5 and regret > REGRET_BOUND:
        return (
            f"seed {seed}: rounds avg score regret {regret:.1f} over "
            f"{n_scored} pods exceeds {REGRET_BOUND}"
        )

    # preemption differential: kernel nominations/victims == oracle's
    if (a_s < 0).any():
        pre = pre_fn(snap, out_s)
        nom = np.asarray(pre.nominated)[: len(pods)]
        vic = np.asarray(pre.victims)[: len(existing)]
        _dec, opre = oracle.schedule_with_preemption(
            nodes, pods, existing
        )
        want_nom = np.full(len(pods), -1, np.int64)
        want_vic = np.zeros(max(len(existing), 1), bool)[: len(existing)]
        for o in opre:
            want_nom[o.pod_index] = o.node_index
            for e in o.victims:
                want_vic[e] = True
        if nom.tolist() != want_nom.tolist() or (
            vic.tolist() != want_vic.tolist()
        ):
            return (
                f"seed {seed}: preemption mismatch "
                f"nom={nom.tolist()} want={want_nom.tolist()} "
                f"vic={vic.tolist()} want={want_vic.tolist()}"
            )
    return None


one_case.regrets = []


def mid_case(seed: int, scan_cycle, rounds_cycle, pre_fn, enc):
    """MID-SIZE differential class (VERDICT r3 item 5): 500 pods x 100
    nodes with real preemption pressure (low-priority existing workload
    filling most capacity, high-priority pending) and static-PV
    contention — the window/bucket/overflow boundaries live between the
    toy range and config-4 scale. Same assertions as one_case."""
    import numpy as np

    from k8s_scheduler_tpu.models import MakePod
    from k8s_scheduler_tpu.models.api import (
        VOLUME_BINDING_WAIT,
        PersistentVolume,
        PersistentVolumeClaim,
        StorageClass,
    )

    rng = np.random.default_rng(seed)
    n_nodes, n_pods = 100, 500
    nodes = make_cluster(
        n_nodes, taint_fraction=0.15, cpu_choices=(4, 8)
    )
    # low-priority existing workload occupying most capacity: pending
    # high-priority pods must preempt, low-priority ones go unschedulable
    existing = [
        (
            MakePod(f"run-{i}")
            .req({"cpu": "1", "memory": "512Mi"})
            .labels({"app": f"app-{i % 16}"})
            .priority(0)
            .created(float(i))
            .obj(),
            f"node-{i % n_nodes}",
        )
        for i in range(3 * n_nodes)
    ]
    pods = make_pods(
        n_pods,
        seed=seed,
        affinity_fraction=0.2,
        anti_affinity_fraction=0.15,
        spread_fraction=0.15,
        selector_fraction=0.25,
        toleration_fraction=0.2,
        priorities=(0, 10, 100),
        num_apps=24,
    )
    # static-PV contention: fewer PVs than claimants of one WFC class
    classes = [
        StorageClass("local", VOLUME_BINDING_WAIT, provisioner=False)
    ]
    GiB = 2**30
    pvs = [
        PersistentVolume(f"pv-{v}", capacity=10 * GiB,
                         storage_class="local")
        for v in range(20)
    ]
    pvcs = [
        PersistentVolumeClaim(f"claim-{j}", storage_class="local",
                              request=5 * GiB)
        for j in range(40)
    ]
    pods = list(pods)
    vol_ids = rng.choice(n_pods, size=40, replace=False)
    for j, pi in enumerate(vol_ids):
        p = pods[pi]
        p.spec.volumes = tuple(p.spec.volumes) + (f"claim-{j}",)

    snap = enc.encode(nodes, pods, existing, pvcs=pvcs, pvs=pvs,
                      storage_classes=classes)

    out_s = scan_cycle(snap)
    a_s = np.asarray(out_s.assignment)[: len(pods)]
    want = [
        d.node_index
        for d in oracle.schedule(nodes, pods, existing, pvcs=pvcs,
                                 pvs=pvs, storage_classes=classes)
    ]
    got = [int(x) for x in a_s]
    if got != want:
        diff = [i for i, (g, w) in enumerate(zip(got, want)) if g != w]
        return (
            f"mid seed {seed}: scan mismatch at {diff[:6]} "
            f"got {[got[i] for i in diff[:6]]} "
            f"want {[want[i] for i in diff[:6]]}"
        )

    out_r = rounds_cycle(snap)
    a_r = np.asarray(out_r.assignment)[: len(pods)]
    errs = oracle.validate_rounds_assignment(
        nodes, pods, a_r, existing, pvcs=pvcs, pvs=pvs,
        storage_classes=classes,
    )
    if errs:
        return f"mid seed {seed}: rounds violations: {errs[:3]}"
    placed_r = int((a_r >= 0).sum())
    placed_o = sum(1 for w in want if w is not None and w >= 0)
    if placed_o > 0 and placed_r < int(0.9 * placed_o):
        return (
            f"mid seed {seed}: rounds quality {placed_r}/{placed_o} "
            f"below 90% of sequential"
        )
    regret, n_scored = rounds_regret(nodes, pods, existing, a_r)
    one_case.regrets.append(regret)
    if n_scored >= 5 and regret > REGRET_BOUND:
        return (
            f"mid seed {seed}: rounds avg score regret {regret:.1f} "
            f"over {n_scored} pods exceeds {REGRET_BOUND}"
        )

    if (a_s < 0).any():
        pre = pre_fn(snap, out_s)
        nom = np.asarray(pre.nominated)[: len(pods)]
        vic = np.asarray(pre.victims)[: len(existing)]
        _dec, opre = oracle.schedule_with_preemption(
            nodes, pods, existing, pvcs=pvcs, pvs=pvs,
            storage_classes=classes,
            budget=preemption_ops.DEFAULT_BUDGET,
            scan_budget=preemption_ops.DEFAULT_SCAN_BUDGET,
        )
        # PRODUCTION budgets on BOTH sides: the oracle mirrors the
        # kernel's prefilter cap and scan cap, so the comparison is
        # exact under budget truncation (~110 feasible preemptors, 64
        # scan slots at this scale)
        opre_k = opre
        want_nom = np.full(len(pods), -1, np.int64)
        want_vic = np.zeros(max(len(existing), 1), bool)[: len(existing)]
        for o in opre_k:
            want_nom[o.pod_index] = o.node_index
            for e in o.victims:
                want_vic[e] = True
        n_prem = int((want_nom >= 0).sum())
        if nom.tolist() != want_nom.tolist() or (
            vic.tolist() != want_vic.tolist()
        ):
            d = [i for i in range(len(pods)) if nom[i] != want_nom[i]]
            return (
                f"mid seed {seed}: preemption mismatch at pods {d[:6]} "
                f"({n_prem} oracle preemptors)"
            )
        print(f"  mid seed {seed}: ok ({n_prem} preemptors, "
              f"{placed_r}/{n_pods} placed)", flush=True)
    return None


def main():
    minutes = float(sys.argv[1]) if len(sys.argv) > 1 else 10.0
    scan_cycle = build_cycle_fn(commit_mode="scan")
    rounds_cycle = build_cycle_fn(commit_mode="rounds")
    pre_fn = build_preemption_fn()
    # mid-size cases exceed the production per-cycle nomination budget;
    # the oracle now carries the SAME budget model (prefilter cap 256 +
    # scan cap 64 over pristine-resource-feasible candidates), so the
    # comparison runs against the PRODUCTION kernel config (VERDICT r4
    # weak #6 closed: budget-truncation semantics are differential-
    # tested at 500x100, not just toy scale)
    # ONE encoder + fixed padding: interning dims stabilize after the first
    # few cases, so each engine compiles a handful of times, not per case
    enc = SnapshotEncoder(pad_pods=128, pad_nodes=64)
    enc_mid = SnapshotEncoder(pad_pods=512, pad_nodes=128)
    deadline = time.time() + minutes * 60
    seed = 10_000
    failures = 0
    mids = 0
    while time.time() < deadline:
        msg = one_case(seed, scan_cycle, rounds_cycle, pre_fn, enc)
        if msg:
            failures += 1
            print("FAIL:", msg, flush=True)
            if failures >= 5:
                break
        if (seed - 10_000) % 15 == 5:
            # a mid-size case (500x100, preemption + PV pressure) every
            # ~15 toy cases — the scale band the toy range cannot reach
            msg = mid_case(seed, scan_cycle, rounds_cycle, pre_fn,
                           enc_mid)
            mids += 1
            if msg:
                failures += 1
                print("FAIL:", msg, flush=True)
                if failures >= 5:
                    break
        seed += 1
        if (seed - 10_000) % 25 == 0:
            r = one_case.regrets
            print(
                f"  {seed - 10_000} cases, {failures} failures, "
                f"avg regret {np.mean(r):.2f} p95 "
                f"{np.percentile(r, 95):.2f}",
                flush=True,
            )
    r = one_case.regrets or [0.0]
    print(
        f"done: {seed - 10_000} cases ({mids} mid-size), "
        f"{failures} failures, "
        f"avg regret {np.mean(r):.2f} p95 {np.percentile(r, 95):.2f} "
        f"max {np.max(r):.2f} (bound {REGRET_BOUND})"
    )
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
