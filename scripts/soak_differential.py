"""Randomized differential soak: many random clusters through the device
engines vs the sequential oracle.

- scan engine: exact assignment match against oracle.schedule.
- rounds engine: validity invariants (oracle.validate_rounds_assignment)
  plus a placement-quality floor (rounds must place >= 90% of what the
  sequential oracle places — catches convergence regressions).

Run:  python scripts/soak_differential.py [minutes]
"""

import sys
import time

sys.path.insert(0, ".")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from k8s_scheduler_tpu import oracle
from k8s_scheduler_tpu.core import build_cycle_fn
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods


def one_case(seed: int, scan_cycle, rounds_cycle, enc):
    rng = np.random.default_rng(seed)
    n_nodes = int(rng.integers(5, 40))
    n_pods = int(rng.integers(5, 120))
    nodes = make_cluster(
        n_nodes,
        taint_fraction=float(rng.uniform(0, 0.4)),
        cpu_choices=(2, 4, 8),
    )
    pods = make_pods(
        n_pods,
        seed=seed,
        affinity_fraction=float(rng.uniform(0, 0.4)),
        anti_affinity_fraction=float(rng.uniform(0, 0.4)),
        spread_fraction=float(rng.uniform(0, 0.3)),
        selector_fraction=float(rng.uniform(0, 0.4)),
        toleration_fraction=float(rng.uniform(0, 0.4)),
        priorities=(0, 5, 10),
        num_apps=int(rng.integers(2, 12)),
    )
    # existing pods must FIT where they are placed (a real cluster's bound
    # pods passed admission) — small fixed requests, capped per node
    from k8s_scheduler_tpu.models import MakePod

    n_exist = int(rng.integers(0, 2 * n_nodes))
    existing = [
        (
            MakePod(f"run-{i}")
            .req({"cpu": "100m", "memory": "64Mi"})
            .labels({"app": f"app-{i % 8}"})
            .obj(),
            f"node-{i % n_nodes}",
        )
        for i in range(n_exist)
    ]
    snap = enc.encode(nodes, pods, existing)

    # scan vs oracle: exact
    out_s = scan_cycle(snap)
    a_s = np.asarray(out_s.assignment)[: len(pods)]
    want = [d.node_index for d in oracle.schedule(nodes, pods, existing)]
    got = [int(x) for x in a_s]
    if got != want:
        return f"seed {seed}: scan mismatch\n  got {got}\n  want {want}"

    # rounds: validity + quality floor
    out_r = rounds_cycle(snap)
    a_r = np.asarray(out_r.assignment)[: len(pods)]
    errs = oracle.validate_rounds_assignment(nodes, pods, a_r, existing)
    if errs:
        return f"seed {seed}: rounds violations: {errs[:3]}"
    placed_r = int((a_r >= 0).sum())
    placed_o = sum(1 for w in want if w is not None and w >= 0)
    if placed_o > 0 and placed_r < int(0.9 * placed_o):
        return (
            f"seed {seed}: rounds quality {placed_r}/{placed_o} "
            f"below 90% of sequential"
        )
    return None


def main():
    minutes = float(sys.argv[1]) if len(sys.argv) > 1 else 10.0
    scan_cycle = build_cycle_fn(commit_mode="scan")
    rounds_cycle = build_cycle_fn(commit_mode="rounds")
    # ONE encoder + fixed padding: interning dims stabilize after the first
    # few cases, so each engine compiles a handful of times, not per case
    enc = SnapshotEncoder(pad_pods=128, pad_nodes=64)
    deadline = time.time() + minutes * 60
    seed = 10_000
    failures = 0
    while time.time() < deadline:
        msg = one_case(seed, scan_cycle, rounds_cycle, enc)
        if msg:
            failures += 1
            print("FAIL:", msg, flush=True)
            if failures >= 5:
                break
        seed += 1
        if (seed - 10_000) % 25 == 0:
            print(f"  {seed - 10_000} cases, {failures} failures", flush=True)
    print(f"done: {seed - 10_000} cases, {failures} failures")
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
