"""Bisect the config-#4 compile blowup: time encode/lower/compile of the
affinity-enabled cycle at increasing pod counts.

Usage: JAX_PLATFORMS=cpu python scripts/compile_probe.py [P ...]
"""

from __future__ import annotations

import sys
import time

import jax

from k8s_scheduler_tpu.core.cycle import build_cycle_fn
from k8s_scheduler_tpu.models.encoding import SnapshotEncoder
from k8s_scheduler_tpu.utils import synth


def probe(P: int, N: int) -> None:
    import os

    nodes = synth.make_cluster(N, taint_fraction=0.1)
    pods = synth.make_pods(
        P,
        affinity_fraction=0.3,
        anti_affinity_fraction=0.2,
        spread_fraction=0.2,
        selector_fraction=0.3,
        toleration_fraction=0.1,
        priorities=(0, 0, 10, 100),
        num_apps=int(os.environ.get("NUM_APPS", "200")),
    )
    existing = []
    n_exist = int(os.environ.get("EXISTING", "0"))
    if n_exist:
        epods = synth.make_pods(
            n_exist,
            seed=7,
            name_prefix="run",
            affinity_fraction=0.3,
            anti_affinity_fraction=0.2,
            spread_fraction=0.2,
            num_apps=int(os.environ.get("NUM_APPS", "200")),
        )
        existing = [(p, f"node-{i % N}") for i, p in enumerate(epods)]
    enc = SnapshotEncoder()
    t0 = time.perf_counter()
    snap = enc.encode(nodes, pods, existing)
    t1 = time.perf_counter()
    shapes = {
        "P": snap.P, "N": snap.N, "E": snap.E,
        "S": snap.sel_exprs.shape[0],
        "MSE": snap.sel_exprs.shape[1],
        "D": snap.domain_key.shape[0],
        "Ex": snap.ex_key.shape[0],
        "MA": snap.pod_aff_terms.shape[1],
    }
    print(f"P={P} N={N} encode={t1-t0:.2f}s shapes={shapes}", flush=True)
    fn = build_cycle_fn()
    t2 = time.perf_counter()
    lowered = fn.lower(snap)
    t3 = time.perf_counter()
    compiled = lowered.compile()
    t4 = time.perf_counter()
    print(f"  lower={t3-t2:.2f}s compile={t4-t3:.2f}s", flush=True)
    t5 = time.perf_counter()
    out = compiled(snap)
    jax.block_until_ready(out.assignment)
    t6 = time.perf_counter()
    t7 = time.perf_counter()
    out = compiled(snap)
    jax.block_until_ready(out.assignment)
    t8 = time.perf_counter()
    print(f"  first_run={t6-t5:.3f}s second_run={t8-t7:.3f}s", flush=True)
    if os.environ.get("PREEMPT"):
        from k8s_scheduler_tpu.core.cycle import build_preemption_fn

        pf = build_preemption_fn()
        t9 = time.perf_counter()
        pl = pf.lower(snap, out)
        t10 = time.perf_counter()
        pc = pl.compile()
        t11 = time.perf_counter()
        pr = pc(snap, out)
        jax.block_until_ready(jax.tree_util.tree_leaves(pr))
        t12 = time.perf_counter()
        print(
            f"  preempt: lower={t10-t9:.2f}s compile={t11-t10:.2f}s "
            f"first_run={t12-t11:.3f}s", flush=True,
        )


if __name__ == "__main__":
    ps = [int(a) for a in sys.argv[1:]] or [1000, 2000]
    n = int(ps[-1] // 2) if len(ps) > 1 else 1000
    for p in ps:
        probe(p, N=int(sys.argv[-1]) if False else max(256, p // 2))
