#!/usr/bin/env python
"""Metric-inventory drift check — THIN SHIM.

The real check moved into the schedlint framework as the
INVENTORY-DRIFT pass (`k8s_scheduler_tpu/analysis/inventory.py`), which
also cross-checks config keys <-> CLI flags <-> the README tables. This
path keeps the historical entry point working:

    JAX_PLATFORMS=cpu python scripts/lint_metrics.py

and `tests/test_metrics.py` keeps importing `check_inventory` from
here. Prefer `python scripts/schedlint.py` (optionally
`--passes INVENTORY-DRIFT`) for the full surface.

The output also carries one machine-readable `schedlint-summary` JSON
row — per-pass new/suppressed/grandfathered finding counts over the
full tree — so bench/CI harnesses that already scrape this script can
diff lint posture across PRs without a second invocation.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from k8s_scheduler_tpu.analysis.inventory import (  # noqa: E402
    REQUIRED_FAMILIES,
    docstring_names,
    metric_inventory_problems,
    readme_names,
    registered_names,
)

__all__ = [
    "REQUIRED_FAMILIES",
    "check_inventory",
    "docstring_names",
    "readme_names",
    "registered_names",
    "schedlint_summary",
]


def check_inventory() -> list[str]:
    """Returns a list of human-readable drift complaints (empty = ok)."""
    return metric_inventory_problems(REPO)


def schedlint_summary() -> dict:
    """Per-pass finding counts over the full tree: {pass_name:
    {"findings": n, "suppressed": n, "grandfathered": n}} plus a
    "total" row. Codes map back to their owning pass through the
    registry, so a pass with zero findings still shows up (a silently
    skipped pass would read identically to a clean one otherwise)."""
    from k8s_scheduler_tpu.analysis import default_registry, run_lint

    registry = default_registry()
    owner: dict[str, str] = {}
    for name in registry.names():
        for code in registry.make(name).codes:
            owner[code] = name
    result = run_lint(REPO)
    rows = {
        name: {"findings": 0, "suppressed": 0, "grandfathered": 0}
        for name in registry.names()
    }
    for bucket, findings in (
        ("findings", result.findings),
        ("suppressed", result.suppressed),
        ("grandfathered", result.grandfathered),
    ):
        for f in findings:
            rows[owner[f.code]][bucket] += 1
    return {
        "files_scanned": result.files_scanned,
        "passes": rows,
        "total": {
            "findings": len(result.findings),
            "suppressed": len(result.suppressed),
            "grandfathered": len(result.grandfathered),
        },
    }


def main() -> int:
    import json

    problems = check_inventory()
    if problems:
        for p in problems:
            print(f"lint_metrics: {p}", file=sys.stderr)
        return 1
    print(f"lint_metrics: ok ({len(registered_names())} metric families "
          "documented in both surfaces)")
    print("schedlint-summary: "
          + json.dumps(schedlint_summary(), sort_keys=True))
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
