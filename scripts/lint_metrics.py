#!/usr/bin/env python
"""Metric-inventory drift check.

Every metric registered by `SchedulerMetrics` (metrics/metrics.py) must
be listed in BOTH documentation surfaces:

- the `metrics/metrics.py` module docstring (the in-code inventory), and
- the README "Observability" metric table;

and neither surface may name a metric that is no longer registered.
Dashboards are built from the docs — silent drift in either direction is
exactly the kind of rot this repo's PARITY/measurement-honesty rules
exist to prevent.

Runs standalone (exit 1 + a diff on drift):

    JAX_PLATFORMS=cpu python scripts/lint_metrics.py

and as a tier-1-adjacent test (tests/test_metrics.py imports
`check_inventory`). Counter families are normalized to their exposition
names (`*_total`); histogram/summary families are listed by their base
name (the `_bucket`/`_count`/`_sum` series are implied).
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_NAME_RE = re.compile(r"\bscheduler_[a-z0-9_]+\b")

# Families that MUST exist: the durable-state (journal/snapshot) and
# leader-election surfaces are operational contracts — dashboards and
# the failover runbook depend on them, so their silent removal from the
# registry is a lint failure even though the two-way doc check above
# would only notice if the docs were cleaned up in the same commit.
REQUIRED_FAMILIES = {
    "scheduler_journal_appends_total",
    "scheduler_journal_bytes_total",
    "scheduler_journal_fsync_seconds",
    "scheduler_journal_buffer_depth",
    "scheduler_journal_segments",
    "scheduler_snapshot_writes_total",
    "scheduler_snapshot_duration_seconds",
    "scheduler_snapshot_last_bytes",
    "scheduler_snapshot_last_restore_records",
    "scheduler_snapshot_last_restore_seconds",
    "scheduler_leader_state",
    "scheduler_leader_lease_age_seconds",
}


def registered_names() -> set[str]:
    """Metric families registered on a fresh SchedulerMetrics, in
    Prometheus exposition naming (counters get their _total suffix)."""
    from k8s_scheduler_tpu.metrics import SchedulerMetrics

    names: set[str] = set()
    for fam in SchedulerMetrics().registry.collect():
        name = fam.name
        if fam.type == "counter":
            name += "_total"
        names.add(name)
    return names


def _strip_series_suffixes(names: set[str], families: set[str]) -> set[str]:
    """Collapse `foo_bucket`/`foo_count`/`foo_sum`/`foo_created` doc
    mentions onto their family name so prose quoting a specific series
    does not count as a phantom metric."""
    out = set()
    for n in names:
        base = re.sub(r"_(bucket|count|sum|created)$", "", n)
        out.add(base if base in families and n not in families else n)
    return out


def docstring_names() -> set[str]:
    import k8s_scheduler_tpu.metrics.metrics as mod

    return set(_NAME_RE.findall(mod.__doc__ or ""))


def readme_names() -> set[str]:
    path = os.path.join(REPO, "README.md")
    with open(path) as f:
        text = f.read()
    m = re.search(r"^## Observability\b(.*?)(?=^## |\Z)", text,
                  re.M | re.S)
    if m is None:
        return set()
    return set(_NAME_RE.findall(m.group(1)))


def check_inventory() -> list[str]:
    """Returns a list of human-readable drift complaints (empty = ok)."""
    reg = registered_names()
    problems: list[str] = []
    gone = sorted(REQUIRED_FAMILIES - reg)
    if gone:
        problems.append(
            "required durable-state/leader metric families no longer "
            f"registered: {gone}"
        )
    for surface, found in (
        ("metrics/metrics.py docstring", docstring_names()),
        ('README "## Observability" section', readme_names()),
    ):
        found = _strip_series_suffixes(found, reg)
        missing = sorted(reg - found)
        phantom = sorted(found - reg)
        if not found:
            problems.append(f"{surface}: no metric names found at all")
        if missing:
            problems.append(
                f"{surface}: registered but undocumented: {missing}"
            )
        if phantom:
            problems.append(
                f"{surface}: documented but not registered: {phantom}"
            )
    return problems


def main() -> int:
    problems = check_inventory()
    if problems:
        for p in problems:
            print(f"lint_metrics: {p}", file=sys.stderr)
        return 1
    print(f"lint_metrics: ok ({len(registered_names())} metric families "
          "documented in both surfaces)")
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
