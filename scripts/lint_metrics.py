#!/usr/bin/env python
"""Metric-inventory drift check — THIN SHIM.

The real check moved into the schedlint framework as the
INVENTORY-DRIFT pass (`k8s_scheduler_tpu/analysis/inventory.py`), which
also cross-checks config keys <-> CLI flags <-> the README tables. This
path keeps the historical entry point working:

    JAX_PLATFORMS=cpu python scripts/lint_metrics.py

and `tests/test_metrics.py` keeps importing `check_inventory` from
here. Prefer `python scripts/schedlint.py` (optionally
`--passes INVENTORY-DRIFT`) for the full surface.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from k8s_scheduler_tpu.analysis.inventory import (  # noqa: E402
    REQUIRED_FAMILIES,
    docstring_names,
    metric_inventory_problems,
    readme_names,
    registered_names,
)

__all__ = [
    "REQUIRED_FAMILIES",
    "check_inventory",
    "docstring_names",
    "readme_names",
    "registered_names",
]


def check_inventory() -> list[str]:
    """Returns a list of human-readable drift complaints (empty = ok)."""
    return metric_inventory_problems(REPO)


def main() -> int:
    problems = check_inventory()
    if problems:
        for p in problems:
            print(f"lint_metrics: {p}", file=sys.stderr)
        return 1
    print(f"lint_metrics: ok ({len(registered_names())} metric families "
          "documented in both surfaces)")
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
