"""Ablation profile of the config-#4 cycle: where do the milliseconds go?

Times (forced-sync, best of 3) each stage of the production program in
isolation on the real device:
  - encode (host)
  - full cycle (rounds engine)
  - cycle with max_rounds=1 (round-1 only)
  - static masks/scores only
  - dyn_batched over the full [P, N] once
  - final attribution pass proxy (same dyn_batched)
  - preemption pass
Run:  python scripts/profile_cycle4.py [cfg]
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import numpy as np

from bench_suite import make_config_base, make_config_workload, CONFIG_SHAPES, _pad
from k8s_scheduler_tpu.core import build_cycle_fn, build_preemption_fn
from k8s_scheduler_tpu.framework.interfaces import CycleContext
from k8s_scheduler_tpu.framework.runtime import Framework
from k8s_scheduler_tpu.models import SnapshotEncoder


def timed(label, fn, *args, n=3):
    outs = None
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        outs = fn(*args)
        jax.tree_util.tree_map(
            lambda x: np.asarray(x) if hasattr(x, "shape") else x, outs
        )
        best = min(best, time.perf_counter() - t0)
    print(f"{label:40s} {best*1e3:9.1f} ms")
    return outs


def main():
    cfg = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    P_real, N_real = CONFIG_SHAPES[cfg]
    enc = SnapshotEncoder(pad_pods=_pad(P_real), pad_nodes=_pad(N_real))
    base_nodes, base_existing = make_config_base(cfg)
    _n, pods, _e, groups = make_config_workload(cfg, seed=1000)

    t0 = time.perf_counter()
    snap = enc.encode(base_nodes, pods, base_existing, groups)
    print(f"{'encode (cold)':40s} {(time.perf_counter()-t0)*1e3:9.1f} ms")
    t0 = time.perf_counter()
    snap = enc.encode(base_nodes, pods, base_existing, groups)
    print(f"{'encode (warm rows)':40s} {(time.perf_counter()-t0)*1e3:9.1f} ms")

    fw = Framework.from_config()

    cycle = build_cycle_fn(commit_mode="rounds")
    t0 = time.perf_counter()
    out = cycle(snap)
    np.asarray(out.assignment)
    print(f"{'cycle compile+run':40s} {(time.perf_counter()-t0)*1e3:9.1f} ms")
    out = timed("cycle (full rounds)", cycle, snap)
    print("  rounds_used:", int(np.asarray(out.rounds_used)),
          " unsched:", int(np.asarray(out.unschedulable).sum()))

    cycle1 = build_cycle_fn(commit_mode="rounds", max_rounds=1)
    t0 = time.perf_counter()
    o1 = cycle1(snap)
    np.asarray(o1.assignment)
    print(f"{'cycle1 compile+run':40s} {(time.perf_counter()-t0)*1e3:9.1f} ms")
    timed("cycle (max_rounds=1)", cycle1, snap)

    @jax.jit
    def static_only(snap):
        ctx = CycleContext(snap)
        m, s, r = fw.static(ctx)
        return m.sum(), s.sum(), r.sum()

    t0 = time.perf_counter(); static_only(snap); print(f"{'static compile':40s} {(time.perf_counter()-t0)*1e3:9.1f} ms")
    timed("static masks+scores+attribution", static_only, snap)

    @jax.jit
    def dyn_once(snap):
        ctx = CycleContext(snap)
        smask, _, _ = fw.static(ctx)
        if snap.has_inter_pod_affinity or snap.has_topology_spread:
            ctx.matched_pending
        extra = fw.extra_init(ctx)
        m, s, pf = fw.dyn_batched(ctx, snap.node_requested, extra, smask)
        return m.sum(), s.sum()

    t0 = time.perf_counter(); dyn_once(snap); print(f"{'static+dyn compile':40s} {(time.perf_counter()-t0)*1e3:9.1f} ms")
    timed("static + dyn_batched (1 full pass)", dyn_once, snap)

    @jax.jit
    def extra_init_only(snap):
        ctx = CycleContext(snap)
        if snap.has_inter_pod_affinity or snap.has_topology_spread:
            ctx.matched_pending
        extra = fw.extra_init(ctx)
        return jax.tree_util.tree_map(lambda x: x.sum(), extra)

    t0 = time.perf_counter(); extra_init_only(snap); print(f"{'extra_init compile':40s} {(time.perf_counter()-t0)*1e3:9.1f} ms")
    timed("matched tables + extra_init", extra_init_only, snap)

    pre = build_preemption_fn()
    if pre is not None and cfg == 4:
        t0 = time.perf_counter()
        pr = pre(snap, out)
        np.asarray(pr.nominated)
        print(f"{'preempt compile+run':40s} {(time.perf_counter()-t0)*1e3:9.1f} ms")
        timed("preemption pass", pre, snap, out)


if __name__ == "__main__":
    main()
