"""Devtime of the current cycle at a config, with the snapshot staged on
device (isolates H2D from compute).

Run:  python scripts/probe_cycle_devtime.py [cfg]
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import numpy as np

from bench_suite import make_config_base, make_config_workload, CONFIG_SHAPES, _pad
from devtime import report
from k8s_scheduler_tpu.core import build_cycle_fn, build_preemption_fn
from k8s_scheduler_tpu.models import SnapshotEncoder


def main():
    cfg = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    P_real, N_real = CONFIG_SHAPES[cfg]
    enc = SnapshotEncoder(pad_pods=_pad(P_real), pad_nodes=_pad(N_real))
    bn, be = make_config_base(cfg)
    _n, pods, _e, groups = make_config_workload(cfg, seed=1000)
    snap = enc.encode(bn, pods, be, groups)
    dsnap = jax.device_put(snap)
    jax.block_until_ready(jax.tree_util.tree_leaves(dsnap)[0])

    cycle = build_cycle_fn(commit_mode="rounds")
    t0 = time.perf_counter()
    out = cycle(dsnap)
    np.asarray(out.assignment)
    print(f"compile+first: {time.perf_counter()-t0:.1f}s", flush=True)
    print("rounds:", int(np.asarray(out.rounds_used)),
          "unsched:", int(np.asarray(out.unschedulable).sum()), flush=True)

    report("cycle (device-staged snap)", cycle, dsnap)
    report("cycle (numpy snap, H2D per call)", cycle, snap)

    pre = build_preemption_fn()
    if pre is not None and cfg == 4:
        report("preemption pass", pre, dsnap, out)


if __name__ == "__main__":
    main()
