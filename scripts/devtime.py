"""True device-time measurement through the tunnel: dispatch the same
jitted program `reps` times back-to-back (async queue pipelines them on
device), force once at the end; slope = device time per call, intercept =
the fixed round-trip. Reports (total - roundtrip)/reps.

Usage as a library:  from scripts.devtime import devtime
"""

import time

import numpy as np


def _force(out):
    leaf = None
    import jax

    for x in jax.tree_util.tree_leaves(out):
        leaf = x
    if leaf is not None:
        np.asarray(leaf if leaf.ndim == 0 else leaf.ravel()[:1])


def devtime(fn, *args, reps=8, warmup=True):
    """Seconds of device time per call (dispatch-overhead amortized)."""
    if warmup:
        _force(fn(*args))
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = fn(*args)
    _force(out)
    total = time.perf_counter() - t0
    # fixed round-trip measured with a single dispatch of the same fn
    t0 = time.perf_counter()
    _force(fn(*args))
    single = time.perf_counter() - t0
    # single = rt + dev; total = rt + reps*dev  (if queue pipelines)
    dev = (total - single) / max(reps - 1, 1)
    return dev


def report(label, fn, *args, reps=8):
    d = devtime(fn, *args, reps=reps)
    print(f"{label:44s} {d*1e3:9.2f} ms/call (device)", flush=True)
    return d
