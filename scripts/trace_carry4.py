"""Trace the carry-based config-#4 latency path (cycle only)."""
import collections, glob, gzip, json, sys
sys.path.insert(0, ".")
import jax

from k8s_scheduler_tpu.utils.compilation_cache import enable_compilation_cache

enable_compilation_cache()
import numpy as np
from bench_suite import make_config_base, make_config_workload, CONFIG_SHAPES, _pad
from k8s_scheduler_tpu.core import (
    build_packed_cycle_carry_fn, build_stable_state_fn,
)
from k8s_scheduler_tpu.core.cycle import CarryKeeper
from k8s_scheduler_tpu.models import SnapshotEncoder


def main():
    cfg = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    P_real, N_real = CONFIG_SHAPES[cfg]
    enc = SnapshotEncoder(pad_pods=_pad(P_real), pad_nodes=_pad(N_real))
    bn, be = make_config_base(cfg)
    _n, pods, _e, groups = make_config_workload(cfg, seed=1000)
    w, b, spec, snap, dirty = enc.encode_packed(bn, pods, be, groups)
    w = jax.device_put(np.asarray(w))
    b = jax.device_put(np.asarray(b))
    cycle = build_packed_cycle_carry_fn(spec)
    stable = build_stable_state_fn(spec)(w, b)
    keeper = CarryKeeper(spec)
    carry = keeper.ci(w, b, stable)
    out = cycle(w, b, stable, carry)
    np.asarray(out.assignment)

    import shutil

    shutil.rmtree("/tmp/jaxtrace3", ignore_errors=True)
    with jax.profiler.trace("/tmp/jaxtrace3"):
        for _ in range(3):
            out = cycle(w, b, stable, carry)
        np.asarray(out.assignment)

    hlo = cycle.lower(w, b, stable, carry).compile().as_text()
    src_of = {}
    for line in hlo.splitlines():
        line = line.strip()
        if not line.startswith("%") or "metadata=" not in line:
            continue
        name = line.split(" ", 1)[0].lstrip("%")
        m = ""
        if 'op_name="' in line:
            m = line.split('op_name="', 1)[1].split('"', 1)[0]
        f = ""
        if 'source_file="' in line:
            f = line.split('source_file="', 1)[1].split('"', 1)[0].split("/")[-1]
            if 'source_line=' in line:
                f += ":" + line.split("source_line=", 1)[1].split(" ", 1)[0]
        src_of[name] = f"{m} {f}"

    tr = sorted(glob.glob("/tmp/jaxtrace3/plugins/profile/*/*.trace.json.gz"))[-1]
    d = json.load(gzip.open(tr))
    evs = d.get("traceEvents", [])
    pids = {}
    for e in evs:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e["args"].get("name", "")
    agg = collections.Counter()
    cnt = collections.Counter()
    for e in evs:
        if e.get("ph") == "X" and "dur" in e and "TPU" in pids.get(e["pid"], ""):
            agg[e["name"]] += e["dur"]
            cnt[e["name"]] += 1
    total = 0
    for n, v in agg.most_common(40):
        if n.startswith("jit_"):
            print(f"{v/3e3:9.2f} ms/rep x{cnt[n]//3:5d}  {n}")
            continue
        total += v
        print(f"{v/3e3:9.2f} ms/rep x{cnt[n]//3:5d}  {n[:28]:28s} {src_of.get(n, '')[:80]}")
    print(f"(sum of listed non-jit ops: {total/3e3:.1f} ms)")


if __name__ == "__main__":
    main()
