#!/usr/bin/env python
"""Fast CPU-runnable smoke probe for the split-phase serving pipeline.

Measures, on tiny shapes (no TPU needed; finishes in ~1-2 min cold,
seconds warm via the persistent compilation cache):

- overlap_pct / encode_hidden_ms: how much of cycle k+1's host encode
  hides behind cycle k's in-flight device execution when driven through
  ServingPipeline (async dispatch, slimmed decision fetch);
- fetch_bytes vs fetch_bytes_full: the blocking decision payload after
  output-transfer slimming (i16 assignment + u8 flags per pod) vs the
  un-slimmed equivalent;
- diag_lag_ms: how long after the decision fetch the deferred
  FailedScheduling attribution (diagnosis program) becomes available.

Prints ONE JSON line. Knobs: --pods/--nodes/--cycles/--churn.

    JAX_PLATFORMS=cpu python scripts/probe_pipeline.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _median(xs):
    ys = sorted(xs)
    return ys[len(ys) // 2] if ys else 0.0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pods", type=int, default=256)
    ap.add_argument("--nodes", type=int, default=64)
    ap.add_argument("--cycles", type=int, default=10)
    ap.add_argument("--churn", type=float, default=0.2)
    args = ap.parse_args()

    import jax
    import numpy as np

    from k8s_scheduler_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()

    from k8s_scheduler_tpu.core import (
        ServingPipeline,
        build_diagnosis_fn,
        build_stable_state_fn,
    )
    from k8s_scheduler_tpu.core.cycle import (
        CarryKeeper,
        build_packed_cycle_carry_fn,
    )
    from k8s_scheduler_tpu.core.profiling import overlap_stats
    from k8s_scheduler_tpu.models import SnapshotEncoder
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    P, N, C = args.pods, args.nodes, args.cycles
    nodes = make_cluster(N)
    rng = np.random.default_rng(0)

    def draw(i, prev):
        if prev is None:
            return make_pods(
                P, seed=i, affinity_fraction=0.2, spread_fraction=0.2,
                num_apps=max(8, P // 8),
            )
        # churn: fresh arrivals replace a fraction of queue slots — the
        # steady state the encoder's delta path serves
        k = max(1, int(P * args.churn))
        fresh = make_pods(
            k, seed=1000 + i, name_prefix=f"pod{i}-",
            affinity_fraction=0.2, spread_fraction=0.2,
            num_apps=max(8, P // 8),
        )
        out = list(prev)
        for j, p in zip(rng.choice(P, size=k, replace=False), fresh):
            out[j] = p
        return out

    # draw + PRIME every pending set once so the sticky pad dims reach
    # their fixed point before programs compile (a mid-loop regime flip
    # would invalidate the compiled cycle and the device carry)
    pendings = []
    prev = None
    for i in range(C + 2):
        prev = draw(i, prev)
        pendings.append(prev)
    enc = SnapshotEncoder(pad_pods=P, pad_nodes=N)
    spec = None
    for pending in pendings:
        wbuf, bbuf, spec, _snap, _dirty = enc.encode_packed(nodes, pending)

    cyc = build_packed_cycle_carry_fn(spec)
    keeper = CarryKeeper(spec)
    # donated diagnosis: the probe runs no preemption program, so the
    # diagnosis program is each slot's last consumer and may consume
    # (donate) the packed buffers outright — exercises the arena-reuse
    # path end to end (a no-op on backends without donation support)
    diag = build_diagnosis_fn(spec, donate=True)
    stable = build_stable_state_fn(spec)(
        jax.device_put(wbuf), jax.device_put(bbuf)
    )
    keeper.warm(wbuf, bbuf, stable)
    pipe = ServingPipeline(
        cyc, keeper=keeper, diag_fn=diag,
        donate_diagnosis=True,
        require_decision_fetch=False,  # fold-free loop (no binds)
    )

    def carry_key():
        st = getattr(enc, "_stable", None)
        return (spec.key(), id(st), getattr(enc, "_carry_key", None))

    def encode(i):
        t0 = time.perf_counter()
        w, b, s2, _snap, dirty = enc.encode_packed(nodes, pendings[i])
        assert s2.key() == spec.key(), "regime flipped mid-probe"
        return (w, b, dirty), time.perf_counter() - t0

    def dispatch(bufs):
        w, b, dirty = bufs
        return pipe.dispatch(
            w, b, stable, dirty=dirty, carry_key=carry_key(),
            pin=getattr(enc, "_stable", None),
        )

    # warm every program (compile outside any timed window)
    bufs, _ = encode(0)
    h = dispatch(bufs)
    h.decisions()
    h.reject_counts()

    # baseline 1: host encode alone (delta path, churned sets)
    encode_s = []
    for i in range(1, C + 1):
        bufs, es = encode(i)
        encode_s.append(es)
        h = dispatch(bufs)
        h.decisions()  # keep the carry in lockstep with the encodes

    # baseline 2: device cycle alone (dispatch + slimmed fetch, forced
    # on the spot; re-dispatches the LAST buffers, carry unchanged)
    device_s = []
    for _ in range(C):
        t0 = time.perf_counter()
        h = dispatch(bufs)
        h.decisions()
        device_s.append(time.perf_counter() - t0)

    # pipelined: dispatch cycle k, encode cycle k+1 while it runs, then
    # block on k's slimmed decision fetch — the production driver shape
    pipelined_s = []
    bufs, _ = encode(0)
    for i in range(1, C + 1):
        t0 = time.perf_counter()
        h = dispatch(bufs)
        bufs, _ = encode(i)  # overlaps the in-flight device cycle
        h.decisions()
        pipelined_s.append(time.perf_counter() - t0)
    fetch_bytes = pipe.stats.get("fetch_bytes", 0)
    fetch_bytes_full = pipe.stats.get("fetch_bytes_full", 0)

    # deferred-diagnosis lag, off the pipelined window
    diag_lag = []
    for _ in range(3):
        h = dispatch(bufs)
        h.decisions()
        h.reject_counts()
        diag_lag.append(pipe.stats.get("diag_lag_ms", 0.0))

    out = {
        "probe": "pipeline",
        "pods": P,
        "nodes": N,
        "cycles": C,
        "churn": args.churn,
        **overlap_stats(
            _median(encode_s), _median(device_s), _median(pipelined_s)
        ),
        "fetch_bytes": int(fetch_bytes),
        "fetch_bytes_full": int(fetch_bytes_full),
        "diag_lag_ms": round(_median(diag_lag), 3),
        "device": str(jax.devices()[0].platform),
    }
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
