"""Sweep rounds-engine (passes_round0, passes) at config #4 on device.

Run:  python scripts/sweep_passes4.py
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import numpy as np

from bench_suite import make_config_base, make_config_workload, _pad
from devtime import devtime
from k8s_scheduler_tpu.core.cycle import build_cycle_fn
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.framework.runtime import Framework
from k8s_scheduler_tpu.ops import rounds as rounds_ops


def main():
    enc = SnapshotEncoder(pad_pods=_pad(10000), pad_nodes=_pad(5000))
    bn, be = make_config_base(4)
    _n, pods, _e, groups = make_config_workload(4, seed=1000)
    snap = jax.device_put(enc.encode(bn, pods, be, groups))

    for p0, p in [(16, 8), (10, 6), (8, 4), (20, 10)]:
        fw = Framework.from_config()

        # the patch must stay installed through the FIRST call (tracing
        # happens at invocation, not at build_cycle_fn time — an earlier
        # version of this script restored it too early and measured the
        # default pass counts four times)
        import functools
        import k8s_scheduler_tpu.core.cycle as cyc

        orig = rounds_ops.rounds_commit

        @functools.wraps(orig)
        def patched(*a, **kw):
            kw["passes_round0"] = p0
            kw["passes"] = p
            return orig(*a, **kw)

        cyc.rounds_ops.rounds_commit = patched
        try:
            cycle = build_cycle_fn(framework=fw, commit_mode="rounds")
            t0 = time.perf_counter()
            out = cycle(snap)
            np.asarray(out.assignment)
            comp = time.perf_counter() - t0
        finally:
            cyc.rounds_ops.rounds_commit = orig
        d = devtime(cycle, snap)
        print(
            f"passes0={p0:2d} passes={p:2d}: device {d*1e3:7.1f} ms  "
            f"rounds={int(np.asarray(out.rounds_used))}  "
            f"unsched={int(np.asarray(out.unschedulable).sum())}  "
            f"(compile {comp:.0f}s)",
            flush=True,
        )


if __name__ == "__main__":
    main()
