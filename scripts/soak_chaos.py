#!/usr/bin/env python
"""Chaos soak: replay a workload while every fault class fires, assert
the degradation ladder's invariants hold and measure MTTR.

Four phases (each selectable; default = all):

- **serve** — one in-process Scheduler (flight recorder + observer +
  compile cache + dispatch watchdog) serves a steady arrival stream
  while a scripted `FaultPlan` fires every injection point that does
  not kill durability: `fetch_delay`, `fetch_hang` (longer than
  `dispatchDeadlineMs` — the watchdog must bound it), `device_error`
  in all three marker classes, `clock_skew`, `cache_torn`, and
  `cache_enospc`. Invariants asserted:
    * the serve loop is NEVER blocked past the deadline (the hang
      cycle's wall time stays far below the injected hang);
    * zero lost accepted pods (every added pod ends bound or still
      tracked in a queue tier);
    * zero duplicate binds (each uid binds at most once);
    * the ladder recovered to rung 0 by the end (MTTR reported);
    * a warm restart against the same compile-cache dir neither
      crashes on the torn entry nor misses every entry.
- **overload** — chaos fusion for the edge (ISSUE 14): arrivals at
  >= 2x measured capacity through the REAL submission API
  (bench_suite.front_door_drive, the bench-config-9 harness) with a
  fetch_hang mid-burst. Asserts bounded admission-queue depth,
  shed-not-lost (every acked pod binds exactly once), /healthz
  degraded DURING the burst, and ladder recovery to rung 0 after it.
- **enospc** — a Scheduler with durable state takes a
  `journal_enospc` hit: the writer dies, DurableState degrades to
  stateless (the documented path), and serving CONTINUES — pods still
  bind after durability is gone.
- **crash** — soak_failover-style kill -9 while the child is BELOW the
  top rung (a fetch_hang degraded it): the parent restores into fresh
  queue/cache and asserts the restored digest matches an op boundary
  the child logged (nothing lost, duplicated, or half-applied) AND
  that degradation state did not leak into the restore — a fresh
  Scheduler starts at rung 0.

Standalone:

    JAX_PLATFORMS=cpu python scripts/soak_chaos.py --smoke

A smoke subset runs as tests/test_faults.py::test_soak_chaos_smoke
(marked slow).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _mean(xs):
    return sum(xs) / len(xs) if xs else 0.0


# ---------------------------------------------------------------------------
# phase 1: chaos serve
# ---------------------------------------------------------------------------

# every non-durability fault class, scripted against warm cycles (the
# first cycles compile; faults land after the programs are warm so the
# deadline assertion measures the fetch, not a compile)
SERVE_PLAN = (
    "seed=7;"
    "cache_enospc@cycle=1:n=1;"
    "cache_torn@cycle=1:n=1;"
    "fetch_delay@cycle=6:ms=120:n=1;"
    "fetch_hang@cycle=8:ms={hang_ms}:n=1;"
    "device_error@cycle=12:kind=transport:n=1;"
    "device_error@cycle=16:kind=corrupt:n=1;"
    "device_error@cycle=20:kind=wedge:n=1;"
    "clock_skew@cycle=24:ms=250:n=1"
)


def run_serve_phase(
    cycles: int = 48,
    deadline_ms: float = 300.0,
    hang_ms: float = 4000.0,
    pods_per_cycle: int = 4,
    cache_dir: str = "",
    verbose: bool = True,
) -> dict:
    # the drive itself is bench_suite.chaos_serve_drive — shared with
    # bench config 7 (fault_storm), so the soak and the bench can never
    # assert different invariants of the same storm; this phase adds
    # the wider fault plan (cache/clock classes) and the warm-restart
    # check over the chaos-written compile cache
    import bench_suite

    from k8s_scheduler_tpu.core import faults

    try:
        d = bench_suite.chaos_serve_drive(
            fault_spec=SERVE_PLAN.format(hang_ms=hang_ms),
            cycles=cycles,
            deadline_ms=deadline_ms,
            pods_per_cycle=pods_per_cycle,
            cache_dir=cache_dir or "off",
        )
        sched = d["sched"]
        plan = faults.plan()
        mttr = d["episodes_ms"]
        result = {
            "phase": "serve",
            "cycles": cycles,
            "added": len(d["added"]),
            "bound": len(d["binds"]),
            "duplicate_binds": d["duplicate_binds"],
            "lost": d["lost"],
            "hang_cycle_wall_ms": round(d["walls"][8] * 1e3, 1),
            "deadline_ms": deadline_ms,
            "hang_ms": hang_ms,
            "fired_points": sorted(
                plan.fired_points()
            ) if plan else [],
            "degradations": sched.ladder.degradations,
            "degraded_cycles": d["degraded_cycles"],
            "final_rung": sched.ladder.rung,
            "mttr_ms": round(_mean(mttr), 1),
            "mttr_max_ms": round(max(mttr), 1) if mttr else 0.0,
            "fetch_failure_events": sum(
                1 for e in sched.events.events()
                if e.reason == "FetchFailed"
            ),
        }
    finally:
        faults.disarm()

    # invariants
    assert not result["lost"], f"lost accepted pods: {result['lost']}"
    assert result["duplicate_binds"] == 0, "duplicate binds"
    assert result["bound"] == result["added"], (
        f"only {result['bound']}/{result['added']} pods bound"
    )
    assert result["hang_cycle_wall_ms"] < hang_ms * 0.5, (
        f"serve loop blocked {result['hang_cycle_wall_ms']}ms against a "
        f"{deadline_ms}ms deadline — watchdog failed"
    )
    assert result["final_rung"] == 0, "ladder never recovered to normal"
    assert result["degradations"] >= 2, "plan fired but nothing degraded"
    expect = {
        "cache_enospc", "cache_torn", "fetch_delay", "fetch_hang",
        "device_error", "clock_skew",
    }
    missing = expect - set(result["fired_points"])
    assert not missing, f"fault classes never fired: {missing}"

    if cache_dir:
        # warm restart against the chaos-written cache: the torn entry
        # must be refused (recompile), never a crash
        from k8s_scheduler_tpu.config import SchedulerConfiguration
        from k8s_scheduler_tpu.core import compile_cache as _cc
        from k8s_scheduler_tpu.core.scheduler import Scheduler
        from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

        _cc.clear_loaded_memo()
        sched2 = Scheduler(
            config=SchedulerConfiguration(
                pad_existing=2048, pad_pods_per_node=512,
                compile_cache_dir=cache_dir,
                speculative_compile=False,
            ),
            binder=lambda p, n: None,
        )
        for nd in make_cluster(16):
            sched2.on_node_add(nd)
        for p in make_pods(pods_per_cycle, seed=99, name_prefix="wz-"):
            sched2.on_pod_add(p)
        sched2.schedule_cycle()
        cc = sched2._compile_cache
        result["warm_cache"] = cc.status() if cc is not None else {}
        assert cc is not None and cc.hits + cc.misses > 0
    if verbose:
        print(json.dumps(result), flush=True)
    return result


# ---------------------------------------------------------------------------
# phase 1b: overload through the real submission API (chaos fusion for
# the edge, ISSUE 14)
# ---------------------------------------------------------------------------


def run_overload_phase(verbose: bool = True) -> dict:
    """Arrival rate >= 2x measured capacity through the REAL front
    door (bench_suite.front_door_drive — the same harness bench
    config 9 asserts, so bench and soak can never drift), with a
    fetch_hang firing MID-BURST so the degradation ladder engages
    while the door is already shedding. Invariants:

    - the admission queue depth never exceeds its bound (+one batch);
    - the door actually shed (RESOURCE_EXHAUSTED, never silent drops);
    - shed-not-lost: every ACKED pod binds exactly once by drain;
    - /healthz reports degraded:true at some point DURING the burst
      (admission saturation is a paging signal) and clean after it;
    - the ladder recovers to rung 0 after the burst, with the hang
      step deadline-classified (the watchdog ended it, not the hang).
    """
    import bench_suite

    from k8s_scheduler_tpu.cmd.httpserver import staleness_healthz
    from k8s_scheduler_tpu.core import faults

    depth_bound = 64
    deadline_ms, hang_ms = 300.0, 2500.0
    try:
        cal = bench_suite.front_door_drive(
            duration_s=1.0, rate_pps=400.0, queue_depth=depth_bound,
            name_prefix="oc",
        )
        cap = max(cal["bind_rate_pps"], 20.0)

        degraded_seen = {"burst": False}
        probe_state: dict = {}

        def probe(sched, admission, _res):
            # the REAL /healthz closure, evaluated inside the burst:
            # admission saturation (or the hang's ladder step) must
            # surface as degraded:true while the door sheds
            if "fn" not in probe_state:
                probe_state["fn"] = staleness_healthz(
                    None, sched.flight, 0.0, observer=sched.observer,
                    ladder=sched.ladder, admission=admission,
                )
            _ok, detail = probe_state["fn"]()
            if detail.get("degraded"):
                degraded_seen["burst"] = True

        d = bench_suite.front_door_drive(
            duration_s=6.0,
            rate_pps=cap * 2.5,
            queue_depth=depth_bound,
            batch=8,
            deadline_ms=deadline_ms,
            fault_spec=(
                f"seed=17;fetch_hang@cycle=8..100000:ms={hang_ms}:n=1"
            ),
            name_prefix="ov",
            on_tick=probe,
        )
        sched = d["sched"]
        plan = faults.plan()
        fn_after = staleness_healthz(
            None, sched.flight, 0.0, observer=sched.observer,
            ladder=sched.ladder, admission=d["admission"],
        )
        _ok, after = fn_after()
        result = {
            "phase": "overload",
            "capacity_pps": round(cap, 1),
            "rate_pps": round(cap * 2.5, 1),
            "accepted": d["accepted"],
            "shed": d["shed"],
            "bound": len(d["binds"]),
            "duplicate_binds": d["duplicate_binds"],
            "lost": d["lost"],
            "max_queue_depth": d["max_depth"],
            "depth_bound": depth_bound,
            "degraded_during_burst": degraded_seen["burst"],
            "degraded_after": bool(after.get("degraded", False)),
            "final_rung": sched.ladder.rung,
            "degradations": sched.ladder.degradations,
            "fired_points": sorted(
                plan.fired_points()
            ) if plan else [],
            "drained": d["drained"],
        }
    finally:
        faults.disarm()

    assert result["shed"] > 0, (
        "overload burst never shed — the admission bound is not "
        f"engaging at {result['rate_pps']} pps vs capacity "
        f"{result['capacity_pps']} pps"
    )
    assert result["max_queue_depth"] <= depth_bound + 8, (
        f"queue depth {result['max_queue_depth']} exceeded the bound "
        f"{depth_bound}: backpressure is not bounding memory"
    )
    assert not result["lost"], (
        f"acked pods lost under overload: {result['lost'][:6]}"
    )
    assert result["duplicate_binds"] == 0, "duplicate binds"
    missing = {u for u in d["acked"] if u not in d["binds"]}
    assert not missing, (
        f"shed-not-lost violated: {len(missing)} acked pods never "
        f"bound ({sorted(missing)[:4]})"
    )
    assert "fetch_hang" in result["fired_points"], (
        "the mid-burst fetch_hang never fired"
    )
    assert result["degradations"] >= 1 and any(
        t["reason"].startswith("deadline")
        for t in sched.ladder.transitions
    ), "no deadline-classified ladder step: the watchdog never expired"
    assert result["degraded_during_burst"], (
        "/healthz never reported degraded during the burst"
    )
    assert result["final_rung"] == 0 and not result["degraded_after"], (
        "front door did not recover to rung 0 / clean healthz"
    )
    if verbose:
        print(json.dumps(result), flush=True)
    return result


# ---------------------------------------------------------------------------
# phase 2: journal ENOSPC -> stateless degrade, serving continues
# ---------------------------------------------------------------------------


def run_enospc_phase(state_dir: str, verbose: bool = True) -> dict:
    from k8s_scheduler_tpu.config import SchedulerConfiguration
    from k8s_scheduler_tpu.core import faults
    from k8s_scheduler_tpu.core.scheduler import Scheduler
    from k8s_scheduler_tpu.state import DurableState
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    st = DurableState(state_dir, snapshot_interval_seconds=0)
    cfg = SchedulerConfiguration(
        fault_spec="journal_enospc@cycle=3:n=1",
        pad_existing=512, pad_pods_per_node=256,
        pod_initial_backoff_seconds=0.05,
    )
    binds: list[str] = []
    sched = Scheduler(
        config=cfg, binder=lambda p, n: binds.append(p.uid), state=st
    )
    try:
        for nd in make_cluster(8):
            sched.on_node_add(nd)
        for i in range(1, 9):
            for p in make_pods(3, seed=7000 + i, name_prefix=f"en{i}-"):
                sched.on_pod_add(p)
            sched.schedule_cycle()
            if i == 3:
                # give the poll-cadence writer time to hit the injected
                # ENOSPC and die before asserting the degrade
                try:
                    st.journal.flush(timeout=5.0)
                except Exception:
                    pass  # a dead writer raises StateError — expected
        binds_after = len(binds)
    finally:
        faults.disarm()
    result = {
        "phase": "enospc",
        "journal_failed": st.journal.failed,
        "emitters_detached": sched.queue._journal is None,
        "bound": binds_after,
    }
    assert st.journal.failed is not None, "journal writer survived ENOSPC"
    assert result["emitters_detached"], "queue still journaling"
    assert binds_after > 9, "serving stopped after durability loss"
    if verbose:
        print(json.dumps(result), flush=True)
    return result


# ---------------------------------------------------------------------------
# phase 3: kill -9 while degraded -> digest-verified restore at rung 0
# ---------------------------------------------------------------------------


def run_crash_child(state_dir: str, digest_log: str) -> int:
    """Child: a real Scheduler with durable state and a fetch_hang plan
    that degrades it, logging the queue/cache digest after EVERY public
    mutation (soak_failover's discipline: journal drains only at the
    per-cycle flush barrier, so every durable boundary is logged)."""
    from k8s_scheduler_tpu.config import SchedulerConfiguration
    from k8s_scheduler_tpu.core.scheduler import Scheduler
    from k8s_scheduler_tpu.state import DurableState, state_digest
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    st = DurableState(state_dir, snapshot_interval_seconds=0)
    st.journal._poll_s = 60.0  # drain only at flush barriers
    cfg = SchedulerConfiguration(
        dispatch_deadline_ms=200.0,
        fault_spec="fetch_hang@cycle=3:ms=60000:n=1",
        pad_existing=512, pad_pods_per_node=256,
        pod_initial_backoff_seconds=0.05,
    )
    sched = Scheduler(config=cfg, binder=lambda p, n: None, state=st)
    q, c = sched.queue, sched.cache
    f = open(digest_log, "a")
    counter = {"i": 0}

    def log_line(kind: str) -> None:
        f.write(f"{kind} {counter['i']} {state_digest(q, c)}\n")
        f.flush()
        os.fsync(f.fileno())

    def _wrap(obj, name):
        orig = getattr(obj, name)

        def wrapped(*a, **k):
            r = orig(*a, **k)
            counter["i"] += 1
            log_line("op")
            return r

        setattr(obj, name, wrapped)

    for name in (
        "add", "update", "delete", "pop_ready", "requeue_unschedulable",
        "requeue_backoff", "flush_backoff", "flush_unschedulable_timeout",
        "move_all_to_active_or_backoff", "recover_in_flight",
        "retire_in_flight",
    ):
        _wrap(q, name)
    for name in (
        "add_node", "update_node", "remove_node", "add_pod",
        "remove_pod", "assume", "finish_binding", "confirm", "forget",
        "cleanup_expired",
    ):
        _wrap(c, name)

    for nd in make_cluster(8):
        sched.on_node_add(nd)
    log_line("start")
    for i in range(1, 200):
        for p in make_pods(3, seed=8000 + i, name_prefix=f"cr{i}-"):
            sched.on_pod_add(p)
        sched.schedule_cycle()
        st.journal.flush()
        log_line("flushed")
        if sched.ladder.rung > 0:
            # below the top rung: tell the parent we are degraded (it
            # kills us mid-degradation from here on)
            log_line("degraded")
        time.sleep(0.01)
    return 0


def run_crash_phase(state_dir: str, verbose: bool = True) -> dict:
    """Parent: spawn the child, SIGKILL it once it reports a degraded
    rung, then restore and check the failover invariants."""
    digest_log = os.path.join(state_dir, "digests.txt")
    if os.path.exists(digest_log):
        os.unlink(digest_log)
    child = subprocess.Popen(
        [
            sys.executable, os.path.abspath(__file__),
            "--crash-child", "--state-dir", state_dir,
            "--digest-log", digest_log,
        ],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    deadline = time.monotonic() + 300
    degraded_seen = False
    try:
        while time.monotonic() < deadline:
            if child.poll() is not None:
                raise RuntimeError(
                    f"crash child exited early rc={child.returncode}"
                )
            if os.path.exists(digest_log):
                with open(digest_log) as f:
                    if any(
                        line.startswith("degraded") for line in f
                    ):
                        degraded_seen = True
                        break
            time.sleep(0.05)
    finally:
        child.send_signal(signal.SIGKILL)
        child.wait()
    assert degraded_seen, "child never reported a degraded rung"

    # standby restore into a BARE queue/cache pair (digest comparable
    # to the child's op-boundary log: the Scheduler ctor's journaled
    # recover_in_flight would move the state past the logged boundary)
    from k8s_scheduler_tpu.internal.cache import SchedulerCache
    from k8s_scheduler_tpu.internal.queue import SchedulingQueue
    from k8s_scheduler_tpu.state import DurableState, state_digest

    q = SchedulingQueue(
        initial_backoff_seconds=0.05, max_backoff_seconds=0.2,
    )
    c = SchedulerCache()
    st = DurableState(state_dir, snapshot_interval_seconds=0)
    st.restore_into(q, c)
    dig = state_digest(q, c)
    digests: set[str] = set()
    with open(digest_log) as f:
        for line in f:
            parts = line.strip().split()
            if len(parts) == 3 and len(parts[2]) == 64:
                digests.add(parts[2])
    st.journal.close()
    # real standby takeover: a Scheduler attached to the same state dir
    # restores the dead (degraded) active's queue/cache — and its
    # ladder starts at the TOP rung, because degradation state is
    # process-local and never journaled as authoritative
    from k8s_scheduler_tpu.config import SchedulerConfiguration
    from k8s_scheduler_tpu.core.scheduler import Scheduler

    st2 = DurableState(state_dir, snapshot_interval_seconds=0)
    standby = Scheduler(
        config=SchedulerConfiguration(
            pad_existing=512, pad_pods_per_node=256,
        ),
        binder=lambda p, n: None,
        state=st2,
    )
    result = {
        "phase": "crash",
        "boundaries": len(digests),
        "digest_matched": dig in digests,
        "restored_rung": standby.ladder.rung,
        "restored_pending": dict(standby.queue.pending_counts()),
        "replayed": st2.last_restore.get("records_replayed"),
    }
    st2.journal.close()
    assert dig in digests, (
        "restored digest matches no op boundary the degraded child "
        "recorded — state lost, duplicated, or half-applied"
    )
    assert result["restored_rung"] == 0, (
        "degradation state leaked into the takeover: a standby must "
        "start at the top rung"
    )
    if verbose:
        print(json.dumps(result), flush=True)
    return result


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--phases", default="serve,overload,enospc,crash",
        help="comma list: serve, overload, enospc, crash",
    )
    ap.add_argument("--cycles", type=int, default=48)
    ap.add_argument("--deadline-ms", type=float, default=300.0)
    ap.add_argument("--hang-ms", type=float, default=4000.0)
    ap.add_argument("--smoke", action="store_true",
                    help="short plan: every fault class fires once")
    ap.add_argument("--state-dir", default="")
    ap.add_argument("--digest-log", default="")
    ap.add_argument("--crash-child", action="store_true", help="internal")
    args = ap.parse_args()
    if args.crash_child:
        return run_crash_child(
            args.state_dir,
            args.digest_log
            or os.path.join(args.state_dir, "digests.txt"),
        )
    import tempfile

    base = args.state_dir or tempfile.mkdtemp(prefix="soak-chaos-")
    phases = [p.strip() for p in args.phases.split(",") if p.strip()]
    cycles = 30 if args.smoke else args.cycles
    results = []
    if "serve" in phases:
        results.append(run_serve_phase(
            cycles=cycles,
            deadline_ms=args.deadline_ms,
            hang_ms=args.hang_ms,
            cache_dir=os.path.join(base, "compile_cache"),
        ))
    if "overload" in phases:
        results.append(run_overload_phase())
    if "enospc" in phases:
        results.append(run_enospc_phase(os.path.join(base, "enospc")))
    if "crash" in phases:
        results.append(run_crash_phase(os.path.join(base, "crash")))
    print(json.dumps({
        "soak_chaos": "ok",
        "phases": [r["phase"] for r in results],
        "mttr_ms": next(
            (r["mttr_ms"] for r in results if "mttr_ms" in r), 0.0
        ),
    }), flush=True)
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
