"""Mirror bench_suite.run_config(3) exactly; strip pieces via env flags.

SKIP_STATS=1   drop the np.asarray stats reads between iterations
SKIP_NODES=1   hoist make_cluster out of the loop
SKIP_KEY=1     drop the shape-key computation
"""

import os
import time

import jax
import numpy as np

from k8s_scheduler_tpu.core import build_cycle_fn
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

AFF = dict(affinity_fraction=0.3, anti_affinity_fraction=0.2,
           spread_fraction=0.2, num_apps=500)

enc = SnapshotEncoder(pad_pods=5120, pad_nodes=1024)
cycle = build_cycle_fn()
shape_keys = set()
nodes_outer = make_cluster(1000) if os.environ.get("SKIP_NODES") else None

for i in range(3):
    nodes = nodes_outer if nodes_outer is not None else make_cluster(1000)
    pods = make_pods(5000, seed=1000 + i, **AFF)
    snap = enc.encode(nodes, pods)
    if not os.environ.get("SKIP_KEY"):
        key = tuple((k, v.shape) for k, v in sorted(snap.array_fields().items()))
    else:
        key = 0
    if key not in shape_keys:
        shape_keys.add(key)
        t0 = time.perf_counter()
        out = cycle(snap)
        jax.block_until_ready(out.assignment)
        print(f"  warmup {time.perf_counter()-t0:.2f}s", flush=True)
    t0 = time.perf_counter()
    out = cycle(snap)
    jax.block_until_ready(out.assignment)
    t_cycle = time.perf_counter() - t0
    if not os.environ.get("SKIP_STATS"):
        a = np.asarray(out.assignment)
        valid = np.asarray(snap.pod_valid)
        sched = int(((a >= 0) & valid).sum())
        unsched = int(np.asarray(out.unschedulable).sum())
        gd = int(np.asarray(out.gang_dropped).sum())
    else:
        sched = unsched = gd = -1
    print(f"iter={i} cycle={t_cycle:.4f}s sched={sched} unsched={unsched}",
          flush=True)
