"""Round-5 baseline: device time of the config-#4 decision chain pieces
(carry cycle, preemption, diagnosis) separately and chained.

Run:  python scripts/probe_chain5.py
"""
import sys, time
sys.path.insert(0, ".")
import jax

from k8s_scheduler_tpu.utils.compilation_cache import enable_compilation_cache

enable_compilation_cache()
import numpy as np
from bench_suite import make_config_base, make_config_workload, _pad
from devtime import devtime
from k8s_scheduler_tpu.core import (
    build_diagnosis_fn,
    build_packed_cycle_carry_fn,
    build_packed_preemption_fn,
    build_stable_state_fn,
)
from k8s_scheduler_tpu.core.cycle import CarryKeeper
from k8s_scheduler_tpu.models import SnapshotEncoder

enc = SnapshotEncoder(pad_pods=_pad(10000), pad_nodes=_pad(5000))
bn, be = make_config_base(4)
_n, pods, _e, groups = make_config_workload(4, seed=1000)
w, b, spec, snap, dirty = enc.encode_packed(bn, pods, be, groups)
w = jax.device_put(np.asarray(w)); b = jax.device_put(np.asarray(b))
t0 = time.perf_counter()
stable_fn = build_stable_state_fn(spec)
stable = stable_fn(w, b)
keeper = CarryKeeper(spec)
carry = keeper.ci(w, b, stable)
cyc = build_packed_cycle_carry_fn(spec)
pre = build_packed_preemption_fn(spec)
diag = build_diagnosis_fn(spec)
out = cyc(w, b, stable, carry)
op = pre(w, b, out, stable)
np.asarray(op.nominated)
print(f"compile+warm {time.perf_counter()-t0:.0f}s", flush=True)

print(f"stable_fn    : {devtime(lambda: stable_fn(w, b), reps=8)*1e3:7.1f} ms")
print(f"cycle        : {devtime(lambda: cyc(w, b, stable, carry), reps=8)*1e3:7.1f} ms")
print(f"preempt      : {devtime(lambda: pre(w, b, out, stable), reps=8)*1e3:7.1f} ms")
print(f"diag         : {devtime(lambda: diag(w, b, stable, out.assignment, out.node_requested, out.pv_claimed), reps=8)*1e3:7.1f} ms")

def chain():
    o = cyc(w, b, stable, carry)
    return pre(w, b, o, stable)

print(f"cycle+preempt: {devtime(chain, reps=8)*1e3:7.1f} ms")

# carry-update program (the per-cycle dirty-row cost in serving)
idx = np.zeros(keeper.bucket, np.int32)
cu = keeper._cu(keeper.bucket)
c2 = cu(w, b, stable, carry, idx)
np.asarray(next(iter(c2.values())))[:1] if isinstance(c2, dict) else None
print(f"carry-update : {devtime(lambda: cu(w, b, stable, carry, idx), reps=8)*1e3:7.1f} ms (bucket {keeper.bucket})")
