"""Device time of the config-#4 cycle with and without injected stable
state.

Run:  python scripts/probe_stable4.py
"""

import sys

sys.path.insert(0, ".")

import jax

from k8s_scheduler_tpu.utils.compilation_cache import enable_compilation_cache

enable_compilation_cache()

from bench_suite import make_config_base, make_config_workload, _pad
from devtime import report
from k8s_scheduler_tpu.core import (
    build_packed_cycle_fn,
    build_packed_preemption_fn,
    build_stable_state_fn,
)
from k8s_scheduler_tpu.models import SnapshotEncoder, packing


def main():
    enc = SnapshotEncoder(pad_pods=_pad(10000), pad_nodes=_pad(5000))
    bn, be = make_config_base(4)
    _n, pods, _e, groups = make_config_workload(4, seed=1000)
    snap = enc.encode(bn, pods, be, groups)
    spec = packing.make_spec(snap)
    w, b = packing.pack(snap, spec)
    w = jax.device_put(w)
    b = jax.device_put(b)

    cycle = build_packed_cycle_fn(spec, commit_mode="rounds")
    pre = build_packed_preemption_fn(spec)
    st_fn = build_stable_state_fn(spec)
    st = st_fn(w, b)
    jax.block_until_ready(jax.tree_util.tree_leaves(st)[0])

    report("stable-state program", st_fn, w, b)
    report("cycle (no injection)", cycle, w, b)
    report("cycle (stable injected)", lambda w, b: cycle(w, b, st), w, b)
    out = cycle(w, b, st)
    report("preemption", pre, w, b, out)


if __name__ == "__main__":
    main()
