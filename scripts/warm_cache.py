#!/usr/bin/env python
"""warm_cache: pre-populate the persistent compiled-program cache.

A deployment that knows its serving shape ahead of time (pod/node pad
buckets, sticky E/MPN pre-sizes, profile config) can pay every compile
BEFORE taking traffic: run this against the scheduler's state dir (or an
explicit --cache-dir), and the first serving process loads every program
from the cache instead of compiling cold (8.8-16.8 s per program on the
rig; ~100 s historical worst case on a regime flip).

    python scripts/warm_cache.py --cache-dir /var/lib/sched/compile_cache \
        --pods 10000 --nodes 5000 [--config scheduler.yaml] \
        [--adjacent 1] [--multi-cycle-k 8]

`--adjacent N` also pre-builds N pad-bucket regimes above the given pod
count — the regimes churn would otherwise flip into mid-serve.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="warm_cache")
    ap.add_argument("--cache-dir", default="",
                    help="compile-cache directory (or use --state-dir)")
    ap.add_argument("--state-dir", default="",
                    help="state dir; cache goes to <state-dir>/compile_cache")
    ap.add_argument("--config", default="",
                    help="KubeSchedulerConfiguration YAML (profiles, pads)")
    ap.add_argument("--pods", type=int, default=256)
    ap.add_argument("--nodes", type=int, default=64)
    ap.add_argument("--pad-bucket", type=int, default=64)
    ap.add_argument("--adjacent", type=int, default=1,
                    help="extra P pad buckets above --pods to pre-build")
    ap.add_argument("--multi-cycle-k", type=int, default=0,
                    help="also warm the multi-cycle batch program for K")
    args = ap.parse_args(argv)

    cache_dir = args.cache_dir or (
        os.path.join(args.state_dir, "compile_cache")
        if args.state_dir else ""
    )
    if not cache_dir:
        ap.error("one of --cache-dir / --state-dir is required")

    from k8s_scheduler_tpu.config import (
        SchedulerConfiguration,
        load_config,
    )
    from k8s_scheduler_tpu.core import Scheduler
    # the scheduler's own bucket rounding: the pre-built regimes must
    # be byte-for-byte the pads serving will ask for
    from k8s_scheduler_tpu.core.scheduler import _pad
    from k8s_scheduler_tpu.models import packing
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    config = (
        load_config(args.config) if args.config
        else SchedulerConfiguration()
    )
    config.compile_cache_dir = cache_dir
    config.speculative_compile = False  # builds run HERE, synchronously
    if args.multi_cycle_k > 1:
        config.multi_cycle_k = args.multi_cycle_k
    sched = Scheduler(config=config, pad_bucket=args.pad_bucket)
    nodes = make_cluster(args.nodes)
    pending = make_pods(args.pods, seed=1)
    bucket = args.pad_bucket

    total = 0
    for profile in sched._profile_order:
        enc = sched._encoders[profile]
        enc.pad_nodes = _pad(args.nodes, bucket)
        for step in range(args.adjacent + 1):
            enc.pad_pods = _pad(args.pods, bucket) + step * bucket
            snap = enc.encode(nodes, pending)
            spec = packing.make_spec(snap)
            t0 = time.perf_counter()
            sched._packed_fns(spec, profile)
            if config.multi_cycle_k > 1:
                sched._mc_programs(spec, profile)
            total += 1
            print(
                f"profile={profile} P={enc.pad_pods} "
                f"source={sched._last_compile_source} "
                f"{time.perf_counter() - t0:.2f}s",
                flush=True,
            )
    cc = sched._compile_cache
    print(
        f"warmed {total} regime(s): {cc.status() if cc else 'no cache'}",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
