"""Bisect the bench_suite vs compile_probe 1000x runtime gap at config #3."""

import time

import jax

from k8s_scheduler_tpu.core import build_cycle_fn
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods


def timeit(label, enc_kwargs, pod_kwargs):
    nodes = make_cluster(1000)
    pods = make_pods(5000, seed=1000, **pod_kwargs)
    enc = SnapshotEncoder(**enc_kwargs)
    snap = enc.encode(nodes, pods)
    cycle = build_cycle_fn()
    out = cycle(snap)
    jax.block_until_ready(out.assignment)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = cycle(snap)
        jax.block_until_ready(out.assignment)
        ts.append(time.perf_counter() - t0)
    print(f"{label}: P={snap.P} N={snap.N} times={[round(t,4) for t in ts]}",
          flush=True)


AFF = dict(affinity_fraction=0.3, anti_affinity_fraction=0.2,
           spread_fraction=0.2, num_apps=500)

timeit("bench-pad(128) bench-pods", dict(pad_pods=5120, pad_nodes=1024), AFF)
timeit("pow2-pad bench-pods", {}, AFF)
timeit(
    "pow2-pad probe-pods",
    {},
    dict(**AFF, selector_fraction=0.3, toleration_fraction=0.1,
         priorities=(0, 0, 10, 100)),
)
