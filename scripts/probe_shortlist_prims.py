"""Shortlist-engine primitive costs on the real TPU (round-4 design
probe): is per-round top-k + [B,k] passes actually cheaper than the
[B,N] pass chain, and which top-k flavor / gather shape to use?

Run:  python scripts/probe_shortlist_prims.py
"""

import sys

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from scripts.devtime import devtime

P, N, K = 10112, 5120, 32


def main():
    print("platform:", jax.devices()[0].platform, flush=True)
    rng = np.random.default_rng(0)
    base = jnp.asarray(rng.standard_normal((P, N)), jnp.float32)
    mask = jnp.asarray(rng.random((P, N)) < 0.5)
    delta = jnp.asarray(rng.standard_normal((N,)), jnp.float32)
    sl = jnp.asarray(rng.integers(0, N, (P, K)), jnp.int32)
    ranks = jnp.asarray(rng.permutation(P), jnp.int32)
    req = jnp.asarray(rng.random((P, 5)), jnp.float32)

    def t(name, fn, *a):
        d = devtime(jax.jit(fn), *a, reps=8)
        print(f"{name:44s} {d*1e3:8.3f} ms", flush=True)
        return d

    scored = jnp.where(mask, base, -1e9)

    t("top_k k=32 [P,N]", lambda s: jax.lax.top_k(s, K), scored)
    t("approx_max_k k=32 [P,N]",
      lambda s: jax.lax.approx_max_k(s, K), scored)
    t("approx_max_k k=32 recall .99",
      lambda s: jax.lax.approx_max_k(s, K, recall_target=0.99), scored)
    t("mask+where only [P,N]", lambda b, m: jnp.where(m, b, -1e9),
      base, mask)
    t("argmax [P,N]", lambda s: jnp.argmax(s, axis=1), scored)

    t("delta gather [P,K] from [N]",
      lambda d, s: d[s.reshape(-1)].reshape(P, K), delta, sl)
    t("take_along_axis [P,K] from [P,N]",
      lambda b, s: jnp.take_along_axis(b, s, axis=1), base, sl)
    t("onehot matmul delta: [P,K]",
      lambda d, s: (jax.nn.one_hot(s, N, dtype=jnp.bfloat16)
                    @ d.astype(jnp.bfloat16)),
      delta, sl)

    t("argsort [P] i32", lambda k: jnp.argsort(k), ranks)
    packed = ranks.astype(jnp.uint32)
    t("lax.sort packed u32+iota [P]",
      lambda p: jax.lax.sort((p, jnp.arange(P, dtype=jnp.int32)),
                             num_keys=1), packed)

    # one wide pass (the current engine's per-pass chain) vs one
    # shortlist pass
    def wide_pass(scored, mask, dead, acc, delta):
        avail = mask & ~dead & ~acc[:, None]
        eff = jnp.where(avail, jnp.round(scored + delta[None, :]), -1e9)
        best = jnp.argmax(eff, axis=1).astype(jnp.int32)
        pid = jnp.arange(P, dtype=jnp.int32)
        has = avail[pid, best]
        dead = dead.at[pid, best].max(has)
        return best, dead

    dead0 = jnp.zeros((P, N), bool)
    acc0 = jnp.zeros((P,), bool)
    t("WIDE pass (avail+round+argmax+deadscatter)",
      wide_pass, scored, mask, dead0, acc0, delta)

    def sl_pass(vals, sl, dead_sl, acc, delta):
        avail = (vals > -5e8) & ~dead_sl & ~acc[:, None]
        dsl = delta[sl.reshape(-1)].reshape(P, K)
        eff = jnp.where(avail, vals + jnp.round(dsl), -1e9)
        bj = jnp.argmax(eff, axis=1).astype(jnp.int32)
        pid = jnp.arange(P, dtype=jnp.int32)
        best = sl[pid, bj]
        dead_sl = dead_sl.at[pid, bj].max(avail[pid, bj])
        return best, dead_sl

    vals = jnp.take_along_axis(scored, sl, axis=1)
    dead_sl0 = jnp.zeros((P, K), bool)
    t("SL pass (gather+argmax_k+deadscatter)",
      sl_pass, vals, sl, dead_sl0, acc0, delta)

    # capacity resolution per pass: claim sort + segmented prefix
    def cap_resolve(best, rank, req, node_req):
        live = best >= 0
        sort_key = jnp.where(live, best * P + rank, jnp.int32(2**31 - 1))
        order = jnp.argsort(sort_key)
        s_node = jnp.where(live, best, N)[order]
        s_req = jnp.where(live[:, None], req, 0.0)[order]
        cum = jnp.cumsum(s_req, axis=0)
        before = cum - s_req
        i = jnp.arange(P, dtype=jnp.int32)
        seg_start = jnp.concatenate(
            [jnp.ones((1,), bool), s_node[1:] != s_node[:-1]])
        seg_first = jax.lax.cummax(jnp.where(seg_start, i, -1))
        seg_before = before - before[seg_first]
        nsafe = jnp.clip(s_node, 0, N - 1)
        fits = jnp.all(seg_before + s_req <= node_req[nsafe], axis=1)
        acc = jnp.zeros((P,), bool).at[order].set(fits & (s_node < N))
        return acc

    node_req = jnp.asarray(rng.random((N, 5)) + 4.0, jnp.float32)
    best0 = jnp.asarray(rng.integers(0, N, (P,)), jnp.int32)
    t("capacity resolve (sort+segprefix) [P]",
      cap_resolve, best0, ranks, req, node_req)

    # node_req scatter-add vs one-hot matmul
    def nr_scatter(node_req, best, req):
        return node_req.at[best].add(req)

    def nr_onehot(node_req, best, req):
        oh = jax.nn.one_hot(best, N, dtype=jnp.float32)  # [P,N]
        return node_req + oh.T @ req

    t("node_req scatter-add [P]->[N,R]", nr_scatter, node_req, best0, req)
    t("node_req one-hot matmul [P]->[N,R]", nr_onehot, node_req, best0,
      req)


if __name__ == "__main__":
    main()
