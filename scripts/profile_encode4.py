"""Host-encode budget attribution at config #4 (10k pods, 20% churn):
prints per-iteration delta-encode segment times from
SnapshotEncoder.delta_profile (detect / rows / ports / apply / order).

Run:  python scripts/profile_encode4.py [iters]
"""

import sys
import time

sys.path.insert(0, ".")

import jax

jax.config.update("jax_platforms", "cpu")


def main():
    iters = int(sys.argv[1]) if len(sys.argv) > 1 else 8

    from bench_suite import _draw_pending, _pad, make_config_base
    from k8s_scheduler_tpu.models import SnapshotEncoder

    enc = SnapshotEncoder(pad_pods=_pad(10000), pad_nodes=_pad(5000))
    bn, be = make_config_base(4)
    pending = None
    for i in range(iters):
        pending, groups = _draw_pending(4, i, pending, 0.2)
        t0 = time.perf_counter()
        enc.encode_packed(bn, pending, be, groups)
        dt = (time.perf_counter() - t0) * 1e3
        segs = " ".join(
            f"{k}={v:.1f}" for k, v in enc.delta_profile.items()
        )
        kind = "delta" if enc.delta_profile else "full"
        print(f"iter {i}: {dt:.1f} ms ({kind})  {segs}", flush=True)
        enc.delta_profile = {}


if __name__ == "__main__":
    main()
