"""Is it the per-snapshot re-encode that makes bench_suite 1000x slower?"""

import time

import jax

from k8s_scheduler_tpu.core import build_cycle_fn
from k8s_scheduler_tpu.models import SnapshotEncoder
from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

AFF = dict(affinity_fraction=0.3, anti_affinity_fraction=0.2,
           spread_fraction=0.2, num_apps=500)

enc = SnapshotEncoder(pad_pods=5120, pad_nodes=1024)
cycle = build_cycle_fn()
nodes = make_cluster(1000)

for i in range(4):
    pods = make_pods(5000, seed=1000 + i, **AFF)
    t0 = time.perf_counter()
    snap = enc.encode(nodes, pods)
    t1 = time.perf_counter()
    out = cycle(snap)
    jax.block_until_ready(out.assignment)
    t2 = time.perf_counter()
    out = cycle(snap)
    jax.block_until_ready(out.assignment)
    t3 = time.perf_counter()
    print(
        f"seed={1000+i} encode={t1-t0:.3f}s first={t2-t1:.3f}s "
        f"second={t3-t2:.4f}s shapes "
        f"S={snap.sel_exprs.shape} Ex={snap.ex_key.shape} "
        f"D={snap.domain_key.shape} ports={snap.num_distinct_ports} "
        f"caps=({snap.has_inter_pod_affinity},{snap.has_topology_spread})",
        flush=True,
    )
