#!/usr/bin/env python
"""audit_sharded: compile-only collective-payload gate for the sharded
carry cycle (ISSUE 10 acceptance; parallel/audit.py holds the committed
budget allowlist).

    python scripts/audit_sharded.py                # audit + assert budgets
    python scripts/audit_sharded.py --no-assert    # report only
    python scripts/audit_sharded.py --devices 8 --pods 10112 --nodes 5120

Builds the production carry-cycle program at the AUDIT SHAPE
(P=10112 x N=5120, the BENCH config-4 padded geometry AUDIT_SHARDED_r05
measured 43.2 MB/cycle on) over an N-device 1-D ('pods',) virtual CPU
mesh, compiles it with the carry partitioned — NO execution, so the
[P, N] arrays are never materialized — and parses every collective out
of the compiled HLO. The per-class totals are asserted against
`parallel/audit.COLLECTIVE_BUDGETS` and the grand total against
`TOTAL_BUDGET_MB`; schedlint ID008 pins those class names to the README
budget table and the mesh-axis names, so the allowlist can only move
together with its documentation.

Output format follows the AUDIT_SHARDED_r05 artifact (shape counts,
payload totals under BOTH the real-dtype-width model and r05's flat
4-bytes-per-element model, budget verdict, rc) so rounds stay
diffable. Exit: 0 within budget, 1 over budget, 2 build error.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _force_devices(n: int) -> None:
    flag = f"--xla_force_host_platform_device_count={n}"
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if flag not in xla_flags:
        os.environ["XLA_FLAGS"] = (xla_flags + " " + flag).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(prog="audit_sharded")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--pods", type=int, default=10112)
    ap.add_argument("--nodes", type=int, default=5120)
    ap.add_argument(
        "--no-assert", action="store_true",
        help="report payloads without gating on the budget allowlist",
    )
    args = ap.parse_args(argv)
    _force_devices(args.devices)

    import jax

    from k8s_scheduler_tpu.core import (
        build_packed_cycle_carry_fn,
        build_stable_state_fn,
    )
    from k8s_scheduler_tpu.core.cycle import CarryKeeper
    from k8s_scheduler_tpu.models import SnapshotEncoder
    from k8s_scheduler_tpu.parallel import audit
    from k8s_scheduler_tpu.parallel.mesh import make_mesh
    from k8s_scheduler_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    enable_compilation_cache()
    P, N = args.pods, args.nodes
    mesh = make_mesh(jax.devices()[: args.devices])

    # the BENCH config-4 pending distribution at the audit scale —
    # affinity/spread/selector terms keep every guard path compiled in
    nodes = make_cluster(
        min(N, 5000), taint_fraction=0.1, cpu_choices=(4, 8, 16)
    )
    pods = make_pods(
        min(P, 10000), seed=0, affinity_fraction=0.3,
        anti_affinity_fraction=0.2, spread_fraction=0.2,
        selector_fraction=0.3, toleration_fraction=0.1,
        priorities=(0, 0, 10, 100), num_apps=500,
    )
    enc = SnapshotEncoder(pad_pods=P, pad_nodes=N)
    wbuf, bbuf, spec, _vs, _dirty = enc.encode_packed(nodes, pods)

    import numpy as np

    w = jax.ShapeDtypeStruct((spec.n_words,), np.uint32)
    b = jax.ShapeDtypeStruct((spec.n_bytes,), np.uint8)

    try:
        stable_fn = build_stable_state_fn(spec)
        stable_sds = jax.tree_util.tree_map(
            lambda o: jax.ShapeDtypeStruct(o.shape, o.dtype),
            stable_fn.lower(w, b).out_info,
        )
        keeper = CarryKeeper(spec, mesh=mesh)
        carry_low = keeper.ci.lower(w, b, stable_sds)
        carry_sds = jax.tree_util.tree_map(
            lambda o: jax.ShapeDtypeStruct(
                o.shape, o.dtype, sharding=getattr(o, "sharding", None)
            ),
            carry_low.out_info,
        )
        cyc = build_packed_cycle_carry_fn(
            spec, mesh=mesh, rounds_kw={"compact_gather": "onehot"}
        )
        compiled = cyc.lower(w, b, stable_sds, carry_sds).compile()
    except Exception as e:
        print(f"audit_sharded: build failed: {e}", file=sys.stderr)
        return 2

    hlo = compiled.as_text()
    colls = audit.parse_collectives(hlo)
    mb = 1024.0 * 1024.0

    # ---- the r05-style shape histogram ----
    from collections import Counter

    hist = Counter((c.base_op, c.type_str, c.bytes) for c in colls)
    print(f"P={P} N={N} devices={args.devices} collectives={len(colls)}")
    for (op, tstr, nbytes), cnt in sorted(
        hist.items(), key=lambda kv: -kv[1]
    ):
        print(
            f"{cnt:>5} x {op:<20} {tstr}  (~{nbytes / 1024.0:.1f} KB "
            "each)"
        )

    total = sum(c.bytes for c in colls)
    flat4 = sum(c.flat4 for c in colls)
    by_class = audit.classify_totals(colls, P, N)
    print(
        f"approx collective payload total: {total / mb:.2f} MB "
        f"(flat-4B model, r05-comparable: {flat4 / mb:.2f} MB)"
    )
    biggest = max(colls, key=lambda c: c.elems, default=None)
    if biggest is not None:
        print(
            f"max single-collective payload: {biggest.elems} elems "
            f"({biggest.bytes / mb:.2f} MB) {biggest.type_str}"
        )
    for cls in sorted(audit.COLLECTIVE_BUDGETS):
        print(
            f"class {cls:<12} {by_class.get(cls, 0) / mb:>8.2f} MB "
            f"(budget {audit.COLLECTIVE_BUDGETS[cls]:.2f} MB)"
        )

    if args.no_assert:
        print("budget assertion SKIPPED (--no-assert)")
        return 0
    problems = audit.check_budgets(by_class)
    if problems:
        for p in problems:
            print(f"BUDGET VIOLATION: {p}")
        print("compile-only audit FAILED (payload over budget)")
        return 1
    print("compile-only audit PASSED (payload bounds asserted)")
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    rc = main()
    print(f"rc={rc}")
    sys.exit(rc)
