"""Oracle: a straightforward per-pod Python reimplementation of the
reference scheduler's semantics, used as the differential-test ground truth
for the batched JAX kernels (SURVEY.md §4 "build-side additions") and as the
CPU fallback path when no accelerator is available.

It deliberately mirrors the reference's shape — one pod at a time in
priority order, Filter plugins then Score plugins then selectHost, state
updated between pods (SURVEY.md §3.2) — NOT the batched design, so that
agreement between the two is meaningful evidence of parity.

Tie-breaking: lowest node index on equal score (the deterministic stand-in
for upstream's random reservoir tie-break; both implementations use it so
differential tests are exact).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

from .config.types import _DEFAULT_FILTERS as _FILTER_ORDER
from .models import api
from .models.api import (
    Affinity,
    LabelSelector,
    Node,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
    PodAffinityTerm,
)

MAX_NODE_SCORE = 100.0


def _match_expression(labels: dict[str, str], req: NodeSelectorRequirement,
                      name: str | None = None) -> bool:
    """labels.Requirement semantics: NotIn/DoesNotExist match on absent key."""
    op = req.operator
    if name is not None:  # matchFields metadata.name
        if op == api.OP_IN:
            return name in req.values
        if op == api.OP_NOT_IN:
            return name not in req.values
        return False
    present = req.key in labels
    val = labels.get(req.key)
    if op == api.OP_IN:
        return present and val in req.values
    if op == api.OP_NOT_IN:
        return not present or val not in req.values
    if op == api.OP_EXISTS:
        return present
    if op == api.OP_DOES_NOT_EXIST:
        return not present
    if op == api.OP_GT:
        try:
            return present and float(val) > float(req.values[0])
        except (ValueError, IndexError):
            return False
    if op == api.OP_LT:
        try:
            return present and float(val) < float(req.values[0])
        except (ValueError, IndexError):
            return False
    raise ValueError(f"unknown operator {op}")


def _match_term(node: Node, term: NodeSelectorTerm) -> bool:
    labels = _node_labels(node)
    return all(
        _match_expression(labels, e) for e in term.match_expressions
    ) and all(
        _match_expression({}, e, name=node.name) for e in term.match_fields
    )


def _node_labels(node: Node) -> dict[str, str]:
    labels = dict(node.metadata.labels)
    labels.setdefault("kubernetes.io/hostname", node.name)
    return labels


def match_label_selector(sel: LabelSelector, labels: dict[str, str]) -> bool:
    for k, v in sel.match_labels.items():
        if labels.get(k) != v:
            return False
    return all(_match_expression(labels, e) for e in sel.match_expressions)


def tolerates(pod: Pod, taint: api.Taint) -> bool:
    for t in pod.spec.tolerations:
        if t.effect and t.effect != taint.effect:
            continue
        if t.operator == "Exists":
            if t.key == "" or t.key == taint.key:
                return True
        else:  # Equal
            if t.key == taint.key and t.value == taint.value:
                return True
    return False


@dataclasses.dataclass
class OracleState:
    """Mutable per-node state mirroring NodeInfo aggregation."""

    nodes: list[Node]
    requested: list[dict[str, float]]  # per node
    pods_on_node: list[list[Pod]]  # per node (existing + committed this run)

    # memoized per-pod / per-image quantities that scoring would otherwise
    # recompute once per candidate node (O(P*N^2) without these)
    _taint_max: dict[str, int] = dataclasses.field(default_factory=dict)
    _image_spread: dict[str, float] = dataclasses.field(default_factory=dict)
    # bootstrap any_match is node-independent; cache per (pod, term) and
    # invalidate via a version bumped on every add/remove
    _version: int = 0
    _bootstrap: dict = dataclasses.field(default_factory=dict)
    # volumes (VolumeBinding): keyed maps, empty = no volume constraints
    pvcs: dict = dataclasses.field(default_factory=dict)  # "ns/name" -> PVC
    pvs: dict = dataclasses.field(default_factory=dict)  # name -> PV
    storage_classes: dict = dataclasses.field(default_factory=dict)
    # derived volume indexes (built once; volume state is per-cycle input)
    pvs_by_class: dict = dataclasses.field(default_factory=dict)
    claimed_pv_names: set = dataclasses.field(default_factory=set)
    # in-cycle static-PV claims (VERDICT r2 item 8): a committed pod with
    # an unbound WaitForFirstConsumer claim takes the lowest-index
    # compatible PV (the kernels' deterministic binder choice); later
    # pods in the same cycle see it as unavailable
    pv_list: list = dataclasses.field(default_factory=list)
    claimed_static: set = dataclasses.field(default_factory=set)
    pod_claims: dict = dataclasses.field(default_factory=dict)

    @staticmethod
    def build(
        nodes: Sequence[Node],
        existing: Sequence[tuple[Pod, str]],
        pvcs: Sequence = (),
        pvs: Sequence = (),
        storage_classes: Sequence = (),
    ) -> "OracleState":
        idx = {n.name: i for i, n in enumerate(nodes)}
        by_class: dict = {}
        for v in pvs:
            by_class.setdefault(v.storage_class, []).append(v)
        st = OracleState(
            nodes=list(nodes),
            requested=[{} for _ in nodes],
            pods_on_node=[[] for _ in nodes],
            pvcs={c.key: c for c in pvcs},
            pvs={v.name: v for v in pvs},
            storage_classes={s.name: s for s in storage_classes},
            pvs_by_class=by_class,
            claimed_pv_names={
                c.volume_name for c in pvcs if c.volume_name
            },
            pv_list=list(pvs),
        )
        for pod, node_name in existing:
            i = idx.get(node_name)
            if i is None:
                continue
            # existing pods' volume usage is already reflected through
            # their PVCs' volume_name (claimed_pv_names); no in-cycle
            # claim (mirrors the encoder's pv_avail)
            st.add(i, pod, claim_volumes=False)
        return st

    def add(self, node_idx: int, pod: Pod,
            claim_volumes: bool = True) -> None:
        for r, v in pod.resource_requests().items():
            self.requested[node_idx][r] = self.requested[node_idx].get(r, 0.0) + v
        self.pods_on_node[node_idx].append(pod)
        self._version += 1
        self._bootstrap.clear()  # keys embed _version; old entries are dead
        if claim_volumes and pod.spec.volumes:
            self._claim_static_pvs(node_idx, pod)

    def _claim_static_pvs(self, node_idx: int, pod: Pod) -> None:
        """Mirror of ops/volumes.chosen_pv_sdr + fold_pv_claims: slots
        claim in spec order; each claims the LOWEST-INDEX compatible
        available unclaimed PV whose removal keeps Hall's condition over
        the pod's remaining static-needy slots (the SDR-safe choice —
        exact: it always extends to a full distinct assignment when one
        exists). A dynamic-capable slot with no safe candidate rides
        dynamic instead of stealing; a needy slot with no safe candidate
        falls back to the lowest candidate (beyond Hall's guarantee)."""
        import itertools

        claims = []
        node = self.nodes[node_idx]
        slots = []  # (pvc, dyn_capable) in spec order
        for claim in pod.spec.volumes:
            pvc = self.pvcs.get(f"{pod.namespace}/{claim}")
            if pvc is None or pvc.volume_name:
                continue
            cls = self.storage_classes.get(pvc.storage_class)
            if cls is None or cls.volume_binding_mode != api.VOLUME_BINDING_WAIT:
                continue
            dyn = bool(cls.provisioner) and (
                not cls.allowed_topologies
                or any(_match_term(node, t) for t in cls.allowed_topologies)
            )
            slots.append((pvc, dyn))

        def cand_of(pvc):  # current claimable PVs, pv_list order
            return [
                pv
                for pv in self.pv_list
                if pv.storage_class == pvc.storage_class
                and _pv_usable(self, pv, pvc, node)
            ]

        def other_subsets(needy_cands):
            """Mirror of ops/volumes._sdr_other_subsets plus the
            capped-regime dominance groups of _sdr_safe_choice."""
            others = sorted(needy_cands)
            if len(others) <= 6:
                return [
                    s
                    for r in range(1, len(others) + 1)
                    for s in itertools.combinations(others, r)
                ]
            subs = [
                *itertools.combinations(others, 1),
                *itertools.combinations(others, 2),
                tuple(others),
            ]
            for a in others:  # dominance groups (needy down-sets)
                subs.append(tuple(
                    t for t in others
                    if needy_cands[t] <= needy_cands[a]
                ))
            return subs

        for j, (pvc, dyn) in enumerate(slots):
            cand = cand_of(pvc)
            # needy = later unresolved slots that REQUIRE a static PV
            needy = [
                (t, slots[t][0])
                for t in range(j + 1, len(slots))
                if not slots[t][1]
            ]
            needy_cands = {t: {pv.name for pv in cand_of(p)} for t, p in needy}
            # tight unions are PV-independent: compute once per slot, not
            # per candidate — a PV is unsafe iff it lies in any of them
            unsafe = set()
            for s in other_subsets(needy_cands):
                union = set().union(*(needy_cands[t] for t in s))
                if len(union) - 1 < len(s):
                    unsafe |= union
            chosen = None
            for pv in cand:
                if pv.name not in unsafe:
                    chosen = pv
                    break
            if chosen is None and not dyn and cand:
                chosen = cand[0]
            if chosen is not None:
                self.claimed_static.add(chosen.name)
                claims.append(chosen.name)
        if claims:
            self.pod_claims[id(pod)] = claims

    def remove(self, node_idx: int, pod: Pod) -> None:
        for r, v in pod.resource_requests().items():
            self.requested[node_idx][r] = self.requested[node_idx].get(r, 0.0) - v
        self.pods_on_node[node_idx].remove(pod)
        self._version += 1
        self._bootstrap.clear()
        for name in self.pod_claims.pop(id(pod), ()):
            self.claimed_static.discard(name)

    def any_pod_matches(self, term: PodAffinityTerm, own_ns: str) -> bool:
        key = (self._version, id(term), own_ns)
        hit = self._bootstrap.get(key)
        if hit is None:
            hit = any(
                _term_matches_pod(term, own_ns, other)
                for pods in self.pods_on_node
                for other in pods
            )
            self._bootstrap[key] = hit
        return hit

    def free(self, node_idx: int) -> dict[str, float]:
        alloc = self.nodes[node_idx].status.allocatable
        return {
            r: alloc.get(r, 0.0) - self.requested[node_idx].get(r, 0.0)
            for r in set(alloc) | set(self.requested[node_idx])
        }


# --------------------------------------------------------------------------
# Filter plugins (feasibility predicates)
# --------------------------------------------------------------------------


def filter_node_resources_fit(pod: Pod, state: OracleState, i: int) -> bool:
    alloc = state.nodes[i].status.allocatable
    used = state.requested[i]
    for r, v in pod.resource_requests().items():
        if used.get(r, 0.0) + v > alloc.get(r, 0.0) * (1 + 1e-5) + 1e-5:
            return False
    return True


def filter_node_name(pod: Pod, state: OracleState, i: int) -> bool:
    return not pod.spec.node_name or pod.spec.node_name == state.nodes[i].name


def filter_node_unschedulable(pod: Pod, state: OracleState, i: int) -> bool:
    return not state.nodes[i].spec.unschedulable


def filter_node_affinity(pod: Pod, state: OracleState, i: int) -> bool:
    node = state.nodes[i]
    labels = _node_labels(node)
    for k, v in pod.spec.node_selector.items():
        if labels.get(k) != v:
            return False
    aff = pod.spec.affinity
    if aff and aff.node_affinity and aff.node_affinity.required:
        if not any(_match_term(node, t) for t in aff.node_affinity.required):
            return False
    return True


def filter_taint_toleration(pod: Pod, state: OracleState, i: int) -> bool:
    for taint in state.nodes[i].spec.taints:
        if taint.effect in (api.NO_SCHEDULE, api.NO_EXECUTE) and not tolerates(pod, taint):
            return False
    return True


def filter_node_ports(pod: Pod, state: OracleState, i: int) -> bool:
    wanted = {(p, proto) for (p, proto, _ip) in pod.host_ports()}
    if not wanted:
        return True
    used = set()
    for other in state.pods_on_node[i]:
        for (p, proto, _ip) in other.host_ports():
            used.add((p, proto))
    return not (wanted & used)


def _domain(node: Node, topology_key: str) -> str | None:
    return _node_labels(node).get(topology_key)


def _term_matches_pod(term: PodAffinityTerm, own_ns: str, other: Pod) -> bool:
    namespaces = term.namespaces or (own_ns,)
    if other.namespace not in namespaces:
        return False
    return match_label_selector(term.label_selector, other.metadata.labels)


def filter_inter_pod_affinity(pod: Pod, state: OracleState, i: int) -> bool:
    node = state.nodes[i]
    aff = pod.spec.affinity or Affinity()
    # required pod affinity: each term needs >=1 matching pod in the domain
    if aff.pod_affinity:
        for term in aff.pod_affinity.required:
            # upstream bootstrap rule: when NO pod anywhere matches the
            # selector and the incoming pod matches its own selector, the
            # term is ignored (lets the first pod of a self-affine group in)
            if not state.any_pod_matches(term, pod.namespace) and _term_matches_pod(
                term, pod.namespace, pod
            ):
                continue
            dom = _domain(node, term.topology_key)
            if dom is None:
                return False
            found = False
            for j, nd in enumerate(state.nodes):
                if _domain(nd, term.topology_key) != dom:
                    continue
                for other in state.pods_on_node[j]:
                    if _term_matches_pod(term, pod.namespace, other):
                        found = True
                        break
                if found:
                    break
            if not found:
                return False
    # required anti-affinity: no matching pod in the domain
    if aff.pod_anti_affinity:
        for term in aff.pod_anti_affinity.required:
            dom = _domain(node, term.topology_key)
            if dom is None:
                continue  # upstream: absent key -> term can't be violated
            for j, nd in enumerate(state.nodes):
                if _domain(nd, term.topology_key) != dom:
                    continue
                for other in state.pods_on_node[j]:
                    if _term_matches_pod(term, pod.namespace, other):
                        return False
    # symmetry: existing pods' required anti-affinity must not be violated
    for j, nd in enumerate(state.nodes):
        for other in state.pods_on_node[j]:
            oa = other.spec.affinity
            if not oa or not oa.pod_anti_affinity:
                continue
            for term in oa.pod_anti_affinity.required:
                dom_other = _domain(nd, term.topology_key)
                dom_new = _domain(node, term.topology_key)
                if dom_other is None or dom_new != dom_other:
                    continue
                if _term_matches_pod(term, other.namespace, pod):
                    return False
    return True


def _pv_usable(state: OracleState, pv, pvc, node) -> bool:
    """ONE eligibility rule shared by the VolumeBinding filter (any-fit)
    and the claim step (first-fit over pv_list): available, unclaimed
    (pre-cycle AND in-cycle), big enough, admissible on the node."""
    if (
        pv.claim_ref
        or pv.name in state.claimed_pv_names
        or pv.name in state.claimed_static
    ):
        return False
    if pv.capacity + 1e-3 < pvc.request:
        return False
    if pv.node_affinity and not any(
        _match_term(node, t) for t in pv.node_affinity
    ):
        return False
    return True


def filter_volume_binding(pod: Pod, state: OracleState, i: int) -> bool:
    """Mirror of ops/volumes.py: bound-PV node affinity; unbound
    WaitForFirstConsumer claims need a static candidate PV or dynamic
    provisioning whose allowedTopologies admit the node; missing PVCs and
    unbound Immediate claims are unschedulable."""
    if not pod.spec.volumes:
        return True
    node = state.nodes[i]
    static_required: list[set] = []
    for claim in pod.spec.volumes:
        pvc = state.pvcs.get(f"{pod.namespace}/{claim}")
        if pvc is None:
            return False
        if pvc.volume_name:
            pv = state.pvs.get(pvc.volume_name)
            if pv is None:
                return False
            if pv.node_affinity and not any(
                _match_term(node, t) for t in pv.node_affinity
            ):
                return False
            continue
        cls = state.storage_classes.get(pvc.storage_class)
        if cls is None or cls.volume_binding_mode != api.VOLUME_BINDING_WAIT:
            return False
        cand = {
            pv.name
            for pv in state.pvs_by_class.get(pvc.storage_class, ())
            if _pv_usable(state, pv, pvc, node)
        }
        dyn = bool(cls.provisioner) and (
            not cls.allowed_topologies
            or any(_match_term(node, t) for t in cls.allowed_topologies)
        )
        if not cand and not dyn:
            return False
        if not dyn:
            static_required.append(cand)
    # Hall's condition across the pod's static-required slots (PARITY #8
    # closure, mirrors ops/volumes._hall_ok): DISTINCT PVs must exist —
    # a pod whose two PVCs are satisfiable only by one PV is infeasible
    if len(static_required) >= 2:
        import itertools

        for r in range(2, len(static_required) + 1):
            for s in itertools.combinations(static_required, r):
                if len(set().union(*s)) < r:
                    return False
    return True


def filter_topology_spread(pod: Pod, state: OracleState, i: int) -> bool:
    node = state.nodes[i]
    for c in pod.spec.topology_spread_constraints:
        if c.when_unsatisfiable != api.DO_NOT_SCHEDULE:
            continue
        dom = _domain(node, c.topology_key)
        if dom is None:
            return False
        counts: dict[str, int] = {}
        for j, nd in enumerate(state.nodes):
            d = _domain(nd, c.topology_key)
            if d is None:
                continue
            counts.setdefault(d, 0)
            for other in state.pods_on_node[j]:
                if other.namespace == pod.namespace and match_label_selector(
                    c.label_selector, other.metadata.labels
                ):
                    counts[d] += 1
        if not counts:
            continue
        min_count = min(counts.values())
        if counts.get(dom, 0) + 1 - min_count > c.max_skew:
            return False
    return True


DEFAULT_FILTERS = (
    filter_node_unschedulable,
    filter_node_name,
    filter_taint_toleration,
    filter_node_affinity,
    filter_node_ports,
    filter_node_resources_fit,
    filter_volume_binding,
    filter_inter_pod_affinity,
    filter_topology_spread,
)

# Plugin names aligned 1:1 with DEFAULT_FILTERS, imported from the ONE
# inventory of record (config/types._DEFAULT_FILTERS — the framework's
# Filter execution order and therefore the column order of the kernels'
# reject-count tables). The trace-level differential (fuzz/) compares
# unschedulable REASONS tuples, so this alignment is load-bearing: a
# second hand-written copy here would drift the moment the plugin list
# changes and read as a phantom engine divergence.
FILTER_PLUGIN_NAMES = tuple(_FILTER_ORDER)

# name lookup for REASONS labeling: keyed by the filter FUNCTION so a
# caller passing a custom `filters` subset gets each filter's own name
# (zip against the full inventory would silently shift labels), and an
# unknown custom filter fails loudly with a KeyError
_FILTER_NAME_OF = dict(zip(DEFAULT_FILTERS, FILTER_PLUGIN_NAMES))
assert len(_FILTER_NAME_OF) == len(FILTER_PLUGIN_NAMES) == len(
    DEFAULT_FILTERS
), "oracle filters and config/types._DEFAULT_FILTERS drifted"


# Filters whose kernel plugin implements a STATIC mask
# (framework/plugins.py): the node-only predicates, NodePorts (existing
# pods' ports are stable-side), and VolumeBinding (pre-cycle
# availability). NodeResourcesFit, InterPodAffinity and
# PodTopologySpread define ONLY dyn_mask — their whole constraint
# (existing pods included) evaluates in the dynamic phase, so the
# attribution mirror must not let them first-reject a node statically.
_STATIC_PART_FILTERS = frozenset({
    filter_node_unschedulable,
    filter_node_name,
    filter_taint_toleration,
    filter_node_affinity,
    filter_node_ports,
    filter_volume_binding,
})


def attribute_rejects(
    pod: Pod,
    pre_state: OracleState,
    dyn_state: OracleState,
    filters=DEFAULT_FILTERS,
) -> list[int]:
    """First-rejector counts per filter, mirroring the kernels'
    attribution structure (framework.runtime.Framework.static + dyn):
    TWO phases per node, matching each plugin's static/dynamic split
    in framework/plugins.py:

    1. first filter WITH A STATIC PART (`_STATIC_PART_FILTERS`) whose
       check fails against `pre_state` — the static-table attribution;
       wholly-dynamic plugins (resources fit, inter-pod affinity,
       topology spread) are skipped here even when the pre-state alone
       would reject, because the kernel evaluates their entire
       constraint as a dynamic mask;
    2. for statically-feasible nodes only, first filter in full order
       whose check fails against `dyn_state` — the state the engine's
       dynamic masks actually saw: the pod's OWN scan step for the
       fused scan program (greedy_commit evaluates dyn_fn at the pod's
       turn, with earlier placements INCLUDING later-gang-unwound
       ones), the final post-cycle state for the rounds/diagnosis
       programs. Static-only predicates can never newly fail here, and
       a ports/volume conflict with EXISTING pods was already taken in
       phase 1, so running the full combined checks reproduces the
       kernel's per-plugin dyn increments.
    """
    counts = [0] * len(filters)
    for i in range(len(pre_state.nodes)):
        statically_rejected = False
        for fi, f in enumerate(filters):
            if f not in _STATIC_PART_FILTERS:
                continue
            if not f(pod, pre_state, i):
                counts[fi] += 1
                statically_rejected = True
                break
        if statically_rejected:
            continue
        for fi, f in enumerate(filters):
            if not f(pod, dyn_state, i):
                counts[fi] += 1
                break
    return counts


# --------------------------------------------------------------------------
# Score plugins
# --------------------------------------------------------------------------


def _score_fracs(pod: Pod, state: OracleState, i: int,
                 resources: Sequence[str]) -> list[float]:
    alloc = state.nodes[i].status.allocatable
    req = pod.resource_requests()
    fracs = []
    for r in resources:
        a = alloc.get(r, 0.0)
        after = state.requested[i].get(r, 0.0) + req.get(r, 0.0)
        fracs.append(min(max(after / a, 0.0), 1.0) if a > 0 else 1.0)
    return fracs


def score_least_requested(pod: Pod, state: OracleState, i: int,
                          resources: Sequence[str] = ("cpu", "memory")) -> float:
    fracs = _score_fracs(pod, state, i, resources)
    return sum((1.0 - f) * MAX_NODE_SCORE for f in fracs) / len(fracs)


def score_balanced_allocation(pod: Pod, state: OracleState, i: int,
                              resources: Sequence[str] = ("cpu", "memory")) -> float:
    fracs = _score_fracs(pod, state, i, resources)
    mean = sum(fracs) / len(fracs)
    var = sum((f - mean) ** 2 for f in fracs) / len(fracs)
    return (1.0 - math.sqrt(var)) * MAX_NODE_SCORE


def score_node_affinity(pod: Pod, state: OracleState, i: int) -> float:
    aff = pod.spec.affinity
    if not aff or not aff.node_affinity or not aff.node_affinity.preferred:
        return 0.0
    total = sum(p.weight for p in aff.node_affinity.preferred)
    if total <= 0:
        return 0.0
    got = sum(
        p.weight
        for p in aff.node_affinity.preferred
        if _match_term(state.nodes[i], p.preference)
    )
    return got / total * MAX_NODE_SCORE


def _untolerated_prefer_count(pod: Pod, state: OracleState, i: int) -> int:
    return sum(
        1
        for t in state.nodes[i].spec.taints
        if t.effect == api.PREFER_NO_SCHEDULE and not tolerates(pod, t)
    )


def score_taint_toleration(pod: Pod, state: OracleState, i: int) -> float:
    """Fewer untolerated PreferNoSchedule taints -> higher score, normalized
    by the max count over ALL nodes (DefaultNormalizeScore(reverse=true)
    analogue; same documented deviation as ops/taints.py: the max is over
    all nodes, not just feasible ones). The per-pod max is memoized on the
    state (taints don't change during a run)."""
    mx = state._taint_max.get(pod.uid)
    if mx is None:
        mx = max(
            (_untolerated_prefer_count(pod, state, j) for j in range(len(state.nodes))),
            default=0,
        )
        state._taint_max[pod.uid] = mx
    if mx == 0:
        return MAX_NODE_SCORE
    return (1.0 - _untolerated_prefer_count(pod, state, i) / mx) * MAX_NODE_SCORE


def _spread(state: OracleState, name: str) -> float:
    """Fraction of nodes holding an image; memoized (images are static)."""
    s = state._image_spread.get(name)
    if s is None:
        n = sum(
            1
            for nd in state.nodes
            if any(name in im.names for im in nd.status.images)
        )
        s = n / max(len(state.nodes), 1)
        state._image_spread[name] = s
    return s


def score_image_locality(pod: Pod, state: OracleState, i: int) -> float:
    images = {}
    for img in state.nodes[i].status.images:
        for nm in img.names:
            images[nm] = img.size_bytes
    # image size scaled by spread (upstream scaledImageScore), then the
    # 23MB..1GB ramp (upstream calculatePriority thresholds)
    have = sum(
        images.get(im, 0) * _spread(state, im) for im in pod.images() if im in images
    )
    lo, hi = 23 * 2**20, 2**30
    clipped = min(max(have, lo), hi)
    return (clipped - lo) / (hi - lo) * MAX_NODE_SCORE


def score_inter_pod_affinity(pod: Pod, state: OracleState, i: int) -> float:
    """Preferred affinity/anti-affinity terms, both directions (incoming
    pod's preferences against existing pods, and existing pods' preferences
    against the incoming pod). Raw weighted sum; normalized by caller."""
    node = state.nodes[i]
    score = 0.0
    aff = pod.spec.affinity or Affinity()
    prefs = []
    if aff.pod_affinity:
        prefs += [(w.weight, w.term) for w in aff.pod_affinity.preferred]
    if aff.pod_anti_affinity:
        prefs += [(-w.weight, w.term) for w in aff.pod_anti_affinity.preferred]
    for weight, term in prefs:
        dom = _domain(node, term.topology_key)
        if dom is None:
            continue
        for j, nd in enumerate(state.nodes):
            if _domain(nd, term.topology_key) != dom:
                continue
            for other in state.pods_on_node[j]:
                if _term_matches_pod(term, pod.namespace, other):
                    score += weight
    # symmetric: existing pods' preferred terms matching the incoming pod
    for j, nd in enumerate(state.nodes):
        for other in state.pods_on_node[j]:
            oa = other.spec.affinity or Affinity()
            oprefs = []
            if oa.pod_affinity:
                oprefs += [(w.weight, w.term) for w in oa.pod_affinity.preferred]
            if oa.pod_anti_affinity:
                oprefs += [(-w.weight, w.term) for w in oa.pod_anti_affinity.preferred]
            for weight, term in oprefs:
                dom_other = _domain(nd, term.topology_key)
                if dom_other is None or _domain(node, term.topology_key) != dom_other:
                    continue
                if _term_matches_pod(term, other.namespace, pod):
                    score += weight
    return score


def _spread_domain_counts(pod: Pod, state: OracleState,
                          c: api.TopologySpreadConstraint) -> dict[str, float]:
    """Matching-pod count per domain for one constraint — computed ONCE per
    (pod, constraint) instead of rescanning all nodes per candidate node."""
    counts: dict[str, float] = {}
    for j, nd in enumerate(state.nodes):
        d = _domain(nd, c.topology_key)
        if d is None:
            continue
        counts.setdefault(d, 0.0)
        for other in state.pods_on_node[j]:
            if other.namespace == pod.namespace and match_label_selector(
                c.label_selector, other.metadata.labels
            ):
                counts[d] += 1.0
    return counts


def score_topology_spread_raw(pod: Pod, state: OracleState, i: int,
                              _counts=None) -> float:
    """ScheduleAnyway constraints: matching-pod count in the node's domain
    (summed over constraints); the caller reverse-normalizes over feasible
    nodes — identical to ops/interpod.spread_dyn_score. `_counts` is the
    precomputed per-constraint domain-count list (see _spread_domain_counts);
    omitted, it is computed here."""
    node = state.nodes[i]
    constraints = [c for c in pod.spec.topology_spread_constraints
                   if c.when_unsatisfiable == api.SCHEDULE_ANYWAY]
    if _counts is None:
        _counts = [_spread_domain_counts(pod, state, c) for c in constraints]
    raw = 0.0
    for c, counts in zip(constraints, _counts):
        dom = _domain(node, c.topology_key)
        if dom is not None:
            raw += counts.get(dom, 0.0)
    return raw


# --------------------------------------------------------------------------
# The sequential scheduler
# --------------------------------------------------------------------------


@dataclasses.dataclass
class OracleDecision:
    pod: Pod
    node_index: int  # -1 = unschedulable


@dataclasses.dataclass(frozen=True)
class OracleWeights:
    """Defaults mirror the default-plugin score weights in config/types.py
    (TaintToleration 3, others 1; InterPodAffinity joins when its kernel
    lands so both sides stay in lockstep)."""

    least_requested: float = 1.0
    balanced_allocation: float = 1.0
    node_affinity: float = 1.0
    taint_toleration: float = 3.0
    image_locality: float = 1.0
    inter_pod_affinity: float = 1.0
    topology_spread: float = 2.0


def queue_order(pending: Sequence[Pod]) -> list[int]:
    """The queue's pop order: priority desc, creation asc, index (the
    PrioritySort QueueSort plugin; same key as the encoder's pod_order)."""
    return sorted(
        range(len(pending)),
        key=lambda i: (-pending[i].spec.priority,
                       pending[i].metadata.creation_timestamp, i),
    )


def feasible_nodes(pod: Pod, state: OracleState, filters) -> list[int]:
    """Filter pass + nominated-node narrowing (upstream evaluates the
    nominated node first and keeps it when it passes filters)."""
    feasible = [
        i for i in range(len(state.nodes))
        if all(f(pod, state, i) for f in filters)
    ]
    if pod.nominated_node_name:
        for i in feasible:
            if state.nodes[i].name == pod.nominated_node_name:
                return [i]
    return feasible


@dataclasses.dataclass
class _CrossNodeRaws:
    """Raw scores needing cross-node normalization over the feasible set
    (upstream NormalizeScore runs after Filter)."""

    ipa: dict
    ipa_hi: float
    spread: dict
    spread_hi: float

    @staticmethod
    def compute(pod: Pod, state: OracleState, feasible: list[int],
                weights: "OracleWeights") -> "_CrossNodeRaws":
        ipa, spread = {}, {}
        if weights.inter_pod_affinity:
            ipa = {i: score_inter_pod_affinity(pod, state, i) for i in feasible}
        if weights.topology_spread and pod.spec.topology_spread_constraints:
            constraints = [c for c in pod.spec.topology_spread_constraints
                           if c.when_unsatisfiable == api.SCHEDULE_ANYWAY]
            counts = [_spread_domain_counts(pod, state, c) for c in constraints]
            spread = {
                i: score_topology_spread_raw(pod, state, i, counts)
                for i in feasible
            }
        return _CrossNodeRaws(
            ipa, max(map(abs, ipa.values()), default=0.0),
            spread, max(spread.values(), default=0.0),
        )


def _score_pod(pod: Pod, state: OracleState, i: int, weights: OracleWeights,
               cn: "_CrossNodeRaws | None" = None) -> float:
    s = (
        weights.least_requested * score_least_requested(pod, state, i)
        + weights.balanced_allocation * score_balanced_allocation(pod, state, i)
        + weights.node_affinity * score_node_affinity(pod, state, i)
        + weights.taint_toleration * score_taint_toleration(pod, state, i)
        + weights.image_locality * score_image_locality(pod, state, i)
    )
    if cn is not None:
        if weights.inter_pod_affinity and cn.ipa_hi > 0:
            s += weights.inter_pod_affinity * (cn.ipa[i] / cn.ipa_hi) * MAX_NODE_SCORE
        if weights.topology_spread and pod.spec.topology_spread_constraints:
            if cn.spread_hi > 0:
                s += weights.topology_spread * (
                    1.0 - cn.spread[i] / cn.spread_hi
                ) * MAX_NODE_SCORE
            else:
                s += weights.topology_spread * MAX_NODE_SCORE
    return s


def validate_assignment(
    nodes: Sequence[Node],
    pending: Sequence[Pod],
    assignment: Sequence[int],
    existing: Sequence[tuple[Pod, str]] = (),
    weights: OracleWeights = OracleWeights(),
    filters=DEFAULT_FILTERS,
    tol: float = 0.05,
) -> list[str]:
    """Semantic differential check that is robust to f32-vs-f64 score ties.

    Replays the kernel's assignment through the oracle's sequential state:
    each chosen node must be oracle-feasible at that point and its oracle
    score within `tol` of the oracle's best feasible score (the batched
    kernel computes scores in float32, so two nodes whose f64 scores differ
    by ~1e-4 are legitimately interchangeable); -1 requires that NO node be
    feasible. Returns a list of human-readable violations (empty = valid)."""
    state = OracleState.build(nodes, existing)
    errors = []
    for pi in queue_order(pending):
        pod = pending[pi]
        node = assignment[pi]
        feasible = feasible_nodes(pod, state, filters)
        if node < 0:
            if feasible:
                errors.append(
                    f"{pod.name}: kernel says unschedulable but oracle finds "
                    f"feasible nodes {feasible}"
                )
            continue
        if node not in feasible:
            errors.append(f"{pod.name}: node {node} infeasible per oracle "
                          f"(feasible: {feasible})")
            continue
        cn = _CrossNodeRaws.compute(pod, state, feasible, weights)
        scores = {i: _score_pod(pod, state, i, weights, cn) for i in feasible}
        best = max(scores.values())
        if scores[node] < best - tol:
            errors.append(
                f"{pod.name}: node {node} scores {scores[node]:.4f}, "
                f"{best - scores[node]:.4f} below best {best:.4f}"
            )
        state.add(node, pod)
    return errors


def validate_rounds_assignment(
    nodes: Sequence[Node],
    pending: Sequence[Pod],
    assignment: Sequence[int],
    existing: Sequence[tuple[Pod, str]] = (),
    round_cap_hit: bool = False,
    allow_feasible_unplaced: Sequence[int] = (),
    pvcs: Sequence = (),
    pvs: Sequence = (),
    storage_classes: Sequence = (),
) -> list[str]:
    """Validity invariants for the round-based commit (ops/rounds.py).

    Unlike `validate_assignment` (which replays strict sequential
    semantics), this checks the FINAL state: with every placement applied,
    each placed pod's hard constraints must hold —
      - static filters (unschedulable/name/taints/node-affinity) exactly;
      - per-node capacity and hostPort uniqueness as aggregates;
      - required anti-affinity strictly (no other matching pod in any of
        the pod's anti domains), in both directions;
      - required affinity with the bootstrap allowance (a pod matching its
        own selector may stand alone);
      - DoNotSchedule spread as final skew <= maxSkew.
    Unplaced pods must be infeasible against the final state, unless the
    round cap was hit or they are listed in `allow_feasible_unplaced`
    (gang-dropped pods). Returns human-readable violations."""
    final = OracleState.build(nodes, existing, pvcs, pvs, storage_classes)
    placed: list[tuple[Pod, int]] = []
    # placed pods enter in QUEUE ORDER so their static-PV claims fold
    # rank-ordered (the shared binder-choice rule); unplaced-but-feasible
    # checks below then see the claimed bitmap
    for pi in queue_order(pending):
        node = assignment[pi]
        if node >= 0:
            final.add(node, pending[pi])
            placed.append((pending[pi], node))

    errors: list[str] = []
    # per-node aggregates: capacity + hostPort uniqueness
    for i, nd in enumerate(final.nodes):
        alloc = nd.status.allocatable
        for r, v in final.requested[i].items():
            if v > alloc.get(r, 0.0) * (1 + 1e-5) + 1e-5:
                errors.append(
                    f"node {nd.name}: {r} over capacity ({v} > "
                    f"{alloc.get(r, 0.0)})"
                )
        seen_ports: set = set()
        for pod in final.pods_on_node[i]:
            for (p, proto, _ip) in pod.host_ports():
                if (p, proto) in seen_ports:
                    errors.append(
                        f"node {nd.name}: duplicate hostPort {p}/{proto}"
                    )
                seen_ports.add((p, proto))

    for pod, i in placed:
        node = final.nodes[i]
        for f in (filter_node_unschedulable, filter_node_name,
                  filter_taint_toleration, filter_node_affinity):
            if not f(pod, final, i):
                errors.append(f"{pod.name}: fails {f.__name__} on {node.name}")
        aff = pod.spec.affinity or Affinity()
        if aff.pod_anti_affinity:
            for term in aff.pod_anti_affinity.required:
                dom = _domain(node, term.topology_key)
                if dom is None:
                    continue
                for j, nd in enumerate(final.nodes):
                    if _domain(nd, term.topology_key) != dom:
                        continue
                    for other in final.pods_on_node[j]:
                        if other is pod:
                            continue
                        if _term_matches_pod(term, pod.namespace, other):
                            errors.append(
                                f"{pod.name}: anti-affinity violated by "
                                f"{other.name} in {term.topology_key}={dom}"
                            )
        if aff.pod_affinity:
            for term in aff.pod_affinity.required:
                if _term_matches_pod(term, pod.namespace, pod):
                    continue  # bootstrap allowance / self-satisfying
                dom = _domain(node, term.topology_key)
                if dom is None:
                    errors.append(
                        f"{pod.name}: affinity key {term.topology_key} "
                        f"absent on {node.name}"
                    )
                    continue
                found = False
                for j, nd in enumerate(final.nodes):
                    if _domain(nd, term.topology_key) != dom:
                        continue
                    for other in final.pods_on_node[j]:
                        if other is not pod and _term_matches_pod(
                            term, pod.namespace, other
                        ):
                            found = True
                            break
                    if found:
                        break
                if not found:
                    errors.append(
                        f"{pod.name}: affinity unsatisfied in "
                        f"{term.topology_key}={dom}"
                    )
        for c in pod.spec.topology_spread_constraints:
            if c.when_unsatisfiable != api.DO_NOT_SCHEDULE:
                continue
            # the skew bound holds at the CONSTRAINED pod's placement time
            # only (upstream semantics): matching pods that carry no
            # constraint of their own may legally raise the final skew
            # afterwards, so the final state can only verify key presence.
            # test_rounds_spread_do_not_schedule_skew_holds covers the
            # all-carriers case, where final skew <= maxSkew is implied.
            if _domain(node, c.topology_key) is None:
                errors.append(
                    f"{pod.name}: spread key {c.topology_key} absent on "
                    f"{node.name}"
                )

    if not round_cap_hit:
        allowed = set(allow_feasible_unplaced)
        for pi, pod in enumerate(pending):
            if assignment[pi] >= 0 or pi in allowed:
                continue
            feas = feasible_nodes(pod, final, DEFAULT_FILTERS)
            if feas:
                errors.append(
                    f"{pod.name}: unplaced but feasible on {feas[:5]} "
                    f"in the final state"
                )
    return errors


# --------------------------------------------------------------------------
# Preemption (DefaultPreemption PostFilter analogue)
# --------------------------------------------------------------------------

# The candidate gate the preemption pass uses — mirrors the kernel's
# Candidate-node gate: the static filters eviction can never satisfy
# (volumes included — evicting a pod does not unbind a PersistentVolume).
# Everything eviction CAN free — resources, hostPorts, inter-pod
# (anti-)affinity, DoNotSchedule spread — is checked per victim PREFIX by
# simulating the prefix's removal from the post-cycle state, mirroring
# upstream's re-run-Filters-with-victims-removed and ops/preemption.py's
# what-if kernel.
PREEMPTION_STATIC_FILTERS = (
    filter_node_unschedulable,
    filter_node_name,
    filter_taint_toleration,
    filter_node_affinity,
    filter_volume_binding,
)
# constraints re-checked with the victim prefix removed
PREEMPTION_WHATIF_FILTERS = (
    filter_node_ports,
    filter_inter_pod_affinity,
    filter_topology_spread,
)


@dataclasses.dataclass
class OraclePreemption:
    pod_index: int
    node_index: int
    victims: list[int]  # indices into the `existing` sequence


def schedule_with_gangs(
    nodes: Sequence[Node],
    pending: Sequence[Pod],
    existing: Sequence[tuple[Pod, str]] = (),
    pod_groups: Sequence[api.PodGroup] = (),
    weights: "OracleWeights | None" = None,
    filters=None,
    pvcs: Sequence = (),
    pvs: Sequence = (),
    storage_classes: Sequence = (),
) -> tuple[list[OracleDecision], list[int]]:
    """schedule() then the all-or-nothing gang unwind (Coscheduling
    analogue, core/cycle.py gang_scheduling): groups whose placed-member
    count is below minMember have all members rolled back. Returns
    (decisions, dropped pod indices)."""
    weights = weights or OracleWeights()
    filters = filters or DEFAULT_FILTERS
    decisions = schedule(
        nodes, pending, existing, weights, filters, pvcs, pvs,
        storage_classes,
    )
    return gang_unwind(decisions, existing, pod_groups)


def gang_unwind(
    decisions: "list[OracleDecision]",
    existing: Sequence[tuple[Pod, str]],
    pod_groups: Sequence[api.PodGroup],
) -> tuple[list[OracleDecision], list[int]]:
    """The all-or-nothing rollback on its own: groups whose placed
    count (plus already-running members) stays below minMember have
    every placement unwound. Factored out of schedule_with_gangs so
    trace replay can keep the PRE-unwind decisions (the scan's turn
    states saw unwound pods as placed). Returns a NEW decisions list
    plus the dropped indices; the input list is not mutated."""
    decisions = list(decisions)
    min_member = {g.name: g.min_member for g in pod_groups}
    placed_count: dict[str, int] = {}
    for p, _node in existing:  # running members count toward minMember
        g = p.spec.pod_group
        if g:
            placed_count[g] = placed_count.get(g, 0) + 1
    for d in decisions:
        g = d.pod.spec.pod_group
        if g and d.node_index >= 0:
            placed_count[g] = placed_count.get(g, 0) + 1
    dropped = []
    for pi, d in enumerate(decisions):
        g = d.pod.spec.pod_group
        if g and d.node_index >= 0 and placed_count.get(g, 0) < min_member.get(g, 0):
            decisions[pi] = OracleDecision(d.pod, -1)
            dropped.append(pi)
    return decisions, dropped


def schedule_with_preemption(
    nodes: Sequence[Node],
    pending: Sequence[Pod],
    existing: Sequence[tuple[Pod, str]] = (),
    weights: "OracleWeights | None" = None,
    filters=None,
    pdbs: Sequence = (),
    pvcs: Sequence = (),
    pvs: Sequence = (),
    storage_classes: Sequence = (),
    budget: int | None = None,
    scan_budget: int | None = None,
) -> tuple[list[OracleDecision], list["OraclePreemption"]]:
    """schedule() then the preemption pass on whatever stayed pending."""
    weights = weights or OracleWeights()
    filters = filters or DEFAULT_FILTERS
    decisions = schedule(
        nodes, pending, existing, weights, filters, pvcs, pvs,
        storage_classes,
    )
    post_state = OracleState.build(
        nodes, existing, pvcs, pvs, storage_classes
    )
    for d in decisions:
        if d.node_index >= 0:
            post_state.add(d.node_index, d.pod)
    return decisions, preempt(
        nodes, pending, existing, decisions, post_state, pdbs=pdbs,
        pvcs=pvcs, pvs=pvs, storage_classes=storage_classes,
        budget=budget, scan_budget=scan_budget,
    )


def _pdb_selects(pdb, pod: Pod) -> bool:
    if pod.namespace != pdb.namespace:
        return False
    return match_label_selector(pdb.selector, pod.metadata.labels)


def preempt(
    nodes: Sequence[Node],
    pending: Sequence[Pod],
    existing: Sequence[tuple[Pod, str]],
    decisions: Sequence[OracleDecision],
    post_state: OracleState,
    pdbs: Sequence = (),
    pvcs: Sequence = (),
    pvs: Sequence = (),
    storage_classes: Sequence = (),
    budget: int | None = None,
    scan_budget: int | None = None,
    excluded: Sequence[int] = (),
) -> list[OraclePreemption]:
    """Sequential preemption over the unschedulable pods in queue order,
    mirroring ops/preemption.py's semantics: per node, victims are a prefix
    of the existing pods sorted ascending by priority; the minimal prefix
    that frees enough resources wins; a victim protected by an exhausted
    PodDisruptionBudget is evicted only as a LAST RESORT — the number of
    PDB violations among the NEW victims is the FIRST node-choice key
    (upstream pickOneNodeForPreemption criterion #1), and claims decrement
    budgets within the pass; node choice then minimizes (highest victim
    priority, victim priority sum, victim count, -(highest victim start
    time), node index). `post_state` is the oracle state AFTER the
    scheduling pass (committed pods consume capacity); the static filters
    run against the pre-cycle state."""
    idx = {n.name: i for i, n in enumerate(nodes)}
    static_state = OracleState.build(
        nodes, existing, pvcs, pvs, storage_classes
    )
    # PDB bookkeeping: per existing pod, the (first two) selecting PDBs —
    # same MB=2 cap as the encoder
    pdb_used = [0] * len(pdbs)
    pod_pdbs: list[list[int]] = []
    for p, _node in existing:
        sels = [gi for gi, pdb in enumerate(pdbs) if _pdb_selects(pdb, p)]
        pod_pdbs.append(sels[:2])
    # per-node victim lists: (priority asc, -existing_index) — same order as
    # the encoder's node_pods table
    per_node: list[list[int]] = [[] for _ in nodes]
    for e, (p, node_name) in enumerate(existing):
        i = idx.get(node_name)
        if i is not None:
            per_node[i].append(e)
    for lst in per_node:
        lst.sort(key=lambda e: (existing[e][0].spec.priority, -e))

    k_claimed = [0] * len(nodes)
    nominated_req: list[dict[str, float]] = [{} for _ in nodes]
    nominated_ports: list[set] = [set() for _ in nodes]
    out: list[OraclePreemption] = []

    # `excluded` mirrors the kernel's run_preemption(excluded=...) mask:
    # gang-dropped members fit without eviction — their group is what
    # failed — so they never preempt (upstream never runs PostFilter for
    # Permit rejections)
    excluded_set = set(excluded)
    unsched = [pi for pi in queue_order(pending)
               if decisions[pi].node_index < 0
               and pi not in excluded_set
               and pending[pi].spec.preemption_policy != "Never"]
    # ---- per-cycle latency budgets (ops/preemption.py mirror) ----
    # `budget`: only the lowest-rank `budget` candidates are considered
    # at all (phase-1 table bound). `scan_budget`: of those, only the
    # first `scan_budget` that are RESOURCE-FEASIBLE against the
    # pristine post-cycle state (the kernel's phase-1 prefilter — static
    # gate + some prefix k in [1, elig] whose freed resources fit,
    # IGNORING contention and the non-resource what-if) get a scan slot;
    # later candidates defer to the next cycle.
    if budget is not None:
        unsched = unsched[:budget]
    if scan_budget is not None and len(unsched) > scan_budget:
        def _pristine_feasible(pi: int) -> bool:
            pod = pending[pi]
            req = pod.resource_requests()
            for i in range(len(nodes)):
                if not all(
                    f(pod, static_state, i)
                    for f in PREEMPTION_STATIC_FILTERS
                ):
                    continue
                victs = per_node[i]
                elig = sum(
                    1 for e in victs
                    if existing[e][0].spec.priority < pod.spec.priority
                )
                alloc = nodes[i].status.allocatable
                freed: dict[str, float] = {}
                for k in range(1, elig + 1):
                    for r, v in (
                        existing[victs[k - 1]][0].resource_requests().items()
                    ):
                        freed[r] = freed.get(r, 0.0) + v
                    ok = True
                    for r, v in req.items():
                        used = (
                            post_state.requested[i].get(r, 0.0)
                            - freed.get(r, 0.0)
                        )
                        a = alloc.get(r, 0.0)
                        if used + v > a * (1 + 1e-5) + 1e-5:
                            ok = False
                            break
                    if ok:
                        return True
            return False

        unsched = [pi for pi in unsched if _pristine_feasible(pi)][
            :scan_budget
        ]
    for pi in unsched:
        pod = pending[pi]
        req = pod.resource_requests()
        pod_ports = {(pt, proto) for pt, proto, _ip in pod.host_ports()}
        candidates = []  # (pdb_violations, max_prio, sum_prio, n_vict, -hi_start, node, k_min)
        for i in range(len(nodes)):
            if not all(f(pod, static_state, i) for f in PREEMPTION_STATIC_FILTERS):
                continue
            if pod_ports & nominated_ports[i]:
                # an earlier nomination in this pass claims the port
                continue
            victs = per_node[i]
            elig = sum(
                1 for e in victs
                if existing[e][0].spec.priority < pod.spec.priority
            )
            # PDB protection no longer truncates: protected victims are
            # last-resort evictable; violations count toward the node
            # choice below. A victim violates when its within-group
            # ordinal among the NEW victims (from k_claimed on; earlier
            # claims already consumed pdb_used) exceeds the remaining
            # budget — per-victim decrement, like upstream's
            # filterPodsWithPDBViolation (kernel mirror).
            protected = [False] * len(victs)
            grp_cnt: dict[int, int] = {}
            for pos_ in range(k_claimed[i], len(victs)):
                e = victs[pos_]
                flag = False
                for g in pod_pdbs[e]:
                    grp_cnt[g] = grp_cnt.get(g, 0) + 1
                    rem = pdbs[g].disruptions_allowed - pdb_used[g]
                    if grp_cnt[g] > rem:
                        flag = True
                protected[pos_] = flag

            def fits(k: int) -> bool:
                alloc = nodes[i].status.allocatable
                freed: dict[str, float] = {}
                for e in victs[:k]:
                    for r, v in existing[e][0].resource_requests().items():
                        freed[r] = freed.get(r, 0.0) + v
                for r, v in req.items():
                    used = (
                        post_state.requested[i].get(r, 0.0)
                        + nominated_req[i].get(r, 0.0)
                        - freed.get(r, 0.0)
                    )
                    a = alloc.get(r, 0.0)
                    if used + v > a * (1 + 1e-5) + 1e-5:
                        return False
                return True

            def whatif_ok(k: int) -> bool:
                """Re-run the evictable filters with victims[:k] removed
                from the post-cycle state (upstream SelectVictimsOnNode
                re-runs Filters on the modified NodeInfo)."""
                removed = [existing[e][0] for e in victs[:k]]
                for rp in removed:
                    post_state.remove(i, rp)
                try:
                    return all(
                        f(pod, post_state, i)
                        for f in PREEMPTION_WHATIF_FILTERS
                    )
                finally:
                    for rp in removed:
                        # existing pods entered the state with
                        # claim_volumes=False; restoring with the default
                        # True would permanently add claimed_static
                        # entries and pollute later candidates' volume
                        # checks within this pass
                        post_state.add(i, rp, claim_volumes=False)

            k_min = None
            for k in range(k_claimed[i], elig + 1):
                if fits(k) and whatif_ok(k):
                    k_min = k
                    break
            if k_min is None or k_min <= k_claimed[i]:
                continue  # no help, or helps without evictions (not preemption)
            new = victs[k_claimed[i]:k_min]
            hi = victs[k_min - 1]  # highest-priority (last) prefix victim
            candidates.append((
                sum(protected[k_claimed[i]:k_min]),  # PDB violations
                max(existing[e][0].spec.priority for e in new),
                sum(existing[e][0].spec.priority for e in new),
                len(new),
                -existing[hi][0].metadata.creation_timestamp,
                i,
                k_min,
            ))
        if not candidates:
            continue
        _viol, max_p, sum_p, n_v, neg_start, node, k_min = min(candidates)
        victims = per_node[node][k_claimed[node]:k_min]
        k_claimed[node] = k_min
        for e in victims:
            for g in pod_pdbs[e]:
                pdb_used[g] += 1
        for r, v in req.items():
            nominated_req[node][r] = nominated_req[node].get(r, 0.0) + v
        nominated_ports[node] |= pod_ports
        out.append(OraclePreemption(pi, node, victims))
    return out


def schedule(
    nodes: Sequence[Node],
    pending: Sequence[Pod],
    existing: Sequence[tuple[Pod, str]] = (),
    weights: OracleWeights = OracleWeights(),
    filters=DEFAULT_FILTERS,
    pvcs: Sequence = (),
    pvs: Sequence = (),
    storage_classes: Sequence = (),
) -> list[OracleDecision]:
    """Sequential greedy scheduling in (priority desc, creation asc) order —
    the reference's queue order (PrioritySort QueueSort plugin)."""
    state = OracleState.build(nodes, existing, pvcs, pvs, storage_classes)
    decisions: dict[int, int] = {}
    for pi in queue_order(pending):
        pod = pending[pi]
        feasible = feasible_nodes(pod, state, filters)
        if not feasible:
            decisions[pi] = -1
            continue
        best, best_score = -1, -float("inf")
        cn = _CrossNodeRaws.compute(pod, state, feasible, weights)
        for i in feasible:
            s = _score_pod(pod, state, i, weights, cn)
            if s > best_score:
                best, best_score = i, s
        decisions[pi] = best
        if best >= 0:
            state.add(best, pod)
    return [OracleDecision(pending[i], decisions[i]) for i in range(len(pending))]


# --------------------------------------------------------------------------
# Trace semantics (the fuzz/ differential's per-cycle ground truth)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class OracleCycleOutcome:
    """Everything ONE scheduling cycle decides, oracle-side — the unit
    the trace-level differential (fuzz/replay.py) compares against the
    live Scheduler's apply phase:

    - `decisions`: per pending index, the chosen node (-1 = unplaced),
      gang rollbacks applied;
    - `dropped`: pending indices unwound by the all-or-nothing gang
      check (their reasons are ("Coscheduling",));
    - `reasons`: unplaced index -> rejecting plugin names, first-
      rejector attribution against the FINAL post-cycle state (the
      diagnosis-program mirror) — these drive the queueing hints, so
      they must match the engine's bit-exactly for the two queues to
      evolve identically;
    - `preemptions`: nominations + victims for the unplaced pods,
      gang-dropped excluded, under the kernel's production budgets.
    """

    decisions: "list[OracleDecision]"
    dropped: "list[int]"
    reasons: "dict[int, tuple[str, ...]]"
    preemptions: "list[OraclePreemption]"


def schedule_cycle_trace(
    nodes: Sequence[Node],
    pending: Sequence[Pod],
    existing: Sequence[tuple[Pod, str]] = (),
    *,
    pod_groups: Sequence[api.PodGroup] = (),
    pvcs: Sequence = (),
    pvs: Sequence = (),
    storage_classes: Sequence = (),
    pdbs: Sequence = (),
    gang_scheduling: bool = True,
    weights: "OracleWeights | None" = None,
    filters=None,
    budget: "int | None" = None,
    scan_budget: "int | None" = None,
) -> OracleCycleOutcome:
    """One full scheduling cycle under trace semantics: sequential
    greedy scheduling, gang unwind, FailedScheduling attribution, and
    the preemption pass — the oracle half of the fuzz differential.
    Callers that replay multi-cycle traces own the queue/cache state
    between cycles (fuzz/replay.py drives the SAME SchedulingQueue /
    SchedulerCache classes the live Scheduler uses, so the differential
    isolates the decision engine, not the host bookkeeping)."""
    weights = weights or OracleWeights()
    filters = filters or DEFAULT_FILTERS
    raw = schedule(
        nodes, pending, existing, weights, filters, pvcs, pvs,
        storage_classes,
    )
    if gang_scheduling:
        decisions, dropped = gang_unwind(raw, existing, pod_groups)
    else:
        decisions, dropped = list(raw), []
    # FailedScheduling attribution replays the scan: phase B of
    # attribute_rejects must see the state AT THE POD'S TURN — earlier
    # placements only, gang-unwound pods still placed (the fused scan
    # program computes dyn rejects per scan step, before the unwind).
    # `pre` is the pre-cycle (existing-only) state the STATIC half
    # sees; `turn` walks the scan in queue order using the PRE-unwind
    # decisions, claims folding rank-ordered (the shared binder-choice
    # rule).
    pre = OracleState.build(nodes, existing, pvcs, pvs, storage_classes)
    turn = OracleState.build(nodes, existing, pvcs, pvs, storage_classes)
    dropped_set = set(dropped)
    reasons: dict[int, tuple[str, ...]] = {}
    for pi in queue_order(pending):
        if raw[pi].node_index >= 0:
            if pi in dropped_set:
                reasons[pi] = ("Coscheduling",)
            turn.add(raw[pi].node_index, pending[pi])
            continue
        counts = attribute_rejects(pending[pi], pre, turn, filters)
        reasons[pi] = tuple(
            _FILTER_NAME_OF[f]
            for f, c in zip(filters, counts)
            if c > 0
        )
    # the preemption pass consumes the POST-unwind state (the kernel's
    # node_requested is rolled back by _gang_unwind before run_preemption)
    post = OracleState.build(nodes, existing, pvcs, pvs, storage_classes)
    for pi in queue_order(pending):
        if decisions[pi].node_index >= 0:
            post.add(decisions[pi].node_index, pending[pi])
    preemptions = preempt(
        nodes, pending, existing, decisions, post, pdbs=pdbs,
        pvcs=pvcs, pvs=pvs, storage_classes=storage_classes,
        budget=budget, scan_budget=scan_budget, excluded=dropped,
    )
    return OracleCycleOutcome(decisions, dropped, reasons, preemptions)
