from .main import main, new_scheduler_command

__all__ = ["main", "new_scheduler_command"]
