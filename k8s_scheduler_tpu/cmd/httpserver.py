"""Health + metrics HTTP endpoints (SURVEY.md §2 C1, §5.5).

The reference family serves /healthz and Prometheus /metrics from its
secure port; dashboards and probes expect those paths. Served here with
the stdlib http.server on a daemon thread — the payloads are tiny and
low-rate (scrapes + probes), no framework needed."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from ..metrics import SchedulerMetrics


def start_http_server(
    metrics: SchedulerMetrics,
    port: int = 10251,
    host: str = "127.0.0.1",
    healthz: Callable[[], tuple[bool, dict]] | None = None,
) -> ThreadingHTTPServer:
    """Serve /healthz, /readyz, /metrics; returns the running server
    (bound port at `.server_address[1]`; pass port=0 for ephemeral)."""
    health_fn = healthz or (lambda: (True, {}))

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802  (stdlib casing)
            if self.path in ("/healthz", "/readyz", "/livez"):
                ok, detail = health_fn()
                body = json.dumps({"ok": ok, **detail}).encode()
                self.send_response(200 if ok else 503)
                self.send_header("Content-Type", "application/json")
            elif self.path == "/metrics":
                body = metrics.expose()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
            else:
                body = b"not found"
                self.send_response(404)
                self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # probes are noisy
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(
        target=server.serve_forever, name="http-metrics", daemon=True
    )
    thread.start()
    return server
