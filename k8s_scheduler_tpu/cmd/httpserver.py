"""Health + metrics + flight-recorder debug HTTP endpoints.

The reference family serves /healthz and Prometheus /metrics from its
secure port (SURVEY.md §2 C1, §5.5); dashboards and probes expect those
paths. On top of them, the cycle flight recorder
(core/flight_recorder.py) is exposed for production debugging:

- `/debug/flightrecorder?last=N` — the last N cycle records as JSON
  (phase marks, phase durations, counts) plus the derived window stats;
- `/debug/traces?last=N|pod=<uid>|trace=<id>` — a Chrome-trace/Perfetto
  JSON download reconstructing the pipeline's overlapped lanes from
  real serving timestamps (open in ui.perfetto.dev), with per-pod
  trace-span tracks (core/spans) merged in when tracing is armed;
  `pod=` slices to the cycles that touched that pod (joined through
  the pod timeline's per-attempt cycle seqs), `trace=` to the cycles
  and spans of one trace id. `/debug/trace` is the deprecated alias
  (same handler, `Deprecation` header);
- `/debug/explain?pod=<uid>` — the joined schedulability verdict: the
  pod's current state, per-attempt first-rejecting plugin, its trace
  spans' durations, the front door's shed/retry history, and the
  anomalies that overlapped its cycles;
- `/debug/pods/<uid>` — the per-pod scheduling timeline
  (queued -> attempts -> bound/evicted, joined with the events ring);
- `/debug/anomalies?last=N[&tenant=<id>]` — the cycle observer's typed
  anomaly ring (tunnel_stall / fetch_stall / recompile / fold_miss /
  wedge_precursor / ... / alert), each event carrying the cycle seq
  that links it to `/debug/flightrecorder` and the matching
  `/debug/trace` window, plus per-class counts, per-phase quantiles,
  and the SLO burn status; `tenant=` filters to one tenant's events
  (the `tenant_starved` detail join) and the payload always carries
  per-tenant anomaly counts;
- `/debug/state` — durable-state health (journal lag/segments, fsync
  latency, last snapshot and last restore stats) plus the degradation
  ladder's wall-timestamped transition ring when `--state-dir` is
  configured;
- `/debug/metrics/history?family=&labels=k=v,...&window=&step=` — the
  in-process TSDB (metrics/tsdb.py): raw points (step<1) or 1 s / 1 m
  aggregate buckets (min/max/sum/count/last) per family/labelset over
  the trailing window; without `family=` it returns the stored-series
  inventory;
- `/debug/alerts` — active + resolved alert-rule firings with wall
  timestamps, plus every rule's current state and value
  (metrics/rules.py RuleEngine);
- `/debug/dashboard` — dependency-free HTML sparkline dashboard over
  the history API (inline SVG, no external assets).

Served with the stdlib http.server on a daemon thread — the payloads are
small and low-rate (scrapes + probes + on-demand debugging), no
framework needed. HEAD is answered for every GET route (probes commonly
use HEAD); any other method gets 405 with an Allow header.
"""

from __future__ import annotations

import json
import math
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from ..metrics import SchedulerMetrics

# POST /submit body cap (parity with gRPC's default 4 MB message
# limit): the front door's bounded-memory contract must hold on the
# HTTP path too — a giant Content-Length is refused BEFORE any read
_MAX_SUBMIT_BODY_BYTES = 4 << 20


# /debug/dashboard: dependency-free sparkline page over the history
# API. Inline SVG + fetch() only — no external assets, so it renders
# from an airgapped box exactly like every other debug endpoint.
_DASHBOARD_HTML = b"""<!doctype html>
<html><head><meta charset="utf-8"><title>scheduler watchtower</title>
<style>
 body{font:13px monospace;background:#111;color:#ddd;margin:1em}
 h1{font-size:15px} .fam{display:inline-block;width:340px;margin:4px;
 padding:6px;background:#1b1b1b;border:1px solid #333;vertical-align:top}
 .fam b{display:block;font-size:11px;overflow:hidden;white-space:nowrap}
 .lbl{color:#8a8;font-size:10px} .val{color:#fc6;float:right}
 svg{width:100%;height:42px;background:#161616}
 polyline{fill:none;stroke:#6cf;stroke-width:1}
 #alerts{padding:6px;margin:4px}
 .firing{color:#f66;font-weight:bold} .quiet{color:#6a6}
</style></head><body>
<h1>scheduler watchtower &mdash; metrics history + alerts</h1>
<div id="alerts">loading alerts&hellip;</div>
<div id="grid">loading series&hellip;</div>
<script>
const W=330,H=42;
function spark(pts){
 if(pts.length<2)return'<svg></svg>';
 const xs=pts.map(p=>p[0]),ys=pts.map(p=>p[p.length>2?5:1]);
 const x0=Math.min(...xs),x1=Math.max(...xs);
 const y0=Math.min(...ys),y1=Math.max(...ys);
 const pl=pts.map((p,i)=>{
  const x=(xs[i]-x0)/Math.max(x1-x0,1e-9)*W;
  const y=H-2-(ys[i]-y0)/Math.max(y1-y0,1e-9)*(H-4);
  return x.toFixed(1)+','+y.toFixed(1)}).join(' ');
 return'<svg viewBox="0 0 '+W+' '+H+'"><polyline points="'+pl+
  '"/></svg>';
}
async function drawAlerts(){
 try{
  const a=await(await fetch('/debug/alerts')).json();
  const act=a.active||[];
  document.getElementById('alerts').innerHTML=act.length
   ?'<span class="firing">FIRING: '+act.map(x=>x.rule+' ['+x.severity+
     '] value='+Number(x.value).toPrecision(4)).join(' &middot; ')+
     '</span>'
   :'<span class="quiet">no active alerts ('+
     (a.fired_total||0)+' lifetime firings, '+
     (a.resolved||[]).length+' resolved in window)</span>';
 }catch(e){
  document.getElementById('alerts').textContent=
   'alerts endpoint unavailable';
 }
}
async function draw(){
 const inv=await(await fetch('/debug/metrics/history')).json();
 const fams=(inv.families||[]).slice(0,48);
 const out=[];
 for(const f of fams){
  const q=await(await fetch('/debug/metrics/history?family='+
   encodeURIComponent(f.family)+'&window=900&step=1')).json();
  for(const s of (q.series||[]).slice(0,4)){
   const pts=s.points||[];if(!pts.length)continue;
   const last=pts[pts.length-1];
   const v=last[last.length>2?5:1];
   const lbl=Object.entries(s.labels||{}).map(([k,x])=>k+'='+x)
    .join(',');
   out.push('<div class="fam"><b>'+f.family+
    '<span class="val">'+Number(v).toPrecision(5)+'</span></b>'+
    '<span class="lbl">'+(lbl||'&nbsp;')+'</span>'+spark(pts)+
    '</div>');
  }
 }
 document.getElementById('grid').innerHTML=
  out.join('')||'no series stored yet';
}
drawAlerts();draw();setInterval(()=>{drawAlerts();draw()},15000);
</script></body></html>
"""


def _parse_last(query: str, default: int = 128) -> int:
    try:
        v = int(urllib.parse.parse_qs(query).get("last", [default])[0])
    except (TypeError, ValueError):
        return default
    return max(1, min(v, 65536))


def staleness_healthz(
    base: Callable[[], dict] | None,
    recorder,
    max_age_seconds: float,
    observer=None,  # core/observe.CycleObserver | None
    ladder=None,  # core/degrade.DegradationLadder | None
    admission=None,  # service/admission.AdmissionController | None
) -> Callable[[], tuple[bool, dict]]:
    """Health closure with flight-recorder staleness: reports
    `last_cycle_age_s` and flips to not-ok (503) once no scheduling
    cycle completed within `max_age_seconds` (0 = never stale). Before
    the FIRST cycle the age anchors at recorder creation, so a
    scheduler wedged during startup also goes unhealthy instead of
    reporting a static 200 forever. With an `observer`, the payload
    additionally carries the SLO burn status and `degraded: true` on a
    fast-window burn — still 200: budget burn is a paging signal, and
    killing the pod does not refill an error budget. With a `ladder`
    (core/degrade.py), the current degradation rung rides the payload
    and any rung below `normal` also reports `degraded: true` (again
    200: the ladder is actively recovering — a restart would only lose
    its progress, and at the bottom rung the standby takeover is
    already underway via the sealed state). With an `admission`
    controller (the submission front door), its status rides the
    payload and `degraded: true` is reported while the front door
    would shed an arriving submission right now (an overload burst is
    a capacity signal like budget burn — still 200: the door is doing
    its job by shedding)."""

    def healthz() -> tuple[bool, dict]:
        detail = dict(base()) if base is not None else {}
        ok = True
        if recorder is not None:
            age = recorder.last_cycle_age_s()
            detail["last_cycle_age_s"] = round(age, 3)
            detail["cycles"] = recorder.cycles
            if max_age_seconds > 0 and age > max_age_seconds:
                ok = False
                detail["reason"] = (
                    f"no cycle completed in {age:.1f}s "
                    f"(deadline {max_age_seconds:g}s)"
                )
        if observer is not None:
            detail.update(observer.healthz_detail())
        if ladder is not None:
            st = ladder.status()
            detail["degradation"] = st
            if st["rung"] > 0:
                detail["degraded"] = True
                detail.setdefault(
                    "degraded_reason",
                    f"degradation ladder at rung {st['rung']} "
                    f"({st['name']}): {st['last_reason']}",
                )
        if admission is not None:
            detail["admission"] = admission.status()
            shed_now = admission.overloaded()
            if shed_now:
                detail["degraded"] = True
                detail.setdefault(
                    "degraded_reason", f"admission shedding: {shed_now}"
                )
        return ok, detail

    return healthz


def start_http_server(
    metrics: SchedulerMetrics,
    port: int = 10251,
    host: str = "127.0.0.1",
    healthz: Callable[[], tuple[bool, dict]] | None = None,
    recorder=None,  # core/flight_recorder.FlightRecorder | None
    pod_timeline: Callable[[str], dict | None] | None = None,
    state=None,  # state.DurableState | None
    observer=None,  # core/observe.CycleObserver | None
    admission=None,  # service/admission.AdmissionController | None
    spans_recorder=None,  # core/spans.SpanRecorder | None
    tsdb=None,  # metrics/tsdb.MetricsTSDB | None
    alerts=None,  # metrics/rules.RuleEngine | None
    dashboard: bool = True,
) -> ThreadingHTTPServer:
    """Serve /healthz, /readyz, /metrics and the /debug endpoints;
    returns the running server (bound port at `.server_address[1]`;
    pass port=0 for ephemeral). `recorder` enables /debug/flightrecorder
    and /debug/traces (plus its deprecated /debug/trace alias);
    `pod_timeline` (usually Scheduler.pod_timeline) enables
    /debug/pods/<uid>, /debug/explain and the /debug/traces?pod=
    filter; `state` (DurableState) enables /debug/state (journal lag,
    segment counts, snapshot + restore stats); `observer`
    (CycleObserver) enables /debug/anomalies; `spans_recorder` (the
    armed span ring) merges per-pod trace tracks into /debug/traces
    and span durations into /debug/explain; `admission` (the
    submission front door) enables the thin `POST /submit` path — a
    JSON body `{"pods": [<state/codec pod dicts>]}` admitted through
    the same controller the gRPC Submit RPC uses (200 on accept, 429 +
    Retry-After on shed, 400 on invalid pods, 503 while draining),
    with a W3C `traceparent` request header joining the submission's
    trace and the effective traceparent echoed as a response header;
    `tsdb` (the armed metrics/tsdb store) enables
    /debug/metrics/history and — unless `dashboard` is False — the
    /debug/dashboard sparkline page; `alerts` (the rules engine)
    enables /debug/alerts."""
    health_fn = healthz or (lambda: (True, {}))

    class Handler(BaseHTTPRequestHandler):
        # (status, content_type, body, extra_headers)
        def _route(self) -> tuple[int, str, bytes, dict[str, str]]:
            parts = urllib.parse.urlsplit(self.path)
            path, query = parts.path, parts.query
            if path in ("/healthz", "/readyz", "/livez"):
                ok, detail = health_fn()
                return (
                    200 if ok else 503,
                    "application/json",
                    json.dumps({"ok": ok, **detail}).encode(),
                    {},
                )
            if path == "/metrics":
                return (
                    200,
                    "text/plain; version=0.0.4; charset=utf-8",
                    metrics.expose(),
                    {},
                )
            if path == "/debug/flightrecorder" and recorder is not None:
                last = _parse_last(query)
                body = json.dumps(
                    {
                        "cycles": recorder.to_dicts(last=last),
                        "derived": recorder.derived(last=last),
                    }
                ).encode()
                return 200, "application/json", body, {}
            if (
                path in ("/debug/trace", "/debug/traces")
                and recorder is not None
            ):
                # ONE handler for both paths: /debug/traces is the
                # canonical route (pod= / trace= / last= filters, span
                # tracks merged when tracing is armed); /debug/trace
                # (PR 5) stays as a deprecation alias with identical
                # behavior so existing tooling keeps working
                status, ctype, body, extra = self._trace_route(query)
                if path == "/debug/trace" and status == 200:
                    extra = dict(extra)
                    extra["Deprecation"] = "true"
                    extra["Link"] = (
                        '</debug/traces>; rel="successor-version"'
                    )
                return status, ctype, body, extra
            if path == "/debug/explain" and pod_timeline is not None:
                uid = (
                    urllib.parse.parse_qs(query).get("pod") or [""]
                )[0]
                return self._explain_route(uid)
            if path == "/debug/anomalies" and observer is not None:
                last = _parse_last(query)
                tenant = (
                    urllib.parse.parse_qs(query).get("tenant") or [""]
                )[0]
                events = observer.anomalies(last=last)
                # per-tenant counts over the returned window: the
                # tenant_starved detail carries the starved tenant id,
                # and alert/arena events ride the same join
                tenant_counts: dict[str, int] = {}
                for ev in events:
                    t = ev.get("detail", {}).get("tenant", "")
                    if t:
                        tenant_counts[t] = tenant_counts.get(t, 0) + 1
                if tenant:
                    events = [
                        ev for ev in events
                        if ev.get("detail", {}).get("tenant", "")
                        == tenant
                    ]
                body = json.dumps(
                    {
                        "anomalies": events,
                        "tenant": tenant or None,
                        "tenant_counts": tenant_counts,
                        **observer.status(),
                    }
                ).encode()
                return 200, "application/json", body, {}
            if path == "/debug/metrics/history" and tsdb is not None:
                return self._history_route(query)
            if path == "/debug/alerts" and alerts is not None:
                return (
                    200,
                    "application/json",
                    json.dumps(alerts.status()).encode(),
                    {},
                )
            if (
                path == "/debug/dashboard"
                and tsdb is not None
                and dashboard
            ):
                return (
                    200,
                    "text/html; charset=utf-8",
                    _DASHBOARD_HTML,
                    {},
                )
            if path == "/debug/state" and state is not None:
                return (
                    200,
                    "application/json",
                    json.dumps(state.status()).encode(),
                    {},
                )
            if path.startswith("/debug/pods/") and pod_timeline is not None:
                uid = urllib.parse.unquote(
                    path[len("/debug/pods/"):]
                )
                tl = pod_timeline(uid) if uid else None
                if tl is None:
                    return (
                        404,
                        "application/json",
                        json.dumps(
                            {"error": f"pod {uid!r} not seen"}
                        ).encode(),
                        {},
                    )
                return 200, "application/json", json.dumps(tl).encode(), {}
            return 404, "text/plain", b"not found", {}

        def _history_route(
            self, query: str
        ) -> tuple[int, str, bytes, dict[str, str]]:
            """GET /debug/metrics/history: the TSDB query surface.
            `family=` selects one family (absent: the stored-series
            inventory), `labels=k=v,k2=v2` is a subset selector,
            `window=` seconds back from now (default 300), `step=`
            selects the tier (>=60 -> 1 m buckets, >=1 -> 1 s,
            else raw points)."""
            qs = urllib.parse.parse_qs(query)
            family = (qs.get("family") or [""])[0]
            if not family:
                body = json.dumps(
                    {"families": tsdb.families(), **tsdb.status()}
                ).encode()
                return 200, "application/json", body, {}
            labels: dict[str, str] = {}
            for pair in (qs.get("labels") or [""])[0].split(","):
                if "=" in pair:
                    k, _, v = pair.partition("=")
                    labels[k.strip()] = v.strip()
            try:
                window = float((qs.get("window") or ["300"])[0])
                step = float((qs.get("step") or ["0"])[0])
            except ValueError:
                return (
                    400,
                    "application/json",
                    json.dumps(
                        {"error": "window/step must be numbers"}
                    ).encode(),
                    {},
                )
            body = json.dumps(
                tsdb.query(
                    family, labels=labels, window_s=window, step_s=step
                )
            ).encode()
            return 200, "application/json", body, {}

        def _trace_route(
            self, query: str
        ) -> tuple[int, str, bytes, dict[str, str]]:
            """GET /debug/traces (and the /debug/trace alias): the
            Perfetto download. `pod=` slices cycle records to the
            cycles that touched the pod and span tracks to its spans;
            `trace=` slices both to one trace id (records join through
            their `trace_ids` exemplar stamp); unfiltered keeps the
            usual last=128 record window."""
            from ..core.flight_recorder import to_chrome_trace

            qs = urllib.parse.parse_qs(query)
            pod_uid = (qs.get("pod") or [""])[0]
            trace_id = (qs.get("trace") or [""])[0]
            # a filtered trace defaults to the WHOLE ring (the
            # matching cycles are sparse); unfiltered keeps the usual
            # last=128 window
            if "last" in qs:
                last: int | None = _parse_last(query)
            else:
                last = None if (pod_uid or trace_id) else 128
            recs = recorder.snapshot(last=last)
            span_list = None
            if spans_recorder is not None:
                if trace_id:
                    span_list = spans_recorder.for_trace(trace_id)
                elif pod_uid:
                    span_list = spans_recorder.for_uid(pod_uid)
                else:
                    span_list = spans_recorder.snapshot()
            if pod_uid:
                # slice to the cycles that touched this pod: every
                # timeline attempt carries its cycle seq, which is
                # the join key back to the flight records
                if pod_timeline is None:
                    return (
                        404, "text/plain",
                        b"pod filter needs the pod timeline", {},
                    )
                tl = pod_timeline(pod_uid)
                if tl is None and not span_list:
                    return (
                        404,
                        "application/json",
                        json.dumps(
                            {"error": f"pod {pod_uid!r} not seen"}
                        ).encode(),
                        {},
                    )
                seqs = {
                    e["cycle"]
                    for e in (tl or {}).get("events", ())
                    if e.get("cycle", -1) >= 0
                }
                # spans carry the cycle seq as their exemplar attr —
                # the reverse join, so the view keeps the batch cycles
                # even when the timeline aged out of its LRU
                for s in span_list or ():
                    if s.attrs.get("seq", -1) >= 0:
                        seqs.add(s.attrs["seq"])
                recs = [r for r in recs if r.seq in seqs]
            if trace_id:
                recs = [r for r in recs if trace_id in r.trace_ids]
            trace = to_chrome_trace(
                recs, epoch=recorder.epoch, spans=span_list
            )
            return (
                200,
                "application/json",
                json.dumps(trace).encode(),
                {
                    "Content-Disposition":
                    'attachment; filename="scheduler-trace.json"'
                },
            )

        def _explain_route(
            self, uid: str
        ) -> tuple[int, str, bytes, dict[str, str]]:
            """GET /debug/explain?pod=<uid>: the joined
            schedulability verdict — why is (was) this pod Pending."""
            if not uid:
                return (
                    400,
                    "application/json",
                    json.dumps(
                        {"error": "missing ?pod=<uid>"}
                    ).encode(),
                    {},
                )
            tl = pod_timeline(uid)
            if tl is None:
                return (
                    404,
                    "application/json",
                    json.dumps(
                        {"error": f"pod {uid!r} not seen"}
                    ).encode(),
                    {},
                )
            attempts = tl.get("attempts", [])
            # per-plugin first-rejector counts over the attempts (the
            # live-timeline analogue of oracle.attribute_rejects'
            # first-rejector attribution): each failed attempt charges
            # ONE plugin — the first one that rejected the pod
            reject_counts: dict[str, int] = {}
            for a in attempts:
                if a.get("result") == "Unschedulable":
                    plug = a.get("plugin", "") or "<unattributed>"
                    reject_counts[plug] = (
                        reject_counts.get(plug, 0) + 1
                    )
            rejectors = [
                a.get("plugin", "")
                for a in attempts
                if a.get("result") == "Unschedulable"
                and a.get("plugin")
            ]
            cycles = {
                e["cycle"]
                for e in tl.get("events", ())
                if e.get("cycle", -1) >= 0
            }
            payload: dict = {
                "uid": uid,
                "name": tl.get("name", ""),
                "state": tl.get("state", "Pending"),
                "attempts": attempts,
                "reject_counts": reject_counts,
                "first_rejector": rejectors[0] if rejectors else "",
                "last_rejector": rejectors[-1] if rejectors else "",
            }
            if admission is not None:
                # the front door's shed/retry history (present even
                # when tracing is unarmed)
                payload["admission_history"] = admission.history_for(
                    uid
                )
            if spans_recorder is not None:
                sp = spans_recorder.for_uid(uid)
                payload["spans"] = [
                    s.to_dict(epoch=spans_recorder.epoch) for s in sp
                ]
                totals: dict[str, float] = {}
                for s in sp:
                    totals[s.name] = totals.get(s.name, 0.0) + max(
                        s.t1 - s.t0, 0.0
                    ) * 1e3
                payload["span_totals_ms"] = {
                    k: round(v, 4) for k, v in totals.items()
                }
                payload["trace_ids"] = sorted(
                    {s.trace_id for s in sp}
                )
                for s in sp:
                    if s.attrs.get("seq", -1) >= 0:
                        cycles.add(s.attrs["seq"])
            if observer is not None:
                # anomalies whose cycle seq overlapped this pod's
                # cycles: the "something else went wrong in the same
                # batch" half of the verdict
                payload["anomalies"] = [
                    a
                    for a in observer.anomalies(last=512)
                    if a.get("seq", -1) in cycles
                ]
            return (
                200,
                "application/json",
                json.dumps(payload).encode(),
                {},
            )

        def _respond(self, include_body: bool) -> None:
            status, ctype, body, extra = self._route()
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in extra.items():
                self.send_header(k, v)
            self.end_headers()
            if include_body:
                self.wfile.write(body)

        def do_GET(self):  # noqa: N802  (stdlib casing)
            self._respond(include_body=True)

        def _submit_route(self) -> tuple[int, bytes, dict[str, str]]:
            """POST /submit: the thin HTTP front-door path. Pods
            travel as state/codec dicts (the journal's own pod
            format), so the HTTP wire needs no second codec."""
            from ..state.codec import pod_from_state

            try:
                length = int(self.headers.get("Content-Length", "0"))
                if length > _MAX_SUBMIT_BODY_BYTES:
                    return (
                        413,
                        json.dumps({
                            "error": "submission body too large",
                            "max_bytes": _MAX_SUBMIT_BODY_BYTES,
                        }).encode(),
                        {},
                    )
                body = json.loads(self.rfile.read(length) or b"{}")
                pods = [
                    pod_from_state(d) for d in body.get("pods", ())
                ]
            except (ValueError, KeyError, TypeError) as e:
                return (
                    400,
                    json.dumps(
                        {"error": f"unparseable submission: {e}"}
                    ).encode(),
                    {},
                )
            res = admission.submit(
                pods,
                traceparent=self.headers.get("traceparent", ""),
            )
            payload = {
                "accepted": res.accepted,
                "shed": res.shed,
                "invalid": list(res.invalid),
                "reason": res.reason,
                "durable": res.durable,
                "queue_depth": res.queue_depth,
            }
            if res.invalid:
                status, extra = 400, {}
            elif res.reason == "draining":
                status, extra = 503, {}
            elif res.shed:
                status = 429
                # RFC 7231 delta-seconds is an INTEGER — fractional
                # values break stdlib/urllib3 retry parsers; round the
                # hint UP so clients never retry early
                extra = {
                    "Retry-After": str(
                        max(1, math.ceil(res.retry_after_ms / 1e3))
                    )
                }
            else:
                status, extra = 200, {}
            if res.traceparent:
                # echo the effective trace context (the caller's own
                # header, or the head-sampled root the scheduler
                # minted) — the HTTP twin of the gRPC trailing
                # metadata echo
                extra = dict(extra)
                extra["traceparent"] = res.traceparent
            return status, json.dumps(payload).encode(), extra

        def do_POST(self):  # noqa: N802 — the ONE mutating route; every
            # other path keeps the read-only 405 contract below
            if admission is None or urllib.parse.urlsplit(
                self.path
            ).path != "/submit":
                self._method_not_allowed()
                return
            status, body, extra = self._submit_route()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in extra.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_HEAD(self):  # noqa: N802 — probes commonly use HEAD; the
            # stdlib handler would 501 without this
            self._respond(include_body=False)

        def _method_not_allowed(self):
            body = b"method not allowed"
            self.send_response(405)
            self.send_header("Allow", "GET, HEAD")
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        # every mutating verb is a client error on a read-only surface
        # (POST carved out above for /submit): 405 + Allow, not the
        # stdlib's 501
        do_PUT = _method_not_allowed  # noqa: N815
        do_DELETE = _method_not_allowed  # noqa: N815
        do_PATCH = _method_not_allowed  # noqa: N815
        do_OPTIONS = _method_not_allowed  # noqa: N815

        def log_message(self, fmt, *args):  # probes are noisy
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    # the serve thread is pinned on the server object so shutdown can
    # JOIN it (stop_http_server): daemon=True alone is not a lifecycle
    # story — the thread would hold the listening socket until process
    # exit (schedlint TR003, the CompileWarmer leak class)
    server._serve_thread = threading.Thread(
        target=server.serve_forever, name="http-metrics", daemon=True
    )
    server._serve_thread.start()
    return server


def stop_http_server(server: ThreadingHTTPServer, timeout: float = 5.0) -> bool:
    """Shut the serve loop down, join its thread, close the listening
    socket. Returns False when the thread failed to exit within
    `timeout` (it is daemon, so the process can still exit; the socket
    is closed either way). Idempotent — the second call is a no-op."""
    thread = getattr(server, "_serve_thread", None)
    server.shutdown()
    if thread is not None:
        # join the CAPTURED reference: a concurrent second stop may
        # have already cleared the attribute (both reads raced past the
        # None check) and joining through it again would be a crash
        thread.join(timeout)
        alive = thread.is_alive()
        server._serve_thread = None
    else:
        alive = False
    server.server_close()
    return not alive
